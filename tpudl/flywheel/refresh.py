"""Per-tenant LoRA refresh training: frozen base, resumable mid-log.

``RefreshTrainer`` is the flywheel's training half. Design points,
each riding an existing seam rather than new machinery:

- **LoRA factors only.** The train model is the serving config with
  ``lora_rank`` set; the serving base params are GRAFTED into the
  fresh init by path (f32 masters), and ``lora_optimizer`` freezes
  everything but ``lora_a``/``lora_b`` (set_to_zero: no moments for
  the frozen base — the tree is 99% frozen). The refreshed artifact
  is ``extract_adapters(params)`` — exactly what
  ``AdapterPool.register`` takes.
- **Precision policy.** ``TPUDL_FLYWHEEL_PRECISION`` (default bf16)
  resolves through ``tpudl.train.precision``; the step mirrors the
  classification step's contract — cast-inside-loss, f32 reductions,
  dynamic loss scaling with skip-on-nonfinite, and with the fp8
  policy the train model's projection sites run Fp8Dense WITH the
  adapter factors (the fp8 x LoRA cell this PR opens): amax rings
  ride ``state.precision`` through checkpoints.
- **Fixed shapes.** Examples pack to constant ``[B, L]`` batches
  (``samples.pack_examples``) so one compiled step serves every
  refresh — compiles happen once per trainer, never per refresh.
- **Resumable mid-log.** Training drives ``tpudl.train.fit`` with an
  ``ft.data.ResumableIterator`` whose ``state()`` carries the batch
  position PLUS the tenant's request-log position; the
  ``ft.AsyncCheckpointManager`` persists it as ``data_state`` next
  to factors + optimizer + precision state. A PR 4 preemption
  (SIGTERM grace) stops fit between steps, the emergency save
  commits, and ``refresh()`` called again resumes schedule-identical
  — bitwise the uninterrupted run (tests pin this).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import optax

from tpudl.flywheel.samples import pack_examples
from tpudl.ft.data import ResumableIterator
from tpudl.models.lora import extract_adapters, lora_optimizer
from tpudl.train import precision as precision_mod
from tpudl.train.loop import TrainState, fit

DEFAULT_BATCH_SIZE = 4
DEFAULT_SEQ_LEN = 32
DEFAULT_LEARNING_RATE = 5e-2
DEFAULT_EPOCHS = 2


def default_precision() -> str:
    """The refresh policy preset (TPUDL_FLYWHEEL_PRECISION): bf16 by
    default — the fp8 arm is opt-in per deployment."""
    from tpudl.analysis.registry import env_str

    return env_str("TPUDL_FLYWHEEL_PRECISION", "bf16")


def _graft_base(init_params: Any, base_params: Any) -> Any:
    """Init tree with every non-adapter leaf replaced by the serving
    base value (cast to the init leaf's dtype — f32 masters stay f32
    even when serving holds bf16). Adapter leaves keep their fresh
    init (zero-B: the grafted model starts exactly at the base)."""

    def walk(init_node, base_node):
        if not isinstance(init_node, dict):
            if base_node is None:
                return init_node
            return jnp.asarray(base_node, init_node.dtype)
        out = {}
        for key, value in init_node.items():
            if key in ("lora_a", "lora_b"):
                out[key] = value
                continue
            sub = (
                base_node.get(key)
                if isinstance(base_node, dict)
                else None
            )
            out[key] = walk(value, sub)
        return out

    return walk(init_params, base_params)


def _apply_adapter(params: Any, adapter: Dict[str, dict]) -> Any:
    """Warm-start: write one tenant's extracted factors over the
    fresh adapter leaves (site paths are '/'-joined module paths, the
    ``extract_adapters`` form)."""
    params = jax.tree.map(lambda x: x, params)
    for path, factors in adapter.items():
        node = params
        for part in path.split("/"):
            if part not in node:
                raise ValueError(
                    f"adapter site {path!r} not in the refresh model "
                    f"(missing {part!r})"
                )
            node = node[part]
        for leaf in ("lora_a", "lora_b"):
            node[leaf] = jnp.asarray(
                factors[leaf], node[leaf].dtype
            )
    return params


class _RefreshData(ResumableIterator):
    """Batch iterator whose ``state()`` also carries the request-log
    position (and tenant) — the dict the checkpoint's ``data_state``
    persists, and ``seek()`` still consumes (extra keys ignored)."""

    def __init__(self, batches: List[dict], epochs: int, extra: dict):
        super().__init__(lambda epoch: iter(batches), epochs=epochs)
        self._extra = dict(extra)

    def state(self) -> dict:
        out = super().state()
        out.update(self._extra)
        return out


class RefreshTrainer:
    """One trainer per serving deployment: compiled once, refreshed
    many (all tenants share the step — shapes and base are common;
    only the grafted adapter differs per refresh)."""

    def __init__(
        self,
        cfg: Any,
        base_params: Any,
        *,
        rank: int = 2,
        alpha: float = 16.0,
        batch_size: int = DEFAULT_BATCH_SIZE,
        seq_len: int = DEFAULT_SEQ_LEN,
        learning_rate: float = DEFAULT_LEARNING_RATE,
        precision: Any = None,
        epochs: int = DEFAULT_EPOCHS,
        seed: int = 0,
    ):
        from tpudl.models.llama import LlamaForCausalLM
        from tpudl.models.lora import strip_adapters

        if precision is None:
            precision = default_precision()
        self.policy = precision_mod.resolve_policy(precision)
        self.rank = int(rank)
        self.alpha = float(alpha)
        self.batch_size = int(batch_size)
        self.seq_len = int(seq_len)
        self.epochs = int(epochs)
        train_cfg = dataclasses.replace(
            cfg,
            lora_rank=self.rank,
            lora_alpha=self.alpha,
            # The serving-only weight tier never trains.
            weight_dtype=None,
        )
        if self.policy is not None:
            if self.policy.use_fp8 and not train_cfg.fp8_train:
                # The fp8 x LoRA cell: Fp8Dense carries the adapter
                # factors, base matmuls run e4m3/e5m2 delayed scaling.
                train_cfg = dataclasses.replace(
                    train_cfg, fp8_train=True
                )
            train_cfg = self.policy.configure_model(train_cfg)
        self.model = LlamaForCausalLM(train_cfg)
        variables = self.model.init(
            jax.random.key(seed),
            jnp.zeros((self.batch_size, self.seq_len), jnp.int32),
        )
        self._fp8_template = variables.get("fp8")
        self._params0 = _graft_base(
            variables["params"], strip_adapters(base_params)
        )
        tx = lora_optimizer(
            optax.adamw(learning_rate), self._params0
        )
        if self.policy is not None:
            tx = precision_mod.apply_moment_rules(tx, self.policy)
        self._tx = tx
        self._step = jax.jit(self._build_step())
        self._eval = None  # compiled lazily: only gated deployments pay

    # -- state ---------------------------------------------------------

    def init_state(
        self, adapter: Optional[Dict[str, dict]] = None
    ) -> TrainState:
        """Fresh refresh state: grafted base + (optionally) the
        tenant's current factors as the warm start."""
        params = self._params0
        if adapter:
            params = _apply_adapter(params, adapter)
        prec_state = None
        if self.policy is not None:
            prec_state = precision_mod.init_precision_state(
                self.policy, self._fp8_template
            )
        return TrainState.create(
            apply_fn=self.model.apply,
            params=params,
            batch_stats=None,
            precision=prec_state,
            tx=self._tx,
        )

    # -- the compiled step ---------------------------------------------

    def _build_step(self):
        policy = self.policy

        def step(state, batch, rng):
            del rng  # no dropout in the decoder; kept for fit()'s shape
            tokens = batch["tokens"]
            mask = batch["mask"]
            prec = state.precision or {}
            loss_scale = (
                prec["loss_scale"]["scale"]
                if policy is not None and policy.loss_scale is not None
                else None
            )
            fp8_vars = (
                prec.get("fp8")
                if policy is not None and policy.use_fp8
                else None
            )

            def loss_fn(params, fp8_vars=None):
                run_params = (
                    policy.cast_params(params)
                    if policy is not None
                    else params
                )
                variables = {"params": run_params}
                if fp8_vars is not None:
                    variables["fp8"] = fp8_vars
                    logits, mutated = state.apply_fn(
                        variables, tokens, mutable=["intermediates"]
                    )
                else:
                    logits = state.apply_fn(variables, tokens)
                    mutated = {}
                logits = logits.astype(
                    policy.reduce_dtype
                    if policy is not None
                    else jnp.float32
                )
                # Next-token CE on OUTPUT positions only: position t
                # predicts token t+1, so weights shift with targets.
                per = optax.softmax_cross_entropy_with_integer_labels(
                    logits[:, :-1], tokens[:, 1:]
                )
                w = mask[:, 1:].astype(jnp.float32)
                loss = jnp.sum(per * w) / jnp.maximum(jnp.sum(w), 1.0)
                objective = (
                    loss if loss_scale is None else loss * loss_scale
                )
                return objective, (loss, mutated)

            if fp8_vars is not None:
                (
                    (_, (loss, mutated)),
                    (grads, fp8_grads),
                ) = jax.value_and_grad(
                    loss_fn, argnums=(0, 1), has_aux=True
                )(state.params, fp8_vars)
            else:
                (_, (loss, mutated)), grads = jax.value_and_grad(
                    loss_fn, has_aux=True
                )(state.params)
                fp8_grads = None
            if loss_scale is not None:
                grads = jax.tree.map(lambda g: g / loss_scale, grads)

            applied = state.apply_gradients(grads=grads)
            metrics = {"loss": loss}
            if policy is None:
                return applied, metrics
            new_prec = dict(prec)
            if policy.loss_scale is not None:
                ok = precision_mod.all_finite(grads)
                new_state = precision_mod.select_tree(
                    ok, applied, state
                )
                metrics["loss_scale"] = prec["loss_scale"]["scale"]
                metrics["grad_skipped"] = jnp.where(ok, 0.0, 1.0)
                new_prec["loss_scale"] = precision_mod.update_loss_scale(
                    prec["loss_scale"], policy.loss_scale, ok
                )
            else:
                ok = jnp.asarray(True)
                new_state = applied
            if policy.use_fp8 and fp8_vars is not None:
                from tpudl.ops.fp8_dot import updated_fp8_state

                new_prec["fp8"] = updated_fp8_state(
                    prec["fp8"],
                    mutated.get("intermediates", {}),
                    fp8_grads,
                    ok,
                )
            if new_prec:
                new_state = new_state.replace(precision=new_prec)
            return new_state, metrics

        return step

    # -- the promotion gate's eval -------------------------------------

    def _build_eval(self):
        policy = self.policy
        fp8_template = self._fp8_template

        def ev(params, tokens, mask):
            run_params = (
                policy.cast_params(params)
                if policy is not None
                else params
            )
            variables = {"params": run_params}
            if policy is not None and policy.use_fp8:
                variables["fp8"] = fp8_template
                logits, _ = self.model.apply(
                    variables, tokens, mutable=["intermediates"]
                )
            else:
                logits = self.model.apply(variables, tokens)
            logits = logits.astype(
                policy.reduce_dtype if policy is not None else jnp.float32
            )
            per = optax.softmax_cross_entropy_with_integer_labels(
                logits[:, :-1], tokens[:, 1:]
            )
            w = mask[:, 1:].astype(jnp.float32)
            return jnp.sum(per * w), jnp.sum(w)

        return ev

    def evaluate(
        self,
        examples: List[dict],
        adapter: Optional[Dict[str, dict]] = None,
    ) -> Optional[float]:
        """Mean next-token loss on ``examples`` under ``adapter``
        (None = the zero-B grafted base, i.e. exactly the serving base
        model). Loss-only — no gradients, no optimizer — using the
        SAME cast/reduce policy as the train step, so a gate
        comparison between two adapters is apples-to-apples. Returns
        None when the examples pack to zero batches (nothing to judge
        — the gate treats that as pass-through)."""
        batches = pack_examples(examples, self.batch_size, self.seq_len)
        if not batches:
            return None
        if self._eval is None:
            self._eval = jax.jit(self._build_eval())
        params = self._params0
        if adapter:
            params = _apply_adapter(params, adapter)
        total = 0.0
        weight = 0.0
        for batch in batches:
            s, w = self._eval(params, batch["tokens"], batch["mask"])
            total += float(s)
            weight += float(w)
        if weight <= 0.0:
            return None
        return total / weight

    # -- driving -------------------------------------------------------

    def refresh(
        self,
        examples: List[dict],
        *,
        adapter: Optional[Dict[str, dict]] = None,
        tenant: Any = None,
        log_state: Optional[dict] = None,
        manager: Any = None,
        checkpoint_every: int = 1,
        rng: Optional[jax.Array] = None,
        max_steps: Optional[int] = None,
    ) -> Tuple[Optional[Dict[str, dict]], dict]:
        """Train the tenant's factors on ``examples``.

        Returns ``(factors, info)``: the ``extract_adapters`` flat
        tree ready for ``AdapterPool.register`` (None when preempted
        before finishing — call again with the same ``manager`` to
        resume schedule-identically), and an info dict with the loss
        trajectory, step count, the consumed log position, and the
        ``preempted`` flag."""
        batches = pack_examples(
            examples, self.batch_size, self.seq_len
        )
        if not batches:
            return None, {
                "steps": 0, "preempted": False, "losses": [],
                "log_state": log_state, "tenant": tenant,
            }
        data = _RefreshData(
            batches, self.epochs,
            {"log": log_state, "tenant": tenant},
        )
        state = self.init_state(adapter)
        if rng is None:
            rng = jax.random.key(0)
        resumed_from = None
        if manager is not None and manager.latest_step() is not None:
            state, saved_rng, data_state = manager.restore_full(state)
            if saved_rng is not None:
                rng = saved_rng
            if data_state:
                data.seek(data_state)
                log_state = data_state.get("log", log_state)
            resumed_from = int(state.step)

        losses: List[float] = []

        def collect(step_no, host_metrics):
            losses.append(float(host_metrics["loss"]))

        state, _, run_info = fit(
            self._step, state, data, rng,
            num_steps=max_steps,
            log_every=1, logger=collect,
            checkpoint_manager=manager,
            checkpoint_every=checkpoint_every if manager else 0,
        )
        info = {
            "steps": int(run_info["steps"]),
            "total_steps": int(state.step),
            "preempted": bool(run_info["preempted"]),
            "resumed_from": resumed_from,
            "losses": losses,
            "log_state": log_state,
            "tenant": tenant,
        }
        if run_info["preempted"]:
            return None, info
        return extract_adapters(state.params), info
