"""Declarative sample filtering over the durable request log.

``SampleFilter`` decides, record by record, what becomes a training
example. Policy is data, in the ``tpudl.rules`` first-match shape the
sharding/quantization/precision engines already use: an ordered
``(regex, "keep" | "drop")`` list matched against the record's
``"{tenant}/{finish_reason}"`` path — tenant allow/deny lists and
finish-reason policy are one mechanism, first match wins, and the
``default`` covers the rest explicitly (no silent fallthrough).

On top of the rule verdict sit the structural gates:

- sample presence — v1 records (and v2 records written with capture
  off) carry no token ids; they are SKIPPED LOUDLY (one warning per
  filter + a counted stat) per the schema version contract, never an
  error: old log segments stay consumable.
- min/max output-token bounds — degenerate one-token completions and
  runaway maxima both train badly.
- dedup by prompt-prefix hash — repeated identical prompts (health
  checks, retries) would otherwise dominate a tenant's refresh.

``SampleStream`` binds a filter to ``ft.data.resumable_request_log``:
iterating yields admitted examples while ``state()`` reports the log
``(epoch, offset)`` position — the exact dict a refresh checkpoint
carries, so a resumed refresh re-reads not a single record.
"""

from __future__ import annotations

import warnings
from typing import Any, Dict, Iterator, List, Optional, Sequence, Tuple

from tpudl import rules as rules_mod
from tpudl.flywheel.samples import example_from_record
from tpudl.ft.data import resumable_request_log

#: Rule verdicts. Anything else in a rule's value raises at the door.
KEEP = "keep"
DROP = "drop"

#: Prompt-prefix length (tokens) the dedup hash covers.
DEFAULT_DEDUP_PREFIX = 16


class SampleFilter:
    """First-match record filter producing per-tenant training
    examples.

    ``rules``: ordered ``(pattern, "keep"|"drop")`` pairs matched
    (``re.search``, first match wins) against ``"{tenant}/
    {finish_reason}"`` — e.g. ``((r"^tenant-a/", "drop"),
    (r"/eos$", "keep"))`` drops tenant-a entirely and keeps only
    eos-finished completions from everyone else when ``default=
    "drop"``. ``None`` tenants match as the literal ``"-"`` (the
    metering BASE_TENANT convention: base-model traffic is usually
    dropped by tenant rules, since there is no adapter to refresh).

    ``stats()`` exposes the admission ledger; ``reset_dedup()`` clears
    the seen-prefix set (a controller does this per refresh so dedup
    is per-refresh, not forever)."""

    def __init__(
        self,
        rules: Sequence[Tuple[str, str]] = (),
        *,
        default: str = KEEP,
        min_output_tokens: int = 1,
        max_output_tokens: Optional[int] = None,
        dedup_prefix: int = DEFAULT_DEDUP_PREFIX,
    ):
        for pattern, verdict in rules:
            if verdict not in (KEEP, DROP):
                raise ValueError(
                    f"rule {pattern!r}: verdict must be "
                    f"{KEEP!r} or {DROP!r}, got {verdict!r}"
                )
        if default not in (KEEP, DROP):
            raise ValueError(
                f"default must be {KEEP!r} or {DROP!r}, got {default!r}"
            )
        if min_output_tokens < 1:
            raise ValueError(
                f"min_output_tokens must be >= 1, got {min_output_tokens}"
            )
        self.rules = tuple(rules)
        self.default = default
        self.min_output_tokens = min_output_tokens
        self.max_output_tokens = max_output_tokens
        self.dedup_prefix = dedup_prefix
        self._seen: set = set()
        self._warned_no_sample = False
        self._stats = {
            "records": 0,
            "admitted": 0,
            "dropped_rule": 0,
            "dropped_no_sample": 0,
            "dropped_bounds": 0,
            "dropped_duplicate": 0,
        }

    def _path(self, record: dict) -> str:
        tenant = record.get("tenant")
        return f"{tenant if tenant is not None else '-'}/" \
               f"{record.get('finish_reason', '?')}"

    def admit(self, record: dict) -> Optional[Dict]:
        """The example this record yields, or None (with the drop
        reason counted in ``stats()``)."""
        self._stats["records"] += 1
        verdict = rules_mod.first_match(self.rules, self._path(record))
        if verdict is rules_mod.NO_MATCH:
            verdict = self.default
        if verdict == DROP:
            self._stats["dropped_rule"] += 1
            return None
        example = example_from_record(record)
        if example is None:
            # The v1-compat path: a record without samples is a valid
            # record that simply predates (or opted out of) capture.
            self._stats["dropped_no_sample"] += 1
            if not self._warned_no_sample:
                self._warned_no_sample = True
                warnings.warn(
                    "SampleFilter: request-log record(s) without "
                    "prompt_ids/output_ids samples (schema v1, or "
                    "TPUDL_OBS_REQUEST_LOG_SAMPLES was off when they "
                    "were served) — skipping them; see "
                    "stats()['dropped_no_sample'] for the count",
                    RuntimeWarning,
                    stacklevel=3,
                )
            return None
        n_out = len(example["output_ids"])
        if n_out < self.min_output_tokens or (
            self.max_output_tokens is not None
            and n_out > self.max_output_tokens
        ):
            self._stats["dropped_bounds"] += 1
            return None
        key = (
            example["tenant"],
            tuple(example["prompt_ids"][: self.dedup_prefix]),
        )
        if key in self._seen:
            self._stats["dropped_duplicate"] += 1
            return None
        self._seen.add(key)
        self._stats["admitted"] += 1
        return example

    def stats(self) -> Dict[str, int]:
        return dict(self._stats)

    def reset_dedup(self) -> None:
        self._seen.clear()


class SampleStream:
    """Admitted examples from a request-log directory, with the log
    position riding along.

    The underlying ``resumable_request_log`` snapshots the segment set
    at construction — a LIVE log needs a fresh ``SampleStream`` per
    poll, seeked to the last checkpointed ``state()`` (exactly how
    ``FlywheelController`` consumes it). ``state()`` after pulling an
    example points one record PAST it: resume never re-trains on a
    consumed sample."""

    def __init__(
        self,
        directory: str,
        filter: SampleFilter,
        state: Optional[Dict[str, int]] = None,
    ):
        self.filter = filter
        self._it = resumable_request_log(directory)
        if state:
            self._it.seek(state)

    def state(self) -> Dict[str, int]:
        return self._it.state()

    def __iter__(self) -> Iterator[Dict]:
        return self

    def __next__(self) -> Dict:
        while True:
            record = next(self._it)  # StopIteration ends the stream
            example = self.filter.admit(record)
            if example is not None:
                return example

    def take(
        self, tenant: Any, limit: Optional[int] = None
    ) -> List[Dict]:
        """Drain the snapshot, returning ONLY ``tenant``'s examples
        (other tenants' records advance the position — per-tenant
        positions mean each tenant scans the log independently)."""
        out: List[Dict] = []
        for example in self:
            if example["tenant"] != tenant:
                continue
            out.append(example)
            if limit is not None and len(out) >= limit:
                break
        return out
