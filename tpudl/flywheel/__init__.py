"""tpudl.flywheel — per-tenant continual LoRA refresh from live traffic.

The consuming half of the PR 16 ingestion stack (ROADMAP item 4's
"NLP at scale" loop, closed): served traffic lands in the durable
request log with optional token samples (schema v2,
``TPUDL_OBS_REQUEST_LOG_SAMPLES``), a declarative ``SampleFilter``
turns raw records into per-tenant training examples on a resumable
log position, a ``RefreshTrainer`` trains ONLY the tenant's LoRA
factors under a ``tpudl.train.precision`` policy (checkpointing
factors + log position, preemption-safe), and the
``FlywheelController`` hot-swaps the refreshed factors back into the
serving ``AdapterPool`` under the PR 14 safe-publish contract — the
next request serves the refreshed adapter, zero serving recompiles.

Module map (one seam each):

- ``samples``  — record <-> training-example conversion + fixed-shape
  batch packing (the zero-recompile contract for the trainer).
- ``filter``   — ``SampleFilter`` (tpudl.rules first-match shape) +
  ``SampleStream`` over ``ft.data.resumable_request_log``.
- ``refresh``  — ``RefreshTrainer``: frozen-base LoRA training,
  precision policy, AsyncCheckpointManager + preemption resume.
- ``loop``     — ``FlywheelController``: TenantMeter deltas ->
  refresh trigger -> AdapterPool.register safe publish + telemetry.
"""

from tpudl.flywheel.filter import SampleFilter, SampleStream
from tpudl.flywheel.loop import FlywheelController
from tpudl.flywheel.refresh import RefreshTrainer
from tpudl.flywheel.samples import (
    example_from_record,
    has_sample,
    pack_examples,
)

__all__ = [
    "FlywheelController",
    "RefreshTrainer",
    "SampleFilter",
    "SampleStream",
    "example_from_record",
    "has_sample",
    "pack_examples",
]
