"""Workload configurations.

The reference hardcodes every knob as a literal inside the notebook
(image dims at notebooks/cv/onnx_experiments.py:29-30, opset at :38,
artifact paths at :36,48, EP choice by commenting lines in/out at :81-83 —
"configuration by comment", SURVEY.md §5.6). Here each BASELINE.json
configs[i] entry is a dataclass with CLI overrides.
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Tuple

from tpudl.runtime.mesh import MeshSpec


@dataclasses.dataclass(frozen=True)
class OptimConfig:
    name: str = "adamw"  # adamw | sgd
    learning_rate: float = 1e-3
    warmup_steps: int = 100
    total_steps: int = 1000
    weight_decay: float = 1e-4
    momentum: float = 0.9  # sgd only
    b1: float = 0.9
    b2: float = 0.999
    #: AdamW first-moment dtype. bf16 halves that state's HBM footprint
    #: and traffic (+2.6% measured on the BERT bench step,
    #: benchmarks/bert_mu_dtype.py); the second moment stays f32 for
    #: numerical range. Default f32 so existing checkpoints restore
    #: unchanged — opt in per config.
    mu_dtype: str = "float32"  # float32 | bfloat16
    grad_clip_norm: Optional[float] = 1.0
    schedule: str = "cosine"  # cosine | constant | linear


@dataclasses.dataclass(frozen=True)
class TrainConfig:
    name: str
    model: str  # resnet18 | resnet50 | bert-base | bert-large | llama3-8b-lora
    dataset: str  # cifar10 | imagenet | sst2
    global_batch_size: int = 128
    image_size: int = 32
    seq_len: int = 128
    num_classes: int = 10
    precision: str = "bf16"  # bf16 | f32
    mesh: MeshSpec = dataclasses.field(default_factory=MeshSpec)
    strategy: str = "dp"  # dp | fsdp | tp | fsdp+tp | lora | pp
    optim: OptimConfig = dataclasses.field(default_factory=OptimConfig)
    num_steps: int = 200
    log_every: int = 20
    #: Gradient-accumulation microbatches per optimizer step (>1 = the
    #: compiled step scans over microbatches — how a declared global batch
    #: larger than the mesh's memory fits; tpudl.train.loop.microbatch).
    accum_steps: int = 1
    label_smoothing: float = 0.0
    data_dir: Optional[str] = None  # parquet dir; None -> synthetic
    checkpoint_dir: Optional[str] = None
    seed: int = 0


# One config per BASELINE.json configs[i] (SURVEY.md §5.6).
CONFIGS = {
    # configs[0]: ResNet-18 on CIFAR-10, single-process smoke.
    "cifar10_resnet18": TrainConfig(
        name="cifar10_resnet18",
        model="resnet18",
        dataset="cifar10",
        global_batch_size=256,
        image_size=32,
        num_classes=10,
        optim=OptimConfig(name="sgd", learning_rate=0.1, warmup_steps=50,
                          total_steps=2000, weight_decay=5e-4),
        num_steps=2000,
    ),
    # configs[1]: BERT-base SST-2 fine-tune, single-process.
    "sst2_bert_base": TrainConfig(
        name="sst2_bert_base",
        model="bert-base",
        dataset="sst2",
        global_batch_size=32,
        seq_len=128,
        num_classes=2,
        optim=OptimConfig(name="adamw", learning_rate=2e-5, warmup_steps=100,
                          total_steps=2000, weight_decay=0.01,
                          mu_dtype="bfloat16"),
        num_steps=2000,
    ),
    # configs[2]: ResNet-50 ImageNet, data-parallel on v4-8.
    "imagenet_resnet50_dp": TrainConfig(
        name="imagenet_resnet50_dp",
        model="resnet50",
        dataset="imagenet",
        global_batch_size=1024,
        image_size=224,
        num_classes=1000,
        mesh=MeshSpec(dp=-1),
        strategy="dp",
        optim=OptimConfig(name="sgd", learning_rate=0.4, warmup_steps=500,
                          total_steps=56300, weight_decay=1e-4),
        num_steps=56300,
        label_smoothing=0.1,
        # Declared global batch 1024 via 128-row microbatches — the
        # measured-good single-chip ResNet-50 batch (BASELINE.md); on a
        # real v4-8 the same config runs accumulated per-chip too.
        accum_steps=8,
    ),
    # configs[3]: BERT-large fine-tune, v4-32 (Horovod -> TpuDistributor migration).
    "bert_large_v4_32": TrainConfig(
        name="bert_large_v4_32",
        model="bert-large",
        dataset="sst2",
        global_batch_size=256,
        seq_len=128,
        num_classes=2,
        mesh=MeshSpec(dp=-1, fsdp=4),
        strategy="fsdp",
        optim=OptimConfig(name="adamw", learning_rate=3e-5, warmup_steps=200,
                          mu_dtype="bfloat16",
                          total_steps=5000, weight_decay=0.01),
        num_steps=5000,
        # Global batch 256 as 4x64 microbatches: the single-chip step OOMs
        # monolithic at batch >=96; accumulated it runs at 74.0% MFU
        # (BASELINE.md). Meshes with more batch shards just split each
        # microbatch further.
        accum_steps=4,
    ),
    # configs[4]: Llama-3-8B LoRA (stretch — FSDP->GSPMD on v5p-64).
    "llama3_8b_lora": TrainConfig(
        name="llama3_8b_lora",
        model="llama3-8b-lora",
        dataset="sst2",
        global_batch_size=64,
        seq_len=2048,
        num_classes=2,
        mesh=MeshSpec(dp=-1, fsdp=8, tp=2),
        strategy="lora",
        optim=OptimConfig(name="adamw", learning_rate=1e-4, warmup_steps=100,
                          total_steps=1000, weight_decay=0.0),
        num_steps=1000,
    ),
}


def get_config(name: str, **overrides) -> TrainConfig:
    cfg = CONFIGS[name]
    if overrides:
        cfg = dataclasses.replace(cfg, **overrides)
    return cfg
