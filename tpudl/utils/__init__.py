"""Shared utilities (logging, pytrees)."""
