"""Host-side span/event recorder: the wall-clock half of observability.

`tpudl.train.profiling` answers "where does the DEVICE step go" from the
XLA trace; this module answers "where does the rest of the RUN go" —
data stalls, compile, checkpointing, idle — by recording host-side spans
around the runtime's blocking calls. Records are plain dicts with a
monotonic timestamp, duration, category, and host/process tags, exported
two ways:

- **JSONL** (one record per line, streamed as recorded) — the greppable
  artifact ``python -m tpudl.obs.report`` aggregates into goodput and
  straggler tables;
- **Chrome trace-event JSON** (``export_chrome_trace``) — loads in
  Perfetto/chrome://tracing NEXT TO the XLA device trace
  ``jax.profiler.trace`` writes, so host spans and device ops line up in
  one timeline view.

Design constraints, all load-bearing:

- **zero hard dependencies** — stdlib only, importable everywhere
  (data workers, checkpoint path, spawned distributor ranks);
- **thread-safe** — async checkpoint flushes and data prefetch threads
  record concurrently with the train loop;
- **injectable clock** — tests pass a fake monotonic clock and get
  byte-deterministic exports;
- **disabled is free** — ``active_recorder()`` returns None unless
  ``enable()`` was called or TPUDL_OBS_DIR is set; instrumentation
  sites guard on that None, so a disabled run adds one env lookup per
  fit() call and nothing per step.

Activation mirrors the profiler hook: set ``TPUDL_OBS_DIR=/path`` (or
call ``enable(path)``) and every instrumented layer streams into
``spans-<host>-p<process>-<pid>.jsonl`` under it.
"""

from __future__ import annotations

import atexit
import json
import os
import socket
import threading
import time
from typing import Callable, Iterable, Optional

from tpudl.analysis.registry import env_int, env_str

#: Span categories the goodput classifier understands (see
#: tpudl.obs.goodput). Instrumentation may invent others; they land in
#: the report's "other" bucket.
CAT_STEP = "step"
CAT_EVAL = "eval"
CAT_COMPILE = "compile"
CAT_DATA_WAIT = "data_wait"
#: Time the train loop blocked on metric readback (the async drain's
#: backpressure or its end-of-fit flush) — separate from data_wait so a
#: report distinguishes "starved for batches" from "throttled by
#: telemetry".
CAT_METRIC_WAIT = "metric_wait"
CAT_CHECKPOINT = "checkpoint"
#: Time lost to failure recovery (supervisor backoff between a cohort
#: death and its relaunch) — accounted as lost wall-clock, the
#: "lost-to-recovery" column of the goodput report.
CAT_RECOVERY = "recovery"
#: Background checkpoint writes (tpudl.ft.writer): they OVERLAP train
#: steps by design, so the classifier reports them but never charges
#: them against the run's wall-clock budget.
CAT_CKPT_BG = "ckpt_bg"
#: Enclosing lifetime spans (a distributor worker's whole run): they
#: OVERLAP the categorized spans inside them, so the goodput classifier
#: uses them only to extend the run window, never as accounted time.
CAT_ENCLOSING = "worker"


class _Span:
    """Context manager recording one span on exit. Created by
    ``SpanRecorder.span`` — never when recording is disabled (the
    module-level ``span()`` returns a shared no-op instead)."""

    __slots__ = ("_rec", "_name", "_cat", "_attrs", "_t0")

    def __init__(self, rec: "SpanRecorder", name: str, cat: str, attrs: dict):
        self._rec = rec
        self._name = name
        self._cat = cat
        self._attrs = attrs

    def __enter__(self) -> "_Span":
        self._t0 = self._rec.clock()
        return self

    def __exit__(self, *exc) -> None:
        self._rec.record(
            self._name, self._cat, self._t0,
            self._rec.clock() - self._t0, self._attrs,
        )


class _NullSpan:
    """Shared no-op context manager for the disabled path (one module
    singleton — entering it allocates nothing)."""

    __slots__ = ()

    def __enter__(self) -> "_NullSpan":
        return self

    def __exit__(self, *exc) -> None:
        pass


_NULL_SPAN = _NullSpan()


class SpanRecorder:
    """Thread-safe span/event sink with streaming JSONL and in-memory
    record lists.

    Every record is a flat dict:

    - spans:    ``{"kind": "span", "name", "cat", "ts", "dur", "host",
      "process", "pid", "tid", ...attrs}``
    - events:   ``{"kind": "event", "name", "cat", "ts", ...tags}``
    - counters: ``{"kind": "counters", "ts", "data": {...}}`` (a
      tpudl.obs.counters snapshot riding the same stream)

    ``ts``/``dur`` are seconds on the injected monotonic ``clock``
    (default ``time.monotonic`` — comparable within one process, not
    across hosts; the report aggregates durations, never cross-host
    timestamps).
    """

    def __init__(
        self,
        path: Optional[str] = None,
        clock: Callable[[], float] = time.monotonic,
        host: Optional[str] = None,
        process: Optional[int] = None,
    ):
        self.clock = clock
        self.path = path
        self.host = host if host is not None else socket.gethostname()
        self.process = (
            process
            if process is not None
            else env_int("TPUDL_PROCESS_ID", 0)
        )
        self._lock = threading.Lock()
        self._records: list = []
        self._file = None
        if path:
            os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
            self._file = open(path, "a")

    # -- recording -----------------------------------------------------

    def span(self, name: str, cat: str = CAT_STEP, **attrs) -> _Span:
        """Context manager: ``with rec.span("save", "checkpoint"): ...``"""
        return _Span(self, name, cat, attrs)

    def record(
        self, name: str, cat: str, ts: float, dur: float,
        attrs: Optional[dict] = None,
    ) -> dict:
        """Append one completed span (the explicit form the hot loops use
        so the disabled branch stays allocation-free)."""
        rec = {
            "kind": "span", "name": name, "cat": cat,
            "ts": ts, "dur": dur,
            "host": self.host, "process": self.process,
            "pid": os.getpid(), "tid": threading.get_ident(),
        }
        if attrs:
            rec.update(attrs)
        self._emit(rec)
        return rec

    def event(self, name: str, cat: str = "event", **tags) -> dict:
        """Instant (zero-duration) event — e.g. a per-step metrics blob.
        ``tags`` must not use the reserved record keys (kind/name/cat/
        ts/host/process/pid); nest free-form payloads under one tag
        (see MetricLogger's ``metrics=``)."""
        rec = {
            "kind": "event", "name": name, "cat": cat, "ts": self.clock(),
            "host": self.host, "process": self.process, "pid": os.getpid(),
        }
        reserved = set(rec) & set(tags)
        if reserved:
            raise ValueError(
                f"event tags collide with reserved record keys: "
                f"{sorted(reserved)} — nest them under one tag instead"
            )
        rec.update(tags)
        self._emit(rec)
        return rec

    def counters(self, snapshot: dict) -> dict:
        """Attach a tpudl.obs.counters snapshot to the stream."""
        rec = {
            "kind": "counters", "ts": self.clock(),
            "host": self.host, "process": self.process, "pid": os.getpid(),
            "data": snapshot,
        }
        self._emit(rec)
        return rec

    def ingest(self, record: dict) -> None:
        """Append an already-built record verbatim (the distributor's
        merge path: worker records keep THEIR host/process tags)."""
        self._emit(record)

    def _emit(self, rec: dict) -> None:
        # Streamed OR buffered, never both: a file-backed recorder keeps
        # nothing in memory (a million-step run must not grow the host
        # RSS by its own telemetry); `records` re-reads the file.
        with self._lock:
            if self._file is not None:
                self._file.write(json.dumps(rec) + "\n")
                self._file.flush()
            else:
                self._records.append(rec)

    # -- export --------------------------------------------------------

    @property
    def records(self) -> list:
        with self._lock:
            if self.path is not None:
                if not os.path.exists(self.path):
                    return []
                return read_jsonl(self.path)
            return list(self._records)

    def export_jsonl(self, path: str) -> str:
        """Write the in-memory records to ``path`` (one JSON per line)."""
        with open(path, "w") as f:
            for rec in self.records:
                f.write(json.dumps(rec) + "\n")
        return path

    def export_chrome_trace(self, path: str) -> str:
        """Write records as Chrome trace-event JSON (see module docstring)."""
        with open(path, "w") as f:
            json.dump({"traceEvents": chrome_trace_events(self.records)}, f)
        return path

    def close(self) -> None:
        with self._lock:
            if self._file is not None:
                self._file.close()
                self._file = None

    def __enter__(self) -> "SpanRecorder":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


def chrome_trace_events(records: Iterable[dict]) -> list:
    """tpudl span/event records -> Chrome trace-event list.

    Spans become complete ("X") events, instants become "i" events; each
    recording process — keyed (host, process-index, OS pid), since a
    distributor parent and its rank-0 worker share the first two but
    have unrelated monotonic clocks — gets its own trace pid with a
    process_name metadata row, so a merged multi-host file renders one
    lane per worker next to the XLA device lanes."""
    out = []
    proc_ids: dict = {}
    seen_labels: dict = {}
    for rec in records:
        key = (rec.get("host", "?"), rec.get("process", 0), rec.get("pid"))
        if key not in proc_ids:
            proc_ids[key] = len(proc_ids) + 1
            label = f"tpudl host:{key[0]} p{key[1]}"
            if seen_labels.setdefault(label, key) != key:
                label = f"{label} pid{key[2]}"
            out.append({
                "ph": "M", "pid": proc_ids[key], "name": "process_name",
                "args": {"name": label},
            })
        pid = proc_ids[key]
        tid = rec.get("tid", 0)
        if rec.get("kind") == "span":
            args = {
                k: v for k, v in rec.items()
                if k not in ("kind", "name", "cat", "ts", "dur",
                             "host", "process", "pid", "tid")
            }
            out.append({
                "ph": "X", "name": rec["name"], "cat": rec["cat"],
                "ts": rec["ts"] * 1e6, "dur": rec["dur"] * 1e6,
                "pid": pid, "tid": tid, "args": args,
            })
        elif rec.get("kind") == "event":
            out.append({
                "ph": "i", "s": "t", "name": rec["name"],
                "cat": rec.get("cat", "event"), "ts": rec["ts"] * 1e6,
                "pid": pid, "tid": tid,
            })
    return out


def read_jsonl(path: str) -> list:
    """Load one span JSONL file back into record dicts.

    A TORN FINAL LINE is skipped, not raised: span files are written
    append-only by live processes, so a worker SIGKILLed mid-flush
    legitimately leaves a partial last record — and the distributor's
    merge runs exactly when workers died, where a JSONDecodeError would
    mask the real failure. Corruption anywhere else still raises."""
    records = []
    with open(path) as f:
        lines = [ln.strip() for ln in f]
    lines = [ln for ln in lines if ln]
    for idx, line in enumerate(lines):
        try:
            records.append(json.loads(line))
        except json.JSONDecodeError:
            if idx == len(lines) - 1:
                break  # torn tail of a killed writer
            raise
    return records


# ---------------------------------------------------------------------------
# Module-level active recorder (the switch every instrumentation site
# consults).
# ---------------------------------------------------------------------------

_active: Optional[SpanRecorder] = None
_atexit_registered = False


def default_span_path(directory: str) -> str:
    """Per-(host, process-index, os-pid) span file under ``directory`` —
    collision-free when a distributor parent and its rank-0 worker share
    the directory."""
    host = socket.gethostname()
    proc = env_int("TPUDL_PROCESS_ID", 0)
    return os.path.join(
        directory, f"spans-{host}-p{proc}-{os.getpid()}.jsonl"
    )


def enable(
    path: str,
    clock: Callable[[], float] = time.monotonic,
    process: Optional[int] = None,
) -> SpanRecorder:
    """Activate recording. ``path`` is a directory (a per-process
    ``spans-*.jsonl`` is created inside) or an explicit ``*.jsonl``
    file. Idempotent per path; re-enabling replaces the active
    recorder."""
    global _active, _atexit_registered
    if _active is not None:
        _active.close()
    file_path = (
        path if path.endswith(".jsonl") else default_span_path(path)
    )
    _active = SpanRecorder(file_path, clock=clock, process=process)
    if not _atexit_registered:
        atexit.register(disable)
        _atexit_registered = True
    return _active


def disable() -> None:
    """Deactivate and flush the active recorder (no-op when inactive)."""
    global _active
    if _active is not None:
        _active.close()
        _active = None


def active_recorder() -> Optional[SpanRecorder]:
    """The active recorder, auto-enabling from TPUDL_OBS_DIR on first
    call (mirrors fit()'s TPUDL_PROFILE_DIR idiom) — None when disabled,
    which is the branch every hot path takes for free."""
    if _active is not None:
        return _active
    obs_dir = env_str("TPUDL_OBS_DIR")
    if obs_dir:
        return enable(obs_dir)
    return None


def span(name: str, cat: str = CAT_STEP, **attrs):
    """Module-level convenience: a recording context manager when
    observability is on, a shared no-op otherwise. Cold paths use this
    (ingest chunks, checkpoint saves); per-step loops use the explicit
    ``active_recorder()``/``record()`` form instead."""
    rec = active_recorder()
    if rec is None:
        return _NULL_SPAN
    return rec.span(name, cat, **attrs)
