"""Durable request/result log: the flywheel ingestion source.

PR 6's per-request trace events live in the ephemeral span stream and
die with ``TPUDL_OBS_DIR``; ROADMAP item 4 (the per-tenant continual-
LoRA flywheel) needs served requests to OUTLIVE the serving process.
This module is that durable log: one versioned-schema JSONL record per
terminal ``Result`` — who (tenant), what (tokens in/out, prefix hits,
speculation accepted/proposed), how much (KV page-seconds, adapter
reloads), and how it ended (finish_reason incl. every shed class and
``failover_exhausted``) — written into crc-guarded rotated segments
with the ``ft/store.py`` commit-or-invisible idiom:

- the ACTIVE segment is named ``requests-NNNNNN.open.jsonl`` — visibly
  uncommitted, append-only, tolerated torn at the tail like a span
  stream;
- on rotation (size >= segment_bytes) or close, the file is fsynced,
  its whole-payload crc32 is computed, and one atomic ``os.rename``
  publishes it as ``requests-NNNNNN-<crc32:08x>.jsonl`` — a committed
  segment either carries a verifiable crc in its NAME or does not
  exist.

The writer NEVER blocks the decode loop: ``log()`` is a bounded-queue
``put_nowait`` feeding a background writer thread; overflow increments
``requestlog_records_dropped`` (visible, accounted) instead of
stalling a serving engine on disk latency.

``read_request_log(dir)`` / ``RequestLogReader`` iterate segments in
index order, verify each committed segment's crc, skip a truncated or
corrupt tail loudly (``warnings.warn``) while recovering every intact
record before the tear — and extend the same tolerance to ANY
uncommitted ``.open`` segment regardless of position, since a crashed
process's orphan stays torn even after a restarted writer opens newer
segments behind it (a new writer also crc-seals such orphans on
startup, trimming the torn line first). Corruption inside a committed
non-final segment raises ``RequestLogCorruptError`` (silent data loss
in the middle of the log is the one unforgivable outcome). The
reader's ``state()``/``seek()`` speak
the exact ``{"epoch": segment, "offset": record}`` contract of
``tpudl.ft.data.ResumableIterator`` — the flywheel ingest resumes
mid-log across restarts like a data loader resumes mid-epoch.

Activation mirrors the span stream: set ``TPUDL_OBS_REQUEST_LOG=/path``
(or call ``enable(path)``) and every Result site logs through
``log_result``; disabled is one env lookup and nothing per request.
"""

from __future__ import annotations

import atexit
import json
import os
import queue
import threading
import time
import warnings
import zlib
from typing import Any, Callable, Iterator, List, Optional, Tuple

from tpudl.analysis.registry import env_int, env_str
from tpudl.obs.counters import registry

#: Schema version stamped into every record as ``"v"``. The contract:
#: consumers accept records with ``v <= SCHEMA_VERSION`` and IGNORE
#: unknown fields; producers only ever ADD fields within a version and
#: bump the version when a field's meaning changes or disappears.
#: v2 adds OPTIONAL ``prompt_ids``/``output_ids`` sample fields
#: (present only when TPUDL_OBS_REQUEST_LOG_SAMPLES capture is on —
#: the tpudl.flywheel training source); v1 records stay readable and
#: sample consumers skip them loudly (tpudl.flywheel.filter).
SCHEMA_VERSION = 2

_PREFIX = "requests-"
_OPEN_SUFFIX = ".open.jsonl"
_COMMIT_SUFFIX = ".jsonl"

DEFAULT_SEGMENT_BYTES = 1 << 20
DEFAULT_QUEUE_DEPTH = 1024


class RequestLogCorruptError(RuntimeError):
    """A committed NON-TAIL segment failed its crc or carries a
    malformed record: the middle of the durable log is damaged, which
    no amount of tail tolerance excuses."""


def _fsync_file(path: str) -> None:
    fd = os.open(path, os.O_RDONLY)
    try:
        os.fsync(fd)
    finally:
        os.close(fd)


def _fsync_dir(path: str) -> None:
    fd = os.open(path, os.O_RDONLY)
    try:
        os.fsync(fd)
    finally:
        os.close(fd)


def _parse_segment_name(name: str) -> Optional[Tuple[int, Optional[int]]]:
    """``requests-000003-1a2b3c4d.jsonl`` -> (3, crc);
    ``requests-000004.open.jsonl`` -> (4, None); anything else None."""
    if not name.startswith(_PREFIX):
        return None
    body = name[len(_PREFIX):]
    if body.endswith(_OPEN_SUFFIX):
        idx = body[: -len(_OPEN_SUFFIX)]
        if idx.isdigit():
            return int(idx), None
        return None
    if body.endswith(_COMMIT_SUFFIX):
        stem = body[: -len(_COMMIT_SUFFIX)]
        if "-" not in stem:
            return None
        idx, _, crc = stem.rpartition("-")
        if idx.isdigit() and len(crc) == 8:
            try:
                return int(idx), int(crc, 16)
            except ValueError:
                return None
    return None


def list_segments(directory: str) -> List[Tuple[int, Optional[int], str]]:
    """Segments under ``directory`` as ``(index, crc_or_None, path)``
    sorted by index. A committed and an open file with the same index
    (a crash between rename and unlink cannot produce this — rename is
    the same inode — but a confused operator can) resolves to the
    COMMITTED one: it carries the verifiable name."""
    by_idx: dict = {}
    try:
        names = os.listdir(directory)
    except FileNotFoundError:
        return []
    for name in names:
        parsed = _parse_segment_name(name)
        if parsed is None:
            continue
        idx, crc = parsed
        prev = by_idx.get(idx)
        if prev is None or (prev[0] is None and crc is not None):
            by_idx[idx] = (crc, os.path.join(directory, name))
    return [
        (idx, crc, path)
        for idx, (crc, path) in sorted(by_idx.items())
    ]


# ---------------------------------------------------------------------------
# Writer
# ---------------------------------------------------------------------------


class RequestLogWriter:
    """Bounded-queue background writer of crc-committed JSONL segments.

    ``log(record)`` is the only hot-path method: a ``put_nowait`` that
    on overflow increments ``self.dropped`` (and the
    ``requestlog_records_dropped`` counter) and RETURNS — the decode
    loop never waits on the log. The writer thread serializes, appends
    to the ``.open`` segment, and rotates at ``segment_bytes`` via
    fsync -> crc -> atomic rename -> dir fsync, so a committed segment
    is verifiable by name and a crash leaves at worst one torn
    ``.open`` tail the reader recovers loudly."""

    def __init__(
        self,
        directory: str,
        segment_bytes: int = DEFAULT_SEGMENT_BYTES,
        queue_depth: int = DEFAULT_QUEUE_DEPTH,
        clock: Callable[[], float] = time.time,
    ):
        if segment_bytes < 1:
            raise ValueError(
                f"segment_bytes must be >= 1, got {segment_bytes}"
            )
        if queue_depth < 1:
            raise ValueError(
                f"queue_depth must be >= 1, got {queue_depth}"
            )
        self.directory = directory
        self.segment_bytes = segment_bytes
        self.clock = clock
        os.makedirs(directory, exist_ok=True)
        self._seal_orphans(directory)
        existing = list_segments(directory)
        # Never append into a previous process's segments (its .open
        # tail may be torn; its committed names are immutable): start
        # past the highest index on disk.
        self._index = (existing[-1][0] + 1) if existing else 0
        self.dropped = 0
        self.written = 0
        self.segments_committed = 0
        self._queue: "queue.Queue" = queue.Queue(maxsize=queue_depth)
        self._lock = threading.Lock()  # guards dropped on the hot path
        self._file = None
        self._bytes = 0
        self._closed = False
        self._thread = threading.Thread(
            target=self._run, name="tpudl-requestlog", daemon=True
        )
        self._thread.start()

    @staticmethod
    def _seal_orphans(directory: str) -> None:
        """Commit any ``.open`` segment a crashed predecessor left
        behind: trim the torn final line (if any), fsync, and publish
        under the crc name. Without this, the orphan would sit
        uncommitted in the MIDDLE of the log forever once this writer
        opens higher-indexed segments behind it — readable only via
        the reader's uncommitted-segment tolerance. Sealing upgrades
        its intact records to full crc protection."""
        for idx, crc, path in list_segments(directory):
            if crc is not None:
                continue
            with open(path, "rb") as f:
                blob = f.read()
            kept = bytearray()
            torn = 0
            for line in blob.split(b"\n"):
                if not line.strip():
                    continue
                try:
                    json.loads(line)
                except json.JSONDecodeError:
                    torn += 1
                    continue
                kept += line + b"\n"
            if torn or len(kept) != len(blob):
                if torn:
                    warnings.warn(
                        f"request-log orphan segment {path} had "
                        f"{torn} torn record(s); sealing the intact "
                        f"prefix",
                        RuntimeWarning,
                        stacklevel=3,
                    )
                with open(path, "wb") as f:
                    f.write(bytes(kept))
            _fsync_file(path)
            new_crc = zlib.crc32(bytes(kept)) & 0xFFFFFFFF
            final = os.path.join(
                directory,
                f"{_PREFIX}{idx:06d}-{new_crc:08x}{_COMMIT_SUFFIX}",
            )
            os.rename(path, final)
            _fsync_dir(directory)
            registry().counter("requestlog_orphans_sealed").inc()

    # -- hot path ------------------------------------------------------

    def log(self, record: dict) -> None:
        """Enqueue one record; NEVER blocks. Overflow is counted, not
        waited out."""
        if self._closed:
            return
        try:
            self._queue.put_nowait(record)
        except queue.Full:
            with self._lock:
                self.dropped += 1
            registry().counter("requestlog_records_dropped").inc()

    # -- writer thread -------------------------------------------------

    def _open_path(self) -> str:
        return os.path.join(
            self.directory, f"{_PREFIX}{self._index:06d}{_OPEN_SUFFIX}"
        )

    def _ensure_open(self):
        if self._file is None:
            self._file = open(self._open_path(), "ab")
            self._bytes = 0
        return self._file

    def _write_one(self, record: dict) -> None:
        line = json.dumps(record, separators=(",", ":")) + "\n"
        data = line.encode("utf-8")
        f = self._ensure_open()
        f.write(data)
        self._bytes += len(data)
        self.written += 1
        registry().counter("requestlog_records_written").inc()
        if self._bytes >= self.segment_bytes:
            self._commit_segment()

    def _commit_segment(self) -> None:
        """fsync -> crc -> atomic rename -> dir fsync: the segment is
        either invisible (still ``.open``) or committed with its crc in
        the name — the store's commit-or-invisible idiom, applied to an
        append-only log."""
        if self._file is None:
            return
        self._file.flush()
        os.fsync(self._file.fileno())
        self._file.close()
        self._file = None
        open_path = self._open_path()
        with open(open_path, "rb") as f:
            crc = zlib.crc32(f.read()) & 0xFFFFFFFF
        final = os.path.join(
            self.directory,
            f"{_PREFIX}{self._index:06d}-{crc:08x}{_COMMIT_SUFFIX}",
        )
        os.rename(open_path, final)
        _fsync_dir(self.directory)
        self.segments_committed += 1
        registry().counter("requestlog_segments_committed").inc()
        self._index += 1

    def _run(self) -> None:
        while True:
            item = self._queue.get()
            try:
                if item is _STOP:
                    return
                if item is _FLUSH_ONLY:
                    if self._file is not None:
                        self._file.flush()
                    continue
                self._write_one(item)
            except Exception:
                # A failing disk must not kill the writer thread (the
                # queue would fill and every record would be dropped
                # silently as "overflow"); count it distinctly.
                registry().counter("requestlog_write_errors").inc()
            finally:
                self._queue.task_done()

    # -- lifecycle -----------------------------------------------------

    def flush(self) -> None:
        """Block until every already-enqueued record has been handed to
        the OS (written + ``file.flush()``, still uncommitted in the
        ``.open`` segment and NOT fsynced — durability against power
        loss only comes with segment commit)."""
        if self._closed:
            return
        try:
            self._queue.put(_FLUSH_ONLY, timeout=30.0)
        except queue.Full:
            pass
        self._queue.join()

    def close(self) -> None:
        """Drain, commit the open segment, stop the thread. Idempotent."""
        if self._closed:
            return
        self._closed = True
        self._queue.join()
        self._queue.put(_STOP)
        self._thread.join(timeout=30.0)
        if self._thread.is_alive():
            # A hung disk write left the writer thread running; racing
            # it on self._file from this thread could interleave a
            # commit with an in-flight append. Leave the .open segment
            # for the next writer's orphan sealing.
            warnings.warn(
                "request-log writer thread did not exit within 30s; "
                "leaving the .open segment uncommitted",
                RuntimeWarning,
                stacklevel=2,
            )
            return
        self._commit_segment()


_STOP = object()
_FLUSH_ONLY = object()


# ---------------------------------------------------------------------------
# Reader
# ---------------------------------------------------------------------------


def segment_records(path: str, crc: Optional[int], is_tail: bool) -> list:
    """Parse one segment. Committed segments verify the whole-payload
    crc first; a TOLERANT segment (``is_tail=True``: the final segment,
    or any uncommitted ``.open`` segment regardless of position — a
    crash's orphan stays torn even once newer segments exist behind it)
    degrades to loud line-by-line recovery; other damage raises."""
    with open(path, "rb") as f:
        blob = f.read()
    damaged = crc is not None and (zlib.crc32(blob) & 0xFFFFFFFF) != crc
    if damaged and not is_tail:
        raise RequestLogCorruptError(
            f"request-log segment {path} failed its crc32 check "
            f"(non-tail corruption — the durable log is damaged)"
        )
    records = []
    lines = blob.split(b"\n")
    for i, line in enumerate(lines):
        if not line.strip():
            continue
        try:
            records.append(json.loads(line))
        except json.JSONDecodeError:
            if is_tail:
                warnings.warn(
                    f"request-log tail segment {path} is truncated at "
                    f"record {len(records)}; recovered {len(records)} "
                    f"intact record(s) and skipped the torn tail",
                    RuntimeWarning,
                    stacklevel=3,
                )
                return records
            raise RequestLogCorruptError(
                f"request-log segment {path} holds a malformed record "
                f"at line {i} (non-tail corruption)"
            )
    if damaged:
        # Tail crc mismatch but every line parsed: a torn final WRITE
        # inside a committed name should be impossible (commit fsyncs
        # first) — surface it, keep the records.
        warnings.warn(
            f"request-log tail segment {path} failed its crc32 check "
            f"but every record parsed; keeping {len(records)} record(s)",
            RuntimeWarning,
            stacklevel=3,
        )
    return records


class RequestLogReader:
    """Positioned iterator over a request-log directory.

    ``state()`` -> ``{"epoch": <segment index>, "offset": <records
    consumed in that segment>}`` and ``seek(state)`` restore it — the
    exact contract of ``ft.data.ResumableIterator.state()``, so the
    flywheel ingest checkpoints its log position next to its model
    state and resumes without re-reading (or double-counting) a single
    record."""

    def __init__(self, directory: str):
        self.directory = directory
        self._segments = list_segments(directory)
        self._seg_pos = 0  # position within self._segments
        self._offset = 0  # records consumed in the current segment
        self._records: Optional[list] = None

    def state(self) -> dict:
        if self._seg_pos < len(self._segments):
            epoch = self._segments[self._seg_pos][0]
        else:
            epoch = (
                self._segments[-1][0] + 1 if self._segments else 0
            )
        return {"epoch": epoch, "offset": self._offset}

    def seek(self, state: dict) -> None:
        epoch = int(state["epoch"])
        offset = int(state["offset"])
        self._seg_pos = len(self._segments)
        for i, (idx, _, _) in enumerate(self._segments):
            if idx >= epoch:
                self._seg_pos = i
                break
        self._offset = offset if (
            self._seg_pos < len(self._segments)
            and self._segments[self._seg_pos][0] == epoch
        ) else 0
        self._records = None

    def _load(self) -> Optional[list]:
        if self._seg_pos >= len(self._segments):
            return None
        if self._records is None:
            _, crc, path = self._segments[self._seg_pos]
            # Tail tolerance is about COMMITMENT, not position: any
            # uncommitted (.open, crc None) segment may be torn — a
            # crashed process's orphan stays torn even after a new
            # writer opens higher-indexed segments behind it. Only a
            # crc-committed segment that is not the last one forfeits
            # tolerance.
            is_tail = (
                crc is None or self._seg_pos == len(self._segments) - 1
            )
            self._records = segment_records(path, crc, is_tail)
        return self._records

    def __iter__(self) -> Iterator[dict]:
        return self

    def __next__(self) -> dict:
        while True:
            records = self._load()
            if records is None:
                raise StopIteration
            if self._offset < len(records):
                rec = records[self._offset]
                self._offset += 1
                return rec
            self._seg_pos += 1
            self._offset = 0
            self._records = None


def read_request_log(directory: str) -> Iterator[dict]:
    """Iterate every recoverable record in ``directory`` in segment
    order: committed segments crc-verified, a truncated/corrupt tail
    skipped with a loud warning, non-tail corruption raised as
    ``RequestLogCorruptError``."""
    return RequestLogReader(directory)


# ---------------------------------------------------------------------------
# Record construction + the module-level active writer
# ---------------------------------------------------------------------------


def build_record(
    request_id: Any,
    finish_reason: str,
    *,
    tenant: Optional[str] = None,
    site: str = "engine",
    tokens_in: int = 0,
    tokens_out: int = 0,
    prefix_hit_tokens: int = 0,
    spec_proposed: int = 0,
    spec_accepted: int = 0,
    kv_page_seconds: float = 0.0,
    kv_byte_seconds: float = 0.0,
    adapter_reloads: int = 0,
    migrations: int = 0,
    queue_wait_s: Optional[float] = None,
    ttft_s: Optional[float] = None,
    tpot_s: Optional[float] = None,
    active_s: float = 0.0,
    ts: Optional[float] = None,
    prompt_ids: Optional[List[int]] = None,
    output_ids: Optional[List[int]] = None,
) -> dict:
    """One schema record. ``active_s`` is the slot-occupancy wall
    time (seat -> last token): the chip-seconds numerator of the
    cost-attribution table and, for tenant-ful requests, the adapter
    residency. ``prompt_ids``/``output_ids`` are the v2 OPTIONAL
    sample fields — only present when the caller passes them (the
    engine does so iff ``samples_enabled()``), so sample-less v2
    records stay byte-shaped like v1 plus the version stamp."""
    record = {
        "v": SCHEMA_VERSION,
        "ts": time.time() if ts is None else ts,
        "request_id": request_id,
        "tenant": tenant,
        "finish_reason": finish_reason,
        "site": site,
        "tokens_in": int(tokens_in),
        "tokens_out": int(tokens_out),
        "prefix_hit_tokens": int(prefix_hit_tokens),
        "spec_proposed": int(spec_proposed),
        "spec_accepted": int(spec_accepted),
        "kv_page_seconds": float(kv_page_seconds),
        "kv_byte_seconds": float(kv_byte_seconds),
        "adapter_reloads": int(adapter_reloads),
        "migrations": int(migrations),
        "queue_wait_s": queue_wait_s,
        "ttft_s": ttft_s,
        "tpot_s": tpot_s,
        "active_s": float(active_s),
    }
    if prompt_ids is not None:
        record["prompt_ids"] = [int(t) for t in prompt_ids]
    if output_ids is not None:
        record["output_ids"] = [int(t) for t in output_ids]
    return record


#: Programmatic override of the sample-capture knob (None = defer to
#: the env): the embedding surface for benches/hosts that toggle
#: capture per run without mutating ``os.environ``.
_samples_override: Optional[bool] = None


def set_samples_capture(value: Optional[bool]) -> None:
    """Force sample capture on/off for this process (``None`` restores
    the TPUDL_OBS_REQUEST_LOG_SAMPLES env knob's say)."""
    global _samples_override
    _samples_override = None if value is None else bool(value)


def samples_enabled() -> bool:
    """Whether completed results should carry ``prompt_ids`` /
    ``output_ids`` (the TPUDL_OBS_REQUEST_LOG_SAMPLES knob, unless
    ``set_samples_capture`` overrode it). Token ids
    are user content — capture is opt-in and separate from the metrics
    log, so operators can meter traffic without retaining prompts."""
    if _samples_override is not None:
        return _samples_override
    from tpudl.analysis.registry import env_flag

    return env_flag("TPUDL_OBS_REQUEST_LOG_SAMPLES")


_active: Optional[RequestLogWriter] = None
_atexit_registered = False


def enable(
    directory: str,
    segment_bytes: Optional[int] = None,
    queue_depth: Optional[int] = None,
) -> RequestLogWriter:
    """Activate the durable log into ``directory``. Idempotent-ish:
    re-enabling closes (commits) the previous writer first."""
    global _active, _atexit_registered
    if _active is not None:
        _active.close()
    _active = RequestLogWriter(
        directory,
        segment_bytes=(
            segment_bytes
            if segment_bytes is not None
            else env_int(
                "TPUDL_OBS_REQUEST_LOG_SEGMENT_BYTES",
                DEFAULT_SEGMENT_BYTES,
            )
        ),
        queue_depth=(
            queue_depth
            if queue_depth is not None
            else env_int(
                "TPUDL_OBS_REQUEST_LOG_QUEUE", DEFAULT_QUEUE_DEPTH
            )
        ),
    )
    if not _atexit_registered:
        atexit.register(disable)
        _atexit_registered = True
    return _active


def disable() -> None:
    """Close (commit) and deactivate the writer. No-op when inactive."""
    global _active
    if _active is not None:
        _active.close()
        _active = None


def active_writer() -> Optional[RequestLogWriter]:
    """The active writer, auto-enabling from TPUDL_OBS_REQUEST_LOG on
    first call (the span stream's TPUDL_OBS_DIR idiom) — None when
    disabled, the free branch every Result site takes."""
    if _active is not None:
        return _active
    log_dir = env_str("TPUDL_OBS_REQUEST_LOG")
    if log_dir:
        return enable(log_dir)
    return None


def log_result(record: dict) -> None:
    """The single emission chokepoint every Result site calls: feed the
    per-tenant meter (always — metering is in-memory and cheap), then
    the durable log iff enabled."""
    from tpudl.obs import metering

    metering.meter().ingest(record)
    w = active_writer()
    if w is not None:
        w.log(record)
