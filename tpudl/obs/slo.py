"""Declarative SLOs with multi-window burn-rate alerting.

An ``Objective`` states a latency target the way an operator would:
"p99 of ``serve_ttft_ms`` stays <= 250 ms over a rolling 5 minutes".
The ``SloMonitor`` evaluates a set of objectives from bounded
time-windowed observation streams and reports *burn rate* — how fast
the objective's error budget is being consumed — over a fast and a
slow window (the Google-SRE multiwindow/multi-burn-rate shape):

- the **error budget** of a p-quantile objective is ``1 - quantile``
  (p99 tolerates 1% of observations over threshold);
- a window's **burn rate** is its violating fraction divided by that
  budget (burn 1.0 = consuming budget exactly as fast as allowed;
  burn 20 = twenty times too fast);
- an objective is **burning** when BOTH windows exceed their burn
  thresholds: the slow window proves the breach is sustained (a single
  slow request cannot page), the fast window proves it is *still
  happening* — which is also what makes recovery fast: once the
  overload stops, the fast window drains and the alert clears without
  waiting out the slow window.

Subscribers (``subscribe(cb)``) get a callback on every transition
into or out of burning — the shed/autoscale hook the serve Engine's
admission path attaches to (``Engine.attach_slo``), and the monitor
registers as a ``/healthz`` source (``register_as_health_source``) so
a burning objective flips the probe to 503 with the burn arithmetic in
the body. The clock is injectable; window math is exact and testable
without sleeping. Stdlib-only, thread-safe, bounded memory (each
window is a deque capped in both time and element count).
"""

from __future__ import annotations

import dataclasses
import threading
import time
from collections import deque
from typing import Callable, Dict, List, Optional, Sequence

#: Cap on buffered observations per window — bounds memory when the
#: observation rate is extreme relative to the window length.
MAX_WINDOW_OBS = 65_536


@dataclasses.dataclass(frozen=True)
class Objective:
    """One declarative objective: ``quantile`` of ``metric`` must stay
    <= ``threshold`` over a rolling ``window_s``. ``fast_window_s`` is
    the confirmation window; ``slow_burn``/``fast_burn`` are the burn
    rates each must exceed for the objective to be burning. Windows
    with fewer than ``min_count`` observations report burn 0 — no
    alarm on no data."""

    name: str
    metric: str
    threshold: float
    quantile: float = 0.99
    window_s: float = 300.0
    fast_window_s: float = 30.0
    slow_burn: float = 1.0
    fast_burn: float = 1.0
    min_count: int = 5

    def __post_init__(self):
        if not 0.0 < self.quantile < 1.0:
            raise ValueError(
                f"quantile must be in (0, 1), got {self.quantile}"
            )
        if self.fast_window_s > self.window_s:
            raise ValueError(
                f"fast_window_s ({self.fast_window_s}) must not exceed "
                f"window_s ({self.window_s})"
            )

    @property
    def budget(self) -> float:
        return 1.0 - self.quantile


class _Window:
    """Time+count-bounded (timestamp, violated) buffer with a running
    violation count — O(evictions) trim, O(1) burn-rate readout."""

    __slots__ = ("span_s", "obs", "violations")

    def __init__(self, span_s: float):
        self.span_s = span_s
        self.obs: deque = deque(maxlen=MAX_WINDOW_OBS)
        self.violations = 0

    def add(self, t: float, violated: bool) -> None:
        if len(self.obs) == self.obs.maxlen and self.obs[0][1]:
            self.violations -= 1  # count-cap eviction of a violation
        self.obs.append((t, violated))
        if violated:
            self.violations += 1

    def trim(self, now: float) -> None:
        cutoff = now - self.span_s
        while self.obs and self.obs[0][0] < cutoff:
            if self.obs.popleft()[1]:
                self.violations -= 1

    def stats(self, budget: float, min_count: int) -> dict:
        n = len(self.obs)
        frac = self.violations / n if n else 0.0
        burn = (
            frac / max(budget, 1e-9) if n >= min_count else 0.0
        )
        return {
            "count": n,
            "violations": self.violations,
            "violation_fraction": frac,
            "burn_rate": burn,
        }


class SloMonitor:
    """Evaluate objectives from observation streams; fire subscriber
    callbacks on burning-state transitions.

    Feed it with ``observe(metric, value)`` (the serve engine routes
    its TTFT/TPOT/queue-wait observations here when attached);
    ``evaluate()`` trims windows against the injected clock, recomputes
    burn state, and fires transition callbacks — called from
    ``observe``, from the engine's admission path, and from
    ``/healthz`` probes, so recovery clears by time passing even with
    no new traffic."""

    def __init__(
        self,
        objectives: Sequence[Objective],
        clock: Callable[[], float] = time.monotonic,
    ):
        names = [o.name for o in objectives]
        if len(set(names)) != len(names):
            raise ValueError(f"duplicate objective names in {names}")
        self.objectives: List[Objective] = list(objectives)
        self.clock = clock
        self._lock = threading.Lock()
        # Serializes whole evaluations (state transition + callback
        # dispatch): without it, two threads (engine + /healthz probe)
        # could each capture one edge of a burn/clear pair and fire
        # the callbacks in the wrong order, latching a subscriber into
        # the stale state forever. Reentrant so a callback may call
        # observe()/evaluate() itself.
        self._eval_lock = threading.RLock()
        self._by_metric: Dict[str, List[Objective]] = {}
        for o in self.objectives:
            self._by_metric.setdefault(o.metric, []).append(o)
        self._fast: Dict[str, _Window] = {
            o.name: _Window(o.fast_window_s) for o in self.objectives
        }
        self._slow: Dict[str, _Window] = {
            o.name: _Window(o.window_s) for o in self.objectives
        }
        self._burning: Dict[str, bool] = {
            o.name: False for o in self.objectives
        }
        self._callbacks: List[Callable[[Objective, dict], None]] = []

    # -- feeding -------------------------------------------------------

    def observe(self, metric: str, value: float) -> None:
        """Record one observation of ``metric`` (same unit as the
        objective threshold) and re-evaluate the objectives watching
        it."""
        targets = self._by_metric.get(metric)
        if not targets:
            return
        now = self.clock()
        with self._lock:
            for o in targets:
                violated = float(value) > o.threshold
                self._fast[o.name].add(now, violated)
                self._slow[o.name].add(now, violated)
        self.evaluate()

    def watched_metrics(self) -> List[str]:
        return list(self._by_metric)

    # -- evaluation ----------------------------------------------------

    def subscribe(self, cb: Callable[[Objective, dict], None]) -> None:
        """``cb(objective, state)`` fires on every transition into or
        out of burning; ``state["burning"]`` is the new state. Fired
        synchronously from whichever thread drove the evaluation."""
        self._callbacks.append(cb)

    def evaluate(self) -> dict:
        """Trim windows to the clock, recompute per-objective burn
        state, fire transition callbacks, and return the full report."""
        with self._eval_lock:
            return self._evaluate_locked()

    def _evaluate_locked(self) -> dict:
        now = self.clock()
        report: dict = {}
        transitions: List[tuple] = []
        with self._lock:
            for o in self.objectives:
                fast, slow = self._fast[o.name], self._slow[o.name]
                fast.trim(now)
                slow.trim(now)
                fs = fast.stats(o.budget, o.min_count)
                ss = slow.stats(o.budget, o.min_count)
                burning = (
                    fs["burn_rate"] >= o.fast_burn
                    and ss["burn_rate"] >= o.slow_burn
                )
                state = {
                    "objective": o.name,
                    "metric": o.metric,
                    "threshold": o.threshold,
                    "quantile": o.quantile,
                    "budget": o.budget,
                    "burning": burning,
                    "fast": fs,
                    "slow": ss,
                }
                if burning != self._burning[o.name]:
                    self._burning[o.name] = burning
                    transitions.append((o, state))
                report[o.name] = state
        # Callbacks outside the STATE lock (a subscriber may call
        # observe()/evaluate() reentrantly) but inside the EVAL lock,
        # so cross-thread transition order matches callback order.
        for o, state in transitions:
            for cb in self._callbacks:
                cb(o, state)
        return report

    def burning_names(self) -> List[str]:
        self.evaluate()
        with self._lock:
            return [n for n, b in self._burning.items() if b]

    # -- surfacing -----------------------------------------------------

    def health(self) -> dict:
        """Health-source payload: unhealthy while any objective burns
        (what flips ``/healthz`` to 503 with the burning objective
        named in the body)."""
        report = self.evaluate()
        burning = sorted(n for n, s in report.items() if s["burning"])
        return {
            "healthy": not burning,
            "burning": burning,
            "objectives": report,
        }

    def register_as_health_source(self, name: str = "slo") -> "SloMonitor":
        from tpudl.obs import exporter as obs_exporter

        obs_exporter.register_health_source(name, self.health)
        return self
