"""Goodput accounting: classify run wall-clock into productive vs. lost.

"Goodput" is the fraction of wall-clock a run spent doing productive
training steps — the headline number large-scale training reports use
(Google's ML-goodput accounting, MegaScale's straggler diagnosis) and
the one the reference lineage never measured at all. Everything else is
attributed loss: compile, data stalls, checkpointing, and idle
(wall-clock no instrumented span covers — host-side Python, restarts,
anything unaccounted).

Input is the span-record stream tpudl.obs.spans produces. Within one
process the instrumented categories are sequential by construction
(fit's loop waits on data, then steps; the synchronous part of a
checkpoint save happens between steps), so seconds per category sum
without overlap bookkeeping; ``idle`` is clamped at zero to stay robust
if a custom instrumentation site violates that.

Multi-process runs classify per (host, process) and aggregate by
summing: total goodput = all productive seconds / all wall seconds, so
a straggler host drags the aggregate exactly as it drags the run."""

from __future__ import annotations

from typing import Dict, Iterable, Optional, Tuple

from tpudl.obs.spans import (
    CAT_CHECKPOINT,
    CAT_CKPT_BG,
    CAT_COMPILE,
    CAT_DATA_WAIT,
    CAT_ENCLOSING,
    CAT_EVAL,
    CAT_METRIC_WAIT,
    CAT_RECOVERY,
    CAT_STEP,
)

#: Categories with a dedicated column in the classification (anything
#: else lands in "other_s").
GOODPUT_CATEGORIES = (
    CAT_STEP, CAT_EVAL, CAT_COMPILE, CAT_DATA_WAIT, CAT_METRIC_WAIT,
    CAT_CHECKPOINT, CAT_RECOVERY,
)

#: Lifetime spans that ENCLOSE categorized spans on the same clock
#: (a distributor worker_run), plus deliberately-OVERLAPPED work (the
#: async checkpoint writer runs concurrently with train steps): they
#: extend the run window but are never accounted time — summing them
#: would double-count their interior and wipe out idle.
_WINDOW_ONLY_CATS = (CAT_ENCLOSING, CAT_CKPT_BG)


def process_key(record: dict) -> tuple:
    """Grouping identity of the RECORDING process: (host, process-index,
    OS pid). The pid matters — a distributor parent and its rank-0
    worker share host and process index 0 but run unrelated monotonic
    clocks, so lumping them together would compute wall-clock across
    incomparable timestamp epochs."""
    return (record.get("host", "?"), record.get("process", 0),
            record.get("pid"))


def process_labels(keys: Iterable[tuple]) -> Dict[tuple, str]:
    """Human labels for process keys: "host/pN", with the OS pid
    appended only when two keys would otherwise collide."""
    keys = sorted(keys, key=lambda k: (str(k[0]), k[1], str(k[2])))
    base: Dict[str, int] = {}
    for h, p, _ in keys:
        base[f"{h}/p{p}"] = base.get(f"{h}/p{p}", 0) + 1
    return {
        (h, p, pid): (
            f"{h}/p{p}" if base[f"{h}/p{p}"] == 1 else f"{h}/p{p}@{pid}"
        )
        for h, p, pid in keys
    }


def classify(
    records: Iterable[dict],
    window: Optional[Tuple[float, float]] = None,
) -> dict:
    """Classify ONE process's records into per-category seconds.

    ``window`` overrides the run extent (seconds on the recording
    process's clock); default is [earliest span start, latest span end].
    Enclosing lifetime spans (cat "worker") and overlapped background
    writes (cat "ckpt_bg") only widen the window.
    Returns ``{"wall_s", "steps", "productive_s", "eval_s", "compile_s",
    "data_wait_s", "checkpoint_s", "recovery_s", "other_s", "idle_s",
    "goodput"}`` where productive_s counts train steps, eval_s counts
    eval steps, recovery_s is wall-clock lost to failure recovery, and
    goodput = (productive_s + eval_s) / wall_s — useful work over
    wall-clock.
    """
    spans = [r for r in records if r.get("kind") == "span"]
    per_cat: Dict[str, float] = {c: 0.0 for c in GOODPUT_CATEGORIES}
    other = 0.0
    steps = 0
    lo, hi = None, None
    for s in spans:
        ts, dur = float(s["ts"]), float(s["dur"])
        lo = ts if lo is None else min(lo, ts)
        hi = ts + dur if hi is None else max(hi, ts + dur)
        cat = s.get("cat")
        if cat in _WINDOW_ONLY_CATS:
            continue
        if cat in per_cat:
            per_cat[cat] += dur
            if cat == CAT_STEP:
                # A fused dispatch_window span covers K train steps in
                # one record (its "window" attr); count them all so
                # goodput-per-step stays comparable across dispatch
                # modes.
                steps += int(s.get("window", 1) or 1)
        else:
            other += dur
    if window is not None:
        lo, hi = window
    wall = (hi - lo) if (lo is not None and hi is not None) else 0.0
    accounted = sum(per_cat.values()) + other
    idle = max(0.0, wall - accounted)
    useful = per_cat[CAT_STEP] + per_cat[CAT_EVAL]
    return {
        "wall_s": wall,
        "steps": steps,
        "productive_s": per_cat[CAT_STEP],
        "eval_s": per_cat[CAT_EVAL],
        "compile_s": per_cat[CAT_COMPILE],
        "data_wait_s": per_cat[CAT_DATA_WAIT],
        "metric_wait_s": per_cat[CAT_METRIC_WAIT],
        "checkpoint_s": per_cat[CAT_CHECKPOINT],
        "recovery_s": per_cat[CAT_RECOVERY],
        "other_s": other,
        "idle_s": idle,
        "goodput": useful / wall if wall > 0 else 0.0,
    }


def classify_by_process(records: Iterable[dict]) -> dict:
    """Group records by recording process (see ``process_key``),
    classify each, and aggregate.

    Returns ``{"per_process": {"host/pN": classification},
    "overall": classification}`` where overall sums seconds across
    processes (goodput = total useful / total wall)."""
    groups: Dict[tuple, list] = {}
    for r in records:
        if r.get("kind") != "span":
            continue
        groups.setdefault(process_key(r), []).append(r)
    labels = process_labels(groups)
    per = {
        labels[key]: classify(groups[key]) for key in sorted(
            groups, key=lambda k: labels[k]
        )
    }
    overall = {
        k: sum(c[k] for c in per.values())
        for k in (
            "wall_s", "steps", "productive_s", "eval_s", "compile_s",
            "data_wait_s", "metric_wait_s", "checkpoint_s", "recovery_s",
            "other_s", "idle_s",
        )
    } if per else classify([])
    if per:
        overall["goodput"] = (
            (overall["productive_s"] + overall["eval_s"])
            / overall["wall_s"]
            if overall["wall_s"] > 0 else 0.0
        )
    return {"per_process": per, "overall": overall}


def format_goodput(cls: dict) -> str:
    """One-line human rendering of a classification."""
    wall = cls["wall_s"]

    def pct(x):
        return 100.0 * x / wall if wall > 0 else 0.0

    useful = cls["productive_s"] + cls.get("eval_s", 0.0)
    recovery = cls.get("recovery_s", 0.0)
    recovery_part = (
        f"recovery {pct(recovery):.1f}%, " if recovery > 0 else ""
    )
    metric_wait = cls.get("metric_wait_s", 0.0)
    metric_part = (
        f"metric_wait {pct(metric_wait):.1f}%, " if metric_wait > 0 else ""
    )
    return (
        f"goodput {100.0 * cls['goodput']:.1f}% "
        f"({useful:.2f}s useful of {wall:.2f}s wall; "
        f"compile {pct(cls['compile_s']):.1f}%, "
        f"data_wait {pct(cls['data_wait_s']):.1f}%, "
        f"{metric_part}"
        f"checkpoint {pct(cls['checkpoint_s']):.1f}%, "
        f"{recovery_part}"
        f"other {pct(cls['other_s']):.1f}%, "
        f"idle {pct(cls['idle_s']):.1f}%)"
    )
