"""Per-tenant usage metering: who consumed which chips.

PR 14 made serving multi-tenant (adapters, quotas, SLO classes) with
zero per-tenant observability — quota sheds land in ONE aggregate
counter and "which tenant ate the fleet" is unanswerable. This module
is the metering plane: a process-wide ``TenantMeter`` fed one
schema-v1 request-log record at a time (``tpudl.obs.requestlog``'s
``log_result`` chokepoint — the SAME records the durable log
persists, so the live meter and the offline cost table can never
disagree about what happened), rolled up per tenant and rendered as
tenant-LABELED Prometheus series via PR 10's
``render_prometheus(labels=...)``:

- ``serve_tenant_requests_total`` / ``serve_tenant_requests_completed``
- ``serve_tenant_tokens_in_total`` / ``serve_tenant_tokens_total``
  (tokens served)
- ``serve_tenant_requests_shed_<reason>`` — sheds split by tenant AND
  reason (the aggregate ``serve_requests_shed_*`` counters in the main
  registry are untouched; labels carry provenance, names never do)
- ``serve_tenant_kv_byte_seconds_total`` — KV footprint x residency,
  the bytes-model cost numerator
- ``serve_tenant_adapter_residency_seconds_total`` — wall time the
  tenant's adapter held slot pins
- ``serve_tenant_adapter_reloads_total`` — thrash attribution
- ``serve_tenant_chip_seconds_total`` — slot-occupancy seconds
- ``serve_tenant_quota_utilization`` — gauge fed by
  ``Router.load_report()`` (inflight tokens / quota)

The base model (tenant None) meters under ``tenant="_base"`` so the
label set is total: every request lands in exactly one tenant series.

``ObsExporter.metrics_text()`` appends ``render_tenants()`` to the
aggregate exposition, so one scrape carries both planes.
"""

from __future__ import annotations

import threading
from typing import Dict, Optional

#: Label value for requests with no tenant (the plain base model):
#: metering must be total over requests, and an absent label would
#: make per-tenant sums silently non-reconciling.
BASE_TENANT = "_base"


class _TenantUsage:
    """Mutable rollup for one tenant (all fields cumulative)."""

    __slots__ = (
        "requests_total", "requests_completed", "tokens_in",
        "tokens_out", "prefix_hit_tokens", "spec_proposed",
        "spec_accepted", "kv_page_seconds", "kv_byte_seconds",
        "adapter_reloads", "adapter_residency_s", "chip_seconds",
        "migrations", "sheds", "quota_utilization",
    )

    def __init__(self):
        self.requests_total = 0
        self.requests_completed = 0
        self.tokens_in = 0
        self.tokens_out = 0
        self.prefix_hit_tokens = 0
        self.spec_proposed = 0
        self.spec_accepted = 0
        self.kv_page_seconds = 0.0
        self.kv_byte_seconds = 0.0
        self.adapter_reloads = 0
        self.adapter_residency_s = 0.0
        self.chip_seconds = 0.0
        self.migrations = 0
        self.sheds: Dict[str, int] = {}
        self.quota_utilization: Optional[float] = None


class TenantMeter:
    """Thread-safe per-tenant usage rollups over request-log records."""

    def __init__(self):
        self._lock = threading.Lock()
        self._tenants: Dict[str, _TenantUsage] = {}

    def _usage(self, tenant: Optional[str]) -> _TenantUsage:
        key = tenant if tenant is not None else BASE_TENANT
        u = self._tenants.get(key)
        if u is None:
            u = self._tenants[key] = _TenantUsage()
        return u

    def ingest(self, record: dict) -> None:
        """Fold one schema-v1 request-log record into its tenant's
        rollup. Records are terminal (one per request), so
        requests_total is exact."""
        reason = record.get("finish_reason", "?")
        with self._lock:
            u = self._usage(record.get("tenant"))
            u.requests_total += 1
            u.tokens_in += int(record.get("tokens_in", 0) or 0)
            u.tokens_out += int(record.get("tokens_out", 0) or 0)
            u.prefix_hit_tokens += int(
                record.get("prefix_hit_tokens", 0) or 0
            )
            u.spec_proposed += int(record.get("spec_proposed", 0) or 0)
            u.spec_accepted += int(record.get("spec_accepted", 0) or 0)
            u.kv_page_seconds += float(
                record.get("kv_page_seconds", 0.0) or 0.0
            )
            u.kv_byte_seconds += float(
                record.get("kv_byte_seconds", 0.0) or 0.0
            )
            u.adapter_reloads += int(
                record.get("adapter_reloads", 0) or 0
            )
            u.migrations += int(record.get("migrations", 0) or 0)
            active = float(record.get("active_s", 0.0) or 0.0)
            u.chip_seconds += active
            if record.get("tenant") is not None:
                u.adapter_residency_s += active
            if reason in ("eos", "length"):
                u.requests_completed += 1
            else:
                # Every non-completion is a shed class (shed_*,
                # failover_exhausted, failed: ..., rejected: ...) —
                # normalize BOTH free-text families ("failed: <exc>"
                # from the engine, "rejected: <exc>" from the router)
                # to one bucket each, so sheds keys (and the Prometheus
                # metric NAMES render() mints from them) stay a closed
                # set instead of growing one series per distinct
                # exception message.
                if reason.startswith("failed"):
                    key = "failed"
                elif reason.startswith("rejected"):
                    key = "rejected"
                else:
                    key = reason
                u.sheds[key] = u.sheds.get(key, 0) + 1

    def set_quota_utilization(
        self, tenant: Optional[str], utilization: float
    ) -> None:
        """Gauge hook for ``Router.load_report()``: inflight-token
        quota utilization in [0, inf) (>1 = over-admitted burst)."""
        with self._lock:
            self._usage(tenant).quota_utilization = float(utilization)

    def tenants(self) -> Dict[str, dict]:
        """Plain-dict snapshot of every tenant's rollup (test +
        report surface)."""
        with self._lock:
            out = {}
            for t, u in self._tenants.items():
                out[t] = {
                    "requests_total": u.requests_total,
                    "requests_completed": u.requests_completed,
                    "tokens_in": u.tokens_in,
                    "tokens_out": u.tokens_out,
                    "prefix_hit_tokens": u.prefix_hit_tokens,
                    "spec_proposed": u.spec_proposed,
                    "spec_accepted": u.spec_accepted,
                    "kv_page_seconds": u.kv_page_seconds,
                    "kv_byte_seconds": u.kv_byte_seconds,
                    "adapter_reloads": u.adapter_reloads,
                    "adapter_residency_s": u.adapter_residency_s,
                    "chip_seconds": u.chip_seconds,
                    "migrations": u.migrations,
                    "sheds": dict(u.sheds),
                    "quota_utilization": u.quota_utilization,
                }
            return out

    def render(self) -> str:
        """Tenant-labeled Prometheus exposition: one
        ``render_prometheus(labels={"tenant": t})`` block per tenant,
        concatenated. Counter semantics hold (cumulative, monotone);
        the label carries provenance so metric NAMES stay tenant-free."""
        from tpudl.obs.exporter import render_prometheus

        parts = []
        snap = self.tenants()
        for tenant in sorted(snap):
            u = snap[tenant]
            counters = {
                "serve_tenant_requests_total": u["requests_total"],
                "serve_tenant_requests_completed": (
                    u["requests_completed"]
                ),
                "serve_tenant_tokens_in_total": u["tokens_in"],
                "serve_tenant_tokens_total": u["tokens_out"],
                "serve_tenant_prefix_hit_tokens_total": (
                    u["prefix_hit_tokens"]
                ),
                "serve_tenant_kv_byte_seconds_total": (
                    u["kv_byte_seconds"]
                ),
                "serve_tenant_adapter_residency_seconds_total": (
                    u["adapter_residency_s"]
                ),
                "serve_tenant_adapter_reloads_total": (
                    u["adapter_reloads"]
                ),
                "serve_tenant_chip_seconds_total": u["chip_seconds"],
            }
            for reason, n in sorted(u["sheds"].items()):
                counters[f"serve_tenant_requests_{reason}"] = n
            gauges = {}
            if u["quota_utilization"] is not None:
                gauges["serve_tenant_quota_utilization"] = (
                    u["quota_utilization"]
                )
            parts.append(
                render_prometheus(
                    {"counters": counters, "gauges": gauges},
                    labels={"tenant": tenant},
                )
            )
        return "".join(parts)

    def reset(self) -> None:
        with self._lock:
            self._tenants.clear()


_meter = TenantMeter()


def meter() -> TenantMeter:
    """The process-wide tenant meter (the ``registry()`` idiom)."""
    return _meter


def render_tenants() -> str:
    """Module-level convenience the exporter appends to its aggregate
    exposition."""
    return _meter.render()
