"""L-cross runtime observability: spans, counters, goodput, reports.

Three observability layers exist in tpudl, deliberately split:

- ``tpudl.train.metrics``   — model-quality and throughput math
  (images/sec/chip, MFU): numbers ABOUT the training computation.
- ``tpudl.train.profiling`` — inside-the-step device view: parses the
  XLA trace ``jax.profiler.trace`` writes into per-op-category time /
  TFLOP/s / GB/s. Answers "where does the DEVICE step go".
- ``tpudl.obs`` (this package) — outside-the-step host view: where the
  rest of the RUN's wall-clock goes. Spans around the runtime's blocking
  calls (data wait, compiled-step dispatch, compile, checkpoint save)
  stream to JSONL; counters accumulate volumes (bytes ingested,
  saves); the goodput classifier turns them into "this run was 71%
  productive and host-3 was the straggler". Answers "where does the
  WALL-CLOCK go" — the question neither of the other two can.

The two trace views compose: ``SpanRecorder.export_chrome_trace``
writes the host spans as Chrome trace-event JSON that loads in
Perfetto NEXT TO the XLA device trace, one timeline.

Zero hard dependencies (stdlib only), thread-safe, and free when
disabled: every instrumentation site guards on
``spans.active_recorder() is None``. Enable by setting
``TPUDL_OBS_DIR=/path`` (the profiler-hook idiom) or calling
``tpudl.obs.enable(path)``; report with
``python -m tpudl.obs.report /path``.

On top of the post-mortem stream sits the LIVE plane
(``tpudl.obs.exporter``, enabled via ``TPUDL_OBS_PORT``): a stdlib
HTTP server exposing ``/metrics`` (Prometheus text from the registry),
``/healthz`` (heartbeats + component health sources, probe-compatible
200/503), and ``/snapshot`` (registry + live goodput + the active span
-stream path) while the process runs — ``tpudl.obs.slo`` evaluates
declarative latency objectives with burn-rate alerting over it, and
``tpudl.obs.fleet`` aggregates N such processes into one labeled
fleet view (merged ``/metrics``, health rollup, cross-process trace
stitching) for the serve tier's autoscaler.

The serve tier additionally persists one versioned record per terminal
``Result`` into a durable crc-guarded request log
(``tpudl.obs.requestlog``, enabled via ``TPUDL_OBS_REQUEST_LOG``) —
the span stream dies with the process, the request log is the artifact
the continual-learning flywheel ingests — and the same records feed
the per-tenant metering plane (``tpudl.obs.metering``):
tenant-labeled Prometheus series and the ``report.py --tenants``
cost-attribution table.
"""

from tpudl.obs.counters import (  # noqa: F401
    Counter,
    Gauge,
    Histogram,
    Registry,
    registry,
)
from tpudl.obs.exporter import (  # noqa: F401
    Heartbeat,
    ObsExporter,
    active_exporter,
    format_labels,
    health_snapshot,
    register_health_source,
    render_prometheus,
    start_exporter,
    stop_exporter,
    unregister_health_source,
)
from tpudl.obs.fleet import (  # noqa: F401
    FleetMonitor,
    render_fleet_prometheus,
)
from tpudl.obs.goodput import (  # noqa: F401
    classify,
    classify_by_process,
    format_goodput,
)
from tpudl.obs.metering import (  # noqa: F401
    TenantMeter,
    meter,
    render_tenants,
)
from tpudl.obs.requestlog import (  # noqa: F401
    SCHEMA_VERSION,
    RequestLogCorruptError,
    RequestLogReader,
    RequestLogWriter,
    build_record,
    log_result,
    read_request_log,
)
from tpudl.obs.report import (  # noqa: F401
    build_fleet_report,
    build_report,
    build_request_timeline,
    format_fleet_report,
    format_report,
    format_request_timeline,
    load_records,
)
from tpudl.obs.slo import Objective, SloMonitor  # noqa: F401
from tpudl.obs.spans import (  # noqa: F401
    SpanRecorder,
    active_recorder,
    chrome_trace_events,
    disable,
    enable,
    read_jsonl,
    span,
)
