"""L-cross runtime observability: spans, counters, goodput, reports.

Three observability layers exist in tpudl, deliberately split:

- ``tpudl.train.metrics``   — model-quality and throughput math
  (images/sec/chip, MFU): numbers ABOUT the training computation.
- ``tpudl.train.profiling`` — inside-the-step device view: parses the
  XLA trace ``jax.profiler.trace`` writes into per-op-category time /
  TFLOP/s / GB/s. Answers "where does the DEVICE step go".
- ``tpudl.obs`` (this package) — outside-the-step host view: where the
  rest of the RUN's wall-clock goes. Spans around the runtime's blocking
  calls (data wait, compiled-step dispatch, compile, checkpoint save)
  stream to JSONL; counters accumulate volumes (bytes ingested,
  saves); the goodput classifier turns them into "this run was 71%
  productive and host-3 was the straggler". Answers "where does the
  WALL-CLOCK go" — the question neither of the other two can.

The two trace views compose: ``SpanRecorder.export_chrome_trace``
writes the host spans as Chrome trace-event JSON that loads in
Perfetto NEXT TO the XLA device trace, one timeline.

Zero hard dependencies (stdlib only), thread-safe, and free when
disabled: every instrumentation site guards on
``spans.active_recorder() is None``. Enable by setting
``TPUDL_OBS_DIR=/path`` (the profiler-hook idiom) or calling
``tpudl.obs.enable(path)``; report with
``python -m tpudl.obs.report /path``.
"""

from tpudl.obs.counters import (  # noqa: F401
    Counter,
    Gauge,
    Histogram,
    Registry,
    registry,
)
from tpudl.obs.goodput import (  # noqa: F401
    classify,
    classify_by_process,
    format_goodput,
)
from tpudl.obs.report import (  # noqa: F401
    build_report,
    format_report,
    load_records,
)
from tpudl.obs.spans import (  # noqa: F401
    SpanRecorder,
    active_recorder,
    chrome_trace_events,
    disable,
    enable,
    read_jsonl,
    span,
)
