"""Process-wide counters / gauges / histograms with snapshot export.

The numeric complement of tpudl.obs.spans: spans say WHEN time went
somewhere, counters say HOW MUCH of something accumulated (bytes
ingested, checkpoint saves, worker retries) and histograms hold the
per-step latency distributions (step_time, data_wait, compile_time,
checkpoint_time) the report quotes p50/p95/p99 from.

Stdlib-only and thread-safe like the span recorder. One module-level
default registry; ``registry().snapshot()`` produces a plain-dict
summary that rides the span JSONL stream as a ``{"kind": "counters"}``
record (``SpanRecorder.counters``), so one file carries both."""

from __future__ import annotations

import math
import threading

from tpudl.analysis.registry import env_int
from typing import Dict, List, Optional

#: Default rolling-window size for Histogram (see TPUDL_OBS_HIST_WINDOW).
DEFAULT_HIST_WINDOW = 65_536


class Counter:
    """Monotonically increasing count (events, bytes, retries)."""

    __slots__ = ("_lock", "_value")

    def __init__(self):
        self._lock = threading.Lock()
        self._value = 0.0

    def inc(self, n: float = 1.0) -> None:
        if n < 0:
            raise ValueError(f"Counter.inc is monotonic, got {n}")
        with self._lock:
            self._value += n

    @property
    def value(self) -> float:
        return self._value


class Gauge:
    """Last-write-wins scalar (current lr, queue depth, loss)."""

    __slots__ = ("_lock", "_value")

    def __init__(self):
        self._lock = threading.Lock()
        self._value = 0.0

    def set(self, v: float) -> None:
        with self._lock:
            self._value = float(v)

    @property
    def value(self) -> float:
        return self._value


def percentile(sorted_values: List[float], q: float) -> float:
    """Linear-interpolation percentile (numpy's default) on an already
    SORTED list — stdlib-only so the obs layer carries no numpy
    dependency."""
    if not sorted_values:
        return math.nan
    if len(sorted_values) == 1:
        return sorted_values[0]
    pos = (len(sorted_values) - 1) * q
    lo = int(math.floor(pos))
    hi = int(math.ceil(pos))
    if lo == hi:
        return sorted_values[lo]
    frac = pos - lo
    return sorted_values[lo] * (1 - frac) + sorted_values[hi] * frac


class Histogram:
    """Latency/size distribution over a bounded rolling window.

    Up to ``window`` raw observations are kept (default 65,536,
    overridable via ``TPUDL_OBS_HIST_WINDOW``), so snapshots report
    EXACT percentiles — of the most recent window — rather than bucket
    estimates. Past the window the oldest observation is ring-evicted:
    a long-lived serving process holds a fixed ~512 KB of floats per
    histogram instead of growing without bound (and each ``snapshot()``
    sorts a bounded list instead of the full run history). ``count``
    and ``sum`` stay CUMULATIVE over every observation ever made — the
    monotone pair Prometheus rate() math needs — while min/max/mean of
    the *windowed* values describe recent behavior."""

    __slots__ = ("_lock", "_values", "_window", "_count", "_sum")

    def __init__(self, window: Optional[int] = None):
        if window is None:
            window = env_int("TPUDL_OBS_HIST_WINDOW", DEFAULT_HIST_WINDOW)
        if window < 1:
            raise ValueError(f"histogram window must be >= 1, got {window}")
        self._lock = threading.Lock()
        self._values: List[float] = []
        self._window = window
        self._count = 0
        self._sum = 0.0

    @property
    def window(self) -> int:
        return self._window

    def observe(self, v: float) -> None:
        v = float(v)
        with self._lock:
            if len(self._values) < self._window:
                self._values.append(v)
            else:
                # Ring-evict the oldest: slot i of the full buffer holds
                # observation (count - window + i), so the write cursor
                # is simply count modulo window.
                self._values[self._count % self._window] = v
            self._count += 1
            self._sum += v

    @property
    def count(self) -> int:
        """Cumulative observation count (not capped by the window)."""
        return self._count

    @property
    def values(self) -> List[float]:
        """The windowed observations, oldest first."""
        with self._lock:
            if self._count <= self._window:
                return list(self._values)
            cursor = self._count % self._window
            return self._values[cursor:] + self._values[:cursor]

    def snapshot(self) -> dict:
        with self._lock:
            vals = sorted(self._values)
            count, total = self._count, self._sum
        if not vals:
            return {"count": 0}
        return {
            "count": count,
            "sum": total,
            "min": vals[0],
            "max": vals[-1],
            # Windowed like min/max/percentiles (self-consistent recent
            # view); count/sum above stay cumulative for rate() math.
            # Identical to sum/count until the window first wraps.
            "mean": sum(vals) / len(vals),
            "p50": percentile(vals, 0.50),
            "p95": percentile(vals, 0.95),
            "p99": percentile(vals, 0.99),
        }


class Registry:
    """Name -> instrument map with get-or-create accessors. A name is
    bound to ONE kind; re-requesting it as another kind raises (two
    subsystems silently sharing "step_time" as counter and histogram
    would corrupt both)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._instruments: Dict[str, object] = {}

    def _get(self, name: str, cls):
        with self._lock:
            inst = self._instruments.get(name)
            if inst is None:
                inst = cls()
                self._instruments[name] = inst
            elif not isinstance(inst, cls):
                raise TypeError(
                    f"instrument {name!r} already registered as "
                    f"{type(inst).__name__}, requested {cls.__name__}"
                )
            return inst

    def counter(self, name: str) -> Counter:
        return self._get(name, Counter)

    def gauge(self, name: str) -> Gauge:
        return self._get(name, Gauge)

    def histogram(self, name: str) -> Histogram:
        return self._get(name, Histogram)

    def snapshot(self) -> dict:
        """Plain-dict summary of every instrument, JSON-ready."""
        with self._lock:
            items = list(self._instruments.items())
        out: dict = {"counters": {}, "gauges": {}, "histograms": {}}
        for name, inst in items:
            if isinstance(inst, Counter):
                out["counters"][name] = inst.value
            elif isinstance(inst, Gauge):
                out["gauges"][name] = inst.value
            else:
                out["histograms"][name] = inst.snapshot()
        return out

    def reset(self) -> None:
        with self._lock:
            self._instruments.clear()


_default: Optional[Registry] = None
_default_lock = threading.Lock()


def registry() -> Registry:
    """The process-wide default registry (created on first use)."""
    global _default
    if _default is None:
        with _default_lock:
            if _default is None:
                _default = Registry()
    return _default
