"""Fleet observability plane: cross-replica metric aggregation + trace
stitching.

Every telemetry surface before this one is per-process: PR 6's
``ObsExporter`` serves ONE registry, the serve router scrapes replicas
only for placement, and a request that hops router -> prefill worker ->
decode replica scatters its spans across separate streams. This module
is the fleet-level consumer:

- **FleetMonitor** polls N exporter endpoints (``/snapshot`` over HTTP,
  or an in-process ``ObsExporter.snapshot`` callable — the test seam
  and the single-process router's path) and merges their registries
  into one LABELED fleet view: ``serve_slots_busy{source="replica1"}``
  instead of N mangled metric names. Its own ``/metrics`` endpoint
  serves the merged exposition plus per-source scrape-age / failure /
  up gauges, ``/fleet`` serves the JSON health rollup (per-source
  healthy/burning/error with an overall verdict), and ``/healthz``
  gives probes the 200/503 contract over that rollup.
- **Trace stitching**: each member's ``/snapshot`` names its active
  span-stream file (``span_path``, written when ``TPUDL_OBS_DIR`` is
  set), so ``trace_records()`` discovers and merges every member's
  JSONL stream with no out-of-band config — the records
  ``report.py --fleet`` / ``--request`` stitch into one
  router-door -> queue -> prefill -> inbox -> decode timeline, and
  ``chrome_trace_events`` renders with one track per process.

Clock discipline: member span streams use per-process MONOTONIC clocks,
so the stitcher never subtracts timestamps across streams — hop
decomposition sums DURATIONS, each measured by the process that owned
the hop (see tpudl.obs.report.build_request_timeline).

Stdlib-only, thread-safe, injectable clock, like the rest of tpudl.obs.
Scrapes are time-gated on access (``scrape_interval_s``) so a scrape
storm against ``/metrics`` does not turn into a scrape storm against
every member.
"""

from __future__ import annotations

import json
import os
import threading
import time
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable, Dict, List, Optional, Union

from tpudl.analysis.concurrency import maybe_wrap_locks
from tpudl.obs.exporter import _QUANTILES, _fmt, _metric_name, format_labels
from tpudl.obs.spans import read_jsonl

#: A source: the member's /snapshot URL, or a zero-arg callable
#: returning the same payload in-process (ObsExporter.snapshot).
Source = Union[str, Callable[[], dict]]


def render_fleet_prometheus(
    snapshots: Dict[str, dict],
    extra_gauges: Optional[Dict[str, Dict[Optional[str], float]]] = None,
) -> str:
    """Merge per-source ``Registry.snapshot()`` dicts into ONE valid
    Prometheus exposition: each metric's ``# TYPE`` line appears once,
    followed by one series per source labeled ``{source="..."}`` —
    the grouping the exposition format requires (concatenating N
    single-source renders would repeat TYPE lines per metric).

    ``extra_gauges`` adds fleet-level gauges: ``{metric: {source:
    value}}`` where a ``None`` source key renders an unlabeled
    (fleet-scoped) series."""
    counters: Dict[str, Dict[str, float]] = {}
    gauges: Dict[str, Dict[str, float]] = {}
    histograms: Dict[str, Dict[str, dict]] = {}
    for source in sorted(snapshots):
        snap = snapshots[source] or {}
        for name, v in snap.get("counters", {}).items():
            counters.setdefault(name, {})[source] = v
        for name, v in snap.get("gauges", {}).items():
            gauges.setdefault(name, {})[source] = v
        for name, h in snap.get("histograms", {}).items():
            histograms.setdefault(name, {})[source] = h
    lines: List[str] = []
    for name in sorted(counters):
        m = _metric_name(name)
        lines.append(f"# TYPE {m} counter")
        for source in sorted(counters[name]):
            suffix = format_labels({"source": source})
            lines.append(f"{m}{suffix} {_fmt(counters[name][source])}")
    for name in sorted(gauges):
        m = _metric_name(name)
        lines.append(f"# TYPE {m} gauge")
        for source in sorted(gauges[name]):
            suffix = format_labels({"source": source})
            lines.append(f"{m}{suffix} {_fmt(gauges[name][source])}")
    for name in sorted(histograms):
        m = _metric_name(name)
        lines.append(f"# TYPE {m} summary")
        for source in sorted(histograms[name]):
            h = histograms[name][source]
            if h.get("count"):
                for q, key in _QUANTILES:
                    qsuffix = format_labels(
                        {"source": source, "quantile": q}
                    )
                    lines.append(f"{m}{qsuffix} {_fmt(h[key])}")
            suffix = format_labels({"source": source})
            lines.append(f"{m}_sum{suffix} {_fmt(h.get('sum', 0.0))}")
            lines.append(f"{m}_count{suffix} {int(h.get('count', 0))}")
    for name in sorted(extra_gauges or {}):
        m = _metric_name(name)
        lines.append(f"# TYPE {m} gauge")
        for source in sorted(
            (extra_gauges or {})[name], key=lambda s: (s is not None, s)
        ):
            suffix = (
                format_labels({"source": source})
                if source is not None else ""
            )
            value = (extra_gauges or {})[name][source]
            lines.append(f"{m}{suffix} {_fmt(value)}")
    return "\n".join(lines) + "\n"


def _burning_names(health: dict) -> List[str]:
    """Every burning objective named anywhere in a member's health
    report (the SloMonitor health source's ``burning`` list; the serve
    router's ``burning_replicas`` ride along too)."""
    out: List[str] = []
    for src in (health or {}).get("sources", {}).values():
        if not isinstance(src, dict):
            continue
        for key in ("burning", "burning_replicas"):
            names = src.get(key)
            if isinstance(names, (list, tuple)):
                out.extend(str(n) for n in names)
    return sorted(set(out))


class FleetMonitor:
    """Poll N member ``/snapshot`` endpoints; serve the merged view.

    ``sources`` maps member name -> ``/snapshot`` URL (or any URL whose
    GET returns the snapshot JSON) or an in-process callable returning
    the same payload. A member that fails to scrape keeps its LAST GOOD
    registry in the merged ``/metrics`` (stale data is visible through
    its ``fleet_scrape_age_s`` gauge, absent data is not) but reads as
    unhealthy in the rollup until a scrape succeeds again."""

    def __init__(
        self,
        sources: Dict[str, Source],
        scrape_interval_s: float = 0.5,
        scrape_timeout_s: float = 2.0,
        clock: Callable[[], float] = time.monotonic,
        retry_backoff_s: float = 0.05,
        retry_backoff_max_s: float = 1.0,
        sleep: Callable[[float], None] = time.sleep,
    ):
        if not sources:
            raise ValueError("FleetMonitor needs at least one source")
        self.sources: Dict[str, Source] = dict(sources)
        self.scrape_interval_s = scrape_interval_s
        self.scrape_timeout_s = scrape_timeout_s
        self.clock = clock
        #: One IN-BAND retry per member per poll, after an exponential
        #: backoff (base doubling with the member's consecutive-failure
        #: count, capped) with jitter — a single transient HTTP hiccup
        #: no longer bumps ``fleet_scrape_failures_total`` and ages the
        #: member; a genuinely down member costs one bounded extra wait.
        self.retry_backoff_s = retry_backoff_s
        self.retry_backoff_max_s = retry_backoff_max_s
        self._sleep = sleep
        #: Chaos seam (tpudl.serve.chaos.install_scrape_chaos): called
        #: with the member name before every scrape ATTEMPT; raising =
        #: blackholed poll, sleeping = slow member.
        self.scrape_fault: Optional[Callable[[str], None]] = None
        self._lock = threading.RLock()
        maybe_wrap_locks(self)
        self._state: Dict[str, dict] = {
            name: {
                "ok": False,
                "snapshot": None,
                "last_ok_at": None,
                "failures": 0,
                "error": "never scraped",
            }
            for name in self.sources
        }
        self._last_scrape = float("-inf")
        self._server: Optional[ThreadingHTTPServer] = None
        self._thread: Optional[threading.Thread] = None

    # -- membership (the autoscaler adds/removes replicas live) --------

    def add_source(self, name: str, source: Source) -> None:
        with self._lock:
            self.sources[name] = source
            self._state[name] = {
                "ok": False, "snapshot": None, "last_ok_at": None,
                "failures": 0, "error": "never scraped",
            }

    def remove_source(self, name: str) -> None:
        with self._lock:
            self.sources.pop(name, None)
            self._state.pop(name, None)

    # -- scraping ------------------------------------------------------

    def _scrape_one(self, name: str, source: Source) -> dict:
        if self.scrape_fault is not None:
            self.scrape_fault(name)
        if callable(source):
            return dict(source())
        with urllib.request.urlopen(
            source, timeout=self.scrape_timeout_s
        ) as resp:
            return json.loads(resp.read().decode())

    def _retry_delay(self, failures: int) -> float:
        """Backoff before the in-band retry: base doubling with the
        member's consecutive-failure count (capped), jittered ±50% so N
        monitors scraping a recovering fleet do not retry in lockstep
        — the standard thundering-herd hedge."""
        import random

        base = min(
            self.retry_backoff_s * (2 ** min(failures, 10)),
            self.retry_backoff_max_s,
        )
        return base * (0.5 + random.random())

    def scrape(self, force: bool = True) -> None:
        """Scrape every member (time-gated unless ``force``), with ONE
        in-band backoff+jitter retry per member before a poll counts as
        failed — a transient hiccup costs a short sleep, not a failure
        counter bump and an aged member. A member failed after the
        retry records the error; its last good snapshot is retained."""
        now = self.clock()
        with self._lock:
            if not force and now - self._last_scrape < self.scrape_interval_s:
                return
            self._last_scrape = now
            sources = dict(self.sources)
            failure_counts = {
                name: st["failures"] for name, st in self._state.items()
            }
        for name, source in sources.items():
            snap = None
            err = None
            for attempt in (0, 1):
                try:
                    snap = self._scrape_one(name, source)
                    err = None
                    break
                except Exception as e:
                    err = f"{type(e).__name__}: {e}"
                    if attempt == 0:
                        self._sleep(
                            self._retry_delay(failure_counts.get(name, 0))
                        )
            with self._lock:
                st = self._state.get(name)
                if st is None:  # removed mid-scrape
                    continue
                if err is None:
                    st["ok"] = True
                    st["snapshot"] = snap
                    st["last_ok_at"] = self.clock()
                    st["error"] = None
                else:
                    st["ok"] = False
                    st["failures"] += 1
                    st["error"] = err

    # -- the merged views ----------------------------------------------

    def snapshots(self) -> Dict[str, Optional[dict]]:
        """Last good full /snapshot payload per member (None until one
        lands)."""
        with self._lock:
            return {
                name: st["snapshot"] for name, st in self._state.items()
            }

    def fleet_snapshot(self) -> dict:
        """The health rollup ``/fleet`` serves: per-member scrape state
        + health verdict + burning objectives, and the fleet-level
        ``healthy`` AND (the k8s-probe contract: one sick member is a
        sick fleet)."""
        self.scrape(force=False)
        now = self.clock()
        with self._lock:
            states = {n: dict(st) for n, st in self._state.items()}
        sources: dict = {}
        healthy = True
        burning_sources: List[str] = []
        for name in sorted(states):
            st = states[name]
            snap = st["snapshot"] or {}
            health = snap.get("health") or {}
            member_healthy = bool(st["ok"]) and bool(
                health.get("healthy", True)
            )
            # Burn state only counts from a member we can still REACH:
            # a dead member's stale last-good snapshot must read as
            # "unhealthy, unreachable", not as a burning SLO — the
            # autoscaler treats burning as pressure, and a crashed
            # replica must not pin the fleet at max_replicas forever.
            burning = _burning_names(health) if st["ok"] else []
            if burning:
                burning_sources.append(name)
            age = (
                now - st["last_ok_at"]
                if st["last_ok_at"] is not None else None
            )
            sources[name] = {
                "ok": st["ok"],
                "healthy": member_healthy,
                "scrape_age_s": age,
                "scrape_failures": st["failures"],
                "error": st["error"],
                "burning": burning,
                "span_path": snap.get("span_path"),
            }
            healthy = healthy and member_healthy
        return {
            "sources": sources,
            "sources_total": len(sources),
            "sources_healthy": sum(
                1 for s in sources.values() if s["healthy"]
            ),
            "burning_sources": burning_sources,
            "healthy": healthy,
        }

    def burning_sources(self) -> List[str]:
        """Members whose health report names a burning SLO objective —
        the fleet-level scale-up signal."""
        return self.fleet_snapshot()["burning_sources"]

    def metrics_text(self) -> str:
        """The merged labeled exposition: every member's registry under
        ``{source="<name>"}`` plus the fleet's own per-source
        scrape-age / failure / up gauges and the health rollup."""
        fleet = self.fleet_snapshot()
        with self._lock:
            regs = {
                name: (st["snapshot"] or {}).get("registry") or {}
                for name, st in self._state.items()
            }
        extra: Dict[str, Dict[Optional[str], float]] = {
            "fleet_sources_total": {None: fleet["sources_total"]},
            "fleet_sources_healthy": {None: fleet["sources_healthy"]},
            "fleet_healthy": {None: float(fleet["healthy"])},
            "fleet_source_up": {},
            "fleet_scrape_failures_total": {},
            "fleet_scrape_age_s": {},
        }
        for name, src in fleet["sources"].items():
            extra["fleet_source_up"][name] = float(src["ok"])
            extra["fleet_scrape_failures_total"][name] = float(
                src["scrape_failures"]
            )
            if src["scrape_age_s"] is not None:
                extra["fleet_scrape_age_s"][name] = src["scrape_age_s"]
        return render_fleet_prometheus(regs, extra_gauges=extra)

    # -- trace stitching -----------------------------------------------

    def trace_paths(self) -> Dict[str, str]:
        """Each member's active span-stream file, discovered from its
        ``/snapshot`` payload (satellite contract: no out-of-band
        config). Members without recording active are absent."""
        self.scrape(force=False)
        out: Dict[str, str] = {}
        with self._lock:
            for name, st in self._state.items():
                path = (st["snapshot"] or {}).get("span_path")
                if path:
                    out[name] = path
        return out

    def trace_records(
        self, extra_paths: tuple = (), missing_ok: bool = True
    ) -> List[dict]:
        """Merge every discovered member span stream (plus
        ``extra_paths`` files/dirs) into one record list — the input to
        ``report.build_request_timeline`` / ``build_fleet_report``. A
        discovered path that does not exist on THIS host (a truly
        remote member) is skipped when ``missing_ok``."""
        from tpudl.obs.report import load_records

        records: List[dict] = []
        for path in sorted(set(self.trace_paths().values())):
            if not os.path.exists(path):
                if missing_ok:
                    continue
                raise FileNotFoundError(path)
            records.extend(read_jsonl(path))
        if extra_paths:
            records.extend(load_records(list(extra_paths)))
        return records

    # -- the HTTP server -----------------------------------------------

    def start(self, port: int = 0, host: str = "127.0.0.1") -> "FleetMonitor":
        """Serve ``/metrics`` (merged labeled exposition), ``/fleet``
        (JSON rollup), and ``/healthz`` (200/503 over the rollup).
        Loopback by default — the endpoints are unauthenticated."""
        if self._server is not None:
            return self
        monitor = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *args):
                pass

            def _send(self, code, body: bytes, ctype: str):
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                path = self.path.split("?", 1)[0].rstrip("/") or "/"
                try:
                    if path == "/metrics":
                        self._send(
                            200,
                            monitor.metrics_text().encode(),
                            "text/plain; version=0.0.4; charset=utf-8",
                        )
                    elif path == "/fleet":
                        self._send(
                            200,
                            json.dumps(monitor.fleet_snapshot()).encode(),
                            "application/json",
                        )
                    elif path == "/healthz":
                        fleet = monitor.fleet_snapshot()
                        self._send(
                            200 if fleet["healthy"] else 503,
                            json.dumps(fleet).encode(),
                            "application/json",
                        )
                    else:
                        self._send(404, b"not found\n", "text/plain")
                except Exception as e:  # never kill the server thread
                    try:
                        self._send(
                            500,
                            f"{type(e).__name__}: {e}\n".encode(),
                            "text/plain",
                        )
                    except OSError:
                        pass
        self._server = ThreadingHTTPServer((host, int(port)), Handler)
        self._server.daemon_threads = True
        self._thread = threading.Thread(
            target=self._server.serve_forever,
            name="tpudl-fleet-monitor",
            daemon=True,
        )
        self._thread.start()
        return self

    @property
    def port(self) -> Optional[int]:
        if self._server is None:
            return None
        return self._server.server_address[1]

    def close(self) -> None:
        if self._server is None:
            return
        self._server.shutdown()
        self._server.server_close()
        self._server = None
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None

    def __enter__(self) -> "FleetMonitor":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
