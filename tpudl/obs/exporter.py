"""Live telemetry plane: background HTTP exporter + health sources.

tpudl.obs's first five subsystem integrations were post-mortem: spans
land in JSONL and answers come from ``report.py`` after the process
exits. This module is the LIVE half — a stdlib-only background HTTP
server any operator (or the serve router) can query while the process
runs:

- ``GET /metrics``  — Prometheus text exposition rendered from
  ``Registry.snapshot()``: counters and gauges verbatim, histograms as
  ``_count``/``_sum`` plus exact-quantile gauges (``quantile`` label),
  and one ``*_heartbeat_age_s`` gauge per registered heartbeat.
- ``GET /healthz``  — liveness + readiness JSON: every registered
  health source (serve engine slots/queue, MetricFetcher / checkpoint
  writer sticky errors, SLO monitor burn state) plus heartbeat ages
  (train-loop last step, distributor per-rank). HTTP 200 when every
  source is healthy and no running heartbeat is stale, 503 otherwise —
  a k8s/probe-compatible contract.
- ``GET /snapshot`` — the full JSON registry snapshot, the health
  report, and the live goodput classification of the active span
  stream (what ``report.py`` would print, computed in-process).

Activation mirrors the span recorder's: set ``TPUDL_OBS_PORT``
(``fit()`` and ``ServeSession`` call ``maybe_start_from_env()``), or
construct/start an ``ObsExporter`` directly — port 0 binds an
ephemeral port (``.port`` reports the real one), which is how tests
inject it. Stdlib-only and thread-safe like the rest of tpudl.obs:
scrapes run concurrently with observation on the instrument locks.

Health sources are process-global so instrumented subsystems need no
handle on the exporter: ``register_health_source(name, fn)`` with
``fn() -> dict`` (a ``"healthy": bool`` key; absent means healthy, a
raising source reports unhealthy with the error). ``Heartbeat`` is the
liveness flavor: a component beats it each unit of progress and the
exporter reports the age, flagging a RUNNING heartbeat that has gone
stale — how a hung train loop or rank becomes visible within seconds
instead of at post-mortem.
"""

from __future__ import annotations

import atexit
import json
import math
import os
import re
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable, Dict, Optional

from tpudl.analysis.registry import env_float, env_int, env_str
from tpudl.obs import counters as obs_counters
from tpudl.obs import spans as obs_spans

#: A running heartbeat older than this is stale (seconds); override
#: per-heartbeat or via TPUDL_OBS_HEARTBEAT_STALE_S.
DEFAULT_HEARTBEAT_STALE_S = 60.0

_state_lock = threading.Lock()
_health_sources: Dict[str, Callable[[], dict]] = {}
_heartbeats: Dict[str, "Heartbeat"] = {}


# ---------------------------------------------------------------------------
# Health sources + heartbeats
# ---------------------------------------------------------------------------


def register_health_source(name: str, fn: Callable[[], dict]) -> None:
    """Register (or replace) a named health callable. ``fn`` returns a
    JSON-ready dict; a ``"healthy": False`` key marks the component
    unhealthy (absent counts as healthy); a raising ``fn`` reports
    unhealthy with the exception text instead of breaking the probe."""
    with _state_lock:
        _health_sources[name] = fn


def unregister_health_source(name: str) -> None:
    with _state_lock:
        _health_sources.pop(name, None)


class Heartbeat:
    """Progress liveness signal: ``beat()`` each unit of work; the
    exporter reports the age and flags a running-but-stale heartbeat as
    unhealthy. ``stop()`` marks orderly completion (a stopped heartbeat
    is never stale — "finished" is healthy, "hung" is not).

    Staleness is ADAPTIVE to the beat cadence: the threshold is
    ``max(stale_after, adaptive_factor x the last beat interval)``, so
    a train loop whose fused dispatch windows legitimately take minutes
    is not flagged hung between beats — only a beat gap far outside
    its own established rhythm is."""

    def __init__(
        self,
        name: str,
        stale_after: Optional[float] = None,
        clock: Callable[[], float] = time.monotonic,
        register: bool = True,
        adaptive_factor: float = 5.0,
    ):
        if stale_after is None:
            stale_after = env_float(
                "TPUDL_OBS_HEARTBEAT_STALE_S", DEFAULT_HEARTBEAT_STALE_S
            )
        self.name = name
        self.stale_after = stale_after
        self.adaptive_factor = adaptive_factor
        self.clock = clock
        self._lock = threading.Lock()
        self._last: Optional[float] = None
        self._interval: Optional[float] = None
        self._step: Optional[int] = None
        self._running = True
        if register:
            with _state_lock:
                _heartbeats[name] = self

    def beat(self, step: Optional[int] = None) -> None:
        self.beat_at(self.clock(), step=step)

    def beat_at(self, t: float, step: Optional[int] = None) -> None:
        """Record a beat observed to have happened at clock time ``t``
        (the distributor's span-file-mtime path, where the parent infers
        a rank's progress time rather than witnessing it)."""
        with self._lock:
            if self._last is not None and t > self._last:
                self._interval = t - self._last
            self._last = t
            if step is not None:
                self._step = int(step)
            self._running = True

    def stop(self) -> None:
        with self._lock:
            self._running = False

    def unregister(self) -> None:
        with _state_lock:
            if _heartbeats.get(self.name) is self:
                del _heartbeats[self.name]

    def age_s(self) -> Optional[float]:
        with self._lock:
            if self._last is None:
                return None
            return max(0.0, self.clock() - self._last)

    def stale_threshold_s(self) -> float:
        with self._lock:
            interval = self._interval
        if interval is None:
            return self.stale_after
        return max(self.stale_after, self.adaptive_factor * interval)

    def health(self) -> dict:
        age = self.age_s()
        threshold = self.stale_threshold_s()
        with self._lock:
            running, step = self._running, self._step
        stale = bool(running and age is not None and age > threshold)
        out = {
            "running": running,
            "age_s": age,
            "stale_threshold_s": threshold,
            "stale": stale,
            "healthy": not stale,
        }
        if step is not None:
            out["step"] = step
        return out


def heartbeat_ages() -> Dict[str, float]:
    """Current age per registered heartbeat (beaten ones only) —
    the cheap read /metrics needs, WITHOUT evaluating health sources
    (a source like SloMonitor.health has transition side effects; a
    scrape endpoint must not drive them)."""
    with _state_lock:
        hearts = dict(_heartbeats)
    out: Dict[str, float] = {}
    for name, hb in hearts.items():
        age = hb.age_s()
        if age is not None:
            out[name] = age
    return out


def health_snapshot() -> dict:
    """Evaluate every health source and heartbeat into one JSON-ready
    report with an overall ``healthy`` verdict."""
    with _state_lock:
        sources = dict(_health_sources)
        hearts = dict(_heartbeats)
    report: dict = {"sources": {}, "heartbeats": {}}
    healthy = True
    for name, fn in sorted(sources.items()):
        try:
            s = dict(fn())
        except Exception as e:  # a broken source IS an unhealthy source
            s = {"healthy": False, "error": f"{type(e).__name__}: {e}"}
        s.setdefault("healthy", True)
        healthy = healthy and bool(s["healthy"])
        report["sources"][name] = s
    for name, hb in sorted(hearts.items()):
        h = hb.health()
        healthy = healthy and h["healthy"]
        report["heartbeats"][name] = h
    report["healthy"] = healthy
    return report


def _reset_health_for_tests() -> None:
    """Drop every registered source/heartbeat (process-global state —
    the test-isolation analog of Registry.reset)."""
    with _state_lock:
        _health_sources.clear()
        _heartbeats.clear()


# ---------------------------------------------------------------------------
# Prometheus text rendering
# ---------------------------------------------------------------------------

_NAME_OK = re.compile(r"[a-zA-Z_:][a-zA-Z0-9_:]*$")
_NAME_FIX = re.compile(r"[^a-zA-Z0-9_:]")
_LABEL_NAME_OK = re.compile(r"[a-zA-Z_][a-zA-Z0-9_]*$")

#: Exact-percentile quantiles rendered per histogram (the keys
#: Registry.snapshot already computes).
_QUANTILES = (("0.5", "p50"), ("0.95", "p95"), ("0.99", "p99"))


def _metric_name(name: str) -> str:
    name = _NAME_FIX.sub("_", name)
    if not _NAME_OK.match(name):
        name = "_" + name
    return name


def _fmt(v: float) -> str:
    v = float(v)
    if math.isnan(v):
        return "NaN"
    if math.isinf(v):
        return "+Inf" if v > 0 else "-Inf"
    return repr(v)


def _label_value(v) -> str:
    """Escape one label value per the exposition format (backslash,
    quote, newline)."""
    return (
        str(v)
        .replace("\\", "\\\\")
        .replace('"', '\\"')
        .replace("\n", "\\n")
    )


def format_labels(labels: Optional[Dict[str, str]]) -> str:
    """``{"source": "replica1"}`` -> ``{source="replica1"}`` (empty
    string for no labels). Label NAMES must already be exposition-legal
    — they come from code, not data, so a bad one is a caller bug and
    raises rather than being silently mangled into the metric name."""
    if not labels:
        return ""
    for k in labels:
        if not _LABEL_NAME_OK.match(k):
            raise ValueError(f"illegal Prometheus label name {k!r}")
    inner = ",".join(
        f'{k}="{_label_value(v)}"' for k, v in sorted(labels.items())
    )
    return "{" + inner + "}"


def render_prometheus(
    snapshot: dict,
    heartbeats: Optional[Dict[str, float]] = None,
    labels: Optional[Dict[str, str]] = None,
) -> str:
    """A ``Registry.snapshot()`` dict -> Prometheus text exposition
    (version 0.0.4). Counters and gauges render verbatim; histograms as
    summaries: cumulative ``_count``/``_sum`` plus exact-quantile rows
    over the bounded window. ``heartbeats`` (name -> age seconds, see
    ``heartbeat_ages``) ride along as gauges.

    ``labels`` attaches the same label set to every series (the fleet
    view's ``{source="replica1"}``) instead of mangling provenance into
    metric names; histogram quantile rows merge it with their
    ``quantile`` label. ``labels=None`` output is byte-identical to the
    pre-label renderer (regression-tested)."""
    suffix = format_labels(labels)
    lines = []
    for name, value in sorted(snapshot.get("counters", {}).items()):
        m = _metric_name(name)
        lines.append(f"# TYPE {m} counter")
        lines.append(f"{m}{suffix} {_fmt(value)}")
    for name, value in sorted(snapshot.get("gauges", {}).items()):
        m = _metric_name(name)
        lines.append(f"# TYPE {m} gauge")
        lines.append(f"{m}{suffix} {_fmt(value)}")
    for name, h in sorted(snapshot.get("histograms", {}).items()):
        m = _metric_name(name)
        lines.append(f"# TYPE {m} summary")
        if h.get("count"):
            for q, key in _QUANTILES:
                qsuffix = format_labels(
                    {**(labels or {}), "quantile": q}
                )
                lines.append(f"{m}{qsuffix} {_fmt(h[key])}")
        lines.append(f"{m}_sum{suffix} {_fmt(h.get('sum', 0.0))}")
        lines.append(f"{m}_count{suffix} {int(h.get('count', 0))}")
    for name, age in sorted((heartbeats or {}).items()):
        m = _metric_name(f"{name}_heartbeat_age_s")
        lines.append(f"# TYPE {m} gauge")
        lines.append(f"{m}{suffix} {_fmt(age)}")
    return "\n".join(lines) + "\n"


# ---------------------------------------------------------------------------
# The HTTP server
# ---------------------------------------------------------------------------


class ObsExporter:
    """Background HTTP server over the obs registry + health state.

    ``port=0`` binds an ephemeral port; ``.port`` reports the bound
    one. ``registry`` defaults to the process-wide default at serve
    time (not bound at construction, so a test-reset registry is picked
    up). One OS thread per in-flight request (ThreadingHTTPServer), so
    a slow scrape never blocks the health probe.

    The default bind is LOOPBACK: the endpoints are unauthenticated,
    so exposing them beyond the host is an explicit choice —
    ``host="0.0.0.0"`` (or ``TPUDL_OBS_HOST`` for the env-activated
    exporter) for a containerized scraper."""

    def __init__(
        self,
        port: int = 0,
        host: str = "127.0.0.1",
        registry: Optional[obs_counters.Registry] = None,
    ):
        self._registry = registry
        self._server: Optional[ThreadingHTTPServer] = None
        self._thread: Optional[threading.Thread] = None
        self._host = host
        self._requested_port = int(port)

    # -- payload builders (also the testable seam) ---------------------

    def _reg(self) -> obs_counters.Registry:
        return (
            self._registry
            if self._registry is not None
            else obs_counters.registry()
        )

    def metrics_text(self) -> str:
        # Aggregate registry first, then the per-tenant metering plane
        # (tenant-LABELED series, same exposition format) — one scrape
        # carries both. Lazy import: metering renders THROUGH
        # render_prometheus above, so a module-level import would be a
        # cycle.
        from tpudl.obs import metering

        return render_prometheus(
            self._reg().snapshot(), heartbeat_ages()
        ) + metering.render_tenants()

    def health(self) -> dict:
        return health_snapshot()

    def snapshot(self) -> dict:
        out = {
            "time": time.time(),
            "registry": self._reg().snapshot(),
            "health": health_snapshot(),
            "goodput": None,
            # Span-stream discovery for the fleet trace stitcher
            # (tpudl.obs.fleet): when TPUDL_OBS_DIR is active this
            # names the file the process is streaming spans into, so
            # stitching needs no out-of-band path config.
            "span_path": None,
        }
        rec = obs_spans.active_recorder()
        if rec is not None:
            out["span_path"] = (
                os.path.abspath(rec.path) if rec.path else None
            )
            try:
                from tpudl.obs import goodput as goodput_mod

                cls = goodput_mod.classify_by_process(rec.records)
                out["goodput"] = cls["overall"]
                out["goodput_per_process"] = cls["per_process"]
            except Exception as e:
                out["goodput_error"] = f"{type(e).__name__}: {e}"
        return out

    # -- lifecycle -----------------------------------------------------

    def start(self) -> "ObsExporter":
        if self._server is not None:
            return self
        exporter = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *args):  # no stderr chatter per scrape
                pass

            def _send(self, code, body: bytes, ctype: str):
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                path = self.path.split("?", 1)[0].rstrip("/") or "/"
                try:
                    if path == "/metrics":
                        self._send(
                            200,
                            exporter.metrics_text().encode(),
                            "text/plain; version=0.0.4; charset=utf-8",
                        )
                    elif path == "/healthz":
                        h = exporter.health()
                        self._send(
                            200 if h["healthy"] else 503,
                            json.dumps(h).encode(),
                            "application/json",
                        )
                    elif path == "/snapshot":
                        self._send(
                            200,
                            json.dumps(exporter.snapshot()).encode(),
                            "application/json",
                        )
                    else:
                        self._send(404, b"not found\n", "text/plain")
                except Exception as e:  # never kill the server thread
                    try:
                        self._send(
                            500,
                            f"{type(e).__name__}: {e}\n".encode(),
                            "text/plain",
                        )
                    except OSError:
                        pass  # client hung up mid-reply

        self._server = ThreadingHTTPServer(
            (self._host, self._requested_port), Handler
        )
        self._server.daemon_threads = True
        self._thread = threading.Thread(
            target=self._server.serve_forever,
            name="tpudl-obs-exporter",
            daemon=True,
        )
        self._thread.start()
        return self

    @property
    def port(self) -> Optional[int]:
        if self._server is None:
            return None
        return self._server.server_address[1]

    @property
    def running(self) -> bool:
        return self._server is not None

    def close(self) -> None:
        if self._server is None:
            return
        self._server.shutdown()
        self._server.server_close()
        self._server = None
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None

    def __enter__(self) -> "ObsExporter":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.close()


# ---------------------------------------------------------------------------
# Module-level active exporter (the TPUDL_OBS_PORT switch)
# ---------------------------------------------------------------------------

_active: Optional[ObsExporter] = None
_atexit_registered = False


def start_exporter(
    port: int = 0, host: Optional[str] = None
) -> ObsExporter:
    """Start (or return) the process-wide exporter. Re-calling with the
    exporter already running returns it unchanged — fit() and serving
    may both call this. ``host`` defaults to ``TPUDL_OBS_HOST`` or
    loopback (see ObsExporter)."""
    global _active, _atexit_registered
    if _active is not None and _active.running:
        return _active
    if host is None:
        host = env_str("TPUDL_OBS_HOST", "127.0.0.1")
    _active = ObsExporter(port=port, host=host).start()
    if not _atexit_registered:
        atexit.register(stop_exporter)
        _atexit_registered = True
    return _active


def stop_exporter() -> None:
    global _active
    if _active is not None:
        _active.close()
        _active = None


def active_exporter() -> Optional[ObsExporter]:
    return _active


def maybe_start_from_env() -> Optional[ObsExporter]:
    """Start the process-wide exporter iff ``TPUDL_OBS_PORT`` is set
    (the instrumented-layer hook — one env lookup when disabled). Port
    0 is honored: it binds an ephemeral port, the test idiom.

    A failed BIND on this path warns and returns None instead of
    raising: distributor workers inherit the env (every rank would
    race for one port), and a supervised restart can overlap its
    predecessor's grace window — telemetry is best-effort, it must
    never turn a port conflict into a dead training run. An explicit
    ``start_exporter()``/``ObsExporter.start()`` still raises."""
    if _active is not None and _active.running:
        return _active
    port = env_int("TPUDL_OBS_PORT")
    if port is None:
        return None
    try:
        return start_exporter(port=port)
    except OSError as e:
        import warnings

        warnings.warn(
            f"tpudl.obs: could not bind the telemetry exporter on port "
            f"{port} ({e}); continuing without live telemetry",
            RuntimeWarning,
            stacklevel=2,
        )
        return None
