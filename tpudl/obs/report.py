"""Aggregate span/counter streams into the run report: step-time
breakdown, goodput, outliers, and per-host straggler attribution.

    python -m tpudl.obs.report /path/to/obs-dir        # or *.jsonl files
    python -m tpudl.obs.report run.jsonl --json
    python -m tpudl.obs.report run.jsonl --chrome-trace trace.json
    python -m tpudl.obs.report serve-run.jsonl --request r17

This is the "why was this run only 71% productive, and which host was
slow" answer as an artifact, not a vibe: it loads one or many span JSONL
files (a distributor run merges its workers' files into the parent's —
see tpudl.runtime.distributor — but loose per-worker files work too
since every record carries host/process tags), then prints

- a per-category latency table (count, total, mean, p50/p95/p99) over
  data_wait / step / compile / checkpoint spans;
- the goodput classification (tpudl.obs.goodput);
- outlier steps (duration > ``outlier_factor`` x the p50 step time),
  each attributed to its host/process;
- per-host step-time means with stragglers flagged (mean above
  ``straggler_factor`` x the cross-host median);
- a served-request outcome breakdown (completed vs each shed reason,
  with queue-wait/TTFT means per reason), when a serve run's
  ``request_complete`` events rode the stream;
- the last counters snapshot per process, if any rode the stream.

``--request <id>`` switches to per-request trace mode: the serve
path's distributed trace (``request_id`` propagated from admission
through prefill, every decode chunk, and completion) is stitched into
one timeline for that request, and its TTFT is decomposed into
queue-wait / prefill / first-decode-chunk, with the total checked
against the measured TTFT + generation time.

``--chrome-trace`` additionally re-exports the loaded records as
Chrome trace-event JSON for Perfetto, next to the XLA device trace."""

from __future__ import annotations

import glob
import json
import os
from typing import Dict, Iterable, List, Optional

from tpudl.obs import goodput as goodput_mod
from tpudl.obs.counters import percentile
from tpudl.obs.spans import (
    CAT_CHECKPOINT,
    CAT_CKPT_BG,
    CAT_COMPILE,
    CAT_DATA_WAIT,
    CAT_EVAL,
    CAT_METRIC_WAIT,
    CAT_RECOVERY,
    CAT_STEP,
    chrome_trace_events,
    read_jsonl,
)

#: Table row order: the lifecycle order of one step; the overlapped
#: background-write row and recovery last (present only when nonzero).
_TABLE_CATS = (CAT_DATA_WAIT, CAT_STEP, CAT_EVAL, CAT_COMPILE,
               CAT_METRIC_WAIT, CAT_CHECKPOINT, CAT_CKPT_BG, CAT_RECOVERY)


def load_records(paths: Iterable[str]) -> List[dict]:
    """Load span records from JSONL files and/or directories (directories
    glob ``*.jsonl``, recursively — a distributor obs dir with a
    ``workers/`` subdir loads in one argument)."""
    files: List[str] = []
    for p in paths:
        if os.path.isdir(p):
            hits = sorted(
                glob.glob(os.path.join(p, "**", "*.jsonl"), recursive=True)
            )
            if not hits:
                raise FileNotFoundError(f"no *.jsonl files under {p}")
            files.extend(hits)
        else:
            files.append(p)
    records: List[dict] = []
    for f in files:
        records.extend(read_jsonl(f))
    return records


def _dist(durs: List[float]) -> dict:
    vals = sorted(durs)
    return {
        "count": len(vals),
        "total_s": sum(vals),
        "mean_ms": 1e3 * sum(vals) / len(vals) if vals else 0.0,
        "p50_ms": 1e3 * percentile(vals, 0.50) if vals else 0.0,
        "p95_ms": 1e3 * percentile(vals, 0.95) if vals else 0.0,
        "p99_ms": 1e3 * percentile(vals, 0.99) if vals else 0.0,
    }


def build_report(
    records: List[dict],
    outlier_factor: float = 3.0,
    straggler_factor: float = 1.2,
) -> dict:
    """Span records -> report dict (see module docstring for contents)."""
    spans = [r for r in records if r.get("kind") == "span"]
    by_cat: Dict[str, List[float]] = {}
    for s in spans:
        by_cat.setdefault(s.get("cat", "other"), []).append(float(s["dur"]))
    breakdown = {
        cat: _dist(by_cat[cat]) for cat in _TABLE_CATS if cat in by_cat
    }
    for cat in sorted(set(by_cat) - set(_TABLE_CATS)):
        breakdown[cat] = _dist(by_cat[cat])

    # Outlier steps: anything beyond outlier_factor x the p50 TRAIN-step
    # time (eval steps have their own duration scale and stay out of
    # these statistics), attributed to host/process so cross-host blips
    # are visible. Fused dispatch_window spans cover K steps each (the
    # "window" attr), so their duration normalizes to per-step time
    # before comparison — a K=8 window is not an 8x outlier.
    step_spans = [s for s in spans if s.get("cat") == CAT_STEP]

    def _per_step_dur(s) -> float:
        return float(s["dur"]) / int(s.get("window", 1) or 1)

    outliers: List[dict] = []
    p50 = (
        percentile(sorted(_per_step_dur(s) for s in step_spans), 0.50)
        if step_spans else 0.0
    )
    if p50 > 0:
        for s in step_spans:
            dur = _per_step_dur(s)
            if dur > outlier_factor * p50:
                outliers.append({
                    "host": s.get("host", "?"),
                    "process": s.get("process", 0),
                    "step": s.get("step"),
                    "ms": 1e3 * dur,
                    "x_p50": dur / p50,
                })
        outliers.sort(key=lambda o: -o["ms"])

    # Per-host/process straggler attribution over per-step means
    # (grouped by recording process incl. OS pid — see
    # goodput.process_key).
    per_host_keyed: Dict[tuple, List[float]] = {}
    for s in step_spans:
        per_host_keyed.setdefault(
            goodput_mod.process_key(s), []
        ).append(_per_step_dur(s))
    labels = goodput_mod.process_labels(per_host_keyed)
    per_host = {
        labels[k]: per_host_keyed[k]
        for k in sorted(per_host_keyed, key=lambda k: labels[k])
    }
    host_rows = {key: _dist(durs) for key, durs in per_host.items()}
    means = sorted(r["mean_ms"] for r in host_rows.values())
    median_mean = percentile(means, 0.50) if means else 0.0
    for key, row in host_rows.items():
        ratio = row["mean_ms"] / median_mean if median_mean > 0 else 0.0
        row["x_median"] = ratio
        row["straggler"] = bool(
            len(host_rows) > 1 and ratio > straggler_factor
        )

    # Last counters snapshot per recording process, if any rode the
    # stream.
    counters_keyed: Dict[tuple, dict] = {}
    for r in records:
        if r.get("kind") == "counters":
            counters_keyed[goodput_mod.process_key(r)] = r.get("data", {})
    clabels = goodput_mod.process_labels(counters_keyed)
    counters = {
        clabels[k]: counters_keyed[k]
        for k in sorted(counters_keyed, key=lambda k: clabels[k])
    }

    return {
        "num_records": len(records),
        "num_span_records": len(spans),
        "breakdown": breakdown,
        "goodput": goodput_mod.classify_by_process(records),
        "outlier_steps": outliers,
        "outlier_factor": outlier_factor,
        "per_host": host_rows,
        "straggler_factor": straggler_factor,
        "serve_requests": serve_request_breakdown(records),
        "counters": counters,
    }


def serve_request_breakdown(records: Iterable[dict]) -> dict:
    """Aggregate serve ``request_complete`` events by outcome: one row
    per finish_reason (completed-by-eos/length vs each shed reason)
    with count and queue-wait/TTFT means — the cross-request view of
    what admission did under load. Empty dict when the stream carries
    no serve traffic."""
    by_reason: Dict[str, List[dict]] = {}
    for r in records:
        if r.get("kind") == "event" and r.get("name") == "request_complete":
            by_reason.setdefault(
                r.get("finish_reason", "?"), []
            ).append(r)
    out: dict = {}
    for reason in sorted(by_reason):
        evs = by_reason[reason]
        waits = [
            float(e["queue_wait_s"]) for e in evs
            if e.get("queue_wait_s") is not None
        ]
        ttfts = [
            float(e["ttft_s"]) for e in evs if e.get("ttft_s") is not None
        ]
        out[reason] = {
            "count": len(evs),
            "mean_queue_wait_ms": (
                1e3 * sum(waits) / len(waits) if waits else None
            ),
            "mean_ttft_ms": 1e3 * sum(ttfts) / len(ttfts) if ttfts else None,
            "tokens": sum(int(e.get("num_tokens", 0) or 0) for e in evs),
        }
    return out


# ---------------------------------------------------------------------------
# Per-request trace mode (--request)
# ---------------------------------------------------------------------------


def build_request_timeline(records: Iterable[dict], request_id) -> dict:
    """Stitch one request's distributed trace out of a serve run's
    records: the admission event, the prefill span carrying its
    ``request_id``, every decode chunk whose ``rids`` include it, and
    the completion event — plus the TTFT/generation decomposition
    (queue-wait / prefill / first-decode-chunk / decode total) checked
    against the completion event's measured aggregates.

    IDs are matched by string form too: a CLI ``--request 17`` finds an
    integer request_id 17."""
    rid = request_id

    def _match(v) -> bool:
        return v == rid or str(v) == str(rid)

    queued = None
    prefill = None
    decode_chunks: List[dict] = []
    complete = None
    for r in records:
        kind = r.get("kind")
        if kind == "event" and _match(r.get("request_id")):
            if r.get("name") == "request_queued":
                queued = r
            elif r.get("name") == "request_complete":
                complete = r
        elif kind == "span":
            if _match(r.get("request_id")):
                prefill = r
            elif any(_match(x) for x in (r.get("rids") or ())):
                decode_chunks.append(r)
    if queued is None and prefill is None and complete is None:
        raise KeyError(
            f"no trace records carry request_id {request_id!r} — was the "
            f"serve run recorded with TPUDL_OBS_DIR set?"
        )
    decode_chunks.sort(key=lambda s: float(s["ts"]))

    timeline: List[dict] = []
    if queued is not None:
        timeline.append({
            "ts": float(queued["ts"]), "dur": 0.0, "what": "queued",
            "detail": {"priority": queued.get("req_priority"),
                       "deadline_s": queued.get("deadline_s"),
                       "depth": queued.get("depth")},
        })
    if prefill is not None:
        timeline.append({
            "ts": float(prefill["ts"]), "dur": float(prefill["dur"]),
            "what": "prefill",
            "detail": {"slot": prefill.get("slot")},
        })
    for i, c in enumerate(decode_chunks):
        timeline.append({
            "ts": float(c["ts"]), "dur": float(c["dur"]),
            "what": "decode_chunk",
            "detail": {"index": i, "busy": c.get("busy")},
        })
    if complete is not None:
        timeline.append({
            "ts": float(complete["ts"]), "dur": 0.0, "what": "complete",
            "detail": {"finish_reason": complete.get("finish_reason"),
                       "num_tokens": complete.get("num_tokens")},
        })
    timeline.sort(key=lambda e: e["ts"])

    # Decomposition. Queue wait prefers the completion event's measured
    # value (exact), falling back to prefill-start minus queued-event
    # time (the two clocks agree when recorder and engine share one).
    queue_wait_s = None
    if complete is not None and complete.get("queue_wait_s") is not None:
        queue_wait_s = float(complete["queue_wait_s"])
    elif prefill is not None and queued is not None:
        queue_wait_s = float(prefill["ts"]) - float(queued["ts"])
    prefill_s = float(prefill["dur"]) if prefill is not None else None
    decode_s = sum(float(c["dur"]) for c in decode_chunks)
    first_chunk_s = (
        float(decode_chunks[0]["dur"]) if decode_chunks else None
    )
    accounted_s = sum(
        v for v in (queue_wait_s, prefill_s, decode_s) if v is not None
    )
    measured_s = None
    ttft_s = None
    generation_s = None
    if complete is not None:
        ttft_s = complete.get("ttft_s")
        generation_s = complete.get("generation_s")
        if ttft_s is not None:
            measured_s = float(ttft_s) + float(generation_s or 0.0)
    return {
        "request_id": request_id,
        "found": {
            "queued": queued is not None,
            "prefill": prefill is not None,
            "decode_chunks": len(decode_chunks),
            "complete": complete is not None,
        },
        "finish_reason": (
            complete.get("finish_reason") if complete is not None else None
        ),
        "num_tokens": (
            complete.get("num_tokens") if complete is not None else None
        ),
        "timeline": timeline,
        "decomposition": {
            "queue_wait_s": queue_wait_s,
            "prefill_s": prefill_s,
            "first_decode_chunk_s": first_chunk_s,
            "decode_s": decode_s,
            "accounted_s": accounted_s,
            "measured_ttft_s": ttft_s,
            "measured_generation_s": generation_s,
            "measured_total_s": measured_s,
            # Host bookkeeping between chunks is real wall-clock the
            # chunks don't cover; coverage near 1.0 says the trace
            # explains the request's life.
            "coverage": (
                accounted_s / measured_s
                if measured_s not in (None, 0.0) else None
            ),
        },
    }


def format_request_timeline(tl: dict) -> str:
    """Human rendering of ``build_request_timeline``."""

    def ms(v):
        return f"{1e3 * v:9.3f}" if v is not None else "        —"

    lines = [
        f"request {tl['request_id']} — "
        f"finish_reason={tl['finish_reason']} "
        f"tokens={tl['num_tokens']}",
        "",
        f"{'t_ms':>10} {'dur_ms':>9}  event",
    ]
    t0 = tl["timeline"][0]["ts"] if tl["timeline"] else 0.0
    for e in tl["timeline"]:
        detail = " ".join(
            f"{k}={v}" for k, v in e["detail"].items() if v is not None
        )
        lines.append(
            f"{1e3 * (e['ts'] - t0):10.3f} {1e3 * e['dur']:9.3f}  "
            f"{e['what']}{'  [' + detail + ']' if detail else ''}"
        )
    d = tl["decomposition"]
    lines += [
        "",
        "TTFT/generation decomposition (ms):",
        f"  queue_wait         {ms(d['queue_wait_s'])}",
        f"  prefill            {ms(d['prefill_s'])}",
        f"  first_decode_chunk {ms(d['first_decode_chunk_s'])}",
        f"  decode total       {ms(d['decode_s'])}",
        f"  accounted          {ms(d['accounted_s'])}",
        f"  measured ttft      {ms(d['measured_ttft_s'])}",
        f"  measured total     {ms(d['measured_total_s'])}"
        + (
            f"  (coverage {d['coverage']:.3f})"
            if d["coverage"] is not None else ""
        ),
    ]
    return "\n".join(lines)


def format_report(report: dict) -> str:
    """Human-readable rendering of a ``build_report`` result."""
    lines = [
        f"tpudl obs report — {report['num_span_records']} spans, "
        f"{len(report['per_host']) or 1} process(es)",
        "",
        f"{'category':14} {'count':>6} {'total_s':>8} {'mean_ms':>9} "
        f"{'p50_ms':>9} {'p95_ms':>9} {'p99_ms':>9}",
    ]
    for cat, r in report["breakdown"].items():
        lines.append(
            f"{cat:14} {r['count']:6d} {r['total_s']:8.2f} "
            f"{r['mean_ms']:9.2f} {r['p50_ms']:9.2f} {r['p95_ms']:9.2f} "
            f"{r['p99_ms']:9.2f}"
        )

    gp = report["goodput"]
    lines += ["", goodput_mod.format_goodput(gp["overall"])]
    if len(gp["per_process"]) > 1:
        for key, cls in gp["per_process"].items():
            lines.append(f"  {key:20} {goodput_mod.format_goodput(cls)}")

    if report["per_host"]:
        lines += [
            "",
            f"{'host/process':20} {'steps':>6} {'mean_ms':>9} "
            f"{'p95_ms':>9} {'x_median':>9}",
        ]
        for key, r in report["per_host"].items():
            flag = "  STRAGGLER" if r["straggler"] else ""
            lines.append(
                f"{key:20} {r['count']:6d} {r['mean_ms']:9.2f} "
                f"{r['p95_ms']:9.2f} {r['x_median']:9.2f}{flag}"
            )

    if report["outlier_steps"]:
        lines += [
            "",
            f"outlier steps (> {report['outlier_factor']:g}x p50): "
            f"{len(report['outlier_steps'])}",
        ]
        for o in report["outlier_steps"][:10]:
            step = f" step {o['step']}" if o["step"] is not None else ""
            lines.append(
                f"  {o['ms']:9.2f} ms ({o['x_p50']:.1f}x p50) "
                f"{o['host']}/p{o['process']}{step}"
            )

    if report.get("serve_requests"):
        lines += [
            "",
            f"{'serve requests':16} {'count':>6} {'tokens':>8} "
            f"{'q_wait_ms':>10} {'ttft_ms':>9}",
        ]
        for reason, r in report["serve_requests"].items():
            qw = (
                f"{r['mean_queue_wait_ms']:10.2f}"
                if r["mean_queue_wait_ms"] is not None else f"{'—':>10}"
            )
            tt = (
                f"{r['mean_ttft_ms']:9.2f}"
                if r["mean_ttft_ms"] is not None else f"{'—':>9}"
            )
            lines.append(
                f"{reason:16} {r['count']:6d} {r['tokens']:8d} {qw} {tt}"
            )

    for key, snap in report["counters"].items():
        cs = snap.get("counters", {})
        if cs:
            rendered = " ".join(f"{k}={v:g}" for k, v in sorted(cs.items()))
            lines.append(f"counters {key}: {rendered}")
        gs = snap.get("gauges", {})
        if gs:
            rendered = " ".join(f"{k}={v:g}" for k, v in sorted(gs.items()))
            lines.append(f"gauges {key}: {rendered}")
        # Registry histograms (e.g. the serving engine's serve_ttft_ms /
        # serve_tpot_ms / serve_queue_wait_ms) ride the same snapshot;
        # quote the tail, which is what a serving SLO reads.
        for name, h in sorted(snap.get("histograms", {}).items()):
            if not h.get("count"):
                continue
            lines.append(
                f"histogram {key}: {name} n={h['count']} "
                f"mean={h['mean']:.3f} p50={h['p50']:.3f} "
                f"p95={h['p95']:.3f} p99={h['p99']:.3f}"
            )
    return "\n".join(lines)


def main(argv: Optional[list] = None) -> int:
    import argparse

    ap = argparse.ArgumentParser(
        description="Aggregate tpudl obs span files into a step-time "
        "breakdown, goodput fraction, and straggler attribution"
    )
    ap.add_argument(
        "paths", nargs="+",
        help="span *.jsonl files and/or obs directories",
    )
    ap.add_argument("--outlier-factor", type=float, default=3.0,
                    help="flag steps slower than this multiple of p50")
    ap.add_argument("--straggler-factor", type=float, default=1.2,
                    help="flag hosts with mean step time above this "
                    "multiple of the cross-host median")
    ap.add_argument("--chrome-trace", metavar="OUT.json",
                    help="also export the records as Chrome trace-event "
                    "JSON for Perfetto")
    ap.add_argument("--request", metavar="ID",
                    help="print ONE served request's stitched trace "
                    "(admission -> prefill -> decode chunks -> "
                    "completion) with its TTFT decomposition, instead "
                    "of the run report")
    ap.add_argument("--json", action="store_true")
    args = ap.parse_args(argv)

    records = load_records(args.paths)
    if args.request is not None:
        try:
            tl = build_request_timeline(records, args.request)
        except KeyError as e:
            print(e.args[0])
            return 1
        print(
            json.dumps(tl) if args.json else format_request_timeline(tl)
        )
        return 0
    report = build_report(
        records,
        outlier_factor=args.outlier_factor,
        straggler_factor=args.straggler_factor,
    )
    if args.chrome_trace:
        with open(args.chrome_trace, "w") as f:
            json.dump({"traceEvents": chrome_trace_events(records)}, f)
    print(json.dumps(report) if args.json else format_report(report))
    return 0


if __name__ == "__main__":
    import sys

    sys.exit(main())
