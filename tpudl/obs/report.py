"""Aggregate span/counter streams into the run report: step-time
breakdown, goodput, outliers, and per-host straggler attribution.

    python -m tpudl.obs.report /path/to/obs-dir        # or *.jsonl files
    python -m tpudl.obs.report run.jsonl --json
    python -m tpudl.obs.report run.jsonl --chrome-trace trace.json
    python -m tpudl.obs.report serve-run.jsonl --request r17

This is the "why was this run only 71% productive, and which host was
slow" answer as an artifact, not a vibe: it loads one or many span JSONL
files (a distributor run merges its workers' files into the parent's —
see tpudl.runtime.distributor — but loose per-worker files work too
since every record carries host/process tags), then prints

- a per-category latency table (count, total, mean, p50/p95/p99) over
  data_wait / step / compile / checkpoint spans;
- the goodput classification (tpudl.obs.goodput);
- outlier steps (duration > ``outlier_factor`` x the p50 step time),
  each attributed to its host/process;
- per-host step-time means with stragglers flagged (mean above
  ``straggler_factor`` x the cross-host median);
- a served-request outcome breakdown (completed vs each shed reason,
  with queue-wait/TTFT means per reason), when a serve run's
  ``request_complete`` events rode the stream;
- the last counters snapshot per process, if any rode the stream.

``--request <id>`` switches to per-request trace mode: the serve
path's distributed trace (``request_id`` propagated from admission
through prefill, every decode chunk, and completion) is stitched into
one timeline for that request, and its TTFT is decomposed into
queue-wait / prefill / first-decode-chunk, with the total checked
against the measured TTFT + generation time.

``--chrome-trace`` additionally re-exports the loaded records as
Chrome trace-event JSON for Perfetto, next to the XLA device trace."""

from __future__ import annotations

import glob
import json
import os
from typing import Dict, Iterable, List, Optional

from tpudl.obs import goodput as goodput_mod
from tpudl.obs.counters import percentile
from tpudl.obs.spans import (
    CAT_CHECKPOINT,
    CAT_CKPT_BG,
    CAT_COMPILE,
    CAT_DATA_WAIT,
    CAT_EVAL,
    CAT_METRIC_WAIT,
    CAT_RECOVERY,
    CAT_STEP,
    chrome_trace_events,
    read_jsonl,
)

#: Table row order: the lifecycle order of one step; the overlapped
#: background-write row and recovery last (present only when nonzero).
_TABLE_CATS = (CAT_DATA_WAIT, CAT_STEP, CAT_EVAL, CAT_COMPILE,
               CAT_METRIC_WAIT, CAT_CHECKPOINT, CAT_CKPT_BG, CAT_RECOVERY)


def load_records(paths: Iterable[str]) -> List[dict]:
    """Load span records from JSONL files and/or directories (directories
    glob ``*.jsonl``, recursively — a distributor obs dir with a
    ``workers/`` subdir loads in one argument)."""
    from tpudl.obs.requestlog import _parse_segment_name

    files: List[str] = []
    for p in paths:
        if os.path.isdir(p):
            hits = sorted(
                glob.glob(os.path.join(p, "**", "*.jsonl"), recursive=True)
            )
            # Durable request-log segments (requests-*.jsonl) are a
            # different artifact with a different schema: a run dir
            # that nests its requestlog under the obs dir must not
            # leak usage records into the span report.
            hits = [
                h for h in hits
                if _parse_segment_name(os.path.basename(h)) is None
            ]
            if not hits:
                raise FileNotFoundError(f"no *.jsonl files under {p}")
            files.extend(hits)
        else:
            files.append(p)
    records: List[dict] = []
    for f in files:
        records.extend(read_jsonl(f))
    return records


def _dist(durs: List[float]) -> dict:
    vals = sorted(durs)
    return {
        "count": len(vals),
        "total_s": sum(vals),
        "mean_ms": 1e3 * sum(vals) / len(vals) if vals else 0.0,
        "p50_ms": 1e3 * percentile(vals, 0.50) if vals else 0.0,
        "p95_ms": 1e3 * percentile(vals, 0.95) if vals else 0.0,
        "p99_ms": 1e3 * percentile(vals, 0.99) if vals else 0.0,
    }


def build_report(
    records: List[dict],
    outlier_factor: float = 3.0,
    straggler_factor: float = 1.2,
) -> dict:
    """Span records -> report dict (see module docstring for contents)."""
    spans = [r for r in records if r.get("kind") == "span"]
    by_cat: Dict[str, List[float]] = {}
    for s in spans:
        by_cat.setdefault(s.get("cat", "other"), []).append(float(s["dur"]))
    breakdown = {
        cat: _dist(by_cat[cat]) for cat in _TABLE_CATS if cat in by_cat
    }
    for cat in sorted(set(by_cat) - set(_TABLE_CATS)):
        breakdown[cat] = _dist(by_cat[cat])

    # Outlier steps: anything beyond outlier_factor x the p50 TRAIN-step
    # time (eval steps have their own duration scale and stay out of
    # these statistics), attributed to host/process so cross-host blips
    # are visible. Fused dispatch_window spans cover K steps each (the
    # "window" attr), so their duration normalizes to per-step time
    # before comparison — a K=8 window is not an 8x outlier.
    step_spans = [s for s in spans if s.get("cat") == CAT_STEP]

    def _per_step_dur(s) -> float:
        return float(s["dur"]) / int(s.get("window", 1) or 1)

    outliers: List[dict] = []
    p50 = (
        percentile(sorted(_per_step_dur(s) for s in step_spans), 0.50)
        if step_spans else 0.0
    )
    if p50 > 0:
        for s in step_spans:
            dur = _per_step_dur(s)
            if dur > outlier_factor * p50:
                outliers.append({
                    "host": s.get("host", "?"),
                    "process": s.get("process", 0),
                    "step": s.get("step"),
                    "ms": 1e3 * dur,
                    "x_p50": dur / p50,
                })
        outliers.sort(key=lambda o: -o["ms"])

    # Per-host/process straggler attribution over per-step means
    # (grouped by recording process incl. OS pid — see
    # goodput.process_key).
    per_host_keyed: Dict[tuple, List[float]] = {}
    for s in step_spans:
        per_host_keyed.setdefault(
            goodput_mod.process_key(s), []
        ).append(_per_step_dur(s))
    labels = goodput_mod.process_labels(per_host_keyed)
    per_host = {
        labels[k]: per_host_keyed[k]
        for k in sorted(per_host_keyed, key=lambda k: labels[k])
    }
    host_rows = {key: _dist(durs) for key, durs in per_host.items()}
    means = sorted(r["mean_ms"] for r in host_rows.values())
    median_mean = percentile(means, 0.50) if means else 0.0
    for key, row in host_rows.items():
        ratio = row["mean_ms"] / median_mean if median_mean > 0 else 0.0
        row["x_median"] = ratio
        row["straggler"] = bool(
            len(host_rows) > 1 and ratio > straggler_factor
        )

    # Last counters snapshot per recording process, if any rode the
    # stream.
    counters_keyed: Dict[tuple, dict] = {}
    for r in records:
        if r.get("kind") == "counters":
            counters_keyed[goodput_mod.process_key(r)] = r.get("data", {})
    clabels = goodput_mod.process_labels(counters_keyed)
    counters = {
        clabels[k]: counters_keyed[k]
        for k in sorted(counters_keyed, key=lambda k: clabels[k])
    }

    return {
        "num_records": len(records),
        "num_span_records": len(spans),
        "breakdown": breakdown,
        "goodput": goodput_mod.classify_by_process(records),
        "outlier_steps": outliers,
        "outlier_factor": outlier_factor,
        "per_host": host_rows,
        "straggler_factor": straggler_factor,
        "serve_requests": serve_request_breakdown(records),
        "counters": counters,
    }


def serve_request_breakdown(records: Iterable[dict]) -> dict:
    """Aggregate serve ``request_complete`` events by outcome: one row
    per finish_reason (completed-by-eos/length vs each shed reason)
    with count and queue-wait/TTFT means — the cross-request view of
    what admission did under load. Empty dict when the stream carries
    no serve traffic."""
    by_reason: Dict[str, List[dict]] = {}
    for r in records:
        if r.get("kind") == "event" and r.get("name") == "request_complete":
            by_reason.setdefault(
                r.get("finish_reason", "?"), []
            ).append(r)
    out: dict = {}
    for reason in sorted(by_reason):
        evs = by_reason[reason]
        waits = [
            float(e["queue_wait_s"]) for e in evs
            if e.get("queue_wait_s") is not None
        ]
        ttfts = [
            float(e["ttft_s"]) for e in evs if e.get("ttft_s") is not None
        ]
        out[reason] = {
            "count": len(evs),
            "mean_queue_wait_ms": (
                1e3 * sum(waits) / len(waits) if waits else None
            ),
            "mean_ttft_ms": 1e3 * sum(ttfts) / len(ttfts) if ttfts else None,
            "tokens": sum(int(e.get("num_tokens", 0) or 0) for e in evs),
        }
    return out


# ---------------------------------------------------------------------------
# Per-request trace mode (--request)
# ---------------------------------------------------------------------------


#: Logical hop order of one served request's life — the sort key the
#: stitched timeline uses FIRST, before timestamps: records from
#: different processes carry unrelated monotonic clocks, so cross-
#: stream ordering must come from the protocol, not the numbers.
_HOP_RANK = {
    "routed": 0, "failover": 1, "replica_dequeue": 2, "queued": 3,
    "prefill": 4, "decode_chunk": 5, "served": 6, "complete": 7,
}


def build_request_timeline(records: Iterable[dict], request_id) -> dict:
    """Stitch one request's distributed trace out of a serve run's
    records — possibly MERGED from several processes' span streams (the
    fleet case: router door events in the router's stream, admission /
    prefill / decode spans in each replica's): the router-door
    ``request_routed`` event, the replica-inbox ``replica_dequeue``
    hop, the admission event, the prefill span carrying its
    ``request_id``, every decode chunk whose ``rids`` include it, and
    the completion event — plus the TTFT/generation decomposition
    (queue-wait / prefill / first-decode-chunk / decode total) checked
    against the completion event's measured aggregates, and the
    router-level decomposition (inbox wait + queue wait + prefill vs
    the router-measured TTFT — all DURATIONS, so the sums survive
    cross-process clock skew; timestamps are never compared across
    streams).

    A hop named in a router event whose records are absent (that
    process's span stream not on disk) lands in ``warnings`` as a
    "partial trace" — the merged directory is incomplete, not the
    request unobserved.

    IDs are matched by string form too: a CLI ``--request 17`` finds an
    integer request_id 17."""
    rid = request_id

    def _match(v) -> bool:
        return v == rid or str(v) == str(rid)

    routed = None
    dequeues: List[dict] = []
    served_events: List[dict] = []
    failovers: List[dict] = []
    queued = None
    prefills: List[dict] = []
    decode_chunks: List[dict] = []
    complete = None
    for r in records:
        kind = r.get("kind")
        if kind == "event" and _match(r.get("request_id")):
            name = r.get("name")
            if name == "request_queued":
                queued = r
            elif name == "request_complete":
                complete = r
            elif name == "request_routed":
                routed = r
            elif name == "replica_dequeue":
                dequeues.append(r)
            elif name == "request_served":
                served_events.append(r)
            elif name == "request_failover":
                failovers.append(r)
        elif kind == "span":
            if _match(r.get("request_id")):
                prefills.append(r)
            elif any(_match(x) for x in (r.get("rids") or ())):
                decode_chunks.append(r)
    if (
        queued is None and not prefills and complete is None
        and routed is None and not dequeues and not served_events
    ):
        raise KeyError(
            f"no trace records carry request_id {request_id!r} — was the "
            f"serve run recorded with TPUDL_OBS_DIR set?"
        )

    # A failed-over request leaves records from BOTH attempts; the
    # completing process's are authoritative (the restarted copy). Key
    # by recording process and prefer its records when the streams
    # disagree — within one stream, "latest wins" is safe (same clock).
    proc_key = (
        goodput_mod.process_key(complete) if complete is not None else None
    )

    def _prefer_proc(cands: List[dict]) -> Optional[dict]:
        if not cands:
            return None
        if proc_key is not None:
            same = [
                c for c in cands
                if goodput_mod.process_key(c) == proc_key
            ]
            if same:
                return max(same, key=lambda s: float(s["ts"]))
        return max(cands, key=lambda s: float(s["ts"]))

    prefill = _prefer_proc(prefills)
    if proc_key is not None:
        same_chunks = [
            c for c in decode_chunks
            if goodput_mod.process_key(c) == proc_key
        ]
        if same_chunks:
            decode_chunks = same_chunks
    decode_chunks.sort(key=lambda s: float(s["ts"]))
    dequeue = _prefer_proc(dequeues)
    served = _prefer_proc(served_events)

    warnings: List[str] = []
    # Any record beyond the router's own door event proves the routed
    # hop's stream made it into the merge — a replica_dequeue with no
    # engine records is a replica-side shed, not a missing stream.
    engine_side = bool(
        queued or prefill or decode_chunks or complete
        or dequeues or served_events
    )
    if routed is not None and not engine_side:
        if routed.get("replica"):
            kind, hop = "replica", routed["replica"]
        elif routed.get("worker"):
            kind, hop = "prefill worker", routed["worker"]
        else:
            kind, hop = "hop", "?"
        warnings.append(
            f"partial trace: request {request_id!r} was routed to "
            f"{kind} {hop!r} but no spans from that hop are on disk — "
            f"merge that process's span stream (TPUDL_OBS_DIR) into "
            f"this report"
        )
    if complete is None and (routed is not None or queued is not None):
        warnings.append(
            f"partial trace: no completion event for {request_id!r} — "
            f"the request is still in flight, or the completing "
            f"process's stream is missing"
        )

    timeline: List[dict] = []
    if routed is not None:
        timeline.append({
            "ts": float(routed["ts"]), "dur": 0.0, "what": "routed",
            "detail": {"replica": routed.get("replica"),
                       "worker": routed.get("worker"),
                       "priority": routed.get("priority")},
            "record": routed,
        })
    for f in failovers:
        timeline.append({
            "ts": float(f["ts"]), "dur": 0.0, "what": "failover",
            "detail": {"from_replica": f.get("from_replica")},
            "record": f,
        })
    if dequeue is not None:
        timeline.append({
            "ts": float(dequeue["ts"]),
            "dur": float(dequeue.get("inbox_wait_s") or 0.0),
            "what": "replica_dequeue",
            "detail": {"replica": dequeue.get("replica"),
                       "inbox_wait_s": dequeue.get("inbox_wait_s")},
            "record": dequeue,
        })
    if queued is not None:
        timeline.append({
            "ts": float(queued["ts"]), "dur": 0.0, "what": "queued",
            "detail": {"priority": queued.get("req_priority"),
                       "deadline_s": queued.get("deadline_s"),
                       "depth": queued.get("depth")},
            "record": queued,
        })
    if prefill is not None:
        timeline.append({
            "ts": float(prefill["ts"]), "dur": float(prefill["dur"]),
            "what": "prefill",
            # prefix_hit_tokens: how much of the prompt the radix
            # prefix cache served for free — the TTFT attribution
            # (prefill dur covers only the unshared suffix when > 0).
            "detail": {"slot": prefill.get("slot"),
                       "worker": prefill.get("worker"),
                       "prefix_hit_tokens": prefill.get(
                           "prefix_hit_tokens")},
            "record": prefill,
        })
    def _spec_share(c: dict):
        """THIS request's (accepted, proposed, emitted) within one
        speculative window: the decode_step span batch-sums its
        numbers, but slot_accepted/slot_emitted align with rids, so a
        single request's trace reads its own column instead of
        claiming the whole batch's."""
        if c.get("proposed") is None:
            return None
        idx = next(
            (j for j, x in enumerate(c.get("rids") or ())
             if _match(x)), None,
        )
        slot_acc = c.get("slot_accepted")
        if idx is not None and slot_acc is not None:
            return (
                int(slot_acc[idx]),
                int(c.get("proposed_per_slot") or 0),
                int((c.get("slot_emitted") or [0] * (idx + 1))[idx]),
            )
        # Older streams without per-slot columns: batch totals are the
        # best available (overstates under multi-slot occupancy).
        return (
            int(c.get("accepted") or 0), int(c.get("proposed") or 0),
            int(c.get("emitted") or 0),
        )

    for i, c in enumerate(decode_chunks):
        detail = {"index": i, "busy": c.get("busy")}
        share = _spec_share(c)
        if share is not None:
            # Speculative windows: accepted/proposed per step shows
            # where TPOT went (a low ratio = the draft disagrees and
            # windows are mostly wasted draft dispatches).
            detail["accepted"], detail["proposed"], detail["emitted"] = (
                share
            )
        timeline.append({
            "ts": float(c["ts"]), "dur": float(c["dur"]),
            "what": "decode_chunk",
            "detail": detail,
            "record": c,
        })
    if served is not None:
        timeline.append({
            "ts": float(served["ts"]), "dur": 0.0, "what": "served",
            "detail": {"replica": served.get("replica"),
                       "router_ttft_s": served.get("router_ttft_s")},
            "record": served,
        })
    if complete is not None:
        timeline.append({
            "ts": float(complete["ts"]), "dur": 0.0, "what": "complete",
            "detail": {"finish_reason": complete.get("finish_reason"),
                       "num_tokens": complete.get("num_tokens")},
            "record": complete,
        })
    # Logical hop order first, timestamps only within it: records from
    # different processes carry unrelated monotonic clocks.
    timeline.sort(key=lambda e: (_HOP_RANK.get(e["what"], 99), e["ts"]))
    # Tag each entry with its recording process (rendered when the
    # stitched trace spans more than one stream) and drop the raw
    # record from the output.
    proc_keys = {goodput_mod.process_key(e["record"]) for e in timeline}
    labels = goodput_mod.process_labels(proc_keys)
    for e in timeline:
        e["process"] = labels[goodput_mod.process_key(e.pop("record"))]
    multi_process = len(proc_keys) > 1

    # Decomposition. Queue wait prefers the completion event's measured
    # value (exact), falling back to prefill-start minus queued-event
    # time (the two clocks agree when recorder and engine share one).
    queue_wait_s = None
    if complete is not None and complete.get("queue_wait_s") is not None:
        queue_wait_s = float(complete["queue_wait_s"])
    elif prefill is not None and queued is not None:
        queue_wait_s = float(prefill["ts"]) - float(queued["ts"])
    prefill_s = float(prefill["dur"]) if prefill is not None else None
    decode_s = sum(float(c["dur"]) for c in decode_chunks)
    first_chunk_s = (
        float(decode_chunks[0]["dur"]) if decode_chunks else None
    )
    accounted_s = sum(
        v for v in (queue_wait_s, prefill_s, decode_s) if v is not None
    )
    measured_s = None
    ttft_s = None
    generation_s = None
    if complete is not None:
        ttft_s = complete.get("ttft_s")
        generation_s = complete.get("generation_s")
        if ttft_s is not None:
            measured_s = float(ttft_s) + float(generation_s or 0.0)

    # Router-level decomposition (fleet runs): the replica-inbox hop
    # plus the engine-measured TTFT is the router-door -> first-token
    # time. Both sides are duration sums, so the identity holds across
    # processes with unrelated clocks:
    #   inbox_wait + queue_wait + prefill  ==  router_ttft
    # (== inbox_wait + ttft, since queue_wait + prefill == ttft by the
    # engine's own timestamps).
    inbox_wait_s = None
    if dequeue is not None and dequeue.get("inbox_wait_s") is not None:
        inbox_wait_s = float(dequeue["inbox_wait_s"])
    elif served is not None and served.get("inbox_wait_s") is not None:
        inbox_wait_s = float(served["inbox_wait_s"])
    router_ttft_s = None
    if served is not None and served.get("router_ttft_s") is not None:
        router_ttft_s = float(served["router_ttft_s"])
    elif ttft_s is not None:
        router_ttft_s = float(ttft_s) + (inbox_wait_s or 0.0)
    router_accounted_s = None
    if queue_wait_s is not None or prefill_s is not None:
        router_accounted_s = sum(
            v for v in (inbox_wait_s, queue_wait_s, prefill_s)
            if v is not None
        )
    return {
        "request_id": request_id,
        "found": {
            "queued": queued is not None,
            "prefill": prefill is not None,
            "decode_chunks": len(decode_chunks),
            "complete": complete is not None,
        },
        "hops": {
            "routed": routed is not None,
            "replica": (
                (served or dequeue or {}).get("replica")
                or (routed or {}).get("replica")
            ),
            "worker": (prefill or routed or {}).get("worker"),
            "failovers": len(failovers),
            "processes": sorted(labels.values()),
            "multi_process": multi_process,
        },
        "warnings": warnings,
        "finish_reason": (
            complete.get("finish_reason") if complete is not None else None
        ),
        "num_tokens": (
            complete.get("num_tokens") if complete is not None else None
        ),
        "prefix_hit_tokens": (
            prefill.get("prefix_hit_tokens")
            if prefill is not None else None
        ),
        "speculation": (
            {
                "proposed": sum(
                    s[1] for s in map(_spec_share, decode_chunks)
                    if s is not None
                ),
                "accepted": sum(
                    s[0] for s in map(_spec_share, decode_chunks)
                    if s is not None
                ),
            }
            if any(c.get("proposed") is not None for c in decode_chunks)
            else None
        ),
        "timeline": timeline,
        "decomposition": {
            "inbox_wait_s": inbox_wait_s,
            "queue_wait_s": queue_wait_s,
            "prefill_s": prefill_s,
            "first_decode_chunk_s": first_chunk_s,
            "decode_s": decode_s,
            "accounted_s": accounted_s,
            "router_accounted_s": router_accounted_s,
            "measured_ttft_s": ttft_s,
            "router_ttft_s": router_ttft_s,
            "measured_generation_s": generation_s,
            "measured_total_s": measured_s,
            # Host bookkeeping between chunks is real wall-clock the
            # chunks don't cover; coverage near 1.0 says the trace
            # explains the request's life.
            "coverage": (
                accounted_s / measured_s
                if measured_s not in (None, 0.0) else None
            ),
        },
    }


def format_request_timeline(tl: dict) -> str:
    """Human rendering of ``build_request_timeline``. In a stitched
    multi-process trace, ``t_ms`` is relative to the FIRST entry of
    the SAME process's stream (cross-stream timestamps are on
    unrelated monotonic clocks and are never subtracted); the process
    column names the stream each hop came from."""

    def ms(v):
        return f"{1e3 * v:9.3f}" if v is not None else "        —"

    hops = tl.get("hops", {})
    multi = bool(hops.get("multi_process"))
    lines = [
        f"request {tl['request_id']} — "
        f"finish_reason={tl['finish_reason']} "
        f"tokens={tl['num_tokens']}",
    ]
    for w in tl.get("warnings", ()):
        lines.append(f"WARNING: {w}")
    if tl.get("prefix_hit_tokens"):
        lines.append(
            f"prefix cache: {tl['prefix_hit_tokens']} prompt tokens "
            f"served from shared pages (prefill paid only the suffix)"
        )
    spec = tl.get("speculation")
    if spec and spec.get("proposed"):
        lines.append(
            f"speculation: {spec['accepted']}/{spec['proposed']} "
            f"proposed tokens accepted across decode windows"
        )
    lines += [
        "",
        f"{'t_ms':>10} {'dur_ms':>9}  event"
        + ("  (t_ms per-process)" if multi else ""),
    ]
    proc_t0: Dict[str, float] = {}
    for e in tl["timeline"]:
        proc_t0.setdefault(e.get("process", "?"), e["ts"])
    for e in tl["timeline"]:
        detail = " ".join(
            f"{k}={v}" for k, v in e["detail"].items() if v is not None
        )
        proc = e.get("process", "?")
        tag = f" @{proc}" if multi else ""
        lines.append(
            f"{1e3 * (e['ts'] - proc_t0[proc]):10.3f} "
            f"{1e3 * e['dur']:9.3f}  "
            f"{e['what']}{'  [' + detail + ']' if detail else ''}{tag}"
        )
    d = tl["decomposition"]
    lines += [
        "",
        "TTFT/generation decomposition (ms):",
    ]
    if d.get("inbox_wait_s") is not None:
        lines.append(f"  replica_inbox_wait {ms(d['inbox_wait_s'])}")
    lines += [
        f"  queue_wait         {ms(d['queue_wait_s'])}",
        f"  prefill            {ms(d['prefill_s'])}",
        f"  first_decode_chunk {ms(d['first_decode_chunk_s'])}",
        f"  decode total       {ms(d['decode_s'])}",
        f"  accounted          {ms(d['accounted_s'])}",
        f"  measured ttft      {ms(d['measured_ttft_s'])}",
    ]
    if d.get("router_ttft_s") is not None:
        lines.append(
            f"  router ttft        {ms(d['router_ttft_s'])}"
            + (
                f"  (hops sum {ms(d['router_accounted_s']).strip()})"
                if d.get("router_accounted_s") is not None else ""
            )
        )
    lines.append(
        f"  measured total     {ms(d['measured_total_s'])}"
        + (
            f"  (coverage {d['coverage']:.3f})"
            if d["coverage"] is not None else ""
        ),
    )
    return "\n".join(lines)


# ---------------------------------------------------------------------------
# Fleet mode (--fleet): the cross-replica view over merged streams
# ---------------------------------------------------------------------------


def build_fleet_report(records: List[dict]) -> dict:
    """The fleet-level rollup over records MERGED from every member's
    span stream (tpudl.obs.fleet.FleetMonitor.trace_records, or just
    ``report.py --fleet dir1 dir2 ...``): per-process record counts,
    the serve-request outcome breakdown, router hop-latency
    distributions (inbox wait, router-level TTFT — duration sums, clock
    -skew free), failover/membership/autoscale activity, and every
    request whose stitched trace is PARTIAL (a hop's stream missing
    from the merge)."""
    per_proc: Dict[tuple, dict] = {}
    rids: List = []
    seen_rids = set()
    membership: List[dict] = []
    autoscale_actions: List[dict] = []
    for r in records:
        key = goodput_mod.process_key(r)
        row = per_proc.setdefault(key, {"records": 0, "spans": 0,
                                        "events": 0})
        row["records"] += 1
        kind = r.get("kind")
        if kind == "span":
            row["spans"] += 1
        elif kind == "event":
            row["events"] += 1
            name = r.get("name")
            if name in (
                "request_routed", "request_served", "request_complete",
            ):
                rid = r.get("request_id")
                marker = str(rid)
                if marker not in seen_rids:
                    seen_rids.add(marker)
                    rids.append(rid)
            elif name in ("replica_added", "replica_removed"):
                membership.append({
                    "what": name, "replica": r.get("replica"),
                    "drained": r.get("drained"),
                })
            elif name == "autoscale":
                autoscale_actions.append({
                    "action": r.get("action"),
                    "replica": r.get("replica"),
                    "reason": r.get("reason"),
                })
    labels = goodput_mod.process_labels(per_proc)
    processes = {
        labels[k]: per_proc[k]
        for k in sorted(per_proc, key=lambda k: labels[k])
    }

    # Bucket records per request ONCE (string-keyed, matching the
    # stitcher's id coercion): stitching each request from its own
    # bucket keeps the report linear in the record count instead of
    # O(requests x records) full rescans.
    buckets: Dict[str, List[dict]] = {}
    for r in records:
        keys = set()
        if r.get("request_id") is not None:
            keys.add(str(r["request_id"]))
        for x in r.get("rids") or ():
            keys.add(str(x))
        for k in keys:
            buckets.setdefault(k, []).append(r)

    router_ttfts: List[float] = []
    inbox_waits: List[float] = []
    failovers = 0
    partial: Dict[str, List[str]] = {}
    for rid in rids:
        try:
            tl = build_request_timeline(buckets.get(str(rid), []), rid)
        except KeyError:
            partial[str(rid)] = ["no stitchable records"]
            continue
        d = tl["decomposition"]
        if d.get("router_ttft_s") is not None:
            router_ttfts.append(float(d["router_ttft_s"]))
        if d.get("inbox_wait_s") is not None:
            inbox_waits.append(float(d["inbox_wait_s"]))
        failovers += tl["hops"]["failovers"]
        if tl["warnings"]:
            partial[str(rid)] = list(tl["warnings"])
    return {
        "num_records": len(records),
        "processes": processes,
        "num_requests": len(rids),
        "serve_requests": serve_request_breakdown(records),
        "router_ttft": _dist(router_ttfts) if router_ttfts else None,
        "replica_inbox_wait": _dist(inbox_waits) if inbox_waits else None,
        "failovers": failovers,
        "membership": membership,
        "autoscale_actions": autoscale_actions,
        "partial_traces": partial,
    }


def format_fleet_report(report: dict) -> str:
    """Human rendering of ``build_fleet_report``."""
    lines = [
        f"tpudl fleet report — {report['num_records']} records from "
        f"{len(report['processes'])} process stream(s), "
        f"{report['num_requests']} request(s)",
        "",
        f"{'process':24} {'records':>8} {'spans':>7} {'events':>7}",
    ]
    for label, row in report["processes"].items():
        lines.append(
            f"{label:24} {row['records']:8d} {row['spans']:7d} "
            f"{row['events']:7d}"
        )
    if report.get("serve_requests"):
        lines += [
            "",
            f"{'serve requests':16} {'count':>6} {'tokens':>8} "
            f"{'q_wait_ms':>10} {'ttft_ms':>9}",
        ]
        for reason, r in report["serve_requests"].items():
            qw = (
                f"{r['mean_queue_wait_ms']:10.2f}"
                if r["mean_queue_wait_ms"] is not None else f"{'—':>10}"
            )
            tt = (
                f"{r['mean_ttft_ms']:9.2f}"
                if r["mean_ttft_ms"] is not None else f"{'—':>9}"
            )
            lines.append(
                f"{reason:16} {r['count']:6d} {r['tokens']:8d} {qw} {tt}"
            )
    for name, key in (
        ("router TTFT", "router_ttft"),
        ("replica inbox wait", "replica_inbox_wait"),
    ):
        d = report.get(key)
        if d:
            lines.append(
                f"{name}: n={d['count']} mean={d['mean_ms']:.2f}ms "
                f"p50={d['p50_ms']:.2f}ms p95={d['p95_ms']:.2f}ms "
                f"p99={d['p99_ms']:.2f}ms"
            )
    if report["failovers"]:
        lines.append(f"failovers: {report['failovers']}")
    for m in report["membership"]:
        drained = (
            f" (drained={m['drained']})"
            if m.get("drained") is not None else ""
        )
        lines.append(f"membership: {m['what']} {m['replica']}{drained}")
    for a in report["autoscale_actions"]:
        lines.append(
            f"autoscale: {a['action']} {a['replica']} "
            f"[reason: {a['reason']}]"
        )
    if report["partial_traces"]:
        lines.append("")
        lines.append(
            f"PARTIAL TRACES ({len(report['partial_traces'])} "
            f"request(s) with hops missing from the merge):"
        )
        for rid, warnings in sorted(report["partial_traces"].items()):
            for w in warnings:
                lines.append(f"  {rid}: {w}")
    return "\n".join(lines)


def format_report(report: dict) -> str:
    """Human-readable rendering of a ``build_report`` result."""
    lines = [
        f"tpudl obs report — {report['num_span_records']} spans, "
        f"{len(report['per_host']) or 1} process(es)",
        "",
        f"{'category':14} {'count':>6} {'total_s':>8} {'mean_ms':>9} "
        f"{'p50_ms':>9} {'p95_ms':>9} {'p99_ms':>9}",
    ]
    for cat, r in report["breakdown"].items():
        lines.append(
            f"{cat:14} {r['count']:6d} {r['total_s']:8.2f} "
            f"{r['mean_ms']:9.2f} {r['p50_ms']:9.2f} {r['p95_ms']:9.2f} "
            f"{r['p99_ms']:9.2f}"
        )

    gp = report["goodput"]
    lines += ["", goodput_mod.format_goodput(gp["overall"])]
    if len(gp["per_process"]) > 1:
        for key, cls in gp["per_process"].items():
            lines.append(f"  {key:20} {goodput_mod.format_goodput(cls)}")

    if report["per_host"]:
        lines += [
            "",
            f"{'host/process':20} {'steps':>6} {'mean_ms':>9} "
            f"{'p95_ms':>9} {'x_median':>9}",
        ]
        for key, r in report["per_host"].items():
            flag = "  STRAGGLER" if r["straggler"] else ""
            lines.append(
                f"{key:20} {r['count']:6d} {r['mean_ms']:9.2f} "
                f"{r['p95_ms']:9.2f} {r['x_median']:9.2f}{flag}"
            )

    if report["outlier_steps"]:
        lines += [
            "",
            f"outlier steps (> {report['outlier_factor']:g}x p50): "
            f"{len(report['outlier_steps'])}",
        ]
        for o in report["outlier_steps"][:10]:
            step = f" step {o['step']}" if o["step"] is not None else ""
            lines.append(
                f"  {o['ms']:9.2f} ms ({o['x_p50']:.1f}x p50) "
                f"{o['host']}/p{o['process']}{step}"
            )

    if report.get("serve_requests"):
        lines += [
            "",
            f"{'serve requests':16} {'count':>6} {'tokens':>8} "
            f"{'q_wait_ms':>10} {'ttft_ms':>9}",
        ]
        for reason, r in report["serve_requests"].items():
            qw = (
                f"{r['mean_queue_wait_ms']:10.2f}"
                if r["mean_queue_wait_ms"] is not None else f"{'—':>10}"
            )
            tt = (
                f"{r['mean_ttft_ms']:9.2f}"
                if r["mean_ttft_ms"] is not None else f"{'—':>9}"
            )
            lines.append(
                f"{reason:16} {r['count']:6d} {r['tokens']:8d} {qw} {tt}"
            )

    for key, snap in report["counters"].items():
        cs = snap.get("counters", {})
        if cs:
            rendered = " ".join(f"{k}={v:g}" for k, v in sorted(cs.items()))
            lines.append(f"counters {key}: {rendered}")
        gs = snap.get("gauges", {})
        if gs:
            rendered = " ".join(f"{k}={v:g}" for k, v in sorted(gs.items()))
            lines.append(f"gauges {key}: {rendered}")
        # Registry histograms (e.g. the serving engine's serve_ttft_ms /
        # serve_tpot_ms / serve_queue_wait_ms) ride the same snapshot;
        # quote the tail, which is what a serving SLO reads.
        for name, h in sorted(snap.get("histograms", {}).items()):
            if not h.get("count"):
                continue
            lines.append(
                f"histogram {key}: {name} n={h['count']} "
                f"mean={h['mean']:.3f} p50={h['p50']:.3f} "
                f"p95={h['p95']:.3f} p99={h['p99']:.3f}"
            )
    return "\n".join(lines)


def load_request_records(paths: Iterable[str]) -> List[dict]:
    """Load durable request-log records (tpudl.obs.requestlog) from
    directories: each path is a request-log directory itself or a run
    directory holding a ``requestlog/`` subdir (the
    TPUDL_OBS_REQUEST_LOG convention of pointing it next to
    TPUDL_OBS_DIR)."""
    from tpudl.obs import requestlog

    records: List[dict] = []
    for p in paths:
        found = None
        for d in (p, os.path.join(p, "requestlog")):
            if os.path.isdir(d) and requestlog.list_segments(d):
                found = d
                break
        if found is None:
            raise FileNotFoundError(
                f"no request-log segments (requests-*.jsonl) under {p}"
            )
        records.extend(requestlog.read_request_log(found))
    return records


def find_request_record(paths: Iterable[str], request_id) -> Optional[dict]:
    """The durable terminal record for one request, or None — the
    ``--request`` fallback when the span stream is gone. Matched by
    string form too (CLI args are strings)."""
    try:
        records = load_request_records(paths)
    except FileNotFoundError:
        return None
    for rec in records:
        rid = rec.get("request_id")
        if rid == request_id or str(rid) == str(request_id):
            return rec
    return None


def build_tenant_report(records: Iterable[dict]) -> dict:
    """Cost-attribution rollup over durable request-log records: one
    row per tenant with request/token volumes, chip-seconds (slot
    occupancy), KV byte-seconds (the bytes-model cost numerator), and
    each tenant's share of total chip time. Reuses the live metering
    plane's fold (``TenantMeter.ingest``) so the offline table and the
    scraped ``serve_tenant_*`` series can never disagree."""
    from tpudl.obs.metering import TenantMeter

    m = TenantMeter()
    n = 0
    for rec in records:
        m.ingest(rec)
        n += 1
    tenants = m.tenants()
    total_chip = sum(u["chip_seconds"] for u in tenants.values())
    for u in tenants.values():
        u["chip_share"] = (
            u["chip_seconds"] / total_chip if total_chip else 0.0
        )
    return {
        "records": n,
        "tenants": tenants,
        "total_chip_seconds": total_chip,
    }


def format_tenant_report(report: dict) -> str:
    lines = [
        f"request-log records: {report['records']}  "
        f"total chip-seconds: {report['total_chip_seconds']:.3f}",
        "",
        f"{'tenant':<16} {'req':>6} {'done':>6} {'shed':>6} "
        f"{'tok_in':>8} {'tok_out':>8} {'chip_s':>10} "
        f"{'kv_gb_s':>10} {'reloads':>8} {'share':>7}",
    ]
    for tenant in sorted(report["tenants"]):
        u = report["tenants"][tenant]
        shed = sum(u["sheds"].values())
        lines.append(
            f"{tenant:<16} {u['requests_total']:>6} "
            f"{u['requests_completed']:>6} {shed:>6} "
            f"{u['tokens_in']:>8} {u['tokens_out']:>8} "
            f"{u['chip_seconds']:>10.3f} "
            f"{u['kv_byte_seconds'] / 1e9:>10.4f} "
            f"{u['adapter_reloads']:>8} {u['chip_share']:>6.1%}"
        )
    sheds: Dict[str, int] = {}
    for u in report["tenants"].values():
        for reason, count in u["sheds"].items():
            sheds[reason] = sheds.get(reason, 0) + count
    if sheds:
        lines.append("")
        lines.append(
            "sheds by reason: " + " ".join(
                f"{r}={n}" for r, n in sorted(sheds.items())
            )
        )
    return "\n".join(lines)


def load_flywheel_state(paths: Iterable[str]) -> dict:
    """The persisted ``FlywheelController`` state
    (``flywheel-state.json``, written next to the request-log
    segments) from the first path that holds one — paths follow the
    ``--tenants`` convention (a request-log directory, or a run dir
    with a ``requestlog/`` subdir)."""
    from tpudl.flywheel.loop import STATE_FILENAME

    for p in paths:
        for d in (p, os.path.join(p, "requestlog")):
            f = os.path.join(d, STATE_FILENAME)
            if os.path.isfile(f):
                with open(f, "r", encoding="utf-8") as fh:
                    return json.load(fh)
    raise FileNotFoundError(
        f"no flywheel-state.json under {list(paths)} — has a "
        f"FlywheelController run against this request log?"
    )


def build_flywheel_report(state: dict) -> dict:
    """Per-tenant refresh rollup over the controller's persisted
    history: refresh count, records consumed, the last consumed log
    position, last swap time, and the last refresh's loss delta."""
    tenants: Dict[str, dict] = {}
    for entry in state.get("history", ()):
        t = str(entry.get("tenant"))
        row = tenants.setdefault(t, {
            "refreshes": 0,
            "records_consumed": 0,
            "steps": 0,
            "log_position": None,
            "last_swap_ts": None,
            "loss_first": None,
            "loss_last": None,
            "pending_swap": False,
        })
        row["refreshes"] += 1
        row["records_consumed"] += int(entry.get("records_consumed", 0))
        row["steps"] += int(entry.get("steps", 0))
        row["log_position"] = entry.get("log_position")
        row["loss_first"] = entry.get("loss_first")
        row["loss_last"] = entry.get("loss_last")
        if entry.get("swapped"):
            row["last_swap_ts"] = entry.get("swap_ts")
            row["pending_swap"] = False
        else:
            row["pending_swap"] = True
    for t, pos in state.get("positions", {}).items():
        tenants.setdefault(str(t), {
            "refreshes": 0, "records_consumed": 0, "steps": 0,
            "log_position": None, "last_swap_ts": None,
            "loss_first": None, "loss_last": None,
            "pending_swap": False,
        })["log_position"] = {
            k: v for k, v in pos.items() if k in ("epoch", "offset")
        }
    return {
        "tenants": tenants,
        "total_refreshes": sum(
            r["refreshes"] for r in tenants.values()
        ),
        "last_swap_ts": state.get("last_swap_ts"),
    }


def format_flywheel_report(report: dict) -> str:
    import datetime

    def when(ts):
        if ts is None:
            return "—"
        return datetime.datetime.fromtimestamp(ts).strftime(
            "%Y-%m-%d %H:%M:%S"
        )

    lines = [
        f"flywheel refreshes: {report['total_refreshes']}  "
        f"last swap: {when(report['last_swap_ts'])}",
        "",
        f"{'tenant':<16} {'refreshes':>9} {'records':>8} {'steps':>6} "
        f"{'log_pos':>12} {'loss_delta':>11} {'last_swap':>20}",
    ]
    for tenant in sorted(report["tenants"]):
        r = report["tenants"][tenant]
        pos = r["log_position"] or {}
        pos_s = (
            f"{pos.get('epoch', '?')}:{pos.get('offset', '?')}"
            if pos else "—"
        )
        if r["loss_first"] is not None and r["loss_last"] is not None:
            delta = f"{r['loss_last'] - r['loss_first']:+11.4f}"
        else:
            delta = f"{'—':>11}"
        swap = when(r["last_swap_ts"]) + (
            " (pending)" if r["pending_swap"] else ""
        )
        lines.append(
            f"{tenant:<16} {r['refreshes']:>9} "
            f"{r['records_consumed']:>8} {r['steps']:>6} "
            f"{pos_s:>12} {delta} {swap:>20}"
        )
    return "\n".join(lines)


def format_request_record(rec: dict) -> str:
    """Render one durable terminal record — the ``--request`` answer
    when the span stream no longer exists (no per-hop timeline, but
    the outcome, volumes, and latency aggregates survive)."""
    lines = [
        f"request {rec.get('request_id')!r} "
        f"(durable record, schema v{rec.get('v')})",
        f"  tenant={rec.get('tenant')} site={rec.get('site')} "
        f"finish_reason={rec.get('finish_reason')}",
        f"  tokens_in={rec.get('tokens_in')} "
        f"tokens_out={rec.get('tokens_out')} "
        f"prefix_hit={rec.get('prefix_hit_tokens')} "
        f"spec={rec.get('spec_accepted')}/{rec.get('spec_proposed')}",
    ]
    qw, ttft, tpot = (
        rec.get("queue_wait_s"), rec.get("ttft_s"), rec.get("tpot_s")
    )
    lines.append(
        "  queue_wait={} ttft={} tpot={}".format(
            f"{1e3 * qw:.1f}ms" if qw is not None else "-",
            f"{1e3 * ttft:.1f}ms" if ttft is not None else "-",
            f"{1e3 * tpot:.2f}ms" if tpot is not None else "-",
        )
    )
    lines.append(
        f"  kv_page_s={rec.get('kv_page_seconds', 0.0):.3f} "
        f"kv_byte_s={rec.get('kv_byte_seconds', 0.0):.1f} "
        f"adapter_reloads={rec.get('adapter_reloads')} "
        f"migrations={rec.get('migrations')}"
    )
    return "\n".join(lines)


def main(argv: Optional[list] = None) -> int:
    import argparse

    ap = argparse.ArgumentParser(
        description="Aggregate tpudl obs span files into a step-time "
        "breakdown, goodput fraction, and straggler attribution"
    )
    ap.add_argument(
        "paths", nargs="+",
        help="span *.jsonl files and/or obs directories",
    )
    ap.add_argument("--outlier-factor", type=float, default=3.0,
                    help="flag steps slower than this multiple of p50")
    ap.add_argument("--straggler-factor", type=float, default=1.2,
                    help="flag hosts with mean step time above this "
                    "multiple of the cross-host median")
    ap.add_argument("--chrome-trace", metavar="OUT.json",
                    help="also export the records as Chrome trace-event "
                    "JSON for Perfetto")
    ap.add_argument("--request", metavar="ID",
                    help="print ONE served request's stitched trace "
                    "(router door -> admission -> prefill -> decode "
                    "chunks -> completion, merged across every span "
                    "stream given) with its TTFT decomposition, "
                    "instead of the run report")
    ap.add_argument("--fleet", action="store_true",
                    help="print the fleet rollup over the merged "
                    "streams: per-process record counts, request "
                    "outcomes, router hop latencies, failover/"
                    "autoscale activity, and partial-trace warnings")
    ap.add_argument("--tenants", action="store_true",
                    help="print the per-tenant cost-attribution table "
                    "from durable request-log records (paths are "
                    "request-log directories or run dirs holding a "
                    "requestlog/ subdir) instead of the span report")
    ap.add_argument("--flywheel", action="store_true",
                    help="print the per-tenant continual-refresh "
                    "history (records consumed, log position, last "
                    "swap, loss delta) from the FlywheelController's "
                    "flywheel-state.json next to the request log")
    ap.add_argument("--json", action="store_true")
    args = ap.parse_args(argv)

    if args.flywheel:
        try:
            fly_state = load_flywheel_state(args.paths)
        except FileNotFoundError as e:
            print(e)
            return 1
        fly = build_flywheel_report(fly_state)
        print(
            json.dumps(fly) if args.json else format_flywheel_report(fly)
        )
        return 0
    if args.tenants:
        # The durable log, not the span stream: --tenants answers
        # "who consumed which chips" after the serving processes (and
        # their TPUDL_OBS_DIR streams) are gone.
        try:
            reqlog = load_request_records(args.paths)
        except FileNotFoundError as e:
            print(e)
            return 1
        tenant_report = build_tenant_report(reqlog)
        print(
            json.dumps(tenant_report)
            if args.json else format_tenant_report(tenant_report)
        )
        return 0
    if args.request is not None:
        # Prefer the stitched span timeline; fall back to the durable
        # terminal record when the span stream is gone (or never held
        # this request) — the request log outlives TPUDL_OBS_DIR.
        try:
            records = load_records(args.paths)
            tl = build_request_timeline(records, args.request)
        except (KeyError, FileNotFoundError) as e:
            rec = find_request_record(args.paths, args.request)
            if rec is not None:
                print(
                    json.dumps(rec)
                    if args.json else format_request_record(rec)
                )
                return 0
            print(e.args[0] if e.args else str(e))
            return 1
        print(
            json.dumps(tl) if args.json else format_request_timeline(tl)
        )
        return 0

    records = load_records(args.paths)
    if args.fleet:
        fleet = build_fleet_report(records)
        if args.chrome_trace:
            with open(args.chrome_trace, "w") as f:
                json.dump(
                    {"traceEvents": chrome_trace_events(records)}, f
                )
        print(
            json.dumps(fleet) if args.json else format_fleet_report(fleet)
        )
        return 0
    report = build_report(
        records,
        outlier_factor=args.outlier_factor,
        straggler_factor=args.straggler_factor,
    )
    if args.chrome_trace:
        with open(args.chrome_trace, "w") as f:
            json.dump({"traceEvents": chrome_trace_events(records)}, f)
    print(json.dumps(report) if args.json else format_report(report))
    return 0


if __name__ == "__main__":
    import sys

    sys.exit(main())
