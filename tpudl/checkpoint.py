"""Train-state checkpoint / resume (Orbax), the recovery half of the
failure story.

The reference serializes models three ways but never training state and
never reads anything back to resume (reference
notebooks/cv/onnx_experiments.py:33-42,198,212-215 — ONNX export,
whole-module pickle, TorchScript trace; SURVEY.md §5.4). Here the full
TrainState — params, optimizer state, step counter, BatchNorm statistics —
round-trips through step-indexed Orbax checkpoints, and restore is
sharding-aware: leaves come back already placed according to the mesh +
rule set of the run being resumed (possibly a different topology than the
one that saved), so no full-state replication spike on big models.
"""

from __future__ import annotations

import json
import os
import shutil
import warnings
from typing import Optional, Tuple

import jax
import orbax.checkpoint as ocp
from jax.sharding import Mesh

from tpudl.ft.store import CheckpointShapeError  # noqa: F401  (re-export:
# the error both backends' restores raise on a changed-model template)
from tpudl.obs import counters as obs_counters
from tpudl.obs import spans as obs_spans
from tpudl.parallel.sharding import Rules, tree_shardings
from tpudl.train.loop import TrainState


def _ckpt_span(name: str, **attrs):
    """Checkpoint-category obs span (no-op when observability is off).
    Covers the SYNCHRONOUS part of a save — for async saves that is the
    device->host copy, which is exactly the slice of wall-clock the
    train loop loses to checkpointing."""
    return obs_spans.span(name, obs_spans.CAT_CHECKPOINT, **attrs)


def _state_payload(state: TrainState) -> dict:
    """The serializable subset of a TrainState (apply_fn/tx are code, not
    data — they come from the resuming program)."""
    payload = {
        "params": state.params,
        "opt_state": state.opt_state,
        # step may be a Python int on a fresh state; canonicalize for Orbax.
        "step": jax.numpy.asarray(state.step, jax.numpy.int32),
    }
    if state.batch_stats is not None:
        payload["batch_stats"] = state.batch_stats
    if getattr(state, "precision", None) is not None:
        # Mixed-precision policy state (tpudl.train.precision): loss
        # scale + fp8 amax rings — without it a resume would restart
        # the loss-scale schedule and re-warm every amax window.
        payload["precision"] = state.precision
    return payload


def _abstract_payload(
    state: TrainState, mesh: Optional[Mesh], rules: Optional[Rules]
) -> dict:
    """ShapeDtypeStruct tree for restore; with a mesh, each leaf carries the
    NamedSharding the rule set assigns, so Orbax materializes shards
    directly onto devices."""
    payload = _state_payload(state)
    if mesh is None:
        return jax.tree.map(
            lambda x: jax.ShapeDtypeStruct(jax.numpy.shape(x), x.dtype), payload
        )
    shardings = tree_shardings(mesh, payload, rules)
    return jax.tree.map(
        lambda x, s: jax.ShapeDtypeStruct(jax.numpy.shape(x), x.dtype, sharding=s),
        payload,
        shardings,
    )


_STAGE_SUFFIX = ".tpudl-staging"
_PREV_SUFFIX = ".tpudl-prev"


def save_train_state(path: str, state: TrainState, overwrite: bool = True) -> None:
    """One-shot full-train-state checkpoint at `path`.

    Crash-safe by construction: the payload is written to a STAGING
    sibling (``<path>.tpudl-staging``) first, then published with two
    renames (old -> ``<path>.tpudl-prev``, staging -> ``<path>``). A
    crash at any point leaves either the old checkpoint at `path`, or
    the new one, or — in the one window between the renames — the old
    one intact under the ``.tpudl-prev`` name, which
    ``restore_train_state`` falls back to. Never a torn directory that
    restore would trust."""
    path = os.path.abspath(path)
    staging = path + _STAGE_SUFFIX
    prev = path + _PREV_SUFFIX
    if os.path.exists(path) and not overwrite:
        raise FileExistsError(f"checkpoint exists at {path}")
    with _ckpt_span("save_train_state"):
        # Stale staging debris from an earlier crash must not block
        # this save.
        shutil.rmtree(staging, ignore_errors=True)
        with ocp.StandardCheckpointer() as ckptr:
            ckptr.save(staging, _state_payload(state), force=True)
        if os.path.exists(path):
            shutil.rmtree(prev, ignore_errors=True)
            os.rename(path, prev)
        # If only a .tpudl-prev survives (a PREVIOUS save crashed
        # mid-publish), it is the sole restorable checkpoint — it must
        # outlive the publish rename below, never be deleted before it.
        os.rename(staging, path)
        shutil.rmtree(prev, ignore_errors=True)


def restore_train_state(
    path: str,
    state: TrainState,
    mesh: Optional[Mesh] = None,
    rules: Optional[Rules] = None,
) -> TrainState:
    """Restore a checkpoint into `state`'s structure (a freshly-initialized
    TrainState from the same model/optimizer code). With `mesh`/`rules`,
    leaves arrive sharded for that topology. If `path` is missing but a
    ``.tpudl-prev`` sibling exists (a save crashed mid-publish), the
    previous committed checkpoint restores with a warning."""
    path = os.path.abspath(path)
    if not os.path.exists(path) and os.path.exists(path + _PREV_SUFFIX):
        warnings.warn(
            f"checkpoint {path} missing but a previous committed copy "
            f"exists ({path + _PREV_SUFFIX}) — a save crashed "
            f"mid-publish; restoring the previous checkpoint",
            stacklevel=2,
        )
        path = path + _PREV_SUFFIX
    with _ckpt_span("restore_train_state"):
        with ocp.StandardCheckpointer() as ckptr:
            payload = ckptr.restore(
                path, _abstract_payload(state, mesh, rules)
            )
    extra = {}
    if hasattr(state, "precision"):
        extra["precision"] = payload.get("precision", state.precision)
    return state.replace(
        params=payload["params"],
        opt_state=payload["opt_state"],
        step=payload["step"],
        batch_stats=payload.get("batch_stats", state.batch_stats),
        **extra,
    )


class CheckpointManager:
    """Step-indexed checkpoints with retention — the periodic-save side of
    fail-fast-then-resume (SURVEY.md §5.3/§5.4).

    save() is asynchronous (training continues while shards flush);
    close()/context-manager exit drains pending writes.

    Multi-process invariants (proved by
    tests/test_distributor.py::test_spawn_checkpoint_save_resume — a
    2-process TpuDistributor spawn that trains, saves, exits, and a
    FRESH spawn restores and continues):

    - every rank calls save()/restore() collectively; Orbax coordinates
      the write over jax.distributed (which TpuDistributor initializes)
      and the shared checkpoint directory, so no rank-0-only gating is
      needed in caller code;
    - restore() with mesh/rules materializes each rank's addressable
      shards directly onto its devices (no full-state host replication);
    - the restored trajectory is EXACTLY the uninterrupted one: params,
      optimizer momenta, BatchNorm stats, and the step counter (which
      seeds the per-step dropout/rng fold) all round-trip, and all
      ranks report identical global losses after the resume boundary.

    Two backends behind one API:

    - **Orbax** (default): multi-process-coordinated shard IO — the pod
      path for state sharded ACROSS processes.
    - **async_save=True**: tpudl.ft.AsyncCheckpointManager — the
      bounded-stall path: device->host snapshot on the step path only,
      serialization + atomic commit on a background writer thread
      (tpudl/ft/). fit() works identically against both.

    Both modes carry FULL resume state when ``save`` is given ``rng`` /
    ``data_state`` (the training RNG key and the data position), and
    ``restore_full`` returns them — so a resumed run replays neither
    batches nor dropout masks (Orbax mode keeps them in an atomically-
    written ``_tpudl_resume/`` sidecar next to the step dirs; the ft
    store carries them natively). Restores validate leaf shapes against
    the SAVED checkpoint's metadata and raise CheckpointShapeError
    naming the mismatched paths — Orbax would otherwise silently return
    the saved shapes and crash later inside the jitted step.
    """

    def __init__(
        self, directory: str, max_to_keep: int = 3, async_save: bool = False
    ):
        directory = os.path.abspath(directory)
        self._max_to_keep = max_to_keep
        self._impl = None
        self._mgr = None
        if async_save:
            from tpudl.ft.manager import AsyncCheckpointManager

            self._impl = AsyncCheckpointManager(
                directory, max_to_keep=max_to_keep
            )
            self.directory = self._impl.directory
        else:
            self._mgr = ocp.CheckpointManager(
                directory,
                options=ocp.CheckpointManagerOptions(
                    max_to_keep=max_to_keep, enable_async_checkpointing=True
                ),
            )
            self.directory = directory

    # -- resume-state sidecar (Orbax mode) ----------------------------

    def _sidecar_dir(self) -> str:
        return os.path.join(self.directory, "_tpudl_resume")

    def _sidecar_path(self, step: int) -> str:
        return os.path.join(self._sidecar_dir(), f"{step}.json")

    def _write_sidecar(
        self, step: int, rng: Optional[jax.Array], data_state: Optional[dict]
    ) -> None:
        if rng is None and data_state is None:
            return
        if jax.process_index() != 0:
            return  # one writer; every rank reads the shared file
        from tpudl.ft.manager import _encode_rng

        payload: dict = {"data_state": data_state}
        if rng is not None:
            rng_arr, rng_meta = _encode_rng(rng)
            payload["rng_data"] = rng_arr.tolist()
            payload["rng_dtype"] = str(rng_arr.dtype)
            payload["rng_meta"] = rng_meta
        os.makedirs(self._sidecar_dir(), exist_ok=True)
        tmp = self._sidecar_path(step) + f".tmp{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump(payload, f)
        os.replace(tmp, self._sidecar_path(step))
        # Retention mirrors the manager's: keep the newest max_to_keep;
        # crash debris (tmp files whose os.replace never ran) is reaped
        # too — this process is the sole writer, so any tmp not our own
        # is a dead writer's.
        try:
            entries = os.listdir(self._sidecar_dir())
        except OSError:
            return
        own_suffix = f".tmp{os.getpid()}"
        for name in entries:
            if ".json.tmp" in name and not name.endswith(own_suffix):
                try:
                    os.remove(os.path.join(self._sidecar_dir(), name))
                except OSError:
                    pass
        if not self._max_to_keep:
            return
        try:
            names = sorted(
                int(n[: -len(".json")])
                for n in entries
                if n.endswith(".json")
            )
        except ValueError:
            return
        for victim in names[: -self._max_to_keep]:
            try:
                os.remove(self._sidecar_path(victim))
            except OSError:
                pass

    def _read_sidecar(
        self, step: int
    ) -> Tuple[Optional[jax.Array], Optional[dict]]:
        try:
            with open(self._sidecar_path(step)) as f:
                payload = json.load(f)
        except (OSError, json.JSONDecodeError):
            return None, None
        rng = None
        if payload.get("rng_data") is not None:
            import numpy as np

            from tpudl.ft.manager import _decode_rng

            rng = _decode_rng(
                np.asarray(
                    payload["rng_data"],
                    dtype=payload.get("rng_dtype", "uint32"),
                ),
                payload.get("rng_meta") or {},
            )
        return rng, payload.get("data_state")

    # -- save/restore --------------------------------------------------

    def save(
        self,
        step: int,
        state: TrainState,
        rng: Optional[jax.Array] = None,
        data_state: Optional[dict] = None,
    ) -> bool:
        # INVARIANT callers rely on (tpudl.train.loop.fit donates the
        # just-saved state's buffers to the next compiled step): both
        # backends perform the device-to-host copy synchronously inside
        # save() and only background the serialization/disk write.
        if self._impl is not None:
            return self._impl.save(step, state, rng=rng, data_state=data_state)
        rec = obs_spans.active_recorder()
        t0 = rec.clock() if rec is not None else None
        saved = self._mgr.save(
            step, args=ocp.args.StandardSave(_state_payload(state))
        )
        if saved:
            self._write_sidecar(step, rng, data_state)
        if rec is not None:
            dur = rec.clock() - t0
            rec.record(
                "checkpoint_save", obs_spans.CAT_CHECKPOINT, t0, dur,
                {"step": step},
            )
            reg = obs_counters.registry()
            reg.histogram("checkpoint_time_s").observe(dur)
            if saved:
                reg.counter("checkpoint_saves").inc()
        return saved

    def _validate_against_metadata(self, step: int, abstract: dict) -> None:
        """Compare the restore template against the checkpoint's SAVED
        array metadata; raise CheckpointShapeError on mismatch (Orbax
        silently restores the saved shapes otherwise — the wrong-shape
        state then crashes later, far from the cause)."""
        try:
            meta = self._mgr.item_metadata(step)
        except Exception:
            return  # metadata unavailable: keep legacy behavior
        if meta is None:
            return
        jtu = jax.tree_util

        def norm(path) -> str:
            # Orbax metadata renders tuple positions as STRING dict
            # keys ('opt_state'/'0'/...), the abstract tree as
            # SequenceKey ints — normalize both to one spelling.
            parts = []
            for k in path:
                if hasattr(k, "key"):
                    parts.append(str(k.key))
                elif hasattr(k, "idx"):
                    parts.append(str(k.idx))
                elif hasattr(k, "name"):
                    parts.append(str(k.name))
                else:
                    parts.append(str(k))
            return "/".join(parts)

        from tpudl.ft.store import diff_leaf_shapes

        diff_leaf_shapes(
            {
                norm(p): tuple(getattr(m, "shape", ()) or ())
                for p, m in jtu.tree_flatten_with_path(meta)[0]
            },
            {
                norm(p): tuple(leaf.shape)
                for p, leaf in jtu.tree_flatten_with_path(abstract)[0]
            },
            f"checkpoint step {step} does not match the restore template",
        )

    def restore(
        self,
        state: TrainState,
        step: Optional[int] = None,
        mesh: Optional[Mesh] = None,
        rules: Optional[Rules] = None,
    ) -> TrainState:
        if self._impl is not None:
            return self._impl.restore(state, step=step, mesh=mesh, rules=rules)
        if step is None:
            step = self.latest_step()
            if step is None:
                raise FileNotFoundError(
                    f"no checkpoint found in {self._mgr.directory}"
                )
        abstract = _abstract_payload(state, mesh, rules)
        self._validate_against_metadata(step, abstract)
        with _ckpt_span("checkpoint_restore", step=step):
            payload = self._mgr.restore(
                step, args=ocp.args.StandardRestore(abstract)
            )
        extra = {}
        if hasattr(state, "precision"):
            extra["precision"] = payload.get("precision", state.precision)
        return state.replace(
            params=payload["params"],
            opt_state=payload["opt_state"],
            step=payload["step"],
            batch_stats=payload.get("batch_stats", state.batch_stats),
            **extra,
        )

    def restore_full(
        self,
        state: TrainState,
        step: Optional[int] = None,
        mesh: Optional[Mesh] = None,
        rules: Optional[Rules] = None,
    ) -> Tuple[TrainState, Optional[jax.Array], Optional[dict]]:
        """Restore ``(state, rng, data_state)`` — the training RNG key
        and data position saved alongside the state (None each when the
        checkpoint predates them)."""
        if self._impl is not None:
            return self._impl.restore_full(
                state, step=step, mesh=mesh, rules=rules
            )
        if step is None:
            step = self.latest_step()
            if step is None:
                raise FileNotFoundError(
                    f"no checkpoint found in {self._mgr.directory}"
                )
        restored = self.restore(state, step=step, mesh=mesh, rules=rules)
        rng, data_state = self._read_sidecar(step)
        return restored, rng, data_state

    def latest_step(self) -> Optional[int]:
        if self._impl is not None:
            return self._impl.latest_step()
        return self._mgr.latest_step()

    def all_steps(self):
        if self._impl is not None:
            return self._impl.all_steps()
        return self._mgr.all_steps()

    def wait_until_finished(self) -> None:
        if self._impl is not None:
            self._impl.wait_until_finished()
        else:
            self._mgr.wait_until_finished()

    def close(self) -> None:
        if self._impl is not None:
            self._impl.close()
        else:
            self._mgr.close()

    def __enter__(self) -> "CheckpointManager":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
