"""Train-state checkpoint / resume (Orbax), the recovery half of the
failure story.

The reference serializes models three ways but never training state and
never reads anything back to resume (reference
notebooks/cv/onnx_experiments.py:33-42,198,212-215 — ONNX export,
whole-module pickle, TorchScript trace; SURVEY.md §5.4). Here the full
TrainState — params, optimizer state, step counter, BatchNorm statistics —
round-trips through step-indexed Orbax checkpoints, and restore is
sharding-aware: leaves come back already placed according to the mesh +
rule set of the run being resumed (possibly a different topology than the
one that saved), so no full-state replication spike on big models.
"""

from __future__ import annotations

import os
from typing import Any, Optional

import jax
import orbax.checkpoint as ocp
from jax.sharding import Mesh

from tpudl.obs import counters as obs_counters
from tpudl.obs import spans as obs_spans
from tpudl.parallel.sharding import Rules, tree_shardings
from tpudl.train.loop import TrainState


def _ckpt_span(name: str, **attrs):
    """Checkpoint-category obs span (no-op when observability is off).
    Covers the SYNCHRONOUS part of a save — for async saves that is the
    device->host copy, which is exactly the slice of wall-clock the
    train loop loses to checkpointing."""
    return obs_spans.span(name, obs_spans.CAT_CHECKPOINT, **attrs)


def _state_payload(state: TrainState) -> dict:
    """The serializable subset of a TrainState (apply_fn/tx are code, not
    data — they come from the resuming program)."""
    payload = {
        "params": state.params,
        "opt_state": state.opt_state,
        # step may be a Python int on a fresh state; canonicalize for Orbax.
        "step": jax.numpy.asarray(state.step, jax.numpy.int32),
    }
    if state.batch_stats is not None:
        payload["batch_stats"] = state.batch_stats
    return payload


def _abstract_payload(
    state: TrainState, mesh: Optional[Mesh], rules: Optional[Rules]
) -> dict:
    """ShapeDtypeStruct tree for restore; with a mesh, each leaf carries the
    NamedSharding the rule set assigns, so Orbax materializes shards
    directly onto devices."""
    payload = _state_payload(state)
    if mesh is None:
        return jax.tree.map(
            lambda x: jax.ShapeDtypeStruct(jax.numpy.shape(x), x.dtype), payload
        )
    shardings = tree_shardings(mesh, payload, rules)
    return jax.tree.map(
        lambda x, s: jax.ShapeDtypeStruct(jax.numpy.shape(x), x.dtype, sharding=s),
        payload,
        shardings,
    )


def save_train_state(path: str, state: TrainState, overwrite: bool = True) -> None:
    """One-shot full-train-state checkpoint at `path`."""
    with _ckpt_span("save_train_state"):
        with ocp.StandardCheckpointer() as ckptr:
            ckptr.save(
                os.path.abspath(path), _state_payload(state), force=overwrite
            )


def restore_train_state(
    path: str,
    state: TrainState,
    mesh: Optional[Mesh] = None,
    rules: Optional[Rules] = None,
) -> TrainState:
    """Restore a checkpoint into `state`'s structure (a freshly-initialized
    TrainState from the same model/optimizer code). With `mesh`/`rules`,
    leaves arrive sharded for that topology."""
    with _ckpt_span("restore_train_state"):
        with ocp.StandardCheckpointer() as ckptr:
            payload = ckptr.restore(
                os.path.abspath(path), _abstract_payload(state, mesh, rules)
            )
    return state.replace(
        params=payload["params"],
        opt_state=payload["opt_state"],
        step=payload["step"],
        batch_stats=payload.get("batch_stats", state.batch_stats),
    )


class CheckpointManager:
    """Step-indexed checkpoints with retention — the periodic-save side of
    fail-fast-then-resume (SURVEY.md §5.3/§5.4).

    save() is asynchronous (training continues while shards flush);
    close()/context-manager exit drains pending writes.

    Multi-process invariants (proved by
    tests/test_distributor.py::test_spawn_checkpoint_save_resume — a
    2-process TpuDistributor spawn that trains, saves, exits, and a
    FRESH spawn restores and continues):

    - every rank calls save()/restore() collectively; Orbax coordinates
      the write over jax.distributed (which TpuDistributor initializes)
      and the shared checkpoint directory, so no rank-0-only gating is
      needed in caller code;
    - restore() with mesh/rules materializes each rank's addressable
      shards directly onto its devices (no full-state host replication);
    - the restored trajectory is EXACTLY the uninterrupted one: params,
      optimizer momenta, BatchNorm stats, and the step counter (which
      seeds the per-step dropout/rng fold) all round-trip, and all
      ranks report identical global losses after the resume boundary.
    """

    def __init__(self, directory: str, max_to_keep: int = 3):
        self._mgr = ocp.CheckpointManager(
            os.path.abspath(directory),
            options=ocp.CheckpointManagerOptions(
                max_to_keep=max_to_keep, enable_async_checkpointing=True
            ),
        )

    def save(self, step: int, state: TrainState) -> bool:
        # INVARIANT callers rely on (tpudl.train.loop.fit donates the
        # just-saved state's buffers to the next compiled step): Orbax's
        # async save performs the device-to-host copy synchronously inside
        # save() and only backgrounds the disk write. If the checkpoint
        # backend ever changes to copy lazily, snapshot the payload here
        # (e.g. jax.device_get on single-host) before returning.
        rec = obs_spans.active_recorder()
        if rec is None:
            return self._mgr.save(
                step, args=ocp.args.StandardSave(_state_payload(state))
            )
        t0 = rec.clock()
        saved = self._mgr.save(
            step, args=ocp.args.StandardSave(_state_payload(state))
        )
        dur = rec.clock() - t0
        rec.record(
            "checkpoint_save", obs_spans.CAT_CHECKPOINT, t0, dur,
            {"step": step},
        )
        reg = obs_counters.registry()
        reg.histogram("checkpoint_time_s").observe(dur)
        if saved:
            reg.counter("checkpoint_saves").inc()
        return saved

    def restore(
        self,
        state: TrainState,
        step: Optional[int] = None,
        mesh: Optional[Mesh] = None,
        rules: Optional[Rules] = None,
    ) -> TrainState:
        if step is None:
            step = self.latest_step()
            if step is None:
                raise FileNotFoundError(
                    f"no checkpoint found in {self._mgr.directory}"
                )
        with _ckpt_span("checkpoint_restore", step=step):
            payload = self._mgr.restore(
                step,
                args=ocp.args.StandardRestore(
                    _abstract_payload(state, mesh, rules)
                ),
            )
        return state.replace(
            params=payload["params"],
            opt_state=payload["opt_state"],
            step=payload["step"],
            batch_stats=payload.get("batch_stats", state.batch_stats),
        )

    def latest_step(self) -> Optional[int]:
        return self._mgr.latest_step()

    def all_steps(self):
        return self._mgr.all_steps()

    def wait_until_finished(self) -> None:
        self._mgr.wait_until_finished()

    def close(self) -> None:
        self._mgr.close()

    def __enter__(self) -> "CheckpointManager":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
