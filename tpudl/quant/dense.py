"""Quantized matmul with dequantization fused into the contraction.

The serving-path identity this module exploits: for symmetric
per-output-channel quantization, ``x @ (q * scale) == (x @ q) * scale``
— the scale broadcasts over the output channel, so it can be applied
AFTER the contraction. The fused path therefore feeds the int8/fp8
values straight into ``lax.dot_general(preferred_element_type=f32)``
(mixed-dtype contraction, f32 accumulation) and pays one broadcast
multiply on the [.., out] result; the full-precision weight matrix is
never materialized, which is the whole bytes-moved point.

Same ``impl=`` dispatch seam as tpudl.ops (norms.resolve_impl's
shape): ``"fused"`` is the contraction-fused form above,
``"reference"`` is the composite — dequantize the kernel, then the
exact ``nn.Dense`` math — kept as the parity baseline (the two differ
only by scale-multiply association, bounded by tests/test_quant.py).
``"auto"`` resolves to fused everywhere: unlike the Pallas tier there
is no interpret-mode cliff off-TPU, both forms are plain XLA.

``QuantDense`` is the flax module the model ``weight_dtype`` seams
swap in for ``nn.Dense`` at the projection sites. Its init declares
the SAME params as ``nn.Dense`` (full-precision kernel [+ bias], same
initializers), so the param tree structure is identical across modes;
at apply time it dispatches on what the tree actually holds — a plain
kernel runs bit-identical ``nn.Dense`` math, a quantized
``{"qvalues","qscale"}`` dict runs the fused matmul. Biases and
everything downstream stay full precision.
"""

from __future__ import annotations

from typing import Any, Callable, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp
from flax.linen import dtypes as flax_dtypes
from jax import lax

from tpudl.quant.quantize import dequantize_leaf, is_quantized


def resolve_impl(impl: str) -> str:
    """``impl`` -> "fused" | "reference" (the tpudl.ops dispatch-seam
    shape). "auto" = fused on every backend — both forms are plain
    XLA, so there is no off-TPU interpret-mode penalty to dodge."""
    if impl == "auto":
        return "fused"
    if impl not in ("fused", "reference"):
        raise ValueError(
            f"impl must be 'auto', 'fused' or 'reference', got {impl!r}"
        )
    return impl


def quant_dot(
    x: jax.Array,
    kernel: Any,
    *,
    impl: str = "auto",
    compute_dtype=None,
    precision=None,
) -> jax.Array:
    """``x @ kernel`` for a quantized-or-plain kernel.

    Quantized (``{"qvalues","qscale"}``): fused = mixed-dtype
    ``dot_general(x, qvalues, preferred_element_type=f32)`` then one
    per-output-channel scale multiply; reference = dequantize first,
    contract in ``compute_dtype``. Plain array kernels contract in
    ``compute_dtype`` directly (the nn.Dense shape). Returns
    ``compute_dtype`` (default: ``x.dtype``)."""
    if compute_dtype is None:
        compute_dtype = x.dtype
    dims = (((x.ndim - 1,), (0,)), ((), ()))
    if not is_quantized(kernel):
        return lax.dot_general(
            x.astype(compute_dtype), kernel.astype(compute_dtype),
            dims, precision=precision,
        )
    if resolve_impl(impl) == "reference":
        w = dequantize_leaf(kernel, compute_dtype)
        y = lax.dot_general(
            x.astype(compute_dtype), w, dims, precision=precision
        )
        return y.astype(compute_dtype)
    y = lax.dot_general(
        x.astype(compute_dtype), kernel["qvalues"], dims,
        precision=precision, preferred_element_type=jnp.float32,
    )
    return (y * kernel["qscale"]).astype(compute_dtype)


class QuantDense(nn.Module):
    """Drop-in ``nn.Dense`` whose kernel may arrive quantized.

    Init-time params are IDENTICAL to ``nn.Dense`` (f32 kernel/bias,
    same initializers) — the ``weight_dtype`` seam changes which module
    runs, never the tree a checkpoint restores into. Serving passes
    the ``tpudl.quant.quantize.quantize_tree`` output, whose matched
    kernels are ``{"qvalues","qscale"}`` dicts; apply dispatches on
    the stored value, so one module serves both precisions."""

    features: int
    use_bias: bool = True
    dtype: Optional[Any] = None
    kernel_init: Callable = nn.initializers.lecun_normal()
    bias_init: Callable = nn.initializers.zeros_init()
    impl: str = "auto"
    precision: Optional[Any] = None

    @nn.compact
    def __call__(self, inputs: jax.Array) -> jax.Array:
        # A quantized kernel must be read around self.param: flax
        # validates a stored param's shape against the initializer's
        # abstract output, and the {"qvalues","qscale"} pair is not
        # the init-time f32 kernel shape. Full-precision trees (and
        # init itself) still flow through self.param unchanged.
        stored = (
            self.get_variable("params", "kernel")
            if self.has_variable("params", "kernel")
            else None
        )
        if is_quantized(stored):
            kernel = stored
        else:
            kernel = self.param(
                "kernel",
                self.kernel_init,
                (jnp.shape(inputs)[-1], self.features),
            )
        bias = (
            self.param("bias", self.bias_init, (self.features,))
            if self.use_bias
            else None
        )
        if is_quantized(kernel):
            compute = self.dtype or inputs.dtype
            y = quant_dot(
                inputs, kernel, impl=self.impl, compute_dtype=compute,
                precision=self.precision,
            )
            if bias is not None:
                y = y + bias.astype(y.dtype)
            return y
        # Full-precision path: nn.Dense's exact math (promote_dtype,
        # dot_general, broadcast bias) so weight_dtype=None-shaped
        # checkpoints run bit-identical to the plain module.
        inputs, kernel, bias = flax_dtypes.promote_dtype(
            inputs, kernel, bias, dtype=self.dtype
        )
        y = lax.dot_general(
            inputs, kernel,
            (((inputs.ndim - 1,), (0,)), ((), ())),
            precision=self.precision,
        )
        if bias is not None:
            y = y + jnp.reshape(bias, (1,) * (y.ndim - 1) + (-1,))
        return y
