"""Post-training weight quantization of a trained param tree.

Rule-driven (the SNIPPETS.md [2] ``match_partition_rules`` shape): a
rule list of ``(path_regex, weight_dtype_or_None)`` pairs is matched
against each leaf's ``module/submodule/kernel`` path string, first
match wins, and the matched dtype decides the leaf's fate — ``None``
keeps full precision, ``"int8"``/``"fp8_e4m3"`` quantize. The default
rule sets quantize exactly the decode-bandwidth-dominant matmul
weights (attention + MLP projections) and keep everything whose
precision is load-bearing (LayerNorm/RMSNorm scales, embeddings, the
LM/classifier head) full precision.

Quantization is symmetric per-OUTPUT-channel: a ``[in, out]`` kernel
gets one f32 scale per output column (``scale = max|w| / range``), so
the matmul dequantizes AFTER the contraction with a single broadcast
multiply (tpudl.quant.dense) — the weight matrix never exists at full
precision on the serving path.

Storage contract: a quantized leaf is a plain dict
``{"qvalues": int8|float8_e4m3fn [..., out], "qscale": f32 [out]}``
sitting under the ORIGINAL param key. The tree's module structure is
therefore identical to the full-precision tree — flax ``apply`` hands
the dict to ``QuantDense``, Orbax checkpoints round-trip it as two
ordinary arrays, and jax.export serializes the in_tree without any
custom pytree registration.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

from tpudl import rules as rules_engine

#: Supported weight storage dtypes. ``int8``: symmetric [-127, 127]
#: (4x smaller than f32, the headline serving mode). ``fp8_e4m3``:
#: values stored in the e4m3 grid (native ``jnp.float8_e4m3fn``) with
#: a per-channel scale mapping the channel max onto e4m3's 448 top —
#: same 4x bytes, coarser mantissa but wider dynamic range per channel.
QUANT_DTYPES = ("int8", "fp8_e4m3")

#: Symmetric int8 range (matches tpudl.models.paged's KV quantizer).
INT8_MAX = 127.0
#: Largest finite e4m3 magnitude.
E4M3_MAX = 448.0
#: Scale floor: an all-zero channel dequantizes to zeros, not NaN.
SCALE_EPS = 1e-12

#: One rule: (regex searched against the leaf's "a/b/kernel" path,
#: weight dtype or None = keep full precision).
Rule = Tuple[str, Optional[str]]
Rules = Sequence[Rule]

#: Which Llama leaves quantize: the seven per-block projections —
#: embeddings, norms, lm_head, the classifier, and any LoRA adapters
#: stay full precision (the rule-class contract tests/test_quant.py
#: pins). Patterns are dtype-free; ``default_quant_rules`` pairs them
#: with the requested storage dtype and appends the keep-all fallback.
LLAMA_QUANT_PATTERNS = (
    r"(q|k|v|o)_proj/kernel$",
    r"(gate|up|down)_proj/kernel$",
)

#: Which BERT leaves quantize: encoder attention + MLP projections.
#: The pooler/classifier head and embeddings keep full precision.
BERT_QUANT_PATTERNS = (
    r"attention/(query|key|value|out)/kernel$",
    r"encoder/layer_\d+/(intermediate|output)/kernel$",
)


def validate_weight_dtype(weight_dtype: str) -> str:
    if weight_dtype not in QUANT_DTYPES:
        raise ValueError(
            f"weight_dtype must be one of {QUANT_DTYPES}, got "
            f"{weight_dtype!r}"
        )
    if weight_dtype == "fp8_e4m3" and not hasattr(jnp, "float8_e4m3fn"):
        raise RuntimeError(
            "fp8_e4m3 weight storage needs jnp.float8_e4m3fn, which this "
            "jax build does not provide — use weight_dtype='int8'"
        )
    return weight_dtype


def is_quantized(leaf: Any) -> bool:
    """True for the ``{"qvalues", "qscale"}`` quantized-leaf dict."""
    return isinstance(leaf, dict) and set(leaf) == {"qvalues", "qscale"}


def quantize_leaf(w: jax.Array, weight_dtype: str) -> dict:
    """Symmetric per-output-channel quantization of one kernel.

    ``w`` [..., out] -> ``{"qvalues": [..., out] in the storage dtype,
    "qscale": f32 [out]}`` with ``scale = max|w_channel| / range``;
    ``qvalues * qscale`` reconstructs ``w`` to within half a
    quantization step (int8) / one e4m3 ulp (fp8) of the channel max —
    the bound tests/test_quant.py asserts per rule class."""
    validate_weight_dtype(weight_dtype)
    if w.ndim < 2:
        raise ValueError(
            f"per-output-channel quantization needs a >=2-D kernel, got "
            f"shape {jnp.shape(w)} — rules must leave scalars/vectors "
            f"(biases, norm scales) full precision"
        )
    wf = jnp.asarray(w, jnp.float32)
    reduce_axes = tuple(range(wf.ndim - 1))
    absmax = jnp.max(jnp.abs(wf), axis=reduce_axes)
    if weight_dtype == "int8":
        scale = jnp.maximum(absmax / INT8_MAX, SCALE_EPS)
        q = jnp.clip(
            jnp.round(wf / scale), -INT8_MAX, INT8_MAX
        ).astype(jnp.int8)
    else:  # fp8_e4m3: cast onto the e4m3 grid at the channel's scale
        scale = jnp.maximum(absmax / E4M3_MAX, SCALE_EPS)
        q = (wf / scale).astype(jnp.float8_e4m3fn)
    return {"qvalues": q, "qscale": scale.astype(jnp.float32)}


def dequantize_leaf(leaf: dict, dtype=jnp.float32) -> jax.Array:
    """Materialize a quantized leaf at full precision (the composite
    reference path; the fused serving matmul never calls this)."""
    return (
        leaf["qvalues"].astype(jnp.float32) * leaf["qscale"]
    ).astype(dtype)


def _path_str(path) -> str:
    return rules_engine.path_str(path)


def _quant_special(name: str, leaf: Any):
    """The quantizer's intrinsic per-leaf rule: leaves with ndim < 2
    (biases, norm scales, scalars) and already-quantized dicts never
    quantize regardless of rules — they annotate None without a rule
    lookup (tpudl.rules.annotate ``special`` hook)."""
    if is_quantized(leaf) or jnp.ndim(leaf) < 2:
        return True, None
    return False, None


def _dtype_for(name: str, leaf: Any, rules: Rules) -> Optional[str]:
    """First-match rule lookup for one leaf through the shared engine
    (tpudl.rules.first_match — bitwise-identical resolution to the
    pre-factoring private loop, tests/test_rules.py pins it). A >=2-D
    leaf no rule covers raises — an uncovered parameter is a rule-set
    bug, not a default."""
    handled, annotation = _quant_special(name, leaf)
    if handled:
        return annotation
    dtype = rules_engine.first_match(rules, name)
    if dtype is rules_engine.NO_MATCH:
        raise ValueError(
            f"no quantization rule matches parameter {name!r} — add an "
            f"explicit (pattern, None) keep rule or a catch-all"
        )
    return dtype


def match_quant_rules(rules: Rules, params: Any) -> Any:
    """Pytree of weight-dtype-or-None per leaf by first-match regex
    over the leaf's ``module/submodule/kernel`` path (the SNIPPETS.md
    [2] shape, via tpudl.rules.annotate). Quantized dicts stay opaque
    to the walk (their two arrays are one logical leaf), hence is_leaf
    on the marker."""
    return rules_engine.annotate(
        rules,
        params,
        special=_quant_special,
        is_leaf=is_quantized,
        what="quantization rule",
    )


def quantize_tree(params: Any, rules: Rules) -> Any:
    """Quantize a trained param tree by rules. Module structure is
    preserved exactly (matched kernels become ``{"qvalues","qscale"}``
    dicts in place); already-quantized leaves pass through untouched,
    so the transform is idempotent."""

    def one(path, leaf):
        dtype = _dtype_for(_path_str(path), leaf, rules)
        return leaf if dtype is None else quantize_leaf(leaf, dtype)

    return jax.tree_util.tree_map_with_path(
        one, params, is_leaf=is_quantized
    )


def dequantize_tree(params: Any, dtype=jnp.float32) -> Any:
    """Inverse transform (to quantized precision, not the original
    values): every quantized leaf materialized at ``dtype``."""
    return jax.tree_util.tree_map(
        lambda leaf: dequantize_leaf(leaf, dtype)
        if is_quantized(leaf)
        else leaf,
        params,
        is_leaf=is_quantized,
    )


def default_quant_rules(model_or_cfg: Any, weight_dtype: str) -> Rules:
    """The model family's rule set at ``weight_dtype``: quantize the
    attention/MLP projections, keep everything else (final ``(".*",
    None)`` fallback). Dispatches on the config shape — Llama
    (``rope_theta``) or BERT (``type_vocab_size``)."""
    validate_weight_dtype(weight_dtype)
    cfg = getattr(model_or_cfg, "cfg", model_or_cfg)
    if hasattr(cfg, "rope_theta"):
        patterns = LLAMA_QUANT_PATTERNS
    elif hasattr(cfg, "type_vocab_size"):
        patterns = BERT_QUANT_PATTERNS
    else:
        raise ValueError(
            f"no default quantization rules for {type(cfg).__name__}; "
            f"pass explicit rules to quantize_tree"
        )
    return tuple((p, weight_dtype) for p in patterns) + ((r".*", None),)


def quantize_model(
    model: Any, params: Any, weight_dtype: str, rules: Optional[Rules] = None
) -> Tuple[Any, Any]:
    """The one-call serving entry: ``(model, params) -> (model with
    ``cfg.weight_dtype`` set — its projections become QuantDense —
    quantized param tree)``. This is what
    ``ServeSession.from_model(weight_dtype=...)`` runs."""
    validate_weight_dtype(weight_dtype)
    cfg = model.cfg
    if not hasattr(cfg, "weight_dtype"):
        raise ValueError(
            f"{type(cfg).__name__} has no weight_dtype seam — only the "
            f"Llama/BERT families serve quantized"
        )
    if rules is None:
        rules = default_quant_rules(cfg, weight_dtype)
    if cfg.weight_dtype != weight_dtype:
        model = model.clone(
            cfg=dataclasses.replace(cfg, weight_dtype=weight_dtype)
        )
    return model, quantize_tree(params, rules)


def weight_bytes_report(params: Any) -> dict:
    """Bytes accounting for the serving bytes-moved model: total
    resident param bytes, the quantized layers' stored bytes vs their
    f32 equivalent (``quant_ratio`` — the >= 3.5x bar the parity grid
    asserts for int8; 4x minus the scale rows), and leaf counts."""
    total = 0
    quant_bytes = 0
    quant_f32_equiv = 0
    n_quant = 0
    n_leaves = 0
    for leaf in jax.tree.leaves(params, is_leaf=is_quantized):
        n_leaves += 1
        if is_quantized(leaf):
            n_quant += 1
            stored = leaf["qvalues"].nbytes + leaf["qscale"].nbytes
            quant_bytes += stored
            quant_f32_equiv += leaf["qvalues"].size * 4
            total += stored
        else:
            total += leaf.nbytes
    return {
        "total_bytes": total,
        "quantized_layer_bytes": quant_bytes,
        "quantized_layer_f32_bytes": quant_f32_equiv,
        "quant_ratio": (
            round(quant_f32_equiv / quant_bytes, 3) if quant_bytes else None
        ),
        "num_quantized_leaves": n_quant,
        "num_leaves": n_leaves,
    }
