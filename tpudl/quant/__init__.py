"""Low-precision weight tier: post-training quantization for serving.

Decode is weight-bandwidth-bound — every parameter is read once per
generated token — so shrinking the resident weight bytes is the TPOT
lever that matches the KV-side int8 tier (tpudl.models.paged). This
package quantizes a TRAINED param tree for serving:

- ``quantize.py``: regex-over-path rules (the SNIPPETS.md [2]
  ``match_partition_rules`` shape) select which leaves quantize —
  attention/MLP projections do, LayerNorm/embeddings/heads stay full
  precision — to symmetric per-output-channel **int8** or bf16-scaled
  **fp8 (e4m3)**. A quantized leaf is carried as a plain
  ``{"qvalues", "qscale"}`` dict under the ORIGINAL kernel key, so the
  param tree's module structure is identical to the full-precision
  tree and checkpoints / StableHLO in_trees round-trip unchanged.
- ``dense.py``: the quantized matmul with dequantization fused into
  the contraction (``lax.dot_general(preferred_element_type=...)``
  then one per-output-channel scale multiply — the weight matrix is
  never materialized at full precision), behind the same ``impl=``
  dispatch seam as tpudl.ops, plus ``QuantDense`` — the flax module
  the ``BertConfig.weight_dtype`` / ``LlamaConfig.weight_dtype`` seams
  swap in (param tree identical to ``nn.Dense`` at init, and it serves
  quantized and full-precision kernels interchangeably).

End to end: ``ServeSession.from_model(..., weight_dtype="int8")``
serves the quantized tree (composing with the paged int8 KV cache),
``tpudl.export.decode`` exports the quantized decoder through the
existing StableHLO path, and ``benchmarks/parity_grid.py`` gates every
precision x backend cell with ``assert_serving_parity``.
"""

from tpudl.quant.dense import (  # noqa: F401
    QuantDense,
    quant_dot,
    resolve_impl,
)
from tpudl.quant.quantize import (  # noqa: F401
    BERT_QUANT_PATTERNS,
    LLAMA_QUANT_PATTERNS,
    QUANT_DTYPES,
    default_quant_rules,
    dequantize_leaf,
    dequantize_tree,
    is_quantized,
    match_quant_rules,
    quantize_leaf,
    quantize_model,
    quantize_tree,
    weight_bytes_report,
)
