"""One rules engine for precision and placement.

Every per-leaf pytree annotation in tpudl — quantization dtypes
(tpudl.quant), PartitionSpecs (tpudl.parallel.sharding), precision
casts and optimizer-moment dtypes (tpudl.train.precision) — follows
the same contract, the SNIPPETS.md [2] ``match_partition_rules``
shape:

- a rule list of ``(path_regex, value)`` pairs is matched against each
  leaf's ``module/submodule/kernel`` path string with ``re.search``;
- FIRST match wins;
- an uncovered leaf is a rule-set bug, not a default — it raises,
  naming the leaf, unless the adapter opts into an explicit default.

This module is that machinery, factored out of tpudl/quant/quantize.py
(ROADMAP item 4's first clause) so precision policy and placement
policy are one regex-over-path contract instead of three private
reimplementations that drift. The adapters below (``annotate``,
``match_partition_rules``) cover the common shapes; consumers with
extra per-leaf semantics (the quantizer's ndim<2 skip, the sharding
engine's divisibility clamp) build on ``first_match``.
"""

from __future__ import annotations

import re
from typing import Any, Callable, Optional, Sequence, Tuple

import jax
import numpy as np

#: One rule: (regex searched — not fullmatched — against the leaf's
#: "a/b/kernel" path string, annotation value). The value's meaning is
#: the adapter's: a dtype name or None for the quantizer, a
#: PartitionSpec (or shape -> PartitionSpec callable) for placement, a
#: cast class for the precision policy.
Rule = Tuple[str, Any]
Rules = Sequence[Rule]


class _NoMatch:
    """Singleton sentinel: no rule covered the path (distinct from a
    rule that matched with value ``None`` — None is a legal, common
    annotation meaning "keep")."""

    __slots__ = ()

    def __repr__(self) -> str:  # pragma: no cover - repr cosmetics
        return "tpudl.rules.NO_MATCH"


NO_MATCH = _NoMatch()


def path_str(path) -> str:
    """'params/Dense_0/kernel'-style path string from a jax tree path.

    The one canonical keypath -> string conversion every rule consumer
    shares (tpudl.parallel.sharding re-exports it as ``_path_str`` for
    back-compat)."""
    parts = []
    for k in path:
        if hasattr(k, "key"):
            parts.append(str(k.key))
        elif hasattr(k, "idx"):
            parts.append(str(k.idx))
        elif hasattr(k, "name"):
            parts.append(str(k.name))
        else:
            parts.append(str(k))
    return "/".join(parts)


def first_match(rules: Optional[Rules], path: str) -> Any:
    """Value of the first rule whose regex searches into ``path``, else
    ``NO_MATCH``. The single resolution primitive — every adapter and
    every ported consumer (quantizer, sharding, precision) resolves
    through this function, so rule semantics cannot diverge."""
    if rules:
        for pattern, value in rules:
            if re.search(pattern, path):
                return value
    return NO_MATCH


def annotate(
    rules: Optional[Rules],
    tree: Any,
    *,
    special: Optional[Callable[[str, Any], Tuple[bool, Any]]] = None,
    default: Any = NO_MATCH,
    resolve: Optional[Callable[[Any, Any], Any]] = None,
    is_leaf: Optional[Callable[[Any], bool]] = None,
    what: str = "rule",
) -> Any:
    """Per-leaf annotation pytree for ``tree`` by first-match regex.

    - ``special(path, leaf) -> (handled, annotation)`` short-circuits
      rule lookup for leaves with intrinsic annotations (the
      quantizer's "ndim < 2 never quantizes", placement's "scalars
      replicate");
    - ``resolve(value, leaf)`` post-processes a matched value against
      the leaf (placement applies callable specs to the shape);
    - an uncovered leaf raises ``ValueError`` naming it — pass an
      explicit ``default`` to opt out (the legacy replicate-by-default
      sharding contract);
    - ``what`` names the rule family in the raise message.
    """

    def one(path, leaf):
        name = path_str(path)
        if special is not None:
            handled, annotation = special(name, leaf)
            if handled:
                return annotation
        value = first_match(rules, name)
        if value is NO_MATCH:
            if default is NO_MATCH:
                raise ValueError(
                    f"no {what} matches parameter {name!r} — add an "
                    f"explicit (pattern, None) keep rule or a catch-all"
                )
            return default
        return resolve(value, leaf) if resolve is not None else value

    return jax.tree_util.tree_map_with_path(one, tree, is_leaf=is_leaf)


def match_partition_rules(
    rules: Optional[Rules], tree: Any, *, default: Any = NO_MATCH
) -> Any:
    """PartitionSpec pytree for ``tree`` (the SNIPPETS.md [2]
    ``match_partition_rules`` shape): scalars and single-element leaves
    replicate, a callable rule value is applied to the leaf's shape
    (rank-dependent placement), first match wins, and an uncovered
    multi-element leaf raises — pass ``default=PartitionSpec()`` for
    the legacy replicate-by-default behavior.

    Covers params AND optimizer state in one call: optax moment trees
    mirror the param tree, so ``kernel$``-style rules match their
    leaves at the ``opt_state/.../mu/...`` paths too (the ROADMAP
    item-4 seam — tests/test_rules.py pins full TrainState coverage).
    """
    from jax.sharding import PartitionSpec

    def special(name, leaf):
        shape = getattr(leaf, "shape", ())
        if len(shape) == 0 or int(np.prod(shape)) == 1:
            return True, PartitionSpec()
        return False, None

    def resolve(value, leaf):
        return value(getattr(leaf, "shape", ())) if callable(value) else value

    return annotate(
        rules,
        tree,
        special=special,
        default=default,
        resolve=resolve,
        what="partition rule",
    )
