"""Flax ResNet family (18/34/50/101).

TPU-native re-design of the CV workload family: the reference consumes
torchvision's pretrained ResNet-50 (reference:
notebooks/cv/onnx_experiments.py:19 `models.resnet50(pretrained=True)`) and
exercises it through export/inference paths. Here the model is a first-party
Flax module so it can be trained (BASELINE.json configs[0] ResNet-18/CIFAR-10,
configs[2] ResNet-50/ImageNet DP) and exported/benched by tpudl.export.

TPU notes:
- NHWC layout (XLA's native conv layout on TPU; torchvision is NCHW).
- bfloat16 compute / float32 params and batch-norm statistics.
- ``small_inputs=True`` switches to the CIFAR stem (3x3 s1 conv, no pool).
- Batch statistics are computed with global semantics: under pjit with the
  batch axis sharded over (dp, fsdp), XLA turns the batch-mean reductions
  into cross-replica collectives automatically — synchronized BatchNorm for
  free, where the GPU lineage needs an explicit SyncBatchNorm wrapper.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Callable, Sequence, Tuple

import flax.linen as nn
import jax.numpy as jnp

from tpudl.parallel.sharding import constrain

ModuleDef = Any


class ResNetBlock(nn.Module):
    """Basic 3x3+3x3 residual block (ResNet-18/34)."""

    filters: int
    conv: ModuleDef
    norm: ModuleDef
    act: Callable
    strides: Tuple[int, int] = (1, 1)

    @nn.compact
    def __call__(self, x):
        residual = x
        y = self.conv(self.filters, (3, 3), self.strides)(x)
        y = self.norm()(y)
        y = self.act(y)
        y = self.conv(self.filters, (3, 3))(y)
        y = self.norm(scale_init=nn.initializers.zeros_init())(y)
        if residual.shape != y.shape:
            residual = self.conv(self.filters, (1, 1), self.strides, name="conv_proj")(
                residual
            )
            residual = self.norm(name="norm_proj")(residual)
        return self.act(residual + y)


class BottleneckResNetBlock(nn.Module):
    """1x1-3x3-1x1 bottleneck block (ResNet-50/101)."""

    filters: int
    conv: ModuleDef
    norm: ModuleDef
    act: Callable
    strides: Tuple[int, int] = (1, 1)

    @nn.compact
    def __call__(self, x):
        residual = x
        y = self.conv(self.filters, (1, 1))(x)
        y = self.norm()(y)
        y = self.act(y)
        y = self.conv(self.filters, (3, 3), self.strides)(y)
        y = self.norm()(y)
        y = self.act(y)
        y = self.conv(self.filters * 4, (1, 1))(y)
        y = self.norm(scale_init=nn.initializers.zeros_init())(y)
        if residual.shape != y.shape:
            residual = self.conv(
                self.filters * 4, (1, 1), self.strides, name="conv_proj"
            )(residual)
            residual = self.norm(name="norm_proj")(residual)
        return self.act(residual + y)


class ResNet(nn.Module):
    """ResNet over NHWC images."""

    stage_sizes: Sequence[int]
    block_cls: ModuleDef
    num_classes: int
    num_filters: int = 64
    dtype: Any = jnp.bfloat16
    act: Callable = nn.relu
    small_inputs: bool = False  # CIFAR stem

    @nn.compact
    def __call__(self, x, train: bool = True):
        conv = partial(
            nn.Conv,
            use_bias=False,
            dtype=self.dtype,
            kernel_init=nn.initializers.he_normal(),
        )
        norm = partial(
            nn.BatchNorm,
            use_running_average=not train,
            momentum=0.9,
            epsilon=1e-5,
            dtype=self.dtype,
        )

        x = x.astype(self.dtype)
        if self.small_inputs:
            x = conv(self.num_filters, (3, 3), (1, 1), name="conv_init")(x)
            x = norm(name="bn_init")(x)
            x = self.act(x)
        else:
            x = conv(self.num_filters, (7, 7), (2, 2), name="conv_init")(x)
            x = norm(name="bn_init")(x)
            x = self.act(x)
            x = nn.max_pool(x, (3, 3), strides=(2, 2), padding="SAME")

        for i, block_size in enumerate(self.stage_sizes):
            for j in range(block_size):
                strides = (2, 2) if i > 0 and j == 0 else (1, 1)
                x = self.block_cls(
                    self.num_filters * 2**i,
                    strides=strides,
                    conv=conv,
                    norm=norm,
                    act=self.act,
                )(x)
            x = constrain(x, ("dp", "fsdp"), None, None, None)

        x = jnp.mean(x, axis=(1, 2))
        x = nn.Dense(self.num_classes, dtype=jnp.float32, name="head")(x)
        return x.astype(jnp.float32)


ResNet18 = partial(ResNet, stage_sizes=(2, 2, 2, 2), block_cls=ResNetBlock)
ResNet34 = partial(ResNet, stage_sizes=(3, 4, 6, 3), block_cls=ResNetBlock)
ResNet50 = partial(ResNet, stage_sizes=(3, 4, 6, 3), block_cls=BottleneckResNetBlock)
ResNet101 = partial(ResNet, stage_sizes=(3, 4, 23, 3), block_cls=BottleneckResNetBlock)

#: Tiny variant for unit tests / CI (fast on the CPU backend).
ResNetTiny = partial(
    ResNet, stage_sizes=(1, 1), block_cls=ResNetBlock, num_filters=8, small_inputs=True
)
