"""Model registry: config model names -> Flax module instances."""

from __future__ import annotations

from typing import Any

import jax.numpy as jnp

from tpudl.models.bert import (
    BERT_BASE,
    BERT_LARGE,
    BERT_TINY,
    BertForSequenceClassification,
)
from tpudl.models.resnet import ResNet18, ResNet34, ResNet50, ResNet101

#: BertConfig factories by size name (tpudl.models.bert).
_BERT_SIZES = {
    "bert-tiny": BERT_TINY,
    "bert-base": BERT_BASE,
    "bert-large": BERT_LARGE,
}


def build_model(name: str, num_classes: int, **kwargs: Any):
    """Build the Flax module for a config `model` name (tpudl.config)."""
    dtype = kwargs.pop("dtype", jnp.bfloat16)
    cv = {
        "resnet18": ResNet18,
        "resnet34": ResNet34,
        "resnet50": ResNet50,
        "resnet101": ResNet101,
    }
    if name in cv:
        return cv[name](num_classes=num_classes, dtype=dtype, **kwargs)
    if name in _BERT_SIZES:
        cfg = _BERT_SIZES[name](num_labels=num_classes, dtype=dtype, **kwargs)
        return BertForSequenceClassification(cfg)
    if name.startswith("llama"):
        from tpudl.models.llama import build_llama

        return build_llama(name, num_classes=num_classes, dtype=dtype, **kwargs)
    raise ValueError(f"unknown model name: {name!r}")


def build_pipelined_model(
    name: str,
    num_classes: int,
    num_stages: int,
    num_microbatches: int,
    param_fsdp: bool = False,
    **kwargs: Any,
):
    """Config strategy='pp' / 'pp+fsdp' model path: a BERT size name as a
    PipelinedBertClassifier (tpudl.parallel.pipelined_bert) whose encoder
    stages train sharded over the pp mesh axis — and, with ``param_fsdp``,
    additionally 1/fsdp within each stage (ZeRO-in-pipeline)."""
    dtype = kwargs.pop("dtype", jnp.bfloat16)
    if name not in _BERT_SIZES:
        raise ValueError(
            f"strategy='pp' supports BERT sizes {sorted(_BERT_SIZES)}; "
            f"got {name!r}"
        )
    from tpudl.parallel.pipelined_bert import PipelinedBertClassifier

    cfg = _BERT_SIZES[name](num_labels=num_classes, dtype=dtype, **kwargs)
    return PipelinedBertClassifier(
        cfg, num_stages, num_microbatches, param_fsdp=param_fsdp
    )
