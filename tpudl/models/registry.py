"""Model registry: config model names -> Flax module instances."""

from __future__ import annotations

from typing import Any

import jax.numpy as jnp

from tpudl.models.resnet import ResNet18, ResNet34, ResNet50, ResNet101


def build_model(name: str, num_classes: int, **kwargs: Any):
    """Build the Flax module for a config `model` name (tpudl.config)."""
    dtype = kwargs.pop("dtype", jnp.bfloat16)
    cv = {
        "resnet18": ResNet18,
        "resnet34": ResNet34,
        "resnet50": ResNet50,
        "resnet101": ResNet101,
    }
    if name in cv:
        return cv[name](num_classes=num_classes, dtype=dtype, **kwargs)
    if name.startswith("bert") or name.startswith("llama"):
        raise NotImplementedError(
            f"model '{name}' is scheduled in SURVEY.md §7.3 (NLP family) "
            "and not built yet"
        )
    raise ValueError(f"unknown model name: {name!r}")
