"""LoRA: low-rank adapters with a frozen base (BASELINE.json configs[4]).

The reference lineage's stretch goal is a Llama LoRA fine-tune under
FSDP→GSPMD sharding (BASELINE.json configs[4]; nothing in the reference
tree implements it — SURVEY.md §0). TPU-native design:

- `LoRADense` keeps the full-rank kernel as an ordinary parameter and adds
  `lora_a` [in, r] / `lora_b` [r, out] with `b` zero-initialized, so the
  adapted layer starts exactly equal to the base layer.
- Freezing is an optimizer concern, not a model concern:
  `lora_optimizer(tx)` wraps any optax transformation with
  `optax.multi_transform` so only `lora_a`/`lora_b` (and explicitly listed
  heads) receive updates — base kernels keep zero updates and never get
  optimizer state moments (the memory win that makes 8B fit).
- Sharding composes: `LORA_RULES` prepends adapter specs to any rule list;
  `lora_a` shards its input dim over fsdp (like the base kernel),
  `lora_b` its output dim over tp (column-parallel, same as the base).
"""

from __future__ import annotations

from typing import Any, Callable, Iterable, Optional, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp
import optax
from jax.sharding import PartitionSpec as P

from tpudl.parallel.sharding import Rules


class LoRADense(nn.Module):
    """Dense layer with a low-rank adapter: y = x W + (alpha/r) (x A) B.

    Drop-in for nn.Dense (same param name "kernel"/"bias" for the base, so
    pretrained-weight import paths are unchanged; adapters are new leaves).
    """

    features: int
    rank: int
    alpha: float = 16.0
    use_bias: bool = True
    dtype: Any = jnp.bfloat16
    kernel_init: Callable = nn.initializers.lecun_normal()

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        in_features = x.shape[-1]
        kernel = self.param(
            "kernel", self.kernel_init, (in_features, self.features)
        )
        y = jnp.dot(x, kernel.astype(self.dtype))
        if self.rank > 0:
            lora_a = self.param(
                "lora_a",
                nn.initializers.normal(1.0 / self.rank),
                (in_features, self.rank),
            )
            lora_b = self.param(
                "lora_b", nn.initializers.zeros, (self.rank, self.features)
            )
            scaling = self.alpha / self.rank
            y = y + jnp.dot(
                jnp.dot(x, lora_a.astype(self.dtype)), lora_b.astype(self.dtype)
            ) * scaling
        if self.use_bias:
            bias = self.param("bias", nn.initializers.zeros, (self.features,))
            y = y + bias.astype(self.dtype)
        return y


#: Adapter sharding, composable by prepending to FSDP/TP rule lists:
#: A like the base kernel's row dim (fsdp), B column-parallel (tp).
LORA_RULES: Rules = (
    (r"lora_a$", P("fsdp", None)),
    (r"lora_b$", P(None, "tp")),
)


def compose_rules(*rule_lists: Rules) -> Rules:
    """First-match-wins concatenation (earlier lists take precedence)."""
    out: list = []
    for rules in rule_lists:
        out.extend(rules)
    return tuple(out)


def is_lora_param(path: str) -> bool:
    """Whether a '/'-joined parameter path is an adapter leaf."""
    return path.endswith("lora_a") or path.endswith("lora_b")


def _path_str(path) -> str:
    from tpudl.parallel.sharding import _path_str as ps

    return ps(path)


def lora_param_labels(
    params: Any, extra_trainable: Iterable[str] = ()
) -> Any:
    """'train'/'freeze' label tree for optax.multi_transform. Paths whose
    '/'-joined form contains any `extra_trainable` substring (e.g. a task
    head: "classifier") also train."""
    extra = tuple(extra_trainable)

    def label(path, _):
        p = _path_str(path)
        if is_lora_param(p) or any(e in p for e in extra):
            return "train"
        return "freeze"

    return jax.tree_util.tree_map_with_path(label, params)


def lora_optimizer(
    tx: optax.GradientTransformation,
    params: Any,
    extra_trainable: Iterable[str] = (),
) -> optax.GradientTransformation:
    """Wrap `tx` so only adapter (+ `extra_trainable`) leaves update; frozen
    leaves get set_to_zero, which also allocates no moments for them."""
    labels = lora_param_labels(params, extra_trainable)
    return optax.multi_transform(
        {"train": tx, "freeze": optax.set_to_zero()}, labels
    )


def trainable_param_count(
    params: Any, extra_trainable: Iterable[str] = ()
) -> Tuple[int, int]:
    """(trainable, total) parameter counts under the LoRA split."""
    labels = lora_param_labels(params, extra_trainable)
    trainable = total = 0
    for leaf, lab in zip(jax.tree.leaves(params), jax.tree.leaves(labels)):
        total += leaf.size
        if lab == "train":
            trainable += leaf.size
    return trainable, total


def merge_lora(params: Any, alpha_by_rank: Optional[float] = None) -> Any:
    """Fold adapters into base kernels (deploy-time: zero inference cost).

    Returns a new tree where each module containing (kernel, lora_a,
    lora_b) has kernel += (alpha/r) A B and the adapter leaves removed.
    """

    def merge(node):
        if not isinstance(node, dict):
            return node
        out = {k: merge(v) for k, v in node.items()}
        if "kernel" in out and "lora_a" in out and "lora_b" in out:
            a, b = out.pop("lora_a"), out.pop("lora_b")
            rank = a.shape[-1]
            scaling = (
                alpha_by_rank if alpha_by_rank is not None else 16.0 / rank
            )
            out["kernel"] = out["kernel"] + (a @ b) * scaling
        return out

    return merge(params)
