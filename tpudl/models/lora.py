"""LoRA: low-rank adapters with a frozen base (BASELINE.json configs[4]).

The reference lineage's stretch goal is a Llama LoRA fine-tune under
FSDP→GSPMD sharding (BASELINE.json configs[4]; nothing in the reference
tree implements it — SURVEY.md §0). TPU-native design:

- `LoRADense` keeps the full-rank kernel as an ordinary parameter and adds
  `lora_a` [in, r] / `lora_b` [r, out] with `b` zero-initialized, so the
  adapted layer starts exactly equal to the base layer.
- Freezing is an optimizer concern, not a model concern:
  `lora_optimizer(tx)` wraps any optax transformation with
  `optax.multi_transform` so only `lora_a`/`lora_b` (and explicitly listed
  heads) receive updates — base kernels keep zero updates and never get
  optimizer state moments (the memory win that makes 8B fit).
- Sharding composes: `LORA_RULES` prepends adapter specs to any rule list;
  `lora_a` shards its input dim over fsdp (like the base kernel),
  `lora_b` its output dim over tp (column-parallel, same as the base).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Dict, Iterable, Optional, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp
import optax
from jax.sharding import PartitionSpec as P

from tpudl.parallel.sharding import Rules


class LoRADense(nn.Module):
    """Dense layer with a low-rank adapter: y = x W + (alpha/r) (x A) B.

    Drop-in for nn.Dense (same param name "kernel"/"bias" for the base, so
    pretrained-weight import paths are unchanged; adapters are new leaves).

    The base kernel may arrive QUANTIZED (a tpudl.quant
    ``{"qvalues","qscale"}`` dict under the original "kernel" key — the
    composed ``weight_dtype`` + ``lora_rank`` config): the base matmul
    then runs the fused ``quant_dot`` contraction while the adapters
    stay full precision on top — the QLoRA-style serving shape. Init
    declares the same full-precision params either way, so param-tree
    structure never depends on what the tree later holds (the
    tpudl.quant.QuantDense dispatch-on-stored-value contract).
    """

    features: int
    rank: int
    alpha: float = 16.0
    use_bias: bool = True
    dtype: Any = jnp.bfloat16
    kernel_init: Callable = nn.initializers.lecun_normal()

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        from tpudl.quant.dense import quant_dot
        from tpudl.quant.quantize import is_quantized

        in_features = x.shape[-1]
        stored = (
            self.get_variable("params", "kernel")
            if self.has_variable("params", "kernel")
            else None
        )
        if is_quantized(stored):
            # Quantized base: flax would shape-validate the dict against
            # the initializer, so read it around self.param (the
            # QuantDense idiom); dequant fuses into the contraction.
            y = quant_dot(x, stored, compute_dtype=self.dtype)
        else:
            kernel = self.param(
                "kernel", self.kernel_init, (in_features, self.features)
            )
            y = jnp.dot(x, kernel.astype(self.dtype))
        if self.rank > 0:
            lora_a = self.param(
                "lora_a",
                nn.initializers.normal(1.0 / self.rank),
                (in_features, self.rank),
            )
            lora_b = self.param(
                "lora_b", nn.initializers.zeros, (self.rank, self.features)
            )
            scaling = self.alpha / self.rank
            y = y + jnp.dot(
                jnp.dot(x, lora_a.astype(self.dtype)), lora_b.astype(self.dtype)
            ) * scaling
        if self.use_bias:
            bias = self.param("bias", nn.initializers.zeros, (self.features,))
            y = y + bias.astype(self.dtype)
        return y


#: Adapter sharding, composable by prepending to FSDP/TP rule lists:
#: A like the base kernel's row dim (fsdp), B column-parallel (tp).
LORA_RULES: Rules = (
    (r"lora_a$", P("fsdp", None)),
    (r"lora_b$", P(None, "tp")),
)


def compose_rules(*rule_lists: Rules) -> Rules:
    """First-match-wins concatenation (earlier lists take precedence)."""
    out: list = []
    for rules in rule_lists:
        out.extend(rules)
    return tuple(out)


def is_lora_param(path: str) -> bool:
    """Whether a '/'-joined parameter path is an adapter leaf."""
    return path.endswith("lora_a") or path.endswith("lora_b")


def _path_str(path) -> str:
    from tpudl.parallel.sharding import _path_str as ps

    return ps(path)


def lora_param_labels(
    params: Any, extra_trainable: Iterable[str] = ()
) -> Any:
    """'train'/'freeze' label tree for optax.multi_transform. Paths whose
    '/'-joined form contains any `extra_trainable` substring (e.g. a task
    head: "classifier") also train."""
    extra = tuple(extra_trainable)

    def label(path, _):
        p = _path_str(path)
        if is_lora_param(p) or any(e in p for e in extra):
            return "train"
        return "freeze"

    return jax.tree_util.tree_map_with_path(label, params)


def lora_optimizer(
    tx: optax.GradientTransformation,
    params: Any,
    extra_trainable: Iterable[str] = (),
) -> optax.GradientTransformation:
    """Wrap `tx` so only adapter (+ `extra_trainable`) leaves update; frozen
    leaves get set_to_zero, which also allocates no moments for them."""
    labels = lora_param_labels(params, extra_trainable)
    return optax.multi_transform(
        {"train": tx, "freeze": optax.set_to_zero()}, labels
    )


def trainable_param_count(
    params: Any, extra_trainable: Iterable[str] = ()
) -> Tuple[int, int]:
    """(trainable, total) parameter counts under the LoRA split."""
    labels = lora_param_labels(params, extra_trainable)
    trainable = total = 0
    for leaf, lab in zip(jax.tree.leaves(params), jax.tree.leaves(labels)):
        total += leaf.size
        if lab == "train":
            trainable += leaf.size
    return trainable, total


def merge_lora(params: Any, alpha_by_rank: Optional[float] = None) -> Any:
    """Fold adapters into base kernels (deploy-time: zero inference cost).

    Returns a new tree where each module containing (kernel, lora_a,
    lora_b) has kernel += (alpha/r) A B and the adapter leaves removed.
    """

    def merge(node):
        if not isinstance(node, dict):
            return node
        out = {k: merge(v) for k, v in node.items()}
        if "kernel" in out and "lora_a" in out and "lora_b" in out:
            a, b = out.pop("lora_a"), out.pop("lora_b")
            rank = a.shape[-1]
            scaling = (
                alpha_by_rank if alpha_by_rank is not None else 16.0 / rank
            )
            out["kernel"] = out["kernel"] + (a @ b) * scaling
        return out

    return merge(params)


# ---------------------------------------------------------------------------
# Multi-tenant adapter serving (tpudl.serve.lora's model-side half).
#
# A single-tenant LoRADense bakes ONE adapter into the module; serving
# thousands of tenants off one resident base model instead threads an
# AdapterView through the decode path: per-slot page-table rows into
# the tpudl.serve.lora.AdapterPool's rank-unit pools, applied AFTER
# each base projection by tpudl.ops.segmented_lora (so the base may be
# nn.Dense OR QuantDense — quantized base and per-tenant adapters
# compose by construction).
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class AdapterView:
    """Per-dispatch multi-tenant adapter addressing.

    ``pools`` is the AdapterPool's pytree — ``{layer_name: {site:
    {"a","b"[,"a_scale","b_scale"]}}}`` of traced pool arrays; ``table``
    ([B, r_max] int32) maps each slot's logical rank units to physical
    pages (0 = the never-written all-zero page, so empty slots and
    short ranks contribute nothing); ``scale`` ([B] f32) is each slot's
    alpha/rank. ``impl`` is the tpudl.ops dispatch seam for the
    segmented kernel and is STATIC (baked into the compiled program);
    the arrays are traced inputs, so loading/evicting adapters between
    dispatches never recompiles."""

    pools: Any
    table: jax.Array
    scale: jax.Array
    impl: str = "auto"

    def for_layer(self, name: str) -> Optional["AdapterView"]:
        """The sub-view a single decoder block consumes (its sites
        keyed "q_proj"/"gate_proj"/...); None when no tenant adapts
        this layer."""
        pools = self.pools.get(name)
        if pools is None:
            return None
        return dataclasses.replace(self, pools=pools)


def adapter_delta(view: Optional[AdapterView], site: str, x) -> Any:
    """The multi-tenant LoRA delta for one projection site (0 when the
    view or the site's pools are absent) — callers add it onto the base
    projection output: ``y = proj(x) + adapter_delta(view, name, x)``."""
    if view is None:
        return 0
    pools = view.pools.get(site)
    if pools is None:
        return 0
    from tpudl.ops.segmented_lora import segmented_lora

    return segmented_lora(
        x, pools, view.table, view.scale, impl=view.impl
    )


def extract_adapters(params: Any) -> Dict[str, dict]:
    """Flatten a LoRA param tree's adapters into ``{site_path:
    {"lora_a": [in, r], "lora_b": [r, out]}}`` (site_path =
    '/'-joined module path, e.g. ``model/layer_0/attention/q_proj``) —
    the per-tenant unit tpudl.serve.lora.AdapterPool registers. The
    base kernels are left behind: one resident base tree serves every
    tenant."""
    out: Dict[str, dict] = {}

    def walk(node, prefix):
        if not isinstance(node, dict):
            return
        if "lora_a" in node and "lora_b" in node:
            out[prefix] = {
                "lora_a": node["lora_a"], "lora_b": node["lora_b"]
            }
        for key, value in node.items():
            walk(value, f"{prefix}/{key}" if prefix else key)

    walk(params, "")
    return out


def as_flat_adapters(tree: Any) -> Dict[str, dict]:
    """Normalize an adapter argument to the ``extract_adapters`` flat
    form: an already-flat ``{site_path: {"lora_a", "lora_b"}}`` dict
    passes through; anything else is treated as a full LoRA param tree
    and extracted. THE one detection rule — AdapterPool.register, the
    serving entry's rank probe, and the parity gate all normalize
    through here, so the flat-form contract cannot drift between
    doors."""
    if tree and all(
        isinstance(v, dict) and {"lora_a", "lora_b"} <= set(v)
        for v in tree.values()
    ):
        return dict(tree)
    return extract_adapters(tree)


def strip_adapters(params: Any) -> Any:
    """The base tree without adapter leaves (the resident-once half of
    the split; ``extract_adapters`` is the per-tenant half)."""

    def walk(node):
        if not isinstance(node, dict):
            return node
        return {
            k: walk(v)
            for k, v in node.items()
            if k not in ("lora_a", "lora_b")
        }

    return walk(params)


def merge_adapter(
    base_params: Any, adapter: Dict[str, dict], alpha: float = 16.0
) -> Any:
    """Fold ONE tenant's extracted adapter into a copy of the base tree
    (kernel += (alpha/r) A B at every adapted site) — the sequential
    one-adapter-at-a-time reference the multi-tenant parity gate
    compares against. Full-precision kernels only: parity references
    are served unquantized."""
    from tpudl.quant.quantize import is_quantized

    merged = jax.tree.map(lambda x: x, base_params)
    for path, factors in adapter.items():
        node = merged
        parts = path.split("/")
        for part in parts[:-1]:
            node = node[part]
        site = node[parts[-1]]
        if "kernel" not in site:
            raise ValueError(f"no kernel at adapter site {path!r}")
        if is_quantized(site["kernel"]):
            raise ValueError(
                f"cannot merge an adapter into the quantized kernel at "
                f"{path!r} — merge into the full-precision tree"
            )
        a = jnp.asarray(factors["lora_a"], jnp.float32)
        b = jnp.asarray(factors["lora_b"], jnp.float32)
        rank = a.shape[-1]
        site["kernel"] = (
            site["kernel"]
            + ((a @ b) * (alpha / rank)).astype(site["kernel"].dtype)
        )
    return merged
