"""Llama-family decoder (RoPE + RMSNorm + SwiGLU + GQA), TPU-native.

The reference's stretch workload is a Llama-3-8B LoRA fine-tune
(BASELINE.json configs[4]; the reference tree ships no decoder at all —
SURVEY.md §0). First-party implementation, same design rules as
tpudl.models.bert: bf16 compute / f32 params, f32 norms and softmax,
attention through the tpudl.ops.attend seam (reference / Pallas flash /
ring over `sp` — causal masking never materializes [S, S]), activation
sharding constraints on the (dp, fsdp) x sp x tp mesh, optional per-layer
remat. LoRA drops in via cfg.lora_rank>0, swapping the attention
projections to tpudl.models.lora.LoRADense (frozen-base training is the
optimizer's job — see lora.lora_optimizer).
"""

from __future__ import annotations

import dataclasses
import re
from functools import partial
from typing import Any, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp

from tpudl.models.lora import LoRADense
from tpudl.ops.attention import attend
from tpudl.parallel.sharding import constrain


@dataclasses.dataclass(frozen=True)
class LlamaConfig:
    vocab_size: int = 128_256
    hidden_size: int = 4096
    num_layers: int = 32
    num_heads: int = 32
    num_kv_heads: int = 8
    intermediate_size: int = 14_336
    max_seq_len: int = 8192
    rope_theta: float = 500_000.0
    rms_norm_eps: float = 1e-5
    num_labels: int = 2
    dtype: Any = jnp.bfloat16
    attention_impl: str = "reference"
    remat: bool = False
    lora_rank: int = 0
    lora_alpha: float = 16.0

    def __post_init__(self):
        if self.lora_rank < 0:
            raise ValueError(
                f"lora_rank must be >= 0 (0 = adapters off), got "
                f"{self.lora_rank}"
            )
    # Fused-epilogue kernel tier (tpudl.ops.norms / mlp_fused): False
    # (default) = composite RMSNorm/SwiGLU, bit-identical to before the
    # tier; True = Pallas fused RMSNorm(+residual) and SwiGLU on TPU,
    # composite off-TPU; "force" = Pallas everywhere (interpret mode
    # off-TPU — the CPU parity-test mode). Same param tree either way.
    # These ops run per serve decode step, so the fused path cuts decode
    # TPOT alongside training step time.
    fused_ops: Any = False
    # Low-precision weight tier (tpudl.quant): None (default) = plain
    # nn.Dense projections, bit-identical to before the tier; "int8" /
    # "fp8_e4m3" = attention+MLP projections become QuantDense, which
    # serves the quantize_tree output (kernels carried as
    # (qvalues, qscale) pairs, dequant fused into the contraction) and
    # runs full-precision kernels through the exact nn.Dense math —
    # same param-tree structure either way, so checkpoints round-trip.
    # Norms/embeddings/lm_head always stay full precision. Serving
    # entry: ServeSession.from_model(weight_dtype=...).
    weight_dtype: Optional[str] = None
    # fp8 TRAINING tier (tpudl.ops.fp8_dot + the tpudl.train.precision
    # "fp8" policy): True routes the SAME rule-class projection sites
    # the quantizer addresses (LLAMA_QUANT_PATTERNS — the seven
    # per-block projections) through Fp8Dense (e4m3 fwd / e5m2 grad,
    # delayed scaling; f32 master params, nn.Dense-identical tree).
    # "force"/"fused"/"reference" pin the fp8_dot impl seam. Mutually
    # exclusive with weight_dtype; COMPOSES with lora_rank (fp8 base
    # matmul + full-precision rank-r adapters — the flywheel refresh's
    # cheapest training cell).
    fp8_train: Any = False
    # MoE (tpudl.ops.moe): >0 swaps the dense SwiGLU MLP for an
    # expert-parallel gated MoE in every block.
    moe_experts: int = 0
    moe_k: int = 2
    moe_capacity_factor: float = 1.25

    @property
    def head_dim(self) -> int:
        return self.hidden_size // self.num_heads


LLAMA_TINY = partial(
    LlamaConfig,
    vocab_size=512,
    hidden_size=128,
    num_layers=2,
    num_heads=4,
    num_kv_heads=2,
    intermediate_size=256,
    max_seq_len=256,
    rope_theta=10_000.0,
)
LLAMA3_8B = LlamaConfig
#: Llama-3.2-1B shape — the largest decoder a single 16G chip serves
#: comfortably in bf16.
LLAMA3_1B = partial(
    LlamaConfig,
    hidden_size=2048,
    num_layers=16,
    num_heads=32,
    num_kv_heads=8,
    intermediate_size=8192,
)

#: Size-name registry for tpudl.models.registry.build_llama.
LLAMA_SIZES = {
    "llama-tiny": LLAMA_TINY,
    "llama3-1b": LLAMA3_1B,
    "llama3-8b": LLAMA3_8B,
}


def _proj(cfg: LlamaConfig, features: int, name: str):
    """Attention/MLP projection: plain Dense, LoRADense when adapters
    are on (cfg.lora_rank > 0), or QuantDense when the low-precision
    weight seam is set (cfg.weight_dtype — serving only; the quantized
    sites are exactly the leaves tpudl.quant's LLAMA_QUANT_PATTERNS
    match). The two COMPOSE: weight_dtype + lora_rank > 0 runs a
    LoRADense over a quantized base kernel (the base matmul dispatches
    on what the tree holds, exactly like QuantDense) with the adapters
    full precision on top — the QLoRA-style quantized-base fine-tune
    shape. Adapter leaves fall under the quantizer's keep-all rule, so
    quantize_model on a LoRA tree quantizes only the base kernels.
    ``fp8_train`` (training-time fp8 matmuls, tpudl.ops.fp8_dot) swaps
    the same sites to Fp8Dense instead — exclusive with serving
    quantization, but it COMPOSES with ``lora_rank``: Fp8Dense carries
    the same ``lora_a``/``lora_b`` leaves as LoRADense (full-precision
    delta over the fp8 base product), so the frozen-base optimizer and
    adapter extraction seams see an identical tree shape."""
    if cfg.fp8_train:
        if cfg.weight_dtype is not None:
            raise ValueError(
                "fp8_train (training-time fp8 matmuls) does not compose "
                "with weight_dtype (frozen-tree serving quantization) "
                "— pick one"
            )
        from tpudl.ops.fp8_dot import Fp8Dense

        impl = cfg.fp8_train if isinstance(cfg.fp8_train, str) else "auto"
        if impl == "force":
            impl = "fused"
        return Fp8Dense(
            features,
            use_bias=False,
            dtype=cfg.dtype,
            kernel_init=nn.initializers.normal(0.02),
            impl=impl,
            rank=cfg.lora_rank,
            alpha=cfg.lora_alpha,
            name=name,
        )
    if cfg.weight_dtype is not None and cfg.lora_rank == 0:
        from tpudl.quant.dense import QuantDense

        return QuantDense(
            features,
            use_bias=False,
            dtype=cfg.dtype,
            kernel_init=nn.initializers.normal(0.02),
            name=name,
        )
    if cfg.lora_rank > 0:
        return LoRADense(
            features,
            rank=cfg.lora_rank,
            alpha=cfg.lora_alpha,
            use_bias=False,
            dtype=cfg.dtype,
            kernel_init=nn.initializers.normal(0.02),
            name=name,
        )
    return nn.Dense(
        features,
        use_bias=False,
        dtype=cfg.dtype,
        kernel_init=nn.initializers.normal(0.02),
        name=name,
    )


class RMSNorm(nn.Module):
    """RMS normalization through the tpudl.ops.norms seam. The default
    ``impl="reference"`` is the original composite math verbatim
    (rms_norm_ref); ``impl="auto"/"fused"`` routes to the Pallas fused
    kernel, which also takes the residual add (``residual=`` returns
    ``(normed, x + residual)`` — the pre-norm block's carried sum) in
    the same activation pass."""

    eps: float = 1e-5
    impl: str = "reference"

    @nn.compact
    def __call__(self, x, residual=None):
        from tpudl.ops.norms import rms_norm

        scale = self.param("scale", nn.initializers.ones, (x.shape[-1],))
        return rms_norm(
            x, scale, residual, eps=self.eps, impl=self.impl
        )


def rope(x: jax.Array, positions: jax.Array, theta: float) -> jax.Array:
    """Rotary embedding on [B, S, H, D] (rotate-half convention)."""
    d = x.shape[-1]
    inv_freq = 1.0 / (
        theta ** (jnp.arange(0, d, 2, dtype=jnp.float32) / d)
    )  # [d/2]
    angles = positions[:, :, None].astype(jnp.float32) * inv_freq  # [B,S,d/2]
    cos = jnp.cos(angles)[:, :, None, :]  # [B,S,1,d/2]
    sin = jnp.sin(angles)[:, :, None, :]
    x1, x2 = x[..., : d // 2], x[..., d // 2:]
    x32_1, x32_2 = x1.astype(jnp.float32), x2.astype(jnp.float32)
    out = jnp.concatenate(
        [x32_1 * cos - x32_2 * sin, x32_2 * cos + x32_1 * sin], axis=-1
    )
    return out.astype(x.dtype)


def _gqa_decode_attention(q, k, v, mask):
    """Decode-path attention with query heads grouped over shared KV
    heads. q: [B, S, H, D]; k, v: [B, T, Hkv, D]; mask: [B, 1, S, T]
    (True = attend). f32 logits/softmax like
    tpudl.ops.attention.dot_product_attention."""
    from tpudl.ops.attention import MASK_VALUE

    b, s, h, d = q.shape
    hkv = k.shape[2]
    g = h // hkv
    qg = q.reshape(b, s, hkv, g, d)
    logits = jnp.einsum("bqhgd,bkhd->bhgqk", qg, k) * (d ** -0.5)
    logits = logits.astype(jnp.float32)
    logits = jnp.where(mask[:, :, None, :, :], logits, MASK_VALUE)
    weights = jax.nn.softmax(logits, axis=-1).astype(v.dtype)
    ctx = jnp.einsum("bhgqk,bkhd->bqhgd", weights, v)
    return ctx.reshape(b, s, h, d)


def _paged_cache_missing():
    raise ValueError(
        "paged decode requires a provided 'cache' collection (the page "
        "pools tpudl.serve.cache.PagedKVCache builds) — there is no "
        "shape information to initialize one here"
    )


class LlamaAttention(nn.Module):
    cfg: LlamaConfig

    @nn.compact
    def __call__(
        self, hidden, positions, kv_mask=None, decode: bool = False,
        paged=None, adapters=None,
    ):
        from tpudl.models.lora import adapter_delta

        cfg = self.cfg
        B, S, _ = hidden.shape
        hd = cfg.head_dim
        # Multi-tenant adapters (tpudl.models.lora.AdapterView): each
        # slot's per-tenant LoRA delta rides AFTER the shared base
        # projection — one segmented-kernel dispatch per site, base
        # weights (full-precision or quantized) resident exactly once.
        q = _proj(cfg, cfg.num_heads * hd, "q_proj")(hidden)
        q = q + adapter_delta(adapters, "q_proj", hidden)
        k = _proj(cfg, cfg.num_kv_heads * hd, "k_proj")(hidden)
        k = k + adapter_delta(adapters, "k_proj", hidden)
        v = _proj(cfg, cfg.num_kv_heads * hd, "v_proj")(hidden)
        v = v + adapter_delta(adapters, "v_proj", hidden)
        q = q.reshape(B, S, cfg.num_heads, hd)
        k = k.reshape(B, S, cfg.num_kv_heads, hd)
        v = v.reshape(B, S, cfg.num_kv_heads, hd)
        q = rope(q, positions, cfg.rope_theta)
        k = rope(k, positions, cfg.rope_theta)

        if decode and paged is not None:
            # Paged decode (tpudl.models.paged): KV lives in page pools
            # addressed by the host-provided page table instead of the
            # dense [B, max_seq] rows below — each slot has its OWN
            # length (no shared write index, so no horizon rollover)
            # and pools may store int8 with per-(page, row, head)
            # dequant scales fused into the gather. Token chunks of any
            # length step together (S=1 is the plain decode step; S=k
            # is the speculative-verify window, causal within itself
            # via the chunked mask); prefill stays dense batch-1 (its
            # row cache is scattered into pages by PagedKVCache.seat).
            from tpudl.models.paged import (
                paged_attend_mask,
                paged_gather,
                paged_write,
            )

            pk = self.variable("cache", "pages_k", _paged_cache_missing)
            pv = self.variable("cache", "pages_v", _paged_cache_missing)
            sk = sv = None
            if paged.quantized:
                sk = self.variable("cache", "scale_k", _paged_cache_missing)
                sv = self.variable("cache", "scale_v", _paged_cache_missing)
            new_k, new_sk = paged_write(
                pk.value, sk.value if sk is not None else None, k, paged
            )
            new_v, new_sv = paged_write(
                pv.value, sv.value if sv is not None else None, v, paged
            )
            pk.value, pv.value = new_k, new_v
            if paged.quantized:
                sk.value, sv.value = new_sk, new_sv
            kf = paged_gather(
                pk.value, sk.value if sk is not None else None, paged, k.dtype
            )
            vf = paged_gather(
                pv.value, sv.value if sv is not None else None, paged, v.dtype
            )
            ctx = _gqa_decode_attention(
                q, kf, vf, paged_attend_mask(paged, chunk=S)
            )
            ctx = ctx.reshape(B, S, cfg.num_heads * hd)
            out = _proj(cfg, cfg.hidden_size, "o_proj")(ctx)
            return out + adapter_delta(adapters, "o_proj", ctx)

        if decode:
            # KV cache (flax decode idiom): static [B, max_seq, Hkv, D]
            # buffers updated in place at the current index — the
            # autoregressive serving path (the reference repo's entire
            # substance is inference benchmarking; this is its decoder
            # analog). Shapes stay static so the step jits once.
            ck = self.variable(
                "cache", "k",
                jnp.zeros, (B, cfg.max_seq_len, cfg.num_kv_heads, hd), k.dtype,
            )
            cv = self.variable(
                "cache", "v",
                jnp.zeros, (B, cfg.max_seq_len, cfg.num_kv_heads, hd), v.dtype,
            )
            # Per-slot validity: padded prompt slots hold garbage k/v and
            # must never be attended. Written alongside k/v from the
            # chunk's kv_mask, so the cache knows which of its slots are
            # real — the contract that lets generate() serve ragged
            # (left-padded) prompt batches.
            cvalid = self.variable(
                "cache", "valid",
                jnp.zeros, (B, cfg.max_seq_len), jnp.bool_,
            )
            idx = self.variable(
                "cache", "index", lambda: jnp.zeros((), jnp.int32)
            )
            start = idx.value
            ck.value = jax.lax.dynamic_update_slice(
                ck.value, k, (0, start, 0, 0)
            )
            cv.value = jax.lax.dynamic_update_slice(
                cv.value, v, (0, start, 0, 0)
            )
            chunk_valid = (
                jnp.ones((B, S), jnp.bool_)
                if kv_mask is None
                else kv_mask.astype(jnp.bool_)
            )
            cvalid.value = jax.lax.dynamic_update_slice(
                cvalid.value, chunk_valid, (0, start)
            )
            idx.value = start + S
            k, v = ck.value, cv.value
            # Attend to slots that are (a) causally prior in WRITE order —
            # slots fill in token order, so slot order IS causal order
            # regardless of padding — and (b) valid. Positions (which pads
            # alias) play no role in masking; they only drive RoPE phases.
            kv_slot = jnp.arange(cfg.max_seq_len)[None, None, None, :]
            q_slot = (start + jnp.arange(S))[None, None, :, None]
            mask = (kv_slot <= q_slot) & cvalid.value[:, None, None, :]
        else:
            mask = None

        if decode:
            # Grouped-query attention against the UNEXPANDED cache — never
            # materialize [B, max_seq, H, D] (the 4x KV blowup per decode
            # step that GQA exists to avoid).
            ctx = _gqa_decode_attention(q, k, v, mask)
            ctx = ctx.reshape(B, S, cfg.num_heads * hd)
            out = _proj(cfg, cfg.hidden_size, "o_proj")(ctx)
            return out + adapter_delta(adapters, "o_proj", ctx)

        if cfg.num_kv_heads != cfg.num_heads:  # GQA: expand kv heads
            reps = cfg.num_heads // cfg.num_kv_heads
            k = jnp.repeat(k, reps, axis=2)
            v = jnp.repeat(v, reps, axis=2)
        q = constrain(q, ("dp", "fsdp"), "sp", "tp", None)
        k = constrain(k, ("dp", "fsdp"), "sp", "tp", None)
        v = constrain(v, ("dp", "fsdp"), "sp", "tp", None)
        # kv_mask ([B, S] validity row) masks padding alongside the causal
        # triangle — without it a LEFT-padded batch would attend to pad
        # garbage (causality only happens to hide trailing pads). All four
        # attention implementations accept the [B, S] row contract.
        ctx = attend(
            q, k, v, mask=kv_mask, causal=True,
            implementation=cfg.attention_impl,
        )
        ctx = ctx.reshape(B, S, cfg.num_heads * hd)
        out = _proj(cfg, cfg.hidden_size, "o_proj")(ctx)
        return out + adapter_delta(adapters, "o_proj", ctx)


class LlamaBlock(nn.Module):
    cfg: LlamaConfig

    @nn.compact
    def __call__(
        self, hidden, positions, kv_mask=None, decode: bool = False,
        paged=None, adapters=None,
    ):
        from tpudl.models.lora import adapter_delta

        cfg = self.cfg
        from tpudl.ops.norms import fused_ops_impl

        impl = fused_ops_impl(cfg.fused_ops)
        attn = LlamaAttention(cfg, name="attention")(
            RMSNorm(cfg.rms_norm_eps, impl, name="input_norm")(hidden),
            positions,
            kv_mask,
            decode,
            paged,
            adapters,
        )
        # The attention residual add rides inside the post-attention
        # norm kernel; the summed value comes back as the carried
        # residual (one activation pass instead of add + norm).
        x, hidden = RMSNorm(
            cfg.rms_norm_eps, impl, name="post_attention_norm"
        )(attn, residual=hidden)
        if cfg.moe_experts > 0:
            from tpudl.ops.moe import MoEMlp

            down = MoEMlp(
                num_experts=cfg.moe_experts,
                intermediate_size=cfg.intermediate_size,
                k=cfg.moe_k,
                capacity_factor=cfg.moe_capacity_factor,
                gated=True,
                act=nn.silu,
                dtype=cfg.dtype,
                name="moe",
            )(x)
        else:
            from tpudl.ops.mlp_fused import swiglu

            gate = _proj(cfg, cfg.intermediate_size, "gate_proj")(x)
            gate = gate + adapter_delta(adapters, "gate_proj", x)
            up = _proj(cfg, cfg.intermediate_size, "up_proj")(x)
            up = up + adapter_delta(adapters, "up_proj", x)
            act = swiglu(gate, up, impl=impl)
            down = _proj(cfg, cfg.hidden_size, "down_proj")(act)
            down = down + adapter_delta(adapters, "down_proj", act)
        hidden = hidden + down
        return constrain(hidden, ("dp", "fsdp"), "sp", "tp")


class LlamaModel(nn.Module):
    """Decoder stack: embeddings + N blocks + final RMSNorm."""

    cfg: LlamaConfig

    @nn.compact
    def __call__(
        self, input_ids, attention_mask=None, decode=False, positions=None,
        paged=None, adapters=None,
    ):
        cfg = self.cfg
        # kv_mask=None keeps the unpadded fast path (no in-kernel validity
        # masking); any explicit attention_mask is enforced in attention.
        kv_mask = attention_mask
        if attention_mask is None:
            attention_mask = jnp.ones_like(input_ids)
        if positions is None:
            # Positions skip padding so RoPE phases match left-padded
            # batches. Decode callers pass absolute positions explicitly
            # (tpudl.models.generate tracks the cache offset).
            positions = jnp.maximum(
                jnp.cumsum(attention_mask, axis=-1) - 1, 0
            ).astype(jnp.int32)
        x = nn.Embed(
            cfg.vocab_size,
            cfg.hidden_size,
            embedding_init=nn.initializers.normal(0.02),
            name="embed_tokens",
        )(input_ids).astype(cfg.dtype)
        x = constrain(x, ("dp", "fsdp"), "sp", "tp")
        block = LlamaBlock
        if cfg.remat and not decode:
            # adapters never reach the remat path: multi-tenant views
            # are decode-only (serving), and decode skips remat.
            block = nn.remat(LlamaBlock, static_argnums=(4, 5))
        for i in range(cfg.num_layers):
            x = block(cfg, name=f"layer_{i}")(
                x, positions, kv_mask, decode, paged,
                adapters.for_layer(f"layer_{i}")
                if adapters is not None
                else None,
            )
        from tpudl.ops.norms import fused_ops_impl

        return RMSNorm(
            cfg.rms_norm_eps, fused_ops_impl(cfg.fused_ops),
            name="final_norm"
        )(x)


class LlamaForCausalLM(nn.Module):
    cfg: LlamaConfig

    @nn.compact
    def __call__(
        self, input_ids, attention_mask=None, decode=False, positions=None,
        paged=None, adapters=None,
    ):
        x = LlamaModel(self.cfg, name="model")(
            input_ids, attention_mask, decode, positions, paged, adapters
        )
        logits = nn.Dense(
            self.cfg.vocab_size,
            use_bias=False,
            dtype=jnp.float32,
            kernel_init=nn.initializers.normal(0.02),
            name="lm_head",
        )(x)
        return logits.astype(jnp.float32)


class LlamaForSequenceClassification(nn.Module):
    """configs[4]-style fine-tune head: classify from the last non-padding
    token's hidden state (causal LM pooling)."""

    cfg: LlamaConfig

    @nn.compact
    def __call__(self, input_ids, attention_mask=None, train: bool = False):
        if attention_mask is None:
            attention_mask = jnp.ones_like(input_ids)
        x = LlamaModel(self.cfg, name="model")(input_ids, attention_mask)
        last = jnp.maximum(jnp.sum(attention_mask, axis=-1) - 1, 0)
        pooled = jnp.take_along_axis(
            x, last[:, None, None].astype(jnp.int32), axis=1
        )[:, 0]
        logits = nn.Dense(
            self.cfg.num_labels,
            dtype=jnp.float32,
            kernel_init=nn.initializers.normal(0.02),
            name="classifier",
        )(pooled)
        return logits.astype(jnp.float32)


# ---------------------------------------------------------------------------
# HuggingFace weight import (torch state_dict -> tpudl param tree).
#
# The reference's first act is loading pretrained weights
# (reference notebooks/cv/onnx_experiments.py:19, resnet50(pretrained=True))
# and BASELINE.json configs[4] is a *pretrained* Llama LoRA fine-tune —
# random-init fine-tuning is not the workload. Same recipe as
# tpudl.models.bert.params_from_hf_bert: regex map, transpose Linear
# kernels, keep norms/embeddings as-is.
# ---------------------------------------------------------------------------

#: HF name pattern -> tpudl path template; bool = transpose ([out,in] ->
#: [in,out]). Conventions verified against this module: rotate-half RoPE,
#: consecutive-group GQA (q head h uses kv head h // (H/Hkv)), silu-gated
#: MLP, f32 RMSNorm — all match HF's modeling_llama semantics, so the map
#: is pure renaming + kernel transposes.
_HF_LLAMA_MAP = [
    (r"^model\.embed_tokens\.weight$", "model/embed_tokens/embedding", False),
    (r"^model\.layers\.(\d+)\.self_attn\.(q|k|v|o)_proj\.weight$",
     "model/layer_{0}/attention/{1}_proj/kernel", True),
    (r"^model\.layers\.(\d+)\.mlp\.(gate|up|down)_proj\.weight$",
     "model/layer_{0}/{1}_proj/kernel", True),
    (r"^model\.layers\.(\d+)\.input_layernorm\.weight$",
     "model/layer_{0}/input_norm/scale", False),
    (r"^model\.layers\.(\d+)\.post_attention_layernorm\.weight$",
     "model/layer_{0}/post_attention_norm/scale", False),
    (r"^model\.norm\.weight$", "model/final_norm/scale", False),
    (r"^lm_head\.weight$", "lm_head/kernel", True),
    # HF LlamaForSequenceClassification names its head `score`.
    (r"^score\.weight$", "classifier/kernel", True),
    (r"^score\.bias$", "classifier/bias", False),
]


def _tensor_to_numpy(value):
    """torch tensor (any dtype, incl. bfloat16 — the dtype pretrained
    Llama checkpoints ship in, which Tensor.numpy() refuses) or array-like
    -> numpy array."""
    import numpy as _np

    if hasattr(value, "detach"):  # torch tensor
        value = value.detach()
        try:
            return value.numpy()
        except TypeError:  # bf16/f8: upcast through f32
            return value.float().numpy()
    return _np.asarray(value)


def params_from_hf_llama(state_dict, like=None):
    """Convert a HF Llama state_dict (LlamaForCausalLM or
    LlamaForSequenceClassification; torch tensors or numpy arrays) to a
    tpudl param tree.

    With ``like`` (a template tree from ``model.init``), mapped leaves are
    grafted into a copy of it — unmapped template leaves (e.g. LoRA
    adapters, a fresh classifier head) keep their initialized values, and
    every graft is shape-checked. Tied-embedding checkpoints (no
    ``lm_head.weight``) fall back to the transposed token embedding when
    the template wants an ``lm_head``.
    """
    converted: dict = {}
    unmapped = []
    for hf_name, value in state_dict.items():
        arr = _tensor_to_numpy(value)
        for pattern, template, transpose in _HF_LLAMA_MAP:
            m = re.match(pattern, hf_name)
            if m:
                converted[template.format(*m.groups())] = (
                    arr.T if transpose else arr
                )
                break
        else:
            if not (
                "rotary_emb" in hf_name or hf_name.endswith("position_ids")
            ):
                unmapped.append(hf_name)
    if unmapped:
        raise ValueError(f"unmapped HF parameters: {unmapped}")
    if (
        "lm_head/kernel" not in converted
        and "model/embed_tokens/embedding" in converted
    ):
        # tie_word_embeddings: the output head shares the embedding.
        converted["lm_head/kernel"] = converted[
            "model/embed_tokens/embedding"
        ].T

    if like is None:
        tree: dict = {}
        for path, arr in converted.items():
            node = tree
            parts = path.split("/")
            for p in parts[:-1]:
                node = node.setdefault(p, {})
            node[parts[-1]] = jnp.asarray(arr)
        return tree

    tree = jax.tree.map(lambda x: x, like)  # shallow-copied structure
    used = set()

    def _graft(node, prefix):
        out = {}
        for name, leaf in node.items():
            path = f"{prefix}/{name}" if prefix else name
            if isinstance(leaf, dict):
                out[name] = _graft(leaf, path)
            elif path in converted:
                arr = converted[path]
                if tuple(arr.shape) != tuple(jnp.shape(leaf)):
                    raise ValueError(
                        f"shape mismatch at {path}: HF {arr.shape} vs "
                        f"model {jnp.shape(leaf)}"
                    )
                used.add(path)
                out[name] = jnp.asarray(arr, dtype=leaf.dtype)
            else:
                out[name] = leaf  # keep init (LoRA adapters, fresh heads)
        return out

    tree = _graft(dict(tree), "")
    unused = set(converted) - used - {"lm_head/kernel", "classifier/kernel",
                                      "classifier/bias"}
    if unused:
        raise ValueError(
            f"HF parameters with no destination in the template: "
            f"{sorted(unused)}"
        )
    return tree


def build_llama(name: str, num_classes: int, dtype=jnp.bfloat16, **kwargs):
    """Registry entry: 'llama-tiny' / 'llama3-8b', with composable
    suffixes: '-lora' enables rank-16 adapters (override via lora_rank=),
    '-moe' swaps every MLP for an 8-expert MoE (override via
    moe_experts=)."""
    base = name
    lora = moe = False
    while True:
        if base.endswith("-lora"):
            base, lora = base.removesuffix("-lora"), True
        elif base.endswith("-moe"):
            base, moe = base.removesuffix("-moe"), True
        else:
            break
    if base not in LLAMA_SIZES:
        raise ValueError(
            f"unknown llama size {base!r}; available: {sorted(LLAMA_SIZES)}"
        )
    if lora:
        kwargs.setdefault("lora_rank", 16)
    if moe:
        kwargs.setdefault("moe_experts", 8)
    cfg = LLAMA_SIZES[base](num_labels=num_classes, dtype=dtype, **kwargs)
    return LlamaForSequenceClassification(cfg)
