"""Flax BERT: encoder, pooler, classification head, HF weight import.

The reference declares an NLP workload family but ships nothing in it
(reference notebooks/nlp/README.md is empty — SURVEY.md §0); the concrete
workloads come from BASELINE.json: BERT-base SST-2 fine-tune (configs[1]),
BERT-large multi-host (configs[3]). This is a first-party TPU-native
implementation, not a port of HF's torch modeling code:

- bf16 compute / f32 params, f32 softmax and LayerNorm;
- attention flows through tpudl.ops.attend so flash/ring kernels and
  sequence parallelism drop in without model changes;
- activation sharding constraints on the (dp,fsdp) x sp x tp mesh axes at
  block boundaries;
- optional per-layer rematerialization (jax.checkpoint) to trade FLOPs for
  HBM on long sequences;
- `params_from_hf_bert` maps a HuggingFace torch state_dict onto the
  parameter tree (transpose Linear kernels, rename LayerNorm), so HF
  checkpoints fine-tune here directly — SURVEY.md §7.4 hard part #3.
"""

from __future__ import annotations

import dataclasses
import re
from functools import partial
from typing import Any, Dict, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np

from tpudl.ops.attention import attend, padding_mask
from tpudl.ops.dropout import Dropout
from tpudl.parallel.sharding import constrain


@dataclasses.dataclass(frozen=True)
class BertConfig:
    vocab_size: int = 30522
    hidden_size: int = 768
    num_layers: int = 12
    num_heads: int = 12
    intermediate_size: int = 3072
    max_position_embeddings: int = 512
    type_vocab_size: int = 2
    layer_norm_eps: float = 1e-12
    hidden_dropout: float = 0.1
    attention_dropout: float = 0.1
    # True = bit-exact jax.random.bernoulli dropout masks; False (default,
    # the headline-perf path) = low-width hardware bits, rate quantized to
    # 1/256 (tpudl.ops.dropout).
    dropout_exact: bool = False
    num_labels: int = 2
    dtype: Any = jnp.bfloat16
    attention_impl: str = "reference"
    #: Rematerialization scope: False/"none" = store all activations;
    #: True/"layer" = recompute the whole encoder layer in the backward
    #: (max memory saving, measured WORSE on the single-chip BERT-large
    #: step: 43.0% vs 46.5% MFU — BASELINE.md); "attention" = recompute
    #: only the self-attention block (drops the S x S probability tensors,
    #: the dominant per-layer activation at large batch, while keeping the
    #:  cheap-to-store/expensive-to-recompute matmul outputs).
    remat: Any = False
    #: jax.checkpoint policy name for "layer" remat — "dots_saveable"
    #: keeps MXU outputs and recomputes only elementwise/softmax work,
    #: a middle ground between full remat and none. None = save nothing.
    remat_policy: Optional[str] = None
    #: Fused-epilogue kernel tier (tpudl.ops.norms / mlp_fused): False
    #: (default) = the original composite path, bit-identical to before
    #: the tier existed; True = Pallas fused LayerNorm(+residual) and
    #: bias+GeLU on TPU, composite off-TPU (what bench flips on as a
    #: measured variant); "force" = Pallas everywhere (interpret mode
    #: off-TPU — the CPU parity-test mode). Param tree is identical in
    #: all modes, so checkpoints and HF imports are interchangeable.
    fused_ops: Any = False
    #: Low-precision weight tier (tpudl.quant): None (default) = plain
    #: nn.Dense, bit-identical to before the tier; "int8"/"fp8_e4m3" =
    #: encoder attention + MLP projections become QuantDense (serves
    #: the quantize_tree output with dequant fused into the
    #: contraction; full-precision kernels run the exact nn.Dense
    #: math). Embeddings, LayerNorms, pooler, and the classifier head
    #: always stay full precision. Param-tree structure is identical
    #: in all modes.
    weight_dtype: Optional[str] = None
    #: fp8 TRAINING tier (tpudl.ops.fp8_dot + the tpudl.train.precision
    #: "fp8" policy): False (default) = nothing changes; True = the
    #: SAME rule-class sites the quantizer addresses (encoder
    #: attention + MLP projections — tpudl.quant BERT_QUANT_PATTERNS)
    #: become Fp8Dense: e4m3 forward / e5m2 gradient matmuls with
    #: delayed scaling, params still nn.Dense-identical f32 masters
    #: (checkpoints interchange); the per-site amax rings live in the
    #: "fp8" variable collection the train step threads through
    #: TrainState.precision. "force"/"fused"/"reference" pin the
    #: fp8_dot impl seam (CPU parity-test modes). Mutually exclusive
    #: with weight_dtype (serving quantization of a frozen tree).
    fp8_train: Any = False

    @property
    def head_dim(self) -> int:
        return self.hidden_size // self.num_heads


BERT_TINY = partial(BertConfig, hidden_size=128, num_layers=2, num_heads=2,
                    intermediate_size=512)
BERT_BASE = BertConfig
BERT_LARGE = partial(BertConfig, hidden_size=1024, num_layers=24, num_heads=16,
                     intermediate_size=4096)


def _dense(cfg: BertConfig, features: int, name: str, quantize: bool = False):
    """Dense projection. ``quantize=True`` marks the encoder
    attention/MLP sites the ``weight_dtype`` seam swaps to QuantDense
    (exactly the leaves tpudl.quant's BERT_QUANT_PATTERNS match) and
    the ``fp8_train`` seam swaps to Fp8Dense — ONE rule-class set,
    three precision tiers; pooler/classifier callers leave it False
    and always stay full precision."""
    if quantize and cfg.fp8_train:
        if cfg.weight_dtype is not None:
            raise ValueError(
                "fp8_train (training-time fp8 matmuls) and weight_dtype "
                "(serving quantization of a frozen tree) are mutually "
                "exclusive — pick one"
            )
        from tpudl.ops.fp8_dot import Fp8Dense

        impl = cfg.fp8_train if isinstance(cfg.fp8_train, str) else "auto"
        if impl == "force":
            impl = "fused"
        return Fp8Dense(
            features,
            dtype=cfg.dtype,
            kernel_init=nn.initializers.normal(0.02),
            impl=impl,
            name=name,
        )
    if quantize and cfg.weight_dtype is not None:
        from tpudl.quant.dense import QuantDense

        return QuantDense(
            features,
            dtype=cfg.dtype,
            kernel_init=nn.initializers.normal(0.02),
            name=name,
        )
    return nn.Dense(
        features,
        dtype=cfg.dtype,
        kernel_init=nn.initializers.normal(0.02),
        name=name,
    )


class FusedLayerNorm(nn.Module):
    """LayerNorm(+optional residual-add) through the tpudl.ops.norms
    seam. Param tree (scale/bias, f32, ones/zeros init) is identical to
    ``nn.LayerNorm``, so fused and composite checkpoints interchange.
    With ``residual`` returns ``(normed, x + residual)``."""

    eps: float
    impl: str

    @nn.compact
    def __call__(self, x, residual=None, return_sum=True):
        from tpudl.ops.norms import layer_norm

        h = x.shape[-1]
        scale = self.param("scale", nn.initializers.ones, (h,))
        bias = self.param("bias", nn.initializers.zeros, (h,))
        return layer_norm(
            x, scale, bias, residual, eps=self.eps, return_sum=return_sum,
            impl=self.impl,
        )


class FusedBiasGeluDense(nn.Module):
    """``nn.Dense`` + exact GeLU with the bias add fused into the GeLU
    epilogue (tpudl.ops.mlp_fused.bias_gelu) — the matmul runs pre-bias
    so the [N, 4H] stream is read/written once. Params (kernel/bias,
    same init) are identical to the composite ``nn.Dense``."""

    cfg: BertConfig
    features: int
    impl: str

    @nn.compact
    def __call__(self, x):
        from tpudl.ops.mlp_fused import bias_gelu
        from tpudl.quant.dense import quant_dot
        from tpudl.quant.quantize import is_quantized

        cfg = self.cfg
        # Read a quantized kernel around self.param (flax shape-checks
        # stored params against the initializer; the (qvalues, qscale)
        # pair is not the init-time kernel shape) — same dispatch as
        # tpudl.quant.dense.QuantDense.
        stored = (
            self.get_variable("params", "kernel")
            if self.has_variable("params", "kernel")
            else None
        )
        if is_quantized(stored):
            kernel = stored
        else:
            kernel = self.param(
                "kernel", nn.initializers.normal(0.02),
                (x.shape[-1], self.features),
            )
        bias = self.param("bias", nn.initializers.zeros, (self.features,))
        # quant_dot dispatches on the kernel itself: a quantized pair
        # runs the contraction-fused dequant (the weight_dtype seam), a
        # plain kernel the exact pre-existing dot_general in cfg.dtype.
        # The bias+GeLU epilogue is unchanged either way.
        y = quant_dot(x, kernel, compute_dtype=cfg.dtype)
        return bias_gelu(y, bias, impl=self.impl)


class BertEmbeddings(nn.Module):
    cfg: BertConfig

    @nn.compact
    def __call__(self, input_ids, token_type_ids, train: bool):
        cfg = self.cfg
        we = nn.Embed(cfg.vocab_size, cfg.hidden_size,
                      embedding_init=nn.initializers.normal(0.02),
                      name="word_embeddings")(input_ids)
        pos = jnp.arange(input_ids.shape[1])[None, :]
        pe = nn.Embed(cfg.max_position_embeddings, cfg.hidden_size,
                      embedding_init=nn.initializers.normal(0.02),
                      name="position_embeddings")(pos)
        te = nn.Embed(cfg.type_vocab_size, cfg.hidden_size,
                      embedding_init=nn.initializers.normal(0.02),
                      name="token_type_embeddings")(token_type_ids)
        x = we + pe + te
        if cfg.fused_ops:
            from tpudl.ops.norms import fused_ops_impl

            x = FusedLayerNorm(
                cfg.layer_norm_eps, fused_ops_impl(cfg.fused_ops),
                name="layer_norm",
            )(x)
        else:
            x = nn.LayerNorm(epsilon=cfg.layer_norm_eps, dtype=jnp.float32,
                             name="layer_norm")(x)
        x = Dropout(cfg.hidden_dropout, exact=cfg.dropout_exact)(x, deterministic=not train)
        return x.astype(cfg.dtype)


class BertSelfAttention(nn.Module):
    cfg: BertConfig

    @nn.compact
    def __call__(self, hidden, attn_mask, train: bool):
        cfg = self.cfg
        B, S, _ = hidden.shape
        shape = (B, S, cfg.num_heads, cfg.head_dim)
        q = _dense(cfg, cfg.hidden_size, "query", quantize=True)(
            hidden
        ).reshape(shape)
        k = _dense(cfg, cfg.hidden_size, "key", quantize=True)(
            hidden
        ).reshape(shape)
        v = _dense(cfg, cfg.hidden_size, "value", quantize=True)(
            hidden
        ).reshape(shape)
        q = constrain(q, ("dp", "fsdp"), "sp", "tp", None)
        k = constrain(k, ("dp", "fsdp"), "sp", "tp", None)
        v = constrain(v, ("dp", "fsdp"), "sp", "tp", None)
        attn_dropout_rng = None
        if train and cfg.attention_dropout > 0.0:
            attn_dropout_rng = self.make_rng("dropout")
        ctx = attend(
            q,
            k,
            v,
            mask=attn_mask,
            implementation=cfg.attention_impl,
            dropout_rate=cfg.attention_dropout if train else 0.0,
            dropout_rng=attn_dropout_rng,
            dropout_exact=cfg.dropout_exact,
        )
        ctx = ctx.reshape(B, S, cfg.hidden_size)
        out = _dense(cfg, cfg.hidden_size, "out", quantize=True)(ctx)
        out = Dropout(cfg.hidden_dropout, exact=cfg.dropout_exact)(out, deterministic=not train)
        return out


def _remat_policy(name: Optional[str]):
    if name is None:
        return None
    return getattr(jax.checkpoint_policies, name)


def remat_options(cli_name: str) -> dict:
    """CLI remat mode name -> BertConfig kwargs — the ONE mapping shared
    by the training driver (notebooks/nlp/train_sst2.py --remat) and the
    benchmark (benchmarks/bert_large_single_chip.py)."""
    opts = {
        "none": {"remat": False},
        "layer": {"remat": "layer"},
        "attention": {"remat": "attention"},
        "dots": {"remat": "layer", "remat_policy": "dots_saveable"},
    }
    if cli_name not in opts:
        raise ValueError(
            f"remat mode must be one of {sorted(opts)}, got {cli_name!r}"
        )
    return dict(opts[cli_name])


class BertLayer(nn.Module):
    cfg: BertConfig

    @nn.compact
    def __call__(self, hidden, attn_mask, train: bool):
        cfg = self.cfg
        attn_cls = BertSelfAttention
        if cfg.remat == "attention":
            attn_cls = nn.remat(BertSelfAttention, static_argnums=(3,))
        attn_out = attn_cls(cfg, name="attention")(
            hidden, attn_mask, train
        )
        if cfg.fused_ops:
            # Fused-epilogue path (tpudl.ops.norms / mlp_fused): the
            # residual add rides inside the LayerNorm kernel, and BERT's
            # post-norm blocks never consume the summed value, so the
            # kernels skip that write (return_sum=False via the module's
            # residual call returning only the normed value). Composite
            # fallback off-TPU keeps these numerics (fused_ops_impl).
            from tpudl.ops.norms import fused_ops_impl

            impl = fused_ops_impl(cfg.fused_ops)
            hidden = FusedLayerNorm(
                cfg.layer_norm_eps, impl, name="attention_norm"
            )(attn_out, hidden, return_sum=False).astype(cfg.dtype)
            inter = FusedBiasGeluDense(
                cfg, cfg.intermediate_size, impl, name="intermediate"
            )(hidden)
            out = _dense(cfg, cfg.hidden_size, "output", quantize=True)(
                inter
            )
            out = Dropout(cfg.hidden_dropout, exact=cfg.dropout_exact)(
                out, deterministic=not train
            )
            hidden = FusedLayerNorm(
                cfg.layer_norm_eps, impl, name="output_norm"
            )(out, hidden, return_sum=False).astype(cfg.dtype)
        else:
            hidden = nn.LayerNorm(
                epsilon=cfg.layer_norm_eps, dtype=jnp.float32,
                name="attention_norm"
            )(hidden + attn_out).astype(cfg.dtype)

            inter = _dense(
                cfg, cfg.intermediate_size, "intermediate", quantize=True
            )(hidden)
            inter = nn.gelu(inter, approximate=False)
            out = _dense(cfg, cfg.hidden_size, "output", quantize=True)(
                inter
            )
            out = Dropout(cfg.hidden_dropout, exact=cfg.dropout_exact)(out, deterministic=not train)
            hidden = nn.LayerNorm(
                epsilon=cfg.layer_norm_eps, dtype=jnp.float32,
                name="output_norm"
            )(hidden + out).astype(cfg.dtype)
        hidden = constrain(hidden, ("dp", "fsdp"), "sp", "tp")
        return hidden


class BertEncoder(nn.Module):
    cfg: BertConfig

    @nn.compact
    def __call__(self, hidden, attn_mask, train: bool):
        layer_cls = BertLayer
        if self.cfg.remat in (True, "layer"):
            layer_cls = nn.remat(
                BertLayer,
                static_argnums=(3,),
                policy=_remat_policy(self.cfg.remat_policy),
            )
        for i in range(self.cfg.num_layers):
            hidden = layer_cls(self.cfg, name=f"layer_{i}")(
                hidden, attn_mask, train
            )
        return hidden


class BertModel(nn.Module):
    """Encoder + pooler ([CLS] tanh projection), HF-compatible structure."""

    cfg: BertConfig

    @nn.compact
    def __call__(
        self,
        input_ids,
        attention_mask=None,
        token_type_ids=None,
        train: bool = False,
    ):
        cfg = self.cfg
        if attention_mask is None:
            attention_mask = jnp.ones_like(input_ids)
        if token_type_ids is None:
            token_type_ids = jnp.zeros_like(input_ids)
        x = BertEmbeddings(cfg, name="embeddings")(input_ids, token_type_ids, train)
        x = constrain(x, ("dp", "fsdp"), "sp", "tp")
        mask = padding_mask(attention_mask)
        x = BertEncoder(cfg, name="encoder")(x, mask, train)
        pooled = _dense(cfg, cfg.hidden_size, "pooler")(x[:, 0])
        pooled = jnp.tanh(pooled)
        return x, pooled


class BertForSequenceClassification(nn.Module):
    """The configs[1]/configs[3] fine-tune model (SST-2-style)."""

    cfg: BertConfig

    @nn.compact
    def __call__(
        self,
        input_ids,
        attention_mask=None,
        token_type_ids=None,
        train: bool = False,
    ):
        _, pooled = BertModel(self.cfg, name="bert")(
            input_ids, attention_mask, token_type_ids, train
        )
        pooled = Dropout(self.cfg.hidden_dropout, exact=self.cfg.dropout_exact)(
            pooled, deterministic=not train
        )
        logits = nn.Dense(
            self.cfg.num_labels,
            dtype=jnp.float32,
            kernel_init=nn.initializers.normal(0.02),
            name="classifier",
        )(pooled)
        return logits.astype(jnp.float32)


# ---------------------------------------------------------------------------
# HuggingFace weight import (torch state_dict -> tpudl param tree).
# ---------------------------------------------------------------------------

#: HF name pattern -> tpudl path template. Linear weights transpose
#: ([out,in] -> [in,out]); embeddings and LayerNorm keep orientation.
_HF_MAP = [
    (r"^bert\.embeddings\.word_embeddings\.weight$",
     "bert/embeddings/word_embeddings/embedding", False),
    (r"^bert\.embeddings\.position_embeddings\.weight$",
     "bert/embeddings/position_embeddings/embedding", False),
    (r"^bert\.embeddings\.token_type_embeddings\.weight$",
     "bert/embeddings/token_type_embeddings/embedding", False),
    (r"^bert\.embeddings\.LayerNorm\.weight$",
     "bert/embeddings/layer_norm/scale", False),
    (r"^bert\.embeddings\.LayerNorm\.bias$",
     "bert/embeddings/layer_norm/bias", False),
    (r"^bert\.encoder\.layer\.(\d+)\.attention\.self\.(query|key|value)\.weight$",
     "bert/encoder/layer_{0}/attention/{1}/kernel", True),
    (r"^bert\.encoder\.layer\.(\d+)\.attention\.self\.(query|key|value)\.bias$",
     "bert/encoder/layer_{0}/attention/{1}/bias", False),
    (r"^bert\.encoder\.layer\.(\d+)\.attention\.output\.dense\.weight$",
     "bert/encoder/layer_{0}/attention/out/kernel", True),
    (r"^bert\.encoder\.layer\.(\d+)\.attention\.output\.dense\.bias$",
     "bert/encoder/layer_{0}/attention/out/bias", False),
    (r"^bert\.encoder\.layer\.(\d+)\.attention\.output\.LayerNorm\.weight$",
     "bert/encoder/layer_{0}/attention_norm/scale", False),
    (r"^bert\.encoder\.layer\.(\d+)\.attention\.output\.LayerNorm\.bias$",
     "bert/encoder/layer_{0}/attention_norm/bias", False),
    (r"^bert\.encoder\.layer\.(\d+)\.intermediate\.dense\.weight$",
     "bert/encoder/layer_{0}/intermediate/kernel", True),
    (r"^bert\.encoder\.layer\.(\d+)\.intermediate\.dense\.bias$",
     "bert/encoder/layer_{0}/intermediate/bias", False),
    (r"^bert\.encoder\.layer\.(\d+)\.output\.dense\.weight$",
     "bert/encoder/layer_{0}/output/kernel", True),
    (r"^bert\.encoder\.layer\.(\d+)\.output\.dense\.bias$",
     "bert/encoder/layer_{0}/output/bias", False),
    (r"^bert\.encoder\.layer\.(\d+)\.output\.LayerNorm\.weight$",
     "bert/encoder/layer_{0}/output_norm/scale", False),
    (r"^bert\.encoder\.layer\.(\d+)\.output\.LayerNorm\.bias$",
     "bert/encoder/layer_{0}/output_norm/bias", False),
    (r"^bert\.pooler\.dense\.weight$", "bert/pooler/kernel", True),
    (r"^bert\.pooler\.dense\.bias$", "bert/pooler/bias", False),
    (r"^classifier\.weight$", "classifier/kernel", True),
    (r"^classifier\.bias$", "classifier/bias", False),
]


def params_from_hf_bert(
    state_dict: Dict[str, "np.ndarray"],
    like: Optional[Dict] = None,
) -> Dict:
    """Convert a HF BertForSequenceClassification state_dict to a tpudl
    param tree. `state_dict` values may be torch tensors or numpy arrays.
    `like` (a template param tree) enables shape validation.

    Ignored HF keys: position_ids buffers and the cls.* pretraining heads.
    """
    from tpudl.models.llama import _tensor_to_numpy

    tree: Dict = {}
    unmapped = []
    for hf_name, value in state_dict.items():
        arr = _tensor_to_numpy(value)
        for pattern, template, transpose in _HF_MAP:
            m = re.match(pattern, hf_name)
            if m:
                path = template.format(*m.groups())
                if transpose:
                    arr = arr.T
                node = tree
                parts = path.split("/")
                for p in parts[:-1]:
                    node = node.setdefault(p, {})
                node[parts[-1]] = jnp.asarray(arr)
                break
        else:
            if not (
                hf_name.endswith("position_ids")
                or hf_name.startswith("cls.")
                or ".seq_relationship." in hf_name
            ):
                unmapped.append(hf_name)
    if unmapped:
        raise ValueError(f"unmapped HF parameters: {unmapped}")
    if like is not None:
        flat_like = jax.tree_util.tree_leaves_with_path(like)
        flat_new = dict(
            (jax.tree_util.keystr(p), l.shape)
            for p, l in jax.tree_util.tree_leaves_with_path(tree)
        )
        for path, leaf in flat_like:
            key = jax.tree_util.keystr(path)
            if key not in flat_new:
                raise ValueError(f"missing parameter {key} in converted tree")
            if tuple(flat_new[key]) != tuple(leaf.shape):
                raise ValueError(
                    f"shape mismatch at {key}: HF {flat_new[key]} vs "
                    f"model {leaf.shape}"
                )
    return tree
