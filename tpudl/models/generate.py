"""Autoregressive decoding with a KV cache (Llama serving path).

The reference repo's substance is inference benchmarking of an exported
model (reference notebooks/cv/onnx_experiments.py:77-140 — build a
session, run it, time it); this is the decoder-model analog: a jitted
prefill + a jitted single-token decode step over static-shape KV caches
(tpudl.models.llama.LlamaAttention decode mode), so the whole generation
loop runs as two compiled XLA programs regardless of length.

Greedy (temperature=0), temperature, top-k, and top-p (nucleus)
sampling. Ragged prompt batches are served LEFT-padded: the cache marks
padded slots invalid (LlamaAttention's ``valid`` buffer) and masks by
slot write-order, while mask-aware positions keep RoPE phases identical
to the unpadded prompt — so a left-padded row generates token-for-token
what it would alone (tests/test_generate.py).
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp


def prefill_fn(model):
    """THE functional prefill contract (cache as explicit pytree I/O):
    (params, input_ids, attention_mask) -> (last_logits, cache). One
    definition serves both the live loop below and the serving export
    (tpudl.export.decode) — they cannot diverge."""

    def fn(params, input_ids, attention_mask):
        positions = jnp.maximum(
            jnp.cumsum(attention_mask, axis=-1) - 1, 0
        ).astype(jnp.int32)
        logits, mutated = model.apply(
            {"params": params},
            input_ids,
            attention_mask,
            decode=True,
            positions=positions,
            mutable=["cache"],
        )
        return logits[:, -1, :], mutated["cache"]

    return fn


def decode_fn(model):
    """THE functional single-token decode contract:
    (params, cache, token, position) -> (logits, new_cache)."""

    def fn(params, cache, token, position):
        logits, mutated = model.apply(
            {"params": params, "cache": cache},
            token[:, None],
            jnp.ones_like(token)[:, None],
            decode=True,
            positions=position[:, None],
            mutable=["cache"],
        )
        return logits[:, -1, :], mutated["cache"]

    return fn


def paged_decode_fn(model, page_size: int, quantized: bool):
    """THE paged single-token decode contract (tpudl.models.paged):
    ``(params, cache, token, position, page_table, start, lens) ->
    (logits, new_cache)`` where ``cache`` holds per-layer page pools
    (``pages_k``/``pages_v`` + ``scale_k``/``scale_v`` when int8) and
    the three small int32 arrays are the HOST-owned addressing state —
    page table [B, P], first attendable logical position [B], and the
    logical write position [B]. ``page_size``/``quantized`` are static
    (baked into the compiled program); placement changes never
    recompile. Built for the serve engine's paged mode
    (tpudl.serve.cache.PagedKVCache owns the pools and addressing)."""
    from tpudl.models.paged import PagedView

    def fn(params, cache, token, position, page_table, start, lens):
        view = PagedView(
            page_table=page_table, start=start, lens=lens,
            page_size=page_size, quantized=quantized,
        )
        logits, mutated = model.apply(
            {"params": params, "cache": cache},
            token[:, None],
            jnp.ones_like(token)[:, None],
            decode=True,
            positions=position[:, None],
            paged=view,
            mutable=["cache"],
        )
        return logits[:, -1, :], mutated["cache"]

    return fn


def lora_prefill_fn(model, impl: str = "auto"):
    """THE multi-tenant prefill contract: ``(params, input_ids,
    attention_mask, adapter_pools, adapter_table [1, r_max],
    adapter_scale [1]) -> (last_logits, cache)``. The batch-1 prefill
    with ONE tenant's adapter applied through the segmented-LoRA seam
    (tpudl.models.lora.AdapterView) — an all-zero table row (every
    entry on the never-written page 0) serves the plain base model, so
    tenantless requests ride the same compiled program. ``impl`` is the
    tpudl.ops dispatch seam for the segmented kernel (static)."""
    from tpudl.models.lora import AdapterView

    def fn(params, input_ids, attention_mask, apools, atable, ascale):
        positions = jnp.maximum(
            jnp.cumsum(attention_mask, axis=-1) - 1, 0
        ).astype(jnp.int32)
        logits, mutated = model.apply(
            {"params": params},
            input_ids,
            attention_mask,
            decode=True,
            positions=positions,
            adapters=AdapterView(
                pools=apools, table=atable, scale=ascale, impl=impl
            ),
            mutable=["cache"],
        )
        return logits[:, -1, :], mutated["cache"]

    return fn


def lora_paged_decode_fn(
    model, page_size: int, quantized: bool, impl: str = "auto"
):
    """THE multi-tenant paged decode contract: ``paged_decode_fn``'s
    seven arguments plus ``(adapter_pools, adapter_table [B, r_max],
    adapter_scale [B])`` — every slot applies ITS tenant's adapter
    pages through one segmented-LoRA dispatch per projection site
    (tpudl.ops.segmented_lora). The pools and tables are traced
    inputs, so loading/evicting adapters between steps never
    recompiles; slots with no tenant carry an all-zero table row and
    decode the plain base model."""
    from tpudl.models.lora import AdapterView
    from tpudl.models.paged import PagedView

    def fn(
        params, cache, token, position, page_table, start, lens,
        apools, atable, ascale,
    ):
        view = PagedView(
            page_table=page_table, start=start, lens=lens,
            page_size=page_size, quantized=quantized,
        )
        logits, mutated = model.apply(
            {"params": params, "cache": cache},
            token[:, None],
            jnp.ones_like(token)[:, None],
            decode=True,
            positions=position[:, None],
            paged=view,
            adapters=AdapterView(
                pools=apools, table=atable, scale=ascale, impl=impl
            ),
            mutable=["cache"],
        )
        return logits[:, -1, :], mutated["cache"]

    return fn


def chunk_prefill_fn(model):
    """THE suffix-prefill contract for prefix-sharing serving
    (tpudl.serve.cache radix mode): ``(params, cache, tokens [B, C],
    positions [B, C]) -> (last_logits, cache)``. The provided ``cache``
    already holds the SHARED prefix KV (gathered out of radix-tree
    pages into dense rows, ``index`` pinned at the prefix length); this
    runs only the C unshared suffix tokens through the dense decode
    branch — which writes the chunk at ``index``..``index+C`` and
    attends slot-order-causally over prefix + chunk — so prefill cost
    is O(suffix), not O(prompt window). Positions are ABSOLUTE (token
    index in the unpadded prompt), keeping RoPE phases identical to a
    cold full prefill."""

    def fn(params, cache, tokens, positions):
        logits, mutated = model.apply(
            {"params": params, "cache": cache},
            tokens,
            jnp.ones_like(tokens),
            decode=True,
            positions=positions,
            mutable=["cache"],
        )
        return logits[:, -1, :], mutated["cache"]

    return fn


def paged_chunk_decode_fn(model, page_size: int, quantized: bool):
    """THE speculative-verify contract: ``(params, cache, tokens
    [B, C], positions [B, C], page_table, start, lens) -> (logits
    [B, C, V], new_cache)``. One slot-batched dispatch writes each
    slot's C-token window into its pages (token j at logical position
    ``lens + j``) and returns the logits for EVERY window position —
    the target model's verdict on all k draft proposals at once
    (tpudl.serve.speculate). Causality within the window rides the
    chunked paged mask; rejected tails roll back on the host by simply
    not advancing ``lens`` past the accepted count (the garbage rows
    are masked and overwritten by the next window)."""
    from tpudl.models.paged import PagedView

    def fn(params, cache, tokens, positions, page_table, start, lens):
        view = PagedView(
            page_table=page_table, start=start, lens=lens,
            page_size=page_size, quantized=quantized,
        )
        logits, mutated = model.apply(
            {"params": params, "cache": cache},
            tokens,
            jnp.ones_like(tokens),
            decode=True,
            positions=positions,
            paged=view,
            mutable=["cache"],
        )
        return logits, mutated["cache"]

    return fn


@functools.partial(jax.jit, static_argnums=(0,))
def _prefill(model, params, input_ids, attention_mask):
    return prefill_fn(model)(params, input_ids, attention_mask)


@functools.partial(jax.jit, static_argnums=(2,))
def _eos_update(token, done, eos_id):
    """Finished rows emit eos forever; one fused dispatch per token (the
    eager two-op form costs two relay round-trips per generated token)."""
    token = jnp.where(done, eos_id, token)
    return token, jnp.logical_or(done, token == eos_id)


@functools.partial(jax.jit, static_argnums=(0,))
def _decode_step(model, params, cache, token, position):
    return decode_fn(model)(params, cache, token, position)


@functools.partial(jax.jit, static_argnums=(0, 1, 2, 3, 4, 5))
def _decode_chunk(
    model, steps, greedy, top_k, has_top_p, has_eos,
    params, cache, token, position, done, rng,
    temperature, top_p, eos_id,
):
    """``steps`` decode iterations as ONE compiled lax.scan: split rng,
    decode from the previous (eos-masked) token, select, eos-mask, emit.
    The per-token Python loop paid ~5 device dispatches per generated
    token (decode, select, eos ops, position, rng split) — pure relay
    latency on remote-attached serving; the scan collapses a whole
    eos-check window into one dispatch. Split order matches the
    un-scanned loop exactly, so tokens are bit-identical.

    Only STRUCTURAL switches are static (greedy, the top-k size, top-p
    and eos presence, the chunk length); temperature / top_p / eos_id
    ride as traced scalars, so a serving process varying per-request
    sampling hyperparameters reuses the one compiled model-sized scan
    instead of recompiling it per (temperature, top_p) tuple.
    """

    def body(carry, _):
        cache, token, position, done, rng = carry
        rng, step_rng = jax.random.split(rng)
        logits, cache = decode_fn(model)(params, cache, token, position)
        nxt = _select_impl(
            logits, step_rng,
            0.0 if greedy else temperature,
            top_k,
            top_p if has_top_p else None,
            greedy=greedy,
        )
        if has_eos:
            nxt = jnp.where(done, eos_id, nxt)
            done = jnp.logical_or(done, nxt == eos_id)
        return (cache, nxt, position + 1, done, rng), nxt

    (cache, token, position, done, rng), toks = jax.lax.scan(
        body, (cache, token, position, done, rng), None, length=steps
    )
    # The all-rows-done scalar is computed IN-GRAPH so the chunk loop's
    # early-exit readback costs zero extra dispatches (an eager
    # done.all() per chunk paid a relay round-trip on remote-attached
    # serving just to ask "may I stop").
    return cache, token, position, done, rng, toks, jnp.all(done)


_NEG_INF = -1e30


def validate_sampling(temperature, top_k, top_p) -> None:
    """Reject sampling-parameter combinations that would silently not do
    what was asked: top_k/top_p only apply to the categorical branch, so
    pairing them with greedy (temperature 0) is an error, not a no-op."""
    if temperature == 0.0 and (top_k is not None or top_p is not None):
        raise ValueError(
            "top_k/top_p require temperature > 0 (temperature=0.0 is "
            "greedy argmax and would silently ignore them)"
        )
    if top_k is not None and not 0 < top_k:
        raise ValueError(f"top_k must be positive, got {top_k}")
    if top_p is not None and not 0.0 < top_p <= 1.0:
        raise ValueError(f"top_p must be in (0, 1], got {top_p}")


def validate_left_padded(attention_mask) -> None:
    """Shared left-padded-mask contract for the live loop AND the
    exported serving loop (tpudl.export.decode — one definition, the
    paths cannot diverge): every row must be BINARY 0s then 1s with at
    least one real token. Right padding would leave the final slot —
    whose logits seed generation — on a pad; a non-binary mask (e.g. a
    2) would pass the monotonicity check yet corrupt
    ``position = sum(mask)`` and with it cache validity. One host sync
    for all three checks fused."""
    m = attention_mask
    ok = jnp.logical_and(
        jnp.logical_and(
            jnp.all(m[:, 1:] >= m[:, :-1]),
            jnp.all(jnp.sum(m, axis=-1) > 0),
        ),
        jnp.all((m == 0) | (m == 1)),
    )
    if not bool(ok):
        raise ValueError(
            "ragged prompt batches are served LEFT-padded: every "
            "attention_mask row must be binary (0/1) 0s then 1s with at "
            "least one real token (right-padding would leave the final "
            "slot — whose logits seed generation — on a pad; non-binary "
            "values corrupt position = sum(mask))"
        )


def _select_impl(logits, rng, temperature, top_k=None, top_p=None,
                 greedy=None):
    """Next-token selection on [B, V] logits: greedy at temperature 0,
    else categorical over temperature-scaled logits optionally truncated
    to the top-k tokens and/or the top-p (nucleus) probability mass.
    top_p keeps the smallest prefix of probability-sorted tokens whose
    cumulative mass reaches p (the argmax always survives). Parameter
    combinations are checked once by validate_sampling, not per step.
    Traced inside _decode_chunk's scan; _select_first serves the one
    prefill-token selection. ``greedy`` makes the structural
    branch explicit when ``temperature`` is a traced scalar (a tracer
    cannot drive the ``== 0.0`` Python branch); None = derive from the
    concrete temperature. top_k (a shape) must be concrete; top_p may
    be traced, but its None-ness is structural.
    """
    if greedy is None:
        greedy = temperature == 0.0
    # Selection math in f32 regardless of model dtype: a 128k-vocab bf16
    # cumsum has ~3-digit resolution — comparable to 1-p at top_p=0.95 —
    # and the scan path's traced f32 scalars would otherwise promote
    # while the first-token path stayed bf16 (different numerics for
    # token 0 than tokens 1..N).
    logits = logits.astype(jnp.float32)
    if greedy:
        return jnp.argmax(logits, axis=-1).astype(jnp.int32)
    logits = logits / temperature
    if top_k is not None:
        kth = jax.lax.top_k(logits, min(top_k, logits.shape[-1]))[0][..., -1:]
        logits = jnp.where(logits < kth, _NEG_INF, logits)
    if top_p is not None:
        # Cutoff-VALUE formulation: sort values (no index permutation),
        # find the smallest prefix whose exclusive cumulative mass stays
        # < p (so the prefix that first reaches p survives — the argmax
        # always does), then keep by comparing against the last kept
        # value. Avoids the two full-vocab index gathers of the
        # argsort/inverse-permutation form, which dominated decode time
        # at a 128k vocab (~20 ms/token -> ~2). Tokens BIT-EQUAL to the
        # cutoff logit are also kept — a measure-zero superset for
        # continuous logits.
        sorted_desc = -jnp.sort(-logits, axis=-1)
        probs = jax.nn.softmax(sorted_desc, axis=-1)
        keep_sorted = (jnp.cumsum(probs, axis=-1) - probs) < top_p
        num_kept = jnp.sum(keep_sorted.astype(jnp.int32), axis=-1,
                           keepdims=True)  # >= 1
        v_cut = jnp.take_along_axis(sorted_desc, num_kept - 1, axis=-1)
        logits = jnp.where(logits >= v_cut, logits, _NEG_INF)
    return jax.random.categorical(rng, logits, axis=-1).astype(jnp.int32)


@functools.partial(jax.jit, static_argnums=(2, 3, 4))
def _select_first(logits, rng, greedy, top_k, has_top_p, temperature, top_p):
    """First-token (prefill-logits) selection with the SAME
    static/traced split as _decode_chunk: only structure is static, so
    per-request temperature/top_p reuse one compiled program instead of
    recompiling the full-vocab sort per float tuple."""
    return _select_impl(
        logits, rng,
        0.0 if greedy else temperature,
        top_k,
        top_p if has_top_p else None,
        greedy=greedy,
    )


def generate(
    model,
    params,
    input_ids: jax.Array,
    attention_mask: Optional[jax.Array] = None,
    max_new_tokens: int = 32,
    temperature: float = 0.0,
    top_k: Optional[int] = None,
    top_p: Optional[float] = None,
    eos_id: Optional[int] = None,
    rng: Optional[jax.Array] = None,
    eos_check_every: int = 8,
) -> jax.Array:
    """Generate continuations for a [B, S] prompt batch.

    ``model`` is a LlamaForCausalLM whose config ``max_seq_len`` bounds
    S + max_new_tokens. Ragged prompts batch via LEFT-padding: pad short
    rows on the left and pass ``attention_mask`` (0 = pad); each row then
    generates exactly what it would unpadded. ``temperature``/``top_k``/
    ``top_p`` select the sampling rule (see ``_select_impl``). Returns
    [B, max_new_tokens] generated ids (after ``eos_id``, positions are
    padded with eos). ``eos_check_every`` paces the all-rows-done
    early-exit readback (1 = check every token).
    """
    b, s = input_ids.shape
    if max_new_tokens < 1:
        raise ValueError(
            f"max_new_tokens must be >= 1, got {max_new_tokens}"
        )
    validate_sampling(temperature, top_k, top_p)
    if attention_mask is None:
        attention_mask = jnp.ones_like(input_ids)
    else:
        validate_left_padded(attention_mask)
    if eos_check_every < 1:
        raise ValueError(
            f"eos_check_every must be >= 1 (1 = check every token), got "
            f"{eos_check_every}"
        )
    if s + max_new_tokens > model.cfg.max_seq_len:
        raise ValueError(
            f"prompt ({s}) + max_new_tokens ({max_new_tokens}) exceeds "
            f"max_seq_len {model.cfg.max_seq_len} (the KV cache bound)"
        )
    if rng is None:
        rng = jax.random.key(0)

    logits, cache = _prefill(model, params, input_ids, attention_mask)
    # Next absolute position per row (mask-aware: left padding skipped).
    position = jnp.sum(attention_mask, axis=-1).astype(jnp.int32)

    done = jnp.zeros((b,), bool)
    rng, sel_rng = jax.random.split(rng)
    greedy = temperature == 0.0
    t_op = jnp.float32(temperature)
    p_op = jnp.float32(top_p if top_p is not None else 1.0)
    token = _select_first(
        logits, sel_rng, greedy, top_k, top_p is not None, t_op, p_op
    )
    if eos_id is not None:
        token, done = _eos_update(token, done, eos_id)
    # The decode loop runs as compiled lax.scan CHUNKS of
    # ``eos_check_every`` tokens (_decode_chunk): one host dispatch per
    # chunk — and with an eos, one done-all readback per chunk —
    # instead of ~5 dispatches per token: the difference between
    # relay-latency-bound and HBM-bandwidth-bound serving. Chunking is
    # unconditional (without an eos the readback is simply skipped), so
    # the jit cache holds the chunk-length scan plus one remainder
    # length per (max_new_tokens - 1) % eos_check_every residue — at
    # most eos_check_every distinct lengths across all requests, not
    # one model-sized executable per requested length.
    out = [token[:, None]]
    remaining = max_new_tokens - 1
    eos_op = jnp.int32(eos_id if eos_id is not None else 0)
    all_done = eos_id is not None and bool(done.all())
    while remaining > 0:
        if all_done:
            # Every row finished: pad the rest with eos, skip dead steps
            # (a batch that finishes at token 1 runs ZERO decode chunks —
            # tests/test_generate.py counts the invocations).
            out.append(jnp.full((b, remaining), eos_id, token.dtype))
            break
        steps = min(eos_check_every, remaining)
        cache, token, position, done, rng, toks, all_done_op = _decode_chunk(
            model, steps, greedy, top_k,
            top_p is not None, eos_id is not None,
            params, cache, token, position, done, rng,
            t_op, p_op, eos_op,
        )
        out.append(toks.T)
        remaining -= steps
        # One readback of the chunk's in-graph all-done scalar — the
        # same sync the chunked design already paid, no extra dispatch.
        all_done = remaining > 0 and eos_id is not None and bool(all_done_op)
    return jnp.concatenate(out, axis=1)
