"""Autoregressive decoding with a KV cache (Llama serving path).

The reference repo's substance is inference benchmarking of an exported
model (reference notebooks/cv/onnx_experiments.py:77-140 — build a
session, run it, time it); this is the decoder-model analog: a jitted
prefill + a jitted single-token decode step over static-shape KV caches
(tpudl.models.llama.LlamaAttention decode mode), so the whole generation
loop runs as two compiled XLA programs regardless of length.

Greedy (temperature=0) or temperature sampling. Prompts must be unpadded
(cache slot == absolute position keeps the in-cache causal mask a pure
index comparison); batch prompts of equal length or generate per group.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp


def prefill_fn(model):
    """THE functional prefill contract (cache as explicit pytree I/O):
    (params, input_ids, attention_mask) -> (last_logits, cache). One
    definition serves both the live loop below and the serving export
    (tpudl.export.decode) — they cannot diverge."""

    def fn(params, input_ids, attention_mask):
        positions = jnp.maximum(
            jnp.cumsum(attention_mask, axis=-1) - 1, 0
        ).astype(jnp.int32)
        logits, mutated = model.apply(
            {"params": params},
            input_ids,
            attention_mask,
            decode=True,
            positions=positions,
            mutable=["cache"],
        )
        return logits[:, -1, :], mutated["cache"]

    return fn


def decode_fn(model):
    """THE functional single-token decode contract:
    (params, cache, token, position) -> (logits, new_cache)."""

    def fn(params, cache, token, position):
        logits, mutated = model.apply(
            {"params": params, "cache": cache},
            token[:, None],
            jnp.ones_like(token)[:, None],
            decode=True,
            positions=position[:, None],
            mutable=["cache"],
        )
        return logits[:, -1, :], mutated["cache"]

    return fn


@functools.partial(jax.jit, static_argnums=(0,))
def _prefill(model, params, input_ids, attention_mask):
    return prefill_fn(model)(params, input_ids, attention_mask)


@functools.partial(jax.jit, static_argnums=(0,))
def _decode_step(model, params, cache, token, position):
    return decode_fn(model)(params, cache, token, position)


def _select(logits, rng, temperature):
    if temperature == 0.0:
        return jnp.argmax(logits, axis=-1).astype(jnp.int32)
    return jax.random.categorical(rng, logits / temperature, axis=-1).astype(
        jnp.int32
    )


def generate(
    model,
    params,
    input_ids: jax.Array,
    attention_mask: Optional[jax.Array] = None,
    max_new_tokens: int = 32,
    temperature: float = 0.0,
    eos_id: Optional[int] = None,
    rng: Optional[jax.Array] = None,
    eos_check_every: int = 8,
) -> jax.Array:
    """Generate continuations for a [B, S] prompt batch.

    ``model`` is a LlamaForCausalLM whose config ``max_seq_len`` bounds
    S + max_new_tokens. Returns [B, max_new_tokens] generated ids (after
    ``eos_id``, positions are padded with eos). ``eos_check_every`` paces
    the all-rows-done early-exit readback (1 = check every token).
    """
    b, s = input_ids.shape
    if attention_mask is None:
        attention_mask = jnp.ones_like(input_ids)
    elif not bool(jnp.all(attention_mask == 1)):
        raise NotImplementedError(
            "generate() requires unpadded prompts (attention_mask all "
            "ones): the KV cache indexes by slot == position"
        )
    if eos_check_every < 1:
        raise ValueError(
            f"eos_check_every must be >= 1 (1 = check every token), got "
            f"{eos_check_every}"
        )
    if s + max_new_tokens > model.cfg.max_seq_len:
        raise ValueError(
            f"prompt ({s}) + max_new_tokens ({max_new_tokens}) exceeds "
            f"max_seq_len {model.cfg.max_seq_len} (the KV cache bound)"
        )
    if rng is None:
        rng = jax.random.key(0)

    logits, cache = _prefill(model, params, input_ids, attention_mask)
    # Next absolute position per row (mask-aware: left padding skipped).
    position = jnp.sum(attention_mask, axis=-1).astype(jnp.int32)

    tokens = []
    done = jnp.zeros((b,), bool)
    rng, sel_rng = jax.random.split(rng)
    token = _select(logits, sel_rng, temperature)
    for i in range(max_new_tokens):
        if eos_id is not None:
            token = jnp.where(done, eos_id, token)
            done = jnp.logical_or(done, token == eos_id)
        tokens.append(token)
        if i + 1 == max_new_tokens:
            break
        # Early-exit check only every `eos_check_every` tokens: a
        # bool(done.all()) is a device readback that serializes decode
        # dispatch (pathological on relay-attached devices), so the
        # steady-state loop stays free of per-token host syncs.
        if (
            eos_id is not None
            and (i + 1) % eos_check_every == 0
            and bool(done.all())
        ):
            # Every row finished: pad the rest with eos, skip dead steps.
            pad = jnp.full_like(token, eos_id)
            tokens.extend([pad] * (max_new_tokens - i - 1))
            break
        rng, step_rng = jax.random.split(rng)
        logits, cache = _decode_step(model, params, cache, token, position)
        position = position + 1
        token = _select(logits, step_rng, temperature)
    return jnp.stack(tokens, axis=1)
