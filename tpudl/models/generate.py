"""Autoregressive decoding with a KV cache (Llama serving path).

The reference repo's substance is inference benchmarking of an exported
model (reference notebooks/cv/onnx_experiments.py:77-140 — build a
session, run it, time it); this is the decoder-model analog: a jitted
prefill + a jitted single-token decode step over static-shape KV caches
(tpudl.models.llama.LlamaAttention decode mode), so the whole generation
loop runs as two compiled XLA programs regardless of length.

Greedy (temperature=0), temperature, top-k, and top-p (nucleus)
sampling. Ragged prompt batches are served LEFT-padded: the cache marks
padded slots invalid (LlamaAttention's ``valid`` buffer) and masks by
slot write-order, while mask-aware positions keep RoPE phases identical
to the unpadded prompt — so a left-padded row generates token-for-token
what it would alone (tests/test_generate.py).
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp


def prefill_fn(model):
    """THE functional prefill contract (cache as explicit pytree I/O):
    (params, input_ids, attention_mask) -> (last_logits, cache). One
    definition serves both the live loop below and the serving export
    (tpudl.export.decode) — they cannot diverge."""

    def fn(params, input_ids, attention_mask):
        positions = jnp.maximum(
            jnp.cumsum(attention_mask, axis=-1) - 1, 0
        ).astype(jnp.int32)
        logits, mutated = model.apply(
            {"params": params},
            input_ids,
            attention_mask,
            decode=True,
            positions=positions,
            mutable=["cache"],
        )
        return logits[:, -1, :], mutated["cache"]

    return fn


def decode_fn(model):
    """THE functional single-token decode contract:
    (params, cache, token, position) -> (logits, new_cache)."""

    def fn(params, cache, token, position):
        logits, mutated = model.apply(
            {"params": params, "cache": cache},
            token[:, None],
            jnp.ones_like(token)[:, None],
            decode=True,
            positions=position[:, None],
            mutable=["cache"],
        )
        return logits[:, -1, :], mutated["cache"]

    return fn


@functools.partial(jax.jit, static_argnums=(0,))
def _prefill(model, params, input_ids, attention_mask):
    return prefill_fn(model)(params, input_ids, attention_mask)


@functools.partial(jax.jit, static_argnums=(0,))
def _decode_step(model, params, cache, token, position):
    return decode_fn(model)(params, cache, token, position)


_NEG_INF = -1e30


def validate_sampling(temperature, top_k, top_p) -> None:
    """Reject sampling-parameter combinations that would silently not do
    what was asked: top_k/top_p only apply to the categorical branch, so
    pairing them with greedy (temperature 0) is an error, not a no-op."""
    if temperature == 0.0 and (top_k is not None or top_p is not None):
        raise ValueError(
            "top_k/top_p require temperature > 0 (temperature=0.0 is "
            "greedy argmax and would silently ignore them)"
        )
    if top_k is not None and not 0 < top_k:
        raise ValueError(f"top_k must be positive, got {top_k}")
    if top_p is not None and not 0.0 < top_p <= 1.0:
        raise ValueError(f"top_p must be in (0, 1], got {top_p}")


def validate_left_padded(attention_mask) -> None:
    """Shared left-padded-mask contract for the live loop AND the
    exported serving loop (tpudl.export.decode — one definition, the
    paths cannot diverge): every row must be 0s then 1s with at least
    one real token. Right padding would leave the final slot — whose
    logits seed generation — on a pad. One host sync."""
    ok = jnp.logical_and(
        jnp.all(attention_mask[:, 1:] >= attention_mask[:, :-1]),
        jnp.all(jnp.sum(attention_mask, axis=-1) > 0),
    )
    if not bool(ok):
        raise ValueError(
            "ragged prompt batches are served LEFT-padded: every "
            "attention_mask row must be 0s then 1s with at least one "
            "real token (right-padding would leave the final slot — "
            "whose logits seed generation — on a pad)"
        )


def _select(logits, rng, temperature, top_k=None, top_p=None):
    """Next-token selection on [B, V] logits: greedy at temperature 0,
    else categorical over temperature-scaled logits optionally truncated
    to the top-k tokens and/or the top-p (nucleus) probability mass.
    top_p keeps the smallest prefix of probability-sorted tokens whose
    cumulative mass reaches p (the argmax always survives). Parameter
    combinations are checked once by validate_sampling, not per step.
    """
    if temperature == 0.0:
        return jnp.argmax(logits, axis=-1).astype(jnp.int32)
    logits = logits / temperature
    if top_k is not None:
        kth = jax.lax.top_k(logits, min(top_k, logits.shape[-1]))[0][..., -1:]
        logits = jnp.where(logits < kth, _NEG_INF, logits)
    if top_p is not None:
        order = jnp.argsort(-logits, axis=-1)
        sorted_logits = jnp.take_along_axis(logits, order, axis=-1)
        probs = jax.nn.softmax(sorted_logits, axis=-1)
        # Exclusive cumulative mass: a token is kept while the mass
        # BEFORE it is < p, so the prefix that first reaches p survives.
        keep_sorted = (jnp.cumsum(probs, axis=-1) - probs) < top_p
        inv = jnp.argsort(order, axis=-1)
        keep = jnp.take_along_axis(keep_sorted, inv, axis=-1)
        logits = jnp.where(keep, logits, _NEG_INF)
    return jax.random.categorical(rng, logits, axis=-1).astype(jnp.int32)


def generate(
    model,
    params,
    input_ids: jax.Array,
    attention_mask: Optional[jax.Array] = None,
    max_new_tokens: int = 32,
    temperature: float = 0.0,
    top_k: Optional[int] = None,
    top_p: Optional[float] = None,
    eos_id: Optional[int] = None,
    rng: Optional[jax.Array] = None,
    eos_check_every: int = 8,
) -> jax.Array:
    """Generate continuations for a [B, S] prompt batch.

    ``model`` is a LlamaForCausalLM whose config ``max_seq_len`` bounds
    S + max_new_tokens. Ragged prompts batch via LEFT-padding: pad short
    rows on the left and pass ``attention_mask`` (0 = pad); each row then
    generates exactly what it would unpadded. ``temperature``/``top_k``/
    ``top_p`` select the sampling rule (see ``_select``). Returns
    [B, max_new_tokens] generated ids (after ``eos_id``, positions are
    padded with eos). ``eos_check_every`` paces the all-rows-done
    early-exit readback (1 = check every token).
    """
    b, s = input_ids.shape
    validate_sampling(temperature, top_k, top_p)
    if attention_mask is None:
        attention_mask = jnp.ones_like(input_ids)
    else:
        validate_left_padded(attention_mask)
    if eos_check_every < 1:
        raise ValueError(
            f"eos_check_every must be >= 1 (1 = check every token), got "
            f"{eos_check_every}"
        )
    if s + max_new_tokens > model.cfg.max_seq_len:
        raise ValueError(
            f"prompt ({s}) + max_new_tokens ({max_new_tokens}) exceeds "
            f"max_seq_len {model.cfg.max_seq_len} (the KV cache bound)"
        )
    if rng is None:
        rng = jax.random.key(0)

    logits, cache = _prefill(model, params, input_ids, attention_mask)
    # Next absolute position per row (mask-aware: left padding skipped).
    position = jnp.sum(attention_mask, axis=-1).astype(jnp.int32)

    tokens = []
    done = jnp.zeros((b,), bool)
    rng, sel_rng = jax.random.split(rng)
    token = _select(logits, sel_rng, temperature, top_k, top_p)
    for i in range(max_new_tokens):
        if eos_id is not None:
            token = jnp.where(done, eos_id, token)
            done = jnp.logical_or(done, token == eos_id)
        tokens.append(token)
        if i + 1 == max_new_tokens:
            break
        # Early-exit check only every `eos_check_every` tokens: a
        # bool(done.all()) is a device readback that serializes decode
        # dispatch (pathological on relay-attached devices), so the
        # steady-state loop stays free of per-token host syncs.
        if (
            eos_id is not None
            and (i + 1) % eos_check_every == 0
            and bool(done.all())
        ):
            # Every row finished: pad the rest with eos, skip dead steps.
            pad = jnp.full_like(token, eos_id)
            tokens.extend([pad] * (max_new_tokens - i - 1))
            break
        rng, step_rng = jax.random.split(rng)
        logits, cache = _decode_step(model, params, cache, token, position)
        position = position + 1
        token = _select(logits, step_rng, temperature, top_k, top_p)
    return jnp.stack(tokens, axis=1)
