"""Paged KV-cache primitives: page pools, gather/scatter, int8 quant.

The dense decode cache (tpudl.models.llama.LlamaAttention decode mode)
allocates ``[num_slots, max_seq_len, Hkv, D]`` per layer whether a slot
holds a 14-token short request or a 256-token horizon-filler, and every
slot shares ONE device-side write index — the source of the serve
engine's horizon rollovers. The paged layout replaces both:

- KV lives in a pool of fixed-size **pages** ``[num_pages, page_size,
  Hkv, D]`` per layer; a slot owns whichever pages its **page table**
  row ``page_table[slot, j]`` maps (logical page ``j`` -> physical page
  id). Memory scales with what requests actually reserve, not with
  ``num_slots x max_seq_len``.
- Each slot carries its OWN length (``lens[slot]``) — decode writes
  row ``b`` at its own logical position, so no horizon is shared and
  rollovers cease to exist.
- Pages optionally store **int8** with a dequant scale per (page, row,
  kv-head) — ~4x the resident tokens per byte vs f32 pools — applied
  inside the decode gather (one fused multiply on the gathered view).

Masking: slot ``b`` attends logical positions ``[start[b], lens[b]]``
(``start`` = its left-pad count, ``lens`` = where this step's token was
just written). Physical page ids play no role in masking — the page
table is pure address translation, updated on the HOST between steps
(it rides into the decode program as a small traced input, so seating
and freeing slots never recompiles anything).

Physical page 0 is reserved as the **trash page**: freed slots' table
rows point at it, so an idle slot's ride-along decode write lands in a
page no live slot ever maps — the paged analog of the dense cache's
"stale rows are masked" contract.

The serving-side pool manager is tpudl.serve.cache.PagedKVCache; the
decode program contract is tpudl.models.generate.paged_decode_fn.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import jax
import jax.numpy as jnp

#: Symmetric int8 range: quantized values live in [-127, 127].
INT8_MAX = 127.0
#: Floor on quantization scales so an all-zero row dequantizes to zeros
#: instead of dividing by zero.
SCALE_EPS = 1e-12


@dataclasses.dataclass
class PagedView:
    """Per-dispatch paged-cache addressing, threaded through the model.

    ``page_table`` ([B, P] int32) maps slot b's logical page j to a
    physical pool page (0 = the trash page for unmapped entries);
    ``start`` ([B] int32) is slot b's first attendable logical position
    (its left-pad count); ``lens`` ([B] int32) is the logical position
    this step's token is written at. ``page_size`` and ``quantized``
    are STATIC (baked into the compiled program); the arrays are traced
    inputs, so the host mutates placement freely between dispatches.
    """

    page_table: jax.Array
    start: jax.Array
    lens: jax.Array
    page_size: int
    quantized: bool

    @property
    def logical_len(self) -> int:
        """Positions addressable per slot: pages_per_slot x page_size."""
        return int(self.page_table.shape[1]) * self.page_size


def quantize_kv(x: jax.Array):
    """Symmetric int8 quantization over the head_dim axis.

    ``x`` [..., Hkv, D] -> (q int8 [..., Hkv, D], scale f32 [..., Hkv]);
    ``q * scale`` reconstructs x to ~0.4% of the per-head max — the
    granularity that keeps greedy decode token-stable at tiny scales
    while costing 4/D extra bytes per element (scale rows ride in the
    pool next to their page)."""
    xf = x.astype(jnp.float32)
    scale = jnp.max(jnp.abs(xf), axis=-1) / INT8_MAX
    scale = jnp.maximum(scale, SCALE_EPS)
    q = jnp.round(xf / scale[..., None])
    q = jnp.clip(q, -INT8_MAX, INT8_MAX).astype(jnp.int8)
    return q, scale


def flat_page_row_index(page_table, page_size: int):
    """Flat row index into a pool reshaped to ``[NP * page_size, ...]``:
    logical position ``j`` of each table row maps to physical row
    ``table[..., j // ps] * ps + j % ps``. Accepts ``[P]`` (one slot's
    page ids — the radix gather and KV-migration paths) or ``[B, P]``
    (the batched decode gather); the trailing axis flattens to
    ``P * page_size`` either way. The ONE definition of page-table
    address arithmetic shared by every pool gather."""
    idx = (
        page_table[..., :, None] * page_size
        + jnp.arange(page_size, dtype=page_table.dtype)[None, :]
    )
    return idx.reshape(*page_table.shape[:-1], -1)


def paged_write(
    pages: jax.Array,
    scales: Optional[jax.Array],
    value: jax.Array,
    view: PagedView,
):
    """Write a token chunk's KV per slot into its current page rows.

    ``pages`` [NP, ps, Hkv, D] (int8 or compute dtype), ``scales``
    [NP, ps, Hkv] f32 (quantized pools only), ``value`` [B, S, Hkv, D]
    (the freshly projected + RoPE'd k or v; [B, Hkv, D] is accepted as
    the S=1 single-token form). Token j of slot b lands at physical
    ``(page_table[b, (lens[b]+j) // ps], (lens[b]+j) % ps)`` — the
    speculative-verify dispatch writes its whole k-token window this
    way; idle slots (lens pinned at 0 on a trash-mapped row) write into
    page 0, which no live slot maps. Positions past the table's logical
    capacity (a verify window overshooting a nearly-full slot) redirect
    to the trash page instead of clamping onto the slot's last page —
    a clamped write would corrupt KEPT rows of the same slot."""
    if value.ndim == 3:
        value = value[:, None]
    s = value.shape[1]
    ps = view.page_size
    p = view.page_table.shape[1]
    pos = view.lens[:, None] + jnp.arange(s, dtype=view.lens.dtype)[None, :]
    pidx = pos // ps
    page = jnp.take_along_axis(
        view.page_table, jnp.minimum(pidx, p - 1), axis=1
    )
    page = jnp.where(pidx < p, page, 0)
    off = pos % ps
    if view.quantized:
        q, sc = quantize_kv(value)
        pages = pages.at[page, off].set(q)
        scales = scales.at[page, off].set(sc)
    else:
        pages = pages.at[page, off].set(value.astype(pages.dtype))
    return pages, scales


def paged_gather(
    pages: jax.Array,
    scales: Optional[jax.Array],
    view: PagedView,
    compute_dtype,
) -> jax.Array:
    """Materialize every slot's logical KV view from the pool.

    Returns [B, L, Hkv, D] in ``compute_dtype`` where L = pages_per_slot
    x page_size; dequantization (``q * scale``) is fused into this
    gather for int8 pools. Unmapped logical pages resolve to the trash
    page — finite garbage the attention mask excludes."""
    np_, ps = pages.shape[0], view.page_size
    flat_idx = flat_page_row_index(view.page_table, ps)
    flat_pages = pages.reshape(np_ * ps, *pages.shape[2:])
    out = flat_pages[flat_idx]  # [B, L, Hkv, D]
    if view.quantized:
        flat_scales = scales.reshape(np_ * ps, scales.shape[2])
        out = out.astype(jnp.float32) * flat_scales[flat_idx][..., None]
    return out.astype(compute_dtype)


def paged_attend_mask(view: PagedView, chunk: int = 1) -> jax.Array:
    """[B, 1, S, L] bool — query j of the chunk attends logical
    positions in [start, lens + j] inclusive (lens + j = where query
    j's own token was just written), so a multi-token verify chunk is
    causal within itself exactly like sequential single-token steps."""
    pos = jnp.arange(view.logical_len)
    upper = view.lens[:, None] + jnp.arange(
        chunk, dtype=view.lens.dtype
    )[None, :]
    mask = (pos[None, None, :] >= view.start[:, None, None]) & (
        pos[None, None, :] <= upper[:, :, None]
    )
    return mask[:, None, :, :]
