"""L2 model families: CV (ResNet) and NLP (BERT, Llama, LoRA)."""

from tpudl.models.bert import (  # noqa: F401
    BERT_BASE,
    BERT_LARGE,
    BERT_TINY,
    BertConfig,
    BertForSequenceClassification,
    BertModel,
    params_from_hf_bert,
)
from tpudl.models.generate import generate  # noqa: F401
from tpudl.models.llama import (  # noqa: F401
    LLAMA3_8B,
    LLAMA_TINY,
    LlamaConfig,
    LlamaForCausalLM,
    LlamaForSequenceClassification,
)
from tpudl.models.lora import (  # noqa: F401
    LoRADense,
    lora_optimizer,
    merge_lora,
    trainable_param_count,
)
from tpudl.models.resnet import (  # noqa: F401
    ResNet,
    ResNet18,
    ResNet34,
    ResNet50,
    ResNet101,
)
