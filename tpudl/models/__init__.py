"""L2 model families: CV (ResNet) and NLP (BERT, LoRA)."""

from tpudl.models.resnet import (  # noqa: F401
    ResNet,
    ResNet18,
    ResNet34,
    ResNet50,
    ResNet101,
)
