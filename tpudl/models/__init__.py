"""L2 model families: CV (ResNet) and NLP (BERT, Llama, LoRA)."""

from tpudl.models.bert import (  # noqa: F401
    BERT_BASE,
    BERT_LARGE,
    BERT_TINY,
    BertConfig,
    BertForSequenceClassification,
    BertModel,
    params_from_hf_bert,
)
from tpudl.models.resnet import (  # noqa: F401
    ResNet,
    ResNet18,
    ResNet34,
    ResNet50,
    ResNet101,
)
