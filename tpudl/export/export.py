"""Model serialization: StableHLO artifacts and Orbax parameter checkpoints.

TPU-native analog of the reference's three export paths
(reference notebooks/cv/onnx_experiments.py):
- ONNX opset-12 export (:33-42)        -> jax.export / StableHLO bytes
- whole-module pickle torch.save (:198) -> Orbax param checkpoint
- TorchScript trace (:206-215)          -> the same StableHLO artifact
  (XLA graph capture is inherent in jit; no separate tracer product)
- artifact size comparison via `ls -all` (:194,202,219) -> artifact_sizes()
"""

from __future__ import annotations

import os
from typing import Any, Callable, Optional, Sequence, Union

import jax

# jax.export is the one dependency of this module that moves between
# jax releases; import-gate it so environments without it can still
# import the package (tests/conftest.py auto-skips export-path tests
# and benchmarks/parity_grid.py skips its exported-backend cells off
# EXPORT_AVAILABLE instead of erroring at collection/import).
try:
    from jax import export as jax_export

    EXPORT_AVAILABLE = True
    _EXPORT_IMPORT_ERROR: Optional[BaseException] = None
except Exception as _e:  # pragma: no cover - version-dependent
    jax_export = None
    EXPORT_AVAILABLE = False
    _EXPORT_IMPORT_ERROR = _e


def _require_export() -> None:
    if not EXPORT_AVAILABLE:
        raise RuntimeError(
            f"jax.export is unavailable in this jax build "
            f"({type(_EXPORT_IMPORT_ERROR).__name__}: "
            f"{_EXPORT_IMPORT_ERROR}) — StableHLO export/deserialize "
            f"paths cannot run"
        )


def export_stablehlo(
    fn: Callable,
    args: Sequence[Any],
    path: Optional[str] = None,
    platforms: Optional[Sequence[str]] = None,
) -> bytes:
    """Trace+lower `fn` at `args` and serialize the StableHLO artifact.

    `platforms` (e.g. ("cpu", "tpu")) bakes multi-platform lowering into one
    artifact — the single-artifact-many-backends property the reference gets
    from ONNX.
    """
    _require_export()
    jitted = jax.jit(fn)
    if platforms:
        exported = jax_export.export(jitted, platforms=tuple(platforms))(*args)
    else:
        exported = jax_export.export(jitted)(*args)
    blob = exported.serialize()
    if path:
        with open(path, "wb") as f:
            f.write(blob)
    return blob


def load_exported_obj(blob_or_path: Union[bytes, str]) -> "jax_export.Exported":
    """Deserialize a StableHLO artifact into the full Exported object —
    callable via ``.call`` AND introspectable via ``.in_avals`` /
    ``.in_tree`` (how a serving runtime recovers the compiled shapes —
    slot count, prompt window, cache bound — from the artifact alone;
    see tpudl.serve.api.ServeSession.from_artifacts)."""
    _require_export()
    if isinstance(blob_or_path, str):
        with open(blob_or_path, "rb") as f:
            blob = f.read()
    else:
        blob = blob_or_path
    try:
        return jax_export.deserialize(blob)
    except Exception as e:
        source = blob_or_path if isinstance(blob_or_path, str) else "<bytes>"
        raise ValueError(
            f"{source} is not a valid serialized StableHLO artifact "
            f"(expected output of export_stablehlo): {type(e).__name__}: {e}"
        ) from e


def load_exported(blob_or_path: Union[bytes, str]) -> Callable:
    """Deserialize a StableHLO artifact into a callable (the
    InferenceSession analog, reference notebooks/cv/onnx_experiments.py:81)."""
    return load_exported_obj(blob_or_path).call


def save_params(path: str, params: Any, overwrite: bool = True) -> None:
    """Orbax checkpoint of a parameter pytree (the torch.save analog)."""
    import orbax.checkpoint as ocp

    path = os.path.abspath(path)
    with ocp.StandardCheckpointer() as ckptr:
        ckptr.save(path, params, force=overwrite)


def load_params(path: str, like: Optional[Any] = None) -> Any:
    import orbax.checkpoint as ocp

    path = os.path.abspath(path)
    with ocp.StandardCheckpointer() as ckptr:
        if like is not None:
            return ckptr.restore(path, like)
        return ckptr.restore(path)


def artifact_sizes(*paths: str) -> dict:
    """Byte sizes of export artifacts (files or checkpoint dirs)."""
    out = {}
    for p in paths:
        if os.path.isdir(p):
            total = 0
            for root, _, files in os.walk(p):
                total += sum(os.path.getsize(os.path.join(root, f)) for f in files)
            out[p] = total
        elif os.path.exists(p):
            out[p] = os.path.getsize(p)
        else:
            out[p] = None
    return out
