"""Serving export of the autoregressive decode path.

The reference's core loop is export -> session -> infer (reference
notebooks/cv/onnx_experiments.py:33-42,81: ONNX export, InferenceSession,
session.run). Its decoder-model analog is this module: the prefill and
single-token decode steps of tpudl.models.generate are exported as
StableHLO artifacts with the KV cache as EXPLICIT inputs/outputs (the
functional form a serving runtime needs — no flax mutable-state plumbing
survives serialization), and a deserialized-artifact generation loop
reproduces live ``generate()`` token for token
(tests/test_decode_export.py).

Artifacts:
- prefill: (params, input_ids, attention_mask) -> (last_logits, cache)
- decode:  (params, cache, token, position) -> (logits, new_cache)

Both can carry multi-platform lowering (cpu + tpu) like the rest of
tpudl.export — one artifact, either backend, the property the reference
buys with ONNX.
"""

from __future__ import annotations

from typing import Callable, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

from tpudl.export.export import export_stablehlo, load_exported

# The functional prefill/decode contracts live with the live generation
# loop (one definition — the exported artifacts CANNOT diverge from
# generate()); re-exported here for the serving-side API. The padded-mask
# contract is shared the same way.
from tpudl.models.generate import (  # noqa: F401
    decode_fn,
    prefill_fn,
    validate_left_padded,
)


def export_decoder(
    model,
    params,
    batch_size: int,
    prompt_len: int,
    path_prefix: Optional[str] = None,
    platforms: Optional[Sequence[str]] = None,
    decode_batch_size: Optional[int] = None,
) -> Tuple[bytes, bytes]:
    """Export (prefill, decode) StableHLO artifacts for fixed
    ``batch_size``/``prompt_len`` shapes (static shapes are the serving
    contract — the KV cache is bounded by model.cfg.max_seq_len).

    ``decode_batch_size`` lets the decode program carry a different
    batch than the prefill (the continuous-batching engine prefills one
    request at a time into a slot-batched decode — see
    ``export_serving_decoder``); default: same as ``batch_size``.

    With ``path_prefix``, writes ``{prefix}.prefill.stablehlo`` and
    ``{prefix}.decode.stablehlo``.
    """
    if decode_batch_size is None:
        decode_batch_size = batch_size
    ids = jnp.zeros((batch_size, prompt_len), jnp.int32)
    mask = jnp.ones((batch_size, prompt_len), jnp.int32)
    pf = prefill_fn(model)
    # A real (abstractly-traced) cache example for the decode export, at
    # the decode program's own batch.
    _, cache = jax.eval_shape(
        pf,
        params,
        jnp.zeros((decode_batch_size, prompt_len), jnp.int32),
        jnp.ones((decode_batch_size, prompt_len), jnp.int32),
    )
    cache = jax.tree.map(lambda s: jnp.zeros(s.shape, s.dtype), cache)
    token = jnp.zeros((decode_batch_size,), jnp.int32)
    position = jnp.full((decode_batch_size,), prompt_len, jnp.int32)

    prefill_blob = export_stablehlo(
        pf,
        (params, ids, mask),
        path=f"{path_prefix}.prefill.stablehlo" if path_prefix else None,
        platforms=platforms,
    )
    decode_blob = export_stablehlo(
        decode_fn(model),
        (params, cache, token, position),
        path=f"{path_prefix}.decode.stablehlo" if path_prefix else None,
        platforms=platforms,
    )
    return prefill_blob, decode_blob


def export_serving_decoder(
    model,
    params,
    num_slots: int,
    prompt_len: int,
    path_prefix: Optional[str] = None,
    platforms: Optional[Sequence[str]] = None,
    paged: bool = False,
    page_size: int = 16,
    kv_dtype: Optional[str] = None,
    num_pages: Optional[int] = None,
) -> Tuple[bytes, bytes]:
    """Export the artifact pair the continuous-batching engine serves
    (tpudl.serve): a BATCH-1 prefill (requests are seated one at a
    time) and a batch-``num_slots`` decode (all slots step together).
    ``ServeSession.from_artifacts`` recovers every shape it needs from
    these blobs — no side-channel metadata.

    ``paged=True`` exports the PAGED decode contract instead
    (tpudl.models.generate.paged_decode_fn): the cache input is the
    page-pool pytree and three host-owned addressing arrays (page
    table, start, lens) ride as extra traced inputs — seating/freeing
    against the deserialized program never recompiles, exactly like
    the live path. ``page_size``/``kv_dtype``/``num_pages`` fix the
    exported pool geometry (a PagedKVCache at the same settings);
    ``from_artifacts`` reads it all back from the avals."""
    if not paged:
        return export_decoder(
            model, params, 1, prompt_len,
            path_prefix=path_prefix, platforms=platforms,
            decode_batch_size=num_slots,
        )
    from tpudl.models.generate import paged_decode_fn
    from tpudl.serve.cache import PagedKVCache

    pf = prefill_fn(model)
    ids = jnp.zeros((1, prompt_len), jnp.int32)
    mask = jnp.ones((1, prompt_len), jnp.int32)
    _, template = jax.eval_shape(
        pf,
        params,
        jnp.zeros((num_slots, prompt_len), jnp.int32),
        jnp.ones((num_slots, prompt_len), jnp.int32),
    )
    cache = PagedKVCache(
        template, page_size=page_size, num_pages=num_pages,
        kv_dtype=kv_dtype,
    )
    token = jnp.zeros((num_slots,), jnp.int32)
    position = jnp.full((num_slots,), prompt_len, jnp.int32)
    prefill_blob = export_stablehlo(
        pf,
        (params, ids, mask),
        path=f"{path_prefix}.prefill.stablehlo" if path_prefix else None,
        platforms=platforms,
    )
    decode_blob = export_stablehlo(
        paged_decode_fn(model, cache.page_size, cache.quantized),
        (params, cache.cache, token, position, *cache.dispatch_args()),
        path=f"{path_prefix}.decode.stablehlo" if path_prefix else None,
        platforms=platforms,
    )
    return prefill_blob, decode_blob


def generate_with_exported(
    prefill_call: Callable,
    decode_call: Callable,
    params,
    input_ids: jax.Array,
    attention_mask: Optional[jax.Array] = None,
    max_new_tokens: int = 32,
    eos_id: Optional[int] = None,
    max_seq_len: Optional[int] = None,
    eos_check_every: int = 8,
) -> jax.Array:
    """Greedy generation driven entirely by deserialized artifacts — the
    session.run loop of the reference, over StableHLO. Ragged prompt
    batches ride LEFT-padded through ``attention_mask`` (0 = pad; same
    contract as tpudl.models.generate — the exported cache carries the
    per-slot validity mask, so padded rows reproduce their unpadded
    tokens). Returns [B, max_new_tokens] token ids, eos-padded like
    generate().

    ``max_seq_len`` is the exporting model's KV-cache bound
    (model.cfg.max_seq_len) — the deserialized callables cannot see it,
    and overflowing it would silently CLAMP cache writes to the last slot
    (corrupted tokens, no error). Always pass it on serving paths.

    ``eos_check_every`` paces the all-rows-done early-exit readback
    (same contract as ``generate()``): the check is a blocking host
    sync, so it runs after the first token (catching the
    finished-at-token-1 batch for free) and then once per
    ``eos_check_every`` tokens — NOT per token, which would serialize
    the otherwise-async decode dispatches on relay-attached devices.
    """
    b, s = input_ids.shape
    if eos_check_every < 1:
        raise ValueError(
            f"eos_check_every must be >= 1, got {eos_check_every}"
        )
    if max_seq_len is not None and s + max_new_tokens > max_seq_len:
        raise ValueError(
            f"prompt ({s}) + max_new_tokens ({max_new_tokens}) exceeds the "
            f"exporting model's KV-cache bound max_seq_len={max_seq_len}"
        )
    if attention_mask is None:
        mask = jnp.ones_like(input_ids)
    else:
        mask = attention_mask
        validate_left_padded(mask)
    logits, cache = prefill_call(params, input_ids, mask)
    position = jnp.sum(mask, axis=-1).astype(jnp.int32)
    token = jnp.argmax(logits, axis=-1).astype(jnp.int32)
    done = jnp.zeros((b,), bool)
    tokens = []
    for i in range(max_new_tokens):
        if eos_id is not None:
            token = jnp.where(done, eos_id, token)
            done = jnp.logical_or(done, token == eos_id)
        tokens.append(token)
        if i + 1 == max_new_tokens:
            break
        if (
            eos_id is not None
            and (i == 0 or (i + 1) % eos_check_every == 0)
            and bool(done.all())
        ):
            # Every row finished: the remaining positions are eos by
            # contract — emit them without paying a dead decode dispatch
            # per token (a batch that finishes at token 1 used to scan
            # all remaining steps; tests/test_decode_export.py asserts
            # the decode-call count).
            break
        logits, cache = decode_call(params, cache, token, position)
        position = position + 1
        token = jnp.argmax(logits, axis=-1).astype(jnp.int32)
    out = jnp.stack(tokens, axis=1)
    if out.shape[1] < max_new_tokens:
        pad = jnp.full(
            (b, max_new_tokens - out.shape[1]), eos_id, out.dtype
        )
        out = jnp.concatenate([out, pad], axis=1)
    return out


def load_decoder(
    prefill_blob_or_path, decode_blob_or_path
) -> Tuple[Callable, Callable]:
    """Deserialize the (prefill, decode) artifact pair."""
    return (
        load_exported(prefill_blob_or_path),
        load_exported(decode_blob_or_path),
    )
