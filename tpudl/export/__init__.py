"""L4 export/serve: StableHLO export, cross-backend parity, latency bench.

The reference's signature behavior (SURVEY.md §0): serialize a model to
multiple formats (ONNX / TorchScript / pickle — reference
notebooks/cv/onnx_experiments.py:33-42,198,206-215), run it on multiple
backends (ONNX Runtime / OpenVINO — :77-140), compare outputs numerically
(:142-144) and report latency (:104,140). Rebuilt TPU-native: one jaxpr
lowered to CPU-XLA and TPU-XLA plays the "two independent backends compiled
from one artifact" role; jax.export/StableHLO is the serialization format.
"""

from tpudl.export.export import (  # noqa: F401
    artifact_sizes,
    export_stablehlo,
    load_exported,
    load_exported_obj,
    load_params,
    save_params,
)
from tpudl.export.parity import ParityReport, assert_parity, check_parity  # noqa: F401
from tpudl.export.latency import latency_benchmark  # noqa: F401
