"""Inference latency benchmark harness.

Fixes the measurement-design flaws of the reference's harness
(reference notebooks/cv/onnx_experiments.py:90-104,130-139 — cold calls
timed, host transfer inside the latency window, OpenVINO "mean" over a
single sample, `latency` mutated as a closure global):
- warmup iterations excluded;
- host->device transfer timed separately from compute;
- percentiles, not just the mean;
- every timing window closed by a scalar host readback (required for
  correctness on relay-attached devices where block_until_ready can
  return early — see .claude/skills/verify/SKILL.md).
"""

from __future__ import annotations

import time
from typing import Any, Callable, Optional, Sequence

import jax
import numpy as np


def _sync(out) -> float:
    """Force completion of `out` via scalar readbacks — one element per
    leaf, so every transfer/computation in the tree is fenced while only
    single elements cross to the host (never a full device-to-host copy).
    """
    total = 0.0
    for leaf in jax.tree.leaves(out):
        if isinstance(leaf, jax.Array):
            total += float(leaf.ravel()[0])
        else:
            total += float(np.asarray(leaf).ravel()[0])
    return total


def latency_benchmark(
    fn: Callable,
    host_args: Sequence[Any],
    device: Optional[jax.Device] = None,
    warmup: int = 5,
    iters: int = 30,
) -> dict:
    """Benchmark `fn` with transfer and compute measured separately."""
    if iters < 1:
        raise ValueError(f"iters must be >= 1, got {iters}")
    if warmup < 0:
        raise ValueError(f"warmup must be >= 0, got {warmup}")
    if device is None:
        device = jax.devices()[0]
    jitted = jax.jit(fn)

    # --- transfer: host -> device, timed per iteration; windows closed by
    # scalar readback, not block_until_ready (module docstring doctrine —
    # block_until_ready can return early on relay-attached devices) ---
    transfer_ms = []
    for _ in range(warmup):
        placed = jax.tree.map(lambda a: jax.device_put(a, device), tuple(host_args))
        _sync(placed)
    for _ in range(iters):
        t0 = time.perf_counter()
        placed = jax.tree.map(lambda a: jax.device_put(a, device), tuple(host_args))
        _sync(placed)
        transfer_ms.append((time.perf_counter() - t0) * 1e3)

    # --- compute: device-resident args, synced by scalar readback ---
    # warmup=0 means the first timed iteration includes compilation.
    out = None
    for _ in range(warmup):
        out = jitted(*placed)
    if out is not None:
        _sync(out)
    compute_ms = []
    for _ in range(iters):
        t0 = time.perf_counter()
        out = jitted(*placed)
        _sync(out)
        compute_ms.append((time.perf_counter() - t0) * 1e3)

    def stats(xs):
        # Tail percentiles alongside the legacy keys: serving SLOs are
        # quoted at p99, and a mean/min pair hides exactly the outliers
        # that matter. Only post-warmup iterations ever enter `xs` (the
        # warmup loops above run outside the timed windows), so these
        # are steady-state statistics.
        xs = np.asarray(xs)
        return {
            "mean_ms": float(xs.mean()),
            "p50_ms": float(np.percentile(xs, 50)),
            "p95_ms": float(np.percentile(xs, 95)),
            "p99_ms": float(np.percentile(xs, 99)),
            "min_ms": float(xs.min()),
            "max_ms": float(xs.max()),
        }

    return {
        "device": str(device),
        "iters": iters,
        "warmup": warmup,
        "transfer": stats(transfer_ms),
        "compute": stats(compute_ms),
    }
