"""Inference latency benchmark harness.

Fixes the measurement-design flaws of the reference's harness
(reference notebooks/cv/onnx_experiments.py:90-104,130-139 — cold calls
timed, host transfer inside the latency window, OpenVINO "mean" over a
single sample, `latency` mutated as a closure global):
- warmup iterations excluded;
- host->device transfer timed separately from compute;
- percentiles, not just the mean;
- every timing window closed by a scalar host readback (required for
  correctness on relay-attached devices where block_until_ready can
  return early — see .claude/skills/verify/SKILL.md).
"""

from __future__ import annotations

import dataclasses
import time
from typing import Any, Callable, Optional, Sequence

import jax
import numpy as np


@dataclasses.dataclass(frozen=True)
class LatencyStats:
    """Percentile summary of one timing series (milliseconds) — the ONE
    definition of "p50/p95/p99/max" every benchmark consumes
    (``latency_benchmark`` below, ``benchmarks/serve_load.py``,
    ``benchmarks/parity_grid.py``) instead of each hand-rolling its own
    np.percentile calls. Only post-warmup samples should ever enter:
    serving SLOs are quoted at tail percentiles, and a mean/min pair
    hides exactly the outliers that matter."""

    count: int
    mean_ms: float
    p50_ms: float
    p95_ms: float
    p99_ms: float
    min_ms: float
    max_ms: float

    @classmethod
    def from_ms(cls, samples_ms: Sequence[float]) -> "LatencyStats":
        xs = np.asarray(samples_ms, dtype=np.float64)
        if xs.size == 0:
            raise ValueError(
                "LatencyStats needs at least one sample (callers decide "
                "how to render an empty series)"
            )
        return cls(
            count=int(xs.size),
            mean_ms=float(xs.mean()),
            p50_ms=float(np.percentile(xs, 50)),
            p95_ms=float(np.percentile(xs, 95)),
            p99_ms=float(np.percentile(xs, 99)),
            min_ms=float(xs.min()),
            max_ms=float(xs.max()),
        )

    @classmethod
    def from_seconds(cls, samples_s: Sequence[float]) -> "LatencyStats":
        return cls.from_ms(np.asarray(samples_s, dtype=np.float64) * 1e3)

    def as_dict(self) -> dict:
        """The legacy ``latency_benchmark`` stats schema (mean/p50/p95/
        p99/min/max, no count — existing consumers key on exactly
        these)."""
        return {
            "mean_ms": self.mean_ms,
            "p50_ms": self.p50_ms,
            "p95_ms": self.p95_ms,
            "p99_ms": self.p99_ms,
            "min_ms": self.min_ms,
            "max_ms": self.max_ms,
        }

    def percentiles(self, digits: int = 3) -> dict:
        """The serving-benchmark tail summary ({p50,p95,p99}_ms,
        rounded) — benchmarks/serve_load.py's per-request TTFT/TPOT
        rendering."""
        return {
            "p50_ms": round(self.p50_ms, digits),
            "p95_ms": round(self.p95_ms, digits),
            "p99_ms": round(self.p99_ms, digits),
        }


def _sync(out) -> float:
    """Force completion of `out` via scalar readbacks — one element per
    leaf, so every transfer/computation in the tree is fenced while only
    single elements cross to the host (never a full device-to-host copy).
    """
    total = 0.0
    for leaf in jax.tree.leaves(out):
        if isinstance(leaf, jax.Array):
            total += float(leaf.ravel()[0])
        else:
            total += float(np.asarray(leaf).ravel()[0])
    return total


def latency_benchmark(
    fn: Callable,
    host_args: Sequence[Any],
    device: Optional[jax.Device] = None,
    warmup: int = 5,
    iters: int = 30,
) -> dict:
    """Benchmark `fn` with transfer and compute measured separately."""
    if iters < 1:
        raise ValueError(f"iters must be >= 1, got {iters}")
    if warmup < 0:
        raise ValueError(f"warmup must be >= 0, got {warmup}")
    if device is None:
        device = jax.devices()[0]
    jitted = jax.jit(fn)

    # --- transfer: host -> device, timed per iteration; windows closed by
    # scalar readback, not block_until_ready (module docstring doctrine —
    # block_until_ready can return early on relay-attached devices) ---
    transfer_ms = []
    for _ in range(warmup):
        placed = jax.tree.map(lambda a: jax.device_put(a, device), tuple(host_args))
        _sync(placed)
    for _ in range(iters):
        t0 = time.perf_counter()
        placed = jax.tree.map(lambda a: jax.device_put(a, device), tuple(host_args))
        _sync(placed)
        transfer_ms.append((time.perf_counter() - t0) * 1e3)

    # --- compute: device-resident args, synced by scalar readback ---
    # warmup=0 means the first timed iteration includes compilation.
    out = None
    for _ in range(warmup):
        out = jitted(*placed)
    if out is not None:
        _sync(out)
    compute_ms = []
    for _ in range(iters):
        t0 = time.perf_counter()
        out = jitted(*placed)
        _sync(out)
        compute_ms.append((time.perf_counter() - t0) * 1e3)

    # Only post-warmup iterations ever enter the series (the warmup
    # loops above run outside the timed windows), so these are
    # steady-state statistics.
    return {
        "device": str(device),
        "iters": iters,
        "warmup": warmup,
        "transfer": LatencyStats.from_ms(transfer_ms).as_dict(),
        "compute": LatencyStats.from_ms(compute_ms).as_dict(),
    }
