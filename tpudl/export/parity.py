"""Cross-backend numerical parity harness.

The reference's only correctness verification is comparing OpenVINO output
against ONNX Runtime output with np.allclose(rtol=1e-05, atol=1e-04)
(reference notebooks/cv/onnx_experiments.py:142-144) — two independent
backends compiled from one artifact. TPU-native analog: one function run on
CPU-XLA and TPU-XLA and compared at the same tolerances (SURVEY.md §3.3).

TPU-specific reality the reference never faced: f32 matmuls ride the MXU at
bf16 input precision by default, so the reference's f32 tolerances only
hold under ``jax.default_matmul_precision('highest')``. The harness exposes
both modes:
- strict=True  — HIGHEST matmul precision, reference tolerances
                 (rtol=1e-5, atol=1e-4): verifies the math.
- strict=False — deployment precision (bf16 MXU), loose tolerances
                 (rtol=2e-2, atol=2e-2): verifies the deployed artifact.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Optional, Sequence

import jax
import numpy as np

#: f32 tolerances from reference notebooks/cv/onnx_experiments.py:144.
STRICT_RTOL, STRICT_ATOL = 1e-5, 1e-4
#: bf16-MXU deployment tolerances.
DEPLOY_RTOL, DEPLOY_ATOL = 2e-2, 2e-2


@dataclasses.dataclass
class ParityReport:
    ok: bool
    rtol: float
    atol: float
    backend_a: str
    backend_b: str
    max_abs_err: float
    max_rel_err: float
    num_outputs: int

    def __str__(self):
        status = "PASS" if self.ok else "FAIL"
        return (
            f"parity {status}: {self.backend_a} vs {self.backend_b} "
            f"rtol={self.rtol} atol={self.atol} "
            f"max_abs={self.max_abs_err:.3e} max_rel={self.max_rel_err:.3e}"
        )


def _run_on(fn: Callable, args, device: jax.Device):
    # default_device so closure-captured constants (e.g. model params) follow
    # the target backend instead of pinning the computation to where they
    # were created; args are placed explicitly.
    with jax.default_device(device):
        placed = jax.tree.map(lambda a: jax.device_put(a, device), tuple(args))
        out = jax.jit(fn)(*placed)
    return jax.tree.map(np.asarray, out)


def compare_outputs(
    out_a: Any,
    out_b: Any,
    rtol: float,
    atol: float,
    backend_a: str = "a",
    backend_b: str = "b",
) -> ParityReport:
    """Numerically compare two output pytrees leaf-by-leaf."""
    leaves_a = jax.tree.leaves(out_a)
    leaves_b = jax.tree.leaves(out_b)
    ok = len(leaves_a) == len(leaves_b)
    max_abs = 0.0
    max_rel = 0.0
    for a, b in zip(leaves_a, leaves_b):
        a64 = np.asarray(a, np.float64)
        b64 = np.asarray(b, np.float64)
        abs_err = np.abs(a64 - b64)
        max_abs = max(max_abs, float(abs_err.max(initial=0.0)))
        denom = np.abs(b64) + 1e-12
        max_rel = max(max_rel, float((abs_err / denom).max(initial=0.0)))
        if not np.allclose(a64, b64, rtol=rtol, atol=atol):
            ok = False
    return ParityReport(
        ok=ok,
        rtol=rtol,
        atol=atol,
        backend_a=backend_a,
        backend_b=backend_b,
        max_abs_err=max_abs,
        max_rel_err=max_rel,
        num_outputs=len(leaves_a),
    )


def check_parity(
    fn: Callable,
    args: Sequence[Any],
    device_a: Optional[jax.Device] = None,
    device_b: Optional[jax.Device] = None,
    rtol: Optional[float] = None,
    atol: Optional[float] = None,
    strict: bool = True,
) -> ParityReport:
    """Run `fn(*args)` on two backends and compare outputs numerically."""
    if device_a is None:
        device_a = jax.devices()[0]
    if device_b is None:
        device_b = jax.devices("cpu")[0]
    if rtol is None:
        rtol = STRICT_RTOL if strict else DEPLOY_RTOL
    if atol is None:
        atol = STRICT_ATOL if strict else DEPLOY_ATOL

    if strict:
        with jax.default_matmul_precision("highest"):
            out_a = _run_on(fn, args, device_a)
            out_b = _run_on(fn, args, device_b)
    else:
        out_a = _run_on(fn, args, device_a)
        out_b = _run_on(fn, args, device_b)

    return compare_outputs(
        out_a,
        out_b,
        rtol,
        atol,
        backend_a=str(device_a.platform),
        backend_b=str(device_b.platform),
    )


def assert_parity(fn, args, **kwargs) -> ParityReport:
    report = check_parity(fn, args, **kwargs)
    if not report.ok:
        raise AssertionError(str(report))
    return report
