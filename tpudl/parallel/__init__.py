"""Parallelism: sharding rules, activation constraints, pipeline schedule."""

from tpudl.parallel.pipeline import (  # noqa: F401
    PIPELINE_RULES,
    pipeline,
    stack_layer_params,
    stack_pytrees,
    stage_param_spec,
)
from tpudl.parallel.sharding import (  # noqa: F401
    Rules,
    active_mesh,
    constrain,
    current_mesh,
    param_shardings,
    spec_for_path,
    tree_shardings,
)
