"""Parallelism: sharding rules, activation constraints, pipeline
schedules (GPipe / 1F1B / interleaved virtual stages), overlap-friendly
bucketed gradient accumulation."""

from tpudl.parallel.overlap import (  # noqa: F401
    accumulate as bucketed_accumulate,
    bucket_assignment,
    bucket_bytes_from_env,
)
from tpudl.parallel.pipeline import (  # noqa: F401
    PIPELINE_RULES,
    interleave_stage_order,
    pipeline,
    pipeline_1f1b,
    pipeline_interleaved,
    schedule_stats,
    stack_layer_params,
    stack_pytrees,
    stage_param_spec,
)
from tpudl.parallel.sharding import (  # noqa: F401
    Rules,
    active_mesh,
    constrain,
    current_mesh,
    param_shardings,
    spec_for_path,
    tree_shardings,
)
