"""Parallelism: sharding rules and activation constraints over the mesh."""

from tpudl.parallel.sharding import (  # noqa: F401
    Rules,
    active_mesh,
    constrain,
    current_mesh,
    param_shardings,
    spec_for_path,
    tree_shardings,
)
