"""Overlap-friendly gradient accumulation: bucketed reduction boundaries.

Under GSPMD the cross-device gradient reduction is not a framework hook —
it is psum/reduce-scatter ops XLA places inside the compiled step
(tpudl.runtime.mesh module docstring). With ``accum_steps > 1`` the
accumulation scan adds each microbatch's gradient tree into the carry,
and XLA is free to fuse the whole add (and the reductions feeding it)
into one monolithic end-of-microbatch group — serializing the entire
gradient sync behind the entire backward pass.

This module restructures that accumulation the way ZeRO/Horovod-style
stacks bucket their allreduces: gradient leaves are assigned to
fixed-size buckets **in param-tree traversal order** (backward produces
late-layer gradients first, so traversal-order buckets complete at
different times), and each bucket's add is wrapped in its own
``lax.optimization_barrier``. The barrier is an identity on values —
bit-for-bit parity with the plain add — but it forbids XLA from fusing
across bucket boundaries, so each bucket's reduction is a separable
dependency group the scheduler can start (and overlap with the
remaining backward compute) as soon as that bucket's gradients exist.

Knob: ``TPUDL_OVERLAP_BUCKET_MB`` — bucket size in MiB (default 4).
``0`` disables bucketing entirely. Bucketing also auto-disables when
the active mesh has a single batch shard (no cross-device reduction to
overlap — the barriers would only cost fusion opportunities).

Observability: when a span recorder is active, tracing a bucketed
accumulation sets the ``overlap_buckets`` gauge (bucket count of the
compiled step).
"""

from __future__ import annotations

from tpudl.analysis.registry import env_float
from typing import List, Optional, Sequence

import jax

#: Default bucket size, bytes. 4 MiB ≈ one BERT-base encoder layer's
#: largest kernel (1024x3072 f32) — small enough that several buckets
#: exist per layer group, large enough that per-bucket latency is not
#: launch-overhead-bound.
DEFAULT_BUCKET_BYTES = 4 << 20

_ENV_KNOB = "TPUDL_OVERLAP_BUCKET_MB"


def bucket_bytes_from_env(default: Optional[int] = None) -> Optional[int]:
    """Resolve the bucket size: ``TPUDL_OVERLAP_BUCKET_MB`` wins, else
    ``default`` (None -> DEFAULT_BUCKET_BYTES). Returns None when the
    knob disables bucketing (``0``)."""
    mb = env_float(_ENV_KNOB)
    if mb is not None:
        if mb <= 0:
            return None
        return int(mb * (1 << 20))
    if default is None:
        return DEFAULT_BUCKET_BYTES
    return int(default)


def bucket_assignment(
    leaves: Sequence, bucket_bytes: int
) -> List[List[int]]:
    """Assign leaf indices to buckets in traversal order.

    Greedy: a bucket closes once its cumulative byte size reaches
    ``bucket_bytes``. A single leaf larger than the budget gets its own
    bucket (never split — a leaf is the reduction granularity XLA
    sees). Deterministic in the tree's traversal order, so the compiled
    program is stable across runs.
    """
    if bucket_bytes <= 0:
        raise ValueError(f"bucket_bytes must be > 0, got {bucket_bytes}")
    buckets: List[List[int]] = []
    current: List[int] = []
    current_bytes = 0
    for idx, leaf in enumerate(leaves):
        size = int(getattr(leaf, "size", 1))
        itemsize = int(getattr(getattr(leaf, "dtype", None), "itemsize", 4))
        nbytes = size * itemsize
        if current and current_bytes + nbytes > bucket_bytes:
            buckets.append(current)
            current = []
            current_bytes = 0
        current.append(idx)
        current_bytes += nbytes
    if current:
        buckets.append(current)
    return buckets


def _batch_shards() -> int:
    """Batch-shard count of the active mesh (1 outside any mesh)."""
    from tpudl.parallel.sharding import current_mesh

    mesh = current_mesh()
    n = 1
    if mesh is not None:
        for ax in ("dp", "fsdp"):
            if ax in mesh.shape:
                n *= mesh.shape[ax]
    return n


def accumulate(acc, new, bucket_bytes: Optional[int] = None):
    """``acc + new`` over a gradient pytree, with per-bucket
    optimization barriers when overlap bucketing is enabled.

    Bit-for-bit identical to ``jax.tree.map(jnp.add, acc, new)`` — the
    barrier is an identity; only the compiled schedule changes. Called
    at trace time inside the accumulation scan body.

    Precedence: an explicit ``bucket_bytes`` wins (``<= 0`` disables);
    else the ``TPUDL_OVERLAP_BUCKET_MB`` knob (``0`` disables); else
    the default bucket size applies — but only when the active mesh
    splits the batch over more than one device (without cross-device
    reductions there is nothing to overlap, and the barriers would
    only cost fusion opportunities).
    """
    if bucket_bytes is not None:
        resolved = int(bucket_bytes)
        if resolved <= 0:
            return jax.tree.map(jax.numpy.add, acc, new)
    else:
        mb = env_float(_ENV_KNOB)
        if mb is not None:
            if mb <= 0:
                return jax.tree.map(jax.numpy.add, acc, new)
            resolved = int(mb * (1 << 20))
        elif _batch_shards() <= 1:
            return jax.tree.map(jax.numpy.add, acc, new)
        else:
            resolved = DEFAULT_BUCKET_BYTES

    leaves_acc, treedef = jax.tree.flatten(acc)
    leaves_new = jax.tree.leaves(new)
    if len(leaves_acc) != len(leaves_new):
        raise ValueError(
            f"accumulate: tree mismatch ({len(leaves_acc)} vs "
            f"{len(leaves_new)} leaves)"
        )
    buckets = bucket_assignment(leaves_acc, resolved)

    from tpudl.obs import counters as obs_counters
    from tpudl.obs import spans as obs_spans

    if obs_spans.active_recorder() is not None:
        obs_counters.registry().gauge("overlap_buckets").set(len(buckets))

    out: List = [None] * len(leaves_acc)
    for bucket in buckets:
        summed = tuple(
            leaves_acc[i] + leaves_new[i] for i in bucket
        )
        summed = jax.lax.optimization_barrier(summed)
        for i, v in zip(bucket, summed):
            out[i] = v
    return jax.tree.unflatten(treedef, out)
