"""Sharding-rule engine: map parameter paths to PartitionSpecs.

The reference lineage distributes by wrapping the model in Horovod /
DistributedDataParallel hooks (SURVEY.md §2.3 — absent from the reference
tree itself). The TPU-native design is declarative instead: a list of
``(path_regex, PartitionSpec)`` rules assigns every parameter a sharding
over the named mesh (tpudl.runtime.mesh.MESH_AXES); pjit/GSPMD then emits
the ICI collectives. Strategy presets (DP / FSDP / TP) are just different
rule lists.
"""

from __future__ import annotations

import contextlib
import threading
from typing import Any, Optional, Sequence, Tuple

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec

from tpudl import rules as rules_engine

P = PartitionSpec

#: A rule list: first regex (searched, not fullmatch) wins.
Rules = Sequence[Tuple[str, PartitionSpec]]

#: Fully-replicated default.
REPLICATED = P()

#: Canonical keypath -> "a/b/kernel" conversion now lives in the shared
#: rules engine (tpudl.rules); kept under the historical name for the
#: call sites (quant, tests) that import it from here.
_path_str = rules_engine.path_str


def spec_for_path(
    path: str, rules: Optional[Rules], shape: Sequence[int] = ()
) -> PartitionSpec:
    """First matching rule wins (tpudl.rules.first_match — the shared
    resolution primitive). A rule's spec may be a PartitionSpec or a
    callable ``shape -> PartitionSpec`` (for rank-dependent placement,
    e.g. conv vs dense kernels under FSDP). No match replicates — the
    legacy default; ``tpudl.rules.match_partition_rules`` is the
    coverage-checked adapter."""
    spec = rules_engine.first_match(rules, path)
    if spec is rules_engine.NO_MATCH:
        return REPLICATED
    return spec(shape) if callable(spec) else spec


def _clamp_entries(mesh: Mesh, spec: PartitionSpec, shape) -> PartitionSpec:
    """Truncate a spec to the array rank and unshard any dimension whose size
    the named mesh axes don't divide — keeps one rule list usable across
    full-size and tiny-test configurations."""
    entries = list(spec)[: len(shape)]
    fixed = []
    for dim, entry in enumerate(entries):
        if entry is None:
            fixed.append(None)
            continue
        names = entry if isinstance(entry, tuple) else (entry,)
        size = 1
        for n in names:
            size *= mesh.shape[n]
        fixed.append(entry if shape[dim] % size == 0 else None)
    return P(*fixed)


def tree_shardings(
    mesh: Mesh, tree: Any, rules: Optional[Rules] = None
) -> Any:
    """NamedSharding pytree for `tree` by matching paths against `rules`,
    with per-dimension divisibility clamping (see _clamp_entries)."""

    def one(path, leaf):
        shape = getattr(leaf, "shape", ())
        spec = spec_for_path(_path_str(path), rules, shape)
        return NamedSharding(mesh, _clamp_entries(mesh, spec, shape))

    return jax.tree_util.tree_map_with_path(one, tree)


def param_shardings(mesh: Mesh, params: Any, rules: Optional[Rules] = None) -> Any:
    return tree_shardings(mesh, params, rules)


def host_to_global_array(x: Any, sharding: "jax.sharding.Sharding"):
    """Place a host value onto ``sharding`` even when the sharding spans
    NON-addressable devices (a multi-process mesh), where plain
    ``jax.device_put`` refuses host inputs.

    ``x`` is interpreted as the GLOBAL value; each process materializes
    only its addressable shards (``jax.make_array_from_callback``) — the
    multi-process placement path for replicated train state, rng keys,
    and checkpoint-restored leaves. Scalars/ints go through
    ``jnp.asarray`` first so weak-typing matches what device_put would
    have produced (a Python int stays int32, not numpy's int64).
    """
    if sharding.is_fully_addressable:
        return jax.device_put(x, sharding)
    import numpy as np

    if not isinstance(x, (np.ndarray, jax.Array)):
        x = jax.numpy.asarray(x)
    if isinstance(x, jax.Array) and not x.is_fully_addressable:
        raise ValueError(
            "host_to_global_array needs a host value or fully-"
            f"addressable array; got a global array sharded as "
            f"{x.sharding}"
        )
    arr = np.asarray(x)
    return jax.make_array_from_callback(
        arr.shape, sharding, lambda idx: arr[idx]
    )


# ---------------------------------------------------------------------------
# Activation-sharding constraints.
#
# Model code calls ``constrain(x, ('dp','fsdp'), 'sp', None)`` on hot
# activations. Outside any mesh context this is a no-op, so models run
# unmodified on a single device.
# ---------------------------------------------------------------------------

_ctx = threading.local()


def current_mesh() -> Optional[Mesh]:
    return getattr(_ctx, "mesh", None)


@contextlib.contextmanager
def active_mesh(mesh: Optional[Mesh]):
    prev = current_mesh()
    _ctx.mesh = mesh
    try:
        yield mesh
    finally:
        _ctx.mesh = prev


def constrain(x: jax.Array, *spec_entries) -> jax.Array:
    """with_sharding_constraint against the active mesh (no-op without one).

    Entries naming mesh axes whose size doesn't divide the corresponding
    array dimension are dropped, so the same model code serves full-scale
    and tiny-test shapes.
    """
    mesh = current_mesh()
    if mesh is None:
        return x
    spec = _clamp_entries(mesh, P(*spec_entries), x.shape)
    return jax.lax.with_sharding_constraint(x, NamedSharding(mesh, spec))


# ---------------------------------------------------------------------------
# Strategy presets (SURVEY.md §2.3 checklist).
# ---------------------------------------------------------------------------

#: Pure data parallelism: every parameter replicated.
DP_RULES: Rules = ()


def _fsdp_largest_dim(shape) -> PartitionSpec:
    """Shard the largest dimension over the fsdp axis (rank-agnostic: for a
    (kh, kw, in, out) conv kernel this picks the channel dim, not kh)."""
    if not shape:
        return REPLICATED
    largest = max(range(len(shape)), key=lambda d: shape[d])
    entries = [None] * len(shape)
    entries[largest] = "fsdp"
    return P(*entries)


#: FSDP / ZeRO-3-style: shard the largest dim of every weight over the fsdp
#: axis; XLA all-gathers per layer and reduce-scatters grads.
FSDP_RULES: Rules = (
    (r"embedding$", P("fsdp", None)),
    (r"kernel$", _fsdp_largest_dim),
)

#: Tensor parallelism for transformer blocks (megatron-style column/row
#: split), composed with fsdp on the other dim.
TP_TRANSFORMER_RULES: Rules = (
    (r"(query|key|value|q_proj|k_proj|v_proj)/kernel$", P("fsdp", "tp")),
    (r"(out|o_proj|attention_output)/kernel$", P("tp", "fsdp")),
    (r"(intermediate|wi|up_proj|gate_proj|mlp_in)/kernel$", P("fsdp", "tp")),
    (r"(output|wo|down_proj|mlp_out)/kernel$", P("tp", "fsdp")),
    (r"(embedding|word_embeddings)/embedding$", P("tp", "fsdp")),
    (r"kernel$", P("fsdp", None)),
)


def strategy_rules(strategy: str) -> Rules:
    """TrainConfig.strategy -> the sharding rule set it names (the
    round-2 'dead config field' is now load-bearing: notebooks pass
    ``strategy_rules(cfg.strategy)`` to compile_step)."""
    if strategy == "dp":
        return DP_RULES
    if strategy == "fsdp":
        return FSDP_RULES
    if strategy in ("tp", "fsdp+tp"):
        return TP_TRANSFORMER_RULES
    if strategy == "lora":
        from tpudl.models.lora import LORA_RULES, compose_rules

        return compose_rules(LORA_RULES, TP_TRANSFORMER_RULES)
    if strategy == "pp":
        from tpudl.parallel.pipelined_bert import PIPELINED_BERT_RULES

        return PIPELINED_BERT_RULES
    if strategy == "pp+fsdp":
        from tpudl.parallel.pipelined_bert import PIPELINED_BERT_FSDP_RULES

        return PIPELINED_BERT_FSDP_RULES
    raise ValueError(
        f"unknown strategy {strategy!r}; expected dp | fsdp | tp | "
        f"fsdp+tp | lora | pp | pp+fsdp"
    )
