"""Pipeline parallelism over the ``pp`` mesh axis (GPipe microbatching).

The reference lineage has no pipeline story (SURVEY.md §2.3 marks PP absent
from the reference tree; the only parallelism the north-star names is the
Horovod-style data-parallel launch path). This module makes layer-pipelined
training first-class the TPU way: no process-rank send/recv loops — one
SPMD program under ``shard_map`` where each ``pp`` mesh slot runs its stage
and activations hop exactly one ICI neighbor per tick via ``ppermute``.

Schedule: classic GPipe. The batch splits into M microbatches; a pipeline
of S stages runs ``M + S - 1`` ticks (a ``lax.scan``, so the whole schedule
is one compiled XLA loop and is reverse-differentiable — backward replays
the ring with the transposed permutation). Bubble fraction is
``(S-1)/(M+S-1)``: pick ``num_microbatches >> pp`` to amortize.

Stages must be shape-homogeneous (stage out like stage in) — the usual
transformer-block case. Stage weights live stacked on a leading
``[num_stages, ...]`` dim sharded over ``pp`` (`stack_pytrees` /
`PIPELINE_RULES`), so each device holds only its own stage's weights:
parameter and optimizer memory scale 1/pp. Activation buffers do NOT: the
microbatched input and the output buffer are replicated over ``pp`` (only
stage 0 / the last stage read or write them — the simple-schedule cost;
each is one local batch of activations, small next to the weights).
"""

from __future__ import annotations

from functools import partial
from typing import Any, Callable, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

from tpudl.runtime.mesh import AXIS_PIPE

def stage_param_spec(ndim: int, axis_name: str = AXIS_PIPE) -> P:
    """PartitionSpec for one stacked stage param: leading (stage) dim over
    the pipeline axis, everything else replicated."""
    return P(*([axis_name] + [None] * (ndim - 1)))


#: Sharding rules for stacked stage params: leading (stage) dim over pp.
PIPELINE_RULES = ((r".*", lambda shape: stage_param_spec(len(shape))),)


def stack_pytrees(trees: Sequence[Any]) -> Any:
    """Stack per-stage param trees into one tree with a leading stage dim
    (the layout `pipeline` consumes; shard it P('pp', ...) on dim 0)."""
    return jax.tree.map(lambda *xs: jnp.stack(xs), *trees)


def stack_layer_params(
    params: Any, layer_fmt: str, num_layers: int
) -> Any:
    """Stack the per-layer subtrees ``params[...][layer_fmt.format(i)]``
    into one tree with a leading stage dim.

    ``layer_fmt`` is a '/'-separated path with one ``{}`` placeholder,
    e.g. ``"encoder/layer_{}"`` for tpudl.models.bert parameter trees.
    """

    def lookup(i: int):
        node = params
        for part in layer_fmt.format(i).split("/"):
            node = node[part]
        return node

    return stack_pytrees([lookup(i) for i in range(num_layers)])


def num_ticks(num_stages: int, num_microbatches: int) -> int:
    return num_microbatches + num_stages - 1


def _pipeline_local(
    params: Any,
    x: jax.Array,
    *,
    stage_fn: Callable[[Any, jax.Array], jax.Array],
    axis_name: str,
    num_microbatches: int,
):
    """Per-device GPipe schedule. Runs inside shard_map over `axis_name`.

    params: this stage's weights (a [1, ...]-blocked shard of the stacked
    tree). x: the full [M, mb, ...] microbatched input, replicated over
    the pp axis (only stage 0 reads it).
    """
    # The pp-sharded stacked params arrive as a [1, ...] block per device.
    params = jax.tree.map(lambda p: jnp.squeeze(p, 0), params)
    n = jax.lax.psum(1, axis_name)
    stage = jax.lax.axis_index(axis_name)
    first = stage == 0
    last = stage == n - 1
    m = num_microbatches

    # Forward neighbor ring: stage s sends to s+1; the wrap edge (n-1 -> 0)
    # carries only garbage (tick indices where stage 0 reads fresh input).
    perm = [(i, (i + 1) % n) for i in range(n)]

    out0 = jax.tree.map(jnp.zeros_like, x)
    carry_in0 = jax.tree.map(lambda a: jnp.zeros(a.shape[1:], a.dtype), x)

    def tick(carry, t):
        carry_in, out = carry
        # Stage 0 consumes microbatch t while t < m (clamped index keeps
        # shapes static; the result past m is garbage that never reaches
        # the output buffer of a valid tick).
        ti = jnp.minimum(t, m - 1)
        mb = jax.tree.map(lambda a: a[ti], x)
        stage_in = jax.tree.map(
            lambda a, b: jnp.where(first, a, b), mb, carry_in
        )
        y = stage_fn(params, stage_in)
        # Last stage's output for microbatch t - (n-1) is valid at tick t
        # >= n-1; everyone else writes into a buffer that is masked out of
        # the psum below.
        out_idx = jnp.clip(t - (n - 1), 0, m - 1)
        valid = jnp.logical_and(last, t >= n - 1)

        def write(buf, val):
            prev = jax.lax.dynamic_index_in_dim(buf, out_idx, keepdims=False)
            return jax.lax.dynamic_update_index_in_dim(
                buf, jnp.where(valid, val, prev), out_idx, 0
            )

        out = jax.tree.map(write, out, y)
        carry_next = jax.lax.ppermute(y, axis_name, perm)
        return (carry_next, out), None

    (_, out), _ = jax.lax.scan(
        tick, (carry_in0, out0), jnp.arange(num_ticks(n, m))
    )
    # Only the last stage holds real outputs; broadcast them to every pp
    # slot so downstream (loss, data-parallel reductions) sees the full
    # batch everywhere. Output is activation-sized — one hop around the pp
    # ring, cheap next to the per-tick traffic.
    return jax.tree.map(
        lambda o: jax.lax.psum(
            jnp.where(last, o, jnp.zeros_like(o)), axis_name
        ),
        out,
    )


def pipeline(
    stage_fn: Callable[[Any, Any], Any],
    stacked_params: Any,
    x: Any,
    *,
    num_microbatches: int,
    mesh: Optional[Mesh] = None,
    axis_name: str = AXIS_PIPE,
    batch_spec: P = P(),
) -> Any:
    """Run `x` through a pipeline of stages spread over the `axis_name`
    mesh axis.

    - ``stage_fn(stage_params, x) -> y`` with ``y`` matching ``x``'s
      pytree structure and shapes (homogeneous stages — transformer
      blocks; side inputs like attention masks ride the pytree: pass
      ``(hidden, mask)`` and return ``(new_hidden, mask)``);
    - ``stacked_params``: pytree with leading dim ``num_stages ==
      mesh.shape[axis_name]`` (see `stack_pytrees`), sharded over `pp`;
    - ``x``: pytree of [batch, ...] arrays; batch must divide by
      ``num_microbatches``;
    - ``batch_spec``: PartitionSpec entry for x's batch dim (e.g.
      ``P(('dp','fsdp'))`` when composing with data parallelism — the
      microbatch split then happens per data shard).

    Without a mesh (or with pp=1) this degenerates to sequentially folding
    the stages — numerically identical, so the same model code runs
    single-device.
    """
    from tpudl.parallel.sharding import current_mesh

    if mesh is None:
        mesh = current_mesh()
    n_stages = mesh.shape[axis_name] if mesh is not None else 1
    if n_stages == 1:
        n = jax.tree.leaves(stacked_params)[0].shape[0]
        y = x
        for i in range(n):
            y = stage_fn(jax.tree.map(lambda p: p[i], stacked_params), y)
        return y

    leaves = jax.tree.leaves(x)
    batch = leaves[0].shape[0]
    if any(l.shape[0] != batch for l in leaves):
        raise ValueError(
            f"all x leaves must share the batch dim; got "
            f"{[l.shape for l in leaves]}"
        )
    if batch % num_microbatches != 0:
        raise ValueError(
            f"batch {batch} not divisible by num_microbatches={num_microbatches}"
        )
    leading = jax.tree.leaves(stacked_params)[0].shape[0]
    if leading != n_stages:
        raise ValueError(
            f"stacked_params leading dim {leading} != mesh {axis_name} size "
            f"{n_stages} (one stage per pp slot)"
        )

    mb = batch // num_microbatches
    n_batch_shards = 1
    for entry in batch_spec:
        for ax in entry if isinstance(entry, tuple) else (entry,):
            n_batch_shards *= mesh.shape[ax]
    if mb % n_batch_shards != 0:
        raise ValueError(
            f"microbatch size {mb} (batch {batch} / num_microbatches="
            f"{num_microbatches}) not divisible by the {batch_spec} mesh "
            f"extent {n_batch_shards}"
        )
    xm = jax.tree.map(
        lambda a: a.reshape((num_microbatches, mb) + a.shape[1:]), x
    )

    param_specs = jax.tree.map(
        lambda p: stage_param_spec(p.ndim, axis_name), stacked_params
    )
    # Microbatched input: the original batch dim is now dim 1.
    x_specs = jax.tree.map(
        lambda a: P(None, *batch_spec, *([None] * (a.ndim - 2))), xm
    )

    fn = jax.shard_map(
        partial(
            _pipeline_local,
            stage_fn=stage_fn,
            axis_name=axis_name,
            num_microbatches=num_microbatches,
        ),
        mesh=mesh,
        in_specs=(param_specs, x_specs),
        out_specs=x_specs,
        check_vma=False,
    )
    out = fn(stacked_params, xm)
    return jax.tree.map(
        lambda a: a.reshape((batch,) + a.shape[2:]), out
    )
