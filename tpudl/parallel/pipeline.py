"""Pipeline parallelism over the ``pp`` mesh axis (GPipe microbatching).

The reference lineage has no pipeline story (SURVEY.md §2.3 marks PP absent
from the reference tree; the only parallelism the north-star names is the
Horovod-style data-parallel launch path). This module makes layer-pipelined
training first-class the TPU way: no process-rank send/recv loops — one
SPMD program under ``shard_map`` where each ``pp`` mesh slot runs its stage
and activations hop exactly one ICI neighbor per tick via ``ppermute``.

Schedule: classic GPipe. The batch splits into M microbatches; a pipeline
of S stages runs ``M + S - 1`` ticks (a ``lax.scan``, so the whole schedule
is one compiled XLA loop and is reverse-differentiable — backward replays
the ring with the transposed permutation). Bubble fraction is
``(S-1)/(M+S-1)``: pick ``num_microbatches >> pp`` to amortize.

Stages must be shape-homogeneous (stage out like stage in) — the usual
transformer-block case. Stage weights live stacked on a leading
``[num_stages, ...]`` dim sharded over ``pp`` (`stack_pytrees` /
`PIPELINE_RULES`), so each device holds only its own stage's weights:
parameter and optimizer memory scale 1/pp. Activation buffers do NOT: the
microbatched input and the output buffer are replicated over ``pp`` (only
stage 0 / the last stage read or write them — the simple-schedule cost;
each is one local batch of activations, small next to the weights).
"""

from __future__ import annotations

from functools import partial
from typing import Any, Callable, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

from tpudl.runtime.mesh import AXIS_PIPE, shard_map

def stage_param_spec(ndim: int, axis_name: str = AXIS_PIPE) -> P:
    """PartitionSpec for one stacked stage param: leading (stage) dim over
    the pipeline axis, everything else replicated."""
    return P(*([axis_name] + [None] * (ndim - 1)))


def stage_fsdp_dim(
    shape, fsdp_size: Optional[int] = None
) -> Optional[int]:
    """Which dim of a stacked stage param [pp, lps, ...] to additionally
    shard over fsdp — the ONE source of truth shared by the sharding
    rules (PIPELINED_BERT_FSDP_RULES) and the pipeline's shard_map
    in_specs, which must agree exactly or every step pays a reshard.

    Matrix-shaped leaves (rank >= 4: pp, layer, then >= 2 weight dims)
    shard their largest weight dim; vectors (biases, LayerNorm scales)
    stay replicated — gather traffic would exceed the memory saved.
    With ``fsdp_size`` given (the shard_map in_specs path), dims the
    extent doesn't divide return None; without it (the rules path),
    divisibility is left to tree_shardings' clamp — the two bail out
    under exactly the same condition."""
    if len(shape) < 4:
        return None
    dim = max(range(2, len(shape)), key=lambda d: shape[d])
    if fsdp_size is not None and (
        fsdp_size <= 1 or shape[dim] % fsdp_size != 0
    ):
        return None
    return dim


def stage_param_spec_fsdp(
    shape, fsdp_size: Optional[int], axis_name: str = AXIS_PIPE,
    fsdp_axis: str = "fsdp",
) -> P:
    """stage_param_spec composed with fsdp sharding on stage_fsdp_dim
    (fsdp_size=None = rules path: divisibility left to the clamp)."""
    entries = [axis_name] + [None] * (len(shape) - 1)
    dim = stage_fsdp_dim(shape, fsdp_size)
    if dim is not None:
        entries[dim] = fsdp_axis
    return P(*entries)


#: Sharding rules for stacked stage params: leading (stage) dim over pp.
PIPELINE_RULES = ((r".*", lambda shape: stage_param_spec(len(shape))),)


def stack_pytrees(trees: Sequence[Any]) -> Any:
    """Stack per-stage param trees into one tree with a leading stage dim
    (the layout `pipeline` consumes; shard it P('pp', ...) on dim 0)."""
    return jax.tree.map(lambda *xs: jnp.stack(xs), *trees)


def stack_layer_params(
    params: Any, layer_fmt: str, num_layers: int
) -> Any:
    """Stack the per-layer subtrees ``params[...][layer_fmt.format(i)]``
    into one tree with a leading stage dim.

    ``layer_fmt`` is a '/'-separated path with one ``{}`` placeholder,
    e.g. ``"encoder/layer_{}"`` for tpudl.models.bert parameter trees.
    """

    def lookup(i: int):
        node = params
        for part in layer_fmt.format(i).split("/"):
            node = node[part]
        return node

    return stack_pytrees([lookup(i) for i in range(num_layers)])


def num_ticks(num_stages: int, num_microbatches: int) -> int:
    return num_microbatches + num_stages - 1


def schedule_stats(
    num_stages: int,
    num_microbatches: int,
    schedule: str = "gpipe",
    virtual_stages: int = 1,
) -> dict:
    """Tick/bubble/memory accounting for a pipeline schedule — the
    numbers a capacity plan needs, reported instead of assumed
    (round-4 VERDICT weak #4).

    - ``ticks``: total fwd+bwd stage-op slots on the critical path. Both
      schedules flush, so both run ``2*(M + S - 1)`` slots and share the
      bubble fraction ``(S-1)/(M+S-1)`` — 1F1B is NOT a bubble
      optimization; pick M >> S to amortize.
    - ``stored_microbatch_inputs``: peak per-stage activation residency.
      GPipe holds every in-flight microbatch until its backward —
      ``M + S - 1`` stage inputs saved by the scan — while 1F1B's
      interleaving bounds it by pipeline DEPTH, ``min(S, M)``: the
      reason to reach for 1F1B when activation memory, not compute, is
      the binding constraint.

    ``schedule="interleaved"`` (``pipeline_interleaved``) is the one
    schedule that genuinely SHRINKS the bubble: ``num_stages`` total
    virtual stages spread v = ``virtual_stages`` per device over
    n = S/v devices run ``M*v + n - 1`` chunk-sized ticks, so the
    bubble fraction is (n-1)/(M*v + n-1) — fill amortizes over
    chunk (1/v stage) ticks — at v times the activation-hop traffic.
    """
    s, m = num_stages, num_microbatches
    stats = {
        "schedule": schedule,
        "num_stages": s,
        "num_microbatches": m,
    }
    if schedule == "gpipe":
        stats["ticks"] = 2 * num_ticks(s, m)
        stats["bubble_fraction"] = (s - 1) / (m + s - 1)
        stats["stored_microbatch_inputs"] = m + s - 1
    elif schedule == "1f1b":
        stats["ticks"] = 2 * num_ticks(s, m)
        stats["bubble_fraction"] = (s - 1) / (m + s - 1)
        stats["stored_microbatch_inputs"] = min(s, m)
    elif schedule == "interleaved":
        if s % virtual_stages:
            raise ValueError(
                f"{s} stages not divisible by virtual_stages={virtual_stages}"
            )
        n_dev = s // virtual_stages
        t1 = m * virtual_stages + n_dev - 1
        stats["virtual_stages"] = virtual_stages
        stats["num_devices"] = n_dev
        stats["ticks"] = 2 * t1  # chunk-sized (1/v stage) ticks
        stats["bubble_fraction"] = (n_dev - 1) / t1
        stats["stored_microbatch_inputs"] = t1
    else:
        raise ValueError(f"unknown schedule {schedule!r}")
    return stats


def _prepare_microbatches(
    x: Any, num_microbatches: int, mesh, batch_spec: P, axis_name: str
):
    """Shared schedule prologue: validate the batch pytree, check
    microbatch/batch_spec divisibility, and reshape to [M, mb, ...] with
    matching shard_map specs. ONE implementation for every pipeline
    schedule (gpipe/interleaved) — the validation and reshape rules must
    not drift between them."""
    leaves = jax.tree.leaves(x)
    batch = leaves[0].shape[0]
    if any(l.shape[0] != batch for l in leaves):
        raise ValueError(
            f"all x leaves must share the batch dim; got "
            f"{[l.shape for l in leaves]}"
        )
    if batch % num_microbatches != 0:
        raise ValueError(
            f"batch {batch} not divisible by num_microbatches="
            f"{num_microbatches}"
        )
    mb = batch // num_microbatches
    n_batch_shards = 1
    for entry in batch_spec:
        for ax in entry if isinstance(entry, tuple) else (entry,):
            n_batch_shards *= mesh.shape[ax]
    if mb % n_batch_shards != 0:
        raise ValueError(
            f"microbatch size {mb} (batch {batch} / num_microbatches="
            f"{num_microbatches}) not divisible by the {batch_spec} mesh "
            f"extent {n_batch_shards}"
        )
    xm = jax.tree.map(
        lambda a: a.reshape((num_microbatches, mb) + a.shape[1:]), x
    )
    x_specs = jax.tree.map(
        lambda a: P(None, *batch_spec, *([None] * (a.ndim - 2))), xm
    )
    return batch, xm, x_specs


def _pipeline_local(
    params: Any,
    x: jax.Array,
    *,
    stage_fn: Callable[[Any, jax.Array], jax.Array],
    axis_name: str,
    num_microbatches: int,
    fsdp_dims: Any = None,
    fsdp_axis: str = "fsdp",
):
    """Per-device GPipe schedule. Runs inside shard_map over `axis_name`.

    params: this stage's weights (a [1, ...]-blocked shard of the stacked
    tree). x: the full [M, mb, ...] microbatched input, replicated over
    the pp axis (only stage 0 reads it).

    ``fsdp_dims`` (pytree of int matching params' structure; -1 = leaf
    not fsdp-sharded): ZeRO-style composition — leaves additionally
    sharded over the fsdp mesh axis on that dim are all-gathered here,
    ONCE per step before the tick scan (every tick reuses the same stage
    weights). The gather's transpose is a reduce-scatter, so stage-weight
    gradients come back fsdp-sharded — persistent params + optimizer
    state stay 1/(pp*fsdp).
    """
    # The pp-sharded stacked params arrive as a [1, ...] block per device.
    params = jax.tree.map(lambda p: jnp.squeeze(p, 0), params)
    if fsdp_dims is not None:
        params = jax.tree.map(
            lambda p, d: p if d < 0 else jax.lax.all_gather(
                # dim d of the stacked [pp, lps, ...] leaf is d-1 after
                # the stage-dim squeeze above
                p, fsdp_axis, axis=d - 1, tiled=True
            ),
            params, fsdp_dims,
        )
    n = jax.lax.psum(1, axis_name)
    stage = jax.lax.axis_index(axis_name)
    first = stage == 0
    last = stage == n - 1
    m = num_microbatches

    # Forward neighbor ring: stage s sends to s+1; the wrap edge (n-1 -> 0)
    # carries only garbage (tick indices where stage 0 reads fresh input).
    perm = [(i, (i + 1) % n) for i in range(n)]

    out0 = jax.tree.map(jnp.zeros_like, x)
    carry_in0 = jax.tree.map(lambda a: jnp.zeros(a.shape[1:], a.dtype), x)

    def tick(carry, t):
        carry_in, out = carry
        # Stage 0 consumes microbatch t while t < m (clamped index keeps
        # shapes static; the result past m is garbage that never reaches
        # the output buffer of a valid tick).
        ti = jnp.minimum(t, m - 1)
        mb = jax.tree.map(lambda a: a[ti], x)
        stage_in = jax.tree.map(
            lambda a, b: jnp.where(first, a, b), mb, carry_in
        )
        y = stage_fn(params, stage_in)
        # Last stage's output for microbatch t - (n-1) is valid at tick t
        # >= n-1; everyone else writes into a buffer that is masked out of
        # the psum below.
        out_idx = jnp.clip(t - (n - 1), 0, m - 1)
        valid = jnp.logical_and(last, t >= n - 1)

        def write(buf, val):
            prev = jax.lax.dynamic_index_in_dim(buf, out_idx, keepdims=False)
            return jax.lax.dynamic_update_index_in_dim(
                buf, jnp.where(valid, val, prev), out_idx, 0
            )

        out = jax.tree.map(write, out, y)
        carry_next = jax.lax.ppermute(y, axis_name, perm)
        return (carry_next, out), None

    (_, out), _ = jax.lax.scan(
        tick, (carry_in0, out0), jnp.arange(num_ticks(n, m))
    )
    # Only the last stage holds real outputs; broadcast them to every pp
    # slot so downstream (loss, data-parallel reductions) sees the full
    # batch everywhere. Output is activation-sized — one hop around the pp
    # ring, cheap next to the per-tick traffic.
    return jax.tree.map(
        lambda o: jax.lax.psum(
            jnp.where(last, o, jnp.zeros_like(o)), axis_name
        ),
        out,
    )


def pipeline(
    stage_fn: Callable[[Any, Any], Any],
    stacked_params: Any,
    x: Any,
    *,
    num_microbatches: int,
    mesh: Optional[Mesh] = None,
    axis_name: str = AXIS_PIPE,
    batch_spec: P = P(),
    param_fsdp: bool = False,
    fsdp_axis: str = "fsdp",
) -> Any:
    """Run `x` through a pipeline of stages spread over the `axis_name`
    mesh axis.

    - ``stage_fn(stage_params, x) -> y`` with ``y`` matching ``x``'s
      pytree structure and shapes (homogeneous stages — transformer
      blocks; side inputs like attention masks ride the pytree: pass
      ``(hidden, mask)`` and return ``(new_hidden, mask)``);
    - ``stacked_params``: pytree with leading dim ``num_stages ==
      mesh.shape[axis_name]`` (see `stack_pytrees`), sharded over `pp`;
    - ``x``: pytree of [batch, ...] arrays; batch must divide by
      ``num_microbatches``;
    - ``batch_spec``: PartitionSpec entry for x's batch dim (e.g.
      ``P(('dp','fsdp'))`` when composing with data parallelism — the
      microbatch split then happens per data shard);
    - ``param_fsdp``: ZeRO-style pp x fsdp composition — stage weights
      arrive ALSO sharded over ``fsdp_axis`` on their stage_fsdp_dim
      (shard the TrainState with PIPELINED_BERT_FSDP_RULES or
      stage_param_spec_fsdp) and are all-gathered inside the shard_map
      once per step; gradients reduce-scatter back. Persistent memory
      per device: params + optimizer state / (pp * fsdp).

    Without a mesh (or with pp=1) this degenerates to sequentially folding
    the stages — numerically identical, so the same model code runs
    single-device.
    """
    from tpudl.parallel.sharding import current_mesh

    if mesh is None:
        mesh = current_mesh()
    n_stages = mesh.shape[axis_name] if mesh is not None else 1
    if n_stages == 1:
        n = jax.tree.leaves(stacked_params)[0].shape[0]
        y = x
        for i in range(n):
            y = stage_fn(jax.tree.map(lambda p: p[i], stacked_params), y)
        return y

    leading = jax.tree.leaves(stacked_params)[0].shape[0]
    if leading != n_stages:
        raise ValueError(
            f"stacked_params leading dim {leading} != mesh {axis_name} size "
            f"{n_stages} (one stage per pp slot)"
        )
    batch, xm, x_specs = _prepare_microbatches(
        x, num_microbatches, mesh, batch_spec, axis_name
    )

    fsdp_dims = None
    if param_fsdp:
        fsdp_size = mesh.shape[fsdp_axis]

        def _dim(p):
            d = stage_fsdp_dim(p.shape, fsdp_size)
            return -1 if d is None else d

        fsdp_dims = jax.tree.map(_dim, stacked_params)
        param_specs = jax.tree.map(
            lambda p: stage_param_spec_fsdp(
                p.shape, fsdp_size, axis_name, fsdp_axis
            ),
            stacked_params,
        )
    else:
        param_specs = jax.tree.map(
            lambda p: stage_param_spec(p.ndim, axis_name), stacked_params
        )

    fn = shard_map(
        partial(
            _pipeline_local,
            stage_fn=stage_fn,
            axis_name=axis_name,
            num_microbatches=num_microbatches,
            fsdp_dims=fsdp_dims,
            fsdp_axis=fsdp_axis,
        ),
        mesh=mesh,
        in_specs=(param_specs, x_specs),
        out_specs=x_specs,
        check_vma=False,
    )
    out = fn(stacked_params, xm)
    return jax.tree.map(
        lambda a: a.reshape((batch,) + a.shape[2:]), out
    )


# ---------------------------------------------------------------------------
# 1F1B (PipeDream-flush) schedule.
# ---------------------------------------------------------------------------


def _1f1b_local(
    params: Any,
    x: Any,
    targets: Any,
    *,
    stage_fn: Callable[[Any, Any], Any],
    loss_fn: Callable[[Any, Any], jax.Array],
    axis_name: str,
    num_microbatches: int,
):
    """Per-device 1F1B slot loop. Runs inside shard_map over `axis_name`.

    Slot-time schedule (t = 0 .. 2(M+S-1)-1, stage s, microbatch i):

    - forward  F(s, i) = s + i         while warming up (i <= S-1-s),
               F(s, i) = 2i + s        once steady (interleaved);
    - backward B(s, i) = 2S - 1 - s + 2i.

    Each slot a stage does at most ONE op (fwd and bwd slots have
    opposite parity in steady state), consuming the activation/gradient
    its neighbor sent LAST slot — one fwd-ring and one reverse-ring
    ppermute per slot. Backward recomputes the stage forward from the
    stored input (jax.vjp at the stored input), so per-stage residency
    is min(S, M) microbatch inputs instead of GPipe's M+S-1.
    """
    params = jax.tree.map(lambda p: jnp.squeeze(p, 0), params)
    n = jax.lax.psum(1, axis_name)
    s = jax.lax.axis_index(axis_name)
    first, last = s == 0, s == n - 1
    m = num_microbatches
    S_ = n
    buf_n = min(n, m)

    perm_f = [(i, (i + 1) % n) for i in range(n)]
    perm_b = [(i, (i - 1) % n) for i in range(n)]

    mb0 = jax.tree.map(lambda a: jnp.zeros(a.shape[1:], a.dtype), x)
    store0 = jax.tree.map(
        lambda a: jnp.zeros((buf_n,) + a.shape[1:], a.dtype), x
    )
    dparams0 = jax.tree.map(jnp.zeros_like, params)

    def fwd_index(stage, t):
        """Microbatch this stage forwards at slot t (garbage when the
        valid flag is False). Warmup runs consecutively, steady state
        interleaves with backwards on alternate slots."""
        iw = t - stage
        warm = (iw >= 0) & (iw <= S_ - 1 - stage) & (iw < m)
        ist = (t - stage) // 2
        steady = (
            ((t - stage) >= 2 * (S_ - stage))
            & (((t - stage) % 2) == 0)
            & (ist < m)
        )
        return jnp.clip(jnp.where(warm, iw, ist), 0, m - 1), warm | steady

    def slot(carry, t):
        fwd_in, bwd_in, store, dparams, loss_acc = carry
        i_f, do_fwd = fwd_index(s, t)
        tb = t - (2 * S_ - 1 - s)
        i_b = jnp.clip(tb // 2, 0, m - 1)
        do_bwd = (tb >= 0) & ((tb % 2) == 0) & ((tb // 2) < m)

        # --- input queue maintenance ---
        # The store is BOTH the arrival queue and the recompute buffer:
        # a microbatch may wait several slots between arriving (one slot
        # after the producer forwards it — schedule-decoded, so a
        # producer's bwd-slot garbage is never stored) and being
        # consumed (this stage may be busy with backwards at the
        # warmup/steady boundary).
        j_prev, prod_did = fwd_index(s - 1, t - 1)
        arrived = prod_did & (s > 0)

        def queue(b, arr_val, self_val):
            j = j_prev % buf_n
            upd = jnp.where(arrived, arr_val, b[j])
            b = jax.lax.dynamic_update_index_in_dim(b, upd, j, 0)
            i = i_f % buf_n
            mine = jnp.where(first & do_fwd, self_val, b[i])
            return jax.lax.dynamic_update_index_in_dim(b, mine, i, 0)

        mb_x = jax.tree.map(lambda a: a[i_f], x)
        store = jax.tree.map(queue, store, fwd_in, mb_x)

        # --- shared forward evaluation (fwd op OR bwd recompute) ---
        read_i = jnp.where(do_bwd, i_b, i_f) % buf_n
        u = jax.tree.map(lambda b: b[read_i], store)
        y, vjp = jax.vjp(stage_fn, params, u)

        # --- backward seed: loss vjp on the last stage, neighbor grad
        # elsewhere ---
        tgt = jax.tree.map(lambda a: a[i_b], targets)
        loss_val, loss_vjp = jax.vjp(lambda yy: loss_fn(yy, tgt), y)
        (dy_loss,) = loss_vjp(jnp.ones((), loss_val.dtype))
        dy = jax.tree.map(
            lambda a, b: jnp.where(last, a, b), dy_loss, bwd_in
        )
        dp, dx = vjp(dy)
        dparams = jax.tree.map(
            lambda acc, g: acc + jnp.where(do_bwd, g, jnp.zeros_like(g)),
            dparams, dp,
        )
        loss_acc = loss_acc + jnp.where(
            do_bwd & last,
            loss_val.astype(jnp.float32),
            jnp.zeros((), jnp.float32),
        )

        # --- neighbor exchange (consumed next slot) ---
        fwd_out = jax.lax.ppermute(y, axis_name, perm_f)
        bwd_out = jax.lax.ppermute(dx, axis_name, perm_b)
        return (fwd_out, bwd_out, store, dparams, loss_acc), None

    total = 2 * num_ticks(n, m)
    (_, _, _, dparams, loss_acc), _ = jax.lax.scan(
        slot,
        (mb0, mb0, store0, dparams0, jnp.zeros((), jnp.float32)),
        jnp.arange(total),
    )
    # Mean-of-microbatch-means loss lives on the last stage; broadcast.
    loss = jax.lax.psum(
        jnp.where(last, loss_acc, jnp.zeros_like(loss_acc)), axis_name
    ) / m
    # Per-microbatch losses are means, so grads sum to M * d(mean loss);
    # normalize to match grad-of-mean semantics.
    dparams = jax.tree.map(lambda g: (g / m)[None], dparams)
    return loss, dparams


def pipeline_1f1b(
    stage_fn: Callable[[Any, Any], Any],
    loss_fn: Callable[[Any, Any], jax.Array],
    stacked_params: Any,
    x: Any,
    targets: Any,
    *,
    num_microbatches: int,
    mesh: Optional[Mesh] = None,
    axis_name: str = AXIS_PIPE,
) -> tuple:
    """1F1B (PipeDream-flush) pipelined loss + stage-weight gradients.

    Same stage partitioning as ``pipeline`` (stacked ``[S, ...]`` params
    over the ``pp`` axis, shape-homogeneous stages), but the schedule
    interleaves one-forward-one-backward per stage, recomputing each
    stage forward from its stored INPUT at backward time — per-stage
    activation residency is ``min(S, M)`` microbatch inputs instead of
    GPipe's ``M + S - 1`` (``schedule_stats``). Because backward is part
    of the schedule, this is a grad-producing primitive, not a forward
    autodiff reverses: it returns ``(mean_loss, stage_grads)`` with
    ``stage_grads`` shaped/sharded like ``stacked_params``.

    ``loss_fn(y_microbatch, target_microbatch) -> scalar mean`` is
    evaluated on the LAST stage; the returned loss is the mean of
    per-microbatch means and the grads match ``jax.grad`` of that loss
    through the GPipe pipeline exactly (tests/test_pipeline.py parity).

    Honest TPU accounting: lockstep SPMD executes the masked fwd and
    bwd datapaths every slot, so 1F1B trades ~1.5x the FLOPs of
    remat-GPipe for the depth-bounded memory — reach for it when
    activation memory (long sequences, many microbatches) is the
    binding constraint, which is exactly when GPipe's M+S-1 residency
    OOMs. GPipe (``pipeline``) stays the default schedule.

    Gradients w.r.t. ``x`` are not returned (stage-0 inputs are data,
    the embedding lookup belongs inside stage 0 if its grads matter).
    Compose data parallelism OUTSIDE this primitive (replicate x per dp
    shard and psum the returned grads) — v1 shards only over ``pp``.
    Without a mesh (or pp=1) it degenerates to a sequential fold +
    jax.grad, numerically identical.
    """
    from tpudl.parallel.sharding import current_mesh

    if mesh is None:
        mesh = current_mesh()
    n_stages = mesh.shape[axis_name] if mesh is not None else 1
    leading = jax.tree.leaves(stacked_params)[0].shape[0]
    batch = jax.tree.leaves(x)[0].shape[0]
    if batch % num_microbatches != 0:
        raise ValueError(
            f"batch {batch} not divisible by num_microbatches="
            f"{num_microbatches}"
        )
    mb = batch // num_microbatches
    xm = jax.tree.map(
        lambda a: a.reshape((num_microbatches, mb) + a.shape[1:]), x
    )
    tm = jax.tree.map(
        lambda a: a.reshape((num_microbatches, mb) + a.shape[1:]), targets
    )

    if n_stages == 1:

        def seq_loss(sp):
            y = x
            for i in range(leading):
                y = stage_fn(jax.tree.map(lambda p: p[i], sp), y)
            # mean of per-microbatch means == mean when sizes are equal
            ym = jax.tree.map(
                lambda a: a.reshape((num_microbatches, mb) + a.shape[1:]), y
            )
            losses = [
                loss_fn(
                    jax.tree.map(lambda a: a[i], ym),
                    jax.tree.map(lambda a: a[i], tm),
                )
                for i in range(num_microbatches)
            ]
            return sum(losses) / num_microbatches

        return jax.value_and_grad(seq_loss)(stacked_params)

    if leading != n_stages:
        raise ValueError(
            f"stacked_params leading dim {leading} != mesh {axis_name} "
            f"size {n_stages}"
        )

    param_specs = jax.tree.map(
        lambda p: stage_param_spec(p.ndim, axis_name), stacked_params
    )
    data_specs = jax.tree.map(lambda a: P(*([None] * a.ndim)), xm)
    tgt_specs = jax.tree.map(lambda a: P(*([None] * a.ndim)), tm)

    fn = shard_map(
        partial(
            _1f1b_local,
            stage_fn=stage_fn,
            loss_fn=loss_fn,
            axis_name=axis_name,
            num_microbatches=num_microbatches,
        ),
        mesh=mesh,
        in_specs=(param_specs, data_specs, tgt_specs),
        out_specs=(P(), param_specs),
        check_vma=False,
    )
    return fn(stacked_params, xm, tm)


# ---------------------------------------------------------------------------
# Interleaved (virtual-stage) GPipe schedule.
# ---------------------------------------------------------------------------


def interleave_stage_order(num_stages: int, num_devices: int) -> list:
    """Storage order for ``pipeline(..., virtual_stages=v)``: row
    ``d*v + c`` must hold pipeline stage ``c*num_devices + d`` (device d
    owns the round-robin stages {d, d+n, d+2n, ...}; a contiguous
    P('pp') shard of the stacked tree then lands exactly those rows on
    device d). Apply to the per-stage list BEFORE stack_pytrees:

        order = interleave_stage_order(S, n)
        stacked = stack_pytrees([stages[i] for i in order])
    """
    if num_stages % num_devices:
        raise ValueError(
            f"{num_stages} stages not divisible by {num_devices} devices"
        )
    v = num_stages // num_devices
    return [c * num_devices + d for d in range(num_devices) for c in range(v)]


def _pipeline_local_interleaved(
    params: Any,
    x: Any,
    *,
    stage_fn: Callable[[Any, Any], Any],
    axis_name: str,
    num_microbatches: int,
    virtual_stages: int,
):
    """Per-device interleaved GPipe. Each device holds ``v`` stage chunks
    (rows of its [v, ...] param block = round-robin stages d, d+n, ...);
    a microbatch laps the ring v times. Schedule (tick t, device d,
    r = t - d): microbatches run in groups of n; within group g, chunk c,
    slot i (r = g*n*v + c*n + i), device d runs chunk c of microbatch
    g*n + i. Every dependency is exactly one tick old, so ticks total
    M*v + n - 1 — each tick is 1/v of a GPipe stage, so the bubble
    fraction drops from (n-1)/(M+n-1) to (n-1)/(M*v + n-1)
    (schedule_stats). Communication scales with v (one full-activation
    ppermute hop per chunk instead of per stage) — the standard
    interleaving trade; it rides the same neighbor ICI links.
    """
    n = jax.lax.psum(1, axis_name)
    d_idx = jax.lax.axis_index(axis_name)
    first = d_idx == 0
    last = d_idx == n - 1
    m, v = num_microbatches, virtual_stages

    # [v, ...] local block: row c = this device's chunk c.
    perm = [(i, (i + 1) % n) for i in range(n)]

    out0 = jax.tree.map(jnp.zeros_like, x)
    carry0 = jax.tree.map(lambda a: jnp.zeros(a.shape[1:], a.dtype), x)

    def tick(carry, t):
        carry_in, out = carry
        r = t - d_idx
        active = (r >= 0) & (r < m * v)
        rem = r % (n * v)
        c = jnp.clip(rem // n, 0, v - 1)
        mb_i = jnp.clip((r // (n * v)) * n + rem % n, 0, m - 1)

        stage_params = jax.tree.map(
            lambda p: jax.lax.dynamic_index_in_dim(p, c, 0, keepdims=False),
            params,
        )
        mb = jax.tree.map(lambda a: a[mb_i], x)
        take_input = first & (c == 0)
        stage_in = jax.tree.map(
            lambda a, b: jnp.where(take_input, a, b), mb, carry_in
        )
        y = stage_fn(stage_params, stage_in)

        write_valid = active & last & (c == v - 1)

        def write(buf, val):
            prev = jax.lax.dynamic_index_in_dim(buf, mb_i, keepdims=False)
            return jax.lax.dynamic_update_index_in_dim(
                buf, jnp.where(write_valid, val, prev), mb_i, 0
            )

        out = jax.tree.map(write, out, y)
        carry_next = jax.lax.ppermute(y, axis_name, perm)
        return (carry_next, out), None

    total = m * v + n - 1
    (_, out), _ = jax.lax.scan(tick, (carry0, out0), jnp.arange(total))
    return jax.tree.map(
        lambda o: jax.lax.psum(
            jnp.where(last, o, jnp.zeros_like(o)), axis_name
        ),
        out,
    )


def pipeline_interleaved(
    stage_fn: Callable[[Any, Any], Any],
    stacked_params: Any,
    x: Any,
    *,
    num_microbatches: int,
    mesh: Optional[Mesh] = None,
    axis_name: str = AXIS_PIPE,
    batch_spec: P = P(),
    virtual_stages: Optional[int] = None,
) -> Any:
    """Interleaved virtual-stage pipeline forward (reverse-differentiable
    like ``pipeline`` — autodiff replays the ring transposed).

    Pass ``virtual_stages`` (the v the storage order was built for —
    interleave_stage_order(S, S // v)) whenever you have it: the
    storage permutation is MESH-DEPENDENT, and running a tree stacked
    for one pp extent on another would silently apply layers out of
    order — with it, the mismatch raises instead.

    ``stacked_params`` has leading dim ``num_stages = n * v`` in
    INTERLEAVED storage order (``interleave_stage_order``): row
    ``d*v + c`` is pipeline stage ``c*n + d``. ``num_microbatches`` must
    be a multiple of the pp extent (the schedule runs groups of n). With
    v = stages/devices > 1 the bubble fraction is (n-1)/(M*v + n-1) —
    the fill/drain cost amortizes over chunk-sized (1/v stage) ticks —
    at v times the activation-hop communication volume. v = 1 is exactly
    GPipe; use ``pipeline`` for it (this function permits it but pays
    the dynamic chunk indexing).

    Without a mesh (or pp=1): sequential fold over stages in PIPELINE
    order, numerically identical.
    """
    from tpudl.parallel.sharding import current_mesh

    if mesh is None:
        mesh = current_mesh()
    n_stages_total = jax.tree.leaves(stacked_params)[0].shape[0]
    n = mesh.shape[axis_name] if mesh is not None else 1
    if virtual_stages is not None and n > 1:
        if n_stages_total != n * virtual_stages:
            raise ValueError(
                f"stacked_params was built for virtual_stages="
                f"{virtual_stages} ({n_stages_total} chunks over "
                f"{n_stages_total // virtual_stages} devices), but the mesh "
                f"{axis_name} extent is {n} — the interleaved storage "
                f"order would scramble the layer order"
            )
    if n == 1:
        # Sequential fold in PIPELINE order. The storage permutation
        # depends on the mesh the tree was built for; with
        # virtual_stages given we can invert it, otherwise identity
        # storage is assumed (v==1 trees).
        if virtual_stages is not None and virtual_stages > 1:
            order = interleave_stage_order(
                n_stages_total, n_stages_total // virtual_stages
            )
            rows = [order.index(c) for c in range(n_stages_total)]
        else:
            rows = list(range(n_stages_total))
        y = x
        for row in rows:
            y = stage_fn(jax.tree.map(lambda p: p[row], stacked_params), y)
        return y
    if n_stages_total % n:
        raise ValueError(
            f"stacked_params leading dim {n_stages_total} not divisible by "
            f"mesh {axis_name} size {n}"
        )
    v = n_stages_total // n
    if num_microbatches % n:
        raise ValueError(
            f"num_microbatches={num_microbatches} must be a multiple of the "
            f"{axis_name} extent {n} (the interleaved schedule runs groups "
            f"of n)"
        )
    batch, xm, x_specs = _prepare_microbatches(
        x, num_microbatches, mesh, batch_spec, axis_name
    )
    param_specs = jax.tree.map(
        lambda p: stage_param_spec(p.ndim, axis_name), stacked_params
    )

    fn = shard_map(
        partial(
            _pipeline_local_interleaved,
            stage_fn=stage_fn,
            axis_name=axis_name,
            num_microbatches=num_microbatches,
            virtual_stages=v,
        ),
        mesh=mesh,
        in_specs=(param_specs, x_specs),
        out_specs=x_specs,
        check_vma=False,
    )
    out = fn(stacked_params, xm)
    return jax.tree.map(lambda a: a.reshape((batch,) + a.shape[2:]), out)
