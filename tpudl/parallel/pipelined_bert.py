"""Pipeline-parallel BERT, drivable end-to-end by the training stack.

Round-2 gap closed here: tpudl.parallel.pipeline was "a library, not a
capability" — the GPipe schedule existed (shard_map + ppermute + scan)
but no model could train under it through compile_step/fit. This module
is that model path: a BERT classifier whose encoder layers run as
pipeline stages over the ``pp`` mesh axis, with

- params restructured into ``{"io": <embeddings/pooler/classifier>,
  "stages": {"layers": [pp, layers_per_stage, ...], "stage_id": [pp]}}``
  so stage weights (and their optimizer state, via PIPELINED_BERT_RULES)
  live sharded 1/pp;
- the same ``init``/``apply(variables, input_ids, attention_mask,
  train, rngs)`` calling convention the classification train step uses,
  so ``create_train_state`` + ``compile_step`` + ``fit`` drive it
  unchanged — optimizer state over the stacked tree included;
- dropout inside the pipeline: per-microbatch keys ride the carry pytree
  (one key-data row per example, constant within a microbatch) and each
  layer folds in its global layer index, so masks are independent across
  (microbatch, layer). The KEY math is layout-invariant, but the mask
  BITS are drawn over each device's local array shape — as in every
  framework, dropout streams differ between mesh layouts (pp=1's global
  [mb, S, H] draw vs pp=n's per-shard draw), which is why the
  pp-parity acceptance test runs with dropout off and dropout gets its
  own determinism/learning test;
- with no mesh (or pp=1) the schedule degenerates to a lax.map over the
  same microbatch structure — numerically identical deterministic math,
  which is what the pp4-vs-pp1 loss test asserts
  (tests/test_pipelined_bert.py).

Composes with data parallelism: the microbatch batch dim keeps its
(dp, fsdp) sharding inside the pipeline (``batch_spec``). Reuses the
exact tpudl.models.bert modules (BertEmbeddings / BertLayer), so layer
weights are interchangeable with the sequential model.
"""

from __future__ import annotations

from typing import Dict, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from tpudl.models.bert import BertConfig, BertEmbeddings, BertLayer, _dense
from tpudl.ops.attention import padding_mask
from tpudl.ops.dropout import Dropout
from tpudl.parallel.pipeline import (
    interleave_stage_order,
    pipeline,
    pipeline_interleaved,
    stack_pytrees,
    stage_param_spec,
    stage_param_spec_fsdp,
)
from tpudl.parallel.sharding import (
    Rules,
    _fsdp_largest_dim,
    active_mesh,
    constrain,
    current_mesh,
)

#: Sharding rules for a PipelinedBertClassifier TrainState: stage weights
#: (and their optimizer moments — the regex matches anywhere in the path)
#: shard their leading stage dim over pp; io stays replicated.
PIPELINED_BERT_RULES: Rules = (
    (r"(^|/)stages/", lambda shape: stage_param_spec(len(shape))),
)


def _stage_fsdp_spec(shape):
    """pp on the stage dim + fsdp on stage_fsdp_dim, via the SAME
    constructor the pipeline's shard_map in_specs use
    (stage_param_spec_fsdp) — fsdp_size=None defers divisibility to
    tree_shardings' clamp, which bails out under the same condition as
    stage_fsdp_dim's size-aware path."""
    return stage_param_spec_fsdp(shape, None)


#: strategy="pp+fsdp": stage weights AND their optimizer moments sharded
#: 1/(pp*fsdp); the io tree (embeddings/pooler/classifier + moments)
#: fsdp-shards too — embeddings on the vocab dim, kernels via the
#: standard largest-dim rule (first match wins, so stages/ hits the
#: pipeline rule before the generic kernel rule).
PIPELINED_BERT_FSDP_RULES: Rules = (
    (r"(^|/)stages/", _stage_fsdp_spec),
    (r"(^|/)io/.*embedding$", P("fsdp", None)),
    (r"(^|/)io/.*kernel$", _fsdp_largest_dim),
)


class PipelinedBertClassifier:
    """BERT sequence classifier with the encoder pipelined over pp.

    Not a flax Module: the parameter tree is deliberately restructured
    (stacked stages) and the pipeline runs under shard_map, so this is a
    thin model object exposing the init/apply surface the train stack
    consumes (tpudl.train.create_train_state / compile_step).
    """

    def __init__(
        self,
        cfg: BertConfig,
        num_stages: int,
        num_microbatches: int,
        param_fsdp: bool = False,
        virtual_stages: int = 1,
    ):
        """``virtual_stages`` > 1 switches to the interleaved schedule
        (tpudl.parallel.pipeline.pipeline_interleaved): ``num_stages``
        remains the pp mesh extent, each device holds ``v`` round-robin
        chunks of layers (num_stages*v chunks total, stored in
        interleave_stage_order so the contiguous pp shard lands each
        device's chunks locally), and the bubble fraction drops from
        (n-1)/(M+n-1) to (n-1)/(M*v + n-1) at v times the
        activation-hop traffic. Not composable with param_fsdp (the
        interleaved kernel does not thread the in-body all-gather)."""
        if virtual_stages < 1:
            raise ValueError(f"virtual_stages must be >= 1, got {virtual_stages}")
        if virtual_stages > 1 and param_fsdp:
            raise ValueError(
                "virtual_stages > 1 does not compose with param_fsdp"
            )
        n_chunks = num_stages * virtual_stages
        if cfg.num_layers % n_chunks != 0:
            raise ValueError(
                f"num_layers {cfg.num_layers} not divisible by "
                f"num_stages*virtual_stages {n_chunks}"
            )
        self.cfg = cfg
        self.num_stages = num_stages
        self.virtual_stages = virtual_stages
        self.num_chunks = n_chunks
        self.layers_per_stage = cfg.num_layers // n_chunks
        self.num_microbatches = num_microbatches
        #: storage row j holds pipeline chunk _chunk_order[j]
        #: (identity when virtual_stages == 1).
        self._chunk_order = (
            interleave_stage_order(n_chunks, num_stages)
            if virtual_stages > 1
            else list(range(n_chunks))
        )
        #: pp x fsdp composition (strategy="pp+fsdp"): shard the
        #: TrainState with PIPELINED_BERT_FSDP_RULES so stage weights +
        #: optimizer moments live 1/(pp*fsdp); the pipeline all-gathers
        #: per step and reduce-scatters gradients.
        self.param_fsdp = param_fsdp

    # -- train-stack surface ----------------------------------------------
    def init(self, rng, input_ids, train: bool = False) -> Dict:
        cfg = self.cfg
        r_emb, r_layers, r_pool, r_cls = jax.random.split(rng, 4)
        token_type_ids = jnp.zeros_like(input_ids)
        emb = BertEmbeddings(cfg)
        emb_params = emb.init(
            r_emb, input_ids, token_type_ids, False
        )["params"]
        x = emb.apply(
            {"params": emb_params}, input_ids, token_type_ids, False
        )
        mask4 = padding_mask(jnp.ones_like(input_ids))
        layer = BertLayer(cfg)
        layer_keys = jax.random.split(r_layers, cfg.num_layers)
        layer_params = [
            layer.init(k, x, mask4, False)["params"] for k in layer_keys
        ]
        # Group consecutive layers into chunks, then stack chunks in
        # STORAGE order (interleaved for virtual_stages > 1, so the
        # contiguous pp shard puts each device's round-robin chunks in
        # its local block).
        chunks = [
            stack_pytrees(
                layer_params[
                    c * self.layers_per_stage:(c + 1) * self.layers_per_stage
                ]
            )
            for c in self._chunk_order
        ]
        stacked = stack_pytrees(chunks)
        pooler = _dense(cfg, cfg.hidden_size, "pooler").init(
            r_pool, x[:, 0]
        )["params"]
        classifier = nn.Dense(
            cfg.num_labels,
            dtype=jnp.float32,
            kernel_init=nn.initializers.normal(0.02),
        ).init(r_cls, jnp.zeros((1, cfg.hidden_size)))["params"]
        return {
            "params": {
                "io": {
                    "embeddings": emb_params,
                    "pooler": pooler,
                    "classifier": classifier,
                },
                # stage_id deliberately NOT a parameter (int leaves break
                # value_and_grad); apply() builds it in-trace.
                "stages": {"layers": stacked},
            }
        }

    def apply(
        self,
        variables: Dict,
        input_ids,
        attention_mask=None,
        token_type_ids=None,
        train: bool = False,
        rngs: Optional[Dict] = None,
    ):
        cfg = self.cfg
        params = variables["params"]
        io, stages = params["io"], params["stages"]
        if attention_mask is None:
            attention_mask = jnp.ones_like(input_ids)
        if token_type_ids is None:
            token_type_ids = jnp.zeros_like(input_ids)

        x = BertEmbeddings(cfg).apply(
            {"params": io["embeddings"]},
            input_ids,
            token_type_ids,
            train,
            rngs=rngs,
        )
        x = constrain(x, ("dp", "fsdp"), "sp", "tp")
        mask4 = padding_mask(attention_mask)

        batch = x.shape[0]
        m = self.num_microbatches
        if batch % m != 0:
            raise ValueError(
                f"batch {batch} not divisible by num_microbatches {m}"
            )
        dropout_on = (
            train
            and rngs is not None
            and (cfg.hidden_dropout > 0.0 or cfg.attention_dropout > 0.0)
        )
        if dropout_on:
            base = rngs["dropout"]
            mb_keys = jax.vmap(
                lambda i: jax.random.key_data(jax.random.fold_in(base, i))
            )(jnp.arange(m))  # [M, key_words]
            key_rows = jnp.repeat(mb_keys, batch // m, axis=0)
        else:
            key_rows = jnp.zeros((batch, 2), jnp.uint32)

        layer = BertLayer(cfg)
        lps = self.layers_per_stage

        def run_layer(lp, h, m4, key_data, global_layer):
            if dropout_on:
                # Per-(microbatch, layer) dropout stream: the microbatch
                # key folds with the global layer index — the SAME key
                # math in the pipelined and degenerate paths below, so
                # pp=1 and pp=n train identically.
                key = jax.random.fold_in(
                    jax.random.wrap_key_data(key_data), global_layer
                )
                return layer.apply(
                    {"params": lp}, h, m4, True, rngs={"dropout": key}
                )
            return layer.apply({"params": lp}, h, m4, train)

        mesh = current_mesh()
        n_pp = mesh.shape["pp"] if mesh is not None else 1
        # Storage row of each pipeline chunk (identity for v == 1).
        row_of_chunk = [
            self._chunk_order.index(c) for c in range(self.num_chunks)
        ]
        if n_pp == 1:
            # Degenerate path: no pipeline, but the SAME per-microbatch
            # structure (a lax.map over microbatches) so dropout keys —
            # and therefore training trajectories — match pp>1 exactly.
            # All BERT ops are per-example, so the split itself is
            # numerically free. Chunks walk in PIPELINE order through
            # the (possibly interleaved) storage rows.
            stacked = stages["layers"]

            def run_mb(args):
                h, m4, kd = args
                for c in range(self.num_chunks):
                    row = row_of_chunk[c]
                    for j in range(lps):
                        lp = jax.tree.map(lambda a: a[row, j], stacked)
                        h = run_layer(lp, h, m4, kd, c * lps + j)
                return h

            mb = batch // m
            xm = x.reshape((m, mb) + x.shape[1:])
            m4m = mask4.reshape((m, mb) + mask4.shape[1:])
            km = key_rows.reshape((m, mb) + key_rows.shape[1:])[:, 0]
            with active_mesh(None):
                x = jax.lax.map(run_mb, (xm, m4m, km))
            x = x.reshape((batch,) + x.shape[2:])
        else:

            def stage_fn(p, carry):
                h, m4, krow = carry
                sid = p["stage_id"]
                for j in range(lps):
                    lp = jax.tree.map(lambda a: a[j], p["layers"])
                    h = run_layer(lp, h, m4, krow[0], sid * lps + j)
                return h, m4, krow

            # The chunk id rides the stacked tree (storage order), so
            # each stage body knows its GLOBAL layer offset regardless
            # of which storage row the schedule handed it.
            stacked_with_id = {
                "layers": stages["layers"],
                "stage_id": jnp.asarray(self._chunk_order, jnp.int32),
            }
            # constrain() must no-op inside the shard_map body (the mesh
            # axes are manual there); pipeline gets the mesh explicitly.
            with active_mesh(None):
                if self.virtual_stages > 1:
                    x, _, _ = pipeline_interleaved(
                        stage_fn,
                        stacked_with_id,
                        (x, mask4, key_rows),
                        num_microbatches=m,
                        mesh=mesh,
                        batch_spec=P(("dp", "fsdp")),
                        # The storage order was built for THIS v; a
                        # different pp extent raises instead of silently
                        # scrambling the layer order.
                        virtual_stages=self.virtual_stages,
                    )
                else:
                    x, _, _ = pipeline(
                        stage_fn,
                        stacked_with_id,
                        (x, mask4, key_rows),
                        num_microbatches=m,
                        mesh=mesh,
                        # fsdp stays a DATA axis (ZeRO semantics): the
                        # batch splits over (dp, fsdp) while param_fsdp
                        # shards the WEIGHTS over fsdp too — the
                        # all-gather transpose reduce-scatters each
                        # shard's gradient contribution.
                        batch_spec=P(("dp", "fsdp")),
                        param_fsdp=self.param_fsdp,
                    )

        x = constrain(x, ("dp", "fsdp"), "sp", "tp")
        pooled = jnp.tanh(
            _dense(cfg, cfg.hidden_size, "pooler").apply(
                {"params": io["pooler"]}, x[:, 0]
            )
        )
        if train and rngs is not None and cfg.hidden_dropout > 0.0:
            pooled = Dropout(cfg.hidden_dropout, exact=cfg.dropout_exact).apply(
                {}, pooled, deterministic=False, rngs=rngs
            )
        logits = (
            pooled.astype(jnp.float32)
            @ io["classifier"]["kernel"].astype(jnp.float32)
            + io["classifier"]["bias"].astype(jnp.float32)
        )
        return logits.astype(jnp.float32)
