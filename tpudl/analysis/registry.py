"""Central declaration table for every ``TPUDL_*`` knob and the metric
naming contract — the single source of truth the registry linter
(tpudl.analysis.lint) enforces against the tree.

Every environment variable the framework reads is DECLARED here with
its type, default, and one-line doc; runtime code reads knobs through
the typed accessors (``env_str`` / ``env_int`` / ``env_float`` /
``env_flag`` / ``env_require``) instead of raw ``os.environ``. The
linter flags any raw ``os.environ["TPUDL_*"]`` read outside this
module, any ``TPUDL_*`` literal that is not declared here, and any
declared knob missing from the README knob table (which
``scripts/lint_tpudl.py --knob-table`` generates from this table, so
docs can never drift from code).

Accessor semantics match the idioms they replaced: an UNSET or
EMPTY-STRING variable reads as the default (``TPUDL_X= python ...``
disables a knob the same way unsetting it does), malformed numerics
raise ``ValueError`` naming the variable, and flags accept
``1/true/yes/on`` (case-insensitive).

Stdlib-only: this module is imported by ``tpudl.obs.counters`` and the
runtime bootstrap, so it must not import jax or any tpudl subsystem.
"""

from __future__ import annotations

import dataclasses
import os
import re
from typing import Dict, Optional

#: Prometheus-conformant metric name: what ``registry().counter(name)``
#: / ``.gauge`` / ``.histogram`` literals must match so the /metrics
#: exposition needs no sanitizing (PR-6 conformance contract — the
#: exporter appends ``_sum`` / ``_count`` / ``_heartbeat_age_s``
#: suffixes, so names stay lower_snake_case with no leading digit).
METRIC_NAME_RE = re.compile(r"^[a-z][a-z0-9_]*$")

#: Characters legal ANYWHERE inside a metric name — the rule applied to
#: the static fragments of f-string metric names (the dynamic parts are
#: runtime-sanitized by the call sites, e.g. router's _metric_suffix).
METRIC_FRAGMENT_RE = re.compile(r"^[a-z0-9_]*$")

_FLAG_TRUTHY = ("1", "true", "yes", "on")


@dataclasses.dataclass(frozen=True)
class Knob:
    """One declared environment knob."""

    name: str
    kind: str  # "int" | "float" | "str" | "flag" | "path"
    default: object
    help: str
    #: Owning module (dotted), for the generated table.
    owner: str
    #: True for process-coordination variables SET by the framework
    #: itself (TpuDistributor worker bootstrap) rather than operator
    #: tuning knobs.
    internal: bool = False


KNOBS: Dict[str, Knob] = {}


def _declare(
    name: str,
    kind: str,
    default,
    help: str,
    owner: str,
    internal: bool = False,
) -> None:
    if name in KNOBS:
        raise ValueError(f"knob {name!r} declared twice")
    if not name.startswith("TPUDL_"):
        raise ValueError(f"knob {name!r} must start with TPUDL_")
    KNOBS[name] = Knob(name, kind, default, help, owner, internal)


# --- observability -------------------------------------------------------
_declare("TPUDL_OBS_DIR", "path", None,
         "Span/counter JSONL output directory; set = recording on.",
         "tpudl.obs.spans")
_declare("TPUDL_OBS_PORT", "int", None,
         "Live telemetry HTTP port (/metrics, /healthz, /snapshot); "
         "0 = ephemeral port (test idiom); unset = exporter off.",
         "tpudl.obs.exporter")
_declare("TPUDL_OBS_HOST", "str", "127.0.0.1",
         "Exporter bind host; loopback by default (endpoints are "
         "unauthenticated), 0.0.0.0 opts into container scraping.",
         "tpudl.obs.exporter")
_declare("TPUDL_OBS_HIST_WINDOW", "int", 65_536,
         "Histogram rolling-window size (bounded memory; cumulative "
         "count/sum are kept regardless).",
         "tpudl.obs.counters")
_declare("TPUDL_OBS_HEARTBEAT_STALE_S", "float", 60.0,
         "Heartbeat staleness floor for /healthz (the effective "
         "threshold is cadence-adaptive: max(floor, 5x last interval)).",
         "tpudl.obs.exporter")
_declare("TPUDL_OBS_REQUEST_LOG", "path", None,
         "Durable request-log output directory (crc-guarded rotated "
         "JSONL segments, one record per terminal serve Result); "
         "set = logging on.",
         "tpudl.obs.requestlog")
_declare("TPUDL_OBS_REQUEST_LOG_SEGMENT_BYTES", "int", 1_048_576,
         "Request-log segment rotation threshold in bytes (each "
         "rotation commits the segment with its crc32 in the name).",
         "tpudl.obs.requestlog")
_declare("TPUDL_OBS_REQUEST_LOG_QUEUE", "int", 1024,
         "Request-log writer queue depth; overflow drops records "
         "(counted in requestlog_records_dropped) instead of blocking "
         "the decode loop.",
         "tpudl.obs.requestlog")
_declare("TPUDL_OBS_REQUEST_LOG_SAMPLES", "flag", False,
         "Capture prompt/output token ids on COMPLETED request-log "
         "records (schema v2 optional fields — the flywheel's "
         "training feedstock); off = records carry metrics only.",
         "tpudl.obs.requestlog")
_declare("TPUDL_PROFILE_DIR", "path", None,
         "jax.profiler trace output directory for fit(profile=...).",
         "tpudl.train.loop")

# --- data / dispatch -----------------------------------------------------
_declare("TPUDL_PREFETCH_DEPTH", "int", None,
         "Pin the device prefetch queue depth and disable the "
         "autotuner; unset = autotune.",
         "tpudl.data.prefetch")
_declare("TPUDL_OVERLAP_BUCKET_MB", "float", None,
         "Gradient-accumulation overlap bucket size in MiB; 0 "
         "disables bucketing; unset = auto (4 MiB buckets on "
         "multi-shard meshes).",
         "tpudl.parallel.overlap")
_declare("TPUDL_COMPILE_CACHE", "path", None,
         "Persistent XLA compile-cache directory; unset = off.",
         "tpudl.runtime.compile_cache")
_declare("TPUDL_NORM_BLOCK_ROWS", "int", None,
         "Row-block override for the fused norm/MLP-epilogue Pallas "
         "kernels (benchmarks/fused_epilogue.py --sweep-blocks prints "
         "the winning pin).",
         "tpudl.ops.norms")
_declare("TPUDL_CE_VOCAB_BLOCK", "int", None,
         "Vocab-block override for the streaming cross-entropy kernel "
         "(must divide the padded vocab; the sweep keeps the "
         "divisibility walk).",
         "tpudl.ops.cross_entropy")

# --- training precision --------------------------------------------------
_declare("TPUDL_TRAIN_PRECISION", "str", None,
         "Mixed-precision training policy preset (f32 | bf16 | fp8): "
         "narrows benchmarks/train_precision.py's default cell sweep "
         "to f32 + that cell (via policy_from_env); unset = full "
         "sweep / no policy.",
         "tpudl.train.precision")
_declare("TPUDL_FP8_AMAX_WINDOW", "int", 16,
         "fp8 delayed-scaling amax-history ring length per tensor "
         "site (larger = smoother scales, slower reaction to "
         "distribution shift).",
         "tpudl.ops.fp8_dot")
_declare("TPUDL_LOSS_SCALE_INIT", "float", 32768.0,
         "Dynamic loss-scale starting value (power of two; backs off "
         "on nonfinite grads, grows back after a clean streak).",
         "tpudl.train.precision")
_declare("TPUDL_LOSS_SCALE_GROWTH_INTERVAL", "int", 2000,
         "Consecutive finite steps before the dynamic loss scale "
         "doubles (capped at 2^24).",
         "tpudl.train.precision")

# --- serving -------------------------------------------------------------
_declare("TPUDL_SERVE_SLOTS", "int", 4,
         "Default decode slot count for ServeSession.from_model "
         "(artifact sessions carry theirs in the program batch dim).",
         "tpudl.serve.api")
_declare("TPUDL_SERVE_QUEUE_DEPTH", "int", 256,
         "Admission queue capacity; overflow sheds shed_capacity.",
         "tpudl.serve.api")
_declare("TPUDL_SERVE_PAGED", "flag", False,
         "Swap the dense fixed-slot KV cache for the paged pool.",
         "tpudl.serve.api")
_declare("TPUDL_SERVE_PAGE_SIZE", "int", 16,
         "Paged KV page size in tokens.",
         "tpudl.serve.api")
_declare("TPUDL_SERVE_KV_DTYPE", "str", None,
         "Paged KV storage dtype (int8 = quantized pages, ~3.5x "
         "resident slots/byte); unset = the model dtype.",
         "tpudl.serve.api")
_declare("TPUDL_SERVE_WEIGHT_DTYPE", "str", None,
         "Post-training weight quantization for from_model (int8 | "
         "fp8); unset = full precision.",
         "tpudl.serve.api")
_declare("TPUDL_SERVE_PREFIX_SHARE", "flag", False,
         "Radix prefix-sharing KV: COW page sharing + chunked suffix "
         "prefill (requires paged).",
         "tpudl.serve.api")
_declare("TPUDL_SERVE_SPEC_K", "int", None,
         "Speculative-decoding window (draft proposes k tokens per "
         "verify dispatch); 0/unset = off.",
         "tpudl.serve.api")
_declare("TPUDL_SERVE_LORA_RANK", "int", None,
         "Multi-tenant adapter serving: per-tenant LoRA rank budget "
         "(r_max, the adapter page-table width); unset = the largest "
         "rank among the registered adapters.",
         "tpudl.serve.api")
_declare("TPUDL_SERVE_LORA_PAGES", "int", None,
         "Multi-tenant adapter serving: adapter pool size in pages "
         "(one page = one rank unit across every site; page 0 is the "
         "all-zero page); unset = 64 full-rank adapters + 1.",
         "tpudl.serve.api")
_declare("TPUDL_SERVE_LORA_DTYPE", "str", None,
         "Multi-tenant adapter serving: adapter page storage (int8 = "
         "quantized pages with per-page f32 dequant scales); unset = "
         "f32 pages.",
         "tpudl.serve.api")
_declare("TPUDL_SERVE_TENANT_QUOTA_TOKENS", "int", None,
         "Router default per-tenant in-flight token quota (sum of "
         "outstanding max_new_tokens); over it a tenant's requests "
         "shed as shed_quota — the isolation lever; unset = "
         "unlimited. Per-tenant overrides via Router(tenant_classes).",
         "tpudl.serve.router")
_declare("TPUDL_SERVE_MAX_FAILOVERS", "int", 3,
         "Per-request failover-resubmission cap: a request ping-"
         "ponging across successively dying replicas sheds as "
         "failover_exhausted instead of looping forever (migrations "
         "resume state and do not count).",
         "tpudl.serve.router")

# --- flywheel ------------------------------------------------------------
_declare("TPUDL_FLYWHEEL_MIN_RECORDS", "int", 8,
         "New completed records a tenant must accrue (TenantMeter "
         "delta since its last refresh) before the controller "
         "triggers a LoRA refresh.",
         "tpudl.flywheel.loop")
_declare("TPUDL_FLYWHEEL_INTERVAL_S", "float", 30.0,
         "FlywheelController.watch() poll cadence in seconds.",
         "tpudl.flywheel.loop")
_declare("TPUDL_FLYWHEEL_PRECISION", "str", "bf16",
         "RefreshTrainer precision policy preset (f32 | bf16 | fp8); "
         "fp8 opens the fp8-base x LoRA-factor training cell.",
         "tpudl.flywheel.refresh")
_declare("TPUDL_FLYWHEEL_HOLDOUT_FRAC", "float", 0.25,
         "Fraction of each refresh's sample stream held OUT of "
         "training and used as the promotion gate's eval slice "
         "(0 disables the gate).",
         "tpudl.flywheel.loop")
_declare("TPUDL_FLYWHEEL_GATE_TOL", "float", 0.0,
         "Promotion gate tolerance: refreshed factors publish only if "
         "held-out loss <= prior-factor loss + tol; failures roll "
         "back to the prior adapter.",
         "tpudl.flywheel.loop")

# --- fault tolerance / chaos --------------------------------------------
_declare("TPUDL_FT_GRACE_S", "float", 15.0,
         "Preemption grace window (SIGTERM -> emergency checkpoint -> "
         "hard-exit watchdog).",
         "tpudl.ft.preemption")
_declare("TPUDL_FT_MAX_RESTARTS", "int", 3,
         "Supervisor cohort-restart retry budget.",
         "tpudl.ft.supervisor")
_declare("TPUDL_FT_BACKOFF_S", "float", 1.0,
         "Initial supervisor restart backoff.",
         "tpudl.ft.supervisor")
_declare("TPUDL_FT_MAX_BACKOFF_S", "float", 30.0,
         "Supervisor restart backoff cap.",
         "tpudl.ft.supervisor")
_declare("TPUDL_CHAOS_KILL_AT_STEP", "int", None,
         "Fault injection: SIGKILL the matching rank at step N.",
         "tpudl.ft.chaos")
_declare("TPUDL_CHAOS_KILL_RANK", "int", None,
         "Fault injection: rank to kill (unset = rank 0).",
         "tpudl.ft.chaos")
_declare("TPUDL_CHAOS_ONCE_DIR", "path", None,
         "Fault injection: marker directory making each rank's kill "
         "fire exactly once across supervised restarts.",
         "tpudl.ft.chaos")
_declare("TPUDL_CHAOS_IO_DELAY_S", "float", 0.0,
         "Fault injection: added per-write delay in the checkpoint "
         "writer (slow-disk simulation).",
         "tpudl.ft.chaos")
_declare("TPUDL_SERVE_CHAOS_KILL_STEP", "int", None,
         "Serving chaos: raise ChaosKill in Engine.step at decode "
         "step N — the replica driver thread crashes (resubmit-"
         "fallback path; KV unrecoverable).",
         "tpudl.serve.chaos")
_declare("TPUDL_SERVE_CHAOS_PREEMPT_STEP", "int", None,
         "Serving chaos: raise ChaosPreempt at decode step N — the "
         "replica turns lame duck (unready, thread answers) and its "
         "seated KV must migrate to survivors.",
         "tpudl.serve.chaos")
_declare("TPUDL_SERVE_CHAOS_FREEZE_STEP", "int", None,
         "Serving chaos: freeze Engine.step at decode step N for "
         "TPUDL_SERVE_CHAOS_FREEZE_S seconds (stale-heartbeat path).",
         "tpudl.serve.chaos")
_declare("TPUDL_SERVE_CHAOS_FREEZE_S", "float", 1.0,
         "Serving chaos: freeze duration for the step freezer.",
         "tpudl.serve.chaos")
_declare("TPUDL_SERVE_CHAOS_ONCE_DIR", "path", None,
         "Serving chaos: marker directory making each injected fault "
         "fire exactly once across every engine in the process (kill "
         "ONE replica, not all).",
         "tpudl.serve.chaos")
_declare("TPUDL_SERVE_CHAOS_SCRAPE_FAIL_N", "int", 0,
         "Serving chaos: blackhole the next N FleetMonitor scrape "
         "attempts (install_scrape_chaos; retries consume the budget).",
         "tpudl.serve.chaos")
_declare("TPUDL_SERVE_CHAOS_SCRAPE_DELAY_S", "float", 0.0,
         "Serving chaos: added delay per FleetMonitor scrape attempt.",
         "tpudl.serve.chaos")
_declare("TPUDL_SERVE_CHAOS_FLIP_MIGRATION", "flag", False,
         "Serving chaos: flip one bit of every migration payload in "
         "transfer — the crc must catch it and shed the request as "
         "failed, never resume it.",
         "tpudl.serve.chaos")

# --- fleet (pod-real meshes / chip mover) --------------------------------
_declare("TPUDL_FLEET_TRANSPORT_HOST", "str", None,
         "Bind/connect host for cross-process MigrationEndpoints "
         "(unset = 127.0.0.1).",
         "tpudl.fleet.transport")
_declare("TPUDL_FLEET_TRANSPORT_TIMEOUT_S", "float", 30.0,
         "Socket send/recv timeout for migration transfers.",
         "tpudl.fleet.transport")
_declare("TPUDL_FLEET_SPOOL_DIR", "path", None,
         "Default directory for FileChannel() spool-file migration "
         "(shared-filesystem transport).",
         "tpudl.fleet.transport")
_declare("TPUDL_FLEET_BURN_SUSTAIN_S", "float", 2.0,
         "How long SLO burn must persist before the chip mover "
         "preempts training and lends devices to serving.",
         "tpudl.fleet.chipmover")
_declare("TPUDL_FLEET_CLEAR_SUSTAIN_S", "float", 5.0,
         "How long burn must stay clear before borrowed devices "
         "drain back to training.",
         "tpudl.fleet.chipmover")
_declare("TPUDL_FLEET_COOLDOWN_S", "float", 2.0,
         "Minimum gap between chip moves (flap damping, the "
         "Autoscaler's cooldown applied to device moves).",
         "tpudl.fleet.chipmover")
_declare("TPUDL_FLEET_SERVE_SHARE", "float", 0.5,
         "Fraction of the training cohort's devices a move lends to "
         "the borrowed serving replica (training keeps >= 1).",
         "tpudl.fleet.chipmover")

# --- analysis ------------------------------------------------------------
_declare("TPUDL_DEBUG_LOCK_ORDER", "flag", False,
         "Wrap subsystem locks (router/replica/fleet) in the ordered-"
         "lock monitor: every acquisition is checked against the "
         "statically derived lock order and the live wait-for graph; "
         "an inversion raises LockOrderViolation at the acquire site.",
         "tpudl.analysis.concurrency")

# --- process coordination (set by TpuDistributor, not operators) ---------
_declare("TPUDL_COORDINATOR", "str", None,
         "jax.distributed coordinator address for spawned workers.",
         "tpudl.runtime.distributor", internal=True)
_declare("TPUDL_NUM_PROCESSES", "int", None,
         "World size handed to spawned workers.",
         "tpudl.runtime.distributor", internal=True)
_declare("TPUDL_PROCESS_ID", "int", 0,
         "This worker's rank (also tags span streams).",
         "tpudl.runtime.distributor", internal=True)
_declare("TPUDL_PLATFORM", "str", None,
         "Backend platform override for spawned workers (cpu/tpu).",
         "tpudl.runtime.distributor", internal=True)


class UnknownKnobError(KeyError):
    """A knob read that is not declared in the table — declare it in
    tpudl.analysis.registry before reading it."""


def _lookup(name: str) -> Knob:
    knob = KNOBS.get(name)
    if knob is None:
        raise UnknownKnobError(
            f"{name!r} is not a declared TPUDL knob — add it to "
            f"tpudl.analysis.registry.KNOBS"
        )
    return knob


def env_raw(name: str) -> Optional[str]:
    """The raw string value, or None when unset OR empty (an empty
    assignment disables a knob the same way unsetting it does)."""
    _lookup(name)
    raw = os.environ.get(name)
    return raw if raw else None


def env_str(name: str, default: Optional[str] = None) -> Optional[str]:
    raw = env_raw(name)
    return raw if raw is not None else default


def env_require(name: str) -> str:
    """A coordination variable the caller cannot run without (worker
    bootstrap); raises KeyError naming it when missing."""
    raw = env_raw(name)
    if raw is None:
        raise KeyError(f"required environment variable {name} is not set")
    return raw


def env_int(
    name: str,
    default: Optional[int] = None,
    min_value: Optional[int] = None,
    required: bool = False,
) -> Optional[int]:
    raw = env_raw(name)
    if raw is None:
        if required:
            raise KeyError(
                f"required environment variable {name} is not set"
            )
        return default
    try:
        value = int(raw)
    except ValueError:
        raise ValueError(
            f"{name} must be an integer, got {raw!r}"
        ) from None
    if min_value is not None and value < min_value:
        raise ValueError(f"{name} must be >= {min_value}, got {value}")
    return value


def env_float(
    name: str, default: Optional[float] = None
) -> Optional[float]:
    raw = env_raw(name)
    if raw is None:
        return default
    try:
        return float(raw)
    except ValueError:
        raise ValueError(
            f"{name} must be a number, got {raw!r}"
        ) from None


def env_flag(name: str) -> bool:
    raw = env_raw(name)
    return raw is not None and raw.strip().lower() in _FLAG_TRUTHY


def knob_table_markdown(include_internal: bool = True) -> str:
    """The env-knob reference table, generated from the declaration
    table — ``scripts/lint_tpudl.py --knob-table`` prints this, and the
    README embeds it between ``<!-- knob-table:begin/end -->`` markers
    (tests/test_analysis.py asserts they match, so the docs cannot
    drift from the code)."""
    lines = [
        "| Knob | Type | Default | What it does |",
        "| --- | --- | --- | --- |",
    ]
    internal_lines: list = []
    for name in sorted(KNOBS):
        knob = KNOBS[name]
        default = "unset" if knob.default is None else str(knob.default)
        row = (
            f"| `{knob.name}` | {knob.kind} | {default} | "
            f"{knob.help} (`{knob.owner}`) |"
        )
        (internal_lines if knob.internal else lines).append(row)
    if include_internal and internal_lines:
        lines.append(
            "\nSet by the framework itself (TpuDistributor worker "
            "bootstrap), not operator knobs:\n"
        )
        lines.append("| Variable | Type | Default | What it does |")
        lines.append("| --- | --- | --- | --- |")
        lines.extend(internal_lines)
    return "\n".join(lines) + "\n"
