"""Concurrency static analysis + runtime lock-order monitor.

Every review round of the threaded serving stack (PR 8-11) hand-found
the same two defect classes; this module turns those audits into
machinery:

**Static pass** (``analyze_paths``): an AST walk over each class that
owns ``threading.Lock`` / ``RLock`` / ``Condition`` attributes,
building

- the per-class **lock-acquisition graph**: which lock is held when a
  ``with self.<other_lock>`` region (or a method that transitively
  acquires one) is entered. A cycle in that graph is a lock-order
  inversion — two threads entering it from different ends deadlock —
  reported as rule ``lock-order-inversion`` (P0).
- the **write-discipline map**: every ``self.<attr>`` store site and
  the locks held there. An attribute written at least once INSIDE a
  lock region and at least once outside any (construction in
  ``__init__`` excluded — no other thread can hold a reference yet) is
  rule ``unguarded-shared-write`` (P0): either the lock is load-bearing
  and the unguarded site races it, or it isn't and the guarded site is
  lying to the reader.

``threading.Condition(self._lock)`` aliases the condition attribute to
the underlying lock's group, so ``with self._not_empty:`` counts as
holding ``_lock``. Private methods (leading underscore) inherit the
intersection of locks held at their intra-class call sites — the
"callers hold ``_books``" idiom analyzes correctly without
annotations.

**Runtime companion** (``TPUDL_DEBUG_LOCK_ORDER``): ``OrderedLock``
wraps a real lock and reports every acquisition to a process-global
``LockOrderMonitor`` that maintains the live held-before graph ACROSS
objects (the static pass is per-class; the classic router-holds-books-
calls-replica / replica-holds-results-calls-router deadlock spans
two). A new edge that closes a cycle — or an acquisition that violates
the statically derived rank order (``derive_lock_ranks``) — raises
``LockOrderViolation`` at the acquire site, naming both lock chains.
``Router``/``Replica``/``FleetMonitor`` opt in via
``maybe_wrap_locks`` when the flag is set (the router/fleet tests
drive real traffic under it).
"""

from __future__ import annotations

import ast
import dataclasses
import os
import threading
from typing import Dict, List, Optional, Sequence, Set, Tuple

from tpudl.analysis.findings import Finding
from tpudl.analysis.registry import env_flag

LOCK_FACTORIES = ("Lock", "RLock", "Condition")

#: Method calls treated as WRITES to the receiving attribute (mutating
#: a shared container is a shared write even without an ``=``).
MUTATOR_METHODS = {
    "append", "appendleft", "extend", "insert", "pop", "popleft",
    "remove", "clear", "add", "discard", "update", "setdefault",
    "sort", "reverse",
}


# ---------------------------------------------------------------------------
# static pass
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class _WriteSite:
    attr: str
    line: int
    held: frozenset
    method: str


@dataclasses.dataclass
class _MethodInfo:
    name: str
    #: lock groups acquired directly via ``with`` anywhere in the body
    acquires: Set[str] = dataclasses.field(default_factory=set)
    #: (held-at-site, acquired-group, line)
    acquire_sites: List[Tuple[frozenset, str, int]] = dataclasses.field(
        default_factory=list
    )
    writes: List[_WriteSite] = dataclasses.field(default_factory=list)
    #: (held-at-site, callee, line)
    calls: List[Tuple[frozenset, str, int]] = dataclasses.field(
        default_factory=list
    )
    #: locks guaranteed held on entry (callers' intersection)
    inherited: frozenset = frozenset()


class _LockCollector(ast.NodeVisitor):
    """Pass 1: find the class's lock attributes and their alias groups
    (a Condition built over a lock belongs to that lock's group)."""

    def __init__(self):
        self.groups: Dict[str, str] = {}  # attr -> canonical group name

    def visit_Assign(self, node: ast.Assign) -> None:
        value = node.value
        factory = _lock_factory_name(value)
        if factory is not None:
            for target in node.targets:
                attr = _self_attr(target)
                if attr is None and isinstance(target, ast.Name):
                    attr = target.id  # module-level lock
                if attr is None:
                    continue
                group = attr
                if factory == "Condition" and value.args:
                    inner = _self_attr(value.args[0])
                    if inner is None and isinstance(
                        value.args[0], ast.Name
                    ):
                        inner = value.args[0].id
                    if inner is not None:
                        group = self.groups.get(inner, inner)
                self.groups[attr] = group
        self.generic_visit(node)


def _lock_factory_name(node: ast.AST) -> Optional[str]:
    """'Lock' / 'RLock' / 'Condition' when node is a call to
    threading.<factory>() (or a bare <factory>() import)."""
    if not isinstance(node, ast.Call):
        return None
    func = node.func
    if isinstance(func, ast.Attribute) and func.attr in LOCK_FACTORIES:
        if isinstance(func.value, ast.Name) and func.value.id == "threading":
            return func.attr
    if isinstance(func, ast.Name) and func.id in LOCK_FACTORIES:
        return func.id
    return None


def _self_attr(node: ast.AST) -> Optional[str]:
    if (
        isinstance(node, ast.Attribute)
        and isinstance(node.value, ast.Name)
        and node.value.id == "self"
    ):
        return node.attr
    return None


class _MethodWalker(ast.NodeVisitor):
    """Pass 2: walk one method body tracking the held-lock stack."""

    def __init__(self, method: str, groups: Dict[str, str]):
        self.groups = groups
        self.info = _MethodInfo(name=method)
        self._held: List[str] = []

    # -- lock regions ---------------------------------------------------

    def _lock_group_of(self, expr: ast.AST) -> Optional[str]:
        attr = _self_attr(expr)
        if attr is None and isinstance(expr, ast.Name):
            attr = expr.id
        if attr is None:
            return None
        return self.groups.get(attr)

    def visit_With(self, node: ast.With) -> None:
        entered: List[str] = []
        for item in node.items:
            group = self._lock_group_of(item.context_expr)
            if group is not None:
                held = frozenset(self._held)
                if group not in held:
                    self.info.acquires.add(group)
                    self.info.acquire_sites.append(
                        (held, group, node.lineno)
                    )
                self._held.append(group)
                entered.append(group)
            else:
                # Non-lock context managers still get visited for
                # nested locks/writes.
                self.visit(item.context_expr)
        for stmt in node.body:
            self.visit(stmt)
        for _ in entered:
            self._held.pop()

    # -- writes ---------------------------------------------------------

    def _record_write(self, attr: Optional[str], line: int) -> None:
        if attr is None or attr in self.groups:
            return  # not a self attribute, or the lock itself
        self.info.writes.append(
            _WriteSite(
                attr=attr,
                line=line,
                held=frozenset(self._held),
                method=self.info.name,
            )
        )

    def _write_target(self, target: ast.AST, line: int) -> None:
        if isinstance(target, (ast.Tuple, ast.List)):
            for elt in target.elts:
                self._write_target(elt, line)
            return
        attr = _self_attr(target)
        if attr is not None:
            self._record_write(attr, line)
            return
        if isinstance(target, ast.Subscript):
            self._write_target_container(target.value, line)
        if isinstance(target, ast.Starred):
            self._write_target(target.value, line)

    def _write_target_container(self, node: ast.AST, line: int) -> None:
        """``self.x[k] = v`` writes x; ``self.x[k][j] = v`` too."""
        attr = _self_attr(node)
        if attr is not None:
            self._record_write(attr, line)
        elif isinstance(node, ast.Subscript):
            self._write_target_container(node.value, line)

    def visit_Assign(self, node: ast.Assign) -> None:
        for target in node.targets:
            self._write_target(target, node.lineno)
        self.visit(node.value)

    def visit_AugAssign(self, node: ast.AugAssign) -> None:
        self._write_target(node.target, node.lineno)
        self.visit(node.value)

    def visit_AnnAssign(self, node: ast.AnnAssign) -> None:
        if node.value is not None:
            self._write_target(node.target, node.lineno)
            self.visit(node.value)

    def visit_Delete(self, node: ast.Delete) -> None:
        for target in node.targets:
            if isinstance(target, ast.Subscript):
                self._write_target_container(target.value, node.lineno)
            else:
                attr = _self_attr(target)
                if attr is not None:
                    self._record_write(attr, node.lineno)

    # -- calls ----------------------------------------------------------

    def visit_Call(self, node: ast.Call) -> None:
        func = node.func
        if isinstance(func, ast.Attribute):
            # self.m(...) -> intra-class call edge
            if (
                isinstance(func.value, ast.Name)
                and func.value.id == "self"
            ):
                self.info.calls.append(
                    (frozenset(self._held), func.attr, node.lineno)
                )
            # self.x.append(...) -> container mutation = write
            recv = _self_attr(func.value)
            if recv is not None and func.attr in MUTATOR_METHODS:
                self._record_write(recv, node.lineno)
        self.generic_visit(node)

    # Nested defs get their own analysis scope only for writes/locks
    # textually inside them — a closure runs on an unknown thread, so
    # treat its body like part of the method (conservative: the held
    # stack at the DEF site does not apply at call time).
    def visit_FunctionDef(self, node: ast.FunctionDef) -> None:
        saved, self._held = self._held, []
        for stmt in node.body:
            self.visit(stmt)
        self._held = saved

    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_Lambda(self, node: ast.Lambda) -> None:
        saved, self._held = self._held, []
        self.visit(node.body)
        self._held = saved


def _analyze_class(
    node: ast.ClassDef, path: str, module_groups: Dict[str, str]
) -> List[Finding]:
    collector = _LockCollector()
    for item in node.body:
        if isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef)):
            collector.visit(item)
    groups = dict(module_groups)
    groups.update(collector.groups)
    if not collector.groups:
        return []  # lockless class: nothing to check
    own_groups = set(collector.groups.values()) | set(
        module_groups.values()
    )

    methods = _build_methods(node, groups)
    findings: List[Finding] = []
    findings.extend(
        _order_findings(node.name, path, methods, own_groups)
    )
    findings.extend(
        _write_findings(node.name, path, methods, own_groups)
    )
    return findings


def _propagate_inherited(methods: Dict[str, _MethodInfo]) -> None:
    """Private methods called only with a lock held analyze as if they
    acquired it: inherited = intersection of (held + caller inherited)
    across intra-class call sites. Public methods never inherit (any
    external caller holds nothing)."""
    for _ in range(4):  # small fixed point; call chains are shallow
        changed = False
        for name, info in methods.items():
            if not name.startswith("_") or name.startswith("__"):
                continue
            site_holds = [
                frozenset(held | caller_info.inherited)
                for caller_info in methods.values()
                for (held, callee, _line) in caller_info.calls
                if callee == name
            ]
            if not site_holds:
                continue
            inherited = frozenset.intersection(*site_holds)
            if inherited != info.inherited:
                info.inherited = inherited
                changed = True
        if not changed:
            break


def _transitive_acquires(
    methods: Dict[str, _MethodInfo],
) -> Dict[str, Set[str]]:
    closure = {n: set(m.acquires) for n, m in methods.items()}
    changed = True
    while changed:
        changed = False
        for name, info in methods.items():
            for _held, callee, _line in info.calls:
                extra = closure.get(callee, set()) - closure[name]
                if extra:
                    closure[name] |= extra
                    changed = True
    return closure


def _build_methods(
    node: ast.ClassDef, groups: Dict[str, str]
) -> Dict[str, _MethodInfo]:
    """Walk every method of a class and resolve inherited locks — the
    shared front half of finding-generation AND rank derivation."""
    methods: Dict[str, _MethodInfo] = {}
    for item in node.body:
        if not isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        walker = _MethodWalker(item.name, groups)
        for stmt in item.body:
            walker.visit(stmt)
        methods[item.name] = walker.info
    _propagate_inherited(methods)
    return methods


def _collect_edges(
    methods: Dict[str, _MethodInfo],
) -> Dict[Tuple[str, str], Tuple[str, int]]:
    """Held-before edges A -> B with one example (method, line) each:
    direct ``with`` nesting plus acquisitions reached through the
    intra-class call graph. The ONE edge definition — findings and the
    runtime monitor's static ranks both consume it."""
    edges: Dict[Tuple[str, str], Tuple[str, int]] = {}
    closure = _transitive_acquires(methods)
    for name, info in methods.items():
        for held, group, line in info.acquire_sites:
            for h in held | info.inherited:
                if h != group:
                    edges.setdefault((h, group), (name, line))
        for held, callee, line in info.calls:
            for acquired in closure.get(callee, set()):
                for h in held | info.inherited:
                    if h != acquired:
                        edges.setdefault((h, acquired), (name, line))
    return edges


def _order_findings(
    cls: str,
    path: str,
    methods: Dict[str, _MethodInfo],
    own_groups: Set[str],
) -> List[Finding]:
    edges = _collect_edges(methods)
    graph: Dict[str, Set[str]] = {}
    for a, b in edges:
        graph.setdefault(a, set()).add(b)

    findings: List[Finding] = []
    reported: Set[frozenset] = set()
    for a, b in sorted(edges):
        if (b, a) not in edges:
            continue
        pair = frozenset((a, b))
        if pair in reported:
            continue
        reported.add(pair)
        m1, l1 = edges[(a, b)]
        m2, l2 = edges[(b, a)]
        findings.append(
            Finding(
                rule="lock-order-inversion",
                path=path,
                line=l1,
                symbol=f"{cls}.{m1}",
                message=(
                    f"lock '{a}' is held while acquiring '{b}' "
                    f"(in {m1}) AND '{b}' while acquiring '{a}' "
                    f"(in {m2}:{l2}) — two threads entering from "
                    f"different ends deadlock"
                ),
                severity="P0",
            )
        )
    # Longer cycles (A->B->C->A) without any 2-cycle inside.
    for cycle in _simple_cycles(graph):
        if len(cycle) < 3:
            continue
        pair = frozenset(cycle)
        if any(
            frozenset((x, y)) in reported
            for x in cycle for y in cycle if x != y
        ):
            continue
        reported.add(pair)
        a, b = cycle[0], cycle[1]
        m1, l1 = edges[(a, b)]
        findings.append(
            Finding(
                rule="lock-order-inversion",
                path=path,
                line=l1,
                symbol=f"{cls}.{m1}",
                message=(
                    "lock-acquisition cycle "
                    + " -> ".join(cycle + [cycle[0]])
                ),
                severity="P0",
            )
        )
    return findings


def _simple_cycles(graph: Dict[str, Set[str]]) -> List[List[str]]:
    """Small-graph cycle enumeration (lock graphs have <10 nodes)."""
    cycles: List[List[str]] = []
    seen: Set[frozenset] = set()

    def dfs(start: str, node: str, trail: List[str]) -> None:
        for nxt in sorted(graph.get(node, ())):
            if nxt == start and len(trail) > 1:
                key = frozenset(trail)
                if key not in seen:
                    seen.add(key)
                    cycles.append(list(trail))
            elif nxt not in trail and nxt > start:
                dfs(start, nxt, trail + [nxt])

    for start in sorted(graph):
        dfs(start, start, [start])
    return cycles


def _write_findings(
    cls: str,
    path: str,
    methods: Dict[str, _MethodInfo],
    own_groups: Set[str],
) -> List[Finding]:
    by_attr: Dict[str, List[_WriteSite]] = {}
    for name, info in methods.items():
        if name == "__init__":
            continue  # construction: no other thread holds a reference
        for site in info.writes:
            effective = site.held | info.inherited
            by_attr.setdefault(site.attr, []).append(
                dataclasses.replace(site, held=frozenset(effective))
            )
    findings: List[Finding] = []
    for attr in sorted(by_attr):
        sites = by_attr[attr]
        guarded = [s for s in sites if s.held & own_groups]
        unguarded = [s for s in sites if not (s.held & own_groups)]
        if not guarded or not unguarded:
            continue
        locks = sorted({g for s in guarded for g in s.held & own_groups})
        seen_methods: Set[str] = set()
        for site in unguarded:
            if site.method in seen_methods:
                continue
            seen_methods.add(site.method)
            findings.append(
                Finding(
                    rule="unguarded-shared-write",
                    path=path,
                    line=site.line,
                    symbol=f"{cls}.{site.method}",
                    message=(
                        f"attribute '{attr}' is written under lock "
                        f"{'/'.join(locks)} elsewhere in {cls} but "
                        f"without a lock here"
                    ),
                    severity="P0",
                )
            )
    return findings


def analyze_source(source: str, path: str) -> List[Finding]:
    """Run the concurrency pass over one file's source text."""
    tree = ast.parse(source, filename=path)
    module_collector = _LockCollector()
    for node in tree.body:
        if isinstance(node, ast.Assign):
            module_collector.visit(node)
    findings: List[Finding] = []
    for node in tree.body:
        if isinstance(node, ast.ClassDef):
            findings.extend(
                _analyze_class(node, path, module_collector.groups)
            )
    return findings


def analyze_file(path: str, repo_root: Optional[str] = None) -> List[Finding]:
    with open(path) as f:
        source = f.read()
    rel = os.path.relpath(path, repo_root) if repo_root else path
    return analyze_source(source, rel.replace(os.sep, "/"))


def analyze_paths(
    paths: Sequence[str], repo_root: Optional[str] = None
) -> List[Finding]:
    """Concurrency findings for every ``.py`` under ``paths`` (files or
    directories)."""
    findings: List[Finding] = []
    for path in paths:
        if os.path.isdir(path):
            for dirpath, dirnames, filenames in os.walk(path):
                dirnames[:] = [
                    d for d in dirnames if d != "__pycache__"
                ]
                for fn in sorted(filenames):
                    if fn.endswith(".py"):
                        findings.extend(
                            analyze_file(
                                os.path.join(dirpath, fn), repo_root
                            )
                        )
        else:
            findings.extend(analyze_file(path, repo_root))
    return findings


def derive_lock_ranks(
    paths: Sequence[str], repo_root: Optional[str] = None
) -> Dict[str, int]:
    """Topological ranks for the runtime monitor, derived from the
    per-class acquisition graphs: ``{"Class.attr": rank}`` where a lock
    acquired while another is held ranks HIGHER (acquire low-to-high).
    Locks on a static cycle (already a P0 finding) get no rank."""
    edges: Set[Tuple[str, str]] = set()
    files: List[str] = []
    for path in paths:
        if os.path.isdir(path):
            for dirpath, dirnames, filenames in os.walk(path):
                dirnames[:] = [d for d in dirnames if d != "__pycache__"]
                files.extend(
                    os.path.join(dirpath, f)
                    for f in sorted(filenames) if f.endswith(".py")
                )
        else:
            files.append(path)
    for file in files:
        with open(file) as f:
            tree = ast.parse(f.read(), filename=file)
        module_collector = _LockCollector()
        for node in tree.body:
            if isinstance(node, ast.Assign):
                module_collector.visit(node)
        for node in tree.body:
            if not isinstance(node, ast.ClassDef):
                continue
            collector = _LockCollector()
            for item in node.body:
                if isinstance(
                    item, (ast.FunctionDef, ast.AsyncFunctionDef)
                ):
                    collector.visit(item)
            if not collector.groups:
                continue
            groups = dict(module_collector.groups)
            groups.update(collector.groups)
            methods = _build_methods(node, groups)
            for (a, b) in _collect_edges(methods):
                edges.add((f"{node.name}.{a}", f"{node.name}.{b}"))
    # Kahn topo-sort; cycle members drop out unranked.
    nodes = {n for e in edges for n in e}
    indeg = {n: 0 for n in nodes}
    for _a, b in edges:
        indeg[b] += 1
    ranks: Dict[str, int] = {}
    frontier = sorted(n for n, d in indeg.items() if d == 0)
    rank = 0
    while frontier:
        nxt: List[str] = []
        for n in frontier:
            ranks[n] = rank
            for a, b in edges:
                if a == n:
                    indeg[b] -= 1
                    if indeg[b] == 0:
                        nxt.append(b)
        frontier = sorted(set(nxt))
        rank += 1
    return ranks


# ---------------------------------------------------------------------------
# runtime companion: TPUDL_DEBUG_LOCK_ORDER
# ---------------------------------------------------------------------------


class LockOrderViolation(RuntimeError):
    """An acquisition that closes a cycle in the live held-before graph
    or violates the statically derived lock order."""


class LockOrderMonitor:
    """Process-global held-before graph over named locks.

    Each ``OrderedLock`` reports acquisitions; the monitor records the
    edge (held -> acquired) for every lock the acquiring thread already
    holds, and raises :class:`LockOrderViolation` when a NEW edge closes
    a cycle — i.e. some other code path acquires these locks in the
    opposite order, which deadlocks under the right interleaving even
    if this run got lucky. With ``ranks`` (see
    :func:`derive_lock_ranks`) it additionally asserts the static
    order: acquiring a lower-ranked lock while holding a higher-ranked
    one is an inversion even before the reverse path ever runs."""

    def __init__(
        self,
        ranks: Optional[Dict[str, int]] = None,
        raise_on_violation: bool = True,
    ):
        self.ranks = dict(ranks or {})
        self.raise_on_violation = raise_on_violation
        self.violations: List[str] = []
        #: Total acquisitions observed — proves wrapping is live even
        #: when the code never nests two locks (edge set empty).
        self.acquisitions = 0
        self._edges: Dict[str, Set[str]] = {}
        self._mu = threading.Lock()  # guards _edges/violations
        self._tls = threading.local()

    # -- per-thread held stack -----------------------------------------

    def _stack(self) -> List[str]:
        stack = getattr(self._tls, "stack", None)
        if stack is None:
            stack = []
            self._tls.stack = stack
        return stack

    def held(self) -> Tuple[str, ...]:
        return tuple(self._stack())

    # -- the check ------------------------------------------------------

    def _reaches(self, src: str, dst: str) -> bool:
        seen = {src}
        frontier = [src]
        while frontier:
            node = frontier.pop()
            for nxt in self._edges.get(node, ()):
                if nxt == dst:
                    return True
                if nxt not in seen:
                    seen.add(nxt)
                    frontier.append(nxt)
        return False

    def _violate(self, message: str) -> None:
        self.violations.append(message)
        if self.raise_on_violation:
            raise LockOrderViolation(message)

    def on_acquire(self, name: str, reentrant: bool = True) -> None:
        stack = self._stack()
        with self._mu:
            self.acquisitions += 1
        if name in stack:
            if not reentrant:
                # A plain Lock re-acquired by its holding thread blocks
                # forever — the classic self-deadlock, and exactly the
                # defect class this monitor exists for.
                self._violate(
                    f"self-deadlock: thread re-acquires non-reentrant "
                    f"lock '{name}' it already holds "
                    f"(held: {list(stack)})"
                )
            stack.append(name)  # RLock reentry: no ordering information
            return
        held = [h for h in stack if h != name]
        with self._mu:
            for h in set(held):
                # Cycle check BEFORE inserting: does a path name->...->h
                # already exist? Then h-before-name and name-before-h
                # both happen — the deadlock interleaving exists.
                if self._reaches(name, h):
                    self._violate(
                        f"lock-order inversion: acquiring '{name}' "
                        f"while holding '{h}', but '{name}' is already "
                        f"held before '{h}' on another path "
                        f"(held here: {list(stack)})"
                    )
                self._edges.setdefault(h, set()).add(name)
            rank = self.ranks.get(name)
            if rank is not None:
                for h in set(held):
                    h_rank = self.ranks.get(h)
                    if h_rank is not None and h_rank > rank:
                        self._violate(
                            f"static lock order violated: acquiring "
                            f"'{name}' (rank {rank}) while holding "
                            f"'{h}' (rank {h_rank}) — the derived "
                            f"order acquires low-to-high"
                        )
        stack.append(name)

    def on_release(self, name: str) -> None:
        stack = self._stack()
        for i in range(len(stack) - 1, -1, -1):
            if stack[i] == name:
                del stack[i]
                return

    def edges(self) -> Dict[str, Set[str]]:
        with self._mu:
            return {a: set(bs) for a, bs in self._edges.items()}


_default_monitor: Optional[LockOrderMonitor] = None
_default_monitor_mu = threading.Lock()


def default_monitor() -> LockOrderMonitor:
    global _default_monitor
    if _default_monitor is None:
        with _default_monitor_mu:
            if _default_monitor is None:
                _default_monitor = LockOrderMonitor()
    return _default_monitor


class OrderedLock:
    """A Lock/RLock wrapper that reports to a :class:`LockOrderMonitor`.
    Context-manager and acquire/release compatible; everything else
    delegates to the wrapped lock."""

    def __init__(self, inner, name: str, monitor: LockOrderMonitor):
        self._inner = inner
        self._name = name
        self._monitor = monitor
        self._reentrant = isinstance(inner, type(threading.RLock()))

    @property
    def name(self) -> str:
        return self._name

    def acquire(self, *args, **kwargs):
        # Check BEFORE blocking: the inversion is a property of the
        # order, not of whether this particular run deadlocks.
        self._monitor.on_acquire(self._name, reentrant=self._reentrant)
        ok = False
        try:
            ok = self._inner.acquire(*args, **kwargs)
            return ok
        finally:
            if not ok:
                # A failed non-blocking/timed acquire (False return OR
                # exception) never held the lock: pop the speculative
                # stack entry or every later acquisition on this thread
                # sees a phantom held lock.
                self._monitor.on_release(self._name)

    def release(self):
        self._monitor.on_release(self._name)
        return self._inner.release()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()
        return False

    def __getattr__(self, item):
        return getattr(self._inner, item)


_LOCK_TYPES = (type(threading.Lock()), type(threading.RLock()))


def wrap_instance_locks(
    obj,
    monitor: Optional[LockOrderMonitor] = None,
    prefix: Optional[str] = None,
) -> List[str]:
    """Replace every plain Lock/RLock attribute on ``obj`` with an
    :class:`OrderedLock` named ``Class.attr`` (matching the static
    pass's rank names). Conditions are left alone — a Condition holds a
    reference to its underlying lock, and swapping one out from under
    it would desynchronize them. Returns the wrapped names."""
    monitor = monitor or default_monitor()
    prefix = prefix or type(obj).__name__
    wrapped: List[str] = []
    for attr, value in list(vars(obj).items()):
        if isinstance(value, OrderedLock):
            continue
        if isinstance(value, _LOCK_TYPES):
            name = f"{prefix}.{attr}"
            setattr(obj, attr, OrderedLock(value, name, monitor))
            wrapped.append(name)
    return wrapped


def maybe_wrap_locks(obj, prefix: Optional[str] = None) -> List[str]:
    """The production seam: no-op unless ``TPUDL_DEBUG_LOCK_ORDER`` is
    set, in which case the object's locks join the process-global
    monitor (Router/Replica/FleetMonitor call this from __init__)."""
    if not env_flag("TPUDL_DEBUG_LOCK_ORDER"):
        return []
    return wrap_instance_locks(obj, default_monitor(), prefix)
