"""Dispatch-hygiene audits: recompiles, implicit host transfers, and
(via tpudl.analysis.donation) lost buffer donation.

The paper's behavioral signature is "export -> backends -> measured
latency" (PAPER.md §0); every PR 8-11 review round hand-found the same
silent regressions in the hot loops: a shape that quietly recompiles
per step, an eager readback that serializes the dispatch pipeline, a
donated buffer that silently copies. These context managers make those
audits reusable — in tests, in benchmarks (serve_load wraps its timed
steady state in both), and ad hoc around any suspect loop:

    with assert_no_recompiles():
        for _ in range(50):
            engine.step()

    with assert_no_host_transfers(allow=("h2d",)):
        run_decode_steady_state()

**Recompiles** are counted via the ``jax.monitoring`` backend-compile
event — the same channel the persistent compile cache's hit counters
ride (tpudl.runtime.compile_cache). One module-level listener feeds a
process-global counter; watchers snapshot it, so nesting and
concurrent use are safe and no listener is ever unregistered (jax only
offers clear-all).

**Host transfers** use ``jax.transfer_guard`` in ``disallow`` mode,
which blocks IMPLICIT transfers only: explicit ``jax.device_put`` /
``jax.device_get`` pass. That is the audit contract — every intended
transfer in a hot loop must be explicit, so anything implicit after
warmup is a regression. ``allow=("h2d",)`` exempts a direction (the
serving decode loop feeds small per-step control arrays from host by
design). Platform caveat: the CPU backend's device-to-host path is
zero-copy and never guarded, so d2h regressions only trip on real
accelerators — tier-1 fixtures therefore seed h2d violations.
"""

from __future__ import annotations

import contextlib
import threading
from typing import Iterable, Optional

_COMPILE_EVENT = "/jax/core/compile/backend_compile_duration"
_TRANSFER_KINDS = ("h2d", "d2h", "d2d")

_compiles = 0
_compiles_mu = threading.Lock()
_listener_installed = False
_install_mu = threading.Lock()


class DispatchHygieneError(AssertionError):
    """A hot loop recompiled or implicitly transferred after warmup."""


def _on_duration_event(event: str, duration: float, **kwargs) -> None:
    global _compiles
    if event == _COMPILE_EVENT:
        with _compiles_mu:
            _compiles += 1


def _ensure_listener() -> None:
    global _listener_installed
    if _listener_installed:
        return
    with _install_mu:
        if _listener_installed:
            return
        import jax.monitoring

        jax.monitoring.register_event_duration_secs_listener(
            _on_duration_event
        )
        _listener_installed = True


def compile_count() -> int:
    """Backend compiles observed process-wide since the listener
    installed (monotonic; diff two reads to bracket a region)."""
    _ensure_listener()
    with _compiles_mu:
        return _compiles


class RecompileWatcher:
    """Counts backend compiles inside a ``with`` region without
    asserting — the benchmark form (serve_load banks the count)."""

    def __init__(self, label: str = ""):
        self.label = label
        self._start: Optional[int] = None
        self._count = 0

    @property
    def count(self) -> int:
        if self._start is None:
            return self._count
        return compile_count() - self._start

    def __enter__(self) -> "RecompileWatcher":
        _ensure_listener()
        self._start = compile_count()
        return self

    def __exit__(self, *exc) -> bool:
        self._count = compile_count() - self._start
        self._start = None
        return False


@contextlib.contextmanager
def assert_no_recompiles(allow: int = 0, label: str = ""):
    """Fail if more than ``allow`` backend compiles happen inside the
    region. Wrap the STEADY STATE (after warmup has compiled every
    program the loop legitimately uses); a recompile inside means a
    shape/dtype/static-arg is quietly varying per step."""
    with RecompileWatcher(label=label) as watcher:
        yield watcher
    if watcher.count > allow:
        where = f" in {label}" if label else ""
        raise DispatchHygieneError(
            f"{watcher.count} backend compile(s){where} after warmup "
            f"(allowed {allow}) — some dispatch in the steady state is "
            f"recompiling; look for a python-varying shape, dtype, or "
            f"static argument"
        )


@contextlib.contextmanager
def assert_no_host_transfers(
    allow: Iterable[str] = (), label: str = ""
):
    """Disallow IMPLICIT transfers inside the region; ``allow`` names
    directions to exempt ("h2d", "d2h", "d2d"). Explicit
    ``device_put``/``device_get`` always pass — intent made visible is
    the contract. The offending transfer raises AT ITS SITE (jax's
    guard error names the aval); this wrapper re-raises it as
    :class:`DispatchHygieneError` with the audit context attached.

    Thread-local, like every jax config context: guards apply to the
    auditing thread only (a MetricFetcher readback on its own thread
    is untouched)."""
    import jax

    allow = set(allow)
    unknown = allow - set(_TRANSFER_KINDS)
    if unknown:
        raise ValueError(
            f"unknown transfer kinds {sorted(unknown)}; expected a "
            f"subset of {_TRANSFER_KINDS}"
        )
    guards = {
        "h2d": jax.transfer_guard_host_to_device,
        "d2h": jax.transfer_guard_device_to_host,
        "d2d": jax.transfer_guard_device_to_device,
    }
    with contextlib.ExitStack() as stack:
        for kind, guard in guards.items():
            stack.enter_context(
                guard("allow" if kind in allow else "disallow")
            )
        try:
            yield
        except Exception as e:
            if "transfer" in str(e).lower() and "Disallowed" in str(e):
                where = f" in {label}" if label else ""
                raise DispatchHygieneError(
                    f"implicit host transfer{where} after warmup: {e} "
                    f"— make the intended transfer explicit "
                    f"(jax.device_put/device_get) or pass "
                    f"allow=(...) if this direction is by design"
                ) from e
            raise
