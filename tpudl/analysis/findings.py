"""Finding model + ratcheted baseline for the static-analysis tier.

A ``Finding`` is one rule violation at one site. Its ``fingerprint``
deliberately EXCLUDES the line number: refactors that move code without
changing the violation keep the same fingerprint, so the checked-in
``analysis_baseline.json`` survives unrelated edits.

The ratchet contract (scripts/lint_tpudl.py):

- a finding whose fingerprint is IN the baseline **warns** (known debt,
  each entry carries a one-line justification);
- a finding NOT in the baseline **fails** the gate — new debt needs a
  fix or an explicit baseline entry in the same PR;
- a baseline entry no fingerprint matches anymore is **stale** and
  warns too: delete it, the ratchet only ever tightens.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
from typing import Dict, Iterable, List, Optional

#: Severities: P0 = fix before merging (the dogfood bar), P1 = real but
#: baselinable with a justification, P2 = advisory.
SEVERITIES = ("P0", "P1", "P2")


@dataclasses.dataclass
class Finding:
    rule: str
    path: str  # repo-relative, forward slashes
    line: int
    symbol: str  # "Class.method", "function", or "<module>"
    message: str
    severity: str = "P1"

    @property
    def fingerprint(self) -> str:
        """Stable id for the baseline ratchet: rule + site + message,
        line number excluded so moved-but-unchanged findings match."""
        key = "|".join((self.rule, self.path, self.symbol, self.message))
        return hashlib.sha256(key.encode()).hexdigest()[:16]

    def to_dict(self) -> dict:
        return {
            "fingerprint": self.fingerprint,
            "rule": self.rule,
            "path": self.path,
            "line": self.line,
            "symbol": self.symbol,
            "message": self.message,
            "severity": self.severity,
        }

    def format(self) -> str:
        return (
            f"{self.path}:{self.line}: [{self.severity}] {self.rule} "
            f"({self.symbol}): {self.message} [{self.fingerprint}]"
        )


@dataclasses.dataclass
class BaselineEntry:
    fingerprint: str
    rule: str
    path: str
    symbol: str
    message: str
    justification: str

    @classmethod
    def from_finding(
        cls, finding: Finding, justification: str
    ) -> "BaselineEntry":
        return cls(
            fingerprint=finding.fingerprint,
            rule=finding.rule,
            path=finding.path,
            symbol=finding.symbol,
            message=finding.message,
            justification=justification,
        )


def load_baseline(path: str) -> Dict[str, BaselineEntry]:
    with open(path) as f:
        doc = json.load(f)
    out: Dict[str, BaselineEntry] = {}
    for row in doc.get("findings", []):
        entry = BaselineEntry(
            fingerprint=row["fingerprint"],
            rule=row.get("rule", "?"),
            path=row.get("path", "?"),
            symbol=row.get("symbol", "?"),
            message=row.get("message", ""),
            justification=row.get("justification", ""),
        )
        out[entry.fingerprint] = entry
    return out


def save_baseline(
    path: str, entries: Iterable[BaselineEntry]
) -> None:
    doc = {
        "comment": (
            "Ratcheted baseline for scripts/lint_tpudl.py: findings "
            "listed here WARN instead of failing the gate. Every entry "
            "needs a one-line justification; delete entries as the "
            "debt is paid (stale entries warn)."
        ),
        "findings": [dataclasses.asdict(e) for e in entries],
    }
    with open(path, "w") as f:
        json.dump(doc, f, indent=2, sort_keys=False)
        f.write("\n")


@dataclasses.dataclass
class GateResult:
    new: List[Finding]
    baselined: List[Finding]
    stale: List[BaselineEntry]

    @property
    def ok(self) -> bool:
        return not self.new


def apply_baseline(
    findings: List[Finding],
    baseline: Optional[Dict[str, BaselineEntry]],
) -> GateResult:
    baseline = baseline or {}
    seen = set()
    new: List[Finding] = []
    old: List[Finding] = []
    for finding in findings:
        fp = finding.fingerprint
        if fp in baseline:
            seen.add(fp)
            old.append(finding)
        else:
            new.append(finding)
    stale = [e for fp, e in baseline.items() if fp not in seen]
    return GateResult(new=new, baselined=old, stale=stale)
