"""Registry linter + the combined static-analysis runner.

AST rules enforcing the declaration contract of
``tpudl.analysis.registry``:

- ``raw-env-read`` (P0): ``os.environ.get("TPUDL_*")`` /
  ``os.environ["TPUDL_*"]`` anywhere outside the registry module.
  Knobs are read through the typed accessors so every knob is
  declared, defaulted, documented, and visible to the generated
  README table. Keys resolved through module-level constants
  (``KNOB = "TPUDL_X"; os.environ.get(KNOB)``) are caught too.
  Writes (``os.environ[k] = v`` — how benchmarks pin block sizes for
  child dispatches) are not reads and pass.
- ``undeclared-knob`` (P0): a ``TPUDL_*`` string literal that is not
  in the declaration table — either declare it or stop implying it
  exists.
- ``undocumented-knob`` (P1): a declared knob whose name never
  appears in README.md (the generated knob table makes this
  structurally impossible unless the table is stale).
- ``bad-metric-name`` (P1): a ``registry().counter/gauge/histogram``
  name literal that fails the PR-6 Prometheus conformance regex
  (lower_snake_case, no leading digit). F-string names are checked on
  their static fragments; fully dynamic names are the call site's
  responsibility (they sanitize — e.g. the router's _metric_suffix).

``run_lint`` combines these with the concurrency pass
(tpudl.analysis.concurrency) over the threaded subsystems — the one
entry point ``scripts/lint_tpudl.py`` and tier-1's
``tests/test_analysis.py`` share.
"""

from __future__ import annotations

import ast
import os
import re
from typing import Dict, List, Optional, Sequence

from tpudl.analysis import concurrency
from tpudl.analysis.findings import Finding
from tpudl.analysis.registry import (
    KNOBS,
    METRIC_FRAGMENT_RE,
    METRIC_NAME_RE,
)

_KNOB_RE = re.compile(r"^TPUDL_[A-Z0-9_]+$")
_METRIC_FACTORIES = ("counter", "gauge", "histogram")

#: The one module allowed to touch os.environ for TPUDL_* keys.
REGISTRY_MODULE = "tpudl/analysis/registry.py"

#: Threaded subsystems the concurrency pass covers (ISSUE 12 scope).
CONCURRENCY_TARGETS = (
    "tpudl/serve",
    "tpudl/obs",
    "tpudl/ft",
    "tpudl/data",
    "tpudl/train",
)

#: Trees the registry/metric rules scan.
REGISTRY_TARGETS = ("tpudl", "benchmarks", "scripts", "bench.py")


def _iter_py_files(root: str, targets: Sequence[str]) -> List[str]:
    files: List[str] = []
    for target in targets:
        path = os.path.join(root, target)
        if os.path.isfile(path):
            files.append(path)
            continue
        for dirpath, dirnames, filenames in os.walk(path):
            dirnames[:] = [d for d in dirnames if d != "__pycache__"]
            for fn in sorted(filenames):
                if fn.endswith(".py"):
                    files.append(os.path.join(dirpath, fn))
    return files


class _RegistryRuleVisitor(ast.NodeVisitor):
    def __init__(self, path: str, constants: Dict[str, str]):
        self.path = path
        self.constants = constants
        self.findings: List[Finding] = []
        self._scope: List[str] = []

    # -- symbol tracking ------------------------------------------------

    def _symbol(self) -> str:
        return ".".join(self._scope) if self._scope else "<module>"

    def visit_ClassDef(self, node: ast.ClassDef) -> None:
        self._scope.append(node.name)
        self.generic_visit(node)
        self._scope.pop()

    def visit_FunctionDef(self, node) -> None:
        self._scope.append(node.name)
        self.generic_visit(node)
        self._scope.pop()

    visit_AsyncFunctionDef = visit_FunctionDef

    # -- helpers --------------------------------------------------------

    def _knob_key(self, node: ast.AST) -> Optional[str]:
        """The TPUDL_* key an expression statically resolves to."""
        if isinstance(node, ast.Constant) and isinstance(node.value, str):
            return node.value if _KNOB_RE.match(node.value) else None
        if isinstance(node, ast.Name):
            value = self.constants.get(node.id)
            if value is not None and _KNOB_RE.match(value):
                return value
        return None

    @staticmethod
    def _is_os_environ(node: ast.AST) -> bool:
        return (
            isinstance(node, ast.Attribute)
            and node.attr == "environ"
            and isinstance(node.value, ast.Name)
            and node.value.id == "os"
        )

    def _flag_env_read(self, key: str, line: int) -> None:
        self.findings.append(
            Finding(
                rule="raw-env-read",
                path=self.path,
                line=line,
                symbol=self._symbol(),
                message=(
                    f"raw os.environ read of {key} — go through "
                    f"tpudl.analysis.registry (env_str/env_int/"
                    f"env_float/env_flag), which declares, types, and "
                    f"documents every knob"
                ),
                severity="P0",
            )
        )

    # -- rules ----------------------------------------------------------

    def visit_Call(self, node: ast.Call) -> None:
        func = node.func
        # os.environ.get(KEY) / os.environ.setdefault(KEY, ...)
        if (
            isinstance(func, ast.Attribute)
            and func.attr in ("get", "setdefault", "pop")
            and self._is_os_environ(func.value)
            and node.args
        ):
            key = self._knob_key(node.args[0])
            if key is not None:
                self._flag_env_read(key, node.lineno)
        # registry().counter("name") conformance
        if (
            isinstance(func, ast.Attribute)
            and func.attr in _METRIC_FACTORIES
            and node.args
        ):
            self._check_metric_name(node.args[0], node.lineno)
        self.generic_visit(node)

    def visit_Subscript(self, node: ast.Subscript) -> None:
        if self._is_os_environ(node.value) and isinstance(
            node.ctx, ast.Load
        ):
            key = self._knob_key(node.slice)
            if key is not None:
                self._flag_env_read(key, node.lineno)
        self.generic_visit(node)

    def visit_Constant(self, node: ast.Constant) -> None:
        if (
            isinstance(node.value, str)
            and _KNOB_RE.match(node.value)
            and node.value not in KNOBS
        ):
            self.findings.append(
                Finding(
                    rule="undeclared-knob",
                    path=self.path,
                    line=node.lineno,
                    symbol=self._symbol(),
                    message=(
                        f"{node.value} is not declared in "
                        f"tpudl.analysis.registry.KNOBS"
                    ),
                    severity="P0",
                )
            )

    def _check_metric_name(self, arg: ast.AST, line: int) -> None:
        bad: Optional[str] = None
        if isinstance(arg, ast.Constant) and isinstance(arg.value, str):
            if not METRIC_NAME_RE.match(arg.value):
                bad = repr(arg.value)
        elif isinstance(arg, ast.JoinedStr):
            fragments = [
                v.value for v in arg.values
                if isinstance(v, ast.Constant)
                and isinstance(v.value, str)
            ]
            if any(
                not METRIC_FRAGMENT_RE.match(f) for f in fragments
            ):
                bad = "".join(fragments) and repr("".join(fragments))
        if bad:
            self.findings.append(
                Finding(
                    rule="bad-metric-name",
                    path=self.path,
                    line=line,
                    symbol=self._symbol(),
                    message=(
                        f"metric name {bad} fails the Prometheus "
                        f"conformance regex "
                        f"{METRIC_NAME_RE.pattern!r} — the /metrics "
                        f"exposition would need sanitizing"
                    ),
                    severity="P1",
                )
            )


def _module_constants(tree: ast.Module) -> Dict[str, str]:
    constants: Dict[str, str] = {}
    for node in tree.body:
        if isinstance(node, ast.Assign) and isinstance(
            node.value, ast.Constant
        ):
            if isinstance(node.value.value, str):
                for target in node.targets:
                    if isinstance(target, ast.Name):
                        constants[target.id] = node.value.value
    return constants


def lint_source(
    source: str, path: str, skip_env_rule: bool = False
) -> List[Finding]:
    """Registry-family rules over one file's source text."""
    tree = ast.parse(source, filename=path)
    visitor = _RegistryRuleVisitor(path, _module_constants(tree))
    visitor.visit(tree)
    findings = visitor.findings
    if skip_env_rule:
        findings = [f for f in findings if f.rule != "raw-env-read"]
    return findings


def lint_registry(repo_root: str) -> List[Finding]:
    findings: List[Finding] = []
    for file in _iter_py_files(repo_root, REGISTRY_TARGETS):
        rel = os.path.relpath(file, repo_root).replace(os.sep, "/")
        with open(file) as f:
            source = f.read()
        findings.extend(
            lint_source(
                source, rel, skip_env_rule=(rel == REGISTRY_MODULE)
            )
        )
    findings.extend(_readme_findings(repo_root))
    return findings


def _readme_findings(repo_root: str) -> List[Finding]:
    readme = os.path.join(repo_root, "README.md")
    if not os.path.exists(readme):
        return []
    with open(readme) as f:
        text = f.read()
    findings: List[Finding] = []
    for name in sorted(KNOBS):
        if name not in text:
            findings.append(
                Finding(
                    rule="undocumented-knob",
                    path="README.md",
                    line=1,
                    symbol=name,
                    message=(
                        f"declared knob {name} does not appear in "
                        f"README.md — regenerate the knob table "
                        f"(scripts/lint_tpudl.py --knob-table)"
                    ),
                    severity="P1",
                )
            )
    return findings


def run_lint(repo_root: str) -> List[Finding]:
    """The full static tier: concurrency over the threaded subsystems
    + registry/metric/knob rules over the runtime tree."""
    findings = concurrency.analyze_paths(
        [
            os.path.join(repo_root, t)
            for t in CONCURRENCY_TARGETS
            if os.path.exists(os.path.join(repo_root, t))
        ],
        repo_root=repo_root,
    )
    findings.extend(lint_registry(repo_root))
    findings.sort(key=lambda f: (f.path, f.line, f.rule))
    return findings
