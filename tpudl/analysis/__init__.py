"""tpudl.analysis — first-party static + runtime analysis tier.

Three families (ISSUE 12), one ratcheted gate:

- ``concurrency``: per-class lock-acquisition graphs (lock-order
  inversions), guarded-vs-unguarded shared-attribute writes, and the
  ``TPUDL_DEBUG_LOCK_ORDER`` runtime ordered-lock monitor.
- ``dispatch``: runtime audits for the compiled hot paths —
  ``assert_no_recompiles`` / ``assert_no_host_transfers`` (jax
  monitoring + transfer guards) and the generalized buffer-donation
  audit (``donation``).
- ``registry`` + ``lint``: the central ``TPUDL_*`` knob declaration
  table, typed env accessors, the Prometheus metric-name conformance
  rule, and the AST linter enforcing all of it.

``scripts/lint_tpudl.py`` runs the static families against the
checked-in ``analysis_baseline.json`` (new findings fail, baselined
ones warn) and is part of tier-1 via tests/test_analysis.py.

This package keeps its import cost near zero: ``registry`` is
stdlib-only (it is imported by tpudl.obs.counters and the runtime
bootstrap), and the analyzer modules — some of which import jax —
load lazily on first attribute access.
"""

from __future__ import annotations

from tpudl.analysis.findings import (  # noqa: F401
    BaselineEntry,
    Finding,
    GateResult,
    apply_baseline,
    load_baseline,
    save_baseline,
)
from tpudl.analysis.registry import (  # noqa: F401
    KNOBS,
    METRIC_NAME_RE,
    env_flag,
    env_float,
    env_int,
    env_raw,
    env_require,
    env_str,
    knob_table_markdown,
)

_LAZY = {
    "analyze_paths": ("tpudl.analysis.concurrency", "analyze_paths"),
    "derive_lock_ranks": (
        "tpudl.analysis.concurrency", "derive_lock_ranks"
    ),
    "LockOrderMonitor": (
        "tpudl.analysis.concurrency", "LockOrderMonitor"
    ),
    "LockOrderViolation": (
        "tpudl.analysis.concurrency", "LockOrderViolation"
    ),
    "wrap_instance_locks": (
        "tpudl.analysis.concurrency", "wrap_instance_locks"
    ),
    "maybe_wrap_locks": (
        "tpudl.analysis.concurrency", "maybe_wrap_locks"
    ),
    "assert_no_recompiles": (
        "tpudl.analysis.dispatch", "assert_no_recompiles"
    ),
    "assert_no_host_transfers": (
        "tpudl.analysis.dispatch", "assert_no_host_transfers"
    ),
    "RecompileWatcher": ("tpudl.analysis.dispatch", "RecompileWatcher"),
    "DispatchHygieneError": (
        "tpudl.analysis.dispatch", "DispatchHygieneError"
    ),
    "audit_donation": ("tpudl.analysis.donation", "audit_donation"),
    "assert_donation": ("tpudl.analysis.donation", "assert_donation"),
    "DonationError": ("tpudl.analysis.donation", "DonationError"),
    "run_lint": ("tpudl.analysis.lint", "run_lint"),
}


def __getattr__(name: str):
    target = _LAZY.get(name)
    if target is None:
        raise AttributeError(
            f"module 'tpudl.analysis' has no attribute {name!r}"
        )
    import importlib

    module = importlib.import_module(target[0])
    value = getattr(module, target[1])
    globals()[name] = value
    return value
