"""Buffer-donation audit, callable on any compiled program.

PR 5's donation test proved the fused train step donates its state
(every old leaf deleted, >=80% of buffer pointers reused in place);
that check lived inside one test. This generalizes it: hand
``audit_donation`` any compiled callable plus its args, name which
positional args the program is supposed to donate, and get back the
outputs plus a report — so serving decode (donates its KV cache),
the fused K-step window, and future compiled paths all audit with the
same ten lines.

Donation failing SILENTLY is the point: XLA falls back to copying when
a donated buffer cannot be aliased (layout mismatch, an extra
reference, a dtype change), the program stays correct, and the only
symptom is doubled memory traffic on the hot loop. The audit makes it
loud:

    out, report = audit_donation(step, (state, batch, rng),
                                 donate_argnums=(0,))
    assert report.ok, report.describe()

The pointer-reuse check compares ``unsafe_buffer_pointer`` of the
donated input shards against every output leaf's — reuse means XLA
aliased in place rather than copied. ``min_reuse`` defaults to 0.8:
scalars and tiny leaves legitimately land elsewhere.

NOTE: the audited call CONSUMES its donated args (that is what
donation means) — pass state you can afford to lose, and keep using
the returned outputs.
"""

from __future__ import annotations

import dataclasses
from typing import Any, List, Sequence, Tuple


class DonationError(AssertionError):
    """A program expected to donate copied instead."""


@dataclasses.dataclass
class DonationReport:
    num_leaves: int
    num_deleted: int
    reuse_frac: float
    min_reuse: float
    #: jax.tree_util key paths of donated leaves still alive after the
    #: call (donation silently fell back to copy for these).
    undeleted: List[str]

    @property
    def ok(self) -> bool:
        return not self.undeleted and self.reuse_frac >= self.min_reuse

    def describe(self) -> str:
        if self.ok:
            return (
                f"donation ok: {self.num_deleted}/{self.num_leaves} "
                f"leaves consumed, {self.reuse_frac:.0%} buffers "
                f"reused in place"
            )
        parts = []
        if self.undeleted:
            shown = ", ".join(self.undeleted[:8])
            more = (
                f" (+{len(self.undeleted) - 8} more)"
                if len(self.undeleted) > 8 else ""
            )
            parts.append(
                f"{len(self.undeleted)}/{self.num_leaves} donated "
                f"leaves were NOT consumed — XLA fell back to copying "
                f"them: {shown}{more}"
            )
        if self.reuse_frac < self.min_reuse:
            parts.append(
                f"only {self.reuse_frac:.0%} of donated buffer "
                f"pointers reappear in the outputs "
                f"(need >= {self.min_reuse:.0%}) — leaves are "
                f"silently copying"
            )
        return "donation audit failed: " + "; ".join(parts)


def buffer_pointers(tree) -> set:
    """Device buffer pointers of every addressable shard in a pytree."""
    import jax

    out = set()
    for leaf in jax.tree.leaves(tree):
        shards = getattr(leaf, "addressable_shards", None)
        if shards is None:
            continue
        for shard in shards:
            out.add(shard.data.unsafe_buffer_pointer())
    return out


def audit_donation(
    fn,
    args: Sequence[Any],
    donate_argnums: Sequence[int] = (0,),
    min_reuse: float = 0.8,
) -> Tuple[Any, DonationReport]:
    """Run ``fn(*args)`` and report whether the args named by
    ``donate_argnums`` were actually donated (consumed + buffers
    reused in the outputs). Returns ``(outputs, report)``."""
    import jax

    donated = [args[i] for i in donate_argnums]
    labeled = [
        (jax.tree_util.keystr(path), leaf)
        for arg in donated
        for path, leaf in jax.tree_util.tree_flatten_with_path(arg)[0]
    ]
    old_ptrs = buffer_pointers(donated)
    outputs = fn(*args)
    undeleted = [
        key for key, leaf in labeled
        if hasattr(leaf, "is_deleted") and not leaf.is_deleted()
    ]
    new_ptrs = buffer_pointers(outputs)
    reuse = (
        len(old_ptrs & new_ptrs) / len(old_ptrs) if old_ptrs else 1.0
    )
    report = DonationReport(
        num_leaves=len(labeled),
        num_deleted=len(labeled) - len(undeleted),
        reuse_frac=reuse,
        min_reuse=min_reuse,
        undeleted=undeleted,
    )
    return outputs, report


def assert_donation(
    fn,
    args: Sequence[Any],
    donate_argnums: Sequence[int] = (0,),
    min_reuse: float = 0.8,
) -> Any:
    """``audit_donation`` that raises :class:`DonationError` on
    failure and returns the program outputs on success."""
    outputs, report = audit_donation(
        fn, args, donate_argnums=donate_argnums, min_reuse=min_reuse
    )
    if not report.ok:
        raise DonationError(report.describe())
    return outputs
