"""Attention ops: the single seam all tpudl transformer models go through.

`dot_product_attention(q, k, v, ...)` is the reference implementation
(einsum + f32 softmax). `attend()` dispatches by implementation name so
models can switch to the Pallas flash kernel (tpudl.ops.flash_attention) or
the ring/sequence-parallel path (tpudl.ops.ring_attention) without touching
model code. The reference repo has no attention anywhere (its NLP family is
an empty placeholder — reference notebooks/nlp/README.md, SURVEY.md §5.7);
this design makes long-context support first-class instead.

Shapes follow the TPU-friendly convention:
  q, k, v: [batch, seq, heads, head_dim]   (BSHD)
  mask:    broadcastable to [batch, heads, q_seq, kv_seq], True = attend
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

#: Large negative fill for masked logits, safe in bf16.
MASK_VALUE = -0.7 * float(jnp.finfo(jnp.float32).max)


def is_tpu_backend() -> bool:
    """Whether the default JAX backend is a TPU — the one place the
    platform list lives ("axon" is a TPU relay registered under another
    platform name). Gates Pallas-kernel defaults: the TPU kernels lower
    only here, and run interpreted elsewhere."""
    return jax.default_backend() in ("tpu", "axon")


def dot_product_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    mask: Optional[jax.Array] = None,
    scale: Optional[float] = None,
    dropout_rate: float = 0.0,
    dropout_rng: Optional[jax.Array] = None,
    dropout_exact: bool = False,
) -> jax.Array:
    """Reference attention: bf16 matmuls on the MXU, softmax in f32.

    q: [B, Sq, H, D]; k, v: [B, Skv, H, D]; returns [B, Sq, H, D].
    ``dropout_rate`` drops attention probabilities (BERT-style) when a
    ``dropout_rng`` is supplied — via low-width hardware bits by default
    (rate quantized to 1/256, tpudl.ops.dropout); ``dropout_exact=True``
    restores bit-exact jax.random.bernoulli masks (4x the bit traffic).
    """
    if scale is None:
        scale = q.shape[-1] ** -0.5
    logits = jnp.einsum("bqhd,bkhd->bhqk", q, k) * scale
    logits = logits.astype(jnp.float32)
    if mask is not None:
        logits = jnp.where(mask, logits, MASK_VALUE)
    weights = jax.nn.softmax(logits, axis=-1)
    weights = weights.astype(v.dtype)
    if dropout_rate > 0.0 and dropout_rng is not None:
        from tpudl.ops.dropout import dropout_keep_mask, quantized_rate

        # Low-width-bits mask (tpudl.ops.dropout): 4x less random-bit
        # traffic than bernoulli — 14.5 ms/step on the headline BERT
        # fine-tune; rate quantizes to 1/256 unless dropout_exact, and
        # the rescale uses the EFFECTIVE (quantized) rate so expectation
        # is preserved exactly.
        keep = dropout_keep_mask(
            dropout_rng, weights.shape, dropout_rate, exact=dropout_exact
        )
        eff = quantized_rate(dropout_rate, dropout_exact)
        weights = jnp.where(keep, weights / (1.0 - eff), 0.0).astype(
            v.dtype
        )
    return jnp.einsum("bhqk,bkhd->bqhd", weights, v)


def causal_mask(q_len: int, kv_len: int) -> jax.Array:
    """[1, 1, q_len, kv_len] lower-triangular mask (True = attend)."""
    i = jnp.arange(q_len)[:, None] + (kv_len - q_len)
    j = jnp.arange(kv_len)[None, :]
    return (j <= i)[None, None, :, :]


def padding_mask(attention_mask: jax.Array) -> jax.Array:
    """[B, Skv] 1/0 padding mask -> [B, 1, 1, Skv] boolean attend-mask."""
    return attention_mask[:, None, None, :].astype(bool)


def normalize_kv_mask(
    mask: Optional[jax.Array],
    batch: int,
    kv_len: int,
    dtype=jnp.int32,
    impl: str = "attention",
) -> jax.Array:
    """The kv-validity-mask contract shared by the flash/ring/ulysses
    implementations: None -> all-ones; [B, 1, 1, S] padding masks squeeze
    to [B, S]; dense [B, H, Sq, Skv] masks are rejected (only the
    reference implementation supports those)."""
    if mask is None:
        return jnp.ones((batch, kv_len), dtype)
    if mask.ndim == 4:
        if mask.shape[1] != 1 or mask.shape[2] != 1:
            raise NotImplementedError(
                f"{impl} supports [B, S] / [B, 1, 1, S] padding masks and "
                f"causal=True; got dense mask {mask.shape} — use "
                f"implementation='reference'"
            )
        mask = mask[:, 0, 0, :]
    return jnp.broadcast_to(mask, (batch, kv_len)).astype(dtype)


def combine_kv_causal_mask(
    mask: Optional[jax.Array], q_len: int, kv_len: int, causal: bool
) -> Optional[jax.Array]:
    """The one mask-assembly rule every einsum-path implementation shares:
    lift a [B, Skv] kv-validity row to [B, 1, 1, Skv] (4-D masks pass
    through), then AND in the causal triangle when asked — a causal model
    with padded batches must not see future positions just because a
    padding mask is set. Returns None when nothing masks."""
    if mask is not None and mask.ndim == 2:
        mask = padding_mask(mask)
    if causal:
        tri = causal_mask(q_len, kv_len)
        mask = tri if mask is None else jnp.logical_and(mask.astype(bool), tri)
    return mask


def unmeshed_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    mask: Optional[jax.Array],
    causal: bool,
    scale: Optional[float] = None,
    dropout_rate: float = 0.0,
    dropout_rng: Optional[jax.Array] = None,
) -> jax.Array:
    """Single-device degenerate path for the sequence-parallel
    implementations: reference attention with the kv-validity mask and the
    causal triangle combined by combine_kv_causal_mask."""
    if mask is not None:
        mask = normalize_kv_mask(mask, q.shape[0], k.shape[1])
    return dot_product_attention(
        q, k, v, combine_kv_causal_mask(mask, q.shape[1], k.shape[1], causal),
        scale=scale, dropout_rate=dropout_rate, dropout_rng=dropout_rng,
    )


def attend(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    mask: Optional[jax.Array] = None,
    *,
    implementation: str = "reference",
    causal: bool = False,
    dropout_rate: float = 0.0,
    dropout_rng: Optional[jax.Array] = None,
    dropout_exact: bool = False,
) -> jax.Array:
    """Dispatch to an attention implementation.

    implementation:
      "reference" — this module's einsum attention (any backend);
      "fused"     — Pallas TPU fused short-seq kernel (full softmax per
                    cell, one-pass backward, IN-KERNEL attention dropout
                    from the hardware PRNG);
      "flash"     — Pallas TPU flash-attention kernel (streaming online
                    softmax; in-kernel dropout at any length);
      "ring"      — sequence-parallel ring attention over the `sp` mesh
                    axis (ppermute K/V rotation, online-softmax merge);
      "ulysses"   — sequence-parallel attention via all-to-all head/seq
                    resharding over `sp` (requires local heads divisible
                    by sp; per-device body is flash on TPU, exact
                    reference numerics on CPU — ulysses_attention's
                    local_impl parameter pins either).

    Attention-probability dropout is supported by EVERY implementation
    (round 4): the Pallas kernels draw in-kernel from the TPU hardware
    PRNG; ulysses applies per-head dropout on its post-all-to-all local
    sequences; ring masks the online-softmax numerator per
    (q-shard, kv-block) tile while denominators stay undropped — exact
    post-softmax semantics even though the softmax itself is
    distributed. Sharded paths fold each mesh slot's position into the
    key, so mask BITS (not statistics) depend on the mesh layout.
    """
    if dropout_rate > 0.0 and dropout_rng is None:
        raise ValueError(
            "dropout_rate > 0 requires a dropout_rng (dropout would "
            "otherwise be silently skipped)"
        )
    if dropout_exact and dropout_rate > 0.0 and implementation != "reference":
        raise ValueError(
            "dropout_exact (bit-exact bernoulli masks) is only available "
            "on implementation='reference'; the fused kernel draws from "
            "the TPU hardware PRNG"
        )
    if implementation == "reference":
        mask = combine_kv_causal_mask(mask, q.shape[1], k.shape[1], causal)
        return dot_product_attention(
            q, k, v, mask, dropout_rate=dropout_rate,
            dropout_rng=dropout_rng, dropout_exact=dropout_exact,
        )
    if implementation == "fused":
        # Three regimes (measured, benchmarks/bert_attn_seq128.py +
        # BASELINE.md): at short S, XLA's batched matmuls are unbeatable
        # and only softmax+dropout is worth fusing (hybrid); at mid S the
        # whole-attention kernel wins (S=256/512: 4.1/4.3 ms vs einsum's
        # 5.0/5.5 fwd+bwd); past MAX_SEQ its one-pass backward blows VMEM
        # and flash's streaming design takes over (with its own
        # in-kernel dropout — see the fallthrough below).
        from tpudl.ops.fused_attention import MAX_SEQ, fused_attention

        if q.shape[1] <= 256:
            from tpudl.ops.softmax_dropout import hybrid_attention

            return hybrid_attention(
                q, k, v, mask=mask, causal=causal,
                dropout_rate=dropout_rate, dropout_rng=dropout_rng,
            )
        if q.shape[1] <= MAX_SEQ:
            return fused_attention(
                q, k, v, mask=mask, causal=causal,
                dropout_rate=dropout_rate, dropout_rng=dropout_rng,
            )
        # Past MAX_SEQ the streaming flash kernel takes over — WITH
        # in-kernel dropout (the round-3 S>512 dropout carve-out is gone;
        # configs[4]'s seq-2048 fine-tune trains with real
        # attention_dropout now). Falls through to the shared branch.
        implementation = "flash"
    if implementation == "flash":
        from tpudl.ops.flash_attention import flash_attention

        return flash_attention(
            q, k, v, mask=mask, causal=causal,
            dropout_rate=dropout_rate, dropout_rng=dropout_rng,
        )
    if implementation == "ulysses":
        # Exact dropout under SP: post-all-to-all every head is fully
        # local, so the per-head masks are plain BERT/Llama semantics.
        from tpudl.ops.ulysses import ulysses_attention

        return ulysses_attention(
            q, k, v, mask=mask, causal=causal,
            dropout_rate=dropout_rate, dropout_rng=dropout_rng,
        )
    if implementation == "ring":
        # Exact post-softmax dropout despite the distributed softmax:
        # the online merge keeps denominators undropped and masks only
        # the numerator per (q-shard, kv-block) tile.
        from tpudl.ops.ring_attention import ring_attention

        return ring_attention(
            q, k, v, mask=mask, causal=causal,
            dropout_rate=dropout_rate, dropout_rng=dropout_rng,
        )
    raise ValueError(f"unknown attention implementation: {implementation!r}")
