"""TPU kernels and fused ops (Pallas flash attention, ring attention)."""
