"""TPU ops: attention behind one dispatch seam, and expert-parallel MoE.

- attention.py        — reference einsum attention (+ masks, dropout);
- flash_attention.py  — Pallas fused online-softmax kernel, fwd + bwd;
- ring_attention.py   — sequence-parallel ring attention over `sp`
                        (ppermute K/V rotation, online-softmax merge);
- ulysses.py          — sequence-parallel attention over `sp` via
                        all-to-all head/seq resharding (exact numerics);
- moe.py              — top-k routed expert FFN over `ep` (all-to-all).
"""

from tpudl.ops.attention import (  # noqa: F401
    attend,
    causal_mask,
    dot_product_attention,
    padding_mask,
)
from tpudl.ops.flash_attention import (  # noqa: F401
    flash_attention,
    flash_attention_with_lse,
)
from tpudl.ops.ring_attention import ring_attention  # noqa: F401
from tpudl.ops.ulysses import ulysses_attention  # noqa: F401
from tpudl.ops.moe import (  # noqa: F401
    EP_MOE_RULES,
    MoEMlp,
    expert_capacity,
    route_topk,
    with_moe_rules,
)
