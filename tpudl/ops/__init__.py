"""TPU attention ops behind one dispatch seam (tpudl.ops.attend):

- attention.py        — reference einsum attention (+ masks, dropout);
- flash_attention.py  — Pallas fused online-softmax kernel, fwd + bwd;
- ring_attention.py   — sequence-parallel ring attention over `sp`.
"""

from tpudl.ops.attention import (  # noqa: F401
    attend,
    causal_mask,
    dot_product_attention,
    padding_mask,
)
