"""TPU ops: attention behind one dispatch seam, the fused-epilogue
kernel tier, and expert-parallel MoE.

- attention.py        — reference einsum attention (+ masks, dropout);
- flash_attention.py  — Pallas fused online-softmax kernel, fwd + bwd;
- fused_attention.py  — Pallas fused short/mid-seq attention (full
                        softmax per cell, one-pass backward, in-kernel
                        hardware-PRNG dropout);
- softmax_dropout.py  — Pallas fused softmax(+mask)+dropout for the
                        short-seq hybrid path (XLA matmuls around it);
- ring_attention.py   — sequence-parallel ring attention over `sp`
                        (ppermute K/V rotation, online-softmax merge);
- ulysses.py          — sequence-parallel attention over `sp` via
                        all-to-all head/seq resharding (exact numerics);
- norms.py            — fused LayerNorm/RMSNorm(+residual-add), f32
                        statistics, one-pass backward;
- mlp_fused.py        — fused bias+GeLU (exact erf) and SwiGLU MLP
                        epilogues, recompute-free backward;
- cross_entropy.py    — fused softmax-cross-entropy streaming the vocab
                        axis (online logsumexp; the [B, V] softmax is
                        never materialized);
- segmented_lora.py   — heterogeneous-adapter batched LoRA delta over
                        page pools (gather-from-pool in-kernel, f32
                        accumulation; the multi-tenant serving matmul);
- fp8_dot.py          — fp8 TRAINING matmul (e4m3 fwd / e5m2 grad) with
                        delayed scaling: per-tensor amax-history rings
                        as traced state, saturate-don't-NaN casts,
                        gradient amax via the g_probe cotangent;
- moe.py              — top-k routed expert FFN over `ep` (all-to-all).
"""

from tpudl.ops.attention import (  # noqa: F401
    attend,
    causal_mask,
    dot_product_attention,
    padding_mask,
)
from tpudl.ops.flash_attention import (  # noqa: F401
    flash_attention,
    flash_attention_with_lse,
)
from tpudl.ops.fused_attention import fused_attention  # noqa: F401
from tpudl.ops.softmax_dropout import (  # noqa: F401
    hybrid_attention,
    softmax_dropout,
)
from tpudl.ops.ring_attention import ring_attention  # noqa: F401
from tpudl.ops.ulysses import ulysses_attention  # noqa: F401
from tpudl.ops.norms import (  # noqa: F401
    fused_ops_impl,
    layer_norm,
    layer_norm_ref,
    rms_norm,
    rms_norm_ref,
)
from tpudl.ops.mlp_fused import (  # noqa: F401
    bias_gelu,
    bias_gelu_ref,
    swiglu,
    swiglu_ref,
)
from tpudl.ops.cross_entropy import (  # noqa: F401
    softmax_cross_entropy,
    softmax_cross_entropy_ref,
)
from tpudl.ops.segmented_lora import (  # noqa: F401
    segmented_lora,
    segmented_lora_ref,
)
from tpudl.ops.fp8_dot import (  # noqa: F401
    Fp8Dense,
    fp8_dot,
)
from tpudl.ops.moe import (  # noqa: F401
    EP_MOE_RULES,
    MoEMlp,
    expert_capacity,
    route_topk,
    with_moe_rules,
)
