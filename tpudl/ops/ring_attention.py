"""Ring attention: sequence-parallel attention over the `sp` mesh axis.

Long-context support the reference lineage never had (its NLP family is an
empty placeholder — reference notebooks/nlp/README.md; SURVEY.md §5.7
records sequence parallelism as the declared TPU-idiomatic path). Design:
activations arrive sharded [B, S/n, H, D] along `sp`; each device computes
blockwise attention against the K/V shard it currently holds while
`ppermute` rotates K/V (and the kv-validity mask) one hop around the ring.
After n steps every query shard has seen every K/V shard, the partial
softmax statistics having been merged online — the full [S, S] logits
matrix never exists, per-device attention memory is O(S^2 / n^2), and the
K/V transfers ride neighbor-to-neighbor ICI hops that overlap with the
per-block compute.

The loop is a `lax.scan` (reverse-differentiable, unlike while/fori), so
the same code trains: gradients flow through `ppermute`'s transpose
(another ppermute in the reverse direction, also riding ICI).
"""

from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from tpudl.ops.attention import MASK_VALUE
from tpudl.runtime.mesh import AXIS_SEQ, BATCH_AXES, AXIS_TENSOR


def _ring_local(q, k, v, kvm, key_data=None, *, axis_name, scale, causal,
                dropout_rate=0.0, key_impl=None, fold_axes=()):
    """Per-device ring loop. q, k, v: [b, s_local, h, d]; kvm: [b, s_local].

    Device i starts holding kv block i; after t rotations it holds block
    (i - t) mod n. The online-softmax merge is the same recurrence as the
    flash kernel's (tpudl.ops.flash_attention), at shard granularity.

    Dropout (round 4) uses the flash kernel's factorization: dropout acts
    AFTER softmax normalization, so the denominator ``l`` accumulates
    undropped probabilities while only the p@V numerator is masked, with
    the 1/(1-rate) rescale applied once at the end. Masks are a pure
    function of (key, q-shard position, kv rotation index), drawn with
    the low-width-bits generator per tile — autodiff through the scan
    replays the identical draw, so forward and backward masks agree by
    construction (no custom-vjp contract needed at this granularity).
    """
    n = jax.lax.psum(1, axis_name)
    idx = jax.lax.axis_index(axis_name)
    b, s_l, h, d = q.shape

    dropout_on = dropout_rate > 0.0
    if dropout_on:
        from tpudl.ops.dropout import (
            device_fold_rng,
            dropout_keep_mask,
            quantized_rate,
        )

        rng = device_fold_rng(key_data, key_impl, fold_axes)
        eff_rate = quantized_rate(dropout_rate, exact=False)
        inv_keep = 1.0 / (1.0 - eff_rate)

    q32 = q.astype(jnp.float32)
    m0 = jnp.full((b, h, s_l, 1), MASK_VALUE, jnp.float32)
    l0 = jnp.zeros((b, h, s_l, 1), jnp.float32)
    acc0 = jnp.zeros((b, h, s_l, d), jnp.float32)
    q_ids = idx * s_l + jax.lax.broadcasted_iota(jnp.int32, (s_l, 1), 0)

    perm = [(i, (i + 1) % n) for i in range(n)]

    def body(carry, t):
        m, l, acc, k, v, kvm = carry
        src = (idx - t) % n  # global block index of the kv shard we hold
        s = jnp.einsum("bqhd,bkhd->bhqk", q32, k.astype(jnp.float32)) * scale
        keep = (kvm > 0)[:, None, None, :]
        if causal:
            kv_ids = src * s_l + jax.lax.broadcasted_iota(
                jnp.int32, (1, s_l), 1
            )
            keep = jnp.logical_and(keep, (kv_ids <= q_ids)[None, None, :, :])
        s = jnp.where(keep, s, MASK_VALUE)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.where(keep, jnp.exp(s - m_new), 0.0)
        corr = jnp.exp(m - m_new)
        # Denominator: UNDROPPED p (dropout acts post-normalization).
        l = l * corr + jnp.sum(p, axis=-1, keepdims=True)
        if dropout_on:
            # Tile keyed by the GLOBAL kv block this device holds at
            # tick t — every (q-shard, kv-block) pair draws its own mask.
            keep_d = dropout_keep_mask(
                jax.random.fold_in(rng, src), p.shape, dropout_rate,
                exact=False,
            )
            p_num = jnp.where(keep_d, p, 0.0)
        else:
            p_num = p
        acc = acc * corr + jnp.einsum(
            "bhqk,bkhd->bhqd", p_num.astype(v.dtype), v
        ).astype(jnp.float32)
        k, v, kvm = (
            jax.lax.ppermute(x, axis_name, perm) for x in (k, v, kvm)
        )
        return (m_new, l, acc, k, v, kvm), None

    (m, l, acc, _, _, _), _ = jax.lax.scan(
        body, (m0, l0, acc0, k, v, kvm), jnp.arange(n)
    )
    l_safe = jnp.where(l > 0.0, l, 1.0)
    o = acc / l_safe
    if dropout_on:
        o = o * inv_keep
    o = o.transpose(0, 2, 1, 3)  # [b, s_l, h, d]
    return o.astype(q.dtype)


def ring_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    mask: Optional[jax.Array] = None,
    causal: bool = False,
    scale: Optional[float] = None,
    mesh: Optional[Mesh] = None,
    axis_name: str = AXIS_SEQ,
    dropout_rate: float = 0.0,
    dropout_rng: Optional[jax.Array] = None,
) -> jax.Array:
    """Sequence-parallel attention on [B, S, H, D] (the
    tpudl.ops.attention contract; Sq == Skv required — queries and keys
    shard along the same sequence axis).

    ``mask`` may be a [B, S] kv-validity mask or a [B, 1, 1, S] padding
    mask; dense masks are rejected like tpudl.ops.flash_attention.
    ``mesh`` defaults to the active tpudl mesh
    (tpudl.parallel.sharding.active_mesh); batch shards over (dp, fsdp),
    sequence over `sp`, heads over `tp`.

    ``dropout_rate`` > 0 (round 4): attention-probability dropout with
    exact post-softmax semantics despite the distributed softmax — the
    online merge keeps the denominator undropped while the numerator is
    masked per (q-shard, kv-block) tile (see _ring_local). Rate
    quantizes to 1/256 (the low-width-bits generator). Each mesh slot
    folds its position into ``dropout_rng``; mask BITS therefore depend
    on the mesh layout, like every sharded dropout path.
    """
    from tpudl.ops.attention import normalize_kv_mask, unmeshed_attention
    from tpudl.parallel.sharding import current_mesh

    if dropout_rate > 0.0 and dropout_rng is None:
        raise ValueError("dropout_rate > 0 requires a dropout_rng")

    if mesh is None:
        mesh = current_mesh()
    if mesh is None:
        # No mesh (single-device init/eval): ring degenerates to reference
        # attention — numerically identical, so models with
        # attention_impl="ring" init and evaluate unmeshed.
        return unmeshed_attention(
            q, k, v, mask, causal, scale,
            dropout_rate=dropout_rate, dropout_rng=dropout_rng,
        )
    b, s, h, d = q.shape
    if k.shape[1] != s:
        raise ValueError(
            f"ring attention shards q and kv along one sequence axis; "
            f"got Sq={s}, Skv={k.shape[1]}"
        )
    n_sp = mesh.shape[axis_name]
    if s % n_sp != 0:
        raise ValueError(f"seq len {s} not divisible by {axis_name}={n_sp}")
    if scale is None:
        scale = d ** -0.5

    kvm = normalize_kv_mask(mask, b, s, impl="ring_attention")

    batch = tuple(a for a in BATCH_AXES if mesh.shape[a] > 1) or None
    n_tp = mesh.shape[AXIS_TENSOR]
    heads_sharded = h % max(n_tp, 1) == 0 and n_tp > 1
    heads = AXIS_TENSOR if heads_sharded else None
    qkv_spec = P(batch, axis_name, heads, None)
    key_impl = (
        jax.random.key_impl(dropout_rng) if dropout_rate > 0.0 else None
    )
    from tpudl.ops.dropout import shard_fold_axes

    fold_axes = shard_fold_axes(mesh, axis_name, heads_sharded, BATCH_AXES)
    body = partial(
        _ring_local, axis_name=axis_name, scale=scale, causal=causal,
        dropout_rate=dropout_rate, key_impl=key_impl, fold_axes=fold_axes,
    )
    operands = [q, k, v, kvm]
    in_specs = [qkv_spec, qkv_spec, qkv_spec, P(batch, axis_name)]
    if dropout_rate > 0.0:
        operands.append(jax.random.key_data(dropout_rng))
        in_specs.append(
            P(*([None] * jax.random.key_data(dropout_rng).ndim))
        )
    fn = jax.shard_map(
        body,
        mesh=mesh,
        in_specs=tuple(in_specs),
        out_specs=qkv_spec,
        check_vma=False,
    )
    return fn(*operands)
