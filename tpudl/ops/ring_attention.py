"""Ring attention: sequence-parallel attention over the `sp` mesh axis.

Long-context support the reference lineage never had (its NLP family is an
empty placeholder — reference notebooks/nlp/README.md; SURVEY.md §5.7
records sequence parallelism as the declared TPU-idiomatic path). Design:
activations arrive sharded [B, S/n, H, D] along `sp`; each device computes
blockwise attention against the K/V shard it currently holds while
`ppermute` rotates K/V (and the kv-validity mask) one hop around the ring.
After n steps every query shard has seen every K/V shard, the partial
softmax statistics having been merged online — the full [S, S] logits
matrix never exists, per-device attention memory is O(S^2 / n^2), and the
K/V transfers ride neighbor-to-neighbor ICI hops that overlap with the
per-block compute.

The loop is a `lax.scan` (reverse-differentiable, unlike while/fori), so
the same code trains: gradients flow through `ppermute`'s transpose
(another ppermute in the reverse direction, also riding ICI).
"""

from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from tpudl.ops.attention import MASK_VALUE
from tpudl.runtime.mesh import AXIS_SEQ, BATCH_AXES, AXIS_TENSOR, shard_map


def _ring_local(q, k, v, kvm, key_data=None, *, axis_name, scale, causal,
                dropout_rate=0.0, key_impl=None, fold_axes=()):
    """Per-device ring loop. q, k, v: [b, s_local, h, d]; kvm: [b, s_local].

    Device i starts holding kv block i; after t rotations it holds block
    (i - t) mod n. The online-softmax merge is the same recurrence as the
    flash kernel's (tpudl.ops.flash_attention), at shard granularity.

    Dropout (round 4) uses the flash kernel's factorization: dropout acts
    AFTER softmax normalization, so the denominator ``l`` accumulates
    undropped probabilities while only the p@V numerator is masked, with
    the 1/(1-rate) rescale applied once at the end. Masks are a pure
    function of (key, q-shard position, kv rotation index), drawn with
    the low-width-bits generator per tile — autodiff through the scan
    replays the identical draw, so forward and backward masks agree by
    construction (no custom-vjp contract needed at this granularity).
    """
    n = jax.lax.psum(1, axis_name)
    idx = jax.lax.axis_index(axis_name)
    b, s_l, h, d = q.shape

    dropout_on = dropout_rate > 0.0
    if dropout_on:
        from tpudl.ops.dropout import (
            device_fold_rng,
            dropout_keep_mask,
            quantized_rate,
        )

        rng = device_fold_rng(key_data, key_impl, fold_axes)
        eff_rate = quantized_rate(dropout_rate, exact=False)
        inv_keep = 1.0 / (1.0 - eff_rate)

    q32 = q.astype(jnp.float32)
    m0 = jnp.full((b, h, s_l, 1), MASK_VALUE, jnp.float32)
    l0 = jnp.zeros((b, h, s_l, 1), jnp.float32)
    acc0 = jnp.zeros((b, h, s_l, d), jnp.float32)
    q_ids = idx * s_l + jax.lax.broadcasted_iota(jnp.int32, (s_l, 1), 0)

    perm = [(i, (i + 1) % n) for i in range(n)]

    def body(carry, t):
        m, l, acc, k, v, kvm = carry
        src = (idx - t) % n  # global block index of the kv shard we hold
        s = jnp.einsum("bqhd,bkhd->bhqk", q32, k.astype(jnp.float32)) * scale
        keep = (kvm > 0)[:, None, None, :]
        if causal:
            kv_ids = src * s_l + jax.lax.broadcasted_iota(
                jnp.int32, (1, s_l), 1
            )
            keep = jnp.logical_and(keep, (kv_ids <= q_ids)[None, None, :, :])
        s = jnp.where(keep, s, MASK_VALUE)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.where(keep, jnp.exp(s - m_new), 0.0)
        corr = jnp.exp(m - m_new)
        # Denominator: UNDROPPED p (dropout acts post-normalization).
        l = l * corr + jnp.sum(p, axis=-1, keepdims=True)
        if dropout_on:
            # Tile keyed by the GLOBAL kv block this device holds at
            # tick t — every (q-shard, kv-block) pair draws its own mask.
            keep_d = dropout_keep_mask(
                jax.random.fold_in(rng, src), p.shape, dropout_rate,
                exact=False,
            )
            p_num = jnp.where(keep_d, p, 0.0)
        else:
            p_num = p
        acc = acc * corr + jnp.einsum(
            "bhqk,bkhd->bhqd", p_num.astype(v.dtype), v
        ).astype(jnp.float32)
        k, v, kvm = (
            jax.lax.ppermute(x, axis_name, perm) for x in (k, v, kvm)
        )
        return (m_new, l, acc, k, v, kvm), None

    (m, l, acc, _, _, _), _ = jax.lax.scan(
        body, (m0, l0, acc0, k, v, kvm), jnp.arange(n)
    )
    l_safe = jnp.where(l > 0.0, l, 1.0)
    o = acc / l_safe
    if dropout_on:
        o = o * inv_keep
    o = o.transpose(0, 2, 1, 3)  # [b, s_l, h, d]
    return o.astype(q.dtype)


def _ring_local_flash(q, k, v, kvm=None, key_data=None, *, axis_name, scale,
                      causal, dropout_rate=0.0, key_impl=None, fold_axes=()):
    """Flash-bodied ring loop (round-5; r4 VERDICT weak #5): each tick
    runs the Pallas flash kernel on the held kv block and merges the
    per-block (o, lse) pairs — per-device attention memory stays
    O(s_local) instead of the einsum body's [b, h, s_local, s_local]
    f32 logits block, which is the whole point of ring on the longest
    sequences.

    Causality without a traced kernel offset: the diagonal tick (the
    device's own block, t=0) runs the CAUSAL kernel; every later block
    is either wholly prior (src < idx: unmasked) or wholly future
    (src > idx: its per-tick lse is overwritten with MASK_VALUE, an
    EXACTLY-zero merge weight, so the block contributes nothing and
    needs no gradient). Masking via the merge weight rather than a
    zeroed kv row keeps ``kvm=None`` (the unpadded long-context hot
    path) on the kernel's maskless fast codegen for every tick.

    Dropout keeps the einsum body's exact factorization: per-tick lse
    is of the UNDROPPED distribution (flash_attention_with_lse), so
    merge weights are dropout-independent and only the p@V numerators
    are masked, per (q-shard, kv-block) via fold_in(rng, src) — the
    same tile-keying convention as the einsum body, drawn by the
    in-kernel hardware PRNG instead of jax.random bits."""
    from tpudl.ops.flash_attention import flash_attention_with_lse

    n = jax.lax.psum(1, axis_name)
    idx = jax.lax.axis_index(axis_name)

    rng = None
    if dropout_rate > 0.0:
        from tpudl.ops.dropout import device_fold_rng

        rng = device_fold_rng(key_data, key_impl, fold_axes)

    def call(k_, v_, kvm_, causal_flag, src):
        tick_rng = None if rng is None else jax.random.fold_in(rng, src)
        o, lse = flash_attention_with_lse(
            q, k_, v_, mask=kvm_, causal=causal_flag, scale=scale,
            dropout_rate=dropout_rate, dropout_rng=tick_rng,
        )
        return o.astype(jnp.float32), lse

    # Tick 0: the diagonal block (the kv shard this device starts with).
    o_acc, lse_acc = call(k, v, kvm, causal, idx)
    perm = [(i, (i + 1) % n) for i in range(n)]

    def rotate(*xs):
        return tuple(
            None if x is None else jax.lax.ppermute(x, axis_name, perm)
            for x in xs
        )

    k, v, kvm = rotate(k, v, kvm)

    def body(carry, t):
        o_acc, lse_acc, k, v, kvm = carry
        src = (idx - t) % n  # global block index of the kv shard we hold
        o_t, lse_t = call(k, v, kvm, False, src)
        if causal:
            # Wholly-future block: exact zero weight in the merge.
            lse_t = jnp.where(src > idx, MASK_VALUE, lse_t)
        new_lse = jnp.logaddexp(lse_acc, lse_t)
        w_acc = jnp.exp(lse_acc - new_lse).transpose(0, 2, 1)[..., None]
        w_t = jnp.exp(lse_t - new_lse).transpose(0, 2, 1)[..., None]
        o_acc = o_acc * w_acc + o_t * w_t
        k, v, kvm = rotate(k, v, kvm)
        return (o_acc, new_lse, k, v, kvm), None

    if kvm is None:
        def body_nokvm(carry, t):
            o_acc, lse_acc, k, v = carry
            (o_acc, new_lse, k, v, _), _ = body(
                (o_acc, lse_acc, k, v, None), t
            )
            return (o_acc, new_lse, k, v), None

        (o_acc, _, _, _), _ = jax.lax.scan(
            body_nokvm, (o_acc, lse_acc, k, v), jnp.arange(1, n)
        )
    else:
        (o_acc, _, _, _, _), _ = jax.lax.scan(
            body, (o_acc, lse_acc, k, v, kvm), jnp.arange(1, n)
        )
    return o_acc.astype(q.dtype)


def ring_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    mask: Optional[jax.Array] = None,
    causal: bool = False,
    scale: Optional[float] = None,
    mesh: Optional[Mesh] = None,
    axis_name: str = AXIS_SEQ,
    local_impl: Optional[str] = None,
    dropout_rate: float = 0.0,
    dropout_rng: Optional[jax.Array] = None,
) -> jax.Array:
    """Sequence-parallel attention on [B, S, H, D] (the
    tpudl.ops.attention contract; Sq == Skv required — queries and keys
    shard along the same sequence axis).

    ``mask`` may be a [B, S] kv-validity mask or a [B, 1, 1, S] padding
    mask; dense masks are rejected like tpudl.ops.flash_attention.
    ``mesh`` defaults to the active tpudl mesh
    (tpudl.parallel.sharding.active_mesh); batch shards over (dp, fsdp),
    sequence over `sp`, heads over `tp`.

    ``local_impl`` picks the per-tick body (round 5, mirroring ulysses):
    "flash" (the Pallas kernel per kv block + an (o, lse) merge —
    per-device attention memory O(s_local), the long-context default on
    TPU) or "reference" (the einsum online-softmax body — exact
    tpudl.ops.attention numerics, materializes one [b, h, s_local,
    s_local] f32 block per tick; the default on CPU where the kernel
    would run interpreted). None = by backend.

    ``dropout_rate`` > 0 (round 4): attention-probability dropout with
    exact post-softmax semantics despite the distributed softmax — the
    online merge keeps the denominator undropped while the numerator is
    masked per (q-shard, kv-block) tile (see _ring_local /
    _ring_local_flash). Each mesh slot folds its position into
    ``dropout_rng``; mask BITS therefore depend on the mesh layout and
    the body implementation, like every sharded dropout path. The
    EFFECTIVE rate also differs slightly per body: the reference body
    quantizes to 1/256 (the low-width-bits generator, e.g. 0.1 ->
    25/256 = 0.0977) while the flash body applies the requested rate
    in-kernel — CPU-vs-TPU training trajectories differ by that 2%
    relative drop-probability, not by a bug.
    """
    from tpudl.ops.attention import normalize_kv_mask, unmeshed_attention
    from tpudl.parallel.sharding import current_mesh

    if local_impl is None:
        from tpudl.ops.attention import is_tpu_backend

        local_impl = "flash" if is_tpu_backend() else "reference"
    if local_impl not in ("flash", "reference"):
        raise ValueError(
            f"local_impl must be 'flash' or 'reference', got {local_impl!r}"
        )

    if dropout_rate > 0.0 and dropout_rng is None:
        raise ValueError("dropout_rate > 0 requires a dropout_rng")

    if mesh is None:
        mesh = current_mesh()
    if mesh is None:
        # No mesh (single-device init/eval): ring degenerates to reference
        # attention — numerically identical, so models with
        # attention_impl="ring" init and evaluate unmeshed.
        return unmeshed_attention(
            q, k, v, mask, causal, scale,
            dropout_rate=dropout_rate, dropout_rng=dropout_rng,
        )
    b, s, h, d = q.shape
    if k.shape[1] != s:
        raise ValueError(
            f"ring attention shards q and kv along one sequence axis; "
            f"got Sq={s}, Skv={k.shape[1]}"
        )
    n_sp = mesh.shape[axis_name]
    if s % n_sp != 0:
        raise ValueError(f"seq len {s} not divisible by {axis_name}={n_sp}")
    if scale is None:
        scale = d ** -0.5

    kvm = normalize_kv_mask(mask, b, s, impl="ring_attention")

    batch = tuple(a for a in BATCH_AXES if mesh.shape[a] > 1) or None
    n_tp = mesh.shape[AXIS_TENSOR]
    heads_sharded = h % max(n_tp, 1) == 0 and n_tp > 1
    heads = AXIS_TENSOR if heads_sharded else None
    qkv_spec = P(batch, axis_name, heads, None)
    key_impl = (
        jax.random.key_impl(dropout_rng) if dropout_rate > 0.0 else None
    )
    from tpudl.ops.dropout import shard_fold_axes

    fold_axes = shard_fold_axes(mesh, axis_name, heads_sharded, BATCH_AXES)
    local_body = _ring_local_flash if local_impl == "flash" else _ring_local
    body = partial(
        local_body, axis_name=axis_name, scale=scale, causal=causal,
        dropout_rate=dropout_rate, key_impl=key_impl, fold_axes=fold_axes,
    )
    # The flash body takes no kv-mask operand when the caller passed no
    # mask, keeping every tick on the kernel's maskless fast codegen
    # (causal future-block zeroing happens via the merge weight, not the
    # mask channel). The einsum body always takes the row (its masking
    # is a where() it pays either way).
    skip_kvm = local_impl == "flash" and mask is None
    operands = [q, k, v]
    in_specs = [qkv_spec, qkv_spec, qkv_spec]
    if not skip_kvm:
        operands.append(kvm)
        in_specs.append(P(batch, axis_name))
    if dropout_rate > 0.0:
        operands.append(jax.random.key_data(dropout_rng))
        in_specs.append(
            P(*([None] * jax.random.key_data(dropout_rng).ndim))
        )
        if skip_kvm:
            # key_data is positional after kvm in the body signature.
            inner = body
            body = lambda q_, k_, v_, kd_: inner(q_, k_, v_, None, kd_)  # noqa: E731
    fn = shard_map(
        body,
        mesh=mesh,
        in_specs=tuple(in_specs),
        out_specs=qkv_spec,
        check_vma=False,
    )
    return fn(*operands)
