"""Pallas TPU fused short-sequence attention with in-kernel dropout.

Why this exists next to tpudl.ops.flash_attention: the flash kernel's
streaming design (kv tiles + online softmax + 3-kernel backward with
saved logsumexp) wins when S is large, but at the configs[1] headline
shape (BERT fine-tune, seq 128) it LOSES to XLA's einsum attention —
measured 257 vs 174 ms/step at batch 256 (benchmarks/bert_attn_seq128.py,
2026-07-30). At short S the whole [S, S] score tile fits in registers, so
the right kernel shape is different:

- one grid cell owns a (batch row, head group): q/k/v arrive as natural
  [B, S, H*D] rows — NO host-side transposes or BSHD->BHSD copies, the
  model's reshape into the kernel is a free bitcast;
- full softmax is computed in-cell (no online merge, no logsumexp
  residual), and the backward pass is ONE kernel that recomputes the
  [S, S] probabilities and emits dq/dk/dv together;
- attention-probability dropout runs IN the kernel from the TPU hardware
  PRNG (pltpu.prng_random_bits): the [B, H, S, S] keep mask never touches
  HBM. Measured on the headline step, materialized-mask dropout costs
  20 ms/step (45.7% -> 50.5% MFU when switched off) — this kernel makes
  that cost disappear instead of making the semantics disappear.

HBM traffic per layer becomes the theoretical floor (read q,k,v + write
o; backward reads those + do and writes dq,dk,dv) — the einsum path's
[B, H, S, S] logits/probs round trips (~800 MB/layer at the headline
shape) are gone.

Scope: self-attention with Sq == Skv == S, S small enough that [S, S]
f32 tiles live in VMEM comfortably (guarded at S <= 1024; use flash
beyond). Masking contract matches flash: [B, S] kv-validity rows or
[B, 1, 1, S] padding masks plus an in-kernel causal triangle; dense
masks are rejected.

Dropout determinism: the keep mask is a pure function of (dropout_rng,
batch row, head group) — forward and backward regenerate identical bits
by reseeding per cell, so no mask is stored anywhere. The PRNG sequence
is the TPU hardware generator's; it does not reproduce
jax.random.bernoulli's threefry stream (the reference implementation's
masks differ — parity tests compare distributions, not bits). Requires a
real TPU: pallas interpret mode has no PRNG emulation, so
dropout_rate > 0 raises under interpret.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from tpudl.ops.attention import MASK_VALUE
from tpudl.ops.pallas_utils import (
    COMPILER_PARAMS,
    flat_cell_id,
    keep_mask,
    round_up as _round_up,
    seed_cell,
)

#: [S, S] f32 score tiles above this do not fit the in-register design
#: (measured 2026-07-30: S=512 compiles and beats einsum 4.3 vs 5.5 ms
#: fwd+bwd; S=1024 blows VMEM in the one-pass backward — use flash).
MAX_SEQ = 512


def _kernel_body(
    g, seed_ref, q_ref, k_ref, v_ref, kvm_ref, *, scale, causal, rate,
    head_dim, has_kvmask,
):
    """Shared fwd recompute for one head g of the cell's group: returns
    (p, keep) where p is the post-softmax pre-dropout probability tile
    [S, S] f32 and keep the dropout keep-mask (or None)."""
    d = head_dim
    q = q_ref[0, :, g * d:(g + 1) * d]
    k = k_ref[0, :, g * d:(g + 1) * d]
    s = jax.lax.dot_general(
        q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
    ) * scale  # [S, S]

    seq = s.shape[0]
    if has_kvmask:
        s = jnp.where((kvm_ref[0, 0, :] > 0.0)[None, :], s, MASK_VALUE)
    if causal:
        q_ids = jax.lax.broadcasted_iota(jnp.int32, (seq, seq), 0)
        kv_ids = jax.lax.broadcasted_iota(jnp.int32, (seq, seq), 1)
        s = jnp.where(kv_ids <= q_ids, s, MASK_VALUE)

    m = jnp.max(s, axis=-1, keepdims=True)
    p = jnp.exp(s - m)
    if has_kvmask or causal:
        # exp(MASK - m) can be 1.0 on fully-masked rows (m == MASK);
        # re-zero explicitly so those rows produce 0, not garbage.
        p = jnp.where(s <= MASK_VALUE, 0.0, p)
    l = jnp.sum(p, axis=-1, keepdims=True)
    p = p / jnp.where(l > 0.0, l, 1.0)

    keep = keep_mask((seq, seq), rate) if rate > 0.0 else None
    return p, keep


def _seed_cell(seed_ref):
    seed_cell(seed_ref, flat_cell_id(2))


def _fwd_kernel(seed_ref, q_ref, k_ref, v_ref, kvm_ref, o_ref, *,
                scale, causal, rate, head_dim, group, has_kvmask):
    if rate > 0.0:
        _seed_cell(seed_ref)
    d = head_dim
    for g in range(group):
        p, keep = _kernel_body(
            g, seed_ref, q_ref, k_ref, v_ref, kvm_ref,
            scale=scale, causal=causal, rate=rate, head_dim=d,
            has_kvmask=has_kvmask,
        )
        if keep is not None:
            p = jnp.where(keep, p * (1.0 / (1.0 - rate)), 0.0)
        v = v_ref[0, :, g * d:(g + 1) * d]
        o_ref[0, :, g * d:(g + 1) * d] = jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        ).astype(o_ref.dtype)


def _bwd_kernel(seed_ref, q_ref, k_ref, v_ref, kvm_ref, do_ref,
                dq_ref, dk_ref, dv_ref, *,
                scale, causal, rate, head_dim, group, has_kvmask):
    if rate > 0.0:
        # Identical reseed + identical per-g generation order as forward
        # -> bit-identical keep masks with nothing stored.
        _seed_cell(seed_ref)
    d = head_dim
    inv = 1.0 / (1.0 - rate) if rate > 0.0 else 1.0
    for g in range(group):
        p, keep = _kernel_body(
            g, seed_ref, q_ref, k_ref, v_ref, kvm_ref,
            scale=scale, causal=causal, rate=rate, head_dim=d,
            has_kvmask=has_kvmask,
        )
        q = q_ref[0, :, g * d:(g + 1) * d]
        k = k_ref[0, :, g * d:(g + 1) * d]
        v = v_ref[0, :, g * d:(g + 1) * d]
        do = do_ref[0, :, g * d:(g + 1) * d]

        # out = drop(p) @ v, drop(p) = keep * p * inv
        dpd = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )  # [S, S] = d out / d drop(p)
        if keep is not None:
            dp = jnp.where(keep, dpd * inv, 0.0)
            pd = jnp.where(keep, p * inv, 0.0)
        else:
            dp = dpd
            pd = p
        dv_ref[0, :, g * d:(g + 1) * d] = jax.lax.dot_general(
            pd.astype(do.dtype), do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        ).astype(dv_ref.dtype)
        # softmax VJP wrt logits: ds = p * (dp - <dp, p>_row), then the
        # scale from s = (q k^T) * scale.
        ds = p * (dp - jnp.sum(dp * p, axis=-1, keepdims=True))
        ds = (ds * scale).astype(q.dtype)
        dq_ref[0, :, g * d:(g + 1) * d] = jax.lax.dot_general(
            ds, k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        ).astype(dq_ref.dtype)
        dk_ref[0, :, g * d:(g + 1) * d] = jax.lax.dot_general(
            ds, q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        ).astype(dk_ref.dtype)


def _specs(b, s_p, h, d, group):
    row = pl.BlockSpec(
        (1, s_p, group * d), lambda bi, hg: (bi, 0, hg),
        memory_space=pltpu.VMEM,
    )
    # [B, 1, S] with a (1, 1, S) block: the lane-dim layout TPU block
    # specs require (middle dim 1 == array dim satisfies the tiling rule).
    kvm = pl.BlockSpec((1, 1, s_p), lambda bi, hg: (bi, 0, 0),
                       memory_space=pltpu.VMEM)
    seed = pl.BlockSpec(memory_space=pltpu.SMEM)
    grid = (b, h // group)
    return grid, seed, row, kvm


def _prep(q, k, v, kvmask):
    """[B, S, H, D] -> padded [B, S_p, H*D] rows (free reshape, S padded
    to the f32 tile sublane/lane quantum) + padded kv row."""
    b, s, h, d = q.shape
    s_p = _round_up(s, 128)
    flat = lambda x: jnp.pad(
        x.reshape(b, s, h * d), ((0, 0), (0, s_p - s), (0, 0))
    )
    kvm = jnp.pad(kvmask, ((0, 0), (0, s_p - s)))[:, None, :]
    return flat(q), flat(k), flat(v), kvm, s_p


@functools.partial(jax.custom_vjp, nondiff_argnums=(5, 6, 7, 8, 9, 10))
def _fused(q, k, v, kvmask, seed, causal, scale, rate, group, interpret,
           has_mask):
    out, _ = _fused_fwd(
        q, k, v, kvmask, seed, causal, scale, rate, group, interpret, has_mask
    )
    return out


def _fused_fwd(q, k, v, kvmask, seed, causal, scale, rate, group, interpret,
               has_mask):
    b, s, h, d = q.shape
    qf, kf, vf, kvm, s_p = _prep(q, k, v, kvmask)
    has_kvmask = bool(has_mask) or s_p != s
    grid, seed_spec, row, kvm_spec = _specs(b, s_p, h, d, group)
    o = pl.pallas_call(
        functools.partial(
            _fwd_kernel, scale=scale, causal=causal, rate=rate,
            head_dim=d, group=group, has_kvmask=has_kvmask,
        ),
        grid=grid,
        compiler_params=COMPILER_PARAMS(
            dimension_semantics=("parallel", "parallel")
        ),
        in_specs=[seed_spec, row, row, row, kvm_spec],
        out_specs=row,
        out_shape=jax.ShapeDtypeStruct((b, s_p, h * d), q.dtype),
        interpret=interpret,
    )(seed, qf, kf, vf, kvm)
    out = o[:, :s, :].reshape(b, s, h, d)
    return out, (q, k, v, kvmask, seed)


def _fused_bwd(causal, scale, rate, group, interpret, has_mask, res, g_out):
    q, k, v, kvmask, seed = res
    b, s, h, d = q.shape
    qf, kf, vf, kvm, s_p = _prep(q, k, v, kvmask)
    # Padded do rows are zero -> their ds/dq contributions vanish; padded
    # kv columns are masked in the recompute exactly as in forward.
    dof = jnp.pad(
        g_out.astype(q.dtype).reshape(b, s, h * d),
        ((0, 0), (0, s_p - s), (0, 0)),
    )
    has_kvmask = bool(has_mask) or s_p != s
    grid, seed_spec, row, kvm_spec = _specs(b, s_p, h, d, group)
    dq, dk, dv = pl.pallas_call(
        functools.partial(
            _bwd_kernel, scale=scale, causal=causal, rate=rate,
            head_dim=d, group=group, has_kvmask=has_kvmask,
        ),
        grid=grid,
        compiler_params=COMPILER_PARAMS(
            dimension_semantics=("parallel", "parallel")
        ),
        in_specs=[seed_spec, row, row, row, kvm_spec, row],
        out_specs=[row, row, row],
        out_shape=[
            jax.ShapeDtypeStruct((b, s_p, h * d), q.dtype),
            jax.ShapeDtypeStruct((b, s_p, h * d), k.dtype),
            jax.ShapeDtypeStruct((b, s_p, h * d), v.dtype),
        ],
        interpret=interpret,
    )(seed, qf, kf, vf, kvm, dof)
    unflat = lambda x: x[:, :s, :].reshape(b, s, h, d)
    return (
        unflat(dq), unflat(dk), unflat(dv),
        jnp.zeros_like(kvmask), jnp.zeros_like(seed),
    )


_fused.defvjp(_fused_fwd, _fused_bwd)


def _pick_group(h: int, s: int) -> int:
    """Largest head group whose [S, group*D] rows stay comfortably inside
    VMEM alongside the [S, S] f32 score tile; at short S, bigger groups
    amortize per-cell grid/DMA overhead."""
    g = h
    # At long S the score tile dominates VMEM; shrink the group.
    while g > 1 and s * g > 4096:
        g = next((x for x in range(g - 1, 0, -1) if h % x == 0), 1)
    return g


def fused_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    mask: Optional[jax.Array] = None,
    causal: bool = False,
    scale: Optional[float] = None,
    dropout_rate: float = 0.0,
    dropout_rng: Optional[jax.Array] = None,
    head_group: Optional[int] = None,
    interpret: Optional[bool] = None,
) -> jax.Array:
    """Fused short-seq attention on [B, S, H, D] (tpudl.ops.attention
    contract): full-softmax Pallas kernel, one-pass backward, optional
    in-kernel attention-probability dropout from the TPU hardware PRNG.

    ``mask``: [B, S] kv-validity row or [B, 1, 1, S] padding mask (dense
    masks rejected — use implementation='reference'). ``head_group``
    packs that many heads into one grid cell (must divide H; default
    auto). ``dropout_rate`` > 0 needs ``dropout_rng`` and a real TPU.
    """
    from tpudl.ops.attention import is_tpu_backend, normalize_kv_mask

    b, s, h, d = q.shape
    if k.shape[1] != s:
        raise ValueError(
            f"fused_attention is self-attention-shaped (Sq == Skv); got "
            f"Sq={s}, Skv={k.shape[1]} — use flash_attention"
        )
    if s > MAX_SEQ:
        raise ValueError(
            f"fused_attention holds full [S, S] score tiles in VMEM; "
            f"S={s} > {MAX_SEQ} — use implementation='flash'"
        )
    if scale is None:
        scale = d ** -0.5
    if interpret is None:
        interpret = not is_tpu_backend()
    if dropout_rate > 0.0:
        if dropout_rng is None:
            raise ValueError("dropout_rate > 0 requires dropout_rng")
        if interpret:
            raise NotImplementedError(
                "in-kernel dropout uses the TPU hardware PRNG, which "
                "pallas interpret mode does not emulate — run on TPU or "
                "use implementation='reference'"
            )
        seed = jax.random.bits(dropout_rng, (2,), jnp.uint32)
    else:
        seed = jnp.zeros((2,), jnp.uint32)

    group = head_group or _pick_group(h, s)
    if h % group != 0:
        raise ValueError(f"head_group {group} does not divide {h} heads")

    has_mask = mask is not None
    kvmask = normalize_kv_mask(
        mask, b, s, dtype=jnp.float32, impl="fused_attention"
    )
    return _fused(
        q, k, v, kvmask, seed, causal, scale, float(dropout_rate), group,
        interpret, has_mask,
    )
