"""Mixture-of-experts FFN with expert parallelism over the ``ep`` mesh axis.

The reference lineage has no MoE (SURVEY.md §2.3 marks expert parallelism
absent from the reference tree); this makes sparse scaling first-class the
TPU way: routing is dense one-hot einsum algebra (GShard/Switch style) —
no gather/scatter, no dynamic shapes — so the dispatch/combine contractions
lower onto the MXU, and with expert weights sharded ``P('ep', ...)`` and
tokens sharded over (dp, fsdp), GSPMD inserts the all-to-all that moves
token blocks to their experts over ICI.

Routing math (top-k, capacity-bounded):
- router probs p = softmax(x @ w_r) in f32;
- k choices peeled off iteratively (argmax, mask, renormalize) with
  earlier choices taking dispatch priority;
- position_in_expert via cumsum over the token axis; tokens past an
  expert's capacity ``C = ceil(k * S * capacity_factor / E)`` are dropped
  (their combine weight is zero — the residual connection around the MoE
  layer carries them through unchanged);
- gate values normalized by the FULL top-k gate sum (GShard-style):
  combine weights sum to 1 only when all k choices were kept; a dropped
  choice's mass shrinks the survivors' weights rather than being
  reassigned to them;
- Switch-style load-balance aux loss ``E * sum_e f_e * p_e`` (f = top-1
  dispatch fraction, p = mean router prob), sown into the
  ``intermediates`` collection as ``moe_aux_loss`` for the train loop to
  pick up (tpudl.train.loop.make_classification_train_step
  ``moe_aux_weight``).
"""

from __future__ import annotations

import math
from typing import Any, Callable

import flax.linen as nn
import jax
import jax.numpy as jnp

from tpudl.parallel.sharding import constrain

P = jax.sharding.PartitionSpec

#: Sharding rules for MoE parameters, composable ahead of FSDP/TP rules:
#: expert dim over ep, then the usual megatron column/row split.
EP_MOE_RULES = (
    (r"(^|/)router/kernel$", P(None, None)),
    (r"(^|/)(wi|wg)$", P("ep", "fsdp", "tp")),
    (r"(^|/)wo$", P("ep", "tp", "fsdp")),
)


def with_moe_rules(base) -> tuple:
    """Prepend the MoE expert rules to a base rule list (first match wins,
    so expert params resolve before the generic kernel rules)."""
    return tuple(EP_MOE_RULES) + tuple(base or ())


def expert_capacity(
    seq_len: int, num_experts: int, k: int, capacity_factor: float
) -> int:
    return max(1, math.ceil(k * seq_len * capacity_factor / num_experts))


def route_topk(probs: jax.Array, k: int, capacity: int):
    """Build dispatch/combine tensors from router probabilities.

    probs: [G, S, E] f32 (softmax over E). Returns
    ``(dispatch [G,S,E,C] bool-ish f32, combine [G,S,E,C] f32, aux f32)``.
    """
    g, s, e = probs.shape
    top1_mask = jax.nn.one_hot(jnp.argmax(probs, -1), e, dtype=probs.dtype)

    remaining = probs
    counts = jnp.zeros((g, 1, e), probs.dtype)
    dispatch = jnp.zeros((g, s, e, capacity), probs.dtype)
    gate_total = jnp.zeros((g, s), probs.dtype)
    combine = jnp.zeros((g, s, e, capacity), probs.dtype)

    for _ in range(k):
        idx = jnp.argmax(remaining, -1)  # [G, S]
        gate = jnp.max(remaining, -1)  # [G, S]
        mask = jax.nn.one_hot(idx, e, dtype=probs.dtype)  # [G, S, E]
        # 0-based slot of each token within its expert, counting earlier
        # choices' kept assignments first (they have priority).
        pos = jnp.cumsum(mask, axis=1) - mask + counts  # [G, S, E]
        keep = (pos < capacity).astype(probs.dtype) * mask
        counts = counts + jnp.sum(keep, axis=1, keepdims=True)
        slot = jax.nn.one_hot(
            jnp.sum(pos * mask, -1).astype(jnp.int32), capacity,
            dtype=probs.dtype,
        )  # [G, S, C]
        disp = keep[..., None] * slot[:, :, None, :]  # [G, S, E, C]
        dispatch = dispatch + disp
        combine = combine + disp * gate[..., None, None]
        gate_total = gate_total + gate
        remaining = remaining * (1.0 - mask)

    # GShard/Switch normalization: divide by the sum of ALL top-k gates
    # (kept or not), so a token whose higher-probability expert was
    # capacity-dropped routes through its surviving choice with a
    # correspondingly SMALLER combine weight — the dropped mass falls to
    # the residual connection, it is not reassigned to the survivor.
    combine = combine / jnp.maximum(gate_total, 1e-9)[..., None, None]

    # Switch load-balance loss: E * sum_e (top-1 dispatch fraction) *
    # (mean router prob). 1.0 at perfect balance.
    f = jnp.mean(top1_mask, axis=(0, 1))
    p = jnp.mean(probs, axis=(0, 1))
    aux = e * jnp.sum(f * p)
    return dispatch, combine, aux


class MoEMlp(nn.Module):
    """Expert-parallel FFN block (drop-in for a dense MLP of the same
    hidden/intermediate sizes; callers keep their residual connection, so
    capacity-dropped tokens pass through unchanged).

    ``gated=True`` gives the SwiGLU variant (Llama-style); otherwise a
    plain act(x@wi)@wo (BERT-style).
    """

    num_experts: int
    intermediate_size: int
    k: int = 2
    capacity_factor: float = 1.25
    gated: bool = False
    act: Callable = nn.gelu
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        b, s, m = x.shape
        e, h = self.num_experts, self.intermediate_size
        cap = expert_capacity(s, e, self.k, self.capacity_factor)

        # Router in f32: small matmul, and routing decisions are
        # precision-sensitive.
        logits = nn.Dense(
            e,
            use_bias=False,
            dtype=jnp.float32,
            kernel_init=nn.initializers.normal(0.02),
            name="router",
        )(x.astype(jnp.float32))
        probs = jax.nn.softmax(logits, axis=-1)
        dispatch, combine, aux = route_topk(probs, self.k, cap)
        self.sow("intermediates", "moe_aux_loss", aux)

        init = nn.initializers.normal(0.02)
        wi = self.param("wi", init, (e, m, h)).astype(self.dtype)
        wo = self.param("wo", init, (e, h, m)).astype(self.dtype)

        xin = jnp.einsum("gsec,gsm->egcm", dispatch.astype(self.dtype), x)
        xin = constrain(xin, "ep", ("dp", "fsdp"), None, None)
        hh = jnp.einsum("egcm,emh->egch", xin, wi)
        if self.gated:
            wg = self.param("wg", init, (e, m, h)).astype(self.dtype)
            hh = self.act(hh) * jnp.einsum("egcm,emh->egch", xin, wg)
        else:
            hh = self.act(hh)
        out = jnp.einsum("egch,ehm->egcm", hh, wo)
        out = constrain(out, "ep", ("dp", "fsdp"), None, None)
        y = jnp.einsum("gsec,egcm->gsm", combine.astype(self.dtype), out)
        return y
