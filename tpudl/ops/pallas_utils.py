"""Shared helpers for the tpudl Pallas TPU kernels.

The cell-seeding + threshold recipe here is a forward/backward
bit-exactness CONTRACT: fused_attention and softmax_dropout regenerate
their dropout masks in the backward pass by reseeding with exactly this
scheme — any change must keep both passes (and both modules) in lockstep,
which is why there is one copy.
"""

from __future__ import annotations

import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


def round_up(x: int, m: int) -> int:
    return ((x + m - 1) // m) * m


#: jax renamed pltpu.TPUCompilerParams -> pltpu.CompilerParams (~0.5);
#: resolve whichever this jax ships so the kernels run on both.
COMPILER_PARAMS = getattr(pltpu, "CompilerParams", None) or getattr(
    pltpu, "TPUCompilerParams"
)


def seed_cell(seed_ref, cell) -> None:
    """Seed the TPU PRNG with a distinct stream per grid cell: prng_seed
    takes at most two 32-bit words, so the flattened cell id folds into
    them arithmetically — distinct cells get distinct (s0, s1) pairs for
    any key."""
    s0 = seed_ref[0] + cell.astype(jnp.uint32)
    s1 = seed_ref[1] ^ (cell.astype(jnp.uint32) * jnp.uint32(2654435761))
    pltpu.prng_seed(s0, s1)


def flat_cell_id(grid_rank: int):
    """Row-major flattened id of the current grid cell."""
    cell = pl.program_id(0)
    for axis in range(1, grid_rank):
        cell = cell * pl.num_programs(axis) + pl.program_id(axis)
    return cell


def keep_mask(shape, rate: float):
    """In-kernel dropout keep-mask from the hardware PRNG (True = keep
    with probability 1 - rate). prng_random_bits yields int32 on TPU —
    reinterpret as uint32 or the threshold compare drops ~55% instead of
    ``rate``."""
    threshold = jnp.uint32(round(rate * (2.0 ** 32)))
    bits = pltpu.bitcast(pltpu.prng_random_bits(shape), jnp.uint32)
    return bits >= threshold
