"""Fused softmax-cross-entropy over integer labels (online logsumexp).

``optax.softmax_cross_entropy_with_integer_labels`` materializes the
[B, V] log-probability tensor in HBM (and its VJP materializes the
[B, V] softmax); at Llama vocab (128k) that is the dominant memory
stream of the loss step, and even BERT's 30k vocab pays a full extra
round-trip over the logits. This kernel streams the vocab axis through
VMEM exactly once — the online-logsumexp recurrence of the flash-
attention lineage applied to the loss — keeping only per-row statistics
(running max, running sum-exp, the label's logit, and under label
smoothing the row logit-sum):

    loss_b = lse_b - (1 - s) * z_b[t_b] - (s / V) * sum_j z_b[j]

The [B, V] probability tensor is NEVER materialized: the forward saves
only ``lse`` [B], and the backward writes the gradient tile-by-tile as
``g_b * (softmax(z)_bj - q_bj)`` with each exp tile living only in VMEM
(q = the (1-s)-smoothed one-hot). Vocab-padding columns (V not a
lane-tile multiple) are masked out of the logsumexp, the label gather,
and the smoothing sum.

Dispatch: ``impl`` = "auto" | "fused" | "reference" with the
tpudl.ops.norms contract; the reference composite is exactly the optax
path tpudl.train.loop always used, so ``impl="reference"`` (the
default at the loss sites) is behavior-identical to the pre-kernel
code.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from tpudl.ops.attention import MASK_VALUE
from tpudl.ops.norms import resolve_impl
from tpudl.ops.pallas_utils import COMPILER_PARAMS, round_up


#: Override for the vocab-block cap below (None = the 1024 default).
#: ``benchmarks/fused_epilogue.py --sweep-blocks`` grid-searches this;
#: ``TPUDL_CE_VOCAB_BLOCK`` pins a tuned winner for production runs.
#: The divisibility walk still applies, so any override stays legal.
VOCAB_BLOCK_OVERRIDE: Optional[int] = None


def _fit_vocab_block(v_pad: int, limit: int = 1024) -> int:
    override = VOCAB_BLOCK_OVERRIDE
    if override is None:
        from tpudl.analysis.registry import env_int

        override = env_int("TPUDL_CE_VOCAB_BLOCK")
    if override is not None:
        if override < 128:
            raise ValueError(
                f"vocab-block override must be >= 128, got {override}"
            )
        limit = override
    b = min(limit, v_pad)
    while b > 128 and v_pad % b != 0:
        b //= 2
    return max(b, 128)


def _setup(logits, labels):
    b, v = logits.shape
    bb = min(256, round_up(b, 8))
    b_pad = round_up(b, bb)
    v_pad = round_up(v, 128)
    bv = _fit_vocab_block(v_pad)
    if (b_pad, v_pad) != (b, v):
        logits = jnp.pad(logits, ((0, b_pad - b), (0, v_pad - v)))
    lab = labels.astype(jnp.int32)[:, None]
    if b_pad != b:
        lab = jnp.pad(lab, ((0, b_pad - b), (0, 0)))
    return logits, lab, bb, bv, b_pad, v_pad


def _row_stat(a, b_pad):
    """[B] f32 -> [B_pad, 128] broadcast (rows on sublanes)."""
    a = a.astype(jnp.float32)[:, None]
    if b_pad != a.shape[0]:
        a = jnp.pad(a, ((0, b_pad - a.shape[0]), (0, 0)))
    return jnp.broadcast_to(a, (b_pad, 128))


# ---------------------------------------------------------------------------
# kernels
# ---------------------------------------------------------------------------


def _xent_fwd_kernel(z_ref, lab_ref, loss_ref, lse_ref,
                     m_scr, l_scr, t_scr, s_scr,
                     *, v, bv, smoothing, has_pad):
    j = pl.program_id(1)
    nv = pl.num_programs(1)

    @pl.when(j == 0)
    def _init():
        m_scr[:, :] = jnp.full_like(m_scr, MASK_VALUE)
        l_scr[:, :] = jnp.zeros_like(l_scr)
        t_scr[:, :] = jnp.zeros_like(t_scr)
        if smoothing > 0.0:
            s_scr[:, :] = jnp.zeros_like(s_scr)

    z = z_ref[:, :].astype(jnp.float32)
    col = j * bv + jax.lax.broadcasted_iota(jnp.int32, z.shape, 1)
    if has_pad:
        valid = col < v
        zm = jnp.where(valid, z, MASK_VALUE)
    else:
        valid = None
        zm = z

    m_prev = m_scr[:, :1]
    m_new = jnp.maximum(m_prev, jnp.max(zm, axis=-1, keepdims=True))
    corr = jnp.exp(m_prev - m_new)
    l_new = l_scr[:, :1] * corr + jnp.sum(
        jnp.exp(zm - m_new), axis=-1, keepdims=True
    )
    m_scr[:, :] = jnp.broadcast_to(m_new, m_scr.shape)
    l_scr[:, :] = jnp.broadcast_to(l_new, l_scr.shape)

    hit = col == lab_ref[:, :1]
    t_scr[:, :1] += jnp.sum(
        jnp.where(hit, z, 0.0), axis=-1, keepdims=True
    )
    if smoothing > 0.0:
        zs = jnp.where(valid, z, 0.0) if has_pad else z
        s_scr[:, :1] += jnp.sum(zs, axis=-1, keepdims=True)

    @pl.when(j == nv - 1)
    def _finalize():
        lse = m_scr[:, :1] + jnp.log(l_scr[:, :1])
        loss = lse - (1.0 - smoothing) * t_scr[:, :1]
        if smoothing > 0.0:
            loss = loss - (smoothing / v) * s_scr[:, :1]
        loss_ref[:, :] = jnp.broadcast_to(loss, loss_ref.shape)
        lse_ref[:, :] = jnp.broadcast_to(lse, lse_ref.shape)


def _xent_bwd_kernel(z_ref, lab_ref, lse_ref, g_ref, dz_ref,
                     *, v, bv, smoothing, has_pad):
    j = pl.program_id(1)
    z = z_ref[:, :].astype(jnp.float32)
    p = jnp.exp(z - lse_ref[:, :1])
    col = j * bv + jax.lax.broadcasted_iota(jnp.int32, z.shape, 1)
    q = jnp.where(col == lab_ref[:, :1], 1.0 - smoothing, 0.0)
    if smoothing > 0.0:
        q = q + smoothing / v
    dz = g_ref[:, :1] * (p - q)
    if has_pad:
        dz = jnp.where(col < v, dz, 0.0)
    dz_ref[:, :] = dz.astype(dz_ref.dtype)


# ---------------------------------------------------------------------------
# pallas_call plumbing + custom_vjp
# ---------------------------------------------------------------------------


def _xent_fwd_call(logits, labels, smoothing, interpret):
    b, v = logits.shape
    zp, lab, bb, bv, b_pad, v_pad = _setup(logits, labels)
    grid = (b_pad // bb, v_pad // bv)
    stat = pl.BlockSpec((bb, 128), lambda i, j: (i, 0),
                        memory_space=pltpu.VMEM)
    loss, lse = pl.pallas_call(
        functools.partial(
            _xent_fwd_kernel, v=v, bv=bv, smoothing=smoothing,
            has_pad=v_pad != v,
        ),
        grid=grid,
        compiler_params=COMPILER_PARAMS(
            dimension_semantics=("parallel", "arbitrary")
        ),
        in_specs=[
            pl.BlockSpec((bb, bv), lambda i, j: (i, j),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((bb, 1), lambda i, j: (i, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=[stat, stat],
        out_shape=[
            jax.ShapeDtypeStruct((b_pad, 128), jnp.float32),
            jax.ShapeDtypeStruct((b_pad, 128), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((bb, 128), jnp.float32),
            pltpu.VMEM((bb, 128), jnp.float32),
            pltpu.VMEM((bb, 128), jnp.float32),
            pltpu.VMEM((bb, 128), jnp.float32),
        ],
        interpret=interpret,
    )(zp, lab)
    return loss[:b, 0], lse[:b, 0]


def _xent_bwd_call(logits, labels, lse, g, smoothing, interpret):
    b, v = logits.shape
    zp, lab, bb, bv, b_pad, v_pad = _setup(logits, labels)
    stat = pl.BlockSpec((bb, 128), lambda i, j: (i, 0),
                        memory_space=pltpu.VMEM)
    dz = pl.pallas_call(
        functools.partial(
            _xent_bwd_kernel, v=v, bv=bv, smoothing=smoothing,
            has_pad=v_pad != v,
        ),
        grid=(b_pad // bb, v_pad // bv),
        compiler_params=COMPILER_PARAMS(
            dimension_semantics=("parallel", "parallel")
        ),
        in_specs=[
            pl.BlockSpec((bb, bv), lambda i, j: (i, j),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((bb, 1), lambda i, j: (i, 0),
                         memory_space=pltpu.VMEM),
            stat,
            stat,
        ],
        out_specs=pl.BlockSpec((bb, bv), lambda i, j: (i, j),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((b_pad, v_pad), logits.dtype),
        interpret=interpret,
    )(zp, lab, _row_stat(lse, b_pad), _row_stat(g, b_pad))
    if (b_pad, v_pad) != (b, v):
        dz = dz[:b, :v]
    return dz


@functools.partial(jax.custom_vjp, nondiff_argnums=(2, 3))
def _xent(logits, labels, smoothing, interpret):
    loss, _ = _xent_fwd_call(logits, labels, smoothing, interpret)
    return loss


def _xent_vjp_fwd(logits, labels, smoothing, interpret):
    loss, lse = _xent_fwd_call(logits, labels, smoothing, interpret)
    return loss, (logits, labels, lse)


def _xent_vjp_bwd(smoothing, interpret, res, g):
    logits, labels, lse = res
    dz = _xent_bwd_call(logits, labels, lse, g, smoothing, interpret)
    return dz, np.zeros(labels.shape, dtype=jax.dtypes.float0)


_xent.defvjp(_xent_vjp_fwd, _xent_vjp_bwd)


# ---------------------------------------------------------------------------
# public entries
# ---------------------------------------------------------------------------


def softmax_cross_entropy_ref(
    logits: jax.Array, labels: jax.Array, label_smoothing: float = 0.0
) -> jax.Array:
    """The optax composite tpudl.train.loop always used (per-example,
    [B] f32) — the behavior baseline every fused parity test compares
    against."""
    import optax

    if label_smoothing > 0.0:
        onehot = optax.smooth_labels(
            jax.nn.one_hot(labels, logits.shape[-1]), label_smoothing
        )
        return optax.softmax_cross_entropy(logits, onehot)
    return optax.softmax_cross_entropy_with_integer_labels(logits, labels)


def softmax_cross_entropy(
    logits: jax.Array,
    labels: jax.Array,
    label_smoothing: float = 0.0,
    *,
    impl: str = "auto",
    interpret: Optional[bool] = None,
) -> jax.Array:
    """Per-example softmax cross-entropy over integer labels
    (``logits`` [..., V], ``labels`` [...] int; returns [...] f32 —
    leading dims are rank-generic like the optax composite, so the
    LM-shaped [B, S, V] call works on both paths).

    ``impl="fused"`` streams the vocab axis (online logsumexp) so the
    [B, V] softmax is never materialized in HBM — forward keeps per-row
    statistics only, backward writes the gradient tile-by-tile. See the
    module docstring for the dispatch contract."""
    if logits.ndim < 2 or labels.shape != logits.shape[:-1]:
        raise ValueError(
            f"expected logits [..., V] and labels [...], got "
            f"{logits.shape} and {labels.shape}"
        )
    fused, interpret = resolve_impl(impl, interpret)
    if not fused:
        # The composite broadcasts leading dims natively — no reshape,
        # bit-identical to the pre-seam optax call.
        return softmax_cross_entropy_ref(logits, labels, label_smoothing)
    lead = labels.shape
    if logits.ndim > 2:
        logits = logits.reshape(-1, logits.shape[-1])
        labels = labels.reshape(-1)
    out = _xent(logits, labels, float(label_smoothing), interpret)
    return out.reshape(lead)
