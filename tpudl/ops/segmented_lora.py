"""Segmented LoRA: heterogeneous-adapter batched matmul over page pools.

Multi-tenant adapter serving (S-LoRA's scenario family on tpudl's paged
substrate) hits one compute problem the fused-ops tier does not cover:
every decode dispatch carries ``num_slots`` requests whose LoRA factors
are DIFFERENT per slot — a different tenant's fine-tune in every row.
Materializing each slot's ``[in, r] @ [r, out]`` delta as dense weights
would re-create the full-matrix bytes LoRA exists to avoid; batching
the base matmul but looping adapters host-side would pay one dispatch
per TENANT instead of one per step.

This kernel computes the whole ragged batch in ONE dispatch:

    delta[b] = scale[b] * (x[b] @ A_{t(b)}) @ B_{t(b)}

where the A/B factors live in fixed-size PAGE POOLS — one page holds
one rank unit (one column of A and the matching row of B) — and each
slot's ``table[b]`` row maps its logical rank indices to physical pool
pages (tpudl.serve.lora.AdapterPool owns the pools and the tables, the
exact shape of the PR-8 paged-KV addressing contract: the table is a
small traced input, so adapter load/evict never recompiles anything).
The gather happens INSIDE the kernel: unmapped table entries point at
physical page 0, which is never written and stays all-zero, so a
tenant of rank ``r < r_max`` (or a slot with no tenant at all)
contributes exactly zero through its unused pages — rank raggedness
needs no mask. Accumulation is f32 regardless of the pool dtype;
``int8`` pools carry one f32 dequant scale per page applied to the
gathered rows (the tpudl.quant symmetric contract at page granularity).

Dispatch seam (the tpudl.ops ``impl=`` contract, norms.resolve_impl's
rule): ``"reference"`` is the XLA composite — gather the pages with a
take, contract with two f32 einsums — and the parity baseline;
``"fused"`` is the Pallas kernel (compiled on TPU, interpret mode
elsewhere — the CPU test mode); ``"auto"`` picks fused on TPU. The two
differ only in f32 reduction order; benchmarks/parity_grid.py's
``lora`` cell gates them (and the sequential one-adapter-at-a-time
merged reference) at EXACT token parity for f32 pools and
teacher-forced logit-margin parity for int8 pools. Inference-only: no
custom VJP (adapters train per-tenant offline; serving only reads
them).
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from tpudl.ops.norms import resolve_impl
from tpudl.ops.pallas_utils import COMPILER_PARAMS, round_up


def _as_3d(x):
    """[B, H] -> [B, 1, H]; [B, S, H] passes through."""
    if x.ndim == 2:
        return x[:, None, :], True
    if x.ndim == 3:
        return x, False
    raise ValueError(
        f"segmented_lora takes [B, H] or [B, S, H] activations, got "
        f"shape {x.shape}"
    )


def segmented_lora_ref(x, pools, table, scale):
    """XLA composite reference: gather each slot's pages, contract in
    f32. ``pools`` is one site's pool dict (``{"a": [NP, in],
    "b": [NP, out]}`` + ``a_scale``/``b_scale`` f32 ``[NP]`` rows for
    int8 storage); ``table`` [B, P] int32 maps logical rank units to
    physical pages (0 = the all-zero trash page); ``scale`` [B] f32 is
    each slot's alpha/rank (0 for slots with no adapter)."""
    x3, squeeze = _as_3d(x)
    table = jnp.asarray(table, jnp.int32)
    scale = jnp.asarray(scale, jnp.float32)
    a = pools["a"][table].astype(jnp.float32)  # [B, P, in]
    b = pools["b"][table].astype(jnp.float32)  # [B, P, out]
    if "a_scale" in pools:
        a = a * pools["a_scale"][table][..., None]
        b = b * pools["b_scale"][table][..., None]
    coef = jnp.einsum(
        "bsh,bph->bsp", x3.astype(jnp.float32), a,
        preferred_element_type=jnp.float32,
    )
    delta = jnp.einsum(
        "bsp,bpo->bso", coef, b, preferred_element_type=jnp.float32,
    )
    delta = (delta * scale[:, None, None]).astype(x.dtype)
    return delta[:, 0, :] if squeeze else delta


def _seg_lora_kernel(
    x_ref, a_ref, b_ref, t_ref, sc_ref, *rest, pages: int, quantized: bool
):
    """One slot: gather its pages and accumulate ``pages`` rank-1
    updates in f32. The page loop is a static unroll (r_max is small —
    it is the rank budget, not the batch); page 0 rows are all-zero by
    the pool contract, so short ranks and empty slots fall out free."""
    if quantized:
        asc_ref, bsc_ref, out_ref = rest
    else:
        (out_ref,) = rest
    x = x_ref[0].astype(jnp.float32)  # [S_pad, H_pad]
    acc = jnp.zeros(out_ref.shape[1:], jnp.float32)  # [S_pad, O_pad]
    for j in range(pages):
        page = t_ref[0, j]
        a_row = a_ref[page, :].astype(jnp.float32)  # [H_pad]
        b_row = b_ref[page, :].astype(jnp.float32)  # [O_pad]
        if quantized:
            a_row = a_row * asc_ref[page, 0]
            b_row = b_row * bsc_ref[page, 0]
        coef = jnp.sum(x * a_row[None, :], axis=-1, keepdims=True)
        acc = acc + coef * b_row[None, :]
    out_ref[0] = (acc * sc_ref[0, 0]).astype(out_ref.dtype)


def _pad_rows(arr, rows: int, cols: Optional[int] = None):
    pad = [(0, rows - arr.shape[0])]
    if cols is not None:
        pad.append((0, cols - arr.shape[1]))
    return jnp.pad(arr, pad)


def segmented_lora_fused(x, pools, table, scale, interpret: bool):
    """The Pallas path: grid over slots, table/scales in SMEM, pools
    VMEM-resident (adapter pools are rank-units, orders of magnitude
    smaller than the weights they adapt — they fit on-chip at every
    geometry this repo serves)."""
    x3, squeeze = _as_3d(x)
    b_dim, s, h = x3.shape
    table = jnp.asarray(table, jnp.int32)
    scale = jnp.asarray(scale, jnp.float32)
    pages = int(table.shape[1])
    quantized = "a_scale" in pools
    o = int(pools["b"].shape[1])
    np_rows = int(pools["a"].shape[0])

    h_pad = round_up(h, 128)
    o_pad = round_up(o, 128)
    s_pad = round_up(s, 8)
    # int8 pools tile at (32, 128); f32 at (8, 128).
    np_pad = round_up(np_rows, 32 if quantized else 8)

    xp = jnp.pad(x3, ((0, 0), (0, s_pad - s), (0, h_pad - h)))
    ap = _pad_rows(pools["a"], np_pad, h_pad)
    bp = _pad_rows(pools["b"], np_pad, o_pad)

    x_spec = pl.BlockSpec(
        (1, s_pad, h_pad), lambda i: (i, 0, 0), memory_space=pltpu.VMEM
    )
    pool_a_spec = pl.BlockSpec(
        (np_pad, h_pad), lambda i: (0, 0), memory_space=pltpu.VMEM
    )
    pool_b_spec = pl.BlockSpec(
        (np_pad, o_pad), lambda i: (0, 0), memory_space=pltpu.VMEM
    )
    t_spec = pl.BlockSpec(
        (1, pages), lambda i: (i, 0), memory_space=pltpu.SMEM
    )
    sc_spec = pl.BlockSpec(
        (1, 1), lambda i: (i, 0), memory_space=pltpu.SMEM
    )
    in_specs = [x_spec, pool_a_spec, pool_b_spec, t_spec, sc_spec]
    args = [xp, ap, bp, table, scale[:, None]]
    if quantized:
        page_sc_spec = pl.BlockSpec(
            (np_pad, 1), lambda i: (0, 0), memory_space=pltpu.SMEM
        )
        in_specs += [page_sc_spec, page_sc_spec]
        args += [
            _pad_rows(pools["a_scale"][:, None], np_pad),
            _pad_rows(pools["b_scale"][:, None], np_pad),
        ]
    out = pl.pallas_call(
        functools.partial(
            _seg_lora_kernel, pages=pages, quantized=quantized
        ),
        grid=(b_dim,),
        compiler_params=COMPILER_PARAMS(
            dimension_semantics=("parallel",)
        ),
        in_specs=in_specs,
        out_specs=pl.BlockSpec(
            (1, s_pad, o_pad), lambda i: (i, 0, 0),
            memory_space=pltpu.VMEM,
        ),
        out_shape=jax.ShapeDtypeStruct((b_dim, s_pad, o_pad), x.dtype),
        interpret=interpret,
    )(*args)
    out = out[:, :s, :o]
    return out[:, 0, :] if squeeze else out


def segmented_lora(
    x,
    pools,
    table,
    scale,
    *,
    impl: str = "auto",
    interpret: Optional[bool] = None,
):
    """``delta[b] = scale[b] * (x[b] @ A_pages(table[b])) @
    B_pages(table[b])`` — the heterogeneous-adapter batched LoRA delta
    for one projection site. Returns ``x.dtype``, shape ``[B, S, out]``
    (or ``[B, out]`` for 2-D ``x``); callers add it onto the base
    projection's output. See the module docstring for the pool/table
    contract and the ``impl`` seam."""
    if set(pools) not in ({"a", "b"}, {"a", "b", "a_scale", "b_scale"}):
        raise ValueError(
            f"pool dict must hold a/b (+ a_scale/b_scale when int8), "
            f"got keys {sorted(pools)}"
        )
    use_fused, interpret = resolve_impl(impl, interpret)
    if use_fused:
        return segmented_lora_fused(x, pools, table, scale, interpret)
    return segmented_lora_ref(x, pools, table, scale)
