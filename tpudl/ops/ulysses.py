"""Ulysses-style sequence parallelism: all-to-all head/sequence resharding.

The second of the two canonical long-context strategies (the brief's
"ring attention OR all-to-all sequence parallelism"; the reference tree
has neither — its NLP family is an empty placeholder, reference
notebooks/nlp/README.md, SURVEY.md §5.7). Complements
tpudl.ops.ring_attention:

- **ring**: K/V shards rotate around the `sp` ring (n-1 ppermute hops
  overlapped with blockwise compute); attention math is reimplemented as
  an online-softmax merge. Communication scales with S but overlaps.
- **ulysses** (this module): two `all_to_all` collectives reshard
  activations from sequence-sharded [B, S/n, H, D] to head-sharded
  [B, S, H/n, D]; in between, every device runs full-sequence attention
  on its head slice. With ``local_impl="reference"`` the numerics are
  exactly the reference implementation's by construction; the default on
  TPU is ``local_impl="flash"`` (the Pallas kernel — flash-tolerance
  numerics, but peak memory linear in S instead of the [B, H/n, S, S]
  score tensor). The all-to-all rides ICI's all-to-all bandwidth;
  requires heads % sp == 0.

Which to use: ulysses while heads ≥ sp (cheap, exact, simple); ring when
sequence length pushes past what a full-S slice of heads can hold or
sp exceeds the head count.
"""

from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from tpudl.runtime.mesh import AXIS_SEQ, BATCH_AXES, AXIS_TENSOR, shard_map


def _ulysses_local(q, k, v, kvm=None, key_data=None, *, axis_name, causal,
                   scale, local_impl, dropout_rate=0.0, key_impl=None,
                   fold_axes=()):
    """Per-device body. q/k/v: [B, S/n, H_local, D] (H_local = H/tp·... the
    heads remaining on this device's tp slice); kvm: [B, S] full-sequence
    kv-validity row (replicated over sp), or None when the caller passed
    no mask — kept None so flash takes its maskless codegen path (no
    per-tile kv-row traffic on the unmasked long-context hot path).

    Dropout: after the all-to-all each device holds FULL sequences for
    its head slice, so attention-probability dropout is exact BERT/Llama
    semantics applied locally (in-kernel hardware PRNG under flash;
    jax.random masks under reference). Ring achieves the same semantics
    differently — numerator-only masking inside its distributed-softmax
    merge (tpudl.ops.ring_attention)."""
    from tpudl.ops.attention import dot_product_attention

    n = jax.lax.psum(1, axis_name)

    # [B, S/n, H, D] -> [B, S, H/n, D]: split heads over the ring, gather
    # the sequence. One ICI all-to-all each way.
    def seq_to_heads(x):
        return jax.lax.all_to_all(
            x, axis_name, split_axis=2, concat_axis=1, tiled=True
        )

    def heads_to_seq(x):
        return jax.lax.all_to_all(
            x, axis_name, split_axis=1, concat_axis=2, tiled=True
        )

    if n > 1:
        q, k, v = seq_to_heads(q), seq_to_heads(k), seq_to_heads(v)

    rng = None
    if dropout_rate > 0.0:
        from tpudl.ops.dropout import device_fold_rng

        rng = device_fold_rng(key_data, key_impl, fold_axes)

    if local_impl == "flash":
        # Pallas flash kernel on the head slice: peak memory stays linear
        # in S instead of materializing the [B, H/n, S, S] score tensor —
        # the whole point of the long-context path ulysses serves.
        from tpudl.ops.flash_attention import flash_attention

        out = flash_attention(
            q, k, v, mask=kvm, causal=causal, scale=scale,
            dropout_rate=dropout_rate, dropout_rng=rng,
        )
    else:
        from tpudl.ops.attention import combine_kv_causal_mask

        out = dot_product_attention(
            q, k, v,
            mask=combine_kv_causal_mask(
                None if kvm is None else kvm > 0,
                q.shape[1], k.shape[1], causal,
            ),
            scale=scale,
            dropout_rate=dropout_rate,
            dropout_rng=rng,
        )
    if n > 1:
        out = heads_to_seq(out)
    return out


def ulysses_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    mask: Optional[jax.Array] = None,
    causal: bool = False,
    scale: Optional[float] = None,
    mesh: Optional[Mesh] = None,
    axis_name: str = AXIS_SEQ,
    local_impl: Optional[str] = None,
    dropout_rate: float = 0.0,
    dropout_rng: Optional[jax.Array] = None,
) -> jax.Array:
    """Sequence-parallel attention on [B, S, H, D] via all-to-all
    (tpudl.ops.attention contract; Sq == Skv — one shared sequence axis).

    ``mask`` may be a [B, S] kv-validity row or a [B, 1, 1, S] padding
    mask (dense masks are rejected, as in ring/flash). ``mesh`` defaults
    to the active tpudl mesh; batch shards over (dp, fsdp), sequence over
    `sp`, heads over `tp` — requires (H / tp) % sp == 0.

    ``local_impl`` picks the per-device attention body: "flash" (Pallas
    kernel — memory linear in S, the long-context default on TPU) or
    "reference" (einsum — exact tpudl.ops.attention numerics, the default
    on CPU where the kernel would run interpreted). None = by backend.

    ``dropout_rate`` > 0 (round 4): attention-probability dropout with
    exact semantics — after the all-to-all every head attends its full
    sequence locally, so this is plain per-head dropout; each mesh slot
    folds its position into ``dropout_rng`` for independent masks. The
    flash body draws in-kernel (TPU hardware PRNG); the reference body
    uses the low-width-bits jax.random path, which also runs on CPU.
    """
    from tpudl.ops.attention import normalize_kv_mask, unmeshed_attention
    from tpudl.parallel.sharding import current_mesh

    # Resolve + validate the local body BEFORE the unmeshed early-return,
    # so an invalid value always errors and an explicitly pinned "flash"
    # (chosen for its O(S) memory) is honored even without a mesh.
    if local_impl is None:
        from tpudl.ops.attention import is_tpu_backend

        # Flash only where the Pallas TPU kernel lowers; cpu/gpu take the
        # exact einsum body.
        local_impl = "flash" if is_tpu_backend() else "reference"
    if local_impl not in ("flash", "reference"):
        raise ValueError(
            f"local_impl must be 'flash' or 'reference', got {local_impl!r}"
        )

    if dropout_rate > 0.0 and dropout_rng is None:
        raise ValueError("dropout_rate > 0 requires a dropout_rng")

    if mesh is None:
        mesh = current_mesh()
    if mesh is None:
        if local_impl == "flash":
            from tpudl.ops.flash_attention import flash_attention

            return flash_attention(
                q, k, v, mask=mask, causal=causal, scale=scale,
                dropout_rate=dropout_rate, dropout_rng=dropout_rng,
            )
        return unmeshed_attention(
            q, k, v, mask, causal, scale,
            dropout_rate=dropout_rate, dropout_rng=dropout_rng,
        )

    b, s, h, d = q.shape
    if k.shape[1] != s:
        raise ValueError(
            f"ulysses attention shards q and kv along one sequence axis; "
            f"got Sq={s}, Skv={k.shape[1]}"
        )
    n_sp = mesh.shape[axis_name]
    n_tp = mesh.shape[AXIS_TENSOR]
    if s % n_sp != 0:
        raise ValueError(f"seq len {s} not divisible by {axis_name}={n_sp}")
    local_heads = h // n_tp if h % n_tp == 0 else h
    if local_heads % n_sp != 0:
        raise ValueError(
            f"{local_heads} local heads not divisible by {axis_name}={n_sp} "
            f"(ulysses shards heads over sp; use implementation='ring' when "
            f"sp exceeds the per-device head count)"
        )
    if scale is None:
        scale = d ** -0.5

    batch = tuple(a for a in BATCH_AXES if mesh.shape[a] > 1) or None
    heads_sharded = h % max(n_tp, 1) == 0 and n_tp > 1
    heads = AXIS_TENSOR if heads_sharded else None
    qkv_spec = P(batch, axis_name, heads, None)
    key_impl = (
        jax.random.key_impl(dropout_rng) if dropout_rate > 0.0 else None
    )
    from tpudl.ops.dropout import shard_fold_axes

    fold_axes = shard_fold_axes(mesh, axis_name, heads_sharded, BATCH_AXES)
    body = partial(_ulysses_local, axis_name=axis_name, causal=causal,
                   scale=scale, local_impl=local_impl,
                   dropout_rate=dropout_rate, key_impl=key_impl,
                   fold_axes=fold_axes)

    operands = [q, k, v]
    in_specs = [qkv_spec, qkv_spec, qkv_spec]
    if mask is not None:
        operands.append(normalize_kv_mask(mask, b, s, impl="ulysses_attention"))
        in_specs.append(P(batch, None))
    if dropout_rate > 0.0:
        # Key data rides as a replicated raw-uint32 operand (key ARRAYS
        # don't thread shard_map specs); each device re-wraps and folds
        # its mesh position in (_device_dropout_rng).
        operands.append(jax.random.key_data(dropout_rng))
        in_specs.append(P(*([None] * jax.random.key_data(dropout_rng).ndim)))
        if mask is None:
            # kvm is positional before key_data in the body signature —
            # wrap the ONE bound partial rather than rebuilding it.
            inner = body
            body = lambda q_, k_, v_, kd_: inner(q_, k_, v_, None, kd_)  # noqa: E731
    fn = shard_map(
        body,
        mesh=mesh,
        in_specs=tuple(in_specs),
        out_specs=qkv_spec,
        check_vma=False,
    )
    return fn(*operands)
