"""Pallas TPU flash attention: fused, memory-linear attention.

The one first-party custom kernel the framework warrants (SURVEY.md §2.2):
attention is the hot op of the NLP configs (BASELINE.json configs[1,3,4])
and the naive einsum path materializes the [B, H, Sq, Skv] logits in HBM.
This kernel never does — the online-softmax recurrence keeps one
(block_q, block_k) tile in VMEM, both matmuls hit the MXU in the input
dtype with f32 accumulation, and the backward pass recomputes probability
tiles from the saved logsumexp instead of storing them (memory O(S), not
O(S^2)).

Layout: grid (batch, heads, q_blocks, kv_blocks) with the kv axis
innermost, so Pallas double-buffers the K/V tile stream from HBM while the
MXU works; running max / denominator / output accumulators live in VMEM
scratch across kv steps.

Masking: padding masks enter as a [B, Skv] kv-validity row (the BERT
case), causal masks are generated in-kernel from block indices (the
decoder case) — neither ever materializes an S×S array. Arbitrary dense
[B, H, Sq, Skv] masks are not supported here; use the reference
implementation for those.

Dropout (attention-probability, BERT/Llama-style) runs IN-KERNEL from
the TPU hardware PRNG using the same reseed-regenerate contract as
tpudl.ops.fused_attention (tpudl.ops.pallas_utils): each logical
(batch, head, q_tile, kv_tile) cell seeds its own stream keyed by the
LOGICAL tile id — not the grid-order cell id, which differs between the
kv-major dk/dv launch and the q-major forward/dq launches — and the
backward regenerates the identical keep mask instead of storing it, so
long-context dropout costs zero HBM. The online-softmax denominator
accumulates UNDROPPED probabilities (dropout applies after softmax
normalization); only the p@V numerator and the dp/dv backward terms are
masked, and the standard delta = sum(do*o) identity still equals
sum_j w'_j dp'_j under the mask, so the backward recurrences are
unchanged in form. TPU-only (like the fused kernel): interpret mode has
no hardware PRNG, so dropout_rate > 0 raises there; real-TPU
verification lives in scripts/tpu_dropout_check.py.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from tpudl.ops.attention import MASK_VALUE
from tpudl.ops.pallas_utils import COMPILER_PARAMS

#: Default tile sizes; VPU/MXU-aligned (multiples of the f32 (8,128) tile).
#: Swept on TPU v5 lite at seq 4096 (2026-07-30): large kv tiles keep the
#: MXU fed (256x256 -> 49 ms, 512x1024 -> 22 ms fwd+bwd; XLA einsum
#: attention: 26.5 ms).
DEFAULT_BLOCK_Q = 512
DEFAULT_BLOCK_K = 1024


def _round_up(x: int, m: int) -> int:
    return ((x + m - 1) // m) * m


def _interpret_default() -> bool:
    # Interpret mode off-TPU so the same kernel runs in the hermetic test
    # environment (SURVEY.md §4.2) and compiled on TPU.
    from tpudl.ops.attention import is_tpu_backend

    return not is_tpu_backend()


#: Grid semantics for every pallas_call here: batch/head/q axes carry no
#: cross-step state (parallel); the kv (resp. q) reduction axis streams
#: through the VMEM scratch accumulators (arbitrary).
_DIM_SEMANTICS = ("parallel", "parallel", "parallel", "arbitrary")


def _fit_block(seq: int, limit: int) -> int:
    """Largest power-of-two block <= limit that divides the 128-aligned
    sequence — avoids pad-to-tile waste on non-power-of-two lengths
    (e.g. skv=1280 takes 256-blocks, not a 2048 pad)."""
    aligned = _round_up(seq, 128)
    b = min(limit, aligned)
    while b > 128 and aligned % b != 0:
        b //= 2
    return max(b, 128)


def _block_sizes(sq: int, skv: int, block_q, block_k):
    bq = block_q or _fit_block(sq, DEFAULT_BLOCK_Q)
    bk = block_k or _fit_block(skv, DEFAULT_BLOCK_K)
    return min(bq, _round_up(sq, 128)), min(bk, _round_up(skv, 128))


def _tile_contributes(qi, kv, causal, block_q, block_k, causal_offset):
    """Whether kv tile `kv` can contribute to q tile `qi` (causal skip).

    Causal masking is bottom-right aligned like
    tpudl.ops.attention.causal_mask: kv_idx <= q_idx + (Skv - Sq)."""
    if not causal:
        return True
    q_end = (qi + 1) * block_q - 1 + causal_offset
    return kv * block_k <= q_end


def _dropout_keep(seed_ref, bi, hi, qi, kv, nh, nq, nkv, shape, rate):
    """Regenerate the dropout keep-mask for logical tile (bi, hi, qi, kv).

    Seeded by the LOGICAL flattened tile id so the q-major forward/dq
    grids and the kv-major dk/dv grid reproduce bit-identical masks for
    the same tile (the pallas_utils reseed contract). One
    prng_random_bits draw per cell, immediately after seeding."""
    from tpudl.ops.pallas_utils import keep_mask, seed_cell

    cell = ((bi * nh + hi) * nq + qi) * nkv + kv
    seed_cell(seed_ref, cell)
    return keep_mask(shape, rate)


def _tile_keep(kvm_row, qi, kv, causal, block_q, block_k, causal_offset,
               has_kvmask):
    """[block_q, block_k] attend-mask for one tile (or None when nothing
    masks): kv validity row plus the (bottom-right-aligned) causal
    triangle, generated from indices — never materialized at [Sq, Skv]."""
    keep = (kvm_row > 0.0)[None, :] if has_kvmask else None
    if causal:
        q_ids = qi * block_q + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 0
        )
        kv_ids = kv * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 1
        )
        tri = kv_ids <= q_ids + causal_offset
        keep = tri if keep is None else jnp.logical_and(keep, tri)
    return keep


# ---------------------------------------------------------------------------
# Forward
# ---------------------------------------------------------------------------


def _fwd_kernel(seed_ref, q_ref, k_ref, v_ref, kvm_ref, o_ref, lse_ref,
                m_scr, l_scr, acc_scr,
                *, scale, causal, block_q, block_k, causal_offset,
                has_kvmask, rate):
    qi, kv = pl.program_id(2), pl.program_id(3)
    nkv = pl.num_programs(3)

    @pl.when(kv == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, MASK_VALUE)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    @pl.when(_tile_contributes(qi, kv, causal, block_q, block_k, causal_offset))
    def _accumulate():
        q = q_ref[0, 0, :, :]
        k = k_ref[0, 0, :, :]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        ) * scale  # [block_q, block_k]

        keep = _tile_keep(kvm_ref[0, 0, :], qi, kv, causal,
                          block_q, block_k, causal_offset, has_kvmask)
        if keep is not None:
            s = jnp.where(keep, s, MASK_VALUE)

        m_prev = m_scr[:, :1]  # [block_q, 1]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        if keep is not None:
            p = jnp.where(keep, p, 0.0)
        corr = jnp.exp(m_prev - m_new)  # [block_q, 1]
        # Denominator: UNDROPPED p (dropout acts after normalization).
        l_new = l_scr[:, :1] * corr + jnp.sum(p, axis=-1, keepdims=True)
        if rate > 0.0:
            keep_d = _dropout_keep(
                seed_ref, pl.program_id(0), pl.program_id(1), qi, kv,
                pl.num_programs(1), pl.num_programs(2), nkv,
                (block_q, block_k), rate,
            )
            p_num = jnp.where(keep_d, p, 0.0)
        else:
            p_num = p
        acc_scr[:] = acc_scr[:] * corr + jax.lax.dot_general(
            p_num.astype(v_ref.dtype), v_ref[0, 0, :, :],
            (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32,
        )
        m_scr[:] = jnp.broadcast_to(m_new, m_scr.shape)
        l_scr[:] = jnp.broadcast_to(l_new, l_scr.shape)

    @pl.when(kv == nkv - 1)
    def _finalize():
        l = l_scr[:, :1]
        l_safe = jnp.where(l > 0.0, l, 1.0)
        out = acc_scr[:] / l_safe
        if rate > 0.0:
            out = out * (1.0 / (1.0 - rate))
        o_ref[0, 0, :, :] = out.astype(o_ref.dtype)
        lse_ref[0, 0, 0, :] = m_scr[:, 0] + jnp.log(l_safe[:, 0])


def _fwd(q, k, v, kvmask, seed, causal, scale, block_q, block_k, interpret,
         has_mask=True, rate=0.0):
    b, sq, h, d = q.shape
    skv = k.shape[1]
    bq, bk = _block_sizes(sq, skv, block_q, block_k)
    sq_p, skv_p = _round_up(sq, bq), _round_up(skv, bk)

    # BSHD -> BHSD, padded to tile multiples; padded kv is masked off.
    qt = jnp.pad(q.transpose(0, 2, 1, 3), ((0, 0), (0, 0), (0, sq_p - sq), (0, 0)))
    kt = jnp.pad(k.transpose(0, 2, 1, 3), ((0, 0), (0, 0), (0, skv_p - skv), (0, 0)))
    vt = jnp.pad(v.transpose(0, 2, 1, 3), ((0, 0), (0, 0), (0, skv_p - skv), (0, 0)))
    # kv-validity mask as [B, 1, Skv]: the lane-dim layout TPU block specs
    # require (last two block dims must be tile-aligned or match the array).
    kvm = jnp.pad(kvmask, ((0, 0), (0, skv_p - skv)))[:, None, :]

    # Padding the kv axis re-introduces masking even without a user mask.
    has_kvmask = bool(has_mask) or skv_p != skv

    grid = (b, h, sq_p // bq, skv_p // bk)
    o, lse = pl.pallas_call(
        functools.partial(
            _fwd_kernel, scale=scale, causal=causal, block_q=bq, block_k=bk,
            causal_offset=skv - sq, has_kvmask=has_kvmask, rate=rate,
        ),
        grid=grid,
        compiler_params=COMPILER_PARAMS(
            dimension_semantics=_DIM_SEMANTICS
        ),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec((1, 1, bq, d), lambda b, h, i, j: (b, h, i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1, bk, d), lambda b, h, i, j: (b, h, j, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1, bk, d), lambda b, h, i, j: (b, h, j, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1, bk), lambda b, h, i, j: (b, 0, j),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=[
            pl.BlockSpec((1, 1, bq, d), lambda b, h, i, j: (b, h, i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1, 1, bq), lambda b, h, i, j: (b, h, 0, i),
                         memory_space=pltpu.VMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b, h, sq_p, d), q.dtype),
            jax.ShapeDtypeStruct((b, h, 1, sq_p), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((bq, 128), jnp.float32),
            pltpu.VMEM((bq, 128), jnp.float32),
            pltpu.VMEM((bq, d), jnp.float32),
        ],
        interpret=interpret,
    )(seed, qt, kt, vt, kvm)
    return o, lse, (qt, kt, vt, kvm)


# ---------------------------------------------------------------------------
# Backward: dq over (q_blocks, kv_blocks); dk/dv over (kv_blocks, q_blocks).
# Probability tiles are recomputed from the saved logsumexp.
# ---------------------------------------------------------------------------


def _dq_kernel(seed_ref, q_ref, k_ref, v_ref, kvm_ref, do_ref, lse_ref,
               dlt_ref, dq_ref, dq_scr,
               *, scale, causal, block_q, block_k, causal_offset,
               has_kvmask, rate):
    qi, kv = pl.program_id(2), pl.program_id(3)
    nkv = pl.num_programs(3)

    @pl.when(kv == 0)
    def _init():
        dq_scr[:] = jnp.zeros_like(dq_scr)

    @pl.when(_tile_contributes(qi, kv, causal, block_q, block_k, causal_offset))
    def _accumulate():
        q = q_ref[0, 0, :, :]
        k = k_ref[0, 0, :, :]
        v = v_ref[0, 0, :, :]
        do = do_ref[0, 0, :, :]
        lse = lse_ref[0, 0, 0, :][:, None]
        delta = dlt_ref[0, 0, 0, :][:, None]

        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        ) * scale
        keep = _tile_keep(kvm_ref[0, 0, :], qi, kv, causal,
                          block_q, block_k, causal_offset, has_kvmask)
        p = jnp.exp(s - lse)
        if keep is not None:
            p = jnp.where(keep, p, 0.0)
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )
        if rate > 0.0:
            # grad w.r.t. TRUE softmax p: g = keep_d * dp / (1-rate);
            # delta (= sum(do*o)) already equals sum_j w'_j dp_j.
            keep_d = _dropout_keep(
                seed_ref, pl.program_id(0), pl.program_id(1), qi, kv,
                pl.num_programs(1), pl.num_programs(2), nkv,
                (block_q, block_k), rate,
            )
            dp = jnp.where(keep_d, dp * (1.0 / (1.0 - rate)), 0.0)
        ds = p * (dp - delta) * scale
        dq_scr[:] += jax.lax.dot_general(
            ds.astype(k.dtype), k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    @pl.when(kv == nkv - 1)
    def _finalize():
        dq_ref[0, 0, :, :] = dq_scr[:].astype(dq_ref.dtype)


def _dkv_kernel(seed_ref, q_ref, k_ref, v_ref, kvm_ref, do_ref, lse_ref,
                dlt_ref, dk_ref, dv_ref, dk_scr, dv_scr,
                *, scale, causal, block_q, block_k, causal_offset,
                has_kvmask, rate):
    kv, qi = pl.program_id(2), pl.program_id(3)
    nq = pl.num_programs(3)

    @pl.when(qi == 0)
    def _init():
        dk_scr[:] = jnp.zeros_like(dk_scr)
        dv_scr[:] = jnp.zeros_like(dv_scr)

    @pl.when(_tile_contributes(qi, kv, causal, block_q, block_k, causal_offset))
    def _accumulate():
        q = q_ref[0, 0, :, :]
        k = k_ref[0, 0, :, :]
        v = v_ref[0, 0, :, :]
        do = do_ref[0, 0, :, :]
        lse = lse_ref[0, 0, 0, :][:, None]
        delta = dlt_ref[0, 0, 0, :][:, None]

        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        ) * scale
        keep = _tile_keep(kvm_ref[0, 0, :], qi, kv, causal,
                          block_q, block_k, causal_offset, has_kvmask)
        p = jnp.exp(s - lse)
        if keep is not None:
            p = jnp.where(keep, p, 0.0)
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )
        if rate > 0.0:
            # kv-major grid: note qi/kv pulled from swapped program_ids,
            # nq from axis 3 and nkv from axis 2 — the LOGICAL id matches
            # the forward/dq launches bit-for-bit.
            keep_d = _dropout_keep(
                seed_ref, pl.program_id(0), pl.program_id(1), qi, kv,
                pl.num_programs(1), nq, pl.num_programs(2),
                (block_q, block_k), rate,
            )
            inv = 1.0 / (1.0 - rate)
            p_num = jnp.where(keep_d, p * inv, 0.0)
            dp = jnp.where(keep_d, dp * inv, 0.0)
        else:
            p_num = p
        dv_scr[:] += jax.lax.dot_general(
            p_num.astype(do.dtype), do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        ds = p * (dp - delta) * scale
        dk_scr[:] += jax.lax.dot_general(
            ds.astype(q.dtype), q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    @pl.when(qi == nq - 1)
    def _finalize():
        dk_ref[0, 0, :, :] = dk_scr[:].astype(dk_ref.dtype)
        dv_ref[0, 0, :, :] = dv_scr[:].astype(dv_ref.dtype)


# ---------------------------------------------------------------------------
# custom_vjp wrapper
# ---------------------------------------------------------------------------


@functools.partial(jax.custom_vjp, nondiff_argnums=(5, 6, 7, 8, 9, 10, 11))
def _flash(q, k, v, kvmask, seed, causal, scale, block_q, block_k,
           interpret, has_mask, rate):
    o, _, _ = _fwd(q, k, v, kvmask, seed, causal, scale, block_q, block_k,
                   interpret, has_mask, rate)
    return o[:, :, : q.shape[1], :].transpose(0, 2, 1, 3)


def _flash_fwd(q, k, v, kvmask, seed, causal, scale, block_q, block_k,
               interpret, has_mask, rate):
    o, lse, (qt, kt, vt, kvm) = _fwd(
        q, k, v, kvmask, seed, causal, scale, block_q, block_k, interpret,
        has_mask, rate,
    )
    out = o[:, :, : q.shape[1], :].transpose(0, 2, 1, 3)
    # Padded tensors are the residuals (no re-pad in bwd); the unpadded
    # kvmask rides along so bwd can recover the original Skv statically.
    return out, (qt, kt, vt, kvm, kvmask, seed, o, lse)


def _flash_bwd(causal, scale, block_q, block_k, interpret, has_mask, rate,
               res, g):
    return _bwd_core(causal, scale, block_q, block_k, interpret, has_mask,
                     rate, res, g, dlse=None)


def _bwd_core(causal, scale, block_q, block_k, interpret, has_mask, rate,
              res, g, dlse):
    """Shared backward for _flash (dlse=None) and _flash_lse.

    The lse cotangent needs NO kernel change: d(lse)/d(s_ij) = p_ij, and
    both kernels compute ``ds = p * (dp - delta)`` — so folding the lse
    cotangent in is exactly ``delta -= dlse`` on the per-row delta
    operand.
    """
    qt, kt, vt, kvm, kvmask, seed, o, lse = res
    b, h, sq_p, d = qt.shape
    skv_p = kt.shape[2]
    sq, skv = g.shape[1], kvmask.shape[1]
    bq, bk = _block_sizes(sq, skv, block_q, block_k)
    has_kvmask = bool(has_mask) or skv_p != skv
    dim_sem = COMPILER_PARAMS(dimension_semantics=_DIM_SEMANTICS)

    do = jnp.pad(
        g.astype(qt.dtype).transpose(0, 2, 1, 3),
        ((0, 0), (0, 0), (0, sq_p - sq), (0, 0)),
    )
    delta = jnp.sum(
        do.astype(jnp.float32) * o.astype(jnp.float32), axis=-1
    )[:, :, None, :]
    if dlse is not None:
        delta = delta - jnp.pad(
            dlse.astype(jnp.float32), ((0, 0), (0, 0), (0, sq_p - sq))
        )[:, :, None, :]

    q_spec = pl.BlockSpec((1, 1, bq, d), lambda b, h, i, j: (b, h, i, 0),
                          memory_space=pltpu.VMEM)
    kv_spec = pl.BlockSpec((1, 1, bk, d), lambda b, h, i, j: (b, h, j, 0),
                           memory_space=pltpu.VMEM)
    kvm_spec = pl.BlockSpec((1, 1, bk), lambda b, h, i, j: (b, 0, j),
                            memory_space=pltpu.VMEM)
    row_spec = pl.BlockSpec((1, 1, 1, bq), lambda b, h, i, j: (b, h, 0, i),
                            memory_space=pltpu.VMEM)

    seed_spec = pl.BlockSpec(memory_space=pltpu.SMEM)
    dq = pl.pallas_call(
        functools.partial(
            _dq_kernel, scale=scale, causal=causal, block_q=bq, block_k=bk,
            causal_offset=skv - sq, has_kvmask=has_kvmask, rate=rate,
        ),
        grid=(b, h, sq_p // bq, skv_p // bk),
        compiler_params=dim_sem,
        in_specs=[seed_spec, q_spec, kv_spec, kv_spec, kvm_spec, q_spec,
                  row_spec, row_spec],
        out_specs=[q_spec],
        out_shape=[jax.ShapeDtypeStruct((b, h, sq_p, d), qt.dtype)],
        scratch_shapes=[pltpu.VMEM((bq, d), jnp.float32)],
        interpret=interpret,
    )(seed, qt, kt, vt, kvm, do, lse, delta)[0]

    # kv-major grid: swap the roles of the last two grid axes in the specs.
    q_spec_t = pl.BlockSpec((1, 1, bq, d), lambda b, h, j, i: (b, h, i, 0),
                            memory_space=pltpu.VMEM)
    kv_spec_t = pl.BlockSpec((1, 1, bk, d), lambda b, h, j, i: (b, h, j, 0),
                             memory_space=pltpu.VMEM)
    kvm_spec_t = pl.BlockSpec((1, 1, bk), lambda b, h, j, i: (b, 0, j),
                              memory_space=pltpu.VMEM)
    row_spec_t = pl.BlockSpec((1, 1, 1, bq), lambda b, h, j, i: (b, h, 0, i),
                              memory_space=pltpu.VMEM)
    dk, dv = pl.pallas_call(
        functools.partial(
            _dkv_kernel, scale=scale, causal=causal, block_q=bq, block_k=bk,
            causal_offset=skv - sq, has_kvmask=has_kvmask, rate=rate,
        ),
        grid=(b, h, skv_p // bk, sq_p // bq),
        compiler_params=dim_sem,
        in_specs=[seed_spec, q_spec_t, kv_spec_t, kv_spec_t, kvm_spec_t,
                  q_spec_t, row_spec_t, row_spec_t],
        out_specs=[kv_spec_t, kv_spec_t],
        out_shape=[
            jax.ShapeDtypeStruct((b, h, skv_p, d), kt.dtype),
            jax.ShapeDtypeStruct((b, h, skv_p, d), vt.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((bk, d), jnp.float32),
            pltpu.VMEM((bk, d), jnp.float32),
        ],
        interpret=interpret,
    )(seed, qt, kt, vt, kvm, do, lse, delta)

    dq = dq[:, :, :sq, :].transpose(0, 2, 1, 3)
    dk = dk[:, :, :skv, :].transpose(0, 2, 1, 3)
    dv = dv[:, :, :skv, :].transpose(0, 2, 1, 3)
    return dq, dk, dv, jnp.zeros_like(kvmask), jnp.zeros_like(seed)


_flash.defvjp(_flash_fwd, _flash_bwd)


def _prep_call(q, k, mask, scale, dropout_rate, dropout_rng, interpret):
    """Shared entry preamble for flash_attention / flash_attention_with_lse
    (ONE place for the scale/interpret defaults, the dropout contract, the
    seed derivation, and kv-mask normalization — the two public entry
    points must not drift)."""
    b, sq, h, d = q.shape
    skv = k.shape[1]
    if scale is None:
        scale = d ** -0.5
    if interpret is None:
        interpret = _interpret_default()

    from tpudl.ops.attention import normalize_kv_mask

    if dropout_rate > 0.0:
        if dropout_rng is None:
            raise ValueError("dropout_rate > 0 requires a dropout_rng")
        if interpret:
            raise NotImplementedError(
                "flash_attention dropout draws from the TPU hardware PRNG, "
                "which interpret mode does not implement — run on TPU or "
                "set dropout_rate=0"
            )
        seed = jax.random.bits(dropout_rng, (2,), jnp.uint32)
    else:
        seed = jnp.zeros((2,), jnp.uint32)

    kvmask = normalize_kv_mask(
        mask, b, skv, dtype=jnp.float32, impl="flash_attention"
    )
    return kvmask, seed, scale, interpret, mask is not None


@functools.partial(jax.custom_vjp, nondiff_argnums=(5, 6, 7, 8, 9, 10, 11))
def _flash_lse(q, k, v, kvmask, seed, causal, scale, block_q, block_k,
               interpret, has_mask, rate):
    o, lse, _ = _fwd(q, k, v, kvmask, seed, causal, scale, block_q, block_k,
                     interpret, has_mask, rate)
    return (
        o[:, :, : q.shape[1], :].transpose(0, 2, 1, 3),
        lse[:, :, 0, : q.shape[1]],
    )


def _flash_lse_fwd(q, k, v, kvmask, seed, causal, scale, block_q, block_k,
                   interpret, has_mask, rate):
    o, lse, (qt, kt, vt, kvm) = _fwd(
        q, k, v, kvmask, seed, causal, scale, block_q, block_k, interpret,
        has_mask, rate,
    )
    out = (
        o[:, :, : q.shape[1], :].transpose(0, 2, 1, 3),
        lse[:, :, 0, : q.shape[1]],
    )
    return out, (qt, kt, vt, kvm, kvmask, seed, o, lse)


def _flash_lse_bwd(causal, scale, block_q, block_k, interpret, has_mask,
                   rate, res, g):
    do, dlse = g
    return _bwd_core(causal, scale, block_q, block_k, interpret, has_mask,
                     rate, res, do, dlse=dlse)


_flash_lse.defvjp(_flash_lse_fwd, _flash_lse_bwd)


def flash_attention_with_lse(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    mask: Optional[jax.Array] = None,
    causal: bool = False,
    scale: Optional[float] = None,
    dropout_rate: float = 0.0,
    dropout_rng: Optional[jax.Array] = None,
    block_q: Optional[int] = None,
    block_k: Optional[int] = None,
    interpret: Optional[bool] = None,
):
    """flash_attention that ALSO returns the per-query logsumexp
    ([B, H, Sq] f32) — the statistic a distributed-softmax caller needs
    to merge partial attention results across kv blocks, e.g. the
    flash-bodied ring attention (tpudl.ops.ring_attention): combined
    output = sum_t o_t * exp(lse_t - logsumexp_t lse_t). Differentiable
    in BOTH outputs (the lse cotangent folds into the backward's delta
    operand — see _bwd_core). Fully-masked query rows report
    lse = MASK_VALUE (an exact zero weight in any merge).

    Under dropout the returned lse is of the UNDROPPED distribution
    (dropout acts after normalization — the kernel's factorization), so
    merge weights are dropout-independent: exactly the distributed
    semantics tpudl.ops.ring_attention's exact-dropout contract needs.
    """
    kvmask, seed, scale, interpret, has_mask = _prep_call(
        q, k, mask, scale, dropout_rate, dropout_rng, interpret
    )
    return _flash_lse(
        q, k, v, kvmask, seed, causal, scale, block_q, block_k, interpret,
        has_mask, float(dropout_rate),
    )


def flash_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    mask: Optional[jax.Array] = None,
    causal: bool = False,
    scale: Optional[float] = None,
    dropout_rate: float = 0.0,
    dropout_rng: Optional[jax.Array] = None,
    block_q: Optional[int] = None,
    block_k: Optional[int] = None,
    interpret: Optional[bool] = None,
) -> jax.Array:
    """Flash attention on [B, S, H, D] inputs (same contract as
    tpudl.ops.attention.dot_product_attention).

    ``mask`` may be a [B, Skv] kv-validity mask or a broadcastable
    [B, 1, 1, Skv] padding mask (tpudl.ops.attention.padding_mask output);
    dense [B, H, Sq, Skv] masks are rejected — use the reference
    implementation for those.

    ``dropout_rate`` > 0 (with a ``dropout_rng``) applies in-kernel
    attention-probability dropout from the TPU hardware PRNG (see module
    docstring) — the long-context dropout path the einsum implementation
    cannot afford (its mask alone is O(S^2) HBM). TPU-only: raises under
    interpret mode, which has no hardware PRNG.
    """
    kvmask, seed, scale, interpret, has_mask = _prep_call(
        q, k, mask, scale, dropout_rate, dropout_rng, interpret
    )
    return _flash(
        q, k, v, kvmask, seed, causal, scale, block_q, block_k, interpret,
        has_mask, float(dropout_rate),
    )
