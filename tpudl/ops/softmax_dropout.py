"""Pallas TPU fused masked-softmax + attention dropout over logits.

The seq-128 lesson (benchmarks/bert_attn_seq128.py, 2026-07-30): XLA's
batched [B, H, S, S] attention matmuls are effectively unbeatable at
short sequence — a whole-attention Pallas kernel spends its time filling
and draining the MXU on 128x64x128 dots (tpudl.ops.fused_attention is at
einsum parity standalone but loses in-step). What XLA is NOT good at is
attention-probability dropout: jax.random.bernoulli materializes the
[B, H, S, S] keep mask through HBM, measured at 20 ms/step on the
headline BERT fine-tune (45.7% -> 50.5% MFU with dropout off).

So this kernel splits the work where each side is strongest: XLA keeps
the batched QK^T and PV matmuls; one bandwidth-bound Pallas pass turns
logits into dropped probabilities — row softmax, kv-validity/causal
masking, and dropout drawn from the TPU hardware PRNG in-kernel, so no
mask ever touches HBM. The backward pass is one more pass: it re-reads
the logits (which XLA already has in HBM — zero extra residual memory),
regenerates the identical dropout bits by reseeding, and emits dlogits.

Traffic per layer at the headline shape: fwd reads logits f32 + writes
probs bf16; bwd reads logits + upstream grad + writes dlogits — ~3 HBM
round trips of the score tensor total, versus the reference path's
softmax + bernoulli + two wheres (~6 round trips plus mask generation).

Seeding matches tpudl.ops.fused_attention: the keep mask is a pure
function of (dropout_rng, grid cell), forward and backward bit-identical
by construction. Requires a real TPU when dropout_rate > 0 (interpret
mode has no PRNG emulation).
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from tpudl.ops.attention import MASK_VALUE
from tpudl.ops.pallas_utils import (
    COMPILER_PARAMS,
    flat_cell_id,
    keep_mask as _keep_mask_impl,
    round_up as _round_up,
    seed_cell,
)


def _seed_cell(seed_ref):
    seed_cell(seed_ref, flat_cell_id(3))


def _masked_softmax(s, kvm_ref, *, causal, q_off, block_q, has_kvmask):
    """Row softmax of one [Gh*bq, Skv] merged logits tile (heads are
    rows too — softmax rows are independent, so head-merging is free and
    buys big enough tiles to amortize grid/DMA overhead) with
    kv-validity and causal masking; returns post-softmax pre-dropout
    probabilities."""
    rows, skv = s.shape
    masked = has_kvmask or causal
    if has_kvmask:
        s = jnp.where((kvm_ref[0, 0, :] > 0.0)[None, :], s, MASK_VALUE)
    if causal:
        row_ids = jax.lax.broadcasted_iota(jnp.int32, (rows, skv), 0)
        q_ids = q_off + jax.lax.rem(row_ids, block_q)
        kv_ids = jax.lax.broadcasted_iota(jnp.int32, (rows, skv), 1)
        s = jnp.where(kv_ids <= q_ids, s, MASK_VALUE)
    m = jnp.max(s, axis=-1, keepdims=True)
    p = jnp.exp(s - m)
    if masked:
        p = jnp.where(s <= MASK_VALUE, 0.0, p)
    l = jnp.sum(p, axis=-1, keepdims=True)
    return p / jnp.where(l > 0.0, l, 1.0)


def _keep_mask(shape, rate):
    return _keep_mask_impl(shape, rate)


def _fwd_kernel(seed_ref, x_ref, kvm_ref, o_ref, *,
                causal, rate, block_q, has_kvmask):
    if rate > 0.0:
        _seed_cell(seed_ref)
    gh, bq, skv = x_ref.shape[1:]
    s = x_ref[0].reshape(gh * bq, skv).astype(jnp.float32)
    p = _masked_softmax(
        s, kvm_ref, causal=causal, q_off=pl.program_id(2) * block_q,
        block_q=block_q, has_kvmask=has_kvmask,
    )
    if rate > 0.0:
        keep = _keep_mask(s.shape, rate)
        p = jnp.where(keep, p * (1.0 / (1.0 - rate)), 0.0)
    o_ref[0] = p.reshape(gh, bq, skv).astype(o_ref.dtype)


def _bwd_kernel(seed_ref, x_ref, kvm_ref, g_ref, dx_ref, *,
                causal, rate, block_q, has_kvmask):
    if rate > 0.0:
        _seed_cell(seed_ref)
    gh, bq, skv = x_ref.shape[1:]
    s = x_ref[0].reshape(gh * bq, skv).astype(jnp.float32)
    p = _masked_softmax(
        s, kvm_ref, causal=causal, q_off=pl.program_id(2) * block_q,
        block_q=block_q, has_kvmask=has_kvmask,
    )
    g = g_ref[0].reshape(gh * bq, skv).astype(jnp.float32)
    if rate > 0.0:
        keep = _keep_mask(s.shape, rate)
        g = jnp.where(keep, g * (1.0 / (1.0 - rate)), 0.0)
    # softmax VJP: dlogits = p * (g - <g, p>_row)
    dx = p * (g - jnp.sum(g * p, axis=-1, keepdims=True))
    dx_ref[0] = dx.reshape(gh, bq, skv).astype(dx_ref.dtype)


def _prep(x, kvmask, block_q):
    b, h, sq, skv = x.shape
    sq_p = _round_up(sq, block_q)
    skv_p = _round_up(skv, 128)
    xp = jnp.pad(x, ((0, 0), (0, 0), (0, sq_p - sq), (0, skv_p - skv)))
    kvm = jnp.pad(kvmask, ((0, 0), (0, skv_p - skv)))[:, None, :]
    return xp, kvm, sq_p, skv_p


def _head_group(h: int, block_q: int, skv_p: int) -> int:
    """Heads per grid cell: target ~2 MB f32 tiles so DMA/grid overhead
    amortizes (the whole point vs per-head cells)."""
    g = h
    while g > 1 and (h % g != 0 or g * block_q * skv_p * 4 > 2**21):
        g -= 1
    return max(g, 1)


def _specs(b, h, sq_p, skv_p, block_q, group):
    tile = pl.BlockSpec(
        (1, group, block_q, skv_p), lambda bi, hi, qi: (bi, hi, qi, 0),
        memory_space=pltpu.VMEM,
    )
    kvm = pl.BlockSpec((1, 1, skv_p), lambda bi, hi, qi: (bi, 0, 0),
                       memory_space=pltpu.VMEM)
    seed = pl.BlockSpec(memory_space=pltpu.SMEM)
    grid = (b, h // group, sq_p // block_q)
    sem = COMPILER_PARAMS(
        dimension_semantics=("parallel", "parallel", "parallel")
    )
    return grid, seed, tile, kvm, sem


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7, 8))
def _sd(x, kvmask, seed, causal, rate, block_q, out_dtype, interpret,
        has_mask):
    out, _ = _sd_fwd(
        x, kvmask, seed, causal, rate, block_q, out_dtype, interpret, has_mask
    )
    return out


def _sd_fwd(x, kvmask, seed, causal, rate, block_q, out_dtype, interpret,
            has_mask):
    b, h, sq, skv = x.shape
    xp, kvm, sq_p, skv_p = _prep(x, kvmask, block_q)
    has_kvmask = bool(has_mask) or skv_p != skv
    group = _head_group(h, block_q, skv_p)
    grid, seed_spec, tile, kvm_spec, sem = _specs(
        b, h, sq_p, skv_p, block_q, group
    )
    o = pl.pallas_call(
        functools.partial(
            _fwd_kernel, causal=causal, rate=rate, block_q=block_q,
            has_kvmask=has_kvmask,
        ),
        grid=grid,
        compiler_params=sem,
        in_specs=[seed_spec, tile, kvm_spec],
        out_specs=tile,
        out_shape=jax.ShapeDtypeStruct((b, h, sq_p, skv_p), out_dtype),
        interpret=interpret,
    )(seed, xp, kvm)
    return o[:, :, :sq, :skv], (x, kvmask, seed)


def _sd_bwd(causal, rate, block_q, out_dtype, interpret, has_mask, res, g):
    x, kvmask, seed = res
    b, h, sq, skv = x.shape
    xp, kvm, sq_p, skv_p = _prep(x, kvmask, block_q)
    gp = jnp.pad(
        g, ((0, 0), (0, 0), (0, sq_p - sq), (0, skv_p - skv))
    )
    has_kvmask = bool(has_mask) or skv_p != skv
    group = _head_group(h, block_q, skv_p)
    grid, seed_spec, tile, kvm_spec, sem = _specs(
        b, h, sq_p, skv_p, block_q, group
    )
    g_tile = pl.BlockSpec(
        (1, group, block_q, skv_p), lambda bi, hi, qi: (bi, hi, qi, 0),
        memory_space=pltpu.VMEM,
    )
    dx = pl.pallas_call(
        functools.partial(
            _bwd_kernel, causal=causal, rate=rate, block_q=block_q,
            has_kvmask=has_kvmask,
        ),
        grid=grid,
        compiler_params=sem,
        in_specs=[seed_spec, tile, kvm_spec, g_tile],
        out_specs=tile,
        out_shape=jax.ShapeDtypeStruct((b, h, sq_p, skv_p), x.dtype),
        interpret=interpret,
    )(seed, xp, kvm, gp)
    return dx[:, :, :sq, :skv], jnp.zeros_like(kvmask), jnp.zeros_like(seed)


_sd.defvjp(_sd_fwd, _sd_bwd)


def softmax_dropout(
    logits: jax.Array,
    mask: Optional[jax.Array] = None,
    causal: bool = False,
    dropout_rate: float = 0.0,
    dropout_rng: Optional[jax.Array] = None,
    out_dtype=jnp.bfloat16,
    block_q: int = 128,
    interpret: Optional[bool] = None,
) -> jax.Array:
    """Masked row-softmax + attention dropout of [B, H, Sq, Skv] logits
    in one Pallas pass, probabilities returned in ``out_dtype``.

    ``mask``: [B, Skv] kv-validity row or [B, 1, 1, Skv] padding mask
    (dense masks rejected). Bottom-right-aligned causal masking assumes
    Sq == Skv when ``causal`` (asserted). ``dropout_rate`` > 0 needs
    ``dropout_rng`` and a real TPU.
    """
    from tpudl.ops.attention import is_tpu_backend, normalize_kv_mask

    b, h, sq, skv = logits.shape
    if causal and sq != skv:
        raise ValueError(
            f"causal softmax_dropout expects Sq == Skv, got {sq} vs {skv}"
        )
    if interpret is None:
        interpret = not is_tpu_backend()
    if dropout_rate > 0.0:
        if dropout_rng is None:
            raise ValueError("dropout_rate > 0 requires dropout_rng")
        if interpret:
            raise NotImplementedError(
                "in-kernel dropout uses the TPU hardware PRNG, which "
                "pallas interpret mode does not emulate — run on TPU or "
                "use implementation='reference'"
            )
        seed = jax.random.bits(dropout_rng, (2,), jnp.uint32)
    else:
        seed = jnp.zeros((2,), jnp.uint32)

    has_mask = mask is not None
    kvmask = normalize_kv_mask(
        mask, b, skv, dtype=jnp.float32, impl="softmax_dropout"
    )
    return _sd(
        logits, kvmask, seed, causal, float(dropout_rate),
        min(block_q, _round_up(sq, 8)), out_dtype, interpret, has_mask,
    )


def hybrid_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    mask: Optional[jax.Array] = None,
    causal: bool = False,
    scale: Optional[float] = None,
    dropout_rate: float = 0.0,
    dropout_rng: Optional[jax.Array] = None,
) -> jax.Array:
    """Short-seq attention on [B, S, H, D]: XLA batched matmuls around the
    fused softmax+dropout kernel — the fastest configuration measured at
    the configs[1] headline shape (each side doing what it's best at)."""
    if scale is None:
        scale = q.shape[-1] ** -0.5
    # Logits materialize in the input dtype (bf16 on the training path) —
    # the same precision the reference einsum path stores them at (its
    # f32 cast happens AFTER the bf16 dot output); the kernel upcasts to
    # f32 in-register for the softmax. Halves score-tensor HBM traffic.
    logits = jnp.einsum("bqhd,bkhd->bhqk", q, k) * jnp.asarray(
        scale, q.dtype
    )
    probs = softmax_dropout(
        logits, mask=mask, causal=causal, dropout_rate=dropout_rate,
        dropout_rng=dropout_rng, out_dtype=v.dtype,
    )
    return jnp.einsum("bhqk,bkhd->bqhd", probs, v)
