"""Fused LayerNorm / RMSNorm (+residual-add) Pallas TPU kernels.

The BERT-base MFU plateau (~0.527 for BENCH_r03-r05, vs 0.73 for
BERT-large on the identical pipeline) is per-op memory traffic: at
hidden 768 the matmuls are too small to hide the epilogue, and every
``LayerNorm(hidden + out)`` is two extra full HBM round-trips over the
activation (write the sum, read it back, write the normed value) plus
f32 statistics passes. These kernels read the activation ONCE, do the
residual add and the f32 statistics in VMEM, and write the normed value
(plus, for the residual form, the summed value the next residual hop
needs) in the same pass.

Backward is one-pass too: the forward saves the per-row statistics
(mean/rstd for LayerNorm, rstd for RMSNorm) so the backward recomputes
x-hat from the raw inputs without re-deriving the statistics, and
accumulates dscale/dbias across row blocks in VMEM scratch instead of
materializing an x-hat tensor.

Dispatch contract (the ``attend`` seam pattern): every public entry
takes ``impl`` —

- ``"reference"`` — the XLA composite (exactly the numerics the models
  used before this tier existed: native-dtype residual add, f32
  statistics and normalization, cast back to the input dtype);
- ``"fused"``     — the Pallas kernel (compiled on TPU, interpret mode
  elsewhere, like tpudl.ops.flash_attention);
- ``"auto"``      — fused on TPU, reference off-TPU (the safe
  production default for model configs' ``fused_ops=True``).

Residual form: ``layer_norm(x, scale, bias, residual=r)`` returns
``(normed, x + r)`` — the summed output is the value the next residual
connection carries (pre-norm decoders) and is produced in the input
dtype; statistics are computed from the f32 sum (bf16-level divergence
from the composite's bf16 add, inside every parity tolerance).
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from tpudl.ops.pallas_utils import COMPILER_PARAMS, round_up


def resolve_impl(impl: str, interpret: Optional[bool]):
    """The epilogue-kernel dispatch rule shared by norms / mlp_fused /
    cross_entropy: ``impl`` -> (use_fused, interpret)."""
    from tpudl.ops.attention import is_tpu_backend

    if impl == "auto":
        impl = "fused" if is_tpu_backend() else "reference"
    if impl not in ("fused", "reference"):
        raise ValueError(
            f"impl must be 'auto', 'fused' or 'reference', got {impl!r}"
        )
    if interpret is None:
        interpret = not is_tpu_backend()
    return impl == "fused", interpret


def fused_ops_impl(flag) -> str:
    """Model-config ``fused_ops`` flag -> ops ``impl`` name.

    False -> "reference" (default; nothing changes), True -> "auto"
    (fused on TPU, composite off-TPU — what bench flips on), "force" ->
    "fused" everywhere (interpret mode off-TPU — the CPU test mode that
    actually exercises the kernels)."""
    if not flag:
        return "reference"
    if flag == "force":
        return "fused"
    return "auto"


#: Override for the row-block heuristic below (None = heuristic).
#: ``benchmarks/fused_epilogue.py --sweep-blocks`` grid-searches this;
#: ``TPUDL_NORM_BLOCK_ROWS`` pins a tuned winner for production runs.
#: Shared by the MLP epilogues too (they grid through ``_grid_setup``).
BLOCK_ROWS_OVERRIDE: Optional[int] = None


def _block_rows(n: int, h_pad: int, itemsize: int) -> int:
    """Row-block height: sublane-aligned (16 covers bf16's min tile),
    capped so one (rows, h_pad) block stays ~1 MB."""
    override = BLOCK_ROWS_OVERRIDE
    if override is None:
        from tpudl.analysis.registry import env_int

        override = env_int("TPUDL_NORM_BLOCK_ROWS")
    if override is not None:
        if override < 1:
            raise ValueError(
                f"block-rows override must be >= 1, got {override}"
            )
        return min(round_up(override, 16), round_up(n, 16))
    cap = max(16, ((1 << 20) // max(h_pad * itemsize, 1)) // 16 * 16)
    return min(256, cap, round_up(n, 16))


def _grid_setup(x2, others):
    """Pad [N, H] operands to (N_pad, H_pad) tile multiples; returns the
    padded arrays plus (bn, n_pad, h_pad)."""
    n, h = x2.shape
    h_pad = round_up(h, 128)
    bn = _block_rows(n, h_pad, x2.dtype.itemsize)
    n_pad = round_up(n, bn)
    def pad(a):
        return jnp.pad(a, ((0, n_pad - a.shape[0]), (0, h_pad - a.shape[1])))
    return pad(x2), [pad(o) for o in others], bn, n_pad, h_pad


def _row_param(p, h_pad):
    """[H] param -> [1, H_pad] f32 row (broadcast over the row block)."""
    return jnp.pad(p.astype(jnp.float32), (0, h_pad - p.shape[0]))[None, :]


# ---------------------------------------------------------------------------
# forward kernels
# ---------------------------------------------------------------------------


def _norm_fwd_kernel(*refs, kind, has_res, emit_sum, eps, h):
    """One row-block: residual add (f32), statistics, normalize, write.

    Ref order: x, [res], scale, [bias], y, [s], [mean], rstd — bias/mean
    only for kind='layer', s only when the caller wants the summed value
    back (pre-norm residual carries; post-norm callers skip the write).
    Padded columns hold zeros, so sum(s)/H and sum(s*s)/H are exact
    without a column mask."""
    it = iter(refs)
    x_ref = next(it)
    r_ref = next(it) if has_res else None
    scale_ref = next(it)
    bias_ref = next(it) if kind == "layer" else None
    y_ref = next(it)
    s_ref = next(it) if (has_res and emit_sum) else None
    mean_ref = next(it) if kind == "layer" else None
    rstd_ref = next(it)

    s = x_ref[:, :].astype(jnp.float32)
    if has_res:
        s = s + r_ref[:, :].astype(jnp.float32)
        if emit_sum:
            s_ref[:, :] = s.astype(s_ref.dtype)
    if kind == "layer":
        mean = jnp.sum(s, axis=-1, keepdims=True) / h
        var = jnp.maximum(
            jnp.sum(s * s, axis=-1, keepdims=True) / h - mean * mean, 0.0
        )
        rstd = jax.lax.rsqrt(var + eps)
        xhat = (s - mean) * rstd
        y = xhat * scale_ref[:, :] + bias_ref[:, :]
        mean_ref[:, :] = jnp.broadcast_to(mean, mean_ref.shape)
    else:
        rstd = jax.lax.rsqrt(
            jnp.sum(s * s, axis=-1, keepdims=True) / h + eps
        )
        y = (s * rstd) * scale_ref[:, :]
    y_ref[:, :] = y.astype(y_ref.dtype)
    rstd_ref[:, :] = jnp.broadcast_to(rstd, rstd_ref.shape)


def _norm_fwd(x2, scale, bias, res2, *, kind, eps, interpret,
              emit_sum=True):
    n, h = x2.shape
    has_res = res2 is not None
    emit_sum = has_res and emit_sum
    xp, extras, bn, n_pad, h_pad = _grid_setup(
        x2, [res2] if has_res else []
    )
    grid = (n_pad // bn,)
    row = pl.BlockSpec((bn, h_pad), lambda i: (i, 0),
                       memory_space=pltpu.VMEM)
    par = pl.BlockSpec((1, h_pad), lambda i: (0, 0),
                       memory_space=pltpu.VMEM)
    stat = pl.BlockSpec((bn, 128), lambda i: (i, 0),
                        memory_space=pltpu.VMEM)

    in_specs = [row] + ([row] if has_res else []) + [par]
    args = [xp] + extras + [_row_param(scale, h_pad)]
    if kind == "layer":
        in_specs.append(par)
        args.append(_row_param(bias, h_pad))
    out_specs = [row] + ([row] if emit_sum else [])
    out_shape = [jax.ShapeDtypeStruct((n_pad, h_pad), x2.dtype)] * (
        1 + int(emit_sum)
    )
    if kind == "layer":
        out_specs.append(stat)
        out_shape.append(jax.ShapeDtypeStruct((n_pad, 128), jnp.float32))
    out_specs.append(stat)
    out_shape.append(jax.ShapeDtypeStruct((n_pad, 128), jnp.float32))

    outs = pl.pallas_call(
        functools.partial(_norm_fwd_kernel, kind=kind, has_res=has_res,
                          emit_sum=emit_sum, eps=eps, h=float(h)),
        grid=grid,
        compiler_params=COMPILER_PARAMS(
            dimension_semantics=("parallel",)
        ),
        in_specs=in_specs,
        out_specs=out_specs,
        out_shape=out_shape,
        interpret=interpret,
    )(*args)
    it = iter(outs)
    y = next(it)[:n, :h]
    s = next(it)[:n, :h] if emit_sum else None
    mean = next(it)[:n, :1] if kind == "layer" else None
    rstd = next(it)[:n, :1]
    return y, s, mean, rstd


# ---------------------------------------------------------------------------
# backward kernels
# ---------------------------------------------------------------------------


def _norm_bwd_kernel(*refs, kind, has_res, has_gs, h):
    """One-pass backward over row blocks: recompute x-hat from the raw
    inputs + saved statistics, emit dx (= dresidual), and accumulate the
    cross-row dscale/dbias partials in VMEM scratch (grid axis is
    sequential — 'arbitrary')."""
    it = iter(refs)
    x_ref = next(it)
    r_ref = next(it) if has_res else None
    scale_ref = next(it)
    g_ref = next(it)
    gs_ref = next(it) if has_gs else None
    mean_ref = next(it) if kind == "layer" else None
    rstd_ref = next(it)
    dx_ref = next(it)
    dscale_ref = next(it)
    dbias_ref = next(it) if kind == "layer" else None
    dsc_scr = next(it)
    dbi_scr = next(it) if kind == "layer" else None

    i = pl.program_id(0)

    @pl.when(i == 0)
    def _init():
        dsc_scr[:, :] = jnp.zeros_like(dsc_scr)
        if kind == "layer":
            dbi_scr[:, :] = jnp.zeros_like(dbi_scr)

    s = x_ref[:, :].astype(jnp.float32)
    if has_res:
        s = s + r_ref[:, :].astype(jnp.float32)
    g = g_ref[:, :].astype(jnp.float32)
    rstd = rstd_ref[:, :1]
    scale = scale_ref[:, :]
    if kind == "layer":
        xhat = (s - mean_ref[:, :1]) * rstd
    else:
        xhat = s * rstd
    dxhat = g * scale
    m2 = jnp.sum(dxhat * xhat, axis=-1, keepdims=True) / h
    if kind == "layer":
        m1 = jnp.sum(dxhat, axis=-1, keepdims=True) / h
        ds = rstd * (dxhat - m1 - xhat * m2)
    else:
        ds = rstd * (dxhat - xhat * m2)
    if has_gs:
        ds = ds + gs_ref[:, :].astype(jnp.float32)
    dx_ref[:, :] = ds.astype(dx_ref.dtype)

    dsc_scr[0:1, :] += jnp.sum(g * xhat, axis=0, keepdims=True)
    if kind == "layer":
        dbi_scr[0:1, :] += jnp.sum(g, axis=0, keepdims=True)

    @pl.when(i == pl.num_programs(0) - 1)
    def _finalize():
        dscale_ref[:, :] = jnp.broadcast_to(
            dsc_scr[0:1, :], dscale_ref.shape
        )
        if kind == "layer":
            dbias_ref[:, :] = jnp.broadcast_to(
                dbi_scr[0:1, :], dbias_ref.shape
            )


def _norm_bwd(x2, scale, res2, mean, rstd, g2, gs2, *, kind, interpret):
    n, h = x2.shape
    has_res = res2 is not None
    has_gs = gs2 is not None
    extras = ([res2] if has_res else []) + [g2] + ([gs2] if has_gs else [])
    xp, extras, bn, n_pad, h_pad = _grid_setup(x2, extras)
    it = iter(extras)
    rp = next(it) if has_res else None
    gp = next(it)
    gsp = next(it) if has_gs else None
    # Per-row stats ride as (N_pad, 128) broadcasts (the flash-kernel
    # lse layout trick, rotated: rows on sublanes).
    def stat_arr(a):
        return jnp.broadcast_to(
            jnp.pad(a, ((0, n_pad - a.shape[0]), (0, 0))), (n_pad, 128)
        )

    row = pl.BlockSpec((bn, h_pad), lambda i: (i, 0),
                       memory_space=pltpu.VMEM)
    par = pl.BlockSpec((1, h_pad), lambda i: (0, 0),
                       memory_space=pltpu.VMEM)
    stat = pl.BlockSpec((bn, 128), lambda i: (i, 0),
                        memory_space=pltpu.VMEM)
    red = pl.BlockSpec((8, h_pad), lambda i: (0, 0),
                       memory_space=pltpu.VMEM)

    in_specs = [row] + ([row] if has_res else []) + [par, row]
    args = [xp] + ([rp] if has_res else []) + [_row_param(scale, h_pad), gp]
    if has_gs:
        in_specs.append(row)
        args.append(gsp)
    if kind == "layer":
        in_specs.append(stat)
        args.append(stat_arr(mean))
    in_specs.append(stat)
    args.append(stat_arr(rstd))

    out_specs = [row, red]
    out_shape = [
        jax.ShapeDtypeStruct((n_pad, h_pad), x2.dtype),
        jax.ShapeDtypeStruct((8, h_pad), jnp.float32),
    ]
    scratch = [pltpu.VMEM((8, h_pad), jnp.float32)]
    if kind == "layer":
        out_specs.append(red)
        out_shape.append(jax.ShapeDtypeStruct((8, h_pad), jnp.float32))
        scratch.append(pltpu.VMEM((8, h_pad), jnp.float32))

    outs = pl.pallas_call(
        functools.partial(_norm_bwd_kernel, kind=kind, has_res=has_res,
                          has_gs=has_gs, h=float(h)),
        grid=(n_pad // bn,),
        compiler_params=COMPILER_PARAMS(
            dimension_semantics=("arbitrary",)
        ),
        in_specs=in_specs,
        out_specs=out_specs,
        out_shape=out_shape,
        scratch_shapes=scratch,
        interpret=interpret,
    )(*args)
    dx = outs[0][:n, :h]
    dscale = outs[1][0, :h].astype(scale.dtype)
    dbias = outs[2][0, :h].astype(scale.dtype) if kind == "layer" else None
    return dx, dscale, dbias


# ---------------------------------------------------------------------------
# custom_vjp wrappers (x flattened to [N, H])
# ---------------------------------------------------------------------------


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def _ln(x2, scale, bias, eps, interpret):
    y, _, _, _ = _norm_fwd(x2, scale, bias, None, kind="layer", eps=eps,
                           interpret=interpret)
    return y


def _ln_fwd(x2, scale, bias, eps, interpret):
    y, _, mean, rstd = _norm_fwd(x2, scale, bias, None, kind="layer",
                                 eps=eps, interpret=interpret)
    return y, (x2, scale, mean, rstd)


def _ln_bwd(eps, interpret, res, g):
    x2, scale, mean, rstd = res
    dx, dscale, dbias = _norm_bwd(x2, scale, None, mean, rstd, g, None,
                                  kind="layer", interpret=interpret)
    return dx, dscale, dbias


_ln.defvjp(_ln_fwd, _ln_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6))
def _ln_res(x2, scale, bias, r2, eps, interpret, emit_sum):
    y, s, _, _ = _norm_fwd(x2, scale, bias, r2, kind="layer", eps=eps,
                           interpret=interpret, emit_sum=emit_sum)
    return (y, s) if emit_sum else y


def _ln_res_fwd(x2, scale, bias, r2, eps, interpret, emit_sum):
    y, s, mean, rstd = _norm_fwd(x2, scale, bias, r2, kind="layer",
                                 eps=eps, interpret=interpret,
                                 emit_sum=emit_sum)
    out = (y, s) if emit_sum else y
    return out, (x2, scale, r2, mean, rstd)


def _ln_res_bwd(eps, interpret, emit_sum, res, g):
    x2, scale, r2, mean, rstd = res
    gy, gs = g if emit_sum else (g, None)
    dx, dscale, dbias = _norm_bwd(x2, scale, r2, mean, rstd, gy, gs,
                                  kind="layer", interpret=interpret)
    return dx, dscale, dbias, dx


_ln_res.defvjp(_ln_res_fwd, _ln_res_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(2, 3))
def _rms(x2, scale, eps, interpret):
    y, _, _, _ = _norm_fwd(x2, scale, None, None, kind="rms", eps=eps,
                           interpret=interpret)
    return y


def _rms_fwd(x2, scale, eps, interpret):
    y, _, _, rstd = _norm_fwd(x2, scale, None, None, kind="rms", eps=eps,
                              interpret=interpret)
    return y, (x2, scale, rstd)


def _rms_bwd(eps, interpret, res, g):
    x2, scale, rstd = res
    dx, dscale, _ = _norm_bwd(x2, scale, None, None, rstd, g, None,
                              kind="rms", interpret=interpret)
    return dx, dscale


_rms.defvjp(_rms_fwd, _rms_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def _rms_res(x2, scale, r2, eps, interpret, emit_sum):
    y, s, _, _ = _norm_fwd(x2, scale, None, r2, kind="rms", eps=eps,
                           interpret=interpret, emit_sum=emit_sum)
    return (y, s) if emit_sum else y


def _rms_res_fwd(x2, scale, r2, eps, interpret, emit_sum):
    y, s, _, rstd = _norm_fwd(x2, scale, None, r2, kind="rms", eps=eps,
                              interpret=interpret, emit_sum=emit_sum)
    out = (y, s) if emit_sum else y
    return out, (x2, scale, r2, rstd)


def _rms_res_bwd(eps, interpret, emit_sum, res, g):
    x2, scale, r2, rstd = res
    gy, gs = g if emit_sum else (g, None)
    dx, dscale, _ = _norm_bwd(x2, scale, r2, None, rstd, gy, gs,
                              kind="rms", interpret=interpret)
    return dx, dscale, dx


_rms_res.defvjp(_rms_res_fwd, _rms_res_bwd)


# ---------------------------------------------------------------------------
# reference composites (exactly the pre-existing model numerics)
# ---------------------------------------------------------------------------


def layer_norm_ref(x, scale, bias, residual=None, *, eps=1e-12):
    """XLA composite LayerNorm(+residual): native-dtype residual add
    (what ``hidden + out`` in the models always did), f32 statistics and
    scale/bias (flax ``nn.LayerNorm(dtype=jnp.float32)`` semantics),
    output cast back to the input dtype."""
    s = x if residual is None else x + residual
    x32 = s.astype(jnp.float32)
    mean = jnp.mean(x32, axis=-1, keepdims=True)
    var = jnp.maximum(
        jnp.mean(x32 * x32, axis=-1, keepdims=True) - mean * mean, 0.0
    )
    # Association matches flax nn.LayerNorm bitwise: scale folds into
    # the rsqrt factor BEFORE the (x - mean) multiply.
    mul = jax.lax.rsqrt(var + eps) * scale.astype(jnp.float32)
    y = ((x32 - mean) * mul + bias.astype(jnp.float32)).astype(x.dtype)
    return y if residual is None else (y, s)


def rms_norm_ref(x, scale, residual=None, *, eps=1e-5):
    """XLA composite RMSNorm(+residual) — the tpudl.models.llama.RMSNorm
    math verbatim: f32 mean-square, ``(norm * scale)`` in f32, cast back."""
    s = x if residual is None else x + residual
    x32 = s.astype(jnp.float32)
    norm = x32 * jax.lax.rsqrt(
        jnp.mean(x32 * x32, axis=-1, keepdims=True) + eps
    )
    y = (norm * scale).astype(x.dtype)
    return y if residual is None else (y, s)


# ---------------------------------------------------------------------------
# public entries
# ---------------------------------------------------------------------------


def _flatten(a):
    return a.reshape(-1, a.shape[-1])


def layer_norm(
    x: jax.Array,
    scale: jax.Array,
    bias: jax.Array,
    residual: Optional[jax.Array] = None,
    *,
    eps: float = 1e-12,
    return_sum: bool = True,
    impl: str = "auto",
    interpret: Optional[bool] = None,
):
    """Fused LayerNorm(+residual-add) over the last axis of ``x``.

    Returns the normed array (input dtype), or ``(normed, x + residual)``
    when ``residual`` is given — one activation read, both writes, f32
    statistics saved for the one-pass backward. ``return_sum=False``
    skips the summed output entirely (post-norm architectures consume
    only the normed value — one fewer full HBM write). ``impl``: see
    module docstring."""
    fused, interpret = resolve_impl(impl, interpret)
    if not fused:
        out = layer_norm_ref(x, scale, bias, residual, eps=eps)
        if residual is not None and not return_sum:
            return out[0]
        return out
    shape = x.shape
    if residual is None:
        y = _ln(_flatten(x), scale, bias, float(eps), interpret)
        return y.reshape(shape)
    out = _ln_res(_flatten(x), scale, bias, _flatten(residual),
                  float(eps), interpret, return_sum)
    if not return_sum:
        return out.reshape(shape)
    y, s = out
    return y.reshape(shape), s.reshape(shape)


def rms_norm(
    x: jax.Array,
    scale: jax.Array,
    residual: Optional[jax.Array] = None,
    *,
    eps: float = 1e-5,
    return_sum: bool = True,
    impl: str = "auto",
    interpret: Optional[bool] = None,
):
    """Fused RMSNorm(+residual-add) over the last axis of ``x`` — the
    decode-path norm (runs every serve decode step). Same contract as
    :func:`layer_norm` minus the bias/mean."""
    fused, interpret = resolve_impl(impl, interpret)
    if not fused:
        out = rms_norm_ref(x, scale, residual, eps=eps)
        if residual is not None and not return_sum:
            return out[0]
        return out
    shape = x.shape
    if residual is None:
        y = _rms(_flatten(x), scale, float(eps), interpret)
        return y.reshape(shape)
    out = _rms_res(_flatten(x), scale, _flatten(residual), float(eps),
                   interpret, return_sum)
    if not return_sum:
        return out.reshape(shape)
    y, s = out
    return y.reshape(shape), s.reshape(shape)
