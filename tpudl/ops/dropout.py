"""Low-width-bits dropout: the TPU-native mask generation path.

jax.random.bernoulli generates 32 random bits per element and converts
them to floats before comparing — for attention-probability dropout on
the configs[1] headline step that random-bit traffic alone is ~6 ms/step
(rng-bit-generator in the profile), and the whole bernoulli dropout chain
costs 21 ms/step (benchmarks/bert_attn_seq128.py: 45.5% -> 50.4% MFU
with attention dropout off).

A keep/drop decision needs nowhere near 32 bits of entropy: this module
draws uint8 bits from the same (hardware-RBG-backed) generator and
compares against ``round(rate * 256)`` — a quarter of the random-bit
traffic and an integer compare instead of a float convert+compare.
Measured: 195.3 -> 180.8 ms/step on the headline BERT fine-tune when
attention dropout uses this path.

The cost: the effective drop rate quantizes to multiples of 1/256
(rate 0.1 becomes 26/256 ~ 0.1016). Dropout rates are loose
hyperparameters — a 0.16-point shift is far inside run-to-run noise —
but it is a real semantic deviation, so it lives here under its own
name instead of silently replacing bernoulli everywhere; `exact=True`
restores bit-exact bernoulli semantics.
"""

from __future__ import annotations

import flax.linen as nn
import jax
import jax.numpy as jnp


def quantized_rate(rate: float, exact: bool = False) -> float:
    """The EFFECTIVE drop rate of dropout_keep_mask: on the uint8 path
    the requested rate rounds to threshold/256. Inverted-dropout rescale
    must use this value, not the nominal rate, or E[output] drifts from
    the input by the quantization gap (~0.17% at rate 0.1)."""
    if exact or rate <= 0.0:
        return rate
    if rate >= 1.0:
        return 1.0
    return min(int(round(rate * 256.0)), 255) / 256.0


def dropout_keep_mask(
    rng: jax.Array, shape, rate: float, exact: bool = False
) -> jax.Array:
    """Boolean keep-mask: True with probability 1 - quantized_rate(rate).

    ``exact=False`` (default) uses uint8 random bits — rate quantized to
    round(rate * 256) / 256; ``exact=True`` uses jax.random.bernoulli
    (f32-uniform compare, 4x the bit traffic).
    """
    if exact:
        return jax.random.bernoulli(rng, 1.0 - rate, shape)
    if rate >= 1.0:
        return jnp.zeros(shape, bool)  # flax.nn.Dropout(1.0) semantics
    threshold = int(round(rate * 256.0))
    if threshold <= 0:
        return jnp.ones(shape, bool)
    bits = jax.random.bits(rng, shape, jnp.uint8)
    return bits >= jnp.uint8(min(threshold, 255))


def dropout(
    rng: jax.Array,
    x: jax.Array,
    rate: float,
    exact: bool = False,
) -> jax.Array:
    """Inverted dropout of ``x`` (scale-at-train by the EFFECTIVE keep
    probability, so E[output] == input on the quantized path too)."""
    if rate <= 0.0:
        return x
    keep = dropout_keep_mask(rng, x.shape, rate, exact=exact)
    eff = quantized_rate(rate, exact)
    return jnp.where(keep, x / (1.0 - eff), 0.0).astype(x.dtype)


class Dropout(nn.Module):
    """Drop-in for flax.linen.Dropout on the low-width-bits path (same
    "dropout" rng collection and `deterministic` contract)."""

    rate: float
    exact: bool = False

    @nn.compact
    def __call__(self, x, deterministic: bool = True):
        if deterministic or self.rate <= 0.0:
            return x
        return dropout(self.make_rng("dropout"), x, self.rate, self.exact)


# ---------------------------------------------------------------------------
# Sharded-attention dropout key plumbing (shared by the ring and ulysses
# sequence-parallel paths — ONE fold convention, or the two would
# silently diverge).
# ---------------------------------------------------------------------------


def shard_fold_axes(mesh, axis_name: str, heads_sharded: bool, batch_axes):
    """(name, size) pairs of the mesh axes whose slots hold DISTINCT
    data and therefore need distinct dropout masks: the sharded batch
    axes, the sequence-parallel axis itself, and tp only when heads are
    genuinely tp-sharded — folding an axis the output is REPLICATED over
    would make 'replicated' shards disagree."""
    from tpudl.runtime.mesh import AXIS_TENSOR

    axes = tuple(
        (a, mesh.shape[a]) for a in batch_axes if mesh.shape[a] > 1
    )
    axes += ((axis_name, mesh.shape[axis_name]),)
    if heads_sharded:
        axes += ((AXIS_TENSOR, mesh.shape[AXIS_TENSOR]),)
    return axes


def device_fold_rng(key_data, key_impl, fold_axes):
    """Inside a shard_map body: re-wrap the replicated raw key data and
    fold in this device's mixed-radix position over ``fold_axes``."""
    import jax

    rng = jax.random.wrap_key_data(key_data, impl=key_impl)
    idx = 0
    for name, size in fold_axes:
        idx = idx * size + jax.lax.axis_index(name)
    return jax.random.fold_in(rng, idx)
