"""fp8 matmul with DELAYED SCALING for training: e4m3 forward, e5m2
gradient.

The training-side mirror of tpudl.quant (which quantizes *frozen*
serving weights): here both matmul operands are cast to fp8 fresh each
step, so the scale must track a *moving* tensor distribution without
forcing a host sync or a recompile. Delayed scaling is the standard
answer (Micikevicius et al., FP8 Formats for Deep Learning): each
tensor site keeps a ring of the last ``window`` step amaxes, the
quantization scale derives from the ring's max, and the CURRENT step's
amax is recorded for the NEXT step's scale — scale computation is pure
traced arithmetic over state carried in the TrainState, so scale
updates never touch python and never recompile
(tests/test_precision.py audits a multi-step run with
``assert_no_recompiles``).

Per-tensor scaling, three tensors per dot site:

- ``x`` (activation) and ``w`` (weight): e4m3 — more mantissa, enough
  range once scaled; forward product accumulates in f32 and the
  dequant (one ``sx*sw`` multiply) fuses onto the output.
- ``g`` (incoming gradient): e5m2 — gradients need the range; the
  backward dots dequantize the same way.

Saturation contract: values are clipped to the format's finite max
BEFORE the cast (a bare ``astype`` to e4m3 maps overflow to NaN), so a
step whose amax outgrew the window's scale produces a saturated-but-
finite product, the true amax enters the history, and the next step's
scale covers it. Nonfinite amaxes (an inf that slipped through from a
diverging loss) never enter the ring — ``update_amax_history`` keeps
the previous window max instead, and the loss-scale machinery
(tpudl.train.precision) skips the step.

The gradient amax rides OUT of the backward pass as the cotangent of a
dummy scalar input (``g_probe``): the forward ignores it, the custom
VJP writes ``max|g|`` as its "gradient", and the train step reads it
from the same ``jax.grad`` call that produces the weight gradients —
no side channel, no extra dispatch.

``impl=`` seam (the tpudl.ops convention): ``"fused"`` feeds the
native ``jnp.float8_*`` values straight into ``lax.dot_general``
(storage dtype on the MXU — the bytes win), ``"reference"``
dequantizes to f32 first and runs the plain dot (bit-comparable
composite, the parity baseline), ``"auto"`` picks fused on TPU.
"""

from __future__ import annotations

import functools
from typing import Any, Callable, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp
from jax import lax

#: Largest finite magnitudes of the two training formats.
E4M3_MAX = 448.0
E5M2_MAX = 57344.0

#: Default amax-history ring length (TPUDL_FP8_AMAX_WINDOW overrides).
DEFAULT_AMAX_WINDOW = 16


def default_amax_window() -> int:
    from tpudl.analysis.registry import env_int

    return env_int("TPUDL_FP8_AMAX_WINDOW", DEFAULT_AMAX_WINDOW, min_value=1)


def resolve_fp8_impl(impl: str) -> bool:
    """``impl`` -> use_native (True = f8 values feed lax.dot_general).
    Mirrors tpudl.ops.norms.resolve_impl: auto = fused on TPU,
    reference off-TPU (the XLA CPU path runs either — tests pin both)."""
    from tpudl.ops.attention import is_tpu_backend

    if impl == "auto":
        impl = "fused" if is_tpu_backend() else "reference"
    if impl not in ("fused", "reference"):
        raise ValueError(
            f"impl must be 'auto', 'fused' or 'reference', got {impl!r}"
        )
    return impl == "fused"


def amax_history_init(window: int) -> jax.Array:
    """Fresh ring: all zeros => scale 1.0 until the first real amax
    lands (see ``history_scale``)."""
    return jnp.zeros((int(window),), jnp.float32)


def update_amax_history(hist: jax.Array, amax: jax.Array) -> jax.Array:
    """Ring insert: newest amax at slot 0, oldest falls off. A
    nonfinite amax (diverged step) is replaced by the window's current
    max so one bad step can't poison ``window`` future scales."""
    amax = jnp.asarray(amax, jnp.float32)
    amax = jnp.where(jnp.isfinite(amax), amax, jnp.max(hist))
    return jnp.concatenate([amax[None], hist[:-1]])


def history_scale(hist: jax.Array, dtype_max: float) -> jax.Array:
    """Quantization scale from the ring: ``max(hist) / dtype_max`` maps
    the window's largest observed magnitude onto the format's top; an
    empty (all-zero) history scales by 1.0 — the first step quantizes
    raw values, records the true amax, and the window takes over."""
    amax = jnp.max(hist)
    return jnp.where(amax > 0.0, amax / dtype_max, 1.0)


def _cast_fp8(x: jax.Array, scale: jax.Array, dtype, dtype_max: float):
    """Scale-then-cast with the saturation contract: clip to the finite
    max first (astype alone maps overflow to NaN on e4m3)."""
    scaled = jnp.asarray(x, jnp.float32) / scale
    return jnp.clip(scaled, -dtype_max, dtype_max).astype(dtype)


def _dot2d(a: jax.Array, b: jax.Array, native: bool) -> jax.Array:
    """[M, K] @ [K, N] -> f32 [M, N]. ``native``: f8 operands feed the
    dot directly (f32 accumulation via preferred_element_type);
    reference dequantizes to f32 first — same math, composite operands."""
    if not native:
        a = a.astype(jnp.float32)
        b = b.astype(jnp.float32)
    return lax.dot_general(
        a, b, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )


@functools.lru_cache(maxsize=2)
def _make_fp8_dot(native: bool) -> Callable:
    """Build the custom-VJP fp8 dot for one impl. Signature:

        fp8_dot(x [..., K], w [K, N], x_hist, w_hist, g_hist, g_probe)
            -> out [..., N]

    Histories are data (traced), not parameters: their cotangents are
    zero. ``g_probe`` (scalar 0.0) exists solely to carry ``max|g|``
    out as its cotangent. The public ``fp8_dot`` wrapper below adds the
    forward amaxes (plain stop-gradient reductions outside the VJP)."""

    def _primal(x, w, x_hist, w_hist, g_hist, g_probe):
        sx = history_scale(x_hist, E4M3_MAX)
        sw = history_scale(w_hist, E4M3_MAX)
        x2 = x.reshape(-1, x.shape[-1])
        qx = _cast_fp8(x2, sx, jnp.float8_e4m3fn, E4M3_MAX)
        qw = _cast_fp8(w, sw, jnp.float8_e4m3fn, E4M3_MAX)
        out = _dot2d(qx, qw, native) * (sx * sw)
        out = out.reshape(*x.shape[:-1], w.shape[-1]).astype(x.dtype)
        # Residuals must be arrays: dtypes ride as zero-size carriers,
        # and x's shape is recoverable from the cotangent's in _vjp_bwd.
        res = (
            qx, qw, sx, sw, g_hist,
            jnp.zeros((0,), x.dtype), jnp.zeros((0,), w.dtype),
        )
        return out, res

    @jax.custom_vjp
    def fp8_dot(x, w, x_hist, w_hist, g_hist, g_probe):
        return _primal(x, w, x_hist, w_hist, g_hist, g_probe)[0]

    def _vjp_fwd(x, w, x_hist, w_hist, g_hist, g_probe):
        return _primal(x, w, x_hist, w_hist, g_hist, g_probe)

    def _vjp_bwd(res, g):
        qx, qw, sx, sw, g_hist, x_proto, w_proto = res
        x_shape = (*g.shape[:-1], qw.shape[0])
        g2 = jnp.asarray(g, jnp.float32).reshape(-1, g.shape[-1])
        sg = history_scale(g_hist, E5M2_MAX)
        g_amax = jnp.max(jnp.abs(g2)).astype(jnp.float32)
        qg = _cast_fp8(g2, sg, jnp.float8_e5m2, E5M2_MAX)
        # dx = g @ w^T at (sg * sw); dw = x^T @ g at (sx * sg).
        dx = _dot2d(qg, qw.T, native) * (sg * sw)
        dw = _dot2d(qx.T, qg, native) * (sx * sg)
        return (
            dx.reshape(x_shape).astype(x_proto.dtype),
            dw.astype(w_proto.dtype),
            jnp.zeros_like(g_hist),  # x_hist: data, no gradient
            jnp.zeros_like(g_hist),  # w_hist
            jnp.zeros_like(g_hist),  # g_hist
            g_amax,  # g_probe cotangent = the gradient-amax ride-out
        )

    fp8_dot.defvjp(_vjp_fwd, _vjp_bwd)
    return fp8_dot


def fp8_dot(
    x: jax.Array,
    w: jax.Array,
    x_hist: jax.Array,
    w_hist: jax.Array,
    g_hist: jax.Array,
    g_probe: jax.Array,
    impl: str = "auto",
):
    """The site-level entry: quantized ``x @ w`` plus the step's
    forward amaxes. Returns ``(out, x_amax, w_amax)``; the gradient
    amax arrives as ``g_probe``'s cotangent (see module docstring)."""
    native = resolve_fp8_impl(impl)
    out = _make_fp8_dot(native)(x, w, x_hist, w_hist, g_hist, g_probe)
    x_amax = jnp.max(jnp.abs(lax.stop_gradient(x))).astype(jnp.float32)
    w_amax = jnp.max(jnp.abs(lax.stop_gradient(w))).astype(jnp.float32)
    return out, x_amax, w_amax


class Fp8Dense(nn.Module):
    """Dense projection whose matmul runs through ``fp8_dot``.

    Params are nn.Dense-identical (f32 master kernel/bias, same init),
    so checkpoints interchange with the plain module — the tpudl.quant
    QuantDense contract, applied to training. Per-site delayed-scaling
    state lives in the ``"fp8"`` variable collection (three amax rings
    + the gradient probe), created at ``model.init`` and carried in
    ``TrainState.precision["fp8"]`` by the train step, which passes it
    back in as a TRACED input every step — scale updates never
    recompile. The step reads each site's new forward amaxes from the
    ``"intermediates"`` sow (key ``fp8_fwd``) and the gradient amax
    from the fp8 collection's cotangents.

    ``rank > 0`` adds LoRA factors over the fp8 base matmul — the same
    ``lora_a``/``lora_b`` leaves (and zero-init-B contract) as
    tpudl.models.lora.LoRADense, so ``extract_adapters`` /
    ``lora_optimizer`` / ``LORA_RULES`` apply unchanged. The adapter
    delta runs FULL precision on top of the quantized base product
    (the fp8-base + high-precision-adapters fine-tune shape): the
    factors are rank-r slivers, so skipping the fp8 cast costs nothing
    while keeping the trainable path's numerics clean.
    """

    features: int
    dtype: Any = None
    use_bias: bool = True
    kernel_init: Callable = nn.initializers.lecun_normal()
    bias_init: Callable = nn.initializers.zeros_init()
    amax_window: Optional[int] = None
    impl: str = "auto"
    rank: int = 0
    alpha: float = 16.0

    @nn.compact
    def __call__(self, x):
        kernel = self.param(
            "kernel", self.kernel_init, (x.shape[-1], self.features)
        )
        bias = (
            self.param("bias", self.bias_init, (self.features,))
            if self.use_bias
            else None
        )
        window = self.amax_window or default_amax_window()
        x_hist = self.variable(
            "fp8", "x_hist", lambda: amax_history_init(window)
        )
        w_hist = self.variable(
            "fp8", "w_hist", lambda: amax_history_init(window)
        )
        g_hist = self.variable(
            "fp8", "g_hist", lambda: amax_history_init(window)
        )
        g_probe = self.variable(
            "fp8", "g_probe", lambda: jnp.zeros((), jnp.float32)
        )
        x, kernel, bias = nn.dtypes.promote_dtype(
            x, kernel, bias, dtype=self.dtype
        )
        out, x_amax, w_amax = fp8_dot(
            x, kernel,
            x_hist.value, w_hist.value, g_hist.value, g_probe.value,
            impl=self.impl,
        )
        # The step rebuilds next step's rings from these (mutable
        # "intermediates"; a read-only apply — eval, export — drops the
        # sow and the rings simply don't advance).
        self.sow(
            "intermediates", "fp8_fwd",
            {"x_amax": x_amax, "w_amax": w_amax},
        )
        if self.rank > 0:
            lora_a = self.param(
                "lora_a",
                nn.initializers.normal(1.0 / self.rank),
                (x.shape[-1], self.rank),
            )
            lora_b = self.param(
                "lora_b", nn.initializers.zeros, (self.rank, self.features)
            )
            out = out + jnp.dot(
                jnp.dot(x, lora_a.astype(x.dtype)),
                lora_b.astype(x.dtype),
            ) * (self.alpha / self.rank)
        if bias is not None:
            out = out + bias
        return out


def is_fp8_site(entry: Any) -> bool:
    """True for one site's slice of the ``"fp8"`` collection."""
    return isinstance(entry, dict) and "x_hist" in entry and "g_probe" in entry


def updated_fp8_state(
    fp8_vars: Any, intermediates: Any, fp8_grads: Any, ok: jax.Array
) -> Any:
    """Next step's fp8 collection: every site's rings advanced with the
    step's observed amaxes — forward amaxes from the site's
    ``fp8_fwd`` sow, gradient amax from the site's ``g_probe``
    cotangent. ``ok`` (the loss-scale finite flag) gates the whole
    update: a skipped step advances nothing, so a divergence never
    writes garbage into the windows."""

    def walk(site, inter, grads):
        if is_fp8_site(site):
            sown = inter["fp8_fwd"]
            if isinstance(sown, (tuple, list)):
                sown = sown[0]
            new = {
                "x_hist": update_amax_history(
                    site["x_hist"], sown["x_amax"]
                ),
                "w_hist": update_amax_history(
                    site["w_hist"], sown["w_amax"]
                ),
                "g_hist": update_amax_history(
                    site["g_hist"], grads["g_probe"]
                ),
                "g_probe": site["g_probe"],
            }
            return {
                k: jnp.where(ok, new[k], site[k]) for k in site
            }
        return {k: walk(site[k], inter[k], grads[k]) for k in site}

    return walk(dict(fp8_vars), dict(intermediates), dict(fp8_grads))
