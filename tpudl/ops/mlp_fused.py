"""Fused MLP epilogues: bias+GeLU (exact) and SwiGLU Pallas TPU kernels.

The transformer MLP's elementwise epilogues run over the 4x intermediate
width — at BERT-base that is the single largest activation stream in the
block, and the composite path pays it several times: the bias add and
the exact (erf) GeLU read/write [N, 4H] separately, and XLA's autodiff
saves the pre-activation AND recomputes erf pieces in the backward.
These kernels do the epilogue in one VMEM pass each way:

- ``bias_gelu(x, bias)``   — y = gelu_exact(x + b); matches
  ``nn.gelu(dense(x), approximate=False)`` given ``dense``'s pre-bias
  output (the BERT intermediate epilogue);
- ``swiglu(gate, up)``     — y = silu(gate) * up (the Llama MLP gate,
  which also runs per serve decode step).

Backward needs NO forward recompute: both derivatives are closed-form
in the saved inputs (u = x + b resp. gate/up), so the backward is a
single elementwise pass that also folds the cross-row dbias reduction
into VMEM scratch instead of a separate [N, F] -> [F] XLA reduce.

Dispatch: ``impl`` = "auto" | "fused" | "reference" with the same
contract as tpudl.ops.norms (auto = fused on TPU, composite off-TPU;
fused runs interpret mode off-TPU for the hermetic parity tests).
"""

from __future__ import annotations

import functools
import math
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from tpudl.ops.norms import resolve_impl, _grid_setup
from tpudl.ops.pallas_utils import COMPILER_PARAMS

_INV_SQRT2 = 1.0 / math.sqrt(2.0)
_INV_SQRT_2PI = 1.0 / math.sqrt(2.0 * math.pi)


def _gelu_exact(u):
    """Exact (erf) GeLU in f32 — matches jax.nn.gelu(approximate=False)."""
    return u * 0.5 * (1.0 + jax.lax.erf(u * _INV_SQRT2))


def _gelu_grad(u):
    """d/du gelu_exact(u) = Phi(u) + u * phi(u)."""
    phi = jnp.exp(-0.5 * u * u) * _INV_SQRT_2PI
    return 0.5 * (1.0 + jax.lax.erf(u * _INV_SQRT2)) + u * phi


# ---------------------------------------------------------------------------
# bias + GeLU
# ---------------------------------------------------------------------------


def _bg_fwd_kernel(x_ref, b_ref, y_ref):
    u = x_ref[:, :].astype(jnp.float32) + b_ref[:, :]
    y_ref[:, :] = _gelu_exact(u).astype(y_ref.dtype)


def _bg_bwd_kernel(x_ref, b_ref, g_ref, dx_ref, db_ref, db_scr):
    i = pl.program_id(0)

    @pl.when(i == 0)
    def _init():
        db_scr[:, :] = jnp.zeros_like(db_scr)

    u = x_ref[:, :].astype(jnp.float32) + b_ref[:, :]
    du = g_ref[:, :].astype(jnp.float32) * _gelu_grad(u)
    dx_ref[:, :] = du.astype(dx_ref.dtype)
    db_scr[0:1, :] += jnp.sum(du, axis=0, keepdims=True)

    @pl.when(i == pl.num_programs(0) - 1)
    def _finalize():
        db_ref[:, :] = jnp.broadcast_to(db_scr[0:1, :], db_ref.shape)


def _bg_call(x2, bias, g2, interpret):
    """Shared pallas_call builder: forward when g2 is None, else backward."""
    n, f = x2.shape
    xp, extras, bn, n_pad, f_pad = _grid_setup(
        x2, [g2] if g2 is not None else []
    )
    bp = jnp.pad(bias.astype(jnp.float32), (0, f_pad - f))[None, :]
    row = pl.BlockSpec((bn, f_pad), lambda i: (i, 0),
                       memory_space=pltpu.VMEM)
    par = pl.BlockSpec((1, f_pad), lambda i: (0, 0),
                       memory_space=pltpu.VMEM)
    if g2 is None:
        y = pl.pallas_call(
            _bg_fwd_kernel,
            grid=(n_pad // bn,),
            compiler_params=COMPILER_PARAMS(
                dimension_semantics=("parallel",)
            ),
            in_specs=[row, par],
            out_specs=row,
            out_shape=jax.ShapeDtypeStruct((n_pad, f_pad), x2.dtype),
            interpret=interpret,
        )(xp, bp)
        return y[:n, :f]
    red = pl.BlockSpec((8, f_pad), lambda i: (0, 0),
                       memory_space=pltpu.VMEM)
    dx, db = pl.pallas_call(
        _bg_bwd_kernel,
        grid=(n_pad // bn,),
        compiler_params=COMPILER_PARAMS(
            dimension_semantics=("arbitrary",)
        ),
        in_specs=[row, par, row],
        out_specs=[row, red],
        out_shape=[
            jax.ShapeDtypeStruct((n_pad, f_pad), x2.dtype),
            jax.ShapeDtypeStruct((8, f_pad), jnp.float32),
        ],
        scratch_shapes=[pltpu.VMEM((8, f_pad), jnp.float32)],
        interpret=interpret,
    )(xp, bp, extras[0])
    return dx[:n, :f], db[0, :f].astype(bias.dtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=(2,))
def _bg(x2, bias, interpret):
    return _bg_call(x2, bias, None, interpret)


def _bg_fwd(x2, bias, interpret):
    return _bg_call(x2, bias, None, interpret), (x2, bias)


def _bg_bwd(interpret, res, g):
    x2, bias = res
    return _bg_call(x2, bias, g, interpret)


_bg.defvjp(_bg_fwd, _bg_bwd)


def bias_gelu_ref(x: jax.Array, bias: jax.Array) -> jax.Array:
    """XLA composite: exactly what the models did — native-dtype bias
    add (nn.Dense's epilogue) followed by exact-erf GeLU."""
    return jax.nn.gelu(x + bias.astype(x.dtype), approximate=False)


def bias_gelu(
    x: jax.Array,
    bias: jax.Array,
    *,
    impl: str = "auto",
    interpret: Optional[bool] = None,
) -> jax.Array:
    """Fused ``gelu_exact(x + bias)`` over the last axis of ``x``
    ([..., F] with bias [F]) — the BERT intermediate epilogue, one VMEM
    pass forward, one (recompute-free) pass backward with the dbias
    reduction folded in."""
    fused, interpret = resolve_impl(impl, interpret)
    if not fused:
        return bias_gelu_ref(x, bias)
    shape = x.shape
    return _bg(x.reshape(-1, shape[-1]), bias, interpret).reshape(shape)


# ---------------------------------------------------------------------------
# SwiGLU
# ---------------------------------------------------------------------------


def _sw_fwd_kernel(g_ref, u_ref, y_ref):
    g = g_ref[:, :].astype(jnp.float32)
    y = g * jax.nn.sigmoid(g) * u_ref[:, :].astype(jnp.float32)
    y_ref[:, :] = y.astype(y_ref.dtype)


def _sw_bwd_kernel(g_ref, u_ref, go_ref, dg_ref, du_ref):
    g = g_ref[:, :].astype(jnp.float32)
    u = u_ref[:, :].astype(jnp.float32)
    go = go_ref[:, :].astype(jnp.float32)
    sg = jax.nn.sigmoid(g)
    silu = g * sg
    dg_ref[:, :] = (go * u * (sg + silu * (1.0 - sg))).astype(dg_ref.dtype)
    du_ref[:, :] = (go * silu).astype(du_ref.dtype)


def _sw_call(g2, u2, go2, interpret):
    n, f = g2.shape
    gp, extras, bn, n_pad, f_pad = _grid_setup(
        g2, [u2] + ([go2] if go2 is not None else [])
    )
    row = pl.BlockSpec((bn, f_pad), lambda i: (i, 0),
                       memory_space=pltpu.VMEM)
    sem = COMPILER_PARAMS(dimension_semantics=("parallel",))
    if go2 is None:
        y = pl.pallas_call(
            _sw_fwd_kernel,
            grid=(n_pad // bn,),
            compiler_params=sem,
            in_specs=[row, row],
            out_specs=row,
            out_shape=jax.ShapeDtypeStruct((n_pad, f_pad), g2.dtype),
            interpret=interpret,
        )(gp, extras[0])
        return y[:n, :f]
    dg, du = pl.pallas_call(
        _sw_bwd_kernel,
        grid=(n_pad // bn,),
        compiler_params=sem,
        in_specs=[row, row, row],
        out_specs=[row, row],
        out_shape=[
            jax.ShapeDtypeStruct((n_pad, f_pad), g2.dtype),
            jax.ShapeDtypeStruct((n_pad, f_pad), u2.dtype),
        ],
        interpret=interpret,
    )(gp, extras[0], extras[1])
    return dg[:n, :f], du[:n, :f]


@functools.partial(jax.custom_vjp, nondiff_argnums=(2,))
def _sw(g2, u2, interpret):
    return _sw_call(g2, u2, None, interpret)


def _sw_fwd(g2, u2, interpret):
    return _sw_call(g2, u2, None, interpret), (g2, u2)


def _sw_bwd(interpret, res, g):
    g2, u2 = res
    return _sw_call(g2, u2, g, interpret)


_sw.defvjp(_sw_fwd, _sw_bwd)


def swiglu_ref(gate: jax.Array, up: jax.Array) -> jax.Array:
    """XLA composite: ``silu(gate) * up`` — tpudl.models.llama verbatim."""
    return jax.nn.silu(gate) * up


def swiglu(
    gate: jax.Array,
    up: jax.Array,
    *,
    impl: str = "auto",
    interpret: Optional[bool] = None,
) -> jax.Array:
    """Fused ``silu(gate) * up`` (the Llama MLP gate): one elementwise
    VMEM pass each way, closed-form backward from the saved inputs."""
    fused, interpret = resolve_impl(impl, interpret)
    if not fused:
        return swiglu_ref(gate, up)
    shape = gate.shape
    f = shape[-1]
    return _sw(gate.reshape(-1, f), up.reshape(-1, f), interpret).reshape(
        shape
    )
