"""tpudl.fleet: pod-real replica meshes, cross-process migration
transport, and elastic reshard-restore (the chip mover).

Everything earlier PRs shipped treats a "replica" as a driver thread
over one local device view. This package is the placement/transport/
restore layer that makes replicas *meshes* and cohorts *elastic*:

- ``meshrep``   — ``MeshReplica``: a serving replica whose compiled
  programs are pjit-sharded over a tensor-parallel device mesh. The
  Router places over mesh replicas exactly as it placed over thread
  replicas (the mesh sits BELOW the placement contract).
- ``transport`` — the PR 13 migration payload (paged KV + tokens +
  sampling position + absolute deadline, plus the speculative draft
  remainder) shipped over a socket or spool-file channel, so failover
  crosses a process boundary instead of a thread boundary.
- ``reshard``   — elastic restore: a checkpoint written on one mesh
  shape restores onto a *different* shape (coverage-checked rules +
  ``AsyncCheckpointManager.restore_full``'s mesh placement), letting
  the Supervisor restart a shrunk or grown cohort.
- ``chipmover`` — the autoscaler action that MOVES chips between
  training and serving: sustained SLO burn preempts the training
  cohort, reshard-restores it smaller, and hands the freed devices to
  a new serving ``MeshReplica``; training grows back when burn clears.
"""

from tpudl.fleet.chipmover import ChipMover, ChipMoverConfig, ElasticTrainer
from tpudl.fleet.meshrep import MeshReplica, build_mesh_session, serving_mesh
from tpudl.fleet.reshard import (
    ELASTIC_RESNET_RULES,
    elastic_shardings,
    reshard_restore,
)
from tpudl.fleet.transport import (
    FileChannel,
    MigrationEndpoint,
    TransportError,
    deliver_to_session,
    migrate_request,
    recv_frame,
    send_frame,
    send_migration,
)

__all__ = [
    "ChipMover",
    "ChipMoverConfig",
    "ElasticTrainer",
    "MeshReplica",
    "build_mesh_session",
    "serving_mesh",
    "ELASTIC_RESNET_RULES",
    "elastic_shardings",
    "reshard_restore",
    "FileChannel",
    "MigrationEndpoint",
    "TransportError",
    "deliver_to_session",
    "migrate_request",
    "recv_frame",
    "send_frame",
    "send_migration",
]
