"""The chip mover: devices migrate between training and serving.

The PR 10 Autoscaler can only add or drain serving replicas inside a
fixed fleet. On a real pod the fleet IS fixed — the lever that remains
is WHICH WORKLOAD each chip runs. This module is that lever:

- ``ElasticTrainer`` — a training cohort over an explicit device
  grant, driven end-to-end by contracts earlier PRs shipped: the step
  compiles per mesh+rules (``compile_step``), preemption is the PR 4
  SIGTERM protocol (``tpudl.ft.preemption``: signal -> cooperative
  stop -> EMERGENCY checkpoint inside the grace window), and every
  (re)start goes through ``resume_run`` + the PR 19 elastic
  reshard-restore — so the cohort restarts on a SMALLER or LARGER
  device grant with bitwise-identical params/opt state and a
  schedule-identical data position.
- ``ChipMover`` — the autoscaler escalation: under SUSTAINED SLO burn
  (the router's ``load_report()["burning"]``, same signal the
  Autoscaler reads) it preempts the training cohort, restarts it on a
  subset of its devices, and hands the freed chips to a freshly
  spawned serving ``MeshReplica`` (``router.add_replica`` — placement
  picks it up immediately). When burn stays clear, the borrowed
  replica DRAINS (migration-first, zero dropped results) and training
  grows back to its full grant. Hysteresis + cooldown mirror the
  Autoscaler's evaluate() tick shape, so a driver can run both.

Knobs: ``TPUDL_FLEET_BURN_SUSTAIN_S`` / ``TPUDL_FLEET_CLEAR_SUSTAIN_S``
(how long burn must persist/stay clear before chips move),
``TPUDL_FLEET_COOLDOWN_S`` (min gap between moves),
``TPUDL_FLEET_SERVE_SHARE`` (fraction of training devices a move
lends to serving).
"""

from __future__ import annotations

import dataclasses
import os
import signal
import threading
import time
from typing import Any, Callable, List, Optional, Sequence

import jax

from tpudl.analysis.registry import env_float
from tpudl.ft import preemption
from tpudl.fleet.reshard import ELASTIC_RESNET_RULES, cohort_mesh
from tpudl.obs import registry


class ElasticTrainer:
    """An elastically-restartable training cohort (in-process tier).

    One worker thread runs ``fit`` over a mesh built from the current
    device grant. ``preempt()`` delivers the real SIGTERM protocol to
    this process (handlers installed by ``start()``, main thread);
    ``fit`` stops between steps, commits the emergency checkpoint, and
    the watchdog is disarmed once the cooperative path completes.
    ``restart(devices)`` resumes from the newest committed checkpoint
    onto a mesh over the NEW grant — the reshard-restore path — and
    continues toward ``total_steps`` with the data iterator
    fast-forwarded (``resume_run``).

    ``make_state`` / ``make_batches`` are factories (a restart needs a
    fresh template and a fresh iterator to seek); ``step_fn`` is the
    uncompiled train step — it recompiles per mesh shape, which is the
    honest cost of moving chips.
    """

    def __init__(
        self,
        make_state: Callable[[], Any],
        step_fn: Callable,
        make_batches: Callable[[], Any],
        manager,
        devices: Sequence[jax.Device],
        total_steps: int,
        rules=ELASTIC_RESNET_RULES,
        spec=None,
        seed: int = 0,
        checkpoint_every: int = 1,
        install_signal_handlers: bool = True,
    ):
        self.make_state = make_state
        self.step_fn = step_fn
        self.make_batches = make_batches
        self.manager = manager
        self.devices: List[jax.Device] = list(devices)
        self.total_steps = int(total_steps)
        self.rules = rules
        self.spec = spec
        self.seed = seed
        self.checkpoint_every = checkpoint_every
        self._install = install_signal_handlers
        self._installed_here = False
        self._thread: Optional[threading.Thread] = None
        self.state = None
        self.last_metrics = None
        self.last_info: Optional[dict] = None
        self.error: Optional[BaseException] = None
        self.steps_done = 0
        self.finished = False
        self.restarts = 0
        self.mesh_shapes: List[tuple] = []  # one entry per (re)start

    # -- lifecycle ------------------------------------------------------

    def start(self) -> "ElasticTrainer":
        if self.running:
            return self
        if self._install and not self._installed_here:
            # Main-thread requirement is the signal module's, same as
            # preemption.install's own contract.
            preemption.install()
            self._installed_here = True
        self._thread = threading.Thread(
            target=self._run, name="tpudl-elastic-trainer", daemon=True
        )
        self._thread.start()
        return self

    @property
    def running(self) -> bool:
        return self._thread is not None and self._thread.is_alive()

    def join(self, timeout_s: Optional[float] = None) -> None:
        if self._thread is not None:
            self._thread.join(timeout=timeout_s)

    def _run(self) -> None:
        from tpudl.ft.supervisor import resume_run
        from tpudl.train import compile_step, fit

        try:
            mesh = cohort_mesh(self.devices, self.spec)
            self.mesh_shapes.append(
                tuple(mesh.shape[a] for a in mesh.axis_names)
            )
            state, rng, batches, start = resume_run(
                self.manager, self.make_state(), self.make_batches(),
                mesh=mesh, rules=self.rules,
            )
            if rng is None:
                rng = jax.random.key(self.seed)
            remaining = self.total_steps - start
            if remaining <= 0:
                self.state, self.finished = state, True
                return
            compiled = compile_step(self.step_fn, mesh, state, self.rules)
            state, metrics, info = fit(
                compiled, state, batches, rng, num_steps=remaining,
                checkpoint_manager=self.manager,
                checkpoint_every=self.checkpoint_every,
            )
            self.state = state
            self.last_metrics = metrics
            self.last_info = info
            self.steps_done = start + info["steps"]
            self.finished = (
                not info["preempted"]
                and self.steps_done >= self.total_steps
            )
            registry().gauge("fleet_training_steps_done").set(
                self.steps_done
            )
        except BaseException as e:  # surfaced by preempt()/the test
            self.error = e

    def preempt(self, timeout_s: float = 120.0) -> None:
        """The PR 4 SIGTERM protocol, aimed at our own cohort: signal,
        wait for fit's cooperative stop + emergency checkpoint, then
        disarm the watchdog (reset) — the grace window must not
        hard-exit a process whose cooperative path completed."""
        if self.running:
            os.kill(os.getpid(), signal.SIGTERM)
            self._thread.join(timeout=timeout_s)
            if self._thread.is_alive():
                raise TimeoutError(
                    f"training cohort did not stop within {timeout_s}s "
                    f"of SIGTERM (grace window would hard-exit)"
                )
        self._thread = None
        preemption.reset()
        if self.error is not None:
            raise self.error

    def restart(self, devices: Sequence[jax.Device]) -> "ElasticTrainer":
        """Resume the cohort on a NEW device grant (shrunk or grown):
        the newest committed checkpoint reshard-restores onto a mesh
        over ``devices`` and training continues schedule-identically."""
        if self.running:
            raise RuntimeError("preempt() the cohort before restart()")
        self.devices = list(devices)
        self.restarts += 1
        registry().counter("fleet_cohort_restarts").inc()
        return self.start()

    def close(self) -> None:
        """Stop (preempting if needed) and restore signal handlers."""
        try:
            if self.running:
                self.preempt()
        finally:
            if self._installed_here:
                preemption.uninstall()
                self._installed_here = False


@dataclasses.dataclass
class ChipMoverConfig:
    """Hysteresis/cooldown/split policy; None reads the knob."""

    burn_sustain_s: Optional[float] = None
    clear_sustain_s: Optional[float] = None
    cooldown_s: Optional[float] = None
    serve_share: Optional[float] = None
    preempt_timeout_s: float = 120.0

    def __post_init__(self):
        if self.burn_sustain_s is None:
            self.burn_sustain_s = env_float(
                "TPUDL_FLEET_BURN_SUSTAIN_S", 2.0
            )
        if self.clear_sustain_s is None:
            self.clear_sustain_s = env_float(
                "TPUDL_FLEET_CLEAR_SUSTAIN_S", 5.0
            )
        if self.cooldown_s is None:
            self.cooldown_s = env_float("TPUDL_FLEET_COOLDOWN_S", 2.0)
        if self.serve_share is None:
            self.serve_share = env_float("TPUDL_FLEET_SERVE_SHARE", 0.5)
        if not 0.0 < self.serve_share < 1.0:
            raise ValueError(
                f"serve_share must be in (0, 1) — training keeps at "
                f"least one device — got {self.serve_share}"
            )


class ChipMover:
    """Move chips between a training cohort and the serving fleet.

    ``evaluate()`` is one hysteresis tick (the Autoscaler's shape —
    drive it from the same loop): burn sustained past
    ``burn_sustain_s`` borrows ``serve_share`` of the training devices
    for a new serving replica; burn clear past ``clear_sustain_s``
    returns them. ``spawn_replica(name, devices)`` builds the serving
    replica over the freed devices (typically a
    ``tpudl.fleet.MeshReplica`` factory closing over model/params);
    it is NOT started — ``router.add_replica`` does that.

    Two states: ``training_full`` (no loan outstanding) and
    ``borrowed`` (one serving replica on loaned devices). One loan at
    a time keeps the accounting auditable — an escalation ladder can
    stack movers.
    """

    def __init__(
        self,
        router,
        trainer: ElasticTrainer,
        spawn_replica: Callable[[str, Sequence[jax.Device]], Any],
        config: Optional[ChipMoverConfig] = None,
        clock: Callable[[], float] = time.monotonic,
        burn_fn: Optional[Callable[[], bool]] = None,
    ):
        self.router = router
        self.trainer = trainer
        self.spawn_replica = spawn_replica
        self.config = config or ChipMoverConfig()
        self.clock = clock
        self.burn_fn = burn_fn
        self.state = "training_full"
        self.full_devices: List[jax.Device] = list(trainer.devices)
        self.borrowed_devices: List[jax.Device] = []
        self.borrowed_name: Optional[str] = None
        self.moves = 0
        self.last_burn_cleared_s: Optional[float] = None
        self._burn_since: Optional[float] = None
        self._clear_since: Optional[float] = None
        self._cooldown_until = float("-inf")
        self._burn_started_at: Optional[float] = None
        registry().gauge("fleet_training_devices").set(
            len(self.full_devices)
        )
        registry().gauge("fleet_borrowed_devices").set(0)

    def burning(self) -> bool:
        if self.burn_fn is not None:
            return bool(self.burn_fn())
        return bool(self.router.load_report()["burning"])

    # -- the hysteresis tick --------------------------------------------

    def evaluate(self) -> Optional[str]:
        """One tick; returns the action taken ("to_serving" /
        "to_training") or None."""
        now = self.clock()
        burning = self.burning()
        if self.state == "training_full":
            if not burning:
                self._burn_since = None
                return None
            if self._burn_since is None:
                self._burn_since = now
                self._burn_started_at = now
            if (
                now - self._burn_since >= self.config.burn_sustain_s
                and now >= self._cooldown_until
            ):
                self.move_to_serving()
                return "to_serving"
            return None
        # borrowed: watch for sustained clear
        if burning:
            self._clear_since = None
            return None
        if self._clear_since is None:
            self._clear_since = now
        if (
            now - self._clear_since >= self.config.clear_sustain_s
            and now >= self._cooldown_until
        ):
            self.move_to_training()
            return "to_training"
        return None

    # -- the two moves --------------------------------------------------

    def _split(self) -> tuple:
        devices = list(self.full_devices)
        n_borrow = max(1, int(round(len(devices) * self.config.serve_share)))
        n_borrow = min(n_borrow, len(devices) - 1)
        if n_borrow < 1:
            raise RuntimeError(
                f"cannot split a {len(devices)}-device cohort: training "
                f"keeps at least one device and serving needs one"
            )
        return devices[: len(devices) - n_borrow], devices[len(devices) - n_borrow:]

    def move_to_serving(self) -> Any:
        """Burn sustained: preempt training (SIGTERM protocol),
        restart it shrunk (reshard-restore), serve on the freed
        chips."""
        t0 = self.clock()
        keep, freed = self._split()
        self.trainer.preempt(timeout_s=self.config.preempt_timeout_s)
        self.trainer.restart(keep)
        self.moves += 1
        name = f"borrowed-{self.moves}"
        replica = self.spawn_replica(name, freed)
        self.router.add_replica(replica)
        self.state = "borrowed"
        self.borrowed_devices = list(freed)
        self.borrowed_name = name
        self._clear_since = None
        self._cooldown_until = self.clock() + self.config.cooldown_s
        reg = registry()
        reg.counter("fleet_chip_moves_total").inc()
        reg.gauge("fleet_training_devices").set(len(keep))
        reg.gauge("fleet_borrowed_devices").set(len(freed))
        reg.histogram("fleet_chipmover_move_s").observe(
            self.clock() - t0
        )
        return replica

    def move_to_training(self) -> None:
        """Burn cleared: drain the borrowed replica (migration-first,
        zero dropped results), then grow training back to its full
        grant."""
        t0 = self.clock()
        if self.borrowed_name is not None:
            self.router.remove_replica(self.borrowed_name, drain=True)
        self.trainer.preempt(timeout_s=self.config.preempt_timeout_s)
        self.trainer.restart(self.full_devices)
        if self._burn_started_at is not None:
            self.last_burn_cleared_s = self.clock() - self._burn_started_at
            registry().histogram("fleet_burn_cleared_s").observe(
                self.last_burn_cleared_s
            )
            self._burn_started_at = None
        self.state = "training_full"
        self.borrowed_devices = []
        self.borrowed_name = None
        self._burn_since = None
        self._cooldown_until = self.clock() + self.config.cooldown_s
        self.moves += 1
        reg = registry()
        reg.counter("fleet_chip_moves_total").inc()
        reg.gauge("fleet_training_devices").set(len(self.full_devices))
        reg.gauge("fleet_borrowed_devices").set(0)
        reg.histogram("fleet_chipmover_move_s").observe(
            self.clock() - t0
        )
