"""Elastic reshard-restore: checkpoints cross mesh shapes.

``AsyncCheckpointManager`` stores every leaf as a FULL host array (the
async tier requires fully-addressable or fully-replicated state), so
the saved bytes are mesh-shape-agnostic — what pins a run to its
topology is only where restore PLACES the leaves. ``restore_full``
already places per ``(mesh, rules)`` via ``tree_shardings`` +
``host_to_global_array``; this module wires the coverage-checked rule
adapter (``tpudl.rules.match_partition_rules``) in front of that path
and turns the combination into a contract:

    save on mesh A  ->  reshard_restore(mgr, template, mesh_B, rules)

restores bitwise-identical params AND optimizer state onto a mesh of a
*different* shape (4 devices -> 8, 8 -> 4, ...). That is the missing
half of the PR 4 Supervisor story: a preempted cohort no longer needs
an identically-shaped replacement — it restarts shrunk or grown, which
is what lets the chip mover (tpudl.fleet.chipmover) trade devices
between training and serving at all.

Why the coverage check matters here: the legacy sharding engine
replicates any leaf no rule covers. On a SAME-shape restart that is at
worst a memory bug; on a reshard it silently changes which leaves are
split, so an uncovered leaf is promoted to an error (first use of the
``match_partition_rules`` adapter outside the tests). Pass
``strict=False`` to keep the replicate-by-default behavior.
"""

from __future__ import annotations

from typing import Any, Optional, Sequence, Tuple

import jax
from jax.sharding import PartitionSpec

from tpudl import rules as rules_engine
from tpudl.ft.manager import state_payload
from tpudl.parallel.sharding import FSDP_RULES, tree_shardings
from tpudl.runtime.mesh import MeshSpec, make_mesh

P = PartitionSpec

#: FSDP preset closed over the non-kernel leaves (bias/scale/BatchNorm
#: stats replicate, optimizer scalars hit match_partition_rules'
#: scalar special-case) — a COVERAGE-COMPLETE rule list for the conv/
#: dense models the elastic-restart tests and the chip mover's
#: training cohort use. Transformer cohorts compose their own list the
#: same way: strategy preset first, explicit keep rules after.
ELASTIC_RESNET_RULES: rules_engine.Rules = tuple(FSDP_RULES) + (
    (r".", P()),
)


def elastic_shardings(
    mesh, state: Any, rules: Optional[rules_engine.Rules],
    strict: bool = True,
) -> Any:
    """NamedSharding pytree for a TrainState's serializable payload
    over ``mesh``. ``strict=True`` resolves every leaf through
    ``tpudl.rules.match_partition_rules`` FIRST — an uncovered
    multi-element leaf raises with its path named (a reshard must
    never silently replicate a leaf the rules forgot) — then hands the
    same rules to the clamping sharding engine for the actual specs."""
    payload = state_payload(state)
    if strict:
        rules_engine.match_partition_rules(rules, payload)
    return tree_shardings(mesh, payload, rules)


def reshard_restore(
    manager,
    state: Any,
    mesh,
    rules: Optional[rules_engine.Rules],
    step: Optional[int] = None,
    strict: bool = True,
) -> Tuple[Any, Optional[jax.Array], Optional[dict]]:
    """Restore ``(state, rng, data_state)`` onto ``mesh`` — which need
    NOT be the shape the checkpoint was written on.

    ``state`` is the restore template (shapes/dtypes validated against
    the committed metadata, as always); ``rules`` place every leaf on
    the new mesh. With ``strict`` (default) the rules must COVER the
    payload — see ``elastic_shardings``. Leaf VALUES are untouched:
    the checkpoint holds full host arrays and resharding only changes
    their placement, so a save -> reshard_restore round-trip is
    bitwise on params and optimizer state (tests/test_fleet_pod.py
    pins 4 -> 8 -> 4)."""
    elastic_shardings(mesh, state, rules, strict=strict)
    return manager.restore_full(state, step=step, mesh=mesh, rules=rules)


def cohort_mesh(
    devices: Sequence[jax.Device],
    spec: Optional[MeshSpec] = None,
):
    """A training-cohort mesh over an explicit device subset. The spec
    (default: pure-DP ``MeshSpec()``) is ``fit()``-clamped to however
    many devices the cohort currently holds, so one declared shape
    drives the full cohort AND every shrunk restart of it."""
    if spec is None:
        spec = MeshSpec()
    return make_mesh(spec.fit(len(devices)), list(devices))
