"""Cross-process migration transport: the PR 13 payload over a wire.

The serving migration payload (tpudl.serve.cache.pack_migration) is
already bytes-on-the-wire by construction — magic + versioned JSON
meta + raw KV leaves + crc32, carrying paged KV pages, generated
tokens, the sampling position, the ABSOLUTE deadline, and (PR 19) the
speculative draft's KV remainder. Inside one process the router moves
those bytes between replica threads with a deque append. This module
moves the SAME bytes across a process boundary, so failover crosses
hosts instead of threads:

- ``send_frame`` / ``recv_frame`` — length-prefixed framing over any
  socket (magic-checked, size-capped; the payload's own crc is
  verified by the RECEIVING engine thread, so a corrupted transfer
  becomes that request's ``failed`` Result, never a transport crash —
  the exact contract the in-process path has).
- ``MigrationEndpoint`` — a listening socket on the survivor process:
  every received payload is handed to a ``deliver`` callback
  (``deliver_to_session`` seats it on a local engine's migrate inbox;
  a pod runs one endpoint per serving process).
- ``send_migration`` — the source-side client: connect, frame each
  payload, close.
- ``FileChannel`` — the spool-file alternative for hosts that share a
  filesystem but no network path (or for handoff across a process
  RESTART): tmp-write + fsync + atomic rename, so a reader never
  observes a torn payload — the checkpoint store's commit protocol
  applied to migration bytes.

Resume-on-survivor: ``migrate_request`` exports a mid-stream request
from a local session (``Engine.export_request`` — the commit point
frees the source slot only once the payload exists) and ships it;
``deliver_to_session`` on the other end enqueues it exactly as a
router-local migration would, and the engine resumes the decode with
ZERO re-prefill. Greedy continuations are token-for-token identical
to an unmigrated run (tests/test_fleet_pod.py pins this across a real
subprocess, speculative draft state included).

Knobs: ``TPUDL_FLEET_TRANSPORT_HOST`` (bind/connect host for
endpoints, default 127.0.0.1), ``TPUDL_FLEET_TRANSPORT_TIMEOUT_S``
(socket send/recv timeout), ``TPUDL_FLEET_SPOOL_DIR`` (default
directory for ``FileChannel()``).
"""

from __future__ import annotations

import os
import socket
import struct
import threading
import uuid
from typing import Any, Callable, List, Optional, Sequence, Tuple

from tpudl.analysis.registry import env_float, env_str
from tpudl.obs import registry

#: Frame magic: distinct from the payload's own TPUDLMIG magic so a
#: stream misaligned by one lost byte fails loudly at the frame layer.
FRAME_MAGIC = b"TPDLFRM1"
#: Refuse absurd frames before allocating for them (a corrupt length
#: prefix must not OOM the survivor). 1 GiB >> any KV payload.
MAX_FRAME_BYTES = 1 << 30

_LEN = struct.Struct("<Q")


class TransportError(RuntimeError):
    """A framing/channel failure (bad magic, truncated stream,
    oversized frame). Distinct from the payload-level
    MigrationCorruptError the engine raises — transport errors mean
    the BYTES never arrived whole, so the caller still holds the
    payload and can retry or resubmit."""


def send_frame(sock: socket.socket, payload: bytes) -> None:
    """Write one length-prefixed payload frame."""
    sock.sendall(FRAME_MAGIC + _LEN.pack(len(payload)) + payload)


def _recv_exact(sock: socket.socket, n: int) -> Optional[bytes]:
    buf = bytearray()
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            if not buf:
                return None  # clean EOF between frames
            raise TransportError(
                f"stream truncated mid-frame ({len(buf)}/{n} bytes)"
            )
        buf.extend(chunk)
    return bytes(buf)


def recv_frame(sock: socket.socket) -> Optional[bytes]:
    """Read one frame; None on a clean end-of-stream."""
    header = _recv_exact(sock, len(FRAME_MAGIC) + _LEN.size)
    if header is None:
        return None
    magic, raw_len = (
        header[: len(FRAME_MAGIC)], header[len(FRAME_MAGIC):]
    )
    if magic != FRAME_MAGIC:
        raise TransportError(f"bad frame magic {magic!r}")
    (length,) = _LEN.unpack(raw_len)
    if length > MAX_FRAME_BYTES:
        raise TransportError(f"frame of {length} bytes exceeds cap")
    payload = _recv_exact(sock, length)
    if payload is None or len(payload) != length:
        raise TransportError("stream truncated inside frame body")
    return payload


def _default_host() -> str:
    return env_str("TPUDL_FLEET_TRANSPORT_HOST") or "127.0.0.1"


def _default_timeout() -> float:
    return env_float("TPUDL_FLEET_TRANSPORT_TIMEOUT_S", 30.0)


def payload_request_id(payload: bytes) -> Any:
    """The request id a migration payload carries (full crc-verified
    parse — a payload we cannot even name is refused at the door)."""
    from tpudl.serve.cache import parse_migration

    return parse_migration(payload)["request"]["request_id"]


def deliver_to_session(session, payload: bytes) -> Any:
    """Enqueue a received payload on a local session's migrate inbox —
    the survivor half of resume-on-survivor. Returns the request id.
    The engine thread re-verifies the crc and seats the request
    mid-stream (zero re-prefill); corruption sheds it as ``failed``,
    identical to the router-local migration path."""
    from tpudl.serve.engine import _Migrated

    rid = payload_request_id(payload)
    session.engine.migrate_inbox.append(_Migrated(rid, payload))
    return rid


class MigrationEndpoint:
    """A migration listener for one serving process.

    Accepts connections on ``(host, port)`` (port 0 = ephemeral; read
    the bound address off ``.address``) and hands every framed payload
    to ``deliver`` on the accept thread. ``deliver`` must only enqueue
    (``deliver_to_session`` does) — the engine thread does the
    expensive verify/seat work, keeping the endpoint responsive while
    a transfer streams in."""

    def __init__(
        self,
        deliver: Callable[[bytes], Any],
        host: Optional[str] = None,
        port: int = 0,
        timeout_s: Optional[float] = None,
    ):
        self.deliver = deliver
        self.timeout_s = (
            _default_timeout() if timeout_s is None else timeout_s
        )
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind((host or _default_host(), port))
        self._sock.listen(8)
        self.address: Tuple[str, int] = self._sock.getsockname()[:2]
        self._stop = threading.Event()
        self.received = 0
        self.errors = 0
        self._thread = threading.Thread(
            target=self._accept_loop,
            name=f"tpudl-migration-endpoint-{self.address[1]}",
            daemon=True,
        )
        self._thread.start()

    def _accept_loop(self) -> None:
        self._sock.settimeout(0.2)
        while not self._stop.is_set():
            try:
                conn, _peer = self._sock.accept()
            except socket.timeout:
                continue
            except OSError:
                break  # socket closed under us: shutting down
            try:
                conn.settimeout(self.timeout_s)
                while True:
                    payload = recv_frame(conn)
                    if payload is None:
                        break
                    self.deliver(payload)
                    self.received += 1
                    registry().counter(
                        "fleet_transport_payloads_received"
                    ).inc()
            except Exception:
                # One bad sender must not kill the endpoint; the
                # source still holds its payload and sees the broken
                # connection.
                self.errors += 1
                registry().counter("fleet_transport_errors").inc()
            finally:
                conn.close()

    def close(self) -> None:
        self._stop.set()
        try:
            self._sock.close()
        finally:
            self._thread.join(timeout=5.0)

    def __enter__(self) -> "MigrationEndpoint":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


def send_migration(
    address: Tuple[str, int],
    payloads: Sequence[bytes],
    timeout_s: Optional[float] = None,
) -> int:
    """Ship payloads to a survivor's ``MigrationEndpoint``. Returns
    total bytes sent; raises (socket error / TransportError) with the
    payloads untouched in the caller's hands — resubmission stays
    possible, which is the router's existing crashed-thread
    fallback."""
    total = 0
    with socket.create_connection(
        address, timeout=_default_timeout() if timeout_s is None else timeout_s
    ) as sock:
        for payload in payloads:
            send_frame(sock, payload)
            total += len(payload)
    registry().counter("fleet_transport_payloads_sent").inc(len(payloads))
    return total


def migrate_request(
    session,
    rid: Any,
    address: Optional[Tuple[str, int]] = None,
    channel: Optional["FileChannel"] = None,
    skip_prefix_tokens: int = 0,
) -> Optional[int]:
    """Export one mid-stream request from a local session and ship it
    over a socket (``address``) or spool (``channel``). Returns the
    payload size, or None when the engine declines the export (dense
    cache / request not seated) — the caller resubmits, as the router
    does. The export's commit point (source slot freed) only passes
    once the payload bytes exist, and a failed send leaves them in
    hand."""
    payload = session.engine.export_request(
        rid, skip_prefix_tokens=skip_prefix_tokens
    )
    if payload is None:
        return None
    if (address is None) == (channel is None):
        raise ValueError(
            "migrate_request needs exactly one of address / channel"
        )
    if address is not None:
        send_migration(address, [payload])
    else:
        channel.put(payload)
    return len(payload)


class FileChannel:
    """Atomic spool-file migration channel over a shared directory.

    ``put`` stages to a ``.tmp`` name, fsyncs, then renames to
    ``.mig`` — the commit protocol tpudl.ft.store uses, so a reader
    (even one that starts AFTER the writer died) observes whole
    payloads or nothing. ``take``/``drain`` consume oldest-first
    (lexicographic sequence names preserve put order within a
    process)."""

    SUFFIX = ".mig"

    def __init__(self, directory: Optional[str] = None):
        directory = directory or env_str("TPUDL_FLEET_SPOOL_DIR")
        if not directory:
            raise ValueError(
                "FileChannel needs a directory (or TPUDL_FLEET_SPOOL_DIR)"
            )
        self.directory = directory
        os.makedirs(directory, exist_ok=True)
        self._seq = 0
        self._lock = threading.Lock()

    def put(self, payload: bytes) -> str:
        with self._lock:
            self._seq += 1
            seq = self._seq
        name = f"{seq:08d}-{os.getpid()}-{uuid.uuid4().hex[:8]}"
        tmp = os.path.join(self.directory, name + ".tmp")
        final = os.path.join(self.directory, name + self.SUFFIX)
        with open(tmp, "wb") as f:
            f.write(payload)
            f.flush()
            os.fsync(f.fileno())
        os.rename(tmp, final)
        return final

    def _committed(self) -> List[str]:
        try:
            names = os.listdir(self.directory)
        except FileNotFoundError:
            return []
        return sorted(n for n in names if n.endswith(self.SUFFIX))

    def __len__(self) -> int:
        return len(self._committed())

    def take(self) -> Optional[bytes]:
        """Consume the oldest committed payload (None when empty).
        Rename-claims before reading, so two drainers sharing the
        spool never double-resume one request."""
        for name in self._committed():
            path = os.path.join(self.directory, name)
            claimed = path + ".claimed"
            try:
                os.rename(path, claimed)
            except OSError:
                continue  # another drainer won this one
            try:
                with open(claimed, "rb") as f:
                    return f.read()
            finally:
                os.unlink(claimed)
        return None

    def drain(self) -> List[bytes]:
        out: List[bytes] = []
        while True:
            payload = self.take()
            if payload is None:
                return out
            out.append(payload)
