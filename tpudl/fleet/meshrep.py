"""MeshReplica: serving replicas whose programs span a device mesh.

A Router ``Replica`` has always been one driver thread over one local
device view. This module keeps that placement contract byte-identical
(health snapshots, routing books, sticky/adapter/prefix affinity,
migration pulls — all unchanged) and moves the MESH below it: the
replica's ServeSession is built from params committed to a
tensor-parallel ``Mesh`` via ``jax.device_put(params,
tree_shardings(mesh, params, rules))``, so every jitted serving
program (prefill, paged decode, chunk verify, the draft path) compiles
for that mesh's device assignment and GSPMD inserts the ICI
collectives. Host-side inputs (token ids, page tables) stay
uncommitted and replicate by propagation — the engine's bookkeeping
code does not know the mesh exists.

Tier-1 surface: ``XLA_FLAGS=--xla_force_host_platform_device_count=8``
(tests/conftest.py) fakes an 8-device host, and several MeshReplicas
may share those devices — exactly like N thread replicas sharing one
chip today. Greedy traffic over a mesh replica is token-for-token
identical to ``generate()`` (tests/test_fleet_pod.py pins router
parity over two 8-device mesh replicas).

Multi-process (a REAL pod: one process per host, jax.distributed):
initialize the slice first — ``TpuDistributor.pod().run(worker)`` or
``jax.distributed.initialize`` — then build the same session over
``jax.devices()`` inside the worker; ``serving_mesh`` lays the tp axis
over the global device list. The CPU jaxlib cannot compile
cross-process computations, so that tier runs under
``@pytest.mark.needs_multiprocess`` (auto-skipped off-TPU by
conftest).
"""

from __future__ import annotations

from typing import Any, Optional, Sequence

import jax

from tpudl.parallel.sharding import TP_TRANSFORMER_RULES, tree_shardings
from tpudl.runtime.mesh import MeshSpec, make_mesh
from tpudl.serve.api import ServeSession
from tpudl.serve.router import Replica

#: Default placement for serving params: megatron column/row splits
#: over the tp axis (the fsdp entries clamp to size 1 on a pure-tp
#: serving mesh). Replicated leaves (norms, biases) ride the engine's
#: replicate-by-default; serving has no optimizer state to cover.
SERVE_MESH_RULES = TP_TRANSFORMER_RULES


def serving_mesh(
    devices: Optional[Sequence[jax.Device]] = None,
    tp: Optional[int] = None,
):
    """A serving mesh over ``devices`` (default: all local devices):
    tensor-parallel over ``tp`` of them (default: all). ``tp`` is
    gcd-clamped to the device count, so one knob value drives full and
    shrunk device grants alike (the chip mover hands this function
    arbitrary subsets)."""
    if devices is None:
        devices = jax.devices()
    devices = list(devices)
    spec = MeshSpec(dp=-1, fsdp=1, sp=1, tp=len(devices) if tp is None else tp)
    return make_mesh(spec.fit(len(devices)), devices)


def build_mesh_session(
    model,
    params: Any,
    prompt_len: int,
    mesh=None,
    devices: Optional[Sequence[jax.Device]] = None,
    tp: Optional[int] = None,
    rules=None,
    **from_model_kwargs,
) -> ServeSession:
    """A ServeSession whose params are committed to ``mesh`` (built
    over ``devices``/``tp`` when not given). Everything else is
    ``ServeSession.from_model`` verbatim — committed params are what
    make jit compile the serving programs for the mesh's device
    assignment; the cache template, paged pools, and speculative draft
    build from the sharded tree and follow by propagation. The
    returned session carries the mesh as ``session.mesh``."""
    if mesh is None:
        mesh = serving_mesh(devices, tp=tp)
    if rules is None:
        rules = SERVE_MESH_RULES
    sharded = jax.device_put(params, tree_shardings(mesh, params, rules))
    session = ServeSession.from_model(
        model, sharded, prompt_len, **from_model_kwargs
    )
    session.mesh = mesh
    return session


class MeshReplica(Replica):
    """A Router replica over a pjit-sharded ServeSession.

    Identical to ``Replica`` above the session (the router cannot tell
    them apart — that is the point); construction either wraps a
    prebuilt mesh session or builds one from ``(model, params,
    prompt_len)`` plus mesh arguments. ``replica.mesh`` names the
    devices this replica occupies — the chip mover's accounting unit.
    """

    def __init__(
        self,
        name: str,
        session: Optional[ServeSession] = None,
        model=None,
        params: Any = None,
        prompt_len: Optional[int] = None,
        mesh=None,
        devices: Optional[Sequence[jax.Device]] = None,
        tp: Optional[int] = None,
        rules=None,
        session_kwargs: Optional[dict] = None,
        **replica_kwargs,
    ):
        if session is None:
            if model is None or params is None or prompt_len is None:
                raise ValueError(
                    "MeshReplica needs either a prebuilt session or "
                    "(model, params, prompt_len) to build one"
                )
            session = build_mesh_session(
                model, params, prompt_len, mesh=mesh, devices=devices,
                tp=tp, rules=rules, **(session_kwargs or {}),
            )
        super().__init__(name, session, **replica_kwargs)
        self.mesh = getattr(session, "mesh", mesh)

    @property
    def mesh_devices(self) -> tuple:
        """The devices this replica's programs run on (flat)."""
        if self.mesh is None:
            return ()
        return tuple(self.mesh.devices.flat)
