#!/usr/bin/env bash
# tpudl CI gate: static analysis + (optional) ruff + the fast test tier.
#
#   scripts/ci_check.sh            # everything
#   scripts/ci_check.sh --lint-only
#
# Exit nonzero on: new (unbaselined) lint_tpudl findings, ruff
# error-tier findings (when ruff is installed — see [tool.ruff] in
# pyproject.toml), or a fast-tier test failure.
set -euo pipefail
cd "$(dirname "$0")/.."

echo "== scripts/lint_tpudl.py (ratcheted static analysis)"
python scripts/lint_tpudl.py

if command -v ruff >/dev/null 2>&1; then
    echo "== ruff check"
    ruff check .
else
    echo "== ruff not installed; skipping (config lives in pyproject.toml)"
fi

if [[ "${1:-}" == "--lint-only" ]]; then
    exit 0
fi

if [[ "${1:-}" == "--precision" ]]; then
    # Mixed-precision training smoke: tiny fixed-seed bf16-vs-f32 (and
    # fp8) parity run — the loss-parity gates assert inside the sweep.
    echo "== precision smoke (bf16/fp8 train-step loss parity vs f32)"
    JAX_PLATFORMS=${JAX_PLATFORMS:-cpu} python -m benchmarks.train_precision --smoke
    exit 0
fi

echo "== precision smoke (bf16 train-step loss parity vs f32)"
JAX_PLATFORMS=${JAX_PLATFORMS:-cpu} python -m benchmarks.train_precision \
    --smoke --cells f32,bf16 > /dev/null

echo "== multi-tenant smoke (adapter pool + segmented-LoRA batched decode)"
JAX_PLATFORMS=${JAX_PLATFORMS:-cpu} python -m benchmarks.serve_load \
    --tenants --tenants-adapters 8 --requests 4 > /dev/null

echo "== request-log smoke (durable JSONL round-trip + per-tenant token reconciliation)"
JAX_PLATFORMS=${JAX_PLATFORMS:-cpu} python -m benchmarks.serve_load \
    --requestlog --requests 4 > /dev/null

echo "== flywheel smoke (samples on -> one LoRA refresh -> safe hot-swap asserted)"
JAX_PLATFORMS=${JAX_PLATFORMS:-cpu} python -m benchmarks.serve_load \
    --flywheel --requests 8 > /dev/null

echo "== fleet smoke (mesh replicas + reshard-restore + chip mover end-to-end)"
JAX_PLATFORMS=${JAX_PLATFORMS:-cpu} python -m benchmarks.fleet_mesh \
    --smoke --json > /dev/null

echo "== chaos smoke (serving fault injection: migration, failover, drains)"
JAX_PLATFORMS=${JAX_PLATFORMS:-cpu} python -m pytest tests/ -q -m 'chaos and not slow' \
    -p no:cacheprovider

echo "== fast test tier (tier-1: not slow)"
JAX_PLATFORMS=${JAX_PLATFORMS:-cpu} python -m pytest tests/ -q -m 'not slow' \
    --continue-on-collection-errors -p no:cacheprovider
