"""Real-TPU statistical checks for the in-kernel (PRNG-backed) dropout
paths — the half of tpudl.ops.fused_attention / tpudl.ops.softmax_dropout
that pallas interpret mode cannot emulate (no PRNG), so the CPU test tier
(tests/test_fused_attention.py) cannot cover it.

Run on a machine with a TPU: python scripts/tpu_dropout_check.py
Prints PASS/FAIL per check; exits nonzero on failure; prints SKIP when
no TPU backend is present (so CI without a chip stays green).
"""

import pathlib
import sys

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parents[1]))

import jax
import jax.numpy as jnp

from tpudl.ops.attention import attend, is_tpu_backend
from tpudl.ops.fused_attention import fused_attention
from tpudl.ops.softmax_dropout import softmax_dropout


def main() -> int:
    if not is_tpu_backend():
        print("SKIP: no TPU backend")
        return 0
    failures = 0

    def check(name, ok):
        nonlocal failures
        print(f"{'PASS' if ok else 'FAIL'}: {name}")
        failures += 0 if ok else 1

    B, S, H, D = 4, 128, 8, 64
    ks = jax.random.split(jax.random.key(0), 3)
    q, k, v = (jax.random.normal(kk, (B, S, H, D), jnp.float32) for kk in ks)
    rng = jax.random.key(42)

    # Determinism: same key -> bit-identical outputs and grads.
    o1 = fused_attention(q, k, v, dropout_rate=0.1, dropout_rng=rng)
    o2 = fused_attention(q, k, v, dropout_rate=0.1, dropout_rng=rng)
    check("fused fwd deterministic per key", bool(jnp.all(o1 == o2)))
    o3 = fused_attention(q, k, v, dropout_rate=0.1,
                         dropout_rng=jax.random.key(43))
    check("fused fwd differs across keys", bool(jnp.any(o1 != o3)))
    g1 = jax.grad(lambda q: jnp.sum(
        fused_attention(q, k, v, dropout_rate=0.1, dropout_rng=rng) ** 2
    ))(q)
    g2 = jax.grad(lambda q: jnp.sum(
        fused_attention(q, k, v, dropout_rate=0.1, dropout_rng=rng) ** 2
    ))(q)
    check("fused bwd deterministic per key", bool(jnp.all(g1 == g2)))
    check("fused bwd finite", bool(jnp.all(jnp.isfinite(g1))))

    # Expectation: mean over keys approaches the no-dropout output.
    base = attend(q, k, v)
    f = jax.jit(lambda r: fused_attention(
        q, k, v, dropout_rate=0.1, dropout_rng=r
    ))
    acc = jnp.zeros_like(base)
    n = 96
    for i in range(n):
        acc = acc + f(jax.random.key(100 + i))
    err = float(jnp.mean(jnp.abs(acc / n - base)))
    check(f"fused E[dropout out] ~ base (mean_abs {err:.4f})", err < 0.02)

    # softmax_dropout keep fraction via uniform probabilities.
    logits = jnp.zeros((2, 2, 128, 128))
    p = softmax_dropout(logits, dropout_rate=0.1,
                        dropout_rng=rng, out_dtype=jnp.float32)
    # each kept element is (1/S)/(1-r); fraction kept ~ 1 - r
    kept = float(jnp.mean((p > 0).astype(jnp.float32)))
    check(f"softmax_dropout keep fraction {kept:.4f} ~ 0.9",
          abs(kept - 0.9) < 0.01)

    # ---- flash attention in-kernel dropout (round-4) --------------------
    # The strong check: extract the kernel's effective post-dropout
    # attention weights by feeding v = I (D = Skv), rebuild the SAME
    # computation in plain XLA from the extracted keep-mask, and compare
    # output AND all three gradients. This verifies (a) the dropout math
    # (denominator undropped, numerator masked+rescaled), (b) the
    # fwd/bwd mask bit-consistency across the q-major and kv-major grids.
    from tpudl.ops.flash_attention import flash_attention

    Bf, Sf, Hf = 2, 256, 2  # D = Sf for the identity-V trick
    rate = 0.3
    kq, kk2 = jax.random.split(jax.random.key(7))
    qf = jax.random.normal(kq, (Bf, Sf, Hf, Sf), jnp.float32)
    kf = jax.random.normal(kk2, (Bf, Sf, Hf, Sf), jnp.float32)
    v_eye = jnp.broadcast_to(
        jnp.eye(Sf, dtype=jnp.float32)[:, None, :], (Sf, Hf, Sf)
    )[None].repeat(Bf, axis=0)
    frng = jax.random.key(11)
    # effective weights w' = keep * softmax / (1-rate), per (b, h)
    w_eff = flash_attention(
        qf, kf, v_eye, dropout_rate=rate, dropout_rng=frng,
        block_q=128, block_k=128,
    )  # [B, Sq, H, Skv]
    w_full = flash_attention(qf, kf, v_eye, block_q=128, block_k=128)
    keep_mask = (jnp.abs(w_eff) > 0).astype(jnp.float32)
    kept_frac = float(jnp.mean(keep_mask))
    check(f"flash dropout keep fraction {kept_frac:.4f} ~ {1 - rate}",
          abs(kept_frac - (1 - rate)) < 0.01)
    # extracted weights == undropped weights masked+rescaled
    w_ref = w_full * keep_mask / (1 - rate)
    werr = float(jnp.max(jnp.abs(w_eff - w_ref)))
    check(f"flash dropout = mask(softmax)/(1-r) (max_abs {werr:.2e})",
          werr < 3e-5)
    # fwd-vs-bwd mask bit-equality: vjp with identity cotangent returns
    # dv[b,k,h,j] = w'_bwd[b,j,h,k] — the BACKWARD pass's effective
    # weights. The kv-major dk/dv grid must regenerate the exact keep
    # pattern the q-major forward drew.
    _, vjp_fn = jax.vjp(
        lambda v_: flash_attention(
            qf, kf, v_, dropout_rate=rate, dropout_rng=frng,
            block_q=128, block_k=128,
        ),
        v_eye,
    )
    w_bwd = jnp.transpose(vjp_fn(v_eye)[0], (0, 3, 2, 1))
    mask_mismatch = int(jnp.sum((w_eff > 0) != (w_bwd > 0)))
    check(f"flash fwd/bwd dropout masks bit-identical "
          f"({mask_mismatch} mismatches)", mask_mismatch == 0)

    # Gradient parity vs the XLA reconstruction with the SAME mask. The
    # keep-mask depends only on (rng, rate, grid geometry) — not on
    # q/k/v values or head_dim — so the mask extracted above (D=Sf for
    # the identity trick) applies verbatim to these D=64 tensors as long
    # as B/H/S/blocks match.
    qs = jax.random.normal(jax.random.key(8), (Bf, Sf, Hf, 64), jnp.float32)
    ks_ = jax.random.normal(jax.random.key(9), (Bf, Sf, Hf, 64), jnp.float32)
    kv3 = jax.random.normal(jax.random.key(10), (Bf, Sf, Hf, 64), jnp.float32)
    scale = qs.shape[-1] ** -0.5

    def ref_fn(q_, k_, v_):
        s = jnp.einsum("bqhd,bkhd->bhqk", q_, k_) * scale
        w = jax.nn.softmax(s.astype(jnp.float32), axis=-1)
        wk = w * jnp.transpose(keep_mask, (0, 2, 1, 3)) / (1 - rate)
        return jnp.einsum("bhqk,bkhd->bqhd", wk, v_)

    def flash_fn(q_, k_, v_):
        return flash_attention(
            q_, k_, v_, dropout_rate=rate, dropout_rng=frng,
            block_q=128, block_k=128,
        )

    def ref_plain(q_, k_, v_):
        s = jnp.einsum("bqhd,bkhd->bhqk", q_, k_) * scale
        w = jax.nn.softmax(s.astype(jnp.float32), axis=-1)
        return jnp.einsum("bhqk,bkhd->bqhd", w, v_)

    def flash_plain(q_, k_, v_):
        return flash_attention(q_, k_, v_, block_q=128, block_k=128)

    gcoef = jax.random.normal(jax.random.key(13), (Bf, Sf, Hf, 64))
    gr = jax.grad(lambda args: jnp.sum(ref_fn(*args) * gcoef))((qs, ks_, kv3))
    gf = jax.grad(lambda args: jnp.sum(flash_fn(*args) * gcoef))((qs, ks_, kv3))
    # Calibrate against the NO-dropout kernel's own numerical floor vs
    # XLA (TPU f32 matmul passes + online-softmax reassociation measure
    # ~1.2-1.6e-3 rel here): dropout grads must sit within 3x of it —
    # a wrong/new mask in the backward shows up orders of magnitude
    # larger (fwd-vs-bwd mask equality is separately asserted exactly by
    # the w'-extraction check above).
    g0r = jax.grad(lambda args: jnp.sum(ref_plain(*args) * gcoef))((qs, ks_, kv3))
    g0f = jax.grad(lambda args: jnp.sum(flash_plain(*args) * gcoef))((qs, ks_, kv3))
    names = ("dq", "dk", "dv")
    for name, a, b2, a0, b0 in zip(names, gr, gf, g0r, g0f):
        rel = float(jnp.max(jnp.abs(a - b2))) / (
            float(jnp.max(jnp.abs(a))) + 1e-9
        )
        base_rel = float(jnp.max(jnp.abs(a0 - b0))) / (
            float(jnp.max(jnp.abs(a0))) + 1e-9
        )
        check(
            f"flash dropout {name} parity (rel {rel:.2e}, no-dropout "
            f"floor {base_rel:.2e})",
            rel < max(3 * base_rel, 1e-4),
        )

    # determinism per key, variation across keys, causal+mask composition
    o1 = flash_fn(qs, ks_, kv3)
    o2 = flash_fn(qs, ks_, kv3)
    check("flash dropout fwd deterministic per key", bool(jnp.all(o1 == o2)))
    o3 = flash_attention(qs, ks_, kv3, dropout_rate=rate,
                         dropout_rng=jax.random.key(12),
                         block_q=128, block_k=128)
    check("flash dropout differs across keys", bool(jnp.any(o1 != o3)))
    padmask = (jnp.arange(Sf)[None, :] < Sf - 17).astype(jnp.int32)
    padmask = jnp.broadcast_to(padmask, (Bf, Sf))
    oc = flash_attention(qs, ks_, kv3, mask=padmask, causal=True,
                         dropout_rate=rate, dropout_rng=frng)
    check("flash dropout + causal + padding finite",
          bool(jnp.all(jnp.isfinite(oc))))
    # attend() long-context dispatch: fused impl beyond MAX_SEQ routes to
    # flash WITH dropout (the removed round-3 carve-out)
    S_long = 2048
    q4 = jax.random.normal(jax.random.key(20), (1, S_long, 2, 64), jnp.bfloat16)
    k4 = jax.random.normal(jax.random.key(21), (1, S_long, 2, 64), jnp.bfloat16)
    v4 = jax.random.normal(jax.random.key(22), (1, S_long, 2, 64), jnp.bfloat16)
    o_long = attend(q4, k4, v4, implementation="fused", causal=True,
                    dropout_rate=0.1, dropout_rng=frng)
    check("attend seq-2048 dropout via flash finite",
          bool(jnp.all(jnp.isfinite(o_long.astype(jnp.float32)))))

    # ulysses dropout with the FLASH local body on the real chip (the CPU
    # tier covers local_impl='reference'): single-device degenerate path
    # (no mesh on one chip) must be deterministic per key and match the
    # expectation of the base output.
    from tpudl.ops.ulysses import ulysses_attention

    qs2 = jax.random.normal(jax.random.key(30), (2, 256, 4, 64), jnp.float32)
    ks2 = jax.random.normal(jax.random.key(31), (2, 256, 4, 64), jnp.float32)
    vs2 = jax.random.normal(jax.random.key(32), (2, 256, 4, 64), jnp.float32)
    u1 = ulysses_attention(qs2, ks2, vs2, local_impl="flash",
                           dropout_rate=0.2, dropout_rng=frng)
    u2 = ulysses_attention(qs2, ks2, vs2, local_impl="flash",
                           dropout_rate=0.2, dropout_rng=frng)
    check("ulysses flash dropout deterministic per key",
          bool(jnp.all(u1 == u2)))
    ubase = ulysses_attention(qs2, ks2, vs2, local_impl="flash")
    uf = jax.jit(lambda r: ulysses_attention(
        qs2, ks2, vs2, local_impl="flash", dropout_rate=0.2, dropout_rng=r
    ))
    uacc = jnp.zeros_like(ubase)
    un = 64
    for i in range(un):
        uacc = uacc + uf(jax.random.key(300 + i))
    uerr = float(jnp.mean(jnp.abs(uacc / un - ubase)))
    check(f"ulysses flash E[dropout out] ~ base (mean_abs {uerr:.4f})",
          uerr < 0.05)

    # ring attention with the FLASH tick body (round 5): per-tick
    # (o, lse) merge with in-kernel dropout whose lse is of the
    # UNDROPPED distribution — deterministic per key, expectation
    # matching the undropped output, on a real sp mesh shape (sp=1 on
    # one chip exercises the shard_map + kernel path end to end).
    from tpudl.ops.ring_attention import ring_attention
    from tpudl.runtime.mesh import MeshSpec, make_mesh

    # Wildcard dp: the mesh fits any device count (the script's
    # run-anywhere contract); sp stays 1 so the ring body is the
    # single-shard degenerate that still runs shard_map + the kernel.
    rmesh = make_mesh(MeshSpec(dp=-1, sp=1))
    r1 = ring_attention(qs2, ks2, vs2, causal=True, mesh=rmesh,
                        local_impl="flash", dropout_rate=0.2,
                        dropout_rng=frng)
    r2 = ring_attention(qs2, ks2, vs2, causal=True, mesh=rmesh,
                        local_impl="flash", dropout_rate=0.2,
                        dropout_rng=frng)
    check("ring flash dropout deterministic per key",
          bool(jnp.all(r1 == r2)))
    rbase = ring_attention(qs2, ks2, vs2, causal=True, mesh=rmesh,
                           local_impl="flash")
    rf = jax.jit(lambda r: ring_attention(
        qs2, ks2, vs2, causal=True, mesh=rmesh, local_impl="flash",
        dropout_rate=0.2, dropout_rng=r,
    ))
    racc = jnp.zeros_like(rbase)
    for i in range(un):
        racc = racc + rf(jax.random.key(400 + i))
    rerr = float(jnp.mean(jnp.abs(racc / un - rbase)))
    check(f"ring flash E[dropout out] ~ base (mean_abs {rerr:.4f})",
          rerr < 0.05)
    return 1 if failures else 0


if __name__ == "__main__":
    raise SystemExit(main())
