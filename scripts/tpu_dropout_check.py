"""Real-TPU statistical checks for the in-kernel (PRNG-backed) dropout
paths — the half of tpudl.ops.fused_attention / tpudl.ops.softmax_dropout
that pallas interpret mode cannot emulate (no PRNG), so the CPU test tier
(tests/test_fused_attention.py) cannot cover it.

Run on a machine with a TPU: python scripts/tpu_dropout_check.py
Prints PASS/FAIL per check; exits nonzero on failure; prints SKIP when
no TPU backend is present (so CI without a chip stays green).
"""

import pathlib
import sys

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parents[1]))

import jax
import jax.numpy as jnp

from tpudl.ops.attention import attend, is_tpu_backend
from tpudl.ops.fused_attention import fused_attention
from tpudl.ops.softmax_dropout import softmax_dropout


def main() -> int:
    if not is_tpu_backend():
        print("SKIP: no TPU backend")
        return 0
    failures = 0

    def check(name, ok):
        nonlocal failures
        print(f"{'PASS' if ok else 'FAIL'}: {name}")
        failures += 0 if ok else 1

    B, S, H, D = 4, 128, 8, 64
    ks = jax.random.split(jax.random.key(0), 3)
    q, k, v = (jax.random.normal(kk, (B, S, H, D), jnp.float32) for kk in ks)
    rng = jax.random.key(42)

    # Determinism: same key -> bit-identical outputs and grads.
    o1 = fused_attention(q, k, v, dropout_rate=0.1, dropout_rng=rng)
    o2 = fused_attention(q, k, v, dropout_rate=0.1, dropout_rng=rng)
    check("fused fwd deterministic per key", bool(jnp.all(o1 == o2)))
    o3 = fused_attention(q, k, v, dropout_rate=0.1,
                         dropout_rng=jax.random.key(43))
    check("fused fwd differs across keys", bool(jnp.any(o1 != o3)))
    g1 = jax.grad(lambda q: jnp.sum(
        fused_attention(q, k, v, dropout_rate=0.1, dropout_rng=rng) ** 2
    ))(q)
    g2 = jax.grad(lambda q: jnp.sum(
        fused_attention(q, k, v, dropout_rate=0.1, dropout_rng=rng) ** 2
    ))(q)
    check("fused bwd deterministic per key", bool(jnp.all(g1 == g2)))
    check("fused bwd finite", bool(jnp.all(jnp.isfinite(g1))))

    # Expectation: mean over keys approaches the no-dropout output.
    base = attend(q, k, v)
    f = jax.jit(lambda r: fused_attention(
        q, k, v, dropout_rate=0.1, dropout_rng=r
    ))
    acc = jnp.zeros_like(base)
    n = 96
    for i in range(n):
        acc = acc + f(jax.random.key(100 + i))
    err = float(jnp.mean(jnp.abs(acc / n - base)))
    check(f"fused E[dropout out] ~ base (mean_abs {err:.4f})", err < 0.02)

    # softmax_dropout keep fraction via uniform probabilities.
    logits = jnp.zeros((2, 2, 128, 128))
    p = softmax_dropout(logits, dropout_rate=0.1,
                        dropout_rng=rng, out_dtype=jnp.float32)
    # each kept element is (1/S)/(1-r); fraction kept ~ 1 - r
    kept = float(jnp.mean((p > 0).astype(jnp.float32)))
    check(f"softmax_dropout keep fraction {kept:.4f} ~ 0.9",
          abs(kept - 0.9) < 0.01)
    return 1 if failures else 0


if __name__ == "__main__":
    raise SystemExit(main())
