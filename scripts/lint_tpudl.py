#!/usr/bin/env python
"""tpudl static-analysis gate: concurrency + registry/metric linters
with a ratcheted baseline.

    python scripts/lint_tpudl.py              # gate the tree
    python scripts/lint_tpudl.py --json       # machine-readable findings
    python scripts/lint_tpudl.py --write-baseline   # re-baseline (ratchet!)
    python scripts/lint_tpudl.py --knob-table # print the env-knob table

Exit status: 0 when every finding is baselined (baselined + stale
entries still warn on stderr), 1 when NEW findings exist, 2 on
internal errors.

The ratchet: ``analysis_baseline.json`` (repo root) lists known-debt
fingerprints, each with a one-line justification. New findings fail
the gate — fix them or baseline them IN THE SAME PR, with a reason.
``--write-baseline`` preserves existing justifications and stamps new
entries ``TODO: justify``; a TODO in the checked-in baseline should
not survive review. Stale entries (debt that got paid) warn until
deleted.

Runs CPU-only and jax-free (pure AST), so it is cheap enough for
tier-1 (tests/test_analysis.py runs the same evaluation in-process)
and for scripts/ci_check.sh.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

REPO_ROOT = os.path.dirname(
    os.path.dirname(os.path.abspath(__file__))
)
sys.path.insert(0, REPO_ROOT)

DEFAULT_BASELINE = os.path.join(REPO_ROOT, "analysis_baseline.json")


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="tpudl static analysis: concurrency + registry "
        "linters with a ratcheted baseline"
    )
    ap.add_argument(
        "--baseline", default=DEFAULT_BASELINE,
        help="baseline JSON path (default: analysis_baseline.json)",
    )
    ap.add_argument(
        "--json", action="store_true",
        help="emit findings as JSON on stdout",
    )
    ap.add_argument(
        "--write-baseline", action="store_true",
        help="rewrite the baseline from the current findings "
        "(existing justifications preserved)",
    )
    ap.add_argument(
        "--knob-table", action="store_true",
        help="print the generated TPUDL_* env-knob markdown table "
        "and exit",
    )
    args = ap.parse_args(argv)

    from tpudl.analysis import findings as F
    from tpudl.analysis.lint import run_lint
    from tpudl.analysis.registry import knob_table_markdown

    if args.knob_table:
        print(knob_table_markdown(), end="")
        return 0

    found = run_lint(REPO_ROOT)

    if args.write_baseline:
        existing = (
            F.load_baseline(args.baseline)
            if os.path.exists(args.baseline) else {}
        )
        entries = []
        for finding in found:
            prior = existing.get(finding.fingerprint)
            entries.append(
                F.BaselineEntry.from_finding(
                    finding,
                    prior.justification if prior else "TODO: justify",
                )
            )
        F.save_baseline(args.baseline, entries)
        print(
            f"baselined {len(entries)} finding(s) -> {args.baseline}",
            file=sys.stderr,
        )
        return 0

    baseline = (
        F.load_baseline(args.baseline)
        if os.path.exists(args.baseline) else {}
    )
    result = F.apply_baseline(found, baseline)

    if args.json:
        print(json.dumps(
            {
                "new": [f.to_dict() for f in result.new],
                "baselined": [f.to_dict() for f in result.baselined],
                "stale": [e.fingerprint for e in result.stale],
            },
            indent=2,
        ))
    else:
        for finding in result.new:
            print(f"NEW  {finding.format()}")
        for finding in result.baselined:
            print(f"warn {finding.format()} (baselined)", file=sys.stderr)
    for entry in result.stale:
        print(
            f"warn stale baseline entry {entry.fingerprint} "
            f"({entry.rule} {entry.path} {entry.symbol}) — the debt "
            f"was paid, delete it",
            file=sys.stderr,
        )
    if result.new:
        print(
            f"\n{len(result.new)} new finding(s) — fix them or add "
            f"justified baseline entries (see --write-baseline)",
            file=sys.stderr,
        )
        return 1
    print(
        f"lint_tpudl: clean ({len(result.baselined)} baselined, "
        f"{len(result.stale)} stale)",
        file=sys.stderr,
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
