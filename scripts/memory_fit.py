"""Memory-fit report for configs[4] at its DECLARED scale — no allocation.

BASELINE.json configs[4] declares "Llama-3-8B LoRA fine-tune, FSDP->GSPMD
sharding on v5p-64". No 64-chip slice (or 8B of HBM) is needed to validate
that deployment: every per-device buffer size is a pure function of the
abstract parameter tree (``jax.eval_shape`` — zero bytes materialized),
the sharding rules (strategy_rules("lora") = LORA_RULES +
TP_TRANSFORMER_RULES, exactly what notebooks/nlp/finetune_lora.py trains
with), and the mesh shape (cfg.mesh.fit(64): dp=4, fsdp=8, tp=2 over 64
fake CPU devices). This script builds the real NamedShardings — including
the per-dimension divisibility clamping of tpudl.parallel.sharding — and
sums ``shard_shape`` bytes per device for:

- parameters (f32 masters; the frozen 8B base + LoRA adapters + head);
- AdamW moments — ONLY trainable (LoRA/head) leaves carry any, because
  lora_optimizer routes frozen leaves to set_to_zero (the memory win
  that makes 8B LoRA fit small meshes at all);
- peak activations at cfg.seq_len (2048), as a documented analytic
  UPPER BOUND for the per-layer-remat + flash-attention configuration
  the LoRA vertical runs (notebooks/nlp/finetune_lora.py): stored
  residual-stream inputs for every layer plus the live recompute /
  gradient working set of one block, batch sharded over (dp, fsdp) and
  projection dims over tp;
- the largest transient all-gathered kernel (fsdp gathers a full bf16
  copy of one layer's weight at a time).

Exit is nonzero if the total exceeds the fit bar (half of v5p HBM — the
other half is headroom for XLA temporaries, collectives buffers, and the
infeed), so this doubles as a CI guard. Run:

    python scripts/memory_fit.py            # v5p-64, llama3_8b_lora
    python scripts/memory_fit.py --devices 16 --hbm-gb 95 --json
"""

from __future__ import annotations

import argparse
import json
import math
import os
import pathlib
import sys

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parents[1]))


def _setup_fake_devices(n: int):
    flags = os.environ.get("XLA_FLAGS", "")
    if "--xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + f" --xla_force_host_platform_device_count={n}"
        ).strip()
    import jax

    jax.config.update("jax_platforms", "cpu")
    devices = jax.devices("cpu")
    if len(devices) < n:
        raise RuntimeError(
            f"need {n} fake CPU devices, got {len(devices)}; set XLA_FLAGS "
            f"before the first jax use"
        )
    return devices[:n]


def _tree_device_bytes(tree, shardings) -> int:
    """Per-device bytes of an abstract tree under NamedShardings: the sum
    of each leaf's shard_shape footprint (every device holds exactly one
    shard of every leaf; replicated leaves count full size)."""
    import jax

    total = 0
    for leaf, sh in zip(jax.tree.leaves(tree), jax.tree.leaves(shardings)):
        if not hasattr(leaf, "shape"):
            continue
        total += math.prod(sh.shard_shape(leaf.shape)) * leaf.dtype.itemsize
    return total


def activation_upper_bound_bytes(
    cfg_model, batch_per_device: int, seq_local: int, tp: int
) -> int:
    """Documented analytic UPPER BOUND on per-device activation bytes for
    one train step of the remat+flash Llama block stack (bf16 activations,
    2 bytes):

    - stored residuals: per-layer remat keeps each block's input
      [b, s, H] alive for the backward -> L * b * s * H;
    - live working set of the block being (re)computed + differentiated,
      with tp sharding the projection outputs: q/k/v/o + attention
      workspace ~= 4H/tp + GQA kv 2*(H*kv/H)/tp, gated MLP ~= 3I/tp,
      plus ~4H of residual/norm/gradient mirrors (unsharded by tp).
    Flash attention keeps no [S, S] term at any length.
    """
    H, I, L = (
        cfg_model.hidden_size,
        cfg_model.intermediate_size,
        cfg_model.num_layers,
    )
    kv_frac = cfg_model.num_kv_heads / cfg_model.num_heads
    stored = L * H
    live = (4 * H + 2 * H * kv_frac + 3 * I) / tp + 4 * H
    return int(batch_per_device * seq_local * (stored + live) * 2)


def report(config_name: str, n_devices: int, hbm_gb: float) -> dict:
    devices = _setup_fake_devices(n_devices)
    import jax
    import jax.numpy as jnp

    from tpudl.config import get_config
    from tpudl.models.lora import lora_optimizer, trainable_param_count
    from tpudl.models.registry import build_model
    from tpudl.parallel.sharding import strategy_rules, tree_shardings
    from tpudl.runtime.mesh import make_mesh
    from tpudl.train.optim import make_optimizer

    cfg = get_config(config_name)
    spec = cfg.mesh.fit(n_devices)
    mesh = make_mesh(spec, devices=devices)
    model = build_model(cfg.model, cfg.num_classes)
    rules = strategy_rules(cfg.strategy)

    params = jax.eval_shape(
        lambda k: model.init(k, jnp.zeros((1, cfg.seq_len), jnp.int32)),
        jax.random.key(0),
    )["params"]
    tx = lora_optimizer(make_optimizer(cfg.optim), params, ("classifier",))
    opt_state = jax.eval_shape(tx.init, params)

    p_bytes = _tree_device_bytes(params, tree_shardings(mesh, params, rules))
    o_bytes = _tree_device_bytes(
        opt_state, tree_shardings(mesh, opt_state, rules)
    )

    dp, fsdp, tp, sp = (
        mesh.shape["dp"],
        mesh.shape["fsdp"],
        mesh.shape["tp"],
        mesh.shape["sp"],
    )
    b_local = max(cfg.global_batch_size // (dp * fsdp), 1)
    a_bytes = activation_upper_bound_bytes(
        model.cfg, b_local, cfg.seq_len // sp, tp
    )
    # fsdp all-gathers one layer's kernels at a time; the largest single
    # gathered bf16 kernel is the transient to budget for.
    gather_bytes = 2 * max(
        math.prod(leaf.shape)
        for leaf in jax.tree.leaves(params)
        if hasattr(leaf, "shape") and len(leaf.shape) >= 2
    )

    trainable, total = trainable_param_count(params, ("classifier",))
    total_bytes = p_bytes + o_bytes + a_bytes + gather_bytes
    fit_bar = hbm_gb * 1e9 / 2  # half of HBM: the rest is XLA headroom
    out = {
        "config": cfg.name,
        "model": cfg.model,
        "devices": n_devices,
        "mesh": {k: int(v) for k, v in mesh.shape.items()},
        "global_batch": cfg.global_batch_size,
        "seq_len": cfg.seq_len,
        "params_total": total,
        "params_trainable": trainable,
        "bytes_per_device": {
            "params": p_bytes,
            "opt_moments": o_bytes,
            "activations_upper_bound": a_bytes,
            "largest_allgathered_kernel": gather_bytes,
            "total": total_bytes,
        },
        "hbm_bytes": int(hbm_gb * 1e9),
        "fit_bar_bytes": int(fit_bar),
        "fits": total_bytes < fit_bar,
    }
    return out


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--config", default="llama3_8b_lora")
    ap.add_argument("--devices", type=int, default=64,
                    help="slice size (default 64: the declared v5p-64)")
    ap.add_argument("--hbm-gb", type=float, default=95.0,
                    help="per-chip HBM (v5p: 95 GB)")
    ap.add_argument("--json", action="store_true")
    args = ap.parse_args(argv)

    out = report(args.config, args.devices, args.hbm_gb)
    if args.json:
        print(json.dumps(out))
    else:
        bb = out["bytes_per_device"]
        print(f"{out['config']} ({out['model']}) on {out['devices']} devices, "
              f"mesh {out['mesh']}")
        print(f"  params: {out['params_total'] / 1e9:.2f}B total, "
              f"{out['params_trainable'] / 1e6:.1f}M trainable (LoRA+head)")
        for k in ("params", "opt_moments", "activations_upper_bound",
                  "largest_allgathered_kernel", "total"):
            print(f"  {k:>28}: {bb[k] / 1e9:8.3f} GB/device")
        print(f"  fit bar (HBM/2): {out['fit_bar_bytes'] / 1e9:.1f} GB -> "
              f"{'FITS' if out['fits'] else 'DOES NOT FIT'}")
    return 0 if out["fits"] else 1


if __name__ == "__main__":
    sys.exit(main())
