"""Noise-aware benchmark regression gate over the BENCH_r*.json bank.

The BENCH_r05 postmortem (BASELINE.md "0.923 regression" row) showed
exactly how a naive ratio lies: comparing one draw of a ±20%
one-sided-noise metric against the MAX of four prior draws reads as a
regression almost always, with no code change. This gate encodes the
corrected protocol:

- the baseline for each metric is the **median of the banked
  same-protocol history** (single draws compared against the center of
  single draws, never against an order statistic);
- each metric carries a **noise band**: the larger of a per-metric
  floor (wide for the short-step relay-jittered ResNet-18 metric,
  tight for the 170 ms BERT steps) and half the relative spread the
  bank itself exhibits — the bank's own noise is evidence;
- a metric is a REGRESSION only when the current draw falls outside
  the band on the bad side (below ``median x (1 - band)`` for
  higher-is-better, above ``median x (1 + band)`` for
  lower-is-better), with at least ``min_history`` banked points.

Usage:

    python scripts/bench_regress.py CURRENT.json           # gate a run
    python scripts/bench_regress.py --current-json '{...}' # inline
    python scripts/bench_regress.py --self-test            # protocol test

Exit status: 0 when no metric regresses (advisory rows still print),
1 on a real regression, 2 on usage errors. ``bench.py`` runs the same
evaluation in-process after printing its JSON line (advisory by
default; ``bench.py --strict`` propagates the nonzero exit).

The self-test replays the r05 incident from the repo's own bank:
history r01-r04, current r05 — ResNet-18's 34,065 img/s MUST classify
as no-regression under this protocol (it sits above the banked
median), and a synthetic halved draw MUST still be caught.
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import sys
from typing import Dict, List, Optional

#: Protocol renames: historical keys folded onto one canonical metric
#: name, so a metric's history survives its key being renamed — but
#: ONLY where the measurement protocol stayed commensurable
#: (best-of-windows draws of the same workload).
ALIASES = {
    "resnet18_cifar10_train_throughput": "resnet18_images_per_sec_chip",
    "resnet18_images_per_sec_chip_best_of_windows":
        "resnet18_images_per_sec_chip",
    "bert_base_sst2_train_throughput": "bert_base_samples_per_sec_chip",
}

#: Per-metric noise-band floors (fraction of the baseline median).
#: resnet18: the BASELINE.md-documented ±20% one-sided ambient relay
#: drift on 9 ms steps (25.1k-36.9k same code, same day) — anything
#: tighter re-creates the r05 false alarm. Default floor 8%: the BERT
#: metrics hold ±1.5% but ratio bases move a few percent round to
#: round (recompiles, jax upgrades).
NOISE_BAND_FLOORS = {
    "resnet18_images_per_sec_chip": 0.25,
    "serve_tokens_per_sec": 0.20,
    "serve_p99_ttft_ms": 0.50,
    # Router sweep rides threads on 1 vCPU in the container: scheduler
    # jitter moves the routed throughput more than the engine's.
    "serve_tokens_per_sec_2rep": 0.25,
    "serve_scaling_efficiency": 0.15,
    # Deterministic byte accounting (cache layout arithmetic, not a
    # timing draw): any drift beyond rounding is a real layout change.
    "serve_kv_slots_per_gb": 0.05,
    # Parity-grid keys (benchmarks/parity_grid.py, banked from r06).
    # TPOT rides the simulated-device sleep + host dispatch on 1 vCPU;
    # the bytes ratio is arithmetic; cells_passed only moves when a
    # cell is added or breaks — a drop of even one cell must gate.
    "serve_tpot_int8_weights_ms": 0.50,
    "quant_weight_bytes_ratio": 0.05,
    "parity_grid_cells_passed": 0.01,
    "input_pipeline_images_per_sec_host": 0.20,
    "checkpoint_step_stall_ms": 0.50,
    "checkpoint_sync_save_ms": 0.50,
    "recovery_time_sec": 0.50,
    "step_dispatch_overhead_ms": 1.00,
    # Fleet-tier keys (benchmarks/serve_load.py --autoscale, banked
    # from r06). Recovery rides SLO window drains + thread scheduling
    # on 1 vCPU; the scrape is two localhost HTTP round trips whose
    # tail the container's scheduler owns.
    "autoscale_recovery_s": 0.60,
    "fleet_scrape_overhead_ms": 0.60,
    # Prefix-sharing + speculative keys (benchmarks/serve_load.py
    # --prefix/--spec, banked from r07). TTFT rides simulated prefill
    # sleeps queued across slots (scheduler-owned tail on 1 vCPU);
    # acceptance is a near-deterministic property of the int8
    # self-draft (greedy agreement), so a real drop means the draft or
    # the acceptance rule changed; spec tokens/sec rides the sim
    # device + host dispatch mix.
    "serve_ttft_shared_prefix_ms": 0.50,
    "spec_accepted_tokens_per_step": 0.15,
    "serve_tokens_per_sec_spec": 0.30,
    # Dispatch-hygiene count (tpudl.analysis wired into serve_load's
    # steady state, banked from r07): expected EXACTLY 0 — it is a
    # count of silent regressions, not a timing draw, so it gates
    # zero-tolerance (see ZERO_TOLERANCE below).
    "serve_steady_state_recompiles": 0.01,
    # Multi-tenant LoRA keys (benchmarks/serve_load.py --tenants,
    # banked from r09). Adapters-per-GB is pool-layout arithmetic
    # (deterministic like the KV capacity key); batched tokens/sec
    # rides the sim device + host dispatch mix at 8 slots on 1 vCPU;
    # the isolation ratio is a ratio of two p99 tails of
    # scheduler-owned TTFTs, so its band stays wide (the in-benchmark
    # 1.3x assertion is the real gate).
    "serve_adapters_per_gb": 0.05,
    "serve_tokens_per_sec_64adapters": 0.30,
    "serve_tenant_isolation_p99_ratio": 0.50,
    # Serving fault-tolerance keys (benchmarks/serve_load.py --chaos,
    # banked from r08). Both ride command-pickup latency on the
    # replica loop thread: on 1 vCPU the scheduler owns their tail
    # (the drain races a simulated-device generation; the gap is one
    # loop hand-off plus a decode step), so the bands stay wide.
    "serve_drain_p99_ms": 0.60,
    "failover_token_gap_ms": 0.60,
    # Mixed-precision training keys (benchmarks/train_precision.py +
    # the bf16-policy BERT variant, banked from r09). The bytes ratio
    # is pure arithmetic over the rule-class sites (drift = the rules
    # stopped matching); the parity cell count only moves when a cell
    # is added or a band breaks — one lost cell must gate; the bf16
    # MFU variant rides the same relay jitter as the headline BERT
    # metrics.
    "train_fp8_bytes_ratio": 0.05,
    "train_precision_parity_cells": 0.01,
    "bert_base_mfu_bf16": 0.10,
    # Durable request-log keys (benchmarks/serve_load.py, banked from
    # r16). The overhead ratio is two p99 TTFT tails of the same
    # scheduler-owned closed loop (writer thread adds a contender on
    # 1 vCPU), so its band stays wide; bytes-per-request is compact-JSON
    # record arithmetic over a fixed request mix — near-deterministic,
    # drift means the schema or the mix changed.
    "requestlog_overhead_p99_ttft_ratio": 0.50,
    "requestlog_bytes_per_request": 0.08,
    # Data-flywheel keys (benchmarks/serve_load.py, banked from r18).
    # Refresh latency is a handful of tiny train steps plus a pool
    # register on a 1-vCPU host that is also paging XLA programs —
    # scheduler jitter dominates a sub-100ms wall time. The impact
    # ratio is two p99 TTFT tails of the same closed loop (the
    # requestlog overhead band's shape, plus sample capture), so it
    # inherits the same wide band.
    "flywheel_refresh_latency_s": 0.60,
    "flywheel_serving_p99_impact_ratio": 0.50,
    # Pod-real fleet keys (benchmarks/fleet_mesh.py subprocess, banked
    # from r19). Reshard-restore is host-array device_put over 8 fake
    # devices on 1 vCPU (scheduler-owned); the payload MB is pure
    # arithmetic (drift = the template changed); the 2-mesh routed
    # throughput rides emulated collectives + thread hand-offs, wider
    # than the 2rep thread-replica band; burn-cleared wall time is
    # dominated by the borrowed replica's serving-program compiles,
    # which vary with XLA's own scheduling on a loaded host.
    "fleet_reshard_restore_s": 0.60,
    "fleet_reshard_payload_mb": 0.05,
    "serve_tokens_per_sec_2mesh": 0.30,
    "chipmover_burn_cleared_s": 0.60,
}
DEFAULT_BAND_FLOOR = 0.08

#: Metrics where smaller is better (latency/stall/recovery); every
#: other numeric metric is treated as higher-is-better throughput/MFU.
LOWER_IS_BETTER = {
    "serve_p99_ttft_ms",
    "serve_tpot_int8_weights_ms",
    "checkpoint_step_stall_ms",
    "checkpoint_sync_save_ms",
    "recovery_time_sec",
    "step_dispatch_overhead_ms",
    "autoscale_recovery_s",
    "fleet_scrape_overhead_ms",
    "serve_ttft_shared_prefix_ms",
    "serve_steady_state_recompiles",
    "serve_drain_p99_ms",
    "failover_token_gap_ms",
    "serve_tenant_isolation_p99_ratio",
    "requestlog_overhead_p99_ttft_ratio",
    "requestlog_bytes_per_request",
    "flywheel_refresh_latency_s",
    "flywheel_serving_p99_impact_ratio",
    "fleet_reshard_restore_s",
    "chipmover_burn_cleared_s",
}

#: Lower-is-better metrics whose banked baseline is 0 and must STAY 0:
#: the ratio protocol divides by the median and goes silent on a zero
#: baseline, so these gate on the absolute value instead — any
#: positive draw is a regression regardless of bands.
ZERO_TOLERANCE = {
    "serve_steady_state_recompiles",
}

#: Non-measurement keys in a bench line: identifiers, config echoes,
#: and ratios whose baselines are already re-derived here.
_SKIP_KEYS = {"metric", "unit", "bert_batch"}


def normalize_round(obj: dict) -> Dict[str, float]:
    """One BENCH_r*.json (or a bench.py output line) -> canonical
    ``{metric: value}``. The headline ``value`` is keyed under the
    line's ``metric`` name; ``vs_*`` ratio fields are dropped (their
    denominators are exactly the protocol this gate replaces)."""
    parsed = obj.get("parsed", obj)
    out: Dict[str, float] = {}
    for key, value in parsed.items():
        if key in _SKIP_KEYS or "vs_" in key:
            continue
        if key == "value":
            key = parsed.get("metric", "value")
        if not isinstance(value, (int, float)) or isinstance(value, bool):
            continue
        out[ALIASES.get(key, key)] = float(value)
    return out


def load_round(path: str) -> Dict[str, float]:
    with open(path) as f:
        return normalize_round(json.load(f))


def _median(vals: List[float]) -> float:
    vals = sorted(vals)
    n = len(vals)
    mid = n // 2
    return vals[mid] if n % 2 else 0.5 * (vals[mid - 1] + vals[mid])


def noise_band(metric: str, history: List[float]) -> float:
    """The metric's tolerance: max(per-metric floor, half the relative
    spread of its own bank) — a bank that scattered 20% peak-to-peak
    testifies to >= 10% one-draw noise regardless of the floor."""
    floor = NOISE_BAND_FLOORS.get(metric, DEFAULT_BAND_FLOOR)
    med = _median(history)
    if med == 0:
        return floor
    spread = (max(history) - min(history)) / abs(med)
    return max(floor, spread / 2.0)


def evaluate_regressions(
    current: Dict[str, float],
    history_rounds: List[Dict[str, float]],
    min_history: int = 2,
) -> List[dict]:
    """Classify every current metric against the banked history.

    Returns one row per metric: ``status`` is ``regression`` /
    ``improved`` / ``ok`` / ``no-baseline`` (fewer than
    ``min_history`` banked draws — advisory only, never gating)."""
    rows: List[dict] = []
    for metric in sorted(current):
        value = current[metric]
        hist = [
            r[metric] for r in history_rounds
            if metric in r and r[metric] is not None
        ]
        if len(hist) < min_history:
            rows.append({
                "metric": metric, "value": value, "baseline": None,
                "band": None, "ratio": None, "status": "no-baseline",
                "n_history": len(hist),
            })
            continue
        baseline = _median(hist)
        band = noise_band(metric, hist)
        ratio = value / baseline if baseline else None
        lower_better = metric in LOWER_IS_BETTER
        status = "ok"
        if metric in ZERO_TOLERANCE and baseline == 0:
            # value/0 has no ratio: gate the count absolutely.
            status = "regression" if value > 0 else "ok"
        elif ratio is not None:
            if lower_better:
                if ratio > 1.0 + band:
                    status = "regression"
                elif ratio < 1.0 - band:
                    status = "improved"
            else:
                if ratio < 1.0 - band:
                    status = "regression"
                elif ratio > 1.0 + band:
                    status = "improved"
        rows.append({
            "metric": metric, "value": value, "baseline": baseline,
            "band": band, "ratio": ratio, "status": status,
            "n_history": len(hist),
        })
    return rows


def format_rows(rows: List[dict]) -> str:
    lines = [
        f"{'metric':44} {'value':>12} {'baseline':>12} {'band':>6} "
        f"{'ratio':>7}  status",
    ]
    for r in rows:
        base = f"{r['baseline']:12.2f}" if r["baseline"] is not None else (
            f"{'—':>12}"
        )
        band = f"{r['band']:6.2f}" if r["band"] is not None else f"{'—':>6}"
        ratio = f"{r['ratio']:7.3f}" if r["ratio"] is not None else (
            f"{'—':>7}"
        )
        flag = r["status"].upper() if r["status"] == "regression" else (
            r["status"]
        )
        lines.append(
            f"{r['metric']:44} {r['value']:12.2f} {base} {band} {ratio}"
            f"  {flag}"
        )
    return "\n".join(lines)


def default_history_paths(root: Optional[str] = None) -> List[str]:
    root = root or os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    return sorted(glob.glob(os.path.join(root, "BENCH_r*.json")))


def gate(
    current: Dict[str, float],
    history_paths: List[str],
    min_history: int = 2,
) -> List[dict]:
    history = [load_round(p) for p in history_paths]
    return evaluate_regressions(current, history, min_history=min_history)


# ---------------------------------------------------------------------------
# Self-test: the protocol's acceptance case IS the r05 incident.
# ---------------------------------------------------------------------------


def self_test(root: Optional[str] = None) -> int:
    paths = default_history_paths(root)
    by_name = {os.path.basename(p): p for p in paths}
    need = [f"BENCH_r0{i}.json" for i in range(1, 6)]
    missing = [n for n in need if n not in by_name]
    if missing:
        print(f"self-test needs {missing} in the repo root", file=sys.stderr)
        return 2
    history = [load_round(by_name[n]) for n in need[:4]]
    r05 = load_round(by_name["BENCH_r05.json"])
    rows = evaluate_regressions(r05, history)
    by_metric = {r["metric"]: r for r in rows}

    resnet = by_metric["resnet18_images_per_sec_chip"]
    assert resnet["status"] != "regression", (
        "the r05 ResNet-18 draw (34,065 img/s vs a banked median "
        f"{resnet['baseline']:.0f}) must classify as NO-regression — "
        "re-creating the max-of-bank false alarm the protocol exists "
        f"to prevent: {resnet}"
    )
    assert by_metric["bert_base_samples_per_sec_chip"]["status"] != (
        "regression"
    ), by_metric["bert_base_samples_per_sec_chip"]

    # And the gate still has teeth: a genuinely halved ResNet draw is
    # outside ANY honest noise band.
    broken = dict(r05)
    broken["resnet18_images_per_sec_chip"] *= 0.5
    rows2 = evaluate_regressions(broken, history)
    bad = {r["metric"]: r for r in rows2}["resnet18_images_per_sec_chip"]
    assert bad["status"] == "regression", bad

    # Lower-is-better direction: a doubled latency regresses, a halved
    # one improves.
    lat_hist = [{"serve_p99_ttft_ms": v} for v in (100.0, 110.0, 105.0)]
    worse = evaluate_regressions({"serve_p99_ttft_ms": 220.0}, lat_hist)
    assert worse[0]["status"] == "regression", worse
    better = evaluate_regressions({"serve_p99_ttft_ms": 40.0}, lat_hist)
    assert better[0]["status"] == "improved", better

    print("bench_regress self-test: OK (r05 classifies as no-regression; "
          "a halved draw still gates)")
    return 0


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        description="Noise-aware regression gate over the BENCH_r*.json "
        "bank (median-of-bank baselines, per-metric noise bands)"
    )
    ap.add_argument("current", nargs="?",
                    help="bench output JSON file to gate ('-' = stdin)")
    ap.add_argument("--current-json", help="inline JSON instead of a file")
    ap.add_argument("--history", nargs="*",
                    help="banked BENCH_r*.json files (default: the repo "
                    "root's)")
    ap.add_argument("--min-history", type=int, default=2,
                    help="banked draws required before a metric gates")
    ap.add_argument("--json", action="store_true")
    ap.add_argument("--self-test", action="store_true",
                    help="assert the r05 protocol case and exit")
    args = ap.parse_args(argv)

    if args.self_test:
        return self_test()

    if args.current_json:
        current_obj = json.loads(args.current_json)
    elif args.current == "-":
        current_obj = json.loads(sys.stdin.read())
    elif args.current:
        with open(args.current) as f:
            current_obj = json.load(f)
    else:
        ap.error("need a CURRENT json file, '-', or --current-json")
        return 2

    history_paths = (
        args.history if args.history else default_history_paths()
    )
    rows = gate(
        normalize_round(current_obj), history_paths,
        min_history=args.min_history,
    )
    print(json.dumps(rows) if args.json else format_rows(rows))
    regressions = [r for r in rows if r["status"] == "regression"]
    if regressions:
        print(
            f"REGRESSION: {', '.join(r['metric'] for r in regressions)}",
            file=sys.stderr,
        )
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
