"""Native C++ augmentation kernel vs the numpy fallback (tpudl.data.augment).

The backend-parity strategy mirrors the repo's cross-backend parity
doctrine (SURVEY.md §3.3): same inputs, same random draws, two
implementations, outputs compared numerically.
"""

import numpy as np
import pytest

from tpudl.data.augment import (
    CIFAR10_MEAN,
    CIFAR10_STD,
    BatchAugmenter,
    _augment_numpy,
    _normalize_numpy,
)
from tpudl.native import load_library

N, H, W, C = 16, 32, 32, 3


def _images(seed=0):
    return np.random.default_rng(seed).integers(
        0, 256, size=(N, H, W, C), dtype=np.uint8
    )


def test_numpy_geometry_no_pad_no_flip_identity():
    """With pad=0, full-size crop, no flip, the transform is pure
    normalization."""
    imgs = _images()
    offsets = np.zeros((N, 2), np.int32)
    flip = np.zeros(N, np.uint8)
    mean = np.asarray(CIFAR10_MEAN, np.float32)
    std = np.asarray(CIFAR10_STD, np.float32)
    out = _augment_numpy(imgs, 0, H, W, offsets, flip, mean, std)
    expected = (imgs.astype(np.float32) / 255.0 - mean) / std
    np.testing.assert_allclose(out, expected, atol=1e-5)


def test_numpy_flip_mirrors_columns():
    imgs = _images()
    offsets = np.zeros((N, 2), np.int32)
    mean = np.zeros(3, np.float32)
    std = np.ones(3, np.float32)
    out_f = _augment_numpy(
        imgs, 0, H, W, offsets, np.ones(N, np.uint8), mean, std
    )
    out = _augment_numpy(
        imgs, 0, H, W, offsets, np.zeros(N, np.uint8), mean, std
    )
    np.testing.assert_allclose(out_f, out[:, :, ::-1, :], atol=0)


def test_numpy_padding_is_zero_pixels():
    """Offset (0, 0) with pad=4 exposes 4 rows/cols of zero padding."""
    imgs = _images()
    mean = np.zeros(3, np.float32)
    std = np.ones(3, np.float32)
    out = _augment_numpy(
        imgs, 4, H, W, np.zeros((N, 2), np.int32), np.zeros(N, np.uint8),
        mean, std,
    )
    np.testing.assert_allclose(out[:, :4, :, :], 0.0, atol=0)
    np.testing.assert_allclose(out[:, :, :4, :], 0.0, atol=0)
    np.testing.assert_allclose(
        out[:, 4:, 4:, :],
        imgs[:, : H - 4, : W - 4, :].astype(np.float32) / 255.0,
        atol=1e-6,
    )


@pytest.mark.skipif(load_library() is None, reason="no native toolchain")
class TestNativeParity:
    def test_augment_matches_numpy(self):
        imgs = _images(1)
        rng = np.random.default_rng(7)
        pad, ch, cw = 4, 32, 32
        offsets = np.stack(
            [rng.integers(0, 9, N), rng.integers(0, 9, N)], axis=1
        ).astype(np.int32)
        flip = (rng.random(N) < 0.5).astype(np.uint8)
        mean = np.asarray(CIFAR10_MEAN, np.float32)
        std = np.asarray(CIFAR10_STD, np.float32)

        expected = _augment_numpy(imgs, pad, ch, cw, offsets, flip, mean, std)

        import ctypes

        lib = load_library()
        out = np.empty((N, ch, cw, C), np.float32)
        lib.tpudl_augment_batch(
            imgs.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
            N, H, W, C, pad, ch, cw,
            offsets.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
            flip.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
            mean.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
            std.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
            out.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
        )
        np.testing.assert_allclose(out, expected, atol=1e-6)

    def test_augmenter_backends_agree_end_to_end(self):
        """Same seed => same random draws => same output either backend."""
        imgs = _images(2)
        a_native = BatchAugmenter(seed=3, backend="native")
        a_numpy = BatchAugmenter(seed=3, backend="numpy")
        assert a_native.backend == "native"
        assert a_numpy.backend == "numpy"
        out_n = a_native({"image": imgs, "label": np.arange(N)})
        out_p = a_numpy({"image": imgs, "label": np.arange(N)})
        np.testing.assert_allclose(out_n["image"], out_p["image"], atol=1e-6)
        np.testing.assert_array_equal(out_n["label"], np.arange(N))
        assert out_n["image"].dtype == np.float32

    def test_center_crop_eval_path(self):
        imgs = _images(3)
        a = BatchAugmenter(
            crop=(24, 24), train=False, backend="native",
        )
        expected = _normalize_numpy(
            imgs, 24, 24,
            np.asarray(CIFAR10_MEAN, np.float32),
            np.asarray(CIFAR10_STD, np.float32),
        )
        np.testing.assert_allclose(a(imgs), expected, atol=1e-6)


def test_augmenter_through_converter(tmp_path):
    """transform= hook: the converter yields augmented f32 batches."""
    from tpudl.data.datasets import materialize_cifar10_like

    conv = materialize_cifar10_like(
        str(tmp_path), num_rows=256, rows_per_file=128
    )
    aug = BatchAugmenter(seed=0, backend="auto")
    it = conv.make_batch_iterator(
        batch_size=64,
        shard_index=0,
        num_shards=1,
        transform=aug,
    )
    batch = next(it)
    assert batch["image"].dtype == np.float32
    assert batch["image"].shape == (64, 32, 32, 3)
    # Normalized stats: roughly zero-mean, unit-ish variance.
    assert abs(float(batch["image"].mean())) < 1.0
    assert 0.2 < float(batch["image"].std()) < 3.0


def test_wide_channel_images_take_numpy_path():
    """The native kernel caps at 16 channels; wider images must fall back
    (not read uninitialized memory)."""
    imgs = np.random.default_rng(0).integers(
        0, 256, size=(4, 8, 8, 32), dtype=np.uint8
    )
    mean = tuple([0.5] * 32)
    std = tuple([0.5] * 32)
    a_auto = BatchAugmenter(
        crop=(8, 8), pad=2, seed=5, mean=mean, std=std, backend="auto"
    )
    a_np = BatchAugmenter(
        crop=(8, 8), pad=2, seed=5, mean=mean, std=std, backend="numpy"
    )
    np.testing.assert_allclose(a_auto(imgs), a_np(imgs), atol=0)


def test_augmenter_rejects_bad_input():
    with pytest.raises(ValueError, match="uint8"):
        BatchAugmenter(backend="numpy")(np.zeros((2, 32, 32, 3), np.float32))
    with pytest.raises(ValueError, match="channels"):
        BatchAugmenter(backend="numpy", mean=(0.5,), std=(0.5,))(
            np.zeros((2, 32, 32, 3), np.uint8)
        )

def test_device_normalize_matches_host_normalize():
    """uint8 host crop/flip + device_normalize == the host-normalized f32
    path bit-for-tolerance — the two placements must train identically
    (the device path ships 4x fewer bytes over the host->device link)."""
    import jax

    from tpudl.data.augment import BatchAugmenter, device_normalize

    rng = np.random.default_rng(3)
    images = rng.integers(0, 256, size=(8, 40, 40, 3)).astype(np.uint8)
    batch = {"image": images, "label": np.arange(8)}

    host = BatchAugmenter(crop=(32, 32), pad=4, seed=7, backend="numpy")
    dev = BatchAugmenter(crop=(32, 32), pad=4, seed=7, backend="numpy",
                         normalize=False)
    want = host(dict(batch))["image"]
    raw = dev(dict(batch))["image"]
    assert raw.dtype == np.uint8
    got = np.asarray(
        jax.jit(device_normalize())({"image": raw, "label": batch["label"]})[
            "image"
        ]
    )
    np.testing.assert_allclose(got, want, atol=1e-6)

    # eval (center-crop) path too
    host_e = BatchAugmenter(crop=(32, 32), pad=0, hflip=False, train=False,
                            backend="numpy")
    dev_e = BatchAugmenter(crop=(32, 32), pad=0, hflip=False, train=False,
                           backend="numpy", normalize=False)
    want_e = host_e(dict(batch))["image"]
    raw_e = dev_e(dict(batch))["image"]
    assert raw_e.dtype == np.uint8
    got_e = np.asarray(
        jax.jit(device_normalize())(
            {"image": raw_e, "label": batch["label"]}
        )["image"]
    )
    np.testing.assert_allclose(got_e, want_e, atol=1e-6)
