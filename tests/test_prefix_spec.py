"""Prefix-sharing radix KV cache + speculative decoding (ISSUE 11).

Two correctness bars on top of test_serve's:

- a request seated against a CACHED prefix produces byte-identical
  tokens to a cold ``generate()`` run (exact-mode parity — sharing is
  an addressing trick, never a numerics change), with refcounts, COW
  splits, LRU eviction, and hash-collision safety asserted at the
  radix-tree level;
- a speculating engine passes teacher-forced margin-mode parity, and a
  slot whose whole proposal window is REJECTED continues decoding with
  state identical to never having speculated (the rollback regression
  — driven hard by a garbage draft that disagrees with the target
  almost everywhere).
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpudl.models.generate import generate
from tpudl.models.llama import LLAMA_TINY, LlamaForCausalLM
from tpudl.obs import registry
from tpudl.serve import (
    PagedKVCache,
    RadixPrefixTree,
    Request,
    ServeSession,
    assert_serving_parity,
)

CFG = LLAMA_TINY(dtype=jnp.float32, max_seq_len=96)
PROMPT_LEN = 16
PAGE = 4


@pytest.fixture(scope="module")
def model_and_params():
    model = LlamaForCausalLM(CFG)
    params = model.init(
        jax.random.key(0), jnp.zeros((1, PROMPT_LEN), jnp.int32)
    )["params"]
    return model, params


def _session(model, params, **kw):
    kw.setdefault("prompt_len", PROMPT_LEN)
    kw.setdefault("num_slots", 2)
    kw.setdefault("paged", True)
    kw.setdefault("page_size", PAGE)
    return ServeSession.from_model(model, params, **kw)


def _shared_requests(n, shared_tokens=12, seed=0, max_new=8, tag="r"):
    rng = np.random.default_rng(seed)
    shared = rng.integers(1, CFG.vocab_size, size=shared_tokens).tolist()
    return [
        Request(
            f"{tag}{i}",
            shared + rng.integers(
                1, CFG.vocab_size,
                size=int(rng.integers(1, PROMPT_LEN - shared_tokens + 1)),
            ).tolist(),
            max_new_tokens=max_new,
        )
        for i in range(n)
    ]


# ---------------------------------------------------------------------------
# Radix tree units
# ---------------------------------------------------------------------------


def test_radix_insert_and_match():
    tree = RadixPrefixTree(PAGE)
    ids = list(range(100, 116))  # 4 full blocks
    assert tree.match_len(ids) == 0
    node = tree.insert_suffix(None, tree.blocks_of(ids), [5, 6, 7, 8])
    assert tree.match_len(ids) == 16
    # Page-granular: a 9-token prefix matches only 2 full blocks.
    assert tree.match_len(ids[:9]) == 8
    assert tree.match_len([1, 2, 3]) == 0  # sub-page prompts never match
    tree.release(node)


def test_radix_cow_split():
    tree = RadixPrefixTree(PAGE)
    ab = list(range(100, 116))
    node = tree.insert_suffix(None, tree.blocks_of(ab), [5, 6, 7, 8])
    tree.release(node)
    # Diverge after 2 blocks: the compressed edge splits; the shared
    # half keeps pages [5, 6], both continuations live below it.
    ac = ab[:8] + [7] * 8
    pages, deepest = tree.match_and_lease(ac)
    assert pages == [5, 6]
    assert tree.stats()["splits"] == 1
    new = tree.insert_suffix(deepest, tree.blocks_of(ac)[2:], [10, 11])
    assert tree.match_len(ab) == 16  # the original path survived the split
    assert tree.match_len(ac) == 16
    tree.release(new)
    assert tree.stats()["nodes"] == 3  # shared half + two tails


def test_radix_split_refcount_accounting():
    """A split inserts an ancestor ABOVE an already-leased node; the
    later release must unpin both halves exactly once (regression for
    the path-walking lease contract)."""
    tree = RadixPrefixTree(PAGE)
    ab = list(range(100, 116))
    lease_ab = tree.insert_suffix(None, tree.blocks_of(ab), [5, 6, 7, 8])
    # Second prompt splits the edge WHILE the first lease is alive.
    pages, lease_ac = tree.match_and_lease(ab[:8] + [9] * 8)
    assert pages == [5, 6]
    tree.release(lease_ac)
    assert tree.evictable_pages == 0  # ab's lease still pins everything
    tree.release(lease_ab)
    assert tree.evictable_pages == 4  # every page reclaimable now


def test_radix_lru_eviction():
    tree = RadixPrefixTree(PAGE)
    a = tree.insert_suffix(None, tree.blocks_of([1] * 8), [2, 3])
    b = tree.insert_suffix(None, tree.blocks_of([2] * 8), [4, 9])
    tree.release(a)
    tree.release(b)
    # Touch a: b becomes the LRU victim.
    _, lease = tree.match_and_lease([1] * 8)
    tree.release(lease)
    assert sorted(tree.evict(2)) == [4, 9]
    assert tree.match_len([2] * 8) == 0
    assert tree.match_len([1] * 8) == 8
    # A leased node is never evictable, whatever the pressure.
    _, lease = tree.match_and_lease([1] * 8)
    assert tree.evict(10) == []
    tree.release(lease)


def test_radix_hash_collision_safety(monkeypatch):
    """Force every block hash to collide: matching must still resolve
    by FULL token-block compare — hash-only matching would hand a
    different prompt another request's KV pages."""
    import tpudl.serve.cache as cache_mod

    monkeypatch.setattr(cache_mod, "block_hash", lambda block: 7)
    tree = RadixPrefixTree(PAGE)
    n1 = tree.insert_suffix(None, tree.blocks_of([1] * 8), [2, 3])
    n2 = tree.insert_suffix(None, tree.blocks_of([9] * 8), [4, 5])
    assert tree.match_len([1] * 8) == 8
    assert tree.match_len([9] * 8) == 8
    assert tree.match_len([3] * 8) == 0
    pages, lease = tree.match_and_lease([9] * 8)
    assert pages == [4, 5]
    tree.release(lease)
    tree.release(n1)
    tree.release(n2)
    # Eviction under collisions detaches the right sibling.
    freed = tree.evict(10)
    assert sorted(freed) == [2, 3, 4, 5]


# ---------------------------------------------------------------------------
# Cache-level sharing
# ---------------------------------------------------------------------------


def _paged_template(num_slots=2, seq=32, hkv=2, hd=4):
    return {"layers_0": {"attn": {
        "k": jax.ShapeDtypeStruct((num_slots, seq, hkv, hd), jnp.float32),
        "v": jax.ShapeDtypeStruct((num_slots, seq, hkv, hd), jnp.float32),
        "valid": jax.ShapeDtypeStruct((num_slots, seq), jnp.bool_),
        "index": jax.ShapeDtypeStruct((), jnp.int32),
    }}}


def _paged_row(seq=32, hkv=2, hd=4, seed=0):
    rng = np.random.default_rng(seed)
    return {"layers_0": {"attn": {
        "k": jnp.asarray(rng.normal(size=(1, seq, hkv, hd)), jnp.float32),
        "v": jnp.asarray(rng.normal(size=(1, seq, hkv, hd)), jnp.float32),
        "valid": jnp.ones((1, seq), jnp.bool_),
        "index": jnp.int32(8),
    }}}


def test_seat_shared_counts_only_new_pages():
    cache = PagedKVCache(_paged_template(), page_size=PAGE,
                         prefix_share=True)
    ids = list(range(100, 110))  # 10 tokens: 2 full blocks + tail
    row = _paged_row()
    free0 = cache.free_pages
    cache.seat_shared(row, 0, ids, reserve_tokens=16,
                      lease=cache.match_and_lease(ids))
    assert free0 - cache.free_pages == 4  # all 4 pages new, cold seat
    # Same prefix, different tail: only the 2 unshared pages allocate.
    ids2 = ids[:8] + [7, 7, 7]
    lease2 = cache.match_and_lease(ids2)
    assert len(lease2[0]) == 2
    free1 = cache.free_pages
    cache.seat_shared(_paged_row(seed=1), 1, ids2, reserve_tokens=15,
                      lease=lease2)
    assert free1 - cache.free_pages == 2
    # COW: both slots map the SAME physical prefix pages.
    assert list(cache.page_table[0][:2]) == list(cache.page_table[1][:2])
    assert (cache.start[1], cache.lens[1]) == (0, 11)  # left-aligned
    # free(): private pages return, tree pages stay cached/evictable.
    cache.free(0)
    cache.free(1)
    assert cache.radix.evictable_pages == 2
    assert cache.available_pages == cache.num_pages - 1


def test_seat_shared_gather_round_trip():
    """Pages -> dense prefix rows reproduces the seated row bytes (the
    input the chunked suffix prefill resumes from)."""
    cache = PagedKVCache(_paged_template(), page_size=PAGE,
                         prefix_share=True)
    ids = list(range(100, 112))
    row = _paged_row(seed=3)
    cache.seat_shared(row, 0, ids, reserve_tokens=16,
                      lease=cache.match_and_lease(ids))
    pages, lease = cache.match_and_lease(ids)
    rows = cache.gather_prefix_rows(pages, 12)
    attn = rows["layers_0"]["attn"]
    np.testing.assert_array_equal(
        np.asarray(attn["k"][0, :12]),
        np.asarray(row["layers_0"]["attn"]["k"][0, :12]),
    )
    assert int(attn["index"]) == 12
    assert np.asarray(attn["valid"]).sum() == 12
    cache.release_lease(lease[1] if isinstance(lease, tuple) else lease)


def test_fits_request_pinned_matched_pages_not_double_counted():
    """Admission must not count a matched prefix's refcount-0 pages
    BOTH as mapped-for-free and as reclaimable: seating pins them
    first, so they cannot also satisfy the remaining allocation
    (regression — the old predicate admitted requests seat_shared then
    crashed on with 'page pool exhausted')."""
    cache = PagedKVCache(_paged_template(seq=32), page_size=PAGE,
                         num_pages=10, prefix_share=True)
    prefix = list(range(100, 108))  # 2 full blocks
    # A seats (2 tree pages + 1 private), B fills most of the pool,
    # then A frees: free pool = 1 page, A's prefix cached evictable.
    cache.seat_shared(_paged_row(), 0, prefix, reserve_tokens=12,
                      lease=cache.match_and_lease(prefix))
    other = [9] * 8
    cache.seat_shared(_paged_row(seed=1), 1, other, reserve_tokens=24,
                      lease=cache.match_and_lease(other))
    cache.free(0)
    assert cache.free_pages == 1 and cache.radix.evictable_pages == 2
    # 12 tokens = 3 pages - 2 matched = 1 new <= 1 free: seatable.
    assert cache.fits_request(prefix, 12)
    # 16 tokens = 4 pages - 2 matched = 2 new, but the only evictable
    # pages ARE the matched ones (pinned at seat): must be denied.
    assert not cache.fits_request(prefix, 16)
    # Sanity: the admitted shape actually seats.
    cache.seat_shared(_paged_row(seed=2), 0, prefix, reserve_tokens=12,
                      lease=cache.match_and_lease(prefix))


def test_prefix_share_rejects_pad_aligned_seat():
    cache = PagedKVCache(_paged_template(), page_size=PAGE,
                         prefix_share=True)
    with pytest.raises(ValueError, match="seat_shared"):
        cache.seat(_paged_row(), 0, pad=2, prompt_len=8, reserve_tokens=8)


# ---------------------------------------------------------------------------
# Engine-level sharing: the exact-parity acceptance bar
# ---------------------------------------------------------------------------


def test_shared_prefix_exact_parity(model_and_params):
    """Requests seated against a cached prefix produce BYTE-IDENTICAL
    tokens to cold generate() runs (exact-mode assert_serving_parity),
    while the radix cache demonstrably served prefix tokens."""
    model, params = model_and_params
    session = _session(model, params, prefix_share=True)
    hits0 = registry().counter("serve_prefix_hit_tokens").value
    requests = _shared_requests(6, seed=2)
    assert_serving_parity(session, model, params, requests)
    assert registry().counter("serve_prefix_hit_tokens").value > hits0
    assert session.engine.cache.radix.stats()["nodes"] > 0


def test_shared_prefix_fully_matched_prompt(model_and_params):
    """A SECOND identical, page-aligned prompt (full-tree hit) still
    yields exact parity — the last prompt token re-runs through the
    chunk program to produce first-token logits."""
    model, params = model_and_params
    session = _session(model, params, prefix_share=True)
    ids = list(np.random.default_rng(5).integers(1, 512, size=12))
    reqs = [
        Request("a", [int(t) for t in ids], max_new_tokens=6),
        Request("b", [int(t) for t in ids], max_new_tokens=6),
    ]
    results = session.serve(reqs)
    want = np.asarray(generate(
        model, params, jnp.asarray(ids, jnp.int32)[None, :],
        max_new_tokens=6,
    ))[0]
    for rid in ("a", "b"):
        np.testing.assert_array_equal(np.asarray(results[rid].tokens), want)


def test_prefix_eviction_under_pool_pressure():
    """A pool too small to cache every prefix evicts LRU refcount-0
    tree pages instead of refusing admission — and every request still
    parity-matches its cold run."""
    # A small compiled bound keeps pages_per_slot (8) under the tiny
    # pool; 4 distinct 3-page prompts against 9 usable pages forces
    # the tree to evict between seats.
    cfg = LLAMA_TINY(dtype=jnp.float32, max_seq_len=32)
    model = LlamaForCausalLM(cfg)
    params = model.init(
        jax.random.key(2), jnp.zeros((1, PROMPT_LEN), jnp.int32)
    )["params"]
    session = _session(
        model, params, prefix_share=True, num_pages=10, num_slots=1,
    )
    rng = np.random.default_rng(9)
    reqs = []
    for i in range(4):
        prefix = rng.integers(1, 512, size=8).tolist()
        reqs.append(Request(f"e{i}", prefix + rng.integers(
            1, 512, size=4).tolist(), max_new_tokens=4))
    assert_serving_parity(session, model, params, reqs)
    assert session.engine.cache.radix.stats()["evictions"] > 0


def test_prefix_share_requires_paged(model_and_params):
    model, params = model_and_params
    with pytest.raises(ValueError, match="require paged"):
        ServeSession.from_model(
            model, params, prompt_len=PROMPT_LEN, num_slots=2,
            prefix_share=True,
        )


# ---------------------------------------------------------------------------
# Speculative decoding
# ---------------------------------------------------------------------------


def test_spec_greedy_margin_parity_and_acceptance(model_and_params):
    """Teacher-forced margin-mode parity for the int8 self-draft, and
    per-stream accepted-tokens/step >= 2 on the greedy config (the
    acceptance bar)."""
    model, params = model_and_params
    session = _session(model, params, spec_k=3)
    reg = registry()
    acc0 = reg.counter("spec_accepted_tokens").value
    slot0 = reg.counter("spec_slot_steps").value
    rng = np.random.default_rng(11)
    reqs = [
        Request(f"s{i}", rng.integers(1, 512, size=int(
            rng.integers(2, PROMPT_LEN + 1))).tolist(), max_new_tokens=12)
        for i in range(5)
    ]
    assert_serving_parity(session, model, params, reqs, atol=0.06)
    accepted = reg.counter("spec_accepted_tokens").value - acc0
    slot_steps = reg.counter("spec_slot_steps").value - slot0
    assert accepted / slot_steps >= 2.0, (accepted, slot_steps)


def test_spec_full_rejection_rollback(model_and_params):
    """THE rollback regression: a draft with unrelated random weights
    disagrees with the target almost everywhere, so windows are
    (nearly always) fully rejected — and the emitted stream must still
    be EXACTLY the non-speculative greedy stream, i.e. state after a
    rejected window is indistinguishable from never having
    speculated."""
    model, params = model_and_params
    garbage = model.init(
        jax.random.key(123), jnp.zeros((1, PROMPT_LEN), jnp.int32)
    )["params"]
    session = _session(
        model, params, spec_k=3, draft_model=model, draft_params=garbage,
    )
    rng = np.random.default_rng(13)
    reqs = [
        Request(f"g{i}", rng.integers(1, 512, size=6).tolist(),
                max_new_tokens=10)
        for i in range(4)
    ]
    results = session.serve(list(reqs))
    for req in reqs:
        want = np.asarray(generate(
            model, params,
            jnp.asarray(req.input_ids, jnp.int32)[None, :],
            max_new_tokens=req.max_new_tokens,
        ))[0]
        got = np.asarray(results[req.request_id].tokens)
        np.testing.assert_array_equal(
            got, want[: got.shape[0]],
            err_msg=f"{req.request_id}: rejected-window rollback "
                    f"corrupted the decode state",
        )


def test_spec_eos_mid_window(model_and_params):
    """An eos accepted in the middle of a window truncates the window
    there, exactly like non-speculative serving stops at eos."""
    model, params = model_and_params
    prompt = [3, 1, 4, 1, 5]
    cold = np.asarray(generate(
        model, params, jnp.asarray(prompt, jnp.int32)[None, :],
        max_new_tokens=12,
    ))[0]
    eos = int(cold[4])  # force a finish at token 5 of 12
    session = _session(model, params, spec_k=3)
    res = session.serve([
        Request("e", prompt, max_new_tokens=12, eos_id=eos)
    ])["e"]
    assert res.finish_reason == "eos"
    assert res.tokens[-1] == eos
    np.testing.assert_array_equal(
        np.asarray(res.tokens), cold[: len(res.tokens)]
    )
    assert eos not in res.tokens[:-1]


def test_spec_sampled_determinism(model_and_params):
    """Sampled requests reproduce their tokens across sessions (the
    per-(request, position) Philox streams), independent of batch
    composition."""
    model, params = model_and_params
    req = Request("t", [5, 6, 7, 8], max_new_tokens=10,
                  temperature=0.8, seed=42)
    out1 = _session(model, params, spec_k=3).serve(
        [dataclasses.replace(req)]
    )["t"].tokens
    # Same request next to a neighbor: its stream must not change.
    session = _session(model, params, spec_k=3)
    other = Request("o", [9, 9, 2], max_new_tokens=10)
    res = session.serve([dataclasses.replace(req), other])
    assert res["t"].tokens == out1


def test_spec_companion_draft_different_architecture(model_and_params):
    """A companion draft with DIFFERENT KV geometry (fewer layers)
    gets its own cache template — only the tokenizer must match
    (regression: the draft pool was built from the target's template,
    crashing any non-self draft at first seat)."""
    model, params = model_and_params
    small_cfg = LLAMA_TINY(dtype=jnp.float32, max_seq_len=96,
                           num_layers=1)
    draft = LlamaForCausalLM(small_cfg)
    draft_params = draft.init(
        jax.random.key(7), jnp.zeros((1, PROMPT_LEN), jnp.int32)
    )["params"]
    session = _session(
        model, params, spec_k=3, draft_model=draft,
        draft_params=draft_params,
    )
    rng = np.random.default_rng(19)
    reqs = [
        Request(f"cd{i}", rng.integers(1, 512, size=5).tolist(),
                max_new_tokens=8)
        for i in range(3)
    ]
    # Greedy correction keeps the stream exact whatever the draft says.
    results = session.serve(list(reqs))
    for req in reqs:
        want = np.asarray(generate(
            model, params,
            jnp.asarray(req.input_ids, jnp.int32)[None, :],
            max_new_tokens=req.max_new_tokens,
        ))[0]
        np.testing.assert_array_equal(
            np.asarray(results[req.request_id].tokens),
            want[: len(results[req.request_id].tokens)],
        )


def test_spec_requires_paged(model_and_params):
    model, params = model_and_params
    with pytest.raises(ValueError, match="require paged"):
        ServeSession.from_model(
            model, params, prompt_len=PROMPT_LEN, num_slots=2, spec_k=3,
        )


def test_acceptance_rules_unit():
    from tpudl.serve.speculate import (
        greedy_accept,
        sample_accept,
        softmax,
    )

    # Greedy: full acceptance emits the proposals verbatim.
    emitted, accepted = greedy_accept([4, 5, 6], [4, 5, 6])
    assert (emitted, accepted) == ([4, 5, 6], 3)
    # First disagreement: target's choice replaces it, window ends.
    emitted, accepted = greedy_accept([4, 9, 6], [4, 5, 6])
    assert (emitted, accepted) == ([4, 5], 1)
    emitted, accepted = greedy_accept([9, 9, 9], [1, 2, 3])
    assert (emitted, accepted) == ([1], 0)

    # Sampling: q == p accepts every proposal (ratio 1).
    p = softmax(np.asarray([1.0, 2.0, 3.0]), 1.0)
    emitted, accepted = sample_accept(
        [2, 2], [p, p], [p, p], seed=1, token_index=0
    )
    assert accepted == 2 and emitted == [2, 2]
    # A proposal with target mass ZERO is always rejected, and the
    # residual draw can only produce tokens with p > q mass.
    q = np.asarray([0.0, 1.0, 0.0])
    p0 = np.asarray([0.7, 0.0, 0.3])
    for seed in range(8):
        emitted, accepted = sample_accept(
            [1], [q], [p0], seed=seed, token_index=0
        )
        assert accepted == 0
        assert emitted[0] in (0, 2)


def test_spec_with_prefix_share_composed(model_and_params):
    """The two tentpole halves compose: radix-shared seating under a
    speculating engine, margin parity intact."""
    model, params = model_and_params
    session = _session(model, params, prefix_share=True, spec_k=3)
    reqs = _shared_requests(4, seed=21, max_new=6, tag="c")
    assert_serving_parity(session, model, params, reqs, atol=0.06)
    assert session.engine.cache.radix.stats()["nodes"] > 0


# ---------------------------------------------------------------------------
# Exported paged artifacts (ROADMAP item 6 leftover)
# ---------------------------------------------------------------------------


@pytest.mark.needs_jax_export
def test_from_artifacts_paged_parity(model_and_params):
    """The paged-KV contract round-trips through StableHLO: geometry
    (page size, pool size, slots, quantization) recovered from avals
    alone, int8 pools included, greedy tokens parity-checked."""
    model, params = model_and_params
    from tpudl.export.decode import export_serving_decoder

    pre, dec = export_serving_decoder(
        model, params, num_slots=2, prompt_len=PROMPT_LEN,
        paged=True, page_size=PAGE, kv_dtype="int8",
    )
    session = ServeSession.from_artifacts(pre, dec, params, paged=True)
    cache = session.engine.cache
    assert cache.paged and cache.quantized and cache.page_size == PAGE
    assert session.num_slots == 2
    rng = np.random.default_rng(17)
    reqs = [
        Request(f"x{i}", rng.integers(1, 512, size=6).tolist(),
                max_new_tokens=8)
        for i in range(3)
    ]
    assert_serving_parity(session, model, params, reqs, atol=0.05)
    # Expectation mismatch is a loud error, not a silent fallback.
    with pytest.raises(ValueError, match="paged"):
        ServeSession.from_artifacts(pre, dec, params, paged=False)


@pytest.mark.needs_jax_export
def test_from_artifacts_paged_clamps_model_bound(model_and_params):
    """A page size that does not divide the model's compiled bound
    rounds the page span past the model's position space; the artifact
    session must clamp admission at the TRUE bound (recovered from the
    prefill artifact's dense rows), exactly like the live path."""
    model, params = model_and_params
    from tpudl.export.decode import export_serving_decoder

    pre, dec = export_serving_decoder(
        model, params, num_slots=2, prompt_len=PROMPT_LEN,
        paged=True, page_size=28,  # 4 * 28 = 112 > the model's 96
    )
    session = ServeSession.from_artifacts(pre, dec, params)
    assert session.max_seq_len == CFG.max_seq_len == 96
    with pytest.raises(ValueError, match="max_seq_len"):
        session.submit(Request("z", [1, 2, 3],
                               max_new_tokens=96 - PROMPT_LEN + 1))


# ---------------------------------------------------------------------------
# Router prefix affinity + trace attribution
# ---------------------------------------------------------------------------


def test_router_prefix_affinity(model_and_params):
    """A request whose prefix lives in one replica's radix tree routes
    there even when another replica is equally idle — prefix affinity
    beats cold least-loaded placement."""
    model, params = model_and_params
    from tpudl.serve import Replica, Router

    replicas = [
        Replica(f"r{i}", _session(model, params, prefix_share=True))
        for i in range(2)
    ]
    reqs = _shared_requests(4, seed=31, max_new=4, tag="af")
    with Router(replicas) as router:
        # Seed: the first request lands somewhere and plants the
        # prefix in that replica's tree.
        router.serve([reqs[0]], timeout_s=120.0)
        seeded = next(
            r for r in replicas
            if r.session.engine.cache.radix.stats()["nodes"] > 0
        )
        other = next(r for r in replicas if r is not seeded)
        results = router.serve(reqs[1:], timeout_s=120.0)
    assert all(r.ok for r in results.values())
    # Every follow-up went to the seeded replica's engine.
    assert other.session.engine.num_prefills == 0
    assert seeded.session.engine.num_prefills == len(reqs)


def test_report_request_prefix_and_spec_attrs(model_and_params, tmp_path):
    """report.py --request surfaces prefix_hit_tokens and per-window
    accepted/proposed — where TTFT and TPOT went."""
    model, params = model_and_params
    from tpudl.obs import report as obs_report
    from tpudl.obs import spans as obs_spans

    obs_spans.enable(str(tmp_path))
    try:
        session = _session(model, params, prefix_share=True, spec_k=3)
        reqs = _shared_requests(3, seed=41, max_new=6, tag="tr")
        session.serve(list(reqs))
        records = obs_spans.active_recorder().records
        timeline = obs_report.build_request_timeline(records, "tr2")
    finally:
        obs_spans.disable()
    assert timeline["prefix_hit_tokens"] and timeline[
        "prefix_hit_tokens"] >= PAGE
    spec = timeline["speculation"]
    assert spec is not None and spec["proposed"] > 0
    chunk = next(
        e for e in timeline["timeline"] if e["what"] == "decode_chunk"
    )
    assert chunk["detail"]["proposed"] > 0
    assert "accepted" in chunk["detail"]
    prefill = next(
        e for e in timeline["timeline"] if e["what"] == "prefill"
    )
    assert prefill["detail"]["prefix_hit_tokens"] >= PAGE
