"""scripts/bench_regress.py: the noise-aware regression gate.

The protocol under test is the one BASELINE.md derived from the false
r05 ResNet-18 "0.923 regression": median-of-bank same-protocol
baselines, per-metric noise bands widened by the bank's own spread —
and the canonical acceptance case is that r05 itself classifies as
NO-regression while a genuinely halved draw still gates."""

import json

import pytest

from scripts.bench_regress import (
    ALIASES,
    evaluate_regressions,
    format_rows,
    main,
    noise_band,
    normalize_round,
    self_test,
)


def test_normalize_round_aliases_and_filters():
    row = normalize_round({
        "parsed": {
            "metric": "bert_base_sst2_train_throughput",
            "value": 1534.0,
            "unit": "samples/sec/chip",
            "vs_baseline": 1.162,
            "mfu": 0.527,
            "bert_batch": 256,
            "resnet18_images_per_sec_chip_best_of_windows": 34065.5,
            "resnet18_vs_baseline_best_vs_best": 0.923,
            "serve_tokens_per_sec": 900.0,
            "checkpoint_step_stall_ms": None,
        }
    })
    # Headline value keyed under its metric name, then canonicalized.
    assert row["bert_base_samples_per_sec_chip"] == 1534.0
    assert row["resnet18_images_per_sec_chip"] == 34065.5
    assert row["serve_tokens_per_sec"] == 900.0
    assert row["mfu"] == 0.527
    # Ratios against the broken protocol, units, config echoes, nulls:
    # all dropped.
    for absent in ("vs_baseline", "resnet18_vs_baseline_best_vs_best",
                   "unit", "metric", "bert_batch",
                   "checkpoint_step_stall_ms", "value"):
        assert absent not in row
    # Works on a bare bench.py line too (no "parsed" wrapper).
    bare = normalize_round({"metric": "x_throughput", "value": 5.0})
    assert bare == {"x_throughput": 5.0}


def test_noise_band_floor_and_spread():
    # Tight bank: the per-metric floor rules.
    assert noise_band("bert_base_samples_per_sec_chip",
                      [1000.0, 1010.0, 990.0]) == pytest.approx(0.08)
    # The resnet floor encodes the documented ±20% ambient drift.
    assert noise_band("resnet18_images_per_sec_chip",
                      [30000.0, 30100.0]) == pytest.approx(0.25)
    # A scattered bank widens the band past the floor: its own spread
    # is evidence of one-draw noise.
    band = noise_band("bert_base_samples_per_sec_chip",
                      [1000.0, 1400.0, 1200.0])
    assert band == pytest.approx((1400 - 1000) / 1200 / 2)


def test_gate_directions_and_no_baseline():
    hist = [
        {"tput": 100.0, "lat_ms": 10.0},
        {"tput": 104.0, "lat_ms": 11.0},
        {"tput": 96.0, "lat_ms": 9.0},
    ]
    hist = [dict(h, **{"serve_p99_ttft_ms": h.pop("lat_ms")}) for h in hist]
    rows = evaluate_regressions(
        {"tput": 80.0, "serve_p99_ttft_ms": 30.0, "brand_new": 1.0}, hist
    )
    by = {r["metric"]: r for r in rows}
    # Higher-is-better: 80 vs median 100 with band max(0.08, 0.04) ->
    # regression. Lower-is-better: 30 ms vs median 10 with band 0.5 ->
    # regression.
    assert by["tput"]["status"] == "regression"
    assert by["tput"]["baseline"] == 100.0
    assert by["serve_p99_ttft_ms"]["status"] == "regression"
    assert by["brand_new"]["status"] == "no-baseline"
    # Inside the band: ok; outside on the good side: improved.
    rows = evaluate_regressions(
        {"tput": 97.0, "serve_p99_ttft_ms": 4.0}, hist
    )
    by = {r["metric"]: r for r in rows}
    assert by["tput"]["status"] == "ok"
    assert by["serve_p99_ttft_ms"]["status"] == "improved"
    # min_history gates gating itself.
    rows = evaluate_regressions({"tput": 1.0}, hist[:1])
    assert rows[0]["status"] == "no-baseline"


def test_r05_incident_is_the_self_test():
    """The banked acceptance case: r05's ResNet-18 draw classifies as
    no-regression under the median-of-bank protocol (the max-of-bank
    ratio called it 0.923), and a halved draw still gates."""
    assert self_test() == 0


def test_cli_gate_and_exit_codes(tmp_path, capsys):
    cur = tmp_path / "cur.json"
    hist_files = []
    for i, v in enumerate([100.0, 102.0, 98.0]):
        p = tmp_path / f"BENCH_r0{i + 1}.json"
        p.write_text(json.dumps(
            {"parsed": {"metric": "tput", "value": v}}
        ))
        hist_files.append(str(p))

    cur.write_text(json.dumps({"metric": "tput", "value": 99.0}))
    assert main([str(cur), "--history"] + hist_files) == 0
    out = capsys.readouterr().out
    assert "tput" in out and "ok" in out

    cur.write_text(json.dumps({"metric": "tput", "value": 50.0}))
    assert main([str(cur), "--history"] + hist_files) == 1
    captured = capsys.readouterr()
    assert "REGRESSION" in captured.err
    # --json emits machine-readable rows.
    assert main([str(cur), "--json", "--history"] + hist_files) == 1
    rows = json.loads(capsys.readouterr().out)
    assert rows[0]["status"] == "regression"


def test_format_rows_renders_every_status():
    rows = evaluate_regressions(
        {"a": 1.0},
        [{"a": 2.0}, {"a": 2.2}],
    ) + evaluate_regressions({"b": 1.0}, [])
    text = format_rows(rows)
    assert "REGRESSION" in text and "no-baseline" in text


def test_aliases_map_to_canonical_names():
    # Every alias target is itself stable (no chains).
    for target in ALIASES.values():
        assert target not in ALIASES


def test_zero_tolerance_metric_gates_on_absolute_value():
    """serve_steady_state_recompiles banks at 0, where the ratio
    protocol is blind (value/0 has no ratio): any positive draw must
    classify as regression, and staying at 0 as ok."""
    hist = [
        {"serve_steady_state_recompiles": 0.0},
        {"serve_steady_state_recompiles": 0.0},
    ]
    bad = evaluate_regressions({"serve_steady_state_recompiles": 3.0}, hist)
    assert bad[0]["status"] == "regression"
    good = evaluate_regressions({"serve_steady_state_recompiles": 0.0}, hist)
    assert good[0]["status"] == "ok"
