import jax
import jax.numpy as jnp

from tpudl.models.resnet import ResNet18, ResNet50, ResNetTiny


def test_resnet_tiny_forward_shape():
    model = ResNetTiny(num_classes=10)
    x = jnp.zeros((2, 16, 16, 3))
    variables = model.init(jax.random.key(0), x, train=False)
    logits = model.apply(variables, x, train=False)
    assert logits.shape == (2, 10)
    assert logits.dtype == jnp.float32
    assert "batch_stats" in variables


def test_resnet18_cifar_stem_shape():
    model = ResNet18(num_classes=10, small_inputs=True)
    x = jnp.zeros((1, 32, 32, 3))
    variables = model.init(jax.random.key(0), x, train=False)
    logits = model.apply(variables, x, train=False)
    assert logits.shape == (1, 10)


def test_resnet50_param_count():
    # torchvision ResNet-50 has ~25.6M params (fc for 1000 classes);
    # parity check on the re-designed Flax module.
    model = ResNet50(num_classes=1000)
    x = jnp.zeros((1, 64, 64, 3))  # spatial size doesn't affect param count
    variables = jax.eval_shape(
        lambda: model.init(jax.random.key(0), x, train=False)
    )
    n = sum(
        int(jnp.prod(jnp.array(p.shape)))
        for p in jax.tree.leaves(variables["params"])
    )
    assert 25.0e6 < n < 26.5e6, n


def test_resnet_batchnorm_updates():
    model = ResNetTiny(num_classes=4)
    x = jnp.ones((2, 16, 16, 3))
    variables = model.init(jax.random.key(0), x, train=False)
    _, mutated = model.apply(
        variables, x, train=True, mutable=["batch_stats"]
    )
    before = jax.tree.leaves(variables["batch_stats"])
    after = jax.tree.leaves(mutated["batch_stats"])
    assert any(
        not jnp.allclose(b, a) for b, a in zip(before, after)
    ), "batch stats should move in train mode"
