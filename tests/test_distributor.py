"""TpuDistributor launch-path tests (SURVEY.md §4.2: localhost multi-process
bring-up substitutes for the reference lineage's run-on-a-cluster testing)."""

import numpy as np
import pytest

from tests import dist_helpers
from tpudl.runtime.distributor import TpuDistributor


def test_in_process_mode():
    d = TpuDistributor(num_processes=1)
    results = d.run(lambda x: x + 1, 41)
    assert results == [42]


def test_unpicklable_fn_error():
    d = TpuDistributor(num_processes=2)
    with pytest.raises(ValueError, match="picklable"):
        d.run(lambda x: x, 1)


@pytest.mark.slow
def test_spawn_two_processes_topology():
    d = TpuDistributor(num_processes=2, platform="cpu", devices_per_process=2)
    results = d.run(dist_helpers.report_topology)
    assert [r["process_index"] for r in results] == [0, 1]
    for r in results:
        assert r["process_count"] == 2
        assert r["local_devices"] == 2
        assert r["global_devices"] == 4


@pytest.mark.slow
def test_spawn_global_collective():
    d = TpuDistributor(num_processes=2, platform="cpu", devices_per_process=2)
    results = d.run(dist_helpers.global_sum)
    # 2 devices * 1.0 (proc 0) + 2 devices * 2.0 (proc 1) = 6.0 on every rank
    assert results == [6.0, 6.0]


@pytest.mark.slow
def test_spawn_distributed_train_smoke():
    d = TpuDistributor(num_processes=2, platform="cpu", devices_per_process=2)
    results = d.run(dist_helpers.distributed_train_smoke)
    for losses in results:
        assert len(losses) == 3
        assert all(l == l for l in losses)  # no NaNs
    # Both ranks computed the same global losses.
    assert results[0] == pytest.approx(results[1])


@pytest.mark.slow
def test_worker_failure_propagates():
    d = TpuDistributor(num_processes=2, platform="cpu", devices_per_process=1)
    with pytest.raises(RuntimeError, match="intentional worker failure"):
        d.run(dist_helpers.failing_worker)


@pytest.mark.slow
def test_spawn_converter_fed_training(tmp_path):
    """BASELINE.json north_star composition, executed: a materialized
    Parquet dataset feeds a 2-process x 2-device fit() run through
    disjoint converter shards and prefetch_to_device's
    make_array_from_process_local_data path. Every rank sees identical
    global losses; the ranks together consume the whole dataset (minus
    per-shard batch truncation)."""
    from tpudl.data.datasets import materialize_cifar10_like

    data_dir = str(tmp_path / "cifar")
    # 250 rows / 2 shards / batch 16: each 125-row shard truncates its
    # last partial batch to 112 consumed rows — real truncation, so the
    # coverage arithmetic below actually verifies the shard contract.
    num_rows, local_batch = 250, 16
    conv = materialize_cifar10_like(
        data_dir, num_rows=num_rows, rows_per_file=64
    )
    assert len(conv) == num_rows

    d = TpuDistributor(num_processes=2, platform="cpu", devices_per_process=2)
    results = d.run(dist_helpers.converter_fed_train, data_dir, local_batch)

    (losses0, rows0), (losses1, rows1) = results
    assert losses0, "no training steps ran"
    # Identical global losses on every rank (the global-array contract).
    assert losses0 == pytest.approx(losses1)
    assert all(np.isfinite(losses0))
    # Disjoint shards cover the dataset minus drop_last truncation only.
    shard = num_rows // 2
    expected_per_rank = (shard // local_batch) * local_batch
    assert expected_per_rank < shard  # truncation genuinely exercised
    assert rows0 == rows1 == expected_per_rank
    assert len(losses0) == expected_per_rank // local_batch


@pytest.mark.slow
def test_spawn_prefetch_multicolumn_global():
    """Multi-column batches through the two-stage prefetch's multi-host
    make_array_from_process_local_data path: global shapes/dtypes, exact
    cross-process sums, and source ORDER (the assembly pool must not
    reorder) agree on every rank."""
    local_batch, num_batches = 8, 6
    d = TpuDistributor(num_processes=2, platform="cpu", devices_per_process=2)
    r0, r1 = d.run(
        dist_helpers.prefetch_multicolumn_global, local_batch, num_batches
    )
    assert len(r0) == len(r1) == num_batches
    for i, (a, b) in enumerate(zip(r0, r1)):
        # Both ranks observed the same GLOBAL batch, in source order.
        assert a == b
        assert a["order"] == i
        assert a["shapes"] == {
            "image": (16, 4, 4, 3),
            "label": (16,),
            "weight": (16,),
            "order": (16,),
        }
        assert a["dtypes"]["image"] == "uint8"
        assert a["dtypes"]["label"] == "int32"
        assert a["dtypes"]["weight"] == "float32"
        # label: rank 0 contributes 8*(i*1000), rank 1 adds 8*(i*1000+100).
        assert a["sums"]["label"] == 8 * (i * 1000) + 8 * (i * 1000 + 100)
        assert a["sums"]["image"] == 16 * 4 * 4 * 3 * (i + 1)
        assert a["sums"]["weight"] == 16.0 * i


@pytest.mark.slow
def test_spawn_checkpoint_save_resume(tmp_path):
    """Multi-process checkpoint/resume — the actual pod recovery story
    (SURVEY.md §5.3-5.4): 2 spawned JAX processes train and save through
    CheckpointManager (Orbax multi-process coordination over the shared
    filesystem), the processes EXIT (the kill), a fresh 2-process spawn
    restores on both ranks and continues — with post-resume losses
    exactly equal to an uninterrupted run's tail, identical on both
    ranks."""
    ckpt = str(tmp_path / "ckpt")
    d = TpuDistributor(num_processes=2, platform="cpu", devices_per_process=2)
    phase1 = d.run(dist_helpers.checkpoint_save_phase, ckpt, 3)
    (r0, losses0), (r1, losses1) = sorted(phase1)
    assert (r0, r1) == (0, 1)
    assert losses0 == pytest.approx(losses1)

    # Fresh distributor = fresh processes: nothing survives but the disk.
    d2 = TpuDistributor(num_processes=2, platform="cpu", devices_per_process=2)
    phase2 = d2.run(dist_helpers.checkpoint_resume_phase, ckpt, 5, 3)
    (_, step0, resumed0, control0), (_, step1, resumed1, control1) = sorted(
        phase2
    )
    assert step0 == step1 == 3  # both ranks restored the same checkpoint
    assert resumed0 == pytest.approx(resumed1)  # ranks agree post-resume
    # The restored trajectory IS the uninterrupted trajectory: the
    # control's first 3 steps reproduce phase 1, its tail equals the
    # post-resume losses (params, momentum, BN stats, and the step
    # counter all round-tripped).
    assert control0[:3] == pytest.approx(losses0)
    assert resumed0 == pytest.approx(control0[3:])
    assert all(np.isfinite(resumed0))
