"""TpuDistributor launch-path tests (SURVEY.md §4.2: localhost multi-process
bring-up substitutes for the reference lineage's run-on-a-cluster testing)."""

import pytest

from tests import dist_helpers
from tpudl.runtime.distributor import TpuDistributor


def test_in_process_mode():
    d = TpuDistributor(num_processes=1)
    results = d.run(lambda x: x + 1, 41)
    assert results == [42]


def test_unpicklable_fn_error():
    d = TpuDistributor(num_processes=2)
    with pytest.raises(ValueError, match="picklable"):
        d.run(lambda x: x, 1)


@pytest.mark.slow
def test_spawn_two_processes_topology():
    d = TpuDistributor(num_processes=2, platform="cpu", devices_per_process=2)
    results = d.run(dist_helpers.report_topology)
    assert [r["process_index"] for r in results] == [0, 1]
    for r in results:
        assert r["process_count"] == 2
        assert r["local_devices"] == 2
        assert r["global_devices"] == 4


@pytest.mark.slow
def test_spawn_global_collective():
    d = TpuDistributor(num_processes=2, platform="cpu", devices_per_process=2)
    results = d.run(dist_helpers.global_sum)
    # 2 devices * 1.0 (proc 0) + 2 devices * 2.0 (proc 1) = 6.0 on every rank
    assert results == [6.0, 6.0]


@pytest.mark.slow
def test_spawn_distributed_train_smoke():
    d = TpuDistributor(num_processes=2, platform="cpu", devices_per_process=2)
    results = d.run(dist_helpers.distributed_train_smoke)
    for losses in results:
        assert len(losses) == 3
        assert all(l == l for l in losses)  # no NaNs
    # Both ranks computed the same global losses.
    assert results[0] == pytest.approx(results[1])


@pytest.mark.slow
def test_worker_failure_propagates():
    d = TpuDistributor(num_processes=2, platform="cpu", devices_per_process=1)
    with pytest.raises(RuntimeError, match="intentional worker failure"):
        d.run(dist_helpers.failing_worker)
