"""SLO-driven autoscaler (ISSUE 10 tentpole, tpudl.serve.autoscale) +
the router's live fleet-membership APIs.

Hysteresis units run against a fake router with an injected clock —
deterministic edge-by-edge checks that a flickering burn cannot flap
the fleet, sustain windows gate both directions, cooldown separates
actions, and min/max bounds hold. The drain contract runs against a
REAL two-replica router: removing a replica that owns in-flight work
must deliver every Result (generate()-parity intact) before the
replica disappears. The end-to-end acceptance (overload -> fleet
burn -> scale-up -> recovery with zero shed_slo -> idle drain) rides
benchmarks/serve_load.run_autoscale_recovery with test-sized load."""

import time
import types

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import tpudl.obs as obs
from tpudl.obs import counters as obs_counters
from tpudl.obs import exporter as obs_exporter
from tpudl.models.generate import generate
from tpudl.models.llama import LLAMA_TINY, LlamaForCausalLM
from tpudl.serve import (
    AutoscaleConfig,
    Autoscaler,
    Replica,
    Request,
    Router,
    ServeSession,
)

CFG = LLAMA_TINY(dtype=jnp.float32, max_seq_len=96)
PROMPT_LEN = 8


@pytest.fixture(autouse=True)
def _clean():
    obs.disable()
    obs_counters.registry().reset()
    obs_exporter._reset_health_for_tests()
    yield
    obs.disable()
    obs_counters.registry().reset()
    obs_exporter._reset_health_for_tests()


@pytest.fixture(scope="module")
def model_and_params():
    model = LlamaForCausalLM(CFG)
    params = model.init(
        jax.random.key(0), jnp.zeros((1, PROMPT_LEN), jnp.int32)
    )["params"]
    return model, params


def _session(model, params, **kw):
    kw.setdefault("prompt_len", PROMPT_LEN)
    kw.setdefault("num_slots", 2)
    return ServeSession.from_model(model, params, **kw)


# ---------------------------------------------------------------------------
# Hysteresis units (fake router, fake clock — fully deterministic)
# ---------------------------------------------------------------------------


class FakeRouter:
    def __init__(self, replicas=2):
        self.n = replicas
        self.hint = 0
        self.burning = False
        self.busy_frac = 0.0
        self.queue_frac = 0.0
        self.added = []
        self.removed = []

    def load_report(self):
        return {
            "replicas": self.n,
            "active_replicas": self.n,
            "ready_replicas": self.n,
            "draining": [],
            "busy_frac": self.busy_frac,
            "queue_frac": self.queue_frac,
            "outstanding": 0,
            "burning": self.burning,
            "autoscale_hint": self.hint,
            "per_replica": {
                f"r{i}": {
                    "ready": True, "busy": i, "inflight_tokens": i * 10,
                }
                for i in range(self.n)
            },
        }

    def add_replica(self, replica):
        self.n += 1
        self.added.append(replica.name)

    def remove_replica(self, name, drain=True, timeout_s=None):
        assert drain, "the autoscaler must always drain on scale-down"
        self.n -= 1
        self.removed.append(name)


def _scaler(router, t, **cfg_kw):
    cfg = AutoscaleConfig(**{
        "min_replicas": 2, "max_replicas": 4, "up_sustain_s": 0.5,
        "down_sustain_s": 3.0, "cooldown_s": 1.0, **cfg_kw,
    })
    spawned = []

    def spawn(name):
        spawned.append(name)
        return types.SimpleNamespace(name=name)

    scaler = Autoscaler(
        router, spawn, cfg, clock=lambda: t[0]
    )
    scaler._spawned = spawned
    return scaler


def test_scale_up_requires_sustained_pressure():
    router, t = FakeRouter(2), [0.0]
    scaler = _scaler(router, t)
    router.hint = 1
    assert scaler.evaluate() is None  # pressure just started
    t[0] = 0.3
    assert scaler.evaluate() is None  # not sustained yet
    t[0] = 0.6
    action = scaler.evaluate()
    assert action is not None and action["action"] == "scale_up"
    assert router.added == ["auto1"] and router.n == 3
    assert "hint" in action["reason"]


def test_flickering_burn_edge_never_flaps():
    """Pressure that flickers on/off faster than the sustain window
    produces NO action in either direction — the no-flapping bar."""
    router, t = FakeRouter(2), [0.0]
    scaler = _scaler(router, t)
    for i in range(20):
        t[0] = 0.2 * i
        router.burning = i % 2 == 0  # flips every 0.2s < 0.5s sustain
        # Off-phases are NOT idle either (busy fleet): timers reset.
        router.busy_frac = 0.5
        assert scaler.evaluate() is None, (i, scaler.history)
    assert router.added == [] and router.removed == []


def test_cooldown_separates_actions_and_max_bounds():
    router, t = FakeRouter(2), [0.0]
    scaler = _scaler(router, t, max_replicas=4)
    router.burning = True
    assert scaler.evaluate() is None  # starts the sustain timer
    t[0] = 0.6
    assert scaler.evaluate()["action"] == "scale_up"  # n -> 3
    # Still burning: cooldown (1.0s) blocks any second action, even
    # though the sustain window rebuilds underneath it.
    t[0] = 0.8
    assert scaler.evaluate() is None  # in cooldown (timer restarts)
    t[0] = 1.2
    assert scaler.evaluate() is None  # still in cooldown
    t[0] = 1.7
    assert scaler.evaluate()["action"] == "scale_up"  # n -> 4
    assert scaler.history[1]["at"] - scaler.history[0]["at"] >= 1.0
    # At max_replicas: sustained pressure is unactionable, no action.
    t[0] = 5.0
    assert scaler.evaluate() is None
    assert router.n == 4


def test_sustained_idle_drains_to_min_and_picks_least_loaded():
    router, t = FakeRouter(4), [0.0]
    scaler = _scaler(router, t, down_sustain_s=2.0, cooldown_s=0.5)
    router.busy_frac = 0.0
    assert scaler.evaluate() is None
    t[0] = 2.5
    action = scaler.evaluate()
    assert action is not None and action["action"] == "scale_down"
    # Victim: fewest in-flight tokens (r0 in the fake's report).
    assert router.removed == ["r0"]
    # Cooldown, then the next sustained idle window drains one more.
    t[0] = 3.2
    assert scaler.evaluate() is None  # restarts the idle timer
    t[0] = 5.5
    assert scaler.evaluate()["action"] == "scale_down"
    assert router.n == 2
    # Never below min_replicas, however long the idle lasts.
    t[0] = 60.0
    assert scaler.evaluate() is None
    assert router.n == 2


def test_busy_but_not_burning_is_neutral():
    """Mid load (no pressure, not idle): both timers stay unset and
    nothing ever fires."""
    router, t = FakeRouter(2), [0.0]
    scaler = _scaler(router, t)
    router.busy_frac = 0.6
    for i in range(10):
        t[0] = float(i)
        assert scaler.evaluate() is None
    assert scaler._pressure_since is None and scaler._idle_since is None


def test_queue_pressure_and_fleet_burn_count_as_pressure():
    router, t = FakeRouter(2), [0.0]

    class FakeFleet:
        burning = []

        def burning_sources(self):
            return self.burning

    fleet = FakeFleet()
    scaler = _scaler(router, t)
    scaler.fleet = fleet
    router.queue_frac = 0.9  # queue depth alone is pressure
    sig = scaler.signals()
    assert sig["pressure"] and any(
        r.startswith("queue_frac") for r in sig["reasons"]
    )
    router.queue_frac = 0.0
    fleet.burning = ["replica7"]  # cross-process burn alone is pressure
    sig = scaler.signals()
    assert sig["pressure"] and any(
        "fleet_burn" in r for r in sig["reasons"]
    )


def test_config_validation():
    with pytest.raises(ValueError, match="min_replicas"):
        AutoscaleConfig(min_replicas=0)
    with pytest.raises(ValueError, match="max_replicas"):
        AutoscaleConfig(min_replicas=4, max_replicas=2)


# ---------------------------------------------------------------------------
# Router live-membership APIs (real replicas)
# ---------------------------------------------------------------------------


def _greedy_requests(n, seed=0, max_new_lo=6, max_new_hi=16, **kw):
    rng = np.random.default_rng(seed)
    return [
        Request(
            request_id=f"r{i}",
            input_ids=rng.integers(
                1, CFG.vocab_size, size=int(rng.integers(2, PROMPT_LEN + 1))
            ).tolist(),
            max_new_tokens=int(rng.integers(max_new_lo, max_new_hi)),
            **kw,
        )
        for i in range(n)
    ]


def _assert_generate_parity(model, params, requests, results):
    for req in requests:
        want = np.asarray(
            generate(
                model, params, jnp.asarray(req.input_ids)[None, :],
                max_new_tokens=req.max_new_tokens,
            )
        )[0]
        got = np.asarray(results[req.request_id].tokens)
        np.testing.assert_array_equal(
            got, want[: got.shape[0]],
            err_msg=f"request {req.request_id} diverged",
        )


def test_add_replica_live_and_validation(model_and_params):
    model, params = model_and_params
    with Router([Replica("r0", _session(model, params))]) as router:
        router.add_replica(Replica("r1", _session(model, params)))
        assert router.load_report()["active_replicas"] == 2
        requests = _greedy_requests(6, seed=3)
        results = router.serve(requests, timeout_s=300.0)
        _assert_generate_parity(model, params, requests, results)
        assert all(
            r.session.engine.num_prefills > 0 for r in router.replicas
        ), "the added replica took no work"
        # Duplicate names and mismatched compiled shapes are rejected.
        with pytest.raises(ValueError, match="duplicate replica name"):
            router.add_replica(Replica("r1", _session(model, params)))
        with pytest.raises(ValueError, match="compiled shapes"):
            router.add_replica(Replica(
                "r2", _session(model, params, prompt_len=4)
            ))


def test_remove_replica_drains_without_dropping(model_and_params):
    """The acceptance drain contract: removing a replica that owns
    in-flight work delivers EVERY Result with generate()-parity before
    the replica disappears, and releases its sticky pins."""
    model, params = model_and_params
    sessions = [_session(model, params) for _ in range(2)]
    for s in sessions:  # slow decodes so work is in flight at removal
        orig = s.engine.decode_call

        def slow(*args, _orig=orig):
            time.sleep(0.02)
            return _orig(*args)

        s.engine.decode_call = slow
    replicas = [Replica(f"r{i}", s) for i, s in enumerate(sessions)]
    requests = _greedy_requests(8, seed=5, max_new_lo=8, max_new_hi=20)
    # Pin one stream to r0 so its sticky release is observable.
    requests[0] = Request(
        "r0-pinned", [3, 5, 7], max_new_tokens=12, session_key="user-1"
    )
    with Router(replicas) as router:
        for req in requests:
            router.submit(req)
        victim = "r0" if any(
            owner == "r0" for owner, _ in router._assigned.values()
        ) else "r1"
        removed = router.remove_replica(victim, drain=True, timeout_s=120.0)
        assert removed.name == victim
        assert all(r.name != victim for r in router.replicas)
        assert victim not in router._ready
        # Nothing the victim owned was dropped, and no request was
        # restarted on a survivor (a drain is not a failover).
        assert router.num_failovers == 0
        results = router.collect(timeout_s=300.0)
        assert set(results) == {r.request_id for r in requests}
        assert all(res.ok for res in results.values()), {
            rid: res.finish_reason for rid, res in results.items()
        }
        _assert_generate_parity(
            model, params,
            [r for r in requests if r.request_id != "r0-pinned"],
            results,
        )
        assert "user-1" not in router._sticky or (
            router._sticky["user-1"] != victim
        )
        # The survivor still serves new work.
        more = _greedy_requests(2, seed=6)
        more = [
            Request(f"post-{r.request_id}", r.input_ids,
                    max_new_tokens=r.max_new_tokens)
            for r in more
        ]
        post = router.serve(more, timeout_s=300.0)
        assert all(res.ok for res in post.values())


def test_remove_replica_timeout_restores_service(model_and_params):
    model, params = model_and_params
    session = _session(model, params)
    orig = session.engine.decode_call

    def slow(*args):
        time.sleep(0.05)
        return orig(*args)

    session.engine.decode_call = slow
    replicas = [
        Replica("r0", session), Replica("r1", _session(model, params)),
    ]
    with Router(replicas) as router:
        # Park long work on r0 (least-loaded placement from cold books).
        for req in _greedy_requests(4, seed=7, max_new_lo=20,
                                    max_new_hi=32):
            router.submit(req)
        victim = next(
            owner for owner, _ in router._assigned.values()
            if owner is not None
        )
        with pytest.raises(TimeoutError, match="still in flight"):
            router.remove_replica(victim, drain=True, timeout_s=0.0)
        # Back in service: not draining, still in the fleet, and the
        # run completes.
        assert victim not in router._draining
        assert any(r.name == victim for r in router.replicas)
        results = router.collect(timeout_s=300.0)
        assert all(res.ok for res in results.values())
        with pytest.raises(ValueError, match="no replica named"):
            router.remove_replica("nope")


# ---------------------------------------------------------------------------
# End-to-end acceptance: overload -> burn -> scale-up -> recovery ->
# idle drain (rides the benchmark scenario at test-sized load)
# ---------------------------------------------------------------------------


def test_autoscale_acceptance_end_to_end(tmp_path):
    from benchmarks.serve_load import run_autoscale_recovery

    obs.enable(str(tmp_path / "obs"))  # the fleet trace rides along
    out = run_autoscale_recovery(
        num_replicas=2,
        max_replicas=3,
        offered_rate=250.0,
        n_requests=90,
        recovery_rate=50.0,
        n_recovery_requests=20,
        sim_step_ms=4.0,
        check=True,  # every acceptance assert lives in the scenario
    )
    assert out["scale_ups"] == 1 and out["scale_downs"] == 1
    assert out["replicas_final"] == 2
    assert out["fleet_burned"] is True
    assert out["autoscale_recovery_s"] is not None
    assert out["post_scale_up"]["finish_reasons"].get("shed_slo", 0) == 0
    assert out["parity_ok"] is True
    # The recorded stream stitches into a fleet report that shows the
    # membership churn.
    from tpudl.obs import report as obs_report
    from tpudl.obs.spans import active_recorder

    records = active_recorder().records
    fleet_report = obs_report.build_fleet_report(records)
    actions = {
        a["action"] for a in fleet_report["autoscale_actions"]
    }
    assert actions == {"scale_up", "scale_down"}
    membership = {
        (m["what"], m["replica"]) for m in fleet_report["membership"]
    }
    assert ("replica_added", "auto1") in membership
    assert any(w == "replica_removed" for w, _ in membership)
