import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpudl.export import (
    artifact_sizes,
    check_parity,
    export_stablehlo,
    latency_benchmark,
    load_exported,
    load_params,
    save_params,
)

# Everything here round-trips StableHLO blobs; on a jax build without
# jax.export the conftest guard skips the module instead of erroring.
pytestmark = pytest.mark.needs_jax_export


def _fn(x, w):
    return jnp.tanh(x @ w)


@pytest.fixture
def args(rng_np):
    return (
        rng_np.normal(size=(4, 8)).astype(np.float32),
        rng_np.normal(size=(8, 3)).astype(np.float32),
    )


def test_stablehlo_roundtrip(tmp_path, args):
    path = str(tmp_path / "model.stablehlo")
    blob = export_stablehlo(_fn, args, path=path)
    assert len(blob) > 0
    restored = load_exported(path)
    np.testing.assert_allclose(
        np.asarray(restored(*args)), np.asarray(_fn(*args)), rtol=1e-5
    )


def test_stablehlo_multiplatform(args):
    blob = export_stablehlo(_fn, args, platforms=("cpu", "tpu"))
    restored = load_exported(blob)
    np.testing.assert_allclose(
        np.asarray(restored(*args)), np.asarray(_fn(*args)), rtol=1e-5
    )


def test_params_roundtrip(tmp_path):
    params = {"dense": {"kernel": jnp.ones((3, 2)), "bias": jnp.zeros((2,))}}
    path = str(tmp_path / "ckpt")
    save_params(path, params)
    restored = load_params(path, like=params)
    np.testing.assert_array_equal(
        np.asarray(restored["dense"]["kernel"]), np.ones((3, 2))
    )
    sizes = artifact_sizes(path)
    assert sizes[path] > 0


def test_artifact_sizes_missing_file(tmp_path):
    missing = str(tmp_path / "nope.bin")
    assert artifact_sizes(missing)[missing] is None


def test_check_parity_same_backend(args):
    report = check_parity(
        _fn, args, device_a=jax.devices()[0], device_b=jax.devices()[0]
    )
    assert report.ok, str(report)
    assert report.max_abs_err < 1e-6


def test_compare_outputs_detects_mismatch():
    from tpudl.export.parity import compare_outputs

    a = {"logits": np.ones((4,), np.float32)}
    b = {"logits": np.ones((4,), np.float32) + 0.01}
    report = compare_outputs(a, b, rtol=1e-5, atol=1e-4)
    assert not report.ok
    assert report.max_abs_err == pytest.approx(0.01, rel=1e-3)
    good = compare_outputs(a, a, rtol=1e-5, atol=1e-4)
    assert good.ok and "PASS" in str(good)


def test_latency_benchmark_shape(args):
    result = latency_benchmark(_fn, args, warmup=1, iters=3)
    assert result["iters"] == 3
    assert result["compute"]["mean_ms"] >= 0.0
    assert result["transfer"]["p95_ms"] >= 0.0


def test_resnet_export_load_parity(tmp_path, rng_np):
    """The reference's signature behavior as a pytest guard: the flagship
    CV model family through export -> load -> numerical parity at the
    reference tolerances (reference notebooks/cv/onnx_experiments.py:
    33-42 export, :81 load, :142-144 allclose), on a tiny ResNet."""
    from tpudl.models import ResNet
    from tpudl.models.resnet import ResNetBlock

    model = ResNet(
        stage_sizes=(1, 1), block_cls=ResNetBlock, num_classes=10,
        num_filters=8, dtype=jnp.float32, small_inputs=True,
    )
    x = rng_np.normal(size=(2, 16, 16, 3)).astype(np.float32)
    variables = model.init(jax.random.key(0), jnp.asarray(x), train=False)

    def forward(params, batch_stats, images):
        return model.apply(
            {"params": params, "batch_stats": batch_stats}, images, train=False
        )

    args = (variables["params"], variables["batch_stats"], jnp.asarray(x))
    path = str(tmp_path / "resnet.stablehlo")
    export_stablehlo(forward, args, path=path)
    restored = load_exported(path)
    np.testing.assert_allclose(
        np.asarray(restored(*args)),
        np.asarray(forward(*args)),
        rtol=1e-5,
        atol=1e-4,  # the reference's parity contract
    )


def test_bert_export_load_parity(tmp_path, rng_np):
    """The NLP family through the same export -> load -> parity guard
    (the deployment artifact for configs[1]/[3] fine-tuned classifiers)."""
    from tpudl.models.bert import BERT_TINY, BertForSequenceClassification

    cfg = BERT_TINY(
        num_labels=2, dtype=jnp.float32,
        hidden_dropout=0.0, attention_dropout=0.0,
    )
    model = BertForSequenceClassification(cfg)
    ids = jnp.asarray(
        rng_np.integers(0, cfg.vocab_size, size=(2, 16)), jnp.int32
    )
    mask = jnp.ones_like(ids)
    params = model.init(jax.random.key(0), ids, train=False)["params"]

    def forward(params, input_ids, attention_mask):
        return model.apply(
            {"params": params}, input_ids, attention_mask, train=False
        )

    args = (params, ids, mask)
    path = str(tmp_path / "bert.stablehlo")
    export_stablehlo(forward, args, path=path)
    restored = load_exported(path)
    np.testing.assert_allclose(
        np.asarray(restored(*args)),
        np.asarray(forward(*args)),
        rtol=1e-5,
        atol=1e-4,  # the reference's parity contract
    )
