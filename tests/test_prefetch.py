"""Two-stage pipelined prefetch + data-wait autotuner
(tpudl.data.prefetch): ordering, shutdown/thread-reaping, prompt error
propagation, and depth autotuning — the round-5 input-pipeline overhaul's
contract surface."""

import threading
import time

import numpy as np
import pytest

from tpudl.data.prefetch import (
    DevicePrefetcher,
    PrefetchAutotuner,
    prefetch_to_device,
)

_THREAD_PREFIX = "tpudl-prefetch"


def _alive_prefetch_threads():
    return [
        t
        for t in threading.enumerate()
        if t.name.startswith(_THREAD_PREFIX) and t.is_alive()
    ]


def _wait_no_prefetch_threads(timeout=5.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if not _alive_prefetch_threads():
            return True
        time.sleep(0.01)
    return False


def _batches(n, batch=4, columns=("image", "label")):
    for i in range(n):
        out = {}
        if "image" in columns:
            out["image"] = np.full((batch, 3, 3, 2), i, np.uint8)
        if "label" in columns:
            out["label"] = np.full((batch,), i, np.int64)
        if "weight" in columns:
            out["weight"] = np.full((batch,), float(i), np.float32)
        yield out


def test_order_and_completeness_single_worker():
    got = [int(b["label"][0]) for b in prefetch_to_device(_batches(12))]
    assert got == list(range(12))
    assert _wait_no_prefetch_threads()


def test_order_preserved_with_assembly_pool():
    """Any worker count must yield the exact single-threaded sequence
    (sequence tickets reorder at the transfer stage)."""

    def jittery(batch):
        # Uneven per-batch transform latency scrambles completion order.
        time.sleep(0.001 * (int(batch["label"][0]) % 3))
        return batch

    it = prefetch_to_device(
        _batches(20), transform=jittery, assembly_workers=4
    )
    got = [int(b["label"][0]) for b in it]
    assert got == list(range(20))


def test_multi_column_batches_and_dtypes(mesh8):
    """Multi-column batches through the mesh path: every column becomes a
    global array sharded over the (dp, fsdp) batch axes, uint8 stays
    uint8 on the wire."""
    it = prefetch_to_device(
        _batches(6, batch=8, columns=("image", "label", "weight")),
        mesh=mesh8,
        assembly_workers=2,
    )
    count = 0
    for i, b in enumerate(it):
        assert set(b) == {"image", "label", "weight"}
        assert b["image"].shape == (8, 3, 3, 2)
        assert b["image"].dtype == np.uint8
        assert b["weight"].dtype == np.float32
        for v in b.values():
            assert v.sharding.spec[0] == ("dp", "fsdp")
        assert int(np.asarray(b["label"])[0]) == i
        count += 1
    assert count == 6


def test_transform_runs_in_pipeline():
    it = prefetch_to_device(
        _batches(5),
        transform=lambda b: {**b, "label": b["label"] + 100},
    )
    assert [int(b["label"][0]) for b in it] == [100, 101, 102, 103, 104]


def test_close_reaps_blocked_workers():
    """Round-5 satellite: a consumer that stops early must not leak a
    worker blocked forever on a full queue. The source here is infinite,
    so the workers are guaranteed to be blocked mid-pipeline when the
    consumer walks away."""

    def infinite():
        i = 0
        while True:
            yield {"x": np.full((4,), i, np.int32)}
            i += 1

    it = prefetch_to_device(infinite(), prefetch=2, assembly_workers=2)
    assert int(np.asarray(next(it)["x"])[0]) == 0
    assert _alive_prefetch_threads()  # pipeline genuinely running
    it.close()
    assert _wait_no_prefetch_threads(), "prefetch workers leaked after close"
    with pytest.raises(StopIteration):
        next(it)
    it.close()  # idempotent


def test_abandoned_handle_reaps_workers():
    """Dropping the handle without close() must still reap the workers:
    the threads reference only the internal pipeline (never the handle),
    so the handle's finalizer can actually fire — a thread holding a
    bound method of the handle would pin it alive forever."""
    import gc

    def infinite():
        while True:
            yield {"x": np.zeros((2,), np.float32)}

    it = prefetch_to_device(infinite(), assembly_workers=2)
    next(it)
    assert _alive_prefetch_threads()
    del it
    deadline = time.monotonic() + 5.0
    while _alive_prefetch_threads() and time.monotonic() < deadline:
        gc.collect()
        time.sleep(0.05)
    assert not _alive_prefetch_threads(), "abandoned prefetcher leaked"


def test_context_manager_and_break():
    def infinite():
        while True:
            yield {"x": np.zeros((2,), np.float32)}

    with prefetch_to_device(infinite()) as it:
        for n, _ in enumerate(it):
            if n >= 3:
                break
    assert _wait_no_prefetch_threads()


def test_error_propagates_promptly():
    """Round-5 satellite: a worker exception surfaces on the consumer's
    NEXT pull — not after every already-queued batch drains."""

    def bad():
        for i in range(3):
            yield {"x": np.full((2,), i, np.float32)}
        raise RuntimeError("reader exploded")

    # Queue deep enough that the worker queues all 3 batches AND reaches
    # the raise without the consumer pulling anything.
    it = prefetch_to_device(bad(), prefetch=8)
    deadline = time.monotonic() + 5.0
    while it._error is None and time.monotonic() < deadline:
        time.sleep(0.005)
    assert it._error is not None
    # 3 good batches are queued ahead of the failure; the error must
    # still win the consumer's very next pull (the old implementation
    # made it wait behind the whole queue).
    with pytest.raises(RuntimeError, match="reader exploded"):
        next(it)
    assert _wait_no_prefetch_threads()


def test_transform_error_propagates():
    def boom(batch):
        raise ValueError("transform exploded")

    it = prefetch_to_device(_batches(3), transform=boom)
    with pytest.raises(ValueError, match="transform exploded"):
        list(it)
    assert _wait_no_prefetch_threads()


def test_transform_stopiteration_surfaces_as_runtimeerror():
    """A transform leaking StopIteration must NOT read as clean source
    exhaustion (silent training truncation) — the prefetcher is a plain
    iterator, so PEP 479 wouldn't save it."""

    def leaky(batch):
        raise StopIteration

    it = prefetch_to_device(_batches(3), transform=leaky)
    with pytest.raises(RuntimeError, match="StopIteration"):
        list(it)
    assert _wait_no_prefetch_threads()


def test_straggling_transform_bounds_host_buffering():
    """One stuck transform must not let the other assembly workers
    stream the whole source into the transfer stage's reorder buffer:
    the ticket window parks them, bounding pulled-ahead batches."""
    release = threading.Event()
    pulled = {"n": 0}

    def src():
        for i in range(100):
            pulled["n"] += 1
            yield {"x": np.full((2,), i, np.int32)}

    def transform(batch):
        if int(batch["x"][0]) == 0:
            assert release.wait(10)
        return batch

    it = prefetch_to_device(
        src(), prefetch=2, assembly_workers=4, transform=transform
    )
    # Give the non-straggler workers time to run as far as they're
    # allowed while ticket 0 is stuck.
    time.sleep(0.3)
    # Window = host_depth(6) + workers(4) + depth(2) = 12 tickets ahead
    # of emit, plus one in-flight pull per worker. Pre-fix this was 100.
    assert pulled["n"] <= 20, pulled["n"]
    release.set()
    assert [int(np.asarray(b["x"])[0]) for b in it] == list(range(100))


def test_empty_source():
    assert list(prefetch_to_device(_batches(0))) == []


def test_env_depth_override(monkeypatch):
    monkeypatch.setenv("TPUDL_PREFETCH_DEPTH", "5")
    it = prefetch_to_device(_batches(3), prefetch=2)
    assert it.depth == 5
    assert it._autotuner is None  # pinned depth disables autotuning
    assert len(list(it)) == 3


class TestAutotuner:
    def test_grows_while_starved(self):
        at = PrefetchAutotuner(depth=2, max_depth=6, target_wait_s=0.01,
                               window=4)
        at.observe(9.9, 1000)  # first pull: pipeline fill, ignored
        for _ in range(3 * 4):
            at.observe(0.05, 1000)  # p95 far above 10 ms
        assert at.depth == 5  # +1 per full window
        assert [d[1:3] for d in at.decisions] == [(2, 3), (3, 4), (4, 5)]

    def test_holds_when_fed(self):
        at = PrefetchAutotuner(depth=2, max_depth=6, target_wait_s=0.01,
                               window=4)
        for _ in range(40):
            at.observe(0.001, 1000)
        assert at.depth == 2 and not at.decisions

    def test_respects_max_depth(self):
        at = PrefetchAutotuner(depth=2, max_depth=3, target_wait_s=0.001,
                               window=2)
        for _ in range(20):
            at.observe(1.0, 100)
        assert at.depth == 3

    def test_respects_byte_budget(self):
        # 3 slots x 500 bytes would blow the 1200-byte budget: stay at 2.
        at = PrefetchAutotuner(depth=2, max_depth=8, target_wait_s=0.001,
                               byte_budget=1200, window=2)
        for _ in range(20):
            at.observe(1.0, 500)
        assert at.depth == 2 and not at.decisions

    def test_validation(self):
        with pytest.raises(ValueError):
            PrefetchAutotuner(depth=4, max_depth=2)

    def test_autotuned_prefetcher_grows_capacity(self):
        """End-to-end: a slow source starves the consumer; the device
        queue's capacity must grow across the run."""

        def slow():
            for i in range(40):
                time.sleep(0.002)
                yield {"x": np.full((2,), i, np.int32)}

        at = PrefetchAutotuner(depth=1, max_depth=4, target_wait_s=1e-4,
                               window=4)
        it = DevicePrefetcher(slow(), depth=1, autotuner=at)
        n = sum(1 for _ in it)
        assert n == 40
        assert it.depth > 1, "depth never grew despite constant starvation"


def test_fit_drives_prefetcher_and_records_data_wait(tmp_path):
    """The training-loop integration: fit() over a two-stage prefetcher
    with device-side normalization records data_wait spans and the
    prefetcher reports its depth gauge into the obs registry."""
    import jax
    import jax.numpy as jnp
    import optax

    from tpudl.data.datasets import device_normalize_cifar, wire_cifar_batch
    from tpudl.models.resnet import ResNetTiny
    from tpudl.obs import counters as obs_counters
    from tpudl.obs import spans as obs_spans
    from tpudl.train import (
        compile_step,
        create_train_state,
        fit,
        make_classification_train_step,
    )
    from tpudl.runtime.mesh import MeshSpec, make_mesh

    def cifar_batches(n):
        rng = np.random.default_rng(0)
        for _ in range(n):
            yield {
                "image": rng.integers(0, 256, (16, 32, 32, 3)).astype(
                    np.uint8
                ),
                "label": rng.integers(0, 10, (16,)).astype(np.int64),
            }

    obs_counters.registry().reset()
    rec = obs_spans.enable(str(tmp_path / "spans.jsonl"))
    try:
        mesh = make_mesh(MeshSpec(dp=-1))
        model = ResNetTiny(num_classes=10)
        state = create_train_state(
            jax.random.key(0), model, jnp.zeros((1, 32, 32, 3)),
            optax.sgd(0.05),
        )
        step = compile_step(
            make_classification_train_step(
                input_transform=device_normalize_cifar()
            ),
            mesh, state, None,
        )
        it = prefetch_to_device(
            cifar_batches(6), mesh=mesh,
            transform=wire_cifar_batch, assembly_workers=2,
        )
        state, metrics, info = fit(step, state, it, jax.random.key(1))
        assert info["steps"] == 6
        assert np.isfinite(metrics["loss"])
        snap = obs_counters.registry().snapshot()
        assert snap["histograms"]["data_wait_s"]["count"] == 6
        assert snap["gauges"]["prefetch_depth"] >= 2
        assert snap["counters"]["prefetch_h2d_bytes"] == 6 * (
            16 * 32 * 32 * 3 + 16 * 4
        )
        spans = [r for r in rec.records if r.get("kind") == "span"]
        assert sum(1 for s in spans if s["cat"] == "data_wait") == 6
    finally:
        obs_spans.disable()
        obs_counters.registry().reset()
