"""The shared rules engine (tpudl.rules) — ROADMAP item 4's first
clause: ONE regex-over-path, first-match-wins, uncovered→raise
machinery behind quantization dtypes, PartitionSpecs, and precision
policies.

Contracts: (1) RESOLUTION — first_match semantics are exactly the
loops it replaced (search not fullmatch, first rule wins, None is a
legal value distinct from NO_MATCH), and the ported quantizer resolves
bitwise-identically to an inline reimplementation of its pre-factoring
private loop; (2) PLACEMENT — match_partition_rules produces the
SNIPPETS.md [2] shape (scalars replicate, callable specs see the leaf
shape, uncovered raises naming the leaf) over params AND optimizer
state in one call, and agrees with parallel.sharding.spec_for_path on
every covered leaf so the two consumers cannot drift.
"""

import re

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from jax.sharding import PartitionSpec as P

from tpudl import rules as rules_engine
from tpudl.models.llama import LLAMA_TINY, LlamaForCausalLM
from tpudl.parallel.sharding import (
    FSDP_RULES,
    TP_TRANSFORMER_RULES,
    spec_for_path,
)
from tpudl.quant.quantize import (
    LLAMA_QUANT_PATTERNS,
    default_quant_rules,
    is_quantized,
    match_quant_rules,
)


@pytest.fixture(scope="module")
def llama_params():
    cfg = LLAMA_TINY(dtype=jnp.float32, max_seq_len=64)
    model = LlamaForCausalLM(cfg)
    return model.init(jax.random.key(0), jnp.zeros((1, 8), jnp.int32))[
        "params"
    ]


# ---------------------------------------------------------------------------
# 1. first_match — the one resolution primitive
# ---------------------------------------------------------------------------


def test_first_match_first_rule_wins():
    rules = ((r"kernel$", "a"), (r"query/kernel$", "b"), (r".*", "c"))
    assert rules_engine.first_match(rules, "x/query/kernel") == "a"
    assert rules_engine.first_match(rules, "x/bias") == "c"


def test_first_match_is_search_not_fullmatch():
    assert (
        rules_engine.first_match(((r"proj/kernel$", 1),),
                                 "layers_0/q_proj/kernel") == 1
    )
    assert (
        rules_engine.first_match(((r"^q_proj", 1),),
                                 "layers_0/q_proj/kernel")
        is rules_engine.NO_MATCH
    )


def test_first_match_none_value_distinct_from_no_match():
    """A rule matching with value None is a decision ("keep"), not a
    miss — the distinction the quantizer's uncovered→raise rests on."""
    assert rules_engine.first_match(((r".*", None),), "x/kernel") is None
    assert (
        rules_engine.first_match((), "x/kernel") is rules_engine.NO_MATCH
    )


def test_annotate_uncovered_raises_naming_leaf():
    with pytest.raises(ValueError, match=r"no dtype rule.*mystery/kernel"):
        rules_engine.annotate(
            ((r"other$", "x"),),
            {"mystery": {"kernel": jnp.ones((2, 2))}},
            what="dtype rule",
        )


# ---------------------------------------------------------------------------
# 2. The ported quantizer resolves bitwise-identically
# ---------------------------------------------------------------------------


def _legacy_dtype_for(name, leaf, rules):
    """The pre-factoring private loop, reimplemented inline — the
    resolution semantics tpudl.quant shipped with in PR 9."""
    if is_quantized(leaf) or jnp.ndim(leaf) < 2:
        return None
    for pattern, dtype in rules:
        if re.search(pattern, name):
            return dtype
    raise ValueError(f"no quantization rule matches parameter {name!r}")


def test_quant_resolution_identical_to_legacy_loop(llama_params):
    rules = default_quant_rules(LLAMA_TINY(), "int8")
    engine = match_quant_rules(rules, llama_params)
    legacy = jax.tree_util.tree_map_with_path(
        lambda p, leaf: _legacy_dtype_for(
            rules_engine.path_str(p), leaf, rules
        ),
        llama_params,
        is_leaf=is_quantized,
    )
    assert jax.tree.structure(engine) == jax.tree.structure(legacy)
    assert jax.tree.leaves(engine) == jax.tree.leaves(legacy)
    # Sanity: the rule classes actually fire (some int8 annotations).
    assert "int8" in jax.tree.leaves(engine)


def test_quant_uncovered_message_preserved():
    """The engine-raised message keeps the pre-port prefix callers and
    tests match on."""
    with pytest.raises(ValueError, match="no quantization rule"):
        match_quant_rules(
            ((r"other/kernel$", "int8"),),
            {"mystery": {"kernel": jnp.ones((4, 4))}},
        )


# ---------------------------------------------------------------------------
# 3. match_partition_rules — the placement adapter (ROADMAP item 4 seam)
# ---------------------------------------------------------------------------

#: A COVERING Llama rule set: the TP preset's projection placements
#: plus explicit keep rules for every remaining leaf class — the
#: uncovered→raise contract then proves nothing slipped through.
_COVERING_RULES = TP_TRANSFORMER_RULES + (
    (r"(embedding|scale|bias)$", P()),
    (r"^(count|mu|nu)$", P()),  # bare optax counters at the tree root
)


def test_partition_rules_cover_params_and_opt_state(llama_params):
    """One call covers the WHOLE TrainState payload: optimizer moment
    trees mirror params, so kernel$-style rules address their leaves
    at the opt_state/.../mu/... paths too."""
    tx = optax.adamw(1e-3)
    tree = {
        "params": llama_params,
        "opt_state": tx.init(llama_params),
    }
    specs = rules_engine.match_partition_rules(_COVERING_RULES, tree)
    spec_leaves = jax.tree.leaves(
        specs, is_leaf=lambda x: isinstance(x, P)
    )
    param_leaves = jax.tree.leaves(tree)
    assert len(spec_leaves) == len(param_leaves)
    assert all(isinstance(s, P) for s in spec_leaves)
    # The projection placements fired — on params AND on the moments.
    flat = {
        rules_engine.path_str(p): s
        for p, s in jax.tree_util.tree_flatten_with_path(
            specs, is_leaf=lambda x: isinstance(x, P)
        )[0]
    }
    q_params = [
        k for k in flat
        if k.startswith("params/") and re.search(r"q_proj/kernel$", k)
    ]
    q_moments = [
        k for k in flat
        if k.startswith("opt_state/") and re.search(r"q_proj/kernel$", k)
        and "/mu/" in k
    ]
    assert q_params and q_moments
    for k in q_params + q_moments:
        assert flat[k] == P("fsdp", "tp"), (k, flat[k])


def test_partition_rules_uncovered_raises(llama_params):
    """Dropping the keep rules makes the first uncovered multi-element
    leaf (the embedding table — its path doesn't match the TP preset's
    ``embedding/embedding$`` pattern) raise by name — coverage is
    enforced, not defaulted."""
    with pytest.raises(
        ValueError, match=r"no partition rule.*embed_tokens"
    ):
        rules_engine.match_partition_rules(
            TP_TRANSFORMER_RULES, llama_params
        )


def test_partition_rules_explicit_default_replicates():
    tree = {"w": jnp.ones((4, 4)), "b": jnp.ones((4,))}
    specs = rules_engine.match_partition_rules(
        ((r"w$", P("fsdp", None)),), tree, default=P()
    )
    assert specs["w"] == P("fsdp", None)
    assert specs["b"] == P()


def test_partition_rules_scalars_replicate_without_rules():
    """The SNIPPETS.md [2] scalar contract: 0-d and single-element
    leaves replicate before any rule lookup."""
    specs = rules_engine.match_partition_rules(
        (), {"count": jnp.zeros(()), "one": jnp.ones((1,))}
    )
    assert specs == {"count": P(), "one": P()}


def test_partition_rules_callable_spec_sees_shape(llama_params):
    """Rank-dependent placement (the FSDP largest-dim idiom) works
    through the adapter — and agrees with spec_for_path leaf by leaf,
    so the legacy consumer and the adapter cannot drift."""
    rules = FSDP_RULES + ((r".*", P()),)
    specs = rules_engine.match_partition_rules(rules, llama_params)
    flat_specs = jax.tree_util.tree_flatten_with_path(
        specs, is_leaf=lambda x: isinstance(x, P)
    )[0]
    flat_params = jax.tree_util.tree_flatten_with_path(llama_params)[0]
    checked = 0
    for (path, spec), (_, leaf) in zip(flat_specs, flat_params):
        shape = jnp.shape(leaf)
        if len(shape) == 0 or int(np.prod(shape)) == 1:
            assert spec == P()
            continue
        assert spec == spec_for_path(
            rules_engine.path_str(path), rules, shape
        )
        checked += 1
    assert checked > 10
    # And at least one kernel actually landed a sharded dim.
    assert any(
        s != P()
        for _, s in flat_specs
    )
