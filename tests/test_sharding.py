import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from tpudl.parallel.sharding import (
    FSDP_RULES,
    TP_TRANSFORMER_RULES,
    spec_for_path,
    tree_shardings,
)


def test_spec_for_path_default_replicated():
    assert spec_for_path("params/Dense_0/kernel", None) == P()
    assert spec_for_path("params/bn/scale", FSDP_RULES) == P()


def test_spec_for_path_fsdp():
    assert spec_for_path("params/Dense_0/kernel", FSDP_RULES, (128, 64)) == P(
        "fsdp", None
    )


def test_fsdp_conv_kernel_shards_channel_dim(mesh8):
    # (kh, kw, in, out) conv kernel: FSDP must shard the channel dim, not kh=3.
    tree = {"conv": {"kernel": jnp.zeros((3, 3, 16, 32))}}
    sh = tree_shardings(mesh8, tree, FSDP_RULES)
    assert sh["conv"]["kernel"].spec == P(None, None, None, "fsdp")


def test_spec_for_path_tp_rules_order():
    assert spec_for_path(
        "params/layer_0/attention/query/kernel", TP_TRANSFORMER_RULES
    ) == P("fsdp", "tp")
    assert spec_for_path(
        "params/layer_0/mlp/wo/kernel", TP_TRANSFORMER_RULES
    ) == P("tp", "fsdp")
    # generic kernel falls through to the last rule
    assert spec_for_path("params/head/kernel", TP_TRANSFORMER_RULES) == P(
        "fsdp", None
    )


def test_tree_shardings_clamps_indivisible(mesh8):
    # fsdp axis is size 2: largest dim sharded; indivisible dims -> replicated
    tree = {
        "a": {"kernel": jnp.zeros((8, 6))},
        "b": {"kernel": jnp.zeros((4, 7))},  # largest dim 7 not divisible by 2
        "c": {"bias": jnp.zeros((6,))},
    }
    sh = tree_shardings(mesh8, tree, FSDP_RULES)
    assert sh["a"]["kernel"].spec == P("fsdp", None)
    assert sh["b"]["kernel"].spec == P(None, None)
    assert sh["c"]["bias"].spec == P()


def test_tree_shardings_puts_arrays(mesh8):
    import jax

    tree = {"w": {"kernel": jnp.ones((8, 4))}}
    sh = tree_shardings(mesh8, tree, FSDP_RULES)
    placed = jax.device_put(tree, sh)
    np.testing.assert_array_equal(np.asarray(placed["w"]["kernel"]), 1.0)
    assert placed["w"]["kernel"].sharding.spec == P("fsdp", None)
