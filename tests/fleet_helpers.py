"""Subprocess target for the cross-process migration test.

Run as ``python -m tests.fleet_helpers <request_id>`` from the repo
root. The child builds the SAME deterministic tiny serving session the
parent holds (same init seed, same config — params are therefore
byte-identical), opens a ``MigrationEndpoint``, prints its port as one
JSON line, and then drives the engine until the migrated-in request
finishes, printing the result as a second JSON line:

    {"port": <int>}
    {"tokens": [...], "finish_reason": "...", "prefills": <int>}

``prefills`` is the child engine's TOTAL prefill-dispatch count — the
parent asserts it stays 0, which is the whole point of shipping KV
instead of re-prefilling on the survivor.
"""

import json
import os
import sys
import time

# Same hermetic backend as tests/conftest.py — this process has no
# conftest, so pin it here before jax initializes.
os.environ["JAX_PLATFORMS"] = "cpu"

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402

PROMPT_LEN = 8
PAGE = 8


def build_session():
    from tpudl.models.llama import LLAMA_TINY, LlamaForCausalLM
    from tpudl.serve import ServeSession

    cfg = LLAMA_TINY(dtype=jnp.float32, max_seq_len=96)
    model = LlamaForCausalLM(cfg)
    params = model.init(
        jax.random.key(0), jnp.zeros((1, PROMPT_LEN), jnp.int32)
    )["params"]
    return ServeSession.from_model(
        model, params, PROMPT_LEN, num_slots=2, paged=True,
        page_size=PAGE,
    )


def main(argv) -> int:
    from tpudl.fleet.transport import MigrationEndpoint, deliver_to_session

    rid = argv[1]
    session = build_session()
    with MigrationEndpoint(
        lambda p: deliver_to_session(session, p)
    ) as endpoint:
        print(json.dumps({"port": endpoint.address[1]}), flush=True)
        deadline = time.monotonic() + 600.0
        while rid not in session.engine.results:
            if not session.engine.step():
                time.sleep(0.01)
            if time.monotonic() > deadline:
                print(json.dumps({"error": "timeout"}), flush=True)
                return 1
    res = session.engine.results[rid]
    print(json.dumps({
        "tokens": [int(t) for t in res.tokens],
        "finish_reason": res.finish_reason,
        "prefills": int(session.engine.num_prefills),
    }), flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv))
