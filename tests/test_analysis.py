"""tpudl.analysis — the ISSUE-12 static + runtime analysis tier.

Three families, each tested on seeded fixture violations (caught) and
clean fixtures (silent), plus the gate acceptance: the SHIPPED tree
has zero unbaselined findings, and the two dispatch audits pass over a
50-step serving decode steady state and a K=8 fused training window.
"""

import json
import os
import subprocess
import sys
import textwrap
import threading

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

import tpudl
from tpudl.analysis import concurrency as conc
from tpudl.analysis import findings as F
from tpudl.analysis import registry as reg
from tpudl.analysis.dispatch import (
    DispatchHygieneError,
    RecompileWatcher,
    assert_no_host_transfers,
    assert_no_recompiles,
)
from tpudl.analysis.donation import (
    DonationError,
    assert_donation,
    audit_donation,
)
from tpudl.analysis.lint import lint_source, run_lint

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _rules(findings):
    return {f.rule for f in findings}


# ---------------------------------------------------------------------------
# concurrency: seeded violations caught, clean fixtures pass
# ---------------------------------------------------------------------------


def test_lock_order_inversion_direct_nesting_caught():
    src = textwrap.dedent(
        """
        import threading
        class T:
            def __init__(self):
                self._a = threading.Lock()
                self._b = threading.Lock()
            def one(self):
                with self._a:
                    with self._b:
                        pass
            def two(self):
                with self._b:
                    with self._a:
                        pass
        """
    )
    found = conc.analyze_source(src, "fix.py")
    assert _rules(found) == {"lock-order-inversion"}
    assert found[0].severity == "P0"
    assert "_a" in found[0].message and "_b" in found[0].message


def test_lock_order_inversion_through_method_call_caught():
    """one() holds _a and calls _grab_b(); two() holds _b and calls
    one() — the inversion only exists through the call graph."""
    src = textwrap.dedent(
        """
        import threading
        class T:
            def __init__(self):
                self._a = threading.Lock()
                self._b = threading.Lock()
            def one(self):
                with self._a:
                    self._grab_b()
            def _grab_b(self):
                with self._b:
                    pass
            def two(self):
                with self._b:
                    self.one()
        """
    )
    assert _rules(conc.analyze_source(src, "call.py")) == {
        "lock-order-inversion"
    }


def test_unguarded_shared_write_caught_and_init_excluded():
    src = textwrap.dedent(
        """
        import threading
        class T:
            def __init__(self):
                self._lock = threading.Lock()
                self.n = 0      # construction: never a finding
            def bump(self):
                with self._lock:
                    self.n += 1
            def race(self):
                self.n = 5
        """
    )
    found = conc.analyze_source(src, "write.py")
    assert [f.rule for f in found] == ["unguarded-shared-write"]
    assert found[0].symbol == "T.race"


def test_container_mutation_counts_as_write():
    src = textwrap.dedent(
        """
        import threading
        class T:
            def __init__(self):
                self._lock = threading.Lock()
                self.items = []
            def push(self, x):
                with self._lock:
                    self.items.append(x)
            def race(self, x):
                self.items.append(x)
        """
    )
    assert _rules(conc.analyze_source(src, "mut.py")) == {
        "unguarded-shared-write"
    }


def test_condition_aliases_to_underlying_lock():
    """``with self._not_empty:`` counts as holding _lock — the
    bounded-queue idiom (prefetch) must analyze clean."""
    src = textwrap.dedent(
        """
        import threading
        class Q:
            def __init__(self):
                self._lock = threading.Lock()
                self._not_empty = threading.Condition(self._lock)
                self.items = []
            def put(self, x):
                with self._not_empty:
                    self.items.append(x)
            def reset(self):
                with self._lock:
                    self.items = []
        """
    )
    assert conc.analyze_source(src, "cond.py") == []


def test_private_method_inherits_callers_lock():
    """The "callers hold _books" idiom: a private helper written only
    under its callers' lock is not an unguarded write."""
    src = textwrap.dedent(
        """
        import threading
        class C:
            def __init__(self):
                self._lock = threading.Lock()
                self.n = 0
            def bump(self):
                with self._lock:
                    self.n += 1
            def _helper(self):
                self.n = 5
            def outer(self):
                with self._lock:
                    self._helper()
        """
    )
    assert conc.analyze_source(src, "inherit.py") == []


def test_lockless_class_is_skipped():
    src = textwrap.dedent(
        """
        class Engine:
            def __init__(self):
                self.n = 0
            def step(self):
                self.n += 1
        """
    )
    assert conc.analyze_source(src, "engine.py") == []


def test_derive_lock_ranks_orders_acquisition_graph():
    src_path = os.path.join("/tmp", "tpudl_rank_fixture.py")
    with open(src_path, "w") as f:
        f.write(textwrap.dedent(
            """
            import threading
            class T:
                def __init__(self):
                    self._outer = threading.Lock()
                    self._inner = threading.Lock()
                def go(self):
                    with self._outer:
                        with self._inner:
                            pass
            """
        ))
    ranks = conc.derive_lock_ranks([src_path])
    assert ranks["T._outer"] < ranks["T._inner"]


# ---------------------------------------------------------------------------
# runtime lock-order monitor
# ---------------------------------------------------------------------------


def test_ordered_lock_detects_live_cycle():
    mon = conc.LockOrderMonitor()
    a = conc.OrderedLock(threading.Lock(), "A", mon)
    b = conc.OrderedLock(threading.Lock(), "B", mon)
    with a:
        with b:
            pass
    with pytest.raises(conc.LockOrderViolation, match="inversion"):
        with b:
            with a:
                pass


def test_ordered_lock_asserts_static_ranks():
    mon = conc.LockOrderMonitor(ranks={"A": 0, "B": 1})
    a = conc.OrderedLock(threading.Lock(), "A", mon)
    b = conc.OrderedLock(threading.Lock(), "B", mon)
    # The static ranks catch the inversion on its FIRST occurrence —
    # before any reverse path has ever run (which is what the live
    # cycle detector would need).
    with pytest.raises(conc.LockOrderViolation, match="static"):
        with b:
            with a:
                pass


def test_ordered_rlock_reentry_is_not_a_violation():
    mon = conc.LockOrderMonitor()
    r = conc.OrderedLock(threading.RLock(), "R", mon)
    with r:
        with r:
            pass
    assert mon.violations == []
    assert mon.acquisitions == 2


def test_wrap_instance_locks_wraps_locks_not_conditions():
    class Obj:
        def __init__(self):
            self._lock = threading.Lock()
            self._rlock = threading.RLock()
            self._cond = threading.Condition()

    obj = Obj()
    mon = conc.LockOrderMonitor()
    wrapped = conc.wrap_instance_locks(obj, mon)
    assert set(wrapped) == {"Obj._lock", "Obj._rlock"}
    assert isinstance(obj._lock, conc.OrderedLock)
    assert isinstance(obj._cond, threading.Condition)
    with obj._lock:  # still a working lock
        pass


def test_maybe_wrap_locks_is_noop_without_flag(monkeypatch):
    monkeypatch.delenv("TPUDL_DEBUG_LOCK_ORDER", raising=False)

    class Obj:
        def __init__(self):
            self._lock = threading.Lock()

    obj = Obj()
    assert conc.maybe_wrap_locks(obj) == []
    assert not isinstance(obj._lock, conc.OrderedLock)


# ---------------------------------------------------------------------------
# registry linter: seeded fixtures
# ---------------------------------------------------------------------------


def test_raw_env_read_caught_literal_and_constant():
    src = textwrap.dedent(
        """
        import os
        KNOB = "TPUDL_OBS_DIR"
        def direct():
            return os.environ.get("TPUDL_SERVE_SLOTS")
        def subscripted():
            return os.environ["TPUDL_OBS_DIR"]
        def via_constant():
            return os.environ.get(KNOB)
        """
    )
    found = lint_source(src, "raw.py")
    raws = [f for f in found if f.rule == "raw-env-read"]
    assert len(raws) == 3
    assert all(f.severity == "P0" for f in raws)


def test_env_write_and_non_tpudl_keys_pass():
    src = textwrap.dedent(
        """
        import os
        def ok():
            os.environ["TPUDL_NORM_BLOCK_ROWS"] = "64"   # a WRITE: pins
            flags = os.environ.get("XLA_FLAGS", "")
            return flags
        """
    )
    assert lint_source(src, "ok.py") == []


def test_undeclared_knob_literal_caught():
    src = 'FLAG = "TPUDL_TOTALLY_NEW_KNOB"\n'
    found = lint_source(src, "undecl.py")
    assert [f.rule for f in found] == ["undeclared-knob"]
    assert "TPUDL_TOTALLY_NEW_KNOB" in found[0].message


def test_bad_metric_name_caught_literal_and_fstring():
    src = textwrap.dedent(
        """
        def record(reg, suffix):
            reg.counter("serve ttft.ms").inc()
            reg.gauge(f"Replica-{suffix}_busy").set(1)
            reg.histogram("serve_ttft_ms").observe(1.0)
            reg.gauge(f"serve_replica_{suffix}_ready").set(1)
        """
    )
    found = lint_source(src, "metric.py")
    assert [f.rule for f in found] == [
        "bad-metric-name", "bad-metric-name"
    ]
    assert found[0].line == 3 and found[1].line == 4


# ---------------------------------------------------------------------------
# knob registry accessors
# ---------------------------------------------------------------------------


def test_env_accessors_semantics(monkeypatch):
    monkeypatch.setenv("TPUDL_SERVE_SLOTS", "8")
    assert reg.env_int("TPUDL_SERVE_SLOTS", 4) == 8
    monkeypatch.setenv("TPUDL_SERVE_SLOTS", "")
    assert reg.env_int("TPUDL_SERVE_SLOTS", 4) == 4  # empty == unset
    monkeypatch.setenv("TPUDL_SERVE_SLOTS", "zero")
    with pytest.raises(ValueError, match="TPUDL_SERVE_SLOTS"):
        reg.env_int("TPUDL_SERVE_SLOTS", 4)
    monkeypatch.setenv("TPUDL_SERVE_SLOTS", "0")
    with pytest.raises(ValueError, match=">= 1"):
        reg.env_int("TPUDL_SERVE_SLOTS", 4, min_value=1)
    for truthy in ("1", "true", "YES", "on"):
        monkeypatch.setenv("TPUDL_SERVE_PAGED", truthy)
        assert reg.env_flag("TPUDL_SERVE_PAGED")
    monkeypatch.setenv("TPUDL_SERVE_PAGED", "0")
    assert not reg.env_flag("TPUDL_SERVE_PAGED")
    monkeypatch.setenv("TPUDL_FT_GRACE_S", "2.5")
    assert reg.env_float("TPUDL_FT_GRACE_S", 15.0) == 2.5


def test_undeclared_knob_read_raises():
    with pytest.raises(reg.UnknownKnobError):
        reg.env_str("TPUDL_NOT_A_KNOB")


def test_knob_table_covers_every_declared_knob():
    table = reg.knob_table_markdown()
    for name in reg.KNOBS:
        assert f"`{name}`" in table, name


def test_readme_knob_table_is_in_sync():
    """The README embeds the GENERATED table between markers; drift
    fails here (and as an undocumented-knob lint finding)."""
    with open(os.path.join(REPO_ROOT, "README.md")) as f:
        readme = f.read()
    begin = "<!-- knob-table:begin -->\n"
    end = "<!-- knob-table:end -->"
    assert begin in readme and end in readme
    embedded = readme.split(begin, 1)[1].split(end, 1)[0]
    assert embedded == reg.knob_table_markdown(), (
        "README knob table drifted — regenerate with "
        "scripts/lint_tpudl.py --knob-table"
    )


# ---------------------------------------------------------------------------
# baseline ratchet
# ---------------------------------------------------------------------------


def _finding(msg="m", line=3):
    return F.Finding(
        rule="r", path="p.py", line=line, symbol="S.m", message=msg
    )


def test_fingerprint_survives_line_moves_not_message_changes():
    assert _finding(line=3).fingerprint == _finding(line=99).fingerprint
    assert _finding("a").fingerprint != _finding("b").fingerprint


def test_apply_baseline_new_known_stale():
    known = _finding("known")
    new = _finding("new")
    baseline = {
        known.fingerprint: F.BaselineEntry.from_finding(known, "ok"),
        "deadbeefdeadbeef": F.BaselineEntry(
            "deadbeefdeadbeef", "r", "gone.py", "S", "paid", "was fixed"
        ),
    }
    result = F.apply_baseline([known, new], baseline)
    assert not result.ok
    assert [f.message for f in result.new] == ["new"]
    assert [f.message for f in result.baselined] == ["known"]
    assert [e.fingerprint for e in result.stale] == ["deadbeefdeadbeef"]


def test_baseline_round_trip_preserves_justification(tmp_path):
    path = str(tmp_path / "baseline.json")
    entry = F.BaselineEntry.from_finding(
        _finding("debt"), "benign: single-writer publish"
    )
    F.save_baseline(path, [entry])
    loaded = F.load_baseline(path)
    assert loaded[entry.fingerprint].justification == (
        "benign: single-writer publish"
    )


# ---------------------------------------------------------------------------
# the gate on the shipped tree
# ---------------------------------------------------------------------------


def test_shipped_tree_has_zero_unbaselined_findings():
    """The ISSUE-12 acceptance bar: the analyzers run over the real
    tree and every finding is either fixed or baselined."""
    found = run_lint(REPO_ROOT)
    baseline_path = os.path.join(REPO_ROOT, "analysis_baseline.json")
    baseline = (
        F.load_baseline(baseline_path)
        if os.path.exists(baseline_path) else {}
    )
    result = F.apply_baseline(found, baseline)
    assert result.ok, "NEW findings:\n" + "\n".join(
        f.format() for f in result.new
    )
    assert not result.stale, (
        "stale baseline entries (debt was paid — delete them): "
        + ", ".join(e.fingerprint for e in result.stale)
    )


def test_lint_cli_exits_zero_on_tree_and_prints_knob_table():
    script = os.path.join(REPO_ROOT, "scripts", "lint_tpudl.py")
    proc = subprocess.run(
        [sys.executable, script], capture_output=True, text=True,
        cwd=REPO_ROOT,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    table = subprocess.run(
        [sys.executable, script, "--knob-table"],
        capture_output=True, text=True, cwd=REPO_ROOT,
    )
    assert table.returncode == 0
    assert table.stdout == reg.knob_table_markdown()
    js = subprocess.run(
        [sys.executable, script, "--json"],
        capture_output=True, text=True, cwd=REPO_ROOT,
    )
    assert js.returncode == 0
    doc = json.loads(js.stdout)
    assert doc["new"] == []


# ---------------------------------------------------------------------------
# dispatch hygiene: seeded violations + acceptance steady states
# ---------------------------------------------------------------------------


def test_assert_no_recompiles_catches_varying_shape():
    f = jax.jit(lambda x: x * 2)
    f(jnp.ones(4))  # warmup
    with pytest.raises(DispatchHygieneError, match="recompil"):
        with assert_no_recompiles():
            for n in range(5, 8):  # new shape per step: the seeded bug
                f(jnp.ones(n))


def test_assert_no_recompiles_passes_warm_loop():
    f = jax.jit(lambda x: x * 2)
    x = jnp.ones(8)
    f(x)
    with assert_no_recompiles() as watcher:
        for _ in range(10):
            f(x)
    assert watcher.count == 0


def test_assert_no_host_transfers_catches_implicit_h2d():
    f = jax.jit(lambda x: x + 1)
    f(jnp.ones(4, jnp.float32))
    with pytest.raises(DispatchHygieneError, match="implicit"):
        with assert_no_host_transfers():
            # np array into a jitted call = implicit h2d: the seeded
            # "host value leaked into the hot loop" bug.
            f(np.ones(4, np.float32))


def test_assert_no_host_transfers_allowance_and_explicit_pass():
    f = jax.jit(lambda x: x + 1)
    x = jnp.ones(4, jnp.float32)
    f(x)
    with assert_no_host_transfers(allow=("h2d",)):
        f(np.ones(4, np.float32))  # allowed direction
    with assert_no_host_transfers():
        y = f(jax.device_put(np.ones(4, np.float32)))  # explicit: fine
    assert jax.device_get(y).shape == (4,)
    with pytest.raises(ValueError, match="unknown transfer kinds"):
        with assert_no_host_transfers(allow=("sideways",)):
            pass


def test_serving_decode_steady_state_is_dispatch_clean():
    """ISSUE-12 acceptance: a 50-step serving decode steady state
    neither recompiles nor implicitly transfers (beyond the per-step
    h2d control arrays, which are by design — every intended readback
    in the engine is an explicit jax.device_get)."""
    from benchmarks.serve_load import build_session, warmup_session
    from tpudl.serve import Request

    session, _, _ = build_session(num_slots=2)
    warmup_session(session)
    steps0 = session.engine.num_decode_steps
    # 52 new tokens = 1 from prefill + 51 decode steps: the audited
    # window spans >= 50 decode dispatches.
    requests = [
        Request("steady0", [5, 6, 7], max_new_tokens=52),
        Request("steady1", [9, 4], max_new_tokens=30),
    ]
    with assert_no_recompiles(label="serve decode steady state"):
        with assert_no_host_transfers(
            allow=("h2d",), label="serve decode steady state"
        ):
            results = session.serve(requests)
    assert session.engine.num_decode_steps - steps0 >= 50
    assert all(r.ok for r in results.values())


def test_fused_training_window_is_dispatch_clean():
    """ISSUE-12 acceptance: one K=8 fused dispatch window (device-
    resident inputs, donated carry) runs with zero recompiles and
    zero implicit transfers in ANY direction after warmup."""
    from tpudl.models.bert import BertConfig, BertForSequenceClassification
    from tpudl.runtime.mesh import MeshSpec, make_mesh
    from tpudl.train.loop import (
        compile_step,
        create_train_state,
        make_classification_train_step,
    )

    cfg = BertConfig(
        vocab_size=64, hidden_size=16, num_layers=1, num_heads=2,
        intermediate_size=32, hidden_dropout=0.0, attention_dropout=0.0,
        dtype=jnp.float32,
    )
    model = BertForSequenceClassification(cfg)
    state = create_train_state(
        jax.random.key(0), model, jnp.zeros((1, 8), jnp.int32),
        optax.adamw(1e-3),
    )
    mesh = make_mesh(MeshSpec(dp=-1))
    step = compile_step(
        make_classification_train_step(
            input_keys=("input_ids", "attention_mask"), label_key="label"
        ),
        mesh, state, None, steps_per_dispatch=8,
    )
    state = jax.device_put(state, step.state_shardings)
    rng_np = np.random.default_rng(0)
    # Batch 8: divisible by the fake 8-device dp mesh the test env
    # forces (XLA_FLAGS host platform device count).
    window = {
        "input_ids": rng_np.integers(0, 64, (8, 8, 8)).astype(np.int32),
        "attention_mask": np.ones((8, 8, 8), np.int32),
        "label": rng_np.integers(0, 2, (8, 8)).astype(np.int32),
    }
    window = jax.device_put(window)  # explicit H2D, outside the audit
    rng = jax.random.key(1)
    state, _ = step.window_step(state, window, rng)  # warmup compile
    with assert_no_recompiles(label="K=8 fused window"):
        with assert_no_host_transfers(label="K=8 fused window"):
            state, stacked = step.window_step(state, window, rng)
    assert np.asarray(jax.device_get(stacked["loss"])).shape == (8,)


def test_recompile_watcher_counts_without_raising():
    f = jax.jit(lambda x: x - 1)
    f(jnp.ones(3))
    with RecompileWatcher() as w:
        f(jnp.ones(6))
    assert w.count >= 1
    assert w.count == w.count  # stable after exit


# ---------------------------------------------------------------------------
# donation audit
# ---------------------------------------------------------------------------


def test_audit_donation_passes_on_donating_program():
    g = jax.jit(lambda s: jax.tree.map(lambda x: x + 1, s),
                donate_argnums=0)
    s = jax.device_put({"w": jnp.ones((64, 64)), "b": jnp.ones(64)})
    out, report = audit_donation(g, (s,))
    assert report.ok and report.num_deleted == 2
    assert jax.device_get(out["b"])[0] == 2.0


def test_audit_donation_catches_lost_donation():
    h = jax.jit(lambda s: jax.tree.map(lambda x: x + 1, s))  # no donation
    s = jax.device_put({"w": jnp.ones((64, 64))})
    _, report = audit_donation(h, (s,))
    assert not report.ok
    assert report.undeleted  # names the copied leaves
    s2 = jax.device_put({"w": jnp.ones((64, 64))})
    with pytest.raises(DonationError, match="NOT consumed"):
        assert_donation(h, (s2,))
