"""Real-data ingesters, round-tripped against fixtures written in the
REAL distribution formats (CIFAR-10 python pickles with bytes keys and
CHW plane rows inside a tar.gz; GLUE SST-2 tab-separated-no-quoting TSV)
— the dataset counterpart of the HF-weight import parity tests."""

import os
import pickle
import tarfile

import numpy as np
import pytest

from tpudl.data.ingest import ingest_cifar10, ingest_sst2_tsv


def _cifar_fixture_batch(rng, n):
    """(pickle dict in the real format, expected HWC images, labels)."""
    hwc = rng.integers(0, 256, size=(n, 32, 32, 3)).astype(np.uint8)
    # real format: [N, 3072] = R plane then G then B, row-major per plane
    rows = hwc.transpose(0, 3, 1, 2).reshape(n, 3072)
    labels = rng.integers(0, 10, size=(n,))
    d = {
        b"data": rows,
        b"labels": labels.tolist(),
        b"batch_label": b"testing batch 1 of 5",
        b"filenames": [b"x.png"] * n,
    }
    return d, hwc, labels.astype(np.int64)


def _write_cifar_archive(tmp_path, batches, as_tar):
    """Write data_batch_i pickles either extracted or inside a tar.gz
    under the real cifar-10-batches-py/ prefix."""
    root = tmp_path / "cifar-10-batches-py"
    root.mkdir()
    for i, (d, _, _) in enumerate(batches, start=1):
        with open(root / f"data_batch_{i}", "wb") as f:
            pickle.dump(d, f)
    # test_batch always present like the real archive
    with open(root / "test_batch", "wb") as f:
        pickle.dump(batches[0][0], f)
    if not as_tar:
        return str(tmp_path)
    tar_path = tmp_path / "cifar-10-python.tar.gz"
    with tarfile.open(tar_path, "w:gz") as tf:
        tf.add(root, arcname="cifar-10-batches-py")
    return str(tar_path)


@pytest.mark.parametrize("as_tar", [False, True])
def test_ingest_cifar10_roundtrip(tmp_path, as_tar):
    rng = np.random.default_rng(0)
    batches = [_cifar_fixture_batch(rng, 40) for _ in range(5)]
    src = _write_cifar_archive(tmp_path, batches, as_tar)

    conv = ingest_cifar10(src, str(tmp_path / "out"))
    assert conv.num_rows == 200
    # ordered read-back equals the concatenated archive content, pixel
    # for pixel in HWC orientation
    got = {"image": [], "label": []}
    for b in conv.make_batch_iterator(20, shuffle=False, drop_last=False,
                                      shard_index=0, num_shards=1):
        got["image"].append(b["image"])
        got["label"].append(b["label"])
    images = np.concatenate(got["image"])
    labels = np.concatenate(got["label"])
    want_images = np.concatenate([hwc for _, hwc, _ in batches])
    want_labels = np.concatenate([lab for _, _, lab in batches])
    np.testing.assert_array_equal(images, want_images)
    np.testing.assert_array_equal(labels, want_labels)
    assert images.dtype == np.uint8


def test_ingest_cifar10_test_split_and_errors(tmp_path):
    rng = np.random.default_rng(1)
    batches = [_cifar_fixture_batch(rng, 8) for _ in range(5)]
    src = _write_cifar_archive(tmp_path, batches, as_tar=False)
    conv = ingest_cifar10(src, str(tmp_path / "t"), split="test")
    assert conv.num_rows == 8
    with pytest.raises(ValueError, match="train|test"):
        ingest_cifar10(src, str(tmp_path / "x"), split="val")
    with pytest.raises(FileNotFoundError):
        ingest_cifar10(str(tmp_path / "nowhere"), str(tmp_path / "y"))


def test_ingest_cifar10_feeds_training_pipeline(tmp_path):
    """Ingested real-format data flows through the augmenter exactly like
    the synthetic materializer's output."""
    from tpudl.data.augment import BatchAugmenter

    rng = np.random.default_rng(2)
    batches = [_cifar_fixture_batch(rng, 16) for _ in range(5)]
    src = _write_cifar_archive(tmp_path, batches, as_tar=True)
    conv = ingest_cifar10(src, str(tmp_path / "out"))
    aug = BatchAugmenter(crop=(32, 32), pad=4, seed=0)
    b = next(conv.make_batch_iterator(16, shuffle=True, shard_index=0,
                                      num_shards=1, transform=aug))
    assert b["image"].shape == (16, 32, 32, 3)
    assert b["image"].dtype == np.float32


def test_ingest_sst2_tsv_roundtrip(tmp_path):
    # Real GLUE SST-2 format: header, tab-separated, NO quoting — include
    # sentences with quotes/commas that would break csv-module parsing.
    rows = [
        ("hide new secretions from the parental units", 0),
        ('contains no wit , only labored "gags"', 0),
        ("that loves its characters and communicates something", 1),
        ("remains utterly satisfied to remain the same throughout", 0),
        ("it's a charming and often affecting journey", 1),
    ]
    tsv = tmp_path / "SST-2" / "train.tsv"
    tsv.parent.mkdir()
    with open(tsv, "w", encoding="utf-8") as f:
        f.write("sentence\tlabel\n")
        for s, lab in rows:
            f.write(f"{s}\t{lab}\n")

    # by file path and by GLUE directory
    for src in (str(tsv), str(tmp_path / "SST-2")):
        out = str(tmp_path / f"out-{os.path.basename(src)}")
        conv = ingest_sst2_tsv(src, out)
        b = next(conv.make_batch_iterator(5, shuffle=False, drop_last=False,
                                          shard_index=0, num_shards=1))
        assert [str(s) for s in b["sentence"]] == [s for s, _ in rows]
        assert b["label"].tolist() == [lab for _, lab in rows]


def test_ingest_sst2_tsv_tokenizer_vertical(tmp_path):
    """TSV -> text Parquet -> WordPiece ids Parquet, the full raw-text
    chain on real-format input."""
    from tpudl.data.datasets import tokenize_text_dataset
    from tpudl.data.tokenizer import WordPieceTokenizer, build_wordpiece_vocab

    tsv = tmp_path / "train.tsv"
    with open(tsv, "w", encoding="utf-8") as f:
        f.write("sentence\tlabel\n")
        for i in range(64):
            s = "a fine movie" if i % 2 else "a dull movie"
            f.write(f"{s}\t{i % 2}\n")
    text_conv = ingest_sst2_tsv(str(tsv), str(tmp_path / "text"))
    corpus = (
        str(s)
        for b in text_conv.make_batch_iterator(
            16, epochs=1, shuffle=False, drop_last=False,
            shard_index=0, num_shards=1, columns=("sentence",),
        )
        for s in b["sentence"]
    )
    tok = WordPieceTokenizer(build_wordpiece_vocab(corpus, 128))
    ids_conv = tokenize_text_dataset(
        str(tmp_path / "text"), str(tmp_path / "ids"), tok, seq_len=16
    )
    b = next(ids_conv.make_batch_iterator(32, shuffle=False,
                                          shard_index=0, num_shards=1))
    assert b["input_ids"].shape == (32, 16)
    assert set(b["label"].tolist()) == {0, 1}


def test_ingest_sst2_tsv_errors(tmp_path):
    bad = tmp_path / "bad.tsv"
    with open(bad, "w") as f:
        f.write("foo\tbar\n")
        f.write("x\t1\n")
    with pytest.raises(ValueError, match="lacks"):
        ingest_sst2_tsv(str(bad), str(tmp_path / "o"))
    short = tmp_path / "short.tsv"
    with open(short, "w") as f:
        f.write("sentence\tlabel\n")
        f.write("only-sentence-no-tab\n")
    with pytest.raises(ValueError, match="short row"):
        ingest_sst2_tsv(str(short), str(tmp_path / "o2"))


def _write_image_tree(tmp_path, sizes_by_class, fmt="JPEG"):
    """Class-subdirectory tree of REAL encoded images: each class gets
    solid-color images (color = class signature) at assorted sizes, so
    decode/resize/crop geometry and label assignment are both checked."""
    from PIL import Image

    root = tmp_path / "imagefolder"
    colors = {"ants": (200, 30, 40), "bees": (20, 180, 60), "cats": (10, 40, 220)}
    for cls, sizes in sizes_by_class.items():
        d = root / cls
        d.mkdir(parents=True)
        for j, (w, h) in enumerate(sizes):
            arr = np.zeros((h, w, 3), np.uint8)
            arr[:] = colors[cls]
            ext = "jpg" if fmt == "JPEG" else "png"
            Image.fromarray(arr).save(d / f"img_{j:03d}.{ext}", format=fmt)
    (root / "notes.txt").write_text("ignored non-image file")
    return root, colors


@pytest.mark.parametrize("fmt", ["JPEG", "PNG"])
def test_ingest_image_folder_roundtrip(tmp_path, fmt):
    from tpudl.data.ingest import ingest_image_folder

    sizes = {
        "ants": [(64, 48), (100, 60)],   # landscape, shorter side = h
        "bees": [(48, 64), (32, 32)],    # portrait and exact-size
        "cats": [(33, 47)],              # odd dims
    }
    root, colors = _write_image_tree(tmp_path, sizes, fmt)
    conv = ingest_image_folder(str(root), str(tmp_path / "out"), image_size=32)
    assert conv.num_rows == 5
    with open(tmp_path / "out" / "classes.txt") as f:
        assert f.read().split() == ["ants", "bees", "cats"]

    b = next(conv.make_batch_iterator(5, shuffle=False, drop_last=False,
                                      shard_index=0, num_shards=1))
    assert b["image"].shape == (5, 32, 32, 3)
    assert b["image"].dtype == np.uint8
    # Sorted-class label order: ants=0 (2 imgs), bees=1 (2), cats=2 (1).
    np.testing.assert_array_equal(b["label"], [0, 0, 1, 1, 2])
    by_label = {0: "ants", 1: "bees", 2: "cats"}
    for img, lab in zip(b["image"], b["label"]):
        want = np.asarray(colors[by_label[int(lab)]], np.float32)
        # Solid color survives resize+crop; JPEG is lossy, PNG exact.
        tol = 4.0 if fmt == "JPEG" else 1.0
        assert np.abs(img.astype(np.float32) - want).max() <= tol, (lab, img[0, 0])


def test_ingest_image_folder_resize_headroom(tmp_path):
    """resize_shorter > image_size reproduces the standard eval preproc
    (resize-256 + center-crop-224 shape contract, scaled down)."""
    from tpudl.data.ingest import ingest_image_folder

    root, _ = _write_image_tree(tmp_path, {"ants": [(80, 50)]}, "PNG")
    conv = ingest_image_folder(
        str(root), str(tmp_path / "out"), image_size=28, resize_shorter=32
    )
    b = next(conv.make_batch_iterator(1, shuffle=False, drop_last=False,
                                      shard_index=0, num_shards=1))
    assert b["image"].shape == (1, 28, 28, 3)
    with pytest.raises(ValueError, match="upscaling"):
        ingest_image_folder(str(root), str(tmp_path / "o2"),
                            image_size=32, resize_shorter=16)


def test_ingest_image_folder_atomic_publish(tmp_path):
    """Regression (ADVICE round 5): an interrupted ingest must not leave
    valid-looking part files in out_dir (label-mapped but class-name-less,
    and a duplicate hazard for re-runs). Parts stage in
    <out_dir>.ingest-tmp and publish only on completion; a re-run after
    failure succeeds with no stale staging dir and no duplicate parts."""
    from tpudl.data.ingest import ingest_image_folder

    sizes = {"ants": [(40, 40), (40, 40)], "bees": [(40, 40)]}
    root, _ = _write_image_tree(tmp_path, sizes, "PNG")
    out = tmp_path / "out"
    stage = tmp_path / "out.ingest-tmp"

    # Corrupt the LAST file (sorted order: bees/) so the first chunk is
    # already written when the decode fails — the partial-ingest shape.
    bad = root / "bees" / "img_000.png"
    good_bytes = bad.read_bytes()
    bad.write_bytes(b"not an image")
    with pytest.raises(Exception):
        ingest_image_folder(str(root), str(out), image_size=32,
                            rows_per_file=1)
    # Nothing published: no out_dir at all, only the staging dir.
    assert not out.exists()
    assert stage.is_dir()

    # Re-run after repair: stale staging is wiped, publish is complete,
    # and the part count is exactly the chunk count (no duplicates).
    bad.write_bytes(good_bytes)
    conv = ingest_image_folder(str(root), str(out), image_size=32,
                               rows_per_file=1)
    assert conv.num_rows == 3
    assert not stage.exists()
    parts = sorted(p.name for p in out.glob("part-*.parquet"))
    assert parts == ["part-00000.parquet", "part-00001.parquet",
                     "part-00002.parquet"]
    assert (out / "classes.txt").read_text().split() == ["ants", "bees"]

    # A re-ingest over an EXISTING complete out_dir replaces it
    # wholesale (directory swap, atomic at every kill point) — fewer
    # chunks must not leave stale high-numbered parts, and unrelated
    # user files in out_dir survive the swap.
    stray = out / "notes.md"
    stray.write_text("keep me")
    conv = ingest_image_folder(str(root), str(out), image_size=32,
                               rows_per_file=4)
    assert conv.num_rows == 3
    assert sorted(p.name for p in out.glob("part-*.parquet")) == [
        "part-00000.parquet"
    ]
    assert stray.read_text() == "keep me"
    retired = tmp_path / "out.ingest-old"
    assert not retired.exists()

    # Kill between the two publish renames: out_dir gone, the old
    # dataset lives only in .ingest-old. The next run must RESTORE it
    # (never wipe it) before re-ingesting — stray user files included.
    out.rename(retired)
    assert not out.exists()
    conv = ingest_image_folder(str(root), str(out), image_size=32,
                               rows_per_file=4)
    assert conv.num_rows == 3
    assert stray.read_text() == "keep me"
    assert not retired.exists()

    # Kill after the swap but before carry-over: both dirs exist, the
    # stray file still sits in .ingest-old. The next run rescues it.
    retired.mkdir()
    (retired / "notes2.md").write_text("rescue me")
    (retired / "part-09999.parquet").write_text("superseded")
    conv = ingest_image_folder(str(root), str(out), image_size=32,
                               rows_per_file=4)
    assert (out / "notes2.md").read_text() == "rescue me"
    assert not (out / "part-09999.parquet").exists()
    assert not retired.exists()


def test_ingest_image_folder_errors(tmp_path):
    from tpudl.data.ingest import ingest_image_folder

    empty = tmp_path / "empty"
    empty.mkdir()
    with pytest.raises(ValueError, match="no class subdirectories"):
        ingest_image_folder(str(empty), str(tmp_path / "o"))
    (empty / "cls").mkdir()
    with pytest.raises(ValueError, match="no .*files"):
        ingest_image_folder(str(empty), str(tmp_path / "o"))
