"""Pipeline parallelism (tpudl.parallel.pipeline) on the fake 8-CPU mesh.

Strategy (SURVEY.md §4.2): the GPipe schedule must be numerically
invisible — outputs and gradients match folding the stages sequentially on
one device, for every mesh composition (pp alone, pp x dp).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from tpudl.parallel.pipeline import (
    num_ticks,
    pipeline,
    stack_pytrees,
)
from tpudl.parallel.sharding import active_mesh
from tpudl.runtime.mesh import MeshSpec, make_mesh

DIM = 8


def _stage_fn(params, x):
    """One homogeneous stage: tanh(x @ w + b) + x."""
    return jnp.tanh(x @ params["w"] + params["b"]) + x


def _make_stage_params(key, n_stages):
    keys = jax.random.split(key, n_stages)
    return [
        {
            "w": jax.random.normal(k, (DIM, DIM)) * 0.3,
            "b": jnp.zeros((DIM,)),
        }
        for k in keys
    ]


def _sequential(stages, x):
    for p in stages:
        x = _stage_fn(p, x)
    return x


def test_num_ticks():
    assert num_ticks(4, 8) == 11
    assert num_ticks(1, 8) == 8


def test_pipeline_matches_sequential_pp4():
    mesh = make_mesh(MeshSpec(dp=1, fsdp=1, sp=1, tp=1, pp=4, ep=2))
    stages = _make_stage_params(jax.random.key(0), 4)
    stacked = stack_pytrees(stages)
    x = jax.random.normal(jax.random.key(1), (16, DIM))

    expected = _sequential(stages, x)
    got = pipeline(
        _stage_fn, stacked, x, num_microbatches=8, mesh=mesh
    )
    np.testing.assert_allclose(np.asarray(got), np.asarray(expected), atol=1e-6)


def test_pipeline_jit_with_sharded_params():
    """Under jit with the stacked params actually device_put pp-sharded,
    the schedule compiles and matches — the multi-chip deployment shape."""
    mesh = make_mesh(MeshSpec(dp=1, fsdp=1, sp=1, tp=1, pp=8, ep=1))
    stages = _make_stage_params(jax.random.key(2), 8)
    stacked = stack_pytrees(stages)
    stacked = jax.device_put(
        stacked,
        jax.tree.map(
            lambda p: NamedSharding(mesh, P(*(["pp"] + [None] * (p.ndim - 1)))),
            stacked,
        ),
    )
    x = jax.random.normal(jax.random.key(3), (32, DIM))

    fn = jax.jit(
        lambda pr, xx: pipeline(
            _stage_fn, pr, xx, num_microbatches=16, mesh=mesh
        )
    )
    got = fn(stacked, x)
    expected = _sequential(stages, x)
    np.testing.assert_allclose(np.asarray(got), np.asarray(expected), atol=1e-6)


def test_pipeline_grad_matches_sequential():
    mesh = make_mesh(MeshSpec(dp=1, fsdp=1, sp=1, tp=1, pp=4, ep=2))
    stages = _make_stage_params(jax.random.key(4), 4)
    stacked = stack_pytrees(stages)
    x = jax.random.normal(jax.random.key(5), (8, DIM))

    def loss_pipe(p):
        return jnp.sum(
            pipeline(_stage_fn, p, x, num_microbatches=4, mesh=mesh) ** 2
        )

    def loss_seq(p):
        y = x
        for i in range(4):
            y = _stage_fn(jax.tree.map(lambda a: a[i], p), y)
        return jnp.sum(y**2)

    g_pipe = jax.grad(loss_pipe)(stacked)
    g_seq = jax.grad(loss_seq)(stacked)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=1e-5
        ),
        g_pipe,
        g_seq,
    )


def test_pipeline_composes_with_dp():
    """pp=4 x dp=2: microbatch split happens per data shard."""
    mesh = make_mesh(MeshSpec(dp=2, fsdp=1, sp=1, tp=1, pp=4, ep=1))
    assert mesh.shape["pp"] == 4 and mesh.shape["dp"] == 2
    stages = _make_stage_params(jax.random.key(6), 4)
    stacked = stack_pytrees(stages)
    x = jax.random.normal(jax.random.key(7), (16, DIM))

    got = pipeline(
        _stage_fn,
        stacked,
        x,
        num_microbatches=4,
        mesh=mesh,
        batch_spec=P("dp"),
    )
    expected = _sequential(stages, x)
    np.testing.assert_allclose(np.asarray(got), np.asarray(expected), atol=1e-6)


def test_pipeline_degenerates_without_mesh():
    stages = _make_stage_params(jax.random.key(8), 3)
    stacked = stack_pytrees(stages)
    x = jax.random.normal(jax.random.key(9), (4, DIM))
    got = pipeline(_stage_fn, stacked, x, num_microbatches=2, mesh=None)
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(_sequential(stages, x)), atol=1e-6
    )


def test_pipeline_uses_active_mesh():
    mesh = make_mesh(MeshSpec(dp=1, fsdp=1, sp=1, tp=1, pp=4, ep=2))
    stages = _make_stage_params(jax.random.key(10), 4)
    stacked = stack_pytrees(stages)
    x = jax.random.normal(jax.random.key(11), (8, DIM))
    with active_mesh(mesh):
        got = pipeline(_stage_fn, stacked, x, num_microbatches=4)
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(_sequential(stages, x)), atol=1e-6
    )


def test_pipeline_bert_layers():
    """Pipeline real BertLayer stages (mask rides the carry pytree) and
    match the sequential encoder stack."""
    from tpudl.models.bert import BERT_TINY, BertLayer
    from tpudl.ops.attention import padding_mask
    from tpudl.parallel.pipeline import stack_layer_params

    cfg = BERT_TINY(
        num_layers=4,
        hidden_dropout=0.0,
        attention_dropout=0.0,
        dtype=jnp.float32,  # isolate the schedule from bf16 rounding
    )
    layer = BertLayer(cfg)
    B, S = 8, 16
    hidden = jax.random.normal(
        jax.random.key(20), (B, S, cfg.hidden_size)
    ).astype(cfg.dtype)
    mask = padding_mask(jnp.ones((B, S), jnp.int32))

    layer_params = [
        layer.init(jax.random.key(30 + i), hidden, mask, False)["params"]
        for i in range(4)
    ]
    stacked = stack_pytrees(layer_params)

    def stage_fn(p, carry):
        h, msk = carry
        return layer.apply({"params": p}, h, msk, False), msk

    mesh = make_mesh(MeshSpec(dp=2, fsdp=1, sp=1, tp=1, pp=4, ep=1))
    got, _ = pipeline(
        stage_fn,
        stacked,
        (hidden, mask),
        num_microbatches=4,
        mesh=mesh,
        batch_spec=P("dp"),
    )

    expected = hidden
    for p in layer_params:
        expected = layer.apply({"params": p}, expected, mask, False)
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(expected), atol=1e-5
    )


def test_stack_layer_params_path():
    from tpudl.parallel.pipeline import stack_layer_params

    params = {
        "encoder": {
            "layer_0": {"w": jnp.ones((2,))},
            "layer_1": {"w": jnp.zeros((2,))},
        }
    }
    stacked = stack_layer_params(params, "encoder/layer_{}", 2)
    assert stacked["w"].shape == (2, 2)
    np.testing.assert_allclose(np.asarray(stacked["w"][0]), 1.0)


def test_pipeline_validates_shapes():
    mesh = make_mesh(MeshSpec(dp=1, fsdp=1, sp=1, tp=1, pp=4, ep=2))
    stages = _make_stage_params(jax.random.key(12), 3)  # wrong stage count
    stacked = stack_pytrees(stages)
    x = jnp.zeros((8, DIM))
    with pytest.raises(ValueError, match="leading dim"):
        pipeline(_stage_fn, stacked, x, num_microbatches=4, mesh=mesh)
    stages4 = _make_stage_params(jax.random.key(13), 4)
    with pytest.raises(ValueError, match="not divisible"):
        pipeline(
            _stage_fn, stack_pytrees(stages4), x, num_microbatches=3, mesh=mesh
        )
    # Microbatch must divide the batch_spec mesh extent (pp x dp mesh).
    mesh2 = make_mesh(MeshSpec(dp=2, fsdp=1, sp=1, tp=1, pp=4, ep=1))
    with pytest.raises(ValueError, match="microbatch size"):
        pipeline(
            _stage_fn,
            stack_pytrees(stages4),
            x,
            num_microbatches=8,  # mb=1, not divisible by dp=2
            mesh=mesh2,
            batch_spec=P("dp"),
        )


# ---------------------------------------------------------------------------
# 1F1B schedule.
# ---------------------------------------------------------------------------


def _mse_loss(y, t):
    return jnp.mean((y - t) ** 2)


def _gpipe_loss_and_grad(stacked, x, targets, m, mesh):
    """Reference: GPipe forward + jax.grad, with the SAME
    mean-of-per-microbatch-means loss semantics as pipeline_1f1b."""
    mb = x.shape[0] // m

    def loss(p):
        y = pipeline(_stage_fn, p, x, num_microbatches=m, mesh=mesh)
        ym = y.reshape((m, mb) + y.shape[1:])
        tm = targets.reshape((m, mb) + targets.shape[1:])
        return sum(_mse_loss(ym[i], tm[i]) for i in range(m)) / m

    return jax.value_and_grad(loss)(stacked)


def test_1f1b_loss_and_grads_match_gpipe():
    """f32 parity: the interleaved 1F1B schedule (manual vjp, recompute
    from stored inputs) produces the same loss and stage gradients as
    autodiff through the GPipe schedule."""
    from tpudl.parallel.pipeline import pipeline_1f1b

    mesh = make_mesh(MeshSpec(dp=1, fsdp=1, sp=1, tp=1, pp=4, ep=2))
    stages = _make_stage_params(jax.random.key(40), 4)
    stacked = stack_pytrees(stages)
    m = 8
    x = jax.random.normal(jax.random.key(41), (16, DIM))
    targets = jax.random.normal(jax.random.key(42), (16, DIM))

    want_loss, want_grads = _gpipe_loss_and_grad(stacked, x, targets, m, mesh)
    got_loss, got_grads = pipeline_1f1b(
        _stage_fn, _mse_loss, stacked, x, targets,
        num_microbatches=m, mesh=mesh,
    )
    np.testing.assert_allclose(
        float(got_loss), float(want_loss), rtol=1e-6
    )
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=1e-5
        ),
        got_grads, want_grads,
    )


def test_1f1b_jit_and_m_less_than_s():
    """Edge shapes: jitted, and M < S (more stages than microbatches —
    pure warmup/drain, no steady state)."""
    from tpudl.parallel.pipeline import pipeline_1f1b

    mesh = make_mesh(MeshSpec(dp=1, fsdp=1, sp=1, tp=1, pp=8, ep=1))
    stages = _make_stage_params(jax.random.key(43), 8)
    stacked = stack_pytrees(stages)
    m = 2
    x = jax.random.normal(jax.random.key(44), (8, DIM))
    targets = jax.random.normal(jax.random.key(45), (8, DIM))

    want_loss, want_grads = _gpipe_loss_and_grad(stacked, x, targets, m, mesh)
    fn = jax.jit(
        lambda p, xx, tt: pipeline_1f1b(
            _stage_fn, _mse_loss, p, xx, tt, num_microbatches=m, mesh=mesh
        )
    )
    got_loss, got_grads = fn(stacked, x, targets)
    np.testing.assert_allclose(float(got_loss), float(want_loss), rtol=1e-6)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=1e-5
        ),
        got_grads, want_grads,
    )


def test_1f1b_degenerates_without_mesh():
    from tpudl.parallel.pipeline import pipeline_1f1b

    stages = _make_stage_params(jax.random.key(46), 3)
    stacked = stack_pytrees(stages)
    x = jax.random.normal(jax.random.key(47), (4, DIM))
    targets = jax.random.normal(jax.random.key(48), (4, DIM))
    loss, grads = pipeline_1f1b(
        _stage_fn, _mse_loss, stacked, x, targets,
        num_microbatches=2, mesh=None,
    )
    y = _sequential(stages, x)
    np.testing.assert_allclose(
        float(loss), float(_mse_loss(y, targets)), rtol=1e-6
    )
    assert jax.tree.structure(grads) == jax.tree.structure(stacked)


def test_schedule_stats():
    """The reported accounting: identical ticks/bubble (both schedules
    flush), depth-bounded memory for 1F1B — the judge-facing numbers at
    the dryrun shape S=4."""
    from tpudl.parallel.pipeline import schedule_stats

    g = schedule_stats(4, 16, "gpipe")
    f = schedule_stats(4, 16, "1f1b")
    assert g["ticks"] == f["ticks"] == 2 * (16 + 3)
    assert g["bubble_fraction"] == f["bubble_fraction"] == 3 / 19
    assert g["stored_microbatch_inputs"] == 19  # grows with M
    assert f["stored_microbatch_inputs"] == 4   # bounded by S
    with pytest.raises(ValueError, match="unknown schedule"):
        schedule_stats(4, 16, "zigzag")


# ---------------------------------------------------------------------------
# Interleaved (virtual-stage) schedule.
# ---------------------------------------------------------------------------


def test_interleaved_matches_sequential_and_grads():
    """8 stages on 4 devices (v=2, round-robin assignment via
    interleave_stage_order): forward and gradients match the sequential
    fold — the schedule that actually shrinks the bubble,
    (n-1)/(M*v+n-1) vs GPipe's (n-1)/(M+n-1)."""
    from tpudl.parallel.pipeline import (
        interleave_stage_order,
        pipeline_interleaved,
    )

    mesh = make_mesh(MeshSpec(dp=1, fsdp=1, sp=1, tp=1, pp=4, ep=2))
    stages = _make_stage_params(jax.random.key(50), 8)
    order = interleave_stage_order(8, 4)
    # order[d*2 + c] == c*4 + d
    assert order == [0, 4, 1, 5, 2, 6, 3, 7]
    stacked = stack_pytrees([stages[i] for i in order])
    x = jax.random.normal(jax.random.key(51), (16, DIM))

    got = pipeline_interleaved(
        _stage_fn, stacked, x, num_microbatches=8, mesh=mesh
    )
    expected = _sequential(stages, x)
    np.testing.assert_allclose(np.asarray(got), np.asarray(expected),
                               atol=1e-6)

    def loss_pipe(sp):
        return jnp.sum(pipeline_interleaved(
            _stage_fn, sp, x, num_microbatches=8, mesh=mesh) ** 2)

    def loss_seq(sp):
        y = x
        for stage in range(8):
            row = order.index(stage)
            y = _stage_fn(jax.tree.map(lambda a: a[row], sp), y)
        return jnp.sum(y ** 2)

    g_pipe = jax.grad(loss_pipe)(stacked)
    g_seq = jax.grad(loss_seq)(stacked)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-4
        ),
        g_pipe, g_seq,
    )


def test_interleaved_validates_and_degenerates():
    from tpudl.parallel.pipeline import pipeline_interleaved

    mesh = make_mesh(MeshSpec(dp=1, fsdp=1, sp=1, tp=1, pp=4, ep=2))
    stages = _make_stage_params(jax.random.key(52), 8)
    stacked = stack_pytrees(stages)
    x = jnp.zeros((12, DIM))
    with pytest.raises(ValueError, match="multiple of"):
        pipeline_interleaved(_stage_fn, stacked, x, num_microbatches=6,
                             mesh=mesh)
    with pytest.raises(ValueError, match="not divisible"):
        pipeline_interleaved(
            _stage_fn, stack_pytrees(stages[:7]), x, num_microbatches=4,
            mesh=mesh,
        )
    # Unmeshed: sequential fold (identity storage order at n=1).
    got = pipeline_interleaved(_stage_fn, stacked, x[:4],
                               num_microbatches=2, mesh=None)
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(_sequential(stages, x[:4])), atol=1e-6
    )


def test_schedule_stats_interleaved():
    from tpudl.parallel.pipeline import schedule_stats

    st = schedule_stats(8, 16, "interleaved", virtual_stages=2)
    assert st["num_devices"] == 4 and st["ticks"] == 2 * (16 * 2 + 3)
    assert st["bubble_fraction"] == 3 / 35  # vs 3/19 plain GPipe at n=4
    g = schedule_stats(4, 16, "gpipe")
    assert st["bubble_fraction"] < g["bubble_fraction"]
    with pytest.raises(ValueError, match="not divisible"):
        schedule_stats(8, 16, "interleaved", virtual_stages=3)
