import jax
import pytest
from jax.sharding import PartitionSpec

from tpudl.runtime.mesh import (
    MESH_AXES,
    MeshSpec,
    batch_partition_spec,
    make_mesh,
)


def test_fake_device_count():
    assert len(jax.devices()) == 8


def test_meshspec_resolve_wildcard():
    assert MeshSpec(dp=-1).resolve(8) == (8, 1, 1, 1, 1, 1)
    assert MeshSpec(dp=-1, fsdp=2).resolve(8) == (4, 2, 1, 1, 1, 1)
    assert MeshSpec(dp=2, fsdp=2, tp=2).resolve(8) == (2, 2, 1, 2, 1, 1)
    assert MeshSpec(dp=-1, pp=2, ep=2).resolve(8) == (2, 1, 1, 1, 2, 2)


def test_meshspec_errors():
    with pytest.raises(ValueError):
        MeshSpec(dp=3).resolve(8)
    with pytest.raises(ValueError):
        MeshSpec(dp=-1, fsdp=3).resolve(8)


def test_make_mesh_axes():
    mesh = make_mesh(MeshSpec(dp=2, fsdp=2, tp=2))
    assert mesh.axis_names == MESH_AXES
    assert mesh.shape["dp"] == 2
    assert mesh.shape["fsdp"] == 2
    assert mesh.shape["sp"] == 1
    assert mesh.shape["tp"] == 2


def test_make_mesh_default_all_dp():
    mesh = make_mesh()
    assert mesh.shape["dp"] == 8


def test_batch_partition_spec():
    assert batch_partition_spec() == PartitionSpec(("dp", "fsdp"))
    assert batch_partition_spec(2) == PartitionSpec(("dp", "fsdp"), None, None)
